"""Headline benchmark: batched scheduling throughput on one TPU chip.

Config #2 from BASELINE.json: NodeResourcesFit + BalancedAllocation,
5k nodes / 5k pods, mixed cpu+mem requests — solved by the batched greedy
kernel (sequential-in-batch semantics identical to the reference's one-
pod-at-a-time cycle).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is against 100 pods/s — the upstream scheduler's ~SLO
throughput at 5k nodes (the reference publishes no in-tree absolute
numbers; see BASELINE.md).  Timing covers the warm end-to-end step the
scheduler would run per batch: snapshot encode + device solve + readback.
"""

import json
import sys
import time

import numpy as np

N_NODES = 5_000
N_PODS = 5_000
BASELINE_PODS_PER_SEC = 100.0


def build_workload():
    from kubernetes_tpu.ops import schema
    from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod

    rng = np.random.default_rng(0)
    nodes = [
        make_node(f"node-{i}")
        .capacity(cpu_milli=32000, mem=64 * GI, pods=110)
        .zone(f"zone-{i % 10}")
        .obj()
        for i in range(N_NODES)
    ]
    pods = [
        make_pod(f"pod-{i}")
        .req(
            cpu_milli=int(rng.choice([100, 250, 500, 1000, 2000])),
            mem=int(rng.choice([128, 256, 512, 1024, 2048])) * MI,
        )
        .obj()
        for i in range(N_PODS)
    ]
    return nodes, pods


def main() -> None:
    from kubernetes_tpu.ops import assign, schema

    nodes, pods = build_workload()
    solver = assign.greedy_assign_jit()

    # cold: encode + compile
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    result = solver(snap)
    result.assignment.block_until_ready()

    # warm, timed end-to-end (encode + solve + readback)
    t0 = time.perf_counter()
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    result = solver(snap)
    a = np.asarray(result.assignment)[: meta.num_pods]
    dt = time.perf_counter() - t0

    placed = int((a >= 0).sum())
    assert placed == N_PODS, f"only {placed}/{N_PODS} pods placed"
    pods_per_sec = N_PODS / dt
    print(
        json.dumps(
            {
                "metric": f"scheduling_throughput_{N_NODES}nodes_{N_PODS}pods",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
