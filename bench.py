"""Headline benchmark: the five BASELINE.json configs on one TPU chip.

Prints ONE JSON line.  Headline metric = config 5, the north star: a
50k-node / 10k-pod gang burst jointly solved on device, reported as
end-to-end warm-step latency (pod-batch encode + device solve + result
readback) against a warm cluster state — the steady-state step a running
scheduler executes per batch, matching the reference scheduler's warm
informer-fed cache.  `extra` carries all five configs:

  c1   500 nodes /  500 pods  NodeResourcesFit, oracle-parity checked
  c2    5k nodes /   5k pods  Fit + BalancedAllocation
  c3   10k nodes /  10k pods  PodTopologySpread (hard) + preferred NodeAffinity
  c3s   5k nodes / 1024 pods  spread, pinned greedy/wavefront (strict budget)
  c4   20k nodes /  10k pods  InterPodAffinity/AntiAffinity (required)
  c4s   5k nodes / 1024 pods  anti-affinity, pinned greedy/wavefront (strict budget)
  c5   50k nodes /  10k pods  gang/coscheduling burst, joint auction solve
  c6    5k nodes /   2k pods  kubemark churn through the full loop
  c6s  50k nodes /   4k pods  SUSTAINED constant-rate arrival stream
       (strict budget: >= 1050 pods/s, watchers_terminated == 0), run
       journaled + ends with a crash-restart recovery gate (snapshot +
       journal-suffix recovery under STRICT_RECOVERY_BUDGET_MS, zero
       lost pods)
  c7  100k nodes /   2k pods  SHARDED solve on a forced 8-device host
       mesh — a snapshot one chip cannot hold; gates: mesh/single-chip
       assignment parity, steady_recompiles == 0, and steady host→device
       transfer O(changed rows) via the mirror delta counters
  c8  100k hollow nodes       the kubemark FLEET harness on the 8-shard
       store: batched wave-committed heartbeats + a sustained
       pod-lifecycle soak across namespaces (concurrent per-shard bind
       sub-waves), p50/p90/p99 lifecycle latency, zero lost/double-bound
       pods, watchers_terminated == 0, and per-shard snapshot+suffix
       recovery under STRICT_RECOVERY_BUDGET_MS
  c10   4k nodes / 64 slices of 4x4x4  SLICE PACKING: mixed gang shapes
       arriving/leaving through the carve-out scorer (prefer policy);
       gates placement QUALITY — BENCH_STRICT floors on the
       contiguous-placement rate and the end-state fragmentation score
       — alongside throughput and steady_recompiles == 0
  c9   20k nodes / 128 preemptors  mixed-priority preemption churn with
       PDBs through the BATCHED PostFilter (one [P, N, K] dry-run per
       pass); gates: oracle + batched-vs-sequential plan parity,
       bound-exactly-once for preemptors and evicted victims, guarded
       victims survive, a sustained preemption-throughput floor, zero
       steady recompiles in the planning phase, and a ≥5x exposed
       PostFilter planning speedup vs the per-pod walk on the same trace
  c11  50k nodes / 64 pod classes  INCREMENTAL churn: <=1% of node rows
       dirtied per cycle under a recurring service-shaped stream; the
       warm-started solve (device-resident Filter/Score partials,
       ISSUE 14) runs the same frozen trace as a cold scheduler — gates:
       bit-identical placements, a ≥3x warm-vs-cold planning speedup,
       zero steady recompiles, and the <=1% dirtied-rows contract;
       reports the partials hit rate and rows re-evaluated (c6/c6s
       report the same accounting for their live loops)
  c12  50k nodes  AUTOSCALE churn: a kubemark NodeGroupScaler drives
       ±1% node add/remove per cycle plus deliberate oscillation around
       the 65536 pad-bucket boundary against the ELASTIC node axis
       (ISSUE 15) — gates: placements bit-identical to the
       full-RESHARDED-rebuild oracle, zero resyncs/recompiles under
       within-bucket churn AND under boundary oscillation (the shrink
       dwell), crossings absorbed by in-place resident grows with exact
       pad-row accounting, ≥90% of partials class rows warm across the
       grow, and the post-dwell drain shrink served; plus a LIVE phase
       (HPA + CA-shaped scaler reconcile over a hollow fleet) gating
       zero unbound pods at peak, ≥1 live in-place grow, and
       watchers_terminated == 0

Every scenario reports step-latency p50/p90/p99 (the windowed sampler:
attempt-duration percentiles for the loop configs, timed-sample
percentiles for the solver configs) plus its commit share per step.

vs_baseline compares c5 against the upstream-folklore scheduler SLO of
~100 pods/s at 5k nodes (the reference publishes no in-tree absolute
numbers; see BASELINE.md): value = (10_000 / latency) / 100.
"""

import json
import os
import time

# c7 needs a multi-device host-platform mesh; the flag must land before
# the first JAX backend init (tests/conftest.py forces the same 8)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np

BASELINE_PODS_PER_SEC = 100.0


def _mk_nodes(n, zones=10):
    from kubernetes_tpu.testing.wrappers import GI, make_node

    return [
        make_node(f"node-{i}")
        .capacity(cpu_milli=32000, mem=64 * GI, pods=110)
        .zone(f"zone-{i % zones}")
        .obj()
        for i in range(n)
    ]


def _mk_basic_pods(p, seed=0, prefix="pod"):
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    rng = np.random.default_rng(seed)
    return [
        make_pod(f"{prefix}-{i}")
        .req(
            cpu_milli=int(rng.choice([100, 250, 500, 1000, 2000])),
            mem=int(rng.choice([128, 256, 512, 1024, 2048])) * MI,
        )
        .obj()
        for i in range(p)
    ]


class _Runner:
    """Warm-state end-to-end step timer: state prebuilt with nodes (the
    warm scheduler cache), timed step = encode pending batch + solve +
    readback.  First call compiles; second identical-shape call is the
    measurement.  The first-shape compile wall and the steady-state
    encode/compile/solve split are reported separately so CI can gate on
    solve-half regressions without compile churn polluting the number."""

    def __init__(self, nodes, mode, mesh=None):
        from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler

        self.sched = TPUBatchScheduler(mode=mode, mesh=mesh)
        for nd in nodes:
            self.sched.add_node(nd)

    def step(self, pods):
        t0 = time.perf_counter()
        names = self.sched.schedule_pending(pods)
        dt = time.perf_counter() - t0
        return names, dt, dict(self.sched.last_timings)

    SAMPLES = 3

    def run(self, mk_pods):
        from kubernetes_tpu.analysis import retrace

        # compile; identical shapes.  Its wall clock IS the first-shape
        # cost (XLA compile dominates) — recorded, not mixed into steady.
        retrace.clear_steady()
        _, first_s, _ = self.step(mk_pods("warmup"))
        # warmup traced every executable this scenario needs; any trace
        # during the timed steps below is a steady-state recompile — a
        # kernel argument escaped the pad-bucket lattice (the
        # recompile-discipline invariant, analysis/retrace.py)
        retrace.mark_steady()
        steady0 = retrace.steady_total()
        # the axon tunnel's latency varies 2-3x run to run; min-of-3
        # timed runs reports the machine, not the tunnel's mood, and
        # the full sample list makes the recorded JSON self-diagnosing
        names, dt, samples, best_t = None, None, [], {}
        for k in range(self.SAMPLES):
            nms, d, lt = self.step(mk_pods(f"run{k}"))
            samples.append(round(d, 4))
            if dt is None or d < dt:
                names, dt, best_t = nms, d, lt
        steady_recompiles = retrace.steady_total() - steady0
        retrace.clear_steady()
        placed = sum(n is not None for n in names)
        return _Run(
            names, placed, dt, samples, first_s, best_t, steady_recompiles
        )


class _Run:
    def __init__(self, names, placed, dt, samples, first_s, timings,
                 steady_recompiles=0):
        self.names = names
        self.placed = placed
        self.dt = dt
        self.samples = samples
        self.first_s = first_s
        self.timings = timings
        self.steady_recompiles = steady_recompiles

    def report(self, nodes, pods, **extra):
        from kubernetes_tpu.kubemark import percentiles

        t = self.timings
        pct = percentiles(list(self.samples))
        out = {
            "nodes": nodes, "pods": pods, "placed": self.placed,
            "latency_s": round(self.dt, 4),
            "pods_per_s": round(pods / self.dt, 1),
            "samples_s": self.samples,
            # windowed-sampler surface (every scenario): step-latency
            # percentiles over the timed samples; solver-only configs
            # have no store in the loop, so their commit share is 0 by
            # construction (the loop configs report the real split)
            "latency_p50_s": round(pct["p50"], 4),
            "latency_p90_s": round(pct["p90"], 4),
            "latency_p99_s": round(pct["p99"], 4),
            "commit_share_per_step": 0.0,
            # first-of-shape step (compile included) vs the steady split
            "first_step_s": round(self.first_s, 4),
            "steady_encode_s": round(t.get("encode_s", 0.0), 4),
            "steady_compile_s": round(t.get("compile_s", 0.0), 4),
            "steady_solve_s": round(t.get("solve_s", 0.0), 4),
            "solve_share": round(
                (t.get("compile_s", 0.0) + t.get("solve_s", 0.0))
                / self.dt, 4,
            ) if self.dt else 0.0,
            # XLA traces during the TIMED steps (warmup excluded): must
            # be zero — a steady-state retrace eats a full compile on
            # the hot path (BENCH_STRICT gates on this)
            "steady_recompiles": self.steady_recompiles,
        }
        out.update(extra)
        return out


def config1():
    """500/500 Fit; placement parity vs the reference-semantics oracle."""
    from kubernetes_tpu.testing.oracle import Oracle

    nodes = _mk_nodes(500)
    runner = _Runner(nodes, mode="auto")
    pods_fn = lambda tag: _mk_basic_pods(500, seed=1, prefix=f"c1-{tag}")
    run = runner.run(pods_fn)
    want = Oracle(nodes).schedule(pods_fn("run0"))
    return run.report(500, 500, oracle_parity=run.names == want)


def config2():
    nodes = _mk_nodes(5_000)
    runner = _Runner(nodes, mode="auto")
    run = runner.run(
        lambda tag: _mk_basic_pods(5_000, seed=2, prefix=f"c2-{tag}")
    )
    return run.report(5_000, 5_000)


def config3():
    """10k/10k: hard zone-spread + preferred node affinity."""
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    nodes = _mk_nodes(10_000, zones=32)

    def mk(tag):
        rng = np.random.default_rng(3)
        pods = []
        for i in range(10_000):
            svc = i % 50
            pw = (
                make_pod(f"c3-{tag}-{i}")
                .req(cpu_milli=int(rng.choice([100, 250, 500])), mem=256 * MI)
                .label("app", f"svc-{svc}")
                .spread(2, api.LABEL_ZONE, "DoNotSchedule", {"app": f"svc-{svc}"})
            )
            if i % 4 == 0:
                pw.preferred_affinity(
                    10, api.LABEL_ZONE, api.OP_IN, [f"zone-{svc % 32}"]
                )
            pods.append(pw.obj())
        return pods

    runner = _Runner(nodes, mode="auto")
    run = runner.run(mk)
    return run.report(10_000, 10_000, **_wave_stats(runner))


def config4():
    """20k/10k: required inter-pod anti-affinity (self-spread per service
    over hostnames) — the O(N^2) pairwise family."""
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    nodes = _mk_nodes(20_000)

    def mk(tag):
        rng = np.random.default_rng(4)
        pods = []
        for i in range(10_000):
            svc = i % 200
            pods.append(
                make_pod(f"c4-{tag}-{i}")
                .req(cpu_milli=int(rng.choice([100, 250, 500])), mem=256 * MI)
                .label("app", f"svc-{svc}")
                .pod_anti_affinity({"app": f"svc-{svc}"}, api.LABEL_HOSTNAME)
                .obj()
            )
        return pods

    runner = _Runner(nodes, mode="auto")
    run = runner.run(mk)
    return run.report(20_000, 10_000, **_wave_stats(runner))


def config5():
    """50k/10k gang burst: joint auction solve, target < 1 s end-to-end."""
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    nodes = _mk_nodes(50_000)

    def mk(tag):
        rng = np.random.default_rng(5)
        return [
            make_pod(f"c5-{tag}-{i}")
            .req(
                cpu_milli=int(rng.choice([100, 250, 500, 1000, 2000])),
                mem=int(rng.choice([128, 256, 512, 1024, 2048])) * MI,
            )
            .group(f"gang-{i % 100}")
            .obj()
            for i in range(10_000)
        ]

    runner = _Runner(nodes, mode="auto")
    run = runner.run(mk)
    return run.report(50_000, 10_000, gangs=100)


def _wave_stats(runner):
    """Wavefront telemetry of the runner's most recent solve."""
    res = runner.sched.last_result
    wc = getattr(res, "wave_count", None)
    if wc is None:
        return {}
    return {
        "solve_waves": int(wc),
        "solve_wave_fallbacks": int(res.wave_fallbacks or 0),
    }


# Steady-state budgets for the 1k-pod greedy-routed shapes, enforced
# under BENCH_STRICT=1.  BENCH_r05 measured these batches at 582.8 ms
# (spread) and 1195.7 ms (inter-pod) per schedule_pending step; the
# wavefront solve must hold ≥2x better.
STRICT_SOLVE_BUDGETS_S = {
    "c3s_spread_1k": 0.291,
    "c4s_interpod_1k": 0.598,
}


def config3s():
    """1024-pod spread batch on 5k nodes pinned to the greedy route (the
    auto-router would hand exactly-1024 to the auction) — the shape whose
    BENCH_r05 solve half ran 582.8 ms.  Wavefront target: < 291 ms."""
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    nodes = _mk_nodes(5_000, zones=32)

    def mk(tag):
        rng = np.random.default_rng(31)
        pods = []
        for i in range(1024):
            svc = i % 50
            pods.append(
                make_pod(f"c3s-{tag}-{i}")
                .req(cpu_milli=int(rng.choice([100, 250, 500])), mem=256 * MI)
                .label("app", f"svc-{svc}")
                .spread(2, api.LABEL_ZONE, "DoNotSchedule", {"app": f"svc-{svc}"})
                .obj()
            )
        return pods

    runner = _Runner(nodes, mode="greedy")
    run = runner.run(mk)
    return run.report(5_000, 1024, **_wave_stats(runner))


def config4s():
    """1024-pod required-anti-affinity batch on 5k nodes — the shape
    whose BENCH_r05 solve half ran 1195.7 ms.  Target: < 598 ms."""
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    nodes = _mk_nodes(5_000)

    def mk(tag):
        rng = np.random.default_rng(41)
        return [
            make_pod(f"c4s-{tag}-{i}")
            .req(cpu_milli=int(rng.choice([100, 250, 500])), mem=256 * MI)
            .label("app", f"svc-{i % 200}")
            .pod_anti_affinity({"app": f"svc-{i % 200}"}, api.LABEL_HOSTNAME)
            .obj()
            for i in range(1024)
        ]

    runner = _Runner(nodes, mode="greedy")
    run = runner.run(mk)
    return run.report(5_000, 1024, **_wave_stats(runner))


def config6():
    """5k-node kubemark churn: the store/informer WRITE path under
    concurrent load (VERDICT r4 #10) — hollow-node heartbeats + pod
    churn + GC/namespace sweeps running while 2,000 measured pods
    schedule through the full informer/cache/queue/solve/bind loop.
    Reports wall throughput, window-scoped attempt p99, and asserts no
    watcher was terminated for falling behind (cacher data-loss
    signal).  Reference shape: performance-config.yaml MixedChurn,
    pkg/kubemark/hollow_kubelet.go:87."""
    import threading

    from kubernetes_tpu import kubemark
    from kubernetes_tpu.api import store as st
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.controllers import ControllerManager
    from kubernetes_tpu.controllers.garbagecollector import GarbageCollector
    from kubernetes_tpu.controllers.namespace import NamespaceController
    from kubernetes_tpu.perf.collectors import histogram_baseline
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    n_nodes, n_measured, n_churn = 5_000, 2_000, 600
    store = st.Store(shards=8)
    hollow = kubemark.HollowCluster(
        store, n_nodes, heartbeat_interval=5.0
    ).start()
    mgr = ControllerManager(
        store, controllers=[GarbageCollector, NamespaceController]
    ).start()
    sched = Scheduler(store, batch_size=1024)
    sched.start()

    def mk(i, prefix):
        return (
            make_pod(f"{prefix}-{i}")
            .req(cpu_milli=100 + (i % 5) * 100, mem=256 * MI)
            .obj()
        )

    # warm the solver's shape buckets outside the measured window
    sched.warmup([mk(i, "warm") for i in range(1024)])
    sched.wait_for_idle(timeout=120)

    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            p = mk(i, "churn")
            try:
                store.create(p)
                store.delete("Pod", p.meta.name, p.meta.namespace)
            except st.NotFound:
                pass
            i += 1
            if i >= n_churn:
                i = 0
            stop.wait(0.002)

    churner = threading.Thread(target=churn, daemon=True)
    baseline = histogram_baseline(sched.metrics)
    terminated0 = store.watchers_terminated
    churner.start()
    t0 = time.perf_counter()
    for i in range(n_measured):
        store.create(mk(i, "c6"))
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        bound = sum(
            1
            for p in sched.informers.informer("Pod").list()
            if p.meta.name.startswith("c6-") and p.spec.node_name
        )
        if bound >= n_measured:
            break
        time.sleep(0.05)
    dt = time.perf_counter() - t0
    stop.set()
    churner.join(timeout=2)
    sched.stop()  # quiesce BEFORE reading histograms (locked reads,
    mgr.stop()    # but a consistent window beats a racing one)
    hollow.stop()
    from kubernetes_tpu.perf.collectors import MetricsCollector

    collector = MetricsCollector(sched.metrics, baseline=baseline)
    win = collector._windowed(
        "scheduler_scheduling_attempt_duration_seconds",
        sched.metrics.scheduling_attempt_duration,
    )
    # pipeline accounting: how much wall clock the binding stage spent
    # committing, how much of that ran under a device solve (overlap),
    # and the commit share of the solve-stage step (commits are
    # off-thread, so a healthy pipeline keeps the non-overlapped share
    # well under the old in-line ~50%)
    m = sched.metrics
    ws = store.watch_stats()
    step_s = m.schedule_batch_duration.total
    commit_s = m.commit_wave_duration.total
    overlap_s = m.pipeline_overlap.total
    exposed = max(commit_s - overlap_s, 0.0)  # commit time NOT hidden
    return {
        "nodes": n_nodes, "pods": n_measured, "placed": bound,
        "latency_s": round(dt, 4),
        "pods_per_s": round(bound / dt, 1) if dt else 0.0,
        "attempt_p50_ms": round(win.percentile(0.50) * 1000, 2),
        "attempt_p90_ms": round(win.percentile(0.90) * 1000, 2),
        "attempt_p99_ms": round(win.percentile(0.99) * 1000, 2),
        "store_shard_count": store.shard_count,
        "commit_subwaves": m.commit_subwave_duration.n,
        "commit_subwave_s_total": round(m.commit_subwave_duration.total, 4),
        "commit_subwave_overlap_s": round(
            m.commit_subwave_overlap.total, 4
        ),
        "watchers_terminated": store.watchers_terminated - terminated0,
        # overload-protection surface: events compacted by per-watcher
        # coalescing, watchers expired to relist, and the adaptive
        # window the loop settled on
        "watch_coalesced_total": ws["watch_coalesced_total"],
        "watch_expired_total": ws["watch_expired_total"],
        "batch_window_ms": round(m.batch_window_ms.total, 2),
        "overload_level": m.overload_level.total,
        "step_s_total": round(step_s, 4),
        # batch_solve now observes the EXPOSED solve cost (encode +
        # compile + the decode wait the host blocked on); readback hidden
        # behind the pop window lands in decode_overlap_s
        "solve_s_total": round(m.batch_solve_duration.total, 4),
        "solve_compile_s": round(m.solve_compile_duration.total, 4),
        "decode_overlap_s": round(m.decode_overlap.total, 4),
        "wave_solves": m.solve_wave_count.n,
        "wave_fallbacks_total": round(m.solve_wave_fallbacks.total, 1),
        # total solver XLA traces this config's full loop performed
        # (retrace tracker mirror; churn legitimately walks buckets, so
        # this is reported, not gated)
        "solve_retrace_total": round(m.solve_retrace_total.total, 1),
        # incremental-solve accounting (ISSUE 14): partials rows served
        # warm vs re-evaluated across the run, and the resulting hit rate
        "partials_hit_rows": int(m.partials_hit_rows.total),
        "partials_recomputed_rows": int(m.partials_recomputed_rows.total),
        "partials_hit_rate": round(
            m.partials_hit_rows.total
            / max(
                m.partials_hit_rows.total + m.partials_recomputed_rows.total,
                1.0,
            ),
            4,
        ),
        "commit_s_total": round(commit_s, 4),
        "commit_overlap_s": round(overlap_s, 4),
        "commit_waves": m.commit_wave_size.n,
        "commit_share_of_step": round(
            exposed / (step_s + exposed), 4
        ) if step_s + exposed > 0 else 0.0,
    }


# Sustained-churn budget, enforced under BENCH_STRICT=1: the control
# plane must hold a CONSTANT arrival stream with zero destructively-
# terminated watchers.  History: 1050 (pre-sharding) -> 1300 (the
# (kind, namespace)-sharded store) -> 4000 with the pipelined
# multi-lane cycle (ISSUE 12): speculative solve overlap keeps the
# device busy through every commit seam and streamed sub-wave commits
# start each shard's store write the moment its slice of the wave
# stages -> 12000 with the columnar host plane (ISSUE 16): vectorized
# snapshot encode, framed group-commit journal writes, and chunked
# watch fan-out take the host encode/commit path off the critical
# rate.  The generator must outrun the floor (measured pods/s can
# never beat the arrival stream), so the stream default rises with it
# — and BENCH_C6S_RAMP=1 measures the true capacity knee instead of
# self-capping at the configured pace.
STRICT_SUSTAINED_MIN_PODS_PER_S = 12_000.0
# Crash-restart budget (ISSUE 8): after the sustained run the store is
# restarted from its journal+snapshot and must recover the full 50k-node
# / 4k-pod state — snapshot load + journal-suffix replay — inside this
# wall-clock budget with ZERO pods lost or unbound in the recovered
# state.  The bound is intentionally loose against today's measured
# recovery (the gate catches unbounded-replay regressions, not noise).
STRICT_RECOVERY_BUDGET_MS = 30_000.0


def config6_sustained():
    """50k-node sustained churn: a CONSTANT pod arrival stream (not a
    burst) against hollow-node heartbeats — the millions-of-users shape.
    The backpressured watch fan-out + adaptive batch window must hold a
    minimum sustained pods/s with `watchers_terminated == 0`; coalescing
    and Expired-relist absorb any consumer that falls behind.

    The run is JOURNALED (interval group-commit — the write-heavy
    deployment shape) and ends with a crash-restart phase: graceful
    close (drains the final dirty batch), then a fresh Store recovers
    from checkpoint snapshot + journal suffix.  BENCH_STRICT gates the
    recovery wall time and zero lost pods."""
    import tempfile
    import threading

    from kubernetes_tpu import kubemark
    from kubernetes_tpu.api import store as st
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    from kubernetes_tpu.perf.collectors import histogram_baseline

    # arrival pacing bounds measurable sustained throughput from above
    # (bound/dt can never beat the stream rate): the 12k STRICT floor
    # needs a stream faster than the floor.  Both knobs are
    # environment-configurable so a capacity hunt does not mean
    # editing the bench:
    #   BENCH_C6S_ARRIVAL=<pods/s>  constant-stream rate
    #       (default 16k — comfortably above the STRICT floor so the
    #       gate measures the control plane, not the generator)
    #   BENCH_C6S_RAMP=1  ramp mode: step the rate up each segment
    #       until the backlog diverges and report the capacity knee
    n_nodes, n_measured = 50_000, 12_000
    arrival_rate = float(os.environ.get("BENCH_C6S_ARRIVAL", "16000"))
    ramp = os.environ.get("BENCH_C6S_RAMP", "") == "1"
    journal_dir = tempfile.mkdtemp(prefix="bench_c6s_")
    journal = os.path.join(journal_dir, "journal.jsonl")
    store = st.Store(
        journal_path=journal, journal_sync="interval", shards=8
    )
    hollow = kubemark.HollowCluster(
        store, n_nodes, heartbeat_interval=10.0
    ).start()
    sched = Scheduler(store, batch_size=1024)
    sched.start()

    def mk(i, prefix):
        # spread the stream across namespaces (the fleet shape): a
        # single-namespace stream hashes every bind wave onto ONE store
        # shard, which silently disables both the concurrent sub-wave
        # commits (PR 9) and the streamed per-shard hand-off (ISSUE 12)
        return (
            make_pod(f"{prefix}-{i}", namespace=f"team-{i % 16}")
            .req(cpu_milli=100 + (i % 5) * 100, mem=256 * MI)
            .obj()
        )

    sched.warmup([mk(i, "warm") for i in range(1024)])
    sched.wait_for_idle(timeout=240)
    # checkpoint the warm 50k-node baseline so the recovery phase below
    # measures snapshot + MEASURED-WINDOW suffix, not setup history
    store.checkpoint()

    terminated0 = store.watchers_terminated
    baseline = histogram_baseline(sched.metrics)

    def _pace(start, count, rate):
        """Create pods [start, start+count) paced at `rate` pods/s —
        the constant-stream primitive both modes share."""
        period = 1.0 / rate
        next_t = time.perf_counter()
        for i in range(start, start + count):
            store.create(mk(i, "c6s"))
            next_t += period
            lag = next_t - time.perf_counter()
            if lag > 0:
                time.sleep(lag)

    def _bound_now():
        return sum(
            1
            for p in sched.informers.informer("Pod").list()
            if p.meta.name.startswith("c6s-") and p.spec.node_name
        )

    knee_rate = 0.0
    t0 = time.perf_counter()
    if ramp:
        # ramp mode: a constant stream can only ever report
        # min(capacity, configured rate) — a self-cap whenever the
        # knob lags the control plane.  Step the rate up per segment;
        # a segment whose backlog drains within the settle budget
        # advances the knee, one whose backlog diverges ends the hunt.
        rate = max(arrival_rate / 4.0, 2_000.0)
        injected = 0
        while injected < n_measured:
            seg = min(max(int(rate * 0.4), 512), n_measured - injected)
            _pace(injected, seg, rate)
            injected += seg
            settle = time.monotonic() + 1.0
            backlog = injected - _bound_now()
            while backlog > 0 and time.monotonic() < settle:
                time.sleep(0.02)
                backlog = injected - _bound_now()
            # a residue under 5% of one second's arrivals is pipeline
            # fill, not divergence
            if backlog <= max(int(rate * 0.05), 64):
                knee_rate = rate
                rate *= 1.5
            else:
                break
        arrival_rate = rate  # the rate the stream ended on
        n_measured = injected
    else:
        # the constant arrival stream: pace creates at arrival_rate
        # instead of dumping a burst — the batch window must adapt to
        # the stream
        _pace(0, n_measured, arrival_rate)
    deadline = time.monotonic() + 600
    while time.monotonic() < deadline:
        bound = sum(
            1
            for p in sched.informers.informer("Pod").list()
            if p.meta.name.startswith("c6s-") and p.spec.node_name
        )
        if bound >= n_measured:
            break
        time.sleep(0.05)
    dt = time.perf_counter() - t0
    sched.stop()
    hollow.stop()
    m = sched.metrics
    if knee_rate:
        m.c6s_arrival_knee.set(knee_rate)
    ws = store.watch_stats()
    from kubernetes_tpu.perf.collectors import MetricsCollector

    win = MetricsCollector(m, baseline=baseline)._windowed(
        "scheduler_scheduling_attempt_duration_seconds",
        m.scheduling_attempt_duration,
    )
    commit_s = m.commit_wave_duration.total
    overlap_s = m.pipeline_overlap.total
    exposed = max(commit_s - overlap_s, 0.0)
    step_s = m.schedule_batch_duration.total
    # crash-restart phase: graceful close (interval-sync's final dirty
    # batch flushes), then recover a fresh store from the same files —
    # the BENCH_STRICT recovery gate
    store.close()
    t_rec = time.perf_counter()
    recovered = st.Store(journal_path=journal)
    recovery_wall_ms = (time.perf_counter() - t_rec) * 1000.0
    rec_bound = sum(
        1
        for p in recovered.list("Pod")[0]
        if p.meta.name.startswith("c6s-") and p.spec.node_name
    )
    return {
        "nodes": n_nodes, "pods": n_measured, "placed": bound,
        "arrival_rate_pods_per_s": arrival_rate,
        # the ramp hunt's capacity knee (0.0 in constant-stream mode):
        # the highest arrival rate whose backlog stayed bounded
        "arrival_knee_pods_per_s": knee_rate,
        "recovery_ms": round(recovery_wall_ms, 1),
        "recovery_snapshot_records": recovered.snapshot_records,
        "recovery_suffix_records": recovered.journal_suffix_records,
        "recovery_lost_pods": bound - rec_bound,
        "latency_s": round(dt, 4),
        "pods_per_s": round(bound / dt, 1) if dt else 0.0,
        "attempt_p50_ms": round(win.percentile(0.50) * 1000, 2),
        "attempt_p90_ms": round(win.percentile(0.90) * 1000, 2),
        "attempt_p99_ms": round(win.percentile(0.99) * 1000, 2),
        "watchers_terminated": store.watchers_terminated - terminated0,
        "watch_coalesced_total": ws["watch_coalesced_total"],
        "watch_expired_total": ws["watch_expired_total"],
        "watch_queue_depth": ws["watch_queue_depth"],
        "batch_window_ms": round(m.batch_window_ms.total, 2),
        "overload_level": m.overload_level.total,
        "overload_shed_total": m.overload_shed_total.total,
        "commit_waves": m.commit_wave_size.n,
        "commit_s_total": round(commit_s, 4),
        "commit_overlap_s": round(overlap_s, 4),
        "commit_share_per_step": round(
            exposed / (step_s + exposed), 4
        ) if step_s + exposed > 0 else 0.0,
        "store_shard_count": store.shard_count,
        "commit_subwaves": m.commit_subwave_duration.n,
        "commit_subwave_s_total": round(m.commit_subwave_duration.total, 4),
        "commit_subwave_overlap_s": round(
            m.commit_subwave_overlap.total, 4
        ),
        "solve_s_total": round(m.batch_solve_duration.total, 4),
        # incremental-solve accounting (ISSUE 14): warm-row hit rate and
        # rows re-evaluated across the sustained stream
        "partials_hit_rows": int(m.partials_hit_rows.total),
        "partials_recomputed_rows": int(m.partials_recomputed_rows.total),
        "partials_hit_rate": round(
            m.partials_hit_rows.total
            / max(
                m.partials_hit_rows.total + m.partials_recomputed_rows.total,
                1.0,
            ),
            4,
        ),
        # pipelined multi-lane cycle (ISSUE 12): lanes in force,
        # per-lane share of the sustained rate, the speculation hit
        # rate (1 - invalidated/dispatched) and the commit lead
        # streaming bought each sub-wave
        "lanes": int(m.lane_count.total) or 1,
        "pods_per_s_per_lane": round(
            (bound / dt) / max(int(m.lane_count.total) or 1, 1), 1
        ) if dt else 0.0,
        "speculative_solves": int(m.speculative_solves_total.total),
        "misspeculations": int(m.misspeculation_total.total),
        "speculation_hit_rate": round(
            1.0
            - m.misspeculation_total.total
            / max(m.speculative_solves_total.total, 1.0),
            4,
        ),
        "subwave_stream_handoffs": m.subwave_stream_lead_ms.n,
        "subwave_stream_lead_ms_p50": round(
            m.subwave_stream_lead_ms.percentile(0.50), 2
        ),
        "subwave_stream_lead_ms_p99": round(
            m.subwave_stream_lead_ms.percentile(0.99), 2
        ),
    }


def config7():
    """c7: 100k hollow nodes / 2048-pod batches solved SHARDED on a
    forced 8-device host-platform mesh — the ≥100k-node scale the
    single chip cannot hold (ROADMAP's structural unlock past 50k).

    Measures the steady mesh-mode schedule_pending step (sharded
    wavefront + NamedSharding-resident mirror), dirtying a bounded set
    of rows between steps so the report can assert that steady-state
    host→device transfer is O(changed rows), not O(N), via the mirror
    delta/resync counters.  A small parity workload per solver family
    (fit/greedy, spread/wavefront, gang/auction) checks mesh vs
    single-chip assignment identity — BENCH_STRICT fails on any
    divergence, on a steady recompile, or on unbounded mirror traffic."""
    import jax

    from kubernetes_tpu.analysis import retrace
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.parallel.sharded import make_mesh
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    n_devices = len(jax.devices())
    mesh = make_mesh(min(8, n_devices))
    n_nodes, n_pods, dirty_rows = 100_000, 2_048, 16

    # -- mesh vs single-chip parity, one workload per solver family ----
    small_nodes = _mk_nodes(512, zones=8)

    def fit_pods():
        return _mk_basic_pods(256, seed=71, prefix="c7p-fit")

    def spread_pods():
        rng = np.random.default_rng(72)
        return [
            make_pod(f"c7p-sp-{i}")
            .req(cpu_milli=int(rng.choice([100, 250, 500])), mem=256 * MI)
            .label("app", f"svc-{i % 20}")
            .spread(2, api.LABEL_ZONE, "DoNotSchedule", {"app": f"svc-{i % 20}"})
            .obj()
            for i in range(128)
        ]

    def gang_pods():
        rng = np.random.default_rng(73)
        return [
            make_pod(f"c7p-g-{i}")
            .req(cpu_milli=int(rng.choice([250, 500])), mem=256 * MI)
            .group(f"gang-{i % 4}")
            .obj()
            for i in range(256)
        ]

    mesh_parity = {}
    for label, mk_parity in (
        ("fit", fit_pods), ("spread", spread_pods), ("gang", gang_pods),
    ):
        pods = mk_parity()
        single = _Runner(small_nodes, mode="auto")
        multi = _Runner(small_nodes, mode="auto", mesh=mesh)
        mesh_parity[label] = (
            single.sched.schedule_pending(pods)
            == multi.sched.schedule_pending(pods)
        )

    # -- the 100k-node sharded steady step -----------------------------
    nodes = _mk_nodes(n_nodes, zones=64)
    runner = _Runner(nodes, mode="greedy", mesh=mesh)  # pinned: sharded wavefront
    mirror = runner.sched._mirror

    step = [0]

    def mk(tag):
        # dirty a bounded row set between steps: the steady-state mirror
        # sync must move exactly these rows, not the 100k-node snapshot
        base = step[0] * dirty_rows
        for j in range(dirty_rows):
            p = make_pod(f"c7-bind-{tag}-{j}").req(cpu_milli=10, mem=MI).obj()
            runner.sched.assume(p, f"node-{(base + j * 97) % n_nodes}")
        step[0] += 1
        return [
            make_pod(f"c7-{tag}-{i}")
            .req(cpu_milli=100 + (i % 5) * 100, mem=256 * MI)
            .obj()
            for i in range(n_pods)
        ]

    retrace.clear_steady()
    _, first_s, _ = runner.step(mk("warmup"))
    retrace.mark_steady()
    steady0 = retrace.steady_total()
    resync0, delta0 = mirror.resync_total, mirror.delta_rows_total
    names, dt, samples, best_t = None, None, [], {}
    for k in range(_Runner.SAMPLES):
        nms, d, lt = runner.step(mk(f"run{k}"))
        samples.append(round(d, 4))
        if dt is None or d < dt:
            names, dt, best_t = nms, d, lt
    steady_recompiles = retrace.steady_total() - steady0
    retrace.clear_steady()
    delta_rows = mirror.delta_rows_total - delta0
    resyncs = mirror.resync_total - resync0
    dirtied = _Runner.SAMPLES * dirty_rows
    run = _Run(
        names, sum(n is not None for n in names), dt, samples, first_s,
        best_t, steady_recompiles,
    )
    return run.report(
        n_nodes, n_pods,
        solve_shard_count=int(mesh.devices.size),
        mesh_parity=mesh_parity,
        watchers_terminated=0,  # raw-solver config: no store in the loop
        # steady host→device traffic: the delta path must have carried
        # exactly the dirtied rows with zero full resyncs — O(changed
        # rows), not O(N) (BENCH_STRICT gates on the bounded flag)
        mirror_delta_rows=delta_rows,
        mirror_resync_total=resyncs,
        dirtied_rows=dirtied,
        mirror_delta_bounded=bool(resyncs == 0 and delta_rows <= dirtied),
        sharded_solve_fallbacks=runner.sched.sharded_fallbacks,
        **_wave_stats(runner),
    )


# c9 preemption gates (BENCH_STRICT=1): the mixed-priority churn's
# batched PostFilter must hold a minimum sustained preemption rate,
# plan identically to the sequential per-pod loop AND the pure-Python
# oracle, never double-bind a preemptor or evicted victim, keep
# PDB-guarded victims alive while unguarded alternatives exist, and the
# batched planning phase must beat the sequential walk by at least
# STRICT_PREEMPT_SPEEDUP_MIN on the same frozen trace.
STRICT_PREEMPT_MIN_PER_S = 0.5  # measured 1.43/s on a 1-CPU host
STRICT_PREEMPT_SPEEDUP_MIN = 5.0  # measured 9.0x on the frozen trace


def config9():
    """c9: mixed-priority preemption churn at 20k nodes with PDBs — the
    batched PostFilter (one [P, N, K] dry-run per pass,
    scheduler/preemption.py shared_pass) as a first-class workload.

    Phase A (live): every node is filled by a low-priority victim
    (every 4th node's victim guarded by a zero-budget PDB), then a
    mixed-priority preemptor stream (50/100/200) arrives — each
    preemptor needs one eviction, so sustained PostFilter work is the
    only way the stream binds.  An event audit asserts bound-exactly-
    once for preemptors AND evicted victims.

    Phase B (frozen trace): the SAME failed-pod set is planned twice on
    an identical 20k-node state — once through the shared batched pass,
    once through the sequential per-pod walk — proving plan parity and
    measuring the exposed PostFilter planning speedup; a 256-node
    randomized sub-state checks oracle parity (the documented
    reprieve-policy divergence stays pinned).  The planning phase runs
    under the retrace tracker with a steady window: zero recompiles."""
    import threading
    from collections import defaultdict

    from kubernetes_tpu.analysis import retrace
    from kubernetes_tpu.api import store as st
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.scheduler.cache import SchedulerCache
    from kubernetes_tpu.scheduler.config import SchedulerConfiguration
    from kubernetes_tpu.scheduler.metrics import Registry
    from kubernetes_tpu.scheduler.preemption import PreemptionEvaluator
    from kubernetes_tpu.testing.oracle import Oracle
    from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod

    n_nodes, n_preempt = 20_000, 128

    def mk_nodes():
        return [
            make_node(f"node-{i}")
            .capacity(cpu_milli=2000, mem=8 * GI, pods=16)
            .zone(f"zone-{i % 16}")
            .obj()
            for i in range(n_nodes)
        ]

    def mk_victim(i):
        pw = (
            make_pod(f"victim-{i}")
            .req(cpu_milli=1600, mem=GI // 2)
            .priority(i % 5)
            .node_name(f"node-{i}")
        )
        if i % 4 == 0:
            pw = pw.labels(app="guarded")
        p = pw.obj()
        p.status.phase = "Running"
        return p

    def mk_preemptor(i, prefix="hi"):
        return (
            make_pod(f"{prefix}-{i}")
            .req(cpu_milli=1800, mem=GI // 2)
            .priority([50, 100, 200][i % 3])
            .obj()
        )

    # -- phase A: live mixed-priority churn ----------------------------
    store = st.Store(shards=8)
    nodes = mk_nodes()
    for nd in nodes:
        store.create(nd)
    for i in range(n_nodes):
        store.create(mk_victim(i))
    pdb = api.PodDisruptionBudget(
        meta=api.ObjectMeta(name="guard", namespace="default"),
        spec=api.PodDisruptionBudgetSpec(
            selector=api.LabelSelector(match_labels={"app": "guarded"})
        ),
    )
    pdb.status.disruptions_allowed = 0
    store.create(pdb)

    # bound-exactly-once audit over every committed event (preemptors
    # AND victims: an evicted victim must never re-bind)
    bound_nodes = defaultdict(set)
    audit_lock = threading.Lock()
    orig_dispatch = store._dispatch
    orig_wave = store._dispatch_wave

    def check(ev):
        if ev.kind == "Pod" and ev.obj.spec.node_name:
            with audit_lock:
                key = f"{ev.obj.meta.namespace}/{ev.obj.meta.name}"
                bound_nodes[key].add(ev.obj.spec.node_name)

    def dispatch(ev):
        check(ev)
        orig_dispatch(ev)

    def dispatch_wave(kind, events):
        for ev in events:
            check(ev)
        orig_wave(kind, events)

    store._dispatch = dispatch
    store._dispatch_wave = dispatch_wave

    # the latency SLO scales with the scenario: a 20k-node cycle on one
    # host runs seconds of decode, and the DEFAULT 0.5s SLO would pin
    # the overload ladder at level 2 (preemption deferred) on platform
    # slowness alone; the short unschedulable flush is the liveness
    # safety net for parked preemptors between eviction wake-ups
    sched = Scheduler(
        store, batch_size=256,
        config=SchedulerConfiguration(
            batch_latency_slo_seconds=10.0,
            unschedulable_flush_seconds=2.0,
        ),
    )
    sched.start()
    sched.warmup([mk_preemptor(i, "warm") for i in range(64)])
    terminated0 = store.watchers_terminated
    m = sched.metrics
    t0 = time.perf_counter()
    for i in range(n_preempt):
        store.create(mk_preemptor(i))
    deadline = time.monotonic() + 600
    bound = 0
    while time.monotonic() < deadline:
        bound = sum(
            1
            for p in sched.informers.informer("Pod").list()
            if p.meta.name.startswith("hi-") and p.spec.node_name
        )
        if bound >= n_preempt:
            break
        time.sleep(0.1)
    dt = time.perf_counter() - t0
    nominated = m.preemption_attempts.get("nominated")
    sched.stop()
    survivors = {p.meta.name for p in store.list("Pod")[0]}
    guarded_total = sum(1 for i in range(0, n_nodes, 4))
    guarded_alive = sum(
        1 for i in range(0, n_nodes, 4) if f"victim-{i}" in survivors
    )
    evicted = sum(
        1 for i in range(n_nodes) if f"victim-{i}" not in survivors
    )
    double_bound = sum(1 for v in bound_nodes.values() if len(v) > 1)

    # -- phase B: frozen-trace planning parity + speedup ----------------
    tpu = TPUBatchScheduler()
    for nd in nodes:
        tpu.add_node(nd)
    for i in range(n_nodes):
        v = mk_victim(i)
        tpu.assume(v, v.spec.node_name)
    ev = PreemptionEvaluator(
        tpu, SchedulerCache(tpu.state), st.Store(), Registry()
    )
    failed = [mk_preemptor(i, "plan") for i in range(16)]

    def plan_key(got):
        if got is None:
            return None
        cands, ranked, min_k = got
        row, name, victims, _ = cands[ranked[0]]
        return (name, [v.meta.name for v in victims[: int(min_k[ranked[0]])]])

    retrace.clear_steady()
    with ev.shared_pass(failed):
        warm_batched = [plan_key(ev._candidates(p)) for p in failed]
    warm_classic = plan_key(ev._candidates_classic(failed[0]))
    retrace.mark_steady()
    steady0 = retrace.steady_total()
    t_b = time.perf_counter()
    with ev.shared_pass(failed):
        batched_plans = [plan_key(ev._candidates(p)) for p in failed]
    t_batched = time.perf_counter() - t_b
    t_s = time.perf_counter()
    seq_plans = [plan_key(ev._candidates_classic(p)) for p in failed]
    t_sequential = time.perf_counter() - t_s
    steady_recompiles = retrace.steady_total() - steady0
    retrace.clear_steady()
    plan_parity = batched_plans == seq_plans
    del warm_batched, warm_classic

    # oracle parity on a randomized 256-node sub-state (no PDBs — the
    # oracle mirrors the minimal-prefix policy, not budgets)
    rng = np.random.default_rng(91)
    small_nodes = [
        make_node(f"o{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=20).obj()
        for i in range(256)
    ]
    small_bound = []
    for i in range(512):
        p = (
            make_pod(f"ov{i}")
            .req(cpu_milli=int(rng.choice([500, 1000, 1500])), mem=GI)
            .priority(int(rng.integers(0, 5)))
            .node_name(f"o{i % 256}")
            .obj()
        )
        small_bound.append(p)
    tpu2 = TPUBatchScheduler()
    for nd in small_nodes:
        tpu2.add_node(nd)
    for p in small_bound:
        tpu2.assume(p, p.spec.node_name)
    ev2 = PreemptionEvaluator(
        tpu2, SchedulerCache(tpu2.state), st.Store(), Registry()
    )
    oracle_parity = True
    for j in range(6):
        preemptor = (
            make_pod(f"op{j}").req(cpu_milli=3500, mem=GI).priority(100).obj()
        )
        with ev2.shared_pass([preemptor]):
            got = ev2._candidates(preemptor)
        want = Oracle(small_nodes, bound_pods=small_bound).preempt(preemptor)
        have = plan_key(got)
        if want is None:
            oracle_parity &= have is None
        else:
            oracle_parity &= have is not None and have[0] == want[0] and (
                sorted(have[1]) == sorted(v.meta.name for v in want[1])
            )

    return {
        "nodes": n_nodes, "preemptors": n_preempt, "placed": bound,
        "latency_s": round(dt, 4),
        "preempted": nominated,
        "preemptions_per_s": round(nominated / dt, 2) if dt else 0.0,
        "victims_evicted": evicted,
        "guarded_total": guarded_total,
        "guarded_alive": guarded_alive,
        "guarded_survived": bool(guarded_alive == guarded_total),
        "double_bound": double_bound,
        "watchers_terminated": store.watchers_terminated - terminated0,
        "preempt_batch_passes": m.preemption_batch_size.n,
        "preempt_batch_size_avg": round(m.preemption_batch_size.average, 2),
        "preempt_solve_s_total": round(
            m.preemption_solve_duration.total, 4
        ),
        "conflict_serializations": (
            m.preemption_conflict_serializations.total
        ),
        "pdb_blocked_total": m.preemption_pdb_blocked_total.total,
        "preemption_victims": m.preemption_victims.n,
        # phase B: the exposed PostFilter planning cost on one frozen
        # 16-pod trace — batched (one encode + one [P, N, K] dispatch)
        # vs the sequential per-pod walk the batch replaced
        "postfilter_batched_s": round(t_batched, 4),
        "postfilter_sequential_s": round(t_sequential, 4),
        "postfilter_speedup": round(t_sequential / t_batched, 2)
        if t_batched else 0.0,
        "plan_parity": plan_parity,
        "oracle_parity": oracle_parity,
        "steady_recompiles": steady_recompiles,
    }


# c8 fleet gates (BENCH_STRICT=1): the 100k-node hollow fleet's
# sustained lifecycle soak must lose no pod, double-bind no pod,
# terminate no watcher, and the post-soak kill-free recovery (per-shard
# snapshot + journal suffix) must land inside the shared budget.
STRICT_FLEET_NODES = 100_000
STRICT_FLEET_SOAK_PODS = 12_288


def config8():
    """c8: the kubemark fleet harness as a first-class store benchmark —
    100k hollow nodes on an 8-shard JOURNALED store (interval group
    commit), batched wave-committed heartbeats, and a sustained
    pod-lifecycle soak (create → concurrent per-shard bind sub-waves →
    hollow kubelets run → delete) across 8 namespaces so every round
    spreads over the shards.  Reports SLO-style p50/p90/p99 lifecycle
    latency and ends with the crash-restart phase: graceful close, then
    a fresh store recovers all 8 shards (snapshot + suffix) under the
    STRICT_RECOVERY_BUDGET_MS gate.  No solver in the loop: this is the
    control-plane ceiling the solve bench can't see."""
    import tempfile

    from kubernetes_tpu import kubemark
    from kubernetes_tpu.api import store as st

    n_nodes, soak_pods = STRICT_FLEET_NODES, STRICT_FLEET_SOAK_PODS
    journal_dir = tempfile.mkdtemp(prefix="bench_c8_")
    journal = os.path.join(journal_dir, "journal.jsonl")
    store = st.Store(
        journal_path=journal, journal_sync="interval", shards=8
    )
    fleet = kubemark.FleetHarness(
        store, n_nodes, namespaces=8, heartbeat_interval=60.0,
        bind_concurrency=4,
    )
    t_reg = time.perf_counter()
    fleet.start()
    register_s = time.perf_counter() - t_reg
    # checkpoint the registered fleet so the recovery phase measures
    # per-shard snapshot + SOAK-WINDOW suffix, not registration history
    store.checkpoint()
    terminated0 = store.watchers_terminated
    report = fleet.soak(total_pods=soak_pods, round_pods=2_048)
    fleet.stop()
    ws = store.watch_stats()
    nodes_before = len(store.list("Node")[0])
    store.close()
    t_rec = time.perf_counter()
    recovered = st.Store(journal_path=journal)
    recovery_wall_ms = (time.perf_counter() - t_rec) * 1000.0
    report.update({
        "register_s": round(register_s, 2),
        "store_shard_count": store.shard_count,
        "watchers_terminated": store.watchers_terminated - terminated0,
        "watch_coalesced_total": ws["watch_coalesced_total"],
        "watch_expired_total": ws["watch_expired_total"],
        "recovery_ms": round(recovery_wall_ms, 1),
        "recovery_shards": recovered.shard_count,
        "recovery_snapshot_records": recovered.snapshot_records,
        "recovery_suffix_records": recovered.journal_suffix_records,
        "recovery_lost_nodes": nodes_before - len(
            recovered.list("Node")[0]
        ),
    })
    return report


# c10 slice-packing gates (BENCH_STRICT=1): the carve-out scorer must
# realize contiguous placements for nearly every gang of the churn mix
# (prefer policy — quality is the scorer's job, not a filter's) and the
# end-state fragmentation must stay bounded after arrivals/departures.
STRICT_SLICE_CONTIG_MIN = 0.9   # contiguous gangs / completed gangs
STRICT_SLICE_FRAG_MAX = 0.5     # final cluster fragmentation score


def config10():
    """c10: slice packing — 4096 nodes as 64 slices of 4x4x4, mixed gang
    shapes arriving and leaving through the carve-out scorer (prefer
    policy).  Gates placement QUALITY, not just throughput: the
    fragmentation score of the end state and the contiguous-placement
    rate across the churn, plus steady_recompiles == 0 (every round
    reuses one executable — fixed gang mix, one pad bucket)."""
    from kubernetes_tpu.analysis import retrace
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
    from kubernetes_tpu.ops import slices as slices_ops
    from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod

    rng = np.random.default_rng(10)
    dims, n_slices = (4, 4, 4), 64
    nodes = [
        make_node(f"s{s:02d}-{x}{y}{z}")
        .capacity(cpu_milli=16000, mem=32 * GI, pods=110)
        .label(api.LABEL_TPU_SLICE, f"slice-{s:02d}")
        .label(api.LABEL_TPU_TOPOLOGY, "4x4x4")
        .label(api.LABEL_TPU_COORDS, f"{x},{y},{z}")
        .obj()
        for s in range(n_slices)
        for z in range(dims[2])
        for y in range(dims[1])
        for x in range(dims[0])
    ]
    sched = TPUBatchScheduler(carveout_policy="prefer")
    for nd in nodes:
        sched.add_node(nd)

    # fixed per-round gang mix (same pod count + gang count each round,
    # so every round hits one executable): 26 gangs / 208 pods per round
    mix = (("2x2x1", 4, 12), ("2x2x2", 8, 8), ("4x2x2", 16, 4),
           ("4x4x1", 16, 2))

    def make_round(r):
        pods, gid = [], 0
        for shape, size, count in mix:
            for _k in range(count):
                for i in range(size):
                    p = (
                        make_pod(f"c10-r{r}-g{gid}-{i}")
                        .req(cpu_milli=100)
                        .group(f"c10-r{r}-g{gid}")
                        .obj()
                    )
                    p.spec.tpu_topology = shape
                    pods.append(p)
                gid += 1
        return pods

    live = []  # (pod, node) per placed member, grouped per gang
    stats = {"completed": 0, "contiguous": 0, "fallbacks": 0,
             "carveouts": 0, "placed": 0, "arrived": 0}

    def run_round(r, timed):
        pods = make_round(r)
        t0 = time.perf_counter()
        names = sched.schedule_pending(pods)
        dt = time.perf_counter() - t0
        ds = sched.last_solve
        stats["arrived"] += len(pods)
        stats["placed"] += sum(n is not None for n in names)
        stats["carveouts"] += ds.carveouts or 0
        stats["contiguous"] += ds.contiguous_gangs or 0
        stats["fallbacks"] += ds.carveout_fallbacks or 0
        stats["completed"] += (ds.contiguous_gangs or 0) + (
            ds.carveout_fallbacks or 0
        )
        by_gang = {}
        for p, n in zip(pods, names):
            if n is not None:
                sched.assume(p, n)
                by_gang.setdefault(p.spec.scheduling_group, []).append((p, n))
        live.extend(by_gang.values())
        return dt, float(ds.frag_score or 0.0)

    rounds = 6
    retrace.clear_steady()
    warm_dt, _ = run_round(0, timed=False)  # compiles the executable
    retrace.mark_steady()
    steady0 = retrace.steady_total()
    walls, frags = [], []
    for r in range(1, rounds):
        # departures: half the live gangs leave (seeded), freeing boxes
        rng.shuffle(live)
        for members in live[: len(live) // 2]:
            for p, n in members:
                sched.forget(p)
        del live[: len(live) // 2]
        dt, frag = run_round(r, timed=True)
        walls.append(dt)
        frags.append(frag)
    steady_recompiles = retrace.steady_total() - steady0
    retrace.clear_steady()
    final_frag = slices_ops.fragmentation_report(sched.state.tensors())
    contig_rate = stats["contiguous"] / max(stats["completed"], 1)
    pods_per_round = stats["arrived"] // rounds
    from kubernetes_tpu.kubemark import percentiles

    pct = percentiles(list(walls))
    return {
        "nodes": len(nodes), "pods": stats["arrived"],
        "placed": stats["placed"],
        "slices": n_slices, "slice_dims": "4x4x4",
        "rounds": rounds, "pods_per_round": pods_per_round,
        "latency_s": round(min(walls), 4),
        "pods_per_s": round(pods_per_round / min(walls), 1),
        "latency_p50_s": round(pct["p50"], 4),
        "latency_p90_s": round(pct["p90"], 4),
        "latency_p99_s": round(pct["p99"], 4),
        "commit_share_per_step": 0.0,
        "first_step_s": round(warm_dt, 4),
        "steady_recompiles": steady_recompiles,
        # the quality gates
        "carveouts": stats["carveouts"],
        "contiguous_gangs": stats["contiguous"],
        "carveout_fallbacks": stats["fallbacks"],
        "contiguous_rate": round(contig_rate, 4),
        "frag_score_per_round": [round(f, 4) for f in frags],
        "frag_score_final": round(final_frag["score"], 4),
    }


# c11 incremental-churn gates (BENCH_STRICT=1): with <=1% of node rows
# dirtied per cycle, the warm-started solve (device-resident partials,
# ISSUE 14) must beat the cold solve by at least this factor on the
# same frozen trace with bit-identical placements and zero steady
# recompiles.  Measured 4-7x per steady cycle on a CPU host.
STRICT_PARTIALS_SPEEDUP_MIN = 3.0
STRICT_PARTIALS_DIRTY_FRAC_MAX = 0.01


def config11():
    """c11: incremental churn at 50k nodes — the warm-started solve as
    a first-class workload.  A sustained service-shaped arrival stream
    (64 distinct selector/preferred pod classes recurring every cycle)
    against bounded churn: <=1% of node rows dirtied per cycle via
    assumes walking the cluster.

    Frozen-trace phase: the SAME (churn, batch) trace runs through a
    warm scheduler (PartialsCache on) and a cold one (off, the
    pre-ISSUE-14 path) sharing identical state mutations; every cycle's
    placements must be bit-identical and the cold/warm wall ratio is
    the gated speedup.  The warm side must also hold
    steady_recompiles == 0 — the partials refresh/gather kernels stay
    on their pad buckets."""
    from kubernetes_tpu.analysis import epochs, retrace
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    n_nodes, n_pods, n_svc, dirty_rows = 50_000, 128, 64, 256
    cycles = 4  # timed cycles after the warmup cycle
    nodes = _mk_nodes(n_nodes, zones=64)
    warm = TPUBatchScheduler(mode="greedy", use_partials=True)
    cold = TPUBatchScheduler(mode="greedy", use_partials=False)
    for nd in nodes:
        warm.add_node(nd)
        cold.add_node(nd)

    def mk(r):
        # the recurring service shapes: selector + preferred affinity
        # per svc — the [S, T, E, K, N] matching the warm start hoists
        pods = []
        for i in range(n_pods):
            svc = i % n_svc
            pods.append(
                make_pod(f"c11-r{r}-{i}")
                .req(cpu_milli=100 + (svc % 5) * 100, mem=256 * MI)
                .required_affinity(
                    api.LABEL_ZONE, api.OP_IN,
                    [f"zone-{svc % 64}", f"zone-{(svc + 1) % 64}",
                     f"zone-{(svc + 32) % 64}"],
                )
                .preferred_affinity(
                    10, api.LABEL_ZONE, api.OP_IN, [f"zone-{svc % 64}"]
                )
                .obj()
            )
        return pods

    def churn(r):
        # <=1% of rows dirtied: small binds walking the cluster (the
        # usage-generation rows the partials refresh re-evaluates)
        base = r * dirty_rows
        for j in range(dirty_rows):
            p = make_pod(f"c11-bind-r{r}-{j}").req(cpu_milli=10, mem=MI).obj()
            nm = f"node-{(base + j * 97) % n_nodes}"
            warm.assume(p, nm)
            cold.assume(p, nm)

    retrace.clear_steady()
    # warmup WITH churn: compiles the warm/cold solver executables AND
    # the partials kernels at their steady buckets.  Two warm solves on
    # purpose: the first sync is a FULL reset (eval kernel), only the
    # second hits the dirty-row refresh kernel the steady cycles use.
    churn(0)
    t0 = time.perf_counter()
    warm.schedule_pending(mk(0))
    warm_first = time.perf_counter() - t0
    cold.schedule_pending(mk(0))
    churn(100)
    warm.schedule_pending(mk(100))
    retrace.mark_steady()
    steady0 = retrace.steady_total()
    stats0 = dict(warm._partials.stats())
    audits0, violations0 = epochs.audits_total(), epochs.violations_total()
    warm_walls, cold_walls, parity = [], [], True
    for r in range(1, cycles + 1):
        churn(r)
        t0 = time.perf_counter()
        names_w = warm.schedule_pending(mk(r))
        warm_walls.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        names_c = cold.schedule_pending(mk(r))
        cold_walls.append(time.perf_counter() - t0)
        parity = parity and names_w == names_c
    steady_recompiles = retrace.steady_total() - steady0
    retrace.clear_steady()
    stats = warm._partials.stats()
    hit = stats["hit_rows_total"] - stats0["hit_rows_total"]
    recomputed = (
        stats["recomputed_rows_total"] - stats0["recomputed_rows_total"]
    )
    from kubernetes_tpu.kubemark import percentiles

    pct = percentiles(list(warm_walls))
    return {
        "nodes": n_nodes, "pods": n_pods * cycles,
        "pod_classes": n_svc, "cycles": cycles,
        "dirtied_rows_per_cycle": dirty_rows,
        "dirty_fraction": round(dirty_rows / n_nodes, 5),
        "latency_s": round(min(warm_walls), 4),
        "pods_per_s": round(n_pods / min(warm_walls), 1),
        "latency_p50_s": round(pct["p50"], 4),
        "latency_p90_s": round(pct["p90"], 4),
        "latency_p99_s": round(pct["p99"], 4),
        "commit_share_per_step": 0.0,
        "first_step_s": round(warm_first, 4),
        "steady_recompiles": steady_recompiles,
        # the frozen-trace gates
        "warm_walls_s": [round(w, 4) for w in warm_walls],
        "cold_walls_s": [round(w, 4) for w in cold_walls],
        "warm_parity": parity,
        "warm_speedup": round(sum(cold_walls) / sum(warm_walls), 2),
        # partials accounting over the timed window: rows served warm
        # vs re-evaluated (the O(changes) claim in numbers)
        "partials_hit_rows": hit,
        "partials_recomputed_rows": recomputed,
        "partials_hit_rate": round(hit / max(hit + recomputed, 1), 4),
        "partials_full_recomputes": stats["full_recomputes"],
        # graftcoh epoch audits over the timed window (main() arms the
        # auditor; 0/0 when run standalone-disarmed)
        "coherence_audits": epochs.audits_total() - audits0,
        "coherence_violations": epochs.violations_total() - violations0,
    }


# c12 autoscale-churn gates (BENCH_STRICT=1): under steady WITHIN-bucket
# node churn (±1% nodes/cycle at 50k nodes) the elastic node axis must
# hold zero full mirror re-uploads and zero steady recompiles;
# bucket-boundary oscillation under the shrink dwell must add zero
# resyncs AND zero recompiles (the hysteresis claim); the crossing
# itself must be absorbed by in-place resident grows whose device-side
# pad rows account exactly for the bucket deltas (mirror_grow_rows —
# host→device stays O(changed rows) throughout, gated like c7), at
# least STRICT_AUTOSCALE_WARM_SLOTS_MIN of the partials class rows must
# stay warm across the grow, and every cycle's placements must be
# bit-identical to the full-RESHARDED-rebuild oracle (incremental_grow
# valves off — every transition re-uploads and reseeds from scratch).
STRICT_AUTOSCALE_WARM_SLOTS_MIN = 0.9


def config12():
    """c12: autoscaler churn at 50k nodes — the elastic node axis as a
    first-class workload.

    Frozen-trace phase: a kubemark NodeGroupScaler generates the node
    add/remove stream (scale-ups, drains, deliberate oscillation around
    the 65536 pad-bucket boundary) and the SAME stream drives an
    elastic scheduler (in-place mirror/partials grows) and the
    full-RESHARDED-rebuild oracle (incremental_grow valves off); every
    cycle solves a recurring service-shaped batch and placements must
    match bit-for-bit.  Measured: steady within-bucket churn (zero
    resyncs, zero recompiles), the boundary crossing (grow events, not
    re-uploads; partials class rows stay warm), oscillation under the
    shrink dwell (bucket pinned — zero shape flips), and the post-dwell
    drain shrink.

    Live phase: the existing HPA scales a Deployment against synthetic
    PodMetrics while the NodeGroupScaler (store-backed, CA-shaped
    reconcile policy) adds nodes under pending-pod pressure and drains
    them when idle — sustained node add/remove against the live
    scheduler loop, crossing pad buckets in both directions."""
    from kubernetes_tpu.analysis import retrace
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.kubemark import NodeGroupScaler
    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
    from kubernetes_tpu.testing.wrappers import MI, make_pod

    base, n_pods, n_svc = 50_000, 128, 32
    churn_half = base // 200  # 250 removed + 250 added = ±1% rows/cycle
    steady_cycles, osc_cycles = 4, 6
    boundary = 65_536  # pad_dim(50_000) — the oscillation axis
    over, under = boundary + 1_024, boundary - 1_024

    elastic = TPUBatchScheduler(mode="greedy", use_partials=True)
    oracle = TPUBatchScheduler(mode="greedy", use_partials=True)
    # the oracle: every node-axis transition takes the full (RESHARDED)
    # re-upload / full-reseed safety path — the parity reference
    oracle._mirror.incremental_grow = False
    oracle._partials.incremental_grow = False
    pair = (elastic, oracle)

    trace_scaler = NodeGroupScaler(group="node", zones=64)

    def apply(added, removed):
        for nd in added:
            for s in pair:
                s.add_node(nd)
        for name in removed:
            for s in pair:
                s.remove_node(name)

    apply(*trace_scaler.scale_to(base))

    def mk(r):
        pods = []
        for i in range(n_pods):
            svc = i % n_svc
            pods.append(
                make_pod(f"c12-r{r}-{i}")
                .req(cpu_milli=100 + (svc % 5) * 100, mem=256 * MI)
                .required_affinity(
                    api.LABEL_ZONE, api.OP_IN,
                    [f"zone-{svc % 64}", f"zone-{(svc + 1) % 64}",
                     f"zone-{(svc + 32) % 64}"],
                )
                .preferred_affinity(
                    10, api.LABEL_ZONE, api.OP_IN, [f"zone-{svc % 64}"]
                )
                .obj()
            )
        return pods

    parity = True

    def cycle(r):
        nonlocal parity
        names_e = elastic.schedule_pending(mk(r))
        names_o = oracle.schedule_pending(mk(r))
        parity = parity and names_e == names_o
        # assume the placements (the next cycle's usage churn — the
        # previous wave's picks are dirty rows, ISSUE 14's contract)
        for p, nm in zip(mk(r), names_e):
            if nm is not None and nm in elastic.state._rows:
                elastic.assume(p, nm)
                oracle.assume(p, nm)
        return names_e

    def churn(r):
        # ±1% membership churn: drain churn_half newest, add churn_half
        # fresh (scale down then up through the scaler so the node-name
        # stream is reproducible)
        apply(*trace_scaler.scale_to(trace_scaler.size() - churn_half))
        apply(*trace_scaler.scale_to(trace_scaler.size() + churn_half))

    retrace.clear_steady()
    # warmup: compile the 65536-bucket executables + the partials
    # eval/refresh kernels (two cycles — the refresh kernel only runs
    # once the store exists, the c11 discipline)
    churn(0)
    t0 = time.perf_counter()
    cycle(0)
    first_step_s = time.perf_counter() - t0
    churn(1)
    cycle(1)

    # -- phase S: steady WITHIN-bucket churn --------------------------------
    e0 = dict(elastic._mirror.stats())
    retrace.mark_steady()
    steady0 = retrace.steady_total()
    walls = []
    for r in range(2, 2 + steady_cycles):
        churn(r)
        t0 = time.perf_counter()
        cycle(r)
        walls.append(time.perf_counter() - t0)
    steady_recompiles = retrace.steady_total() - steady0
    retrace.clear_steady()
    eS = dict(elastic._mirror.stats())
    steady_resyncs = eS["resync_total"] - e0["resync_total"]
    steady_delta_rows = eS["delta_rows_total"] - e0["delta_rows_total"]
    # dirtied per steady cycle: removals + adds (static+usage gens each)
    # + the assumed placements of the previous cycle
    steady_dirtied = steady_cycles * (2 * 2 * churn_half + n_pods)

    # -- phase X: cross the boundary, then oscillate under the dwell --------
    slots_before = set(elastic._partials._slots.keys())
    full0 = elastic._partials.stats()["full_recomputes"]
    apply(*trace_scaler.scale_to(over))  # the crossing (one sync)
    cycle(100)
    grow_after_cross = dict(elastic._mirror.stats())
    for k in range(osc_cycles):
        apply(*trace_scaler.scale_to(under if k % 2 == 0 else over))
        cycle(101 + k)
    eX = dict(elastic._mirror.stats())
    osc_resyncs = eX["resync_total"] - grow_after_cross["resync_total"]
    # the dwell must pin the bucket across the oscillation: the crossing
    # is the ONLY shape change (grow_syncs moves once, then holds)
    osc_grows = eX["grow_syncs"] - grow_after_cross["grow_syncs"]
    slots_after = set(elastic._partials._slots.keys())
    warm_slots_frac = (
        len(slots_before & slots_after) / max(len(slots_before), 1)
    )
    partials_reseeds_x = (
        elastic._partials.stats()["full_recomputes"] - full0
    )

    # -- phase D: drain home; the shrink fires only after the dwell ---------
    apply(*trace_scaler.scale_to(base))
    pre_shrink_bucket = elastic.state.node_axis_bucket
    for k in range(elastic.state.bucket_shrink_dwell + 1):
        churn(200 + k)
        cycle(200 + k)
    post_shrink_bucket = elastic.state.node_axis_bucket
    eD = dict(elastic._mirror.stats())
    pD = dict(elastic._partials.stats())

    from kubernetes_tpu.kubemark import percentiles

    pct = percentiles(list(walls))
    live = _c12_live_phase()
    return {
        "nodes": base, "pods": n_pods, "pod_classes": n_svc,
        "churn_frac_per_cycle": round(2 * churn_half / base, 4),
        "latency_s": round(min(walls), 4),
        "pods_per_s": round(n_pods / min(walls), 1),
        "latency_p50_s": round(pct["p50"], 4),
        "latency_p90_s": round(pct["p90"], 4),
        "latency_p99_s": round(pct["p99"], 4),
        "commit_share_per_step": 0.0,
        "first_step_s": round(first_step_s, 4),
        "steady_recompiles": steady_recompiles,
        # the elastic-axis gates
        "oracle_parity": parity,
        "steady_resyncs": steady_resyncs,
        "steady_delta_rows": steady_delta_rows,
        "steady_dirtied_rows": steady_dirtied,
        "steady_delta_bounded": steady_delta_rows <= steady_dirtied,
        "grow_syncs": eD["grow_syncs"],
        "mirror_grow_rows": eD["grow_rows_total"],
        # every grow's device-side pad rows must account exactly for the
        # bucket deltas (one 65536->131072 crossing; the drain shrink
        # adds no rows) — anything more means a hidden re-upload
        "grow_rows_expected": 131_072 - 65_536,
        "grow_bounded": eD["grow_rows_total"] == 131_072 - 65_536,
        "osc_resyncs": osc_resyncs,
        "osc_grows": osc_grows,
        "warm_slots_frac": round(warm_slots_frac, 4),
        "partials_reseeds_in_osc": partials_reseeds_x,
        "partials_grows": pD["grows"],
        "pre_shrink_bucket": pre_shrink_bucket,
        "post_shrink_bucket": post_shrink_bucket,
        "shrink_served": post_shrink_bucket == boundary,
        "mirror_resync_total": eD["resync_total"],
        "compactions_total": elastic.state.compactions_total,
        "compaction_moved_rows": elastic.state.compaction_moved_rows_total,
        "scaler_nodes_added": trace_scaler.nodes_added,
        "scaler_nodes_removed": trace_scaler.nodes_removed,
        # top-level so the generic terminated gate sees the live phase
        "watchers_terminated": live["watchers_terminated"],
        **{f"live_{k}": v for k, v in live.items()},
    }


def _c12_live_phase():
    """The autoscaler-in-the-loop half of c12: a live Scheduler over a
    journal-less store while the existing HorizontalPodAutoscaler
    scales a Deployment (synthetic PodMetrics drive utilization) and a
    store-backed NodeGroupScaler reacts to pending-pod pressure / idle
    capacity — sustained node add/remove, pad buckets crossed in both
    directions, zero destructive watcher terminations."""
    import threading

    from kubernetes_tpu import kubemark
    from kubernetes_tpu.api import store as st
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.controllers import ControllerManager
    from kubernetes_tpu.controllers.deployment import DeploymentController
    from kubernetes_tpu.controllers.podautoscaler import (
        HorizontalPodAutoscalerController,
    )
    from kubernetes_tpu.controllers.replicaset import ReplicaSetController
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import MI

    store = st.Store()
    # the permanent fleet sits just UNDER the 512 pad bucket, so the
    # autoscaler's scale-up crosses a large boundary where the dirty
    # fraction is small enough for the in-place grow path (tiny fleets
    # cross small buckets in over-fraction bulk, which correctly takes
    # the full-upload safety path instead).  Hollow kubelets run the
    # status half (-> Running) for every hollow-* node; the scaler's
    # group shares the prefix so scaled-up nodes' pods run too.
    # base nodes are deliberately too small for the web pods (100m vs
    # 2000m requests): every replica PENDS until the scaler provisions
    # group capacity — the pressure signal the CA policy keys on
    hollow = kubemark.HollowCluster(
        store, 504, cpu_milli=100, heartbeat_interval=10.0
    ).start()
    scaler = kubemark.NodeGroupScaler(
        store, group="hollow-asg", cpu_milli=32000, mem=64 * kubemark.GI,
        max_nodes=64,
    )
    pods_per_node = 16  # 32000m / 2000m requests

    def hpa_factory(*args, **kw):
        return HorizontalPodAutoscalerController(
            *args, downscale_stabilization_s=0.2, **kw
        )

    hpa_factory.KIND = "HorizontalPodAutoscaler"
    mgr = ControllerManager(
        store,
        controllers=[DeploymentController, ReplicaSetController, hpa_factory],
    ).start()
    sched = Scheduler(store, batch_size=256)
    sched.start()
    stop = threading.Event()

    def autoscale_loop():
        # the CA-shaped reconcile: pending pods scale the group up,
        # idle group capacity drains it one step at a time
        while not stop.wait(0.05):
            pods, _ = store.list("Pod")
            pending = sum(1 for p in pods if not p.spec.node_name)
            used = {p.spec.node_name for p in pods if p.spec.node_name}
            idle = sum(
                1 for i in range(scaler.size())
                if f"{scaler.group}-{i}" not in used
            )
            try:
                scaler.reconcile(
                    pending, pods_per_node, idle_nodes=idle,
                    step=2, idle_headroom=1, up_step_cap=4,
                )
            except Exception:  # noqa: BLE001 — reconcile is best-effort
                pass

    ca = threading.Thread(target=autoscale_loop, daemon=True)
    ca.start()

    labels = {"app": "web"}
    deployment = api.Deployment(
        meta=api.ObjectMeta(name="web"),
        spec=api.DeploymentSpec(
            replicas=8,
            selector=api.LabelSelector(match_labels=labels),
            template=api.PodTemplateSpec(
                meta=api.ObjectMeta(labels=labels),
                spec=api.PodSpec(
                    containers=[
                        api.Container(
                            requests={api.CPU: 2000, api.MEMORY: 64 * MI}
                        )
                    ]
                ),
            ),
        ),
    )
    peak_target, idle_target = 192, 8
    unbound_at_peak = 0
    grow_syncs = 0
    replicas = 0
    peak_nodes = 0
    try:
        store.create(deployment)
        store.create(
            api.HorizontalPodAutoscaler(
                meta=api.ObjectMeta(name="web-hpa"),
                spec=api.HorizontalPodAutoscalerSpec(
                    scale_target_ref=api.ScaleTargetRef("Deployment", "web"),
                    min_replicas=idle_target,
                    max_replicas=peak_target,
                    target_cpu_utilization_percentage=50,
                ),
            )
        )

        def feed_metrics(cpu):
            for p in store.list("Pod")[0]:
                m = api.PodMetrics(
                    meta=api.ObjectMeta(
                        name=p.meta.name, namespace=p.meta.namespace
                    ),
                    usage={api.CPU: cpu},
                    timestamp=time.time(),
                )
                try:
                    store.create(m)
                except st.AlreadyExists:
                    cur = store.get("PodMetrics", p.meta.name, p.meta.namespace)
                    cur.usage = {api.CPU: cpu}
                    store.update(cur, force=True)

        # scale-up half: hot metrics drive the HPA toward max_replicas,
        # pending pods drive the scaler up with it
        deadline = time.monotonic() + 120
        replicas = 8
        while time.monotonic() < deadline:
            feed_metrics(2000)  # 100% utilization vs the 50% target
            pods, _ = store.list("Pod")
            replicas = sum(1 for p in pods if p.meta.name.startswith("web-"))
            bound = sum(
                1
                for p in pods
                if p.meta.name.startswith("web-") and p.spec.node_name
            )
            if replicas >= peak_target and bound >= replicas:
                break
            time.sleep(0.1)
        pods, _ = store.list("Pod")
        unbound_at_peak = sum(
            1
            for p in pods
            if p.meta.name.startswith("web-") and not p.spec.node_name
        )
        peak_nodes = scaler.size()
        # scale-down half: idle metrics shrink the deployment, the
        # ReplicaSet deletes pods, idle capacity drains the node group
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            feed_metrics(100)  # 5% utilization
            pods, _ = store.list("Pod")
            n_web = sum(1 for p in pods if p.meta.name.startswith("web-"))
            if n_web <= idle_target * 2 and scaler.size() < peak_nodes:
                break
            time.sleep(0.1)
        grow_syncs = sched.tpu._mirror.grow_syncs
    finally:
        stop.set()
        ca.join(timeout=5)
        sched.stop()
        mgr.stop()
        hollow.stop()
    return {
        "replicas_peak": replicas,
        "unbound_at_peak": unbound_at_peak,
        "nodes_peak": peak_nodes,
        "nodes_final": scaler.size(),
        "scaler_nodes_added": scaler.nodes_added,
        "scaler_nodes_removed": scaler.nodes_removed,
        "mirror_grow_syncs": grow_syncs,
        "mirror_resync_total": sched.tpu._mirror.resync_total,
        "node_axis_bucket": sched.tpu.state.node_axis_bucket,
        "watchers_terminated": store.watchers_terminated,
    }


# c13 serving-fleet gates (BENCH_STRICT=1): the serving plane must hold
# ≥1000 concurrent multiplexed HTTP informers over ≥2 read replicas, a
# mid-soak replica kill must recover (every stream failed over and
# caught up on a post-kill marker) inside the shared restart budget
# with NO wedged watcher, delivery must stay rv-monotonic per shard
# segment with zero lost pods and zero double binds, and p99
# watch-delivery latency is always reported.
STRICT_SERVING_INFORMERS = 1_000
STRICT_SERVING_REPLICAS = 2
STRICT_SERVING_SOAK_PODS = 4_096


def config13():
    """c13: the fleet-scale serving plane — an APIServerReplicaSet over
    the sharded store, a thousand informers multiplexed over HTTP
    (client/watchmux.py, a few selector loops instead of a thousand
    threads), pods created THROUGH the HTTP path and bound via the
    store's wave path while hollow kubelets run them, and a mid-soak
    replica kill + restart.  Measures p99 watch-delivery latency
    (create-call → event delivery), failover/recovery health, and the
    adaptive-APF serving gauges the scheduler mirrors.

    Env knobs (smoke-scale a laptop run):
      BENCH_C13_INFORMERS=<n>  informer count   (default 1000)
      BENCH_C13_REPLICAS=<n>   replica count    (default 2)
      BENCH_C13_PODS=<n>       soak pods        (default 4096)
    """
    from kubernetes_tpu import kubemark
    from kubernetes_tpu.api import store as st

    informers = int(
        os.environ.get("BENCH_C13_INFORMERS", STRICT_SERVING_INFORMERS)
    )
    replicas = int(
        os.environ.get("BENCH_C13_REPLICAS", STRICT_SERVING_REPLICAS)
    )
    soak_pods = int(
        os.environ.get("BENCH_C13_PODS", STRICT_SERVING_SOAK_PODS)
    )
    store = st.Store(shards=8)
    fleet = kubemark.FleetHarness(
        store, n_nodes=256, namespaces=8, heartbeat_interval=60.0,
        bind_concurrency=4,
    )
    fleet.start()
    terminated0 = store.watchers_terminated
    try:
        report = fleet.serve(
            replicas=replicas,
            informers=informers,
            soak_pods=soak_pods,
            round_pods=min(1_024, soak_pods),
            recovery_budget_s=STRICT_RECOVERY_BUDGET_MS / 1000.0,
        )
    finally:
        fleet.stop()
    report["watchers_terminated"] = (
        store.watchers_terminated - terminated0
    )
    return report


def main() -> None:
    import sys

    from kubernetes_tpu.analysis import epochs, ledger, retrace
    from kubernetes_tpu.utils import trace as tracemod

    tracemod.drain_overruns()  # measure only this run's traces
    # arm the recompile-discipline runtime tracker for the whole run:
    # each _Runner marks its steady window after warmup, and the churn
    # config's scheduler mirrors the trace total into
    # scheduler_solve_retrace_total (perf/collectors SCALAR_METRICS).
    # c6 deliberately has no steady window — churn walks the pod-bucket
    # ladder by design, so its first-seen buckets are not steady-state
    # retraces.  The graftcoh epoch auditor is armed alongside it: every
    # resident buffer a solve consumes is audited against the scheduler
    # cache's current generations, and BENCH_STRICT fails on any
    # violation (docs/static_analysis.md coherence section).  The
    # graftobl exactly-once ledger rides along: every popped pod, cache
    # assume, APF seat, arbiter slot and inflight counter must discharge
    # exactly once, and BENCH_STRICT fails on any leak or
    # double-discharge (docs/static_analysis.md obligations section).
    with retrace.tracked(), epochs.tracked() as coh, \
            ledger.tracked() as led:
        extra = {
            "c1_fit_500": config1(),
            "c2_balanced_5k": config2(),
            "c3_spread_10k": config3(),
            "c3s_spread_1k": config3s(),
            "c4_interpod_20k": config4(),
            "c4s_interpod_1k": config4s(),
            "c5_gang_50k": config5(),
            "c6_churn_5k": config6(),
            "c6s_sustained_50k": config6_sustained(),
            "c7_sharded_100k": config7(),
            "c8_store_100k": config8(),
            "c9_preempt_churn": config9(),
            "c10_slice_pack": config10(),
            "c11_incremental_churn": config11(),
            "c12_autoscale_churn": config12(),
            "c13_serving_fleet": config13(),
        }
    # every over-threshold schedule_batch cycle, with its per-step share
    # (commit- and solve-share per step are readable straight off the
    # steps list); BENCH_STRICT=1 turns any such trace into a non-zero
    # exit so CI fails on slow cycles instead of shipping them as log
    # warnings
    overruns = tracemod.drain_overruns()

    def _share(o, prefixes):
        if not o["total_s"]:
            return 0.0
        return round(
            sum(dt for w, dt in o["steps"] if w.startswith(prefixes))
            / o["total_s"], 4,
        )

    extra["trace_overruns"] = [
        {
            "name": o["name"],
            "total_s": o["total_s"],
            "steps": o["steps"],
            "commit_share": _share(o, ("commit",)),
            # encode + decode + deferred-readback overlap = the solve
            # half of the step
            "solve_share": _share(o, ("encode", "decode", "overlap")),
            **o["fields"],
        }
        for o in overruns
    ]
    # steady-state solve-half regression gate: the 1k-pod greedy shapes
    # must hold their budget (2x better than the BENCH_r05 traces)
    solve_regressions = [
        {
            "config": name,
            "latency_s": extra[name]["latency_s"],
            "budget_s": budget,
        }
        for name, budget in STRICT_SOLVE_BUDGETS_S.items()
        if extra[name]["latency_s"] > budget
    ]
    extra["solve_regressions"] = solve_regressions
    # recompile-discipline gate: zero steady-state retraces on every
    # fixed-shape scenario (c6 reports through solve_retrace_total
    # instead — see the tracked() comment above)
    steady_retraces = {
        name: cfg["steady_recompiles"]
        for name, cfg in extra.items()
        if isinstance(cfg, dict) and cfg.get("steady_recompiles")
    }
    extra["steady_retraces"] = steady_retraces
    # graftcoh epoch-auditor totals for the whole run (the warm-path
    # configs — c11/c12 — drive the audited consume sites)
    extra["coherence"] = {
        "audits_total": coh.audits_total,
        "violations_total": coh.violations_total,
        "rollbacks_blocked": coh.rollbacks_blocked,
        "violations": coh.violations[:5],
    }
    # graftobl ledger totals for the whole run (leaks are computed at
    # this point — after every runner quiesced, so anything still held
    # really is leaked, not merely in flight)
    extra["obligations"] = {
        "tracked_total": led.tracked_total,
        "leaks_total": led.leaks_total,
        "double_discharge_total": led.double_discharge_total,
        "leaks": led.outstanding()[:5],
        "double_discharges": led.double[:5],
    }
    c5 = extra["c5_gang_50k"]
    pods_per_s = 10_000 / c5["latency_s"]
    print(
        json.dumps(
            {
                "metric": "gang_burst_latency_50k_nodes_10k_pods",
                "value": c5["latency_s"],
                "unit": "s",
                "vs_baseline": round(pods_per_s / BASELINE_PODS_PER_SEC, 2),
                "extra": extra,
            }
        )
    )
    if os.environ.get("BENCH_STRICT") == "1":
        failures = []
        n_slow = sum(o["name"] == "schedule_batch" for o in overruns)
        if n_slow:
            failures.append(
                f"{n_slow} over-threshold schedule_batch trace(s)"
            )
        if solve_regressions:
            failures.append(
                "steady-state solve-half over budget: "
                + ", ".join(
                    f"{r['config']}={r['latency_s']}s (budget {r['budget_s']}s)"
                    for r in solve_regressions
                )
            )
        if steady_retraces:
            failures.append(
                "steady-state XLA retraces (pad-bucket escape): "
                + ", ".join(
                    f"{name}={n}" for name, n in sorted(steady_retraces.items())
                )
            )
        # graftcoh gate: the armed auditor must have observed the warm
        # path (audits > 0) and found every consumed resident epoch
        # consistent (violations == 0)
        if coh.violations_total:
            failures.append(
                f"{coh.violations_total} resident-epoch coherence "
                "violation(s): " + "; ".join(coh.violations[:3])
            )
        if not coh.audits_total:
            failures.append(
                "coherence auditor armed but performed 0 audits (warm "
                "path never reached an audited consume site)"
            )
        # graftobl gates: the armed ledger must have tracked real
        # acquisitions and seen every one discharged exactly once
        obl = extra["obligations"]
        if obl["leaks_total"]:
            failures.append(
                f"{obl['leaks_total']} leaked obligation(s): "
                + "; ".join(obl["leaks"][:3])
            )
        if obl["double_discharge_total"]:
            failures.append(
                f"{obl['double_discharge_total']} obligation "
                "double-discharge(s): "
                + "; ".join(obl["double_discharges"][:3])
            )
        if not obl["tracked_total"]:
            failures.append(
                "obligation ledger armed but tracked 0 acquisitions "
                "(hooks never reached)"
            )
        # overload-protection gates: NO scenario may destructively
        # terminate a watcher (backpressure must absorb the load), and
        # the sustained-churn stream must hold its throughput floor
        terminated = {
            name: cfg["watchers_terminated"]
            for name, cfg in extra.items()
            if isinstance(cfg, dict) and cfg.get("watchers_terminated")
        }
        if terminated:
            failures.append(
                "watchers terminated (backpressure must hold): "
                + ", ".join(f"{k}={v}" for k, v in sorted(terminated.items()))
            )
        c6s = extra["c6s_sustained_50k"]
        # in ramp mode the whole-run average includes the deliberately
        # slow early segments; the knee is the sustained figure there
        sustained = max(
            c6s["pods_per_s"], c6s.get("arrival_knee_pods_per_s", 0.0)
        )
        if sustained < STRICT_SUSTAINED_MIN_PODS_PER_S:
            failures.append(
                f"sustained churn below budget: {sustained} < "
                f"{STRICT_SUSTAINED_MIN_PODS_PER_S} pods/s"
            )
        # crash-restart recovery gates: snapshot+suffix recovery of the
        # post-run store must finish inside the fixed budget and lose
        # NOTHING (close() flushed the final interval-sync batch)
        if c6s["recovery_ms"] > STRICT_RECOVERY_BUDGET_MS:
            failures.append(
                f"c6s recovery over budget: {c6s['recovery_ms']}ms > "
                f"{STRICT_RECOVERY_BUDGET_MS}ms"
            )
        if c6s["recovery_lost_pods"]:
            failures.append(
                f"c6s recovery lost {c6s['recovery_lost_pods']} bound "
                "pod(s)"
            )
        # sharded-solve gates: mesh placements must be assignment-
        # identical to single-chip, and steady mesh-mode host→device
        # transfer must be O(changed rows) (zero resyncs, delta rows
        # bounded by the dirtied set)
        c7 = extra["c7_sharded_100k"]
        bad_parity = sorted(
            k for k, ok in c7["mesh_parity"].items() if not ok
        )
        if bad_parity:
            failures.append(
                "sharded solve diverged from single-chip on: "
                + ", ".join(bad_parity)
            )
        if not c7["mirror_delta_bounded"]:
            failures.append(
                "c7 steady host→device transfer not O(changed rows): "
                f"{c7['mirror_delta_rows']} delta rows / "
                f"{c7['mirror_resync_total']} resyncs for "
                f"{c7['dirtied_rows']} dirtied rows"
            )
        # fleet-harness gates: the 100k-node soak must be lossless
        # (every created pod ran exactly once on exactly one node) and
        # the 8-shard recovery must fit the shared restart budget
        c8 = extra["c8_store_100k"]
        if c8["lost_pods"]:
            failures.append(f"c8 fleet lost {c8['lost_pods']} pod(s)")
        if c8["double_bound_pods"]:
            failures.append(
                f"c8 fleet double-bound {c8['double_bound_pods']} pod(s)"
            )
        if c8["recovery_lost_nodes"]:
            failures.append(
                f"c8 recovery lost {c8['recovery_lost_nodes']} node(s)"
            )
        if c8["recovery_ms"] > STRICT_RECOVERY_BUDGET_MS:
            failures.append(
                f"c8 per-shard recovery over budget: {c8['recovery_ms']}ms"
                f" > {STRICT_RECOVERY_BUDGET_MS}ms"
            )
        # batched-preemption gates: oracle + batched-vs-sequential plan
        # parity, bound-exactly-once across preemptors AND evicted
        # victims, PDB-guarded victims alive, the sustained preemption
        # floor, and the ≥5x exposed-PostFilter planning speedup on the
        # same frozen trace (steady_recompiles rides the generic gate)
        c9 = extra["c9_preempt_churn"]
        if not c9["oracle_parity"]:
            failures.append("c9 batched preemption diverged from the oracle")
        if not c9["plan_parity"]:
            failures.append(
                "c9 batched plans diverged from the sequential walk"
            )
        if c9["double_bound"] or c9["placed"] < c9["preemptors"]:
            failures.append(
                f"c9 bound-exactly-once violated: {c9['double_bound']} "
                f"double binds, {c9['placed']}/{c9['preemptors']} "
                "preemptors placed"
            )
        if not c9["guarded_survived"]:
            failures.append(
                f"c9 evicted PDB-guarded victims: {c9['guarded_alive']}/"
                f"{c9['guarded_total']} survived"
            )
        if c9["preemptions_per_s"] < STRICT_PREEMPT_MIN_PER_S:
            failures.append(
                f"c9 preemption throughput below floor: "
                f"{c9['preemptions_per_s']} < {STRICT_PREEMPT_MIN_PER_S}/s"
            )
        if c9["postfilter_speedup"] < STRICT_PREEMPT_SPEEDUP_MIN:
            failures.append(
                f"c9 batched PostFilter speedup below floor: "
                f"{c9['postfilter_speedup']}x < "
                f"{STRICT_PREEMPT_SPEEDUP_MIN}x"
            )
        # slice-packing quality gates: the carve-out scorer must keep
        # placing gangs contiguously through churn and the end state
        # must not shatter (steady_recompiles rides the generic gate)
        c10 = extra["c10_slice_pack"]
        if c10["contiguous_rate"] < STRICT_SLICE_CONTIG_MIN:
            failures.append(
                f"c10 contiguous-placement rate below floor: "
                f"{c10['contiguous_rate']} < {STRICT_SLICE_CONTIG_MIN}"
            )
        c11 = extra["c11_incremental_churn"]
        if not c11["warm_parity"]:
            failures.append(
                "c11 warm-started placements diverged from cold solves "
                "(the partials parity gate)"
            )
        if c11["warm_speedup"] < STRICT_PARTIALS_SPEEDUP_MIN:
            failures.append(
                f"c11 warm-solve speedup {c11['warm_speedup']}x < "
                f"{STRICT_PARTIALS_SPEEDUP_MIN}x on the frozen churn trace"
            )
        if c11["dirty_fraction"] > STRICT_PARTIALS_DIRTY_FRAC_MAX:
            failures.append(
                f"c11 dirtied {c11['dirty_fraction']} of rows per cycle > "
                f"{STRICT_PARTIALS_DIRTY_FRAC_MAX} (the <=1% churn contract)"
            )
        if c10["frag_score_final"] > STRICT_SLICE_FRAG_MAX:
            failures.append(
                f"c10 fragmentation above ceiling: "
                f"{c10['frag_score_final']} > {STRICT_SLICE_FRAG_MAX}"
            )
        # elastic-node-axis gates: within-bucket autoscaler churn must
        # never force a full mirror re-upload, boundary oscillation
        # under the shrink dwell must not flip shapes, bucket crossings
        # must be absorbed by in-place grows with exact pad-row
        # accounting (transfer stays O(changed rows)), the partials
        # class rows must stay warm across the grow, and the elastic
        # placements must match the full-RESHARDED-rebuild oracle
        # bit-for-bit (steady_recompiles rides the generic gate)
        c12 = extra["c12_autoscale_churn"]
        if not c12["oracle_parity"]:
            failures.append(
                "c12 elastic placements diverged from the full-rebuild "
                "oracle"
            )
        if c12["steady_resyncs"]:
            failures.append(
                f"c12 within-bucket churn forced {c12['steady_resyncs']} "
                "full mirror re-upload(s)"
            )
        if not c12["steady_delta_bounded"]:
            failures.append(
                "c12 steady host→device transfer not O(changed rows): "
                f"{c12['steady_delta_rows']} delta rows for "
                f"{c12['steady_dirtied_rows']} dirtied"
            )
        if c12["osc_resyncs"] or c12["osc_grows"]:
            failures.append(
                "c12 bucket-boundary oscillation escaped the shrink "
                f"dwell: {c12['osc_resyncs']} resyncs / "
                f"{c12['osc_grows']} shape changes during oscillation"
            )
        if not c12["grow_bounded"]:
            failures.append(
                "c12 bucket crossing not absorbed in place: "
                f"{c12['mirror_grow_rows']} grow rows != "
                f"{c12['grow_rows_expected']} expected "
                f"({c12['mirror_resync_total']} resyncs total)"
            )
        if c12["warm_slots_frac"] < STRICT_AUTOSCALE_WARM_SLOTS_MIN:
            failures.append(
                f"c12 partials class rows went cold across the grow: "
                f"{c12['warm_slots_frac']} warm < "
                f"{STRICT_AUTOSCALE_WARM_SLOTS_MIN}"
            )
        if c12["partials_reseeds_in_osc"] or not c12["partials_grows"]:
            failures.append(
                "c12 partials did not stay warm through the crossing: "
                f"{c12['partials_reseeds_in_osc']} reseed(s) during "
                f"oscillation, {c12['partials_grows']} in-place grow(s) "
                "— node churn must not flush the cache (the per-key "
                "expansion watermark)"
            )
        if not c12["shrink_served"]:
            failures.append(
                "c12 post-dwell drain never shrank the bucket "
                f"(still {c12['post_shrink_bucket']})"
            )
        if c12["live_unbound_at_peak"]:
            failures.append(
                f"c12 live autoscale left {c12['live_unbound_at_peak']} "
                "pod(s) unbound at peak"
            )
        if not c12["live_mirror_grow_syncs"]:
            failures.append(
                "c12 live autoscale crossing never took the in-place "
                "grow path (0 grow syncs)"
            )
        # serving-plane gates: the replica-set soak must run at fleet
        # scale (>=1000 informers over >=2 replicas), the mid-soak
        # replica kill must recover inside the shared restart budget
        # with no wedged watcher, delivery must stay rv-monotonic with
        # zero lost pods / double binds, and p99 delivery latency must
        # be reported (NaN-free) for the SLO trendline
        c13 = extra["c13_serving_fleet"]
        if (
            c13["informers"] < STRICT_SERVING_INFORMERS
            or c13["replicas"] < STRICT_SERVING_REPLICAS
        ):
            failures.append(
                f"c13 ran under scale: {c13['informers']} informers / "
                f"{c13['replicas']} replicas < "
                f"{STRICT_SERVING_INFORMERS}/{STRICT_SERVING_REPLICAS}"
            )
        if c13["recovery_ms"] is None:
            failures.append("c13 never exercised the mid-soak replica kill")
        elif c13["recovery_ms"] > STRICT_RECOVERY_BUDGET_MS:
            failures.append(
                f"c13 replica-kill recovery over budget: "
                f"{c13['recovery_ms']}ms > {STRICT_RECOVERY_BUDGET_MS}ms"
            )
        if c13["wedged_watchers"]:
            failures.append(
                f"c13 left {c13['wedged_watchers']} watcher(s) wedged "
                "after the replica kill"
            )
        if c13["rv_violations"]:
            failures.append(
                f"c13 rv-monotonic delivery violated {c13['rv_violations']}"
                " time(s)"
            )
        if c13["lost_watch_pods"] or c13["double_bound_pods"]:
            failures.append(
                f"c13 lost {c13['lost_watch_pods']} pod(s) / "
                f"double-bound {c13['double_bound_pods']} through the "
                "serving path"
            )
        if not (c13["watch_p99_ms"] == c13["watch_p99_ms"]):
            failures.append("c13 p99 watch-delivery latency not measured")
        if failures:
            print("BENCH_STRICT: " + "; ".join(failures), file=sys.stderr)
            sys.exit(1)


if __name__ == "__main__":
    main()
