/* _hostplane: optional CPython extension for the columnar host plane's
 * byte-level hot paths — journal frame trailer splice + CRC, and the
 * proto transport's length-prefix wire framing.
 *
 * kubernetes_tpu/api/framing.py is the contract: it holds the pure
 * Python reference implementations and falls back to them whenever
 * this module is absent, so the extension is a pure accelerator —
 * every function here must be byte-identical to its Python twin
 * (tests/test_journal_framing.py asserts that when the module is
 * importable).
 *
 * Build (no dependencies beyond the CPython headers; CRC-32 is the
 * self-contained IEEE/zlib polynomial so we never link zlib):
 *   make native-ext        # top-level Makefile, skips without a compiler
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>

/* zlib-compatible CRC-32 (reflected 0xEDB88320), table generated once. */
static uint32_t crc_table[256];
static int crc_table_ready = 0;

static void crc_init(void) {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_table_ready = 1;
}

static uint32_t crc32_ieee(const unsigned char *buf, Py_ssize_t len) {
  if (!crc_table_ready) crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (Py_ssize_t i = 0; i < len; i++)
    c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

/* crc32(data: bytes) -> int  (zlib.crc32 twin) */
static PyObject *hp_crc32(PyObject *self, PyObject *args) {
  Py_buffer view;
  if (!PyArg_ParseTuple(args, "y*", &view)) return NULL;
  uint32_t c = crc32_ieee((const unsigned char *)view.buf, view.len);
  PyBuffer_Release(&view);
  return PyLong_FromUnsignedLong(c);
}

/* crc_line(s: bytes) -> bytes
 * Splice the CRC trailer onto a serialized JSON object in one pass:
 *   b'{...}'  ->  b'{..., "crc": N}\n'
 * Byte-identical to framing.crc_line / store._encode_record's trailer. */
static PyObject *hp_crc_line(PyObject *self, PyObject *args) {
  Py_buffer view;
  if (!PyArg_ParseTuple(args, "y*", &view)) return NULL;
  if (view.len < 2) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "not a serialized JSON object");
    return NULL;
  }
  uint32_t c = crc32_ieee((const unsigned char *)view.buf, view.len);
  char trailer[32];
  int tn = snprintf(trailer, sizeof(trailer), ", \"crc\": %u}\n", c);
  PyObject *out = PyBytes_FromStringAndSize(NULL, view.len - 1 + tn);
  if (out == NULL) {
    PyBuffer_Release(&view);
    return NULL;
  }
  char *dst = PyBytes_AS_STRING(out);
  memcpy(dst, view.buf, (size_t)(view.len - 1)); /* drop closing '}' */
  memcpy(dst + view.len - 1, trailer, (size_t)tn);
  PyBuffer_Release(&view);
  return out;
}

/* length_prefix(payload: bytes) -> bytes
 * 4-byte big-endian length header + payload (the proto transport's
 * framing; native/proto_client.cpp speaks the same header). */
static PyObject *hp_length_prefix(PyObject *self, PyObject *args) {
  Py_buffer view;
  if (!PyArg_ParseTuple(args, "y*", &view)) return NULL;
  if (view.len > (Py_ssize_t)0xFFFFFFFF) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_OverflowError, "payload exceeds u32 framing");
    return NULL;
  }
  PyObject *out = PyBytes_FromStringAndSize(NULL, view.len + 4);
  if (out == NULL) {
    PyBuffer_Release(&view);
    return NULL;
  }
  unsigned char *dst = (unsigned char *)PyBytes_AS_STRING(out);
  uint32_t n = (uint32_t)view.len;
  dst[0] = (unsigned char)(n >> 24);
  dst[1] = (unsigned char)(n >> 16);
  dst[2] = (unsigned char)(n >> 8);
  dst[3] = (unsigned char)(n);
  memcpy(dst + 4, view.buf, (size_t)view.len);
  PyBuffer_Release(&view);
  return out;
}

static PyMethodDef hp_methods[] = {
    {"crc32", hp_crc32, METH_VARARGS, "zlib-compatible CRC-32"},
    {"crc_line", hp_crc_line, METH_VARARGS,
     "splice the journal CRC trailer onto a serialized JSON object"},
    {"length_prefix", hp_length_prefix, METH_VARARGS,
     "u32 big-endian length framing for the proto transport"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef hp_module = {
    PyModuleDef_HEAD_INIT, "_hostplane",
    "byte-level host-plane hot paths (journal framing, wire framing)",
    -1, hp_methods,
};

PyMODINIT_FUNC PyInit__hostplane(void) { return PyModule_Create(&hp_module); }
