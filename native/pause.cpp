// The pod-sandbox holder: the TPU-native equivalent of the reference's
// only in-tree C component (build/pause/linux/pause.c — hold the pod's
// namespaces, reap orphans, exit on TERM/INT).  Re-designed, not
// transliterated: a blocked-signal + sigsuspend loop (no lost-wakeup
// window), PR_SET_CHILD_SUBREAPER so orphans reparent here even outside
// a PID namespace, and a -v flag for the image version handshake.
//
// Build: make -C native pause   (static; see native/Makefile)

#include <csignal>
#include <cstdio>
#include <cstring>
#include <sys/prctl.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

namespace {

volatile sig_atomic_t should_exit = 0;

void on_terminate(int) { should_exit = 1; }

void on_child(int) {
  // reap every exited child without blocking; WNOHANG drains the queue
  while (waitpid(-1, nullptr, WNOHANG) > 0) {
  }
}

}  // namespace

int main(int argc, char **argv) {
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "-v") == 0) {
      std::printf("kubernetes_tpu pause 1.0\n");
      return 0;
    }
  }
  // orphaned descendants reparent to the nearest subreaper — us — so the
  // reap loop sees them even when we are not PID 1 of a namespace
  prctl(PR_SET_CHILD_SUBREAPER, 1, 0, 0, 0);

  struct sigaction term {};
  term.sa_handler = on_terminate;
  sigaction(SIGINT, &term, nullptr);
  sigaction(SIGTERM, &term, nullptr);

  struct sigaction chld {};
  chld.sa_handler = on_child;
  chld.sa_flags = SA_RESTART;
  sigaction(SIGCHLD, &chld, nullptr);
  // drain children that died before the handler existed (a shell that
  // exec'd us may have left an already-exited child behind — its
  // SIGCHLD was discarded under the default disposition)
  on_child(0);

  // Block the signals outside sigsuspend: checking should_exit and THEN
  // parking with plain pause() loses a signal delivered in between (the
  // classic lost-wakeup; the reference avoids it by exiting from the
  // handler).  With the set blocked, delivery happens only inside
  // sigsuspend, atomically with the wakeup.
  sigset_t block, orig;
  sigemptyset(&block);
  sigaddset(&block, SIGINT);
  sigaddset(&block, SIGTERM);
  sigaddset(&block, SIGCHLD);
  sigprocmask(SIG_BLOCK, &block, &orig);
  while (!should_exit) {
    sigsuspend(&orig);
  }
  return 0;
}
