// Stock-C++ proof of the dense-snapshot scheduling boundary: build a
// SolveRequest with protoc-generated code, ship it to the TPU solver
// service over the length-framed TCP transport, and read back
// assignments — no Python, no JSON, tensors on the wire.
//
// This is the SURVEY §2.6 north-star shim exercised from the native
// side (the role a Go scheduler core would play; the image has no Go
// toolchain, and C++ is the same proof).  Reference analogue: a CRI
// client driving the runtime over its proto contract
// (staging/src/k8s.io/cri-api/pkg/apis/runtime/v1/api.proto).
//
// Build (tests/test_protoserver.py does this automatically):
//   protoc --cpp_out=build/ kubernetes_tpu/proto/snapshot.proto
//   g++ -O2 -o proto_client native/proto_client.cpp \
//       build/kubernetes_tpu/proto/snapshot.pb.cc \
//       -Ibuild/kubernetes_tpu/proto $(pkg-config --cflags --libs protobuf)
//
// Usage: proto_client <port> <n_nodes> <n_pods>
// Prints: "placed <k>/<n> pods in <secs>s" and exits 0 on full placement.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include "snapshot.pb.h"

namespace pb = kubernetes_tpu::v1;

static bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

static bool read_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr, "usage: %s <port> <n_nodes> <n_pods>\n", argv[0]);
    return 2;
  }
  const int port = std::atoi(argv[1]);
  const int n_nodes = std::atoi(argv[2]);
  const int n_pods = std::atoi(argv[3]);

  pb::SolveRequest req;
  auto* cluster = req.mutable_cluster();
  auto* vocab = cluster->mutable_resources();
  vocab->add_names("cpu");     // milli
  vocab->add_names("memory");  // bytes (fits float32 at test scale)
  vocab->add_names("pods");

  auto* alloc = cluster->mutable_allocatable();
  alloc->set_rows(n_nodes);
  alloc->set_cols(3);
  for (int i = 0; i < n_nodes; ++i) {
    cluster->add_node_names("node-" + std::to_string(i));
    alloc->add_data(32000.0f);              // 32 cores
    alloc->add_data(64.0f * (1u << 20));    // 64 Mi-as-bytes scale-down
    alloc->add_data(110.0f);
  }

  auto* pods = req.mutable_pods();
  auto* preq = pods->mutable_requests();
  preq->set_rows(n_pods);
  preq->set_cols(3);
  for (int i = 0; i < n_pods; ++i) {
    pods->add_pod_names("pod-" + std::to_string(i));
    preq->add_data(500.0f);
    preq->add_data(0.5f * (1u << 20));
    preq->add_data(1.0f);
  }

  std::string payload;
  if (!req.SerializeToString(&payload)) {
    std::fprintf(stderr, "serialize failed\n");
    return 1;
  }

  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    std::perror("connect");
    return 1;
  }

  uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
  if (!write_all(fd, &len, 4) ||
      !write_all(fd, payload.data(), payload.size())) {
    std::fprintf(stderr, "send failed\n");
    return 1;
  }
  if (!read_all(fd, &len, 4)) {
    std::fprintf(stderr, "recv header failed\n");
    return 1;
  }
  std::string in(ntohl(len), '\0');
  if (!read_all(fd, in.data(), in.size())) {
    std::fprintf(stderr, "recv body failed\n");
    return 1;
  }
  close(fd);

  pb::SolveResponse resp;
  if (!resp.ParseFromString(in)) {
    std::fprintf(stderr, "parse failed\n");
    return 1;
  }
  int placed = 0;
  for (const auto& a : resp.assignments()) {
    if (!a.node_name().empty()) ++placed;
  }
  std::printf("placed %d/%d pods in %.3fs\n", placed, n_pods,
              resp.solve_seconds());
  return placed == n_pods ? 0 : 3;
}
