"""The all-in-one Cluster composition (hyperkube / kind role):
apiserver + scheduler + controllers + agents + proxy in one object.
"""

import time

from kubernetes_tpu.api import types as api
from kubernetes_tpu.cluster import Cluster


def _wait(cond, timeout=90.0, every=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


def test_cluster_end_to_end():
    cluster = Cluster(n_agents=2, with_proxy=True).start()
    try:
        client = cluster.client()
        labels = {"app": "web"}
        client.create(api.Deployment(
            meta=api.ObjectMeta(name="web"),
            spec=api.DeploymentSpec(
                replicas=2,
                selector=api.LabelSelector(match_labels=labels),
                template=api.PodTemplateSpec(
                    meta=api.ObjectMeta(labels=labels),
                    spec=api.PodSpec(containers=[
                        api.Container(requests={api.CPU: 100})
                    ]),
                ),
            ),
        ))
        svc = client.create(api.Service(
            meta=api.ObjectMeta(name="web"),
            spec=api.ServiceSpec(
                selector=labels,
                ports=[api.ServicePort(name="http", port=80,
                                       target_port=8080)],
            ),
        ))
        # pods schedule onto agent nodes, agents run them to Ready,
        # slices populate, the proxy resolves the VIP
        assert _wait(lambda: sum(
            1 for p in client.list("Pod")[0]
            if p.spec.node_name and api.pod_is_ready(p)
        ) == 2)
        vip = svc.spec.cluster_ip
        assert _wait(
            lambda: cluster.proxy.resolve(vip, 80) is not None
        )
        backend = cluster.proxy.resolve(vip, 80)
        assert backend[0].startswith("10.88.") and backend[1] == 8080
        # default ServiceAccount materialized; pods run as it
        pod = client.list("Pod")[0][0]
        assert pod.spec.service_account == "default"
    finally:
        cluster.stop()
