"""Preferred inter-pod (anti-)affinity scoring — the O(pods²) pairwise
scoring family (interpodaffinity/scoring.go) as domain-summed term rows.

Both directions are covered: the incoming pod's preferred terms against
existing pods, and existing pods' preferred/required terms judging the
incoming pod (hardPodAffinityWeight)."""

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import assign, auction, schema
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _zone_nodes(n, zones=2):
    return [
        make_node(f"n{i}")
        .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
        .zone(f"z{i % zones}")
        .obj()
        for i in range(n)
    ]


def _pref_aff(pw, selector, weight=50, anti=False, topo=api.LABEL_ZONE):
    aff = pw.pod.spec.affinity or api.Affinity()
    pw.pod.spec.affinity = aff
    term = api.WeightedPodAffinityTerm(
        weight=weight,
        term=api.PodAffinityTerm(
            label_selector=api.LabelSelector(match_labels=selector),
            topology_key=topo,
        ),
    )
    if anti:
        if aff.pod_anti_affinity is None:
            aff.pod_anti_affinity = api.PodAntiAffinity()
        aff.pod_anti_affinity.preferred.append(term)
    else:
        if aff.pod_affinity is None:
            aff.pod_affinity = api.PodAffinity()
        aff.pod_affinity.preferred.append(term)
    return pw


def test_feature_flag_set():
    nodes = _zone_nodes(2)
    pods = [_pref_aff(make_pod("p").req(cpu_milli=100), {"app": "x"}).obj()]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    assert assign.features_of(snap).interpod_pref


def test_preferred_affinity_attracts():
    """All else equal, the pod lands in the zone holding the matching
    bound pod."""
    nodes = _zone_nodes(4)  # z0: n0,n2  z1: n1,n3
    bound = [make_pod("b").label("app", "x").node_name("n1").obj()]
    pods = [_pref_aff(make_pod("p").req(cpu_milli=100), {"app": "x"}).obj()]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    r = assign.greedy_assign(snap)
    assert int(r.assignment[0]) % 2 == 1, "did not land in z1"


def test_preferred_anti_affinity_repels():
    nodes = _zone_nodes(4)
    bound = [make_pod("b").label("app", "x").node_name("n1").obj()]
    pods = [
        _pref_aff(
            make_pod("p").req(cpu_milli=100), {"app": "x"}, anti=True
        ).obj()
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    r = assign.greedy_assign(snap)
    assert int(r.assignment[0]) % 2 == 0, "did not avoid z1"


def test_owner_preferred_terms_judge_incoming():
    """A bound pod PREFERRING app=y pulls an incoming app=y pod into its
    topology (the existing-pods'-terms direction)."""
    nodes = _zone_nodes(4)
    owner = _pref_aff(
        make_pod("owner"), {"app": "y"}, weight=80
    ).node_name("n3").obj()
    pods = [make_pod("p").req(cpu_milli=100).label("app", "y").obj()]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods, bound_pods=[owner])
    r = assign.greedy_assign(snap)
    assert int(r.assignment[0]) % 2 == 1, "owner's preference ignored"


def test_required_affinity_of_bound_pod_contributes_hard_weight():
    """Bound pods' REQUIRED affinity terms score with
    hardPodAffinityWeight (scoring.go processExistingPod)."""
    nodes = _zone_nodes(4)
    owner = (
        make_pod("owner")
        .pod_affinity({"app": "z"}, api.LABEL_ZONE)
        .label("app", "z")  # self-match so it could have scheduled
        .node_name("n1")
        .obj()
    )
    pods = [make_pod("p").req(cpu_milli=100).label("app", "z").obj()]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods, bound_pods=[owner])
    r = assign.greedy_assign(snap)
    assert int(r.assignment[0]) % 2 == 1


def test_auction_route_scores_preferred_terms():
    nodes = _zone_nodes(8)
    bound = [make_pod("b").label("app", "x").node_name("n1").obj()]
    pods = [
        _pref_aff(
            make_pod(f"p{i}").req(cpu_milli=100), {"app": "x"}, weight=90
        ).obj()
        for i in range(4)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[:4]
    assert (a >= 0).all()
    assert (a % 2 == 1).all(), f"auction ignored preferred affinity: {a}"


def test_weights_balance_between_terms():
    """Two preferred terms with different weights: the heavier wins."""
    nodes = _zone_nodes(4)
    bound = [
        make_pod("bx").label("app", "x").node_name("n0").obj(),  # z0
        make_pod("by").label("app", "y").node_name("n1").obj(),  # z1
    ]
    pw = make_pod("p").req(cpu_milli=100)
    _pref_aff(pw, {"app": "x"}, weight=10)
    _pref_aff(pw, {"app": "y"}, weight=90)
    snap, _ = schema.SnapshotBuilder().build(nodes, [pw.obj()], bound_pods=bound)
    r = assign.greedy_assign(snap)
    assert int(r.assignment[0]) % 2 == 1, "heavier preferred term lost"


def test_requested_to_capacity_ratio_strategy():
    """RTCR with a rising shape prefers the fuller node (bin packing)."""
    from kubernetes_tpu.ops.scores import ScoreConfig

    nodes = [
        make_node("empty").capacity(cpu_milli=8000, mem=16 * GI, pods=10).obj(),
        make_node("half").capacity(cpu_milli=8000, mem=16 * GI, pods=10).obj(),
    ]
    bound = [make_pod("b").req(cpu_milli=4000, mem=8 * GI).node_name("half").obj()]
    pods = [make_pod("p").req(cpu_milli=500, mem=GI).obj()]
    cfg = ScoreConfig(
        fit_strategy="RequestedToCapacityRatio",
        balanced_weight=0.0,
    )
    snap, meta = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    r = assign.greedy_assign(snap, cfg)
    assert meta.node_name(int(r.assignment[0])) == "half"


def test_dispatch_path_scores_preferred_terms():
    """Through TPUBatchScheduler (the production dispatch): a batch with
    ONLY preferred interpod terms must still size topo_z for its slots —
    the old gate aliased every domain to one value and silently zeroed
    the scores (review-confirmed bug)."""
    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler

    sched = TPUBatchScheduler()
    nodes = _zone_nodes(4)
    bound = make_pod("b").label("app", "x").node_name("n1").obj()
    for n in nodes:
        sched.add_node(n)
    sched.assume(bound, "n1")
    pods = [_pref_aff(make_pod("p").req(cpu_milli=100), {"app": "x"}).obj()]
    placements = sched.schedule_pending(pods)
    assert placements[0] in ("n1", "n3"), placements  # z1 nodes
