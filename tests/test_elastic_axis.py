"""Elastic node axis (ISSUE 15): O(changed-rows) node add/remove.

Backing-array growth and pad-bucket crossings are no longer struct
events: the device mirror and the partials cache absorb them with
in-place resident resizes (device-side pad/slice + delta scatter), the
exposed bucket follows grow-eager / shrink-lazy dwell hysteresis so
autoscaler oscillation never flip-flops compile keys, and remove_node
compaction is deferred and bounded (a drain is O(live) total work).
The full (RESHARDED) re-upload survives as the safety path and the
parity oracle — `incremental_grow = False` pins the old behavior and
every grow here is checked bit-identical against it.
"""

import numpy as np
import pytest

from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
from kubernetes_tpu.models.mirror import DeviceClusterMirror
from kubernetes_tpu.ops import schema
from kubernetes_tpu.scheduler.config import SchedulerConfiguration, load_config
from kubernetes_tpu.scheduler.framework import FrameworkRegistry
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _node(name, zone="z-0", cpu=8000):
    return (
        make_node(name)
        .capacity(cpu_milli=cpu, mem=16 * GI, pods=110)
        .zone(zone)
        .obj()
    )


def _pods(prefix, n, zone=None):
    out = []
    for i in range(n):
        w = make_pod(f"{prefix}-{i}").req(cpu_milli=100, mem=64 * MI)
        if zone is not None:
            w = w.node_selector_kv(
                "topology.kubernetes.io/zone", zone
            )
        out.append(w.obj())
    return out


def _mk_state(n):
    state = schema.ClusterState()
    for i in range(n):
        state.add_node(_node(f"n-{i}", zone=f"z-{i % 3}"))
    return state


def _assert_mirror_matches(mirror, state):
    dev = mirror.sync()
    want = state.tensors()
    for f in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(dev, f)),
            np.asarray(getattr(want, f)),
            err_msg=f"leaf {f} diverged",
        )
    return dev


# -- within-bucket adds are delta-only -------------------------------------


def test_within_bucket_add_is_delta_only():
    """Adding nodes inside the current pad bucket must ride the delta
    scatter: zero full re-uploads, zero resident resizes, and the warm
    partials rows survive (no reseed)."""
    sched = TPUBatchScheduler(mode="greedy", use_partials=True)
    for i in range(40):  # bucket 64, room to grow within it
        sched.add_node(_node(f"n-{i}", zone=f"z-{i % 3}"))
    sched.schedule_pending(_pods("w0", 6, zone="z-0"))
    sched.schedule_pending(_pods("w1", 6, zone="z-1"))  # warm refresh path
    m0 = dict(sched._mirror.stats())
    p0 = dict(sched._partials.stats())
    slots0 = set(sched._partials._slots)
    for i in range(40, 45):  # 45 < 64: same bucket, under-fraction delta
        sched.add_node(_node(f"n-{i}", zone=f"z-{i % 3}"))
    names = sched.schedule_pending(_pods("w2", 6, zone="z-2"))
    assert all(n is not None for n in names)
    m1 = dict(sched._mirror.stats())
    p1 = dict(sched._partials.stats())
    assert m1["resync_total"] == m0["resync_total"]  # delta-only
    assert m1["grow_syncs"] == m0["grow_syncs"]      # no shape change
    assert m1["delta_rows_total"] > m0["delta_rows_total"]
    assert p1["full_recomputes"] == p0["full_recomputes"]  # stayed warm
    assert slots0 <= set(sched._partials._slots)
    assert p1["hit_rows_total"] > p0["hit_rows_total"]


def test_node_churn_does_not_flush_partials():
    """Every autoscaled node interns a fresh hostname label pair; the
    per-key expansion watermark must ignore vocab growth under keys no
    selector references, so sustained churn keeps the cache hot."""
    sched = TPUBatchScheduler(mode="greedy", use_partials=True)
    for i in range(12):
        sched.add_node(_node(f"n-{i}", zone=f"z-{i % 3}"))
    sched.schedule_pending(_pods("w0", 6, zone="z-0"))
    sched.schedule_pending(_pods("w1", 6, zone="z-1"))
    full0 = sched._partials.stats()["full_recomputes"]
    for r in range(3):
        sched.remove_node(f"n-{r}")
        sched.add_node(_node(f"fresh-{r}", zone=f"z-{r % 3}"))
        names = sched.schedule_pending(_pods(f"c{r}", 4, zone="z-1"))
        assert all(n is not None for n in names)
    assert sched._partials.stats()["full_recomputes"] == full0


# -- bucket-boundary oscillation under the dwell ---------------------------


def test_bucket_oscillation_under_dwell_is_quiet():
    """Add/remove oscillation across a pad-bucket boundary: after the
    one eager grow, the shrink dwell pins the bucket — no further shape
    changes, no full re-uploads, no partials reseeds (i.e. zero new
    compile keys in either direction)."""
    sched = TPUBatchScheduler(mode="greedy", use_partials=True)
    sched.state.configure_elastic_axis(shrink_dwell=8)
    for i in range(15):  # bucket 16, one below the boundary
        sched.add_node(_node(f"n-{i}", zone=f"z-{i % 3}"))
    sched.schedule_pending(_pods("w0", 6, zone="z-0"))
    sched.schedule_pending(_pods("w1", 6, zone="z-1"))
    m0 = dict(sched._mirror.stats())
    p0 = dict(sched._partials.stats())
    shapes = set()
    for k in range(6):  # 3 crossings up, 3 back down
        if k % 2 == 0:
            for j in range(3):  # 15 -> 18: crosses to bucket 32
                sched.add_node(_node(f"osc-{k}-{j}", zone="z-0"))
        else:
            for j in range(3):
                sched.remove_node(f"osc-{k - 1}-{j}")
        names = sched.schedule_pending(_pods(f"o{k}", 4, zone="z-1"))
        assert all(n is not None for n in names)
        shapes.add(int(sched._mirror.sync().allocatable.shape[0]))
    m1 = dict(sched._mirror.stats())
    p1 = dict(sched._partials.stats())
    assert m1["resync_total"] == m0["resync_total"]  # zero full resyncs
    # exactly the one eager grow at the first crossing; the dwell holds
    # the bucket through every later dip below the boundary
    assert m1["grow_syncs"] == m0["grow_syncs"] + 1
    assert shapes == {32}
    assert p1["full_recomputes"] == p0["full_recomputes"]
    assert p1["grows"] == p0["grows"] + 1


# -- bucket-crossing grow: bit-identical to the cold rebuild ---------------


def _crossing_pair(mesh=None):
    elastic = TPUBatchScheduler(mode="greedy", use_partials=True, mesh=mesh)
    oracle = TPUBatchScheduler(mode="greedy", use_partials=True, mesh=mesh)
    oracle._mirror.incremental_grow = False
    oracle._partials.incremental_grow = False
    for i in range(8):
        for s in (elastic, oracle):
            s.add_node(_node(f"n-{i}", zone=f"z-{i % 3}"))
    return elastic, oracle


def _drive_crossing(elastic, oracle):
    for r, batch in enumerate((
        _pods("w0", 6, zone="z-0"), _pods("w1", 6, zone="z-1"),
    )):
        a = elastic.schedule_pending(batch)
        b = oracle.schedule_pending(batch)
        assert a == b
    # the crossing: 8 -> 10 nodes moves the bucket 8 -> 16
    for i in range(8, 10):
        for s in (elastic, oracle):
            s.add_node(_node(f"g-{i}", zone="z-1"))
    batch = _pods("x", 8, zone="z-1")
    names_e = elastic.schedule_pending(batch)
    names_o = oracle.schedule_pending(batch)
    assert names_e == names_o
    # the elastic side grew in place; the oracle re-uploaded in full
    assert elastic._mirror.grow_syncs >= 1
    assert elastic._mirror.resync_total < oracle._mirror.resync_total
    # the resident tensors are bit-identical to the rebuild oracle's
    for f in schema.ClusterTensors._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(elastic._mirror.sync(), f)),
            np.asarray(getattr(oracle._mirror.sync(), f)),
            err_msg=f"leaf {f} diverged after grow",
        )
    # and the resident partials match a from-scratch oracle recompute
    assert elastic._partials.verify(
        elastic._mirror.sync(), None
    )


def test_crossing_grow_bit_identical_single_chip():
    elastic, oracle = _crossing_pair()
    _drive_crossing(elastic, oracle)


@pytest.mark.multichip
def test_crossing_grow_bit_identical_sharded():
    """Mesh mode: the in-place grow re-pads per shard, preserving the
    NamedSharding node-axis layout, and stays bit-identical to the full
    RESHARDED re-upload oracle."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubernetes_tpu.parallel.sharded import make_mesh

    mesh = make_mesh(8)
    elastic, oracle = _crossing_pair(mesh=mesh)
    _drive_crossing(elastic, oracle)
    dev = elastic._mirror.sync()
    if dev.allocatable.shape[0] % 8 == 0:
        assert dev.allocatable.sharding == NamedSharding(
            mesh, P("nodes")
        )


# -- invalidation contracts still hold after a grow ------------------------


def test_reconcile_invalidate_after_grow():
    """Leadership reconcile invalidates mirror+partials; after an
    in-place grow the invalidation must still force one full re-upload
    and one full partials recompute (the delta protocol's history
    assumption no longer holds for a reconciled cache)."""
    elastic, oracle = _crossing_pair()
    _drive_crossing(elastic, oracle)
    r0 = elastic._mirror.resync_total
    f0 = elastic._partials.stats()["full_recomputes"]
    elastic._mirror.invalidate()
    elastic._partials.invalidate()
    names = elastic.schedule_pending(_pods("post", 4, zone="z-0"))
    assert all(n is not None for n in names)
    assert elastic._mirror.resync_total == r0 + 1
    assert elastic._partials.stats()["full_recomputes"] == f0 + 1
    _assert_mirror_matches(elastic._mirror, elastic.state)


def test_speculation_rollback_across_grow():
    """A speculation bookmark taken BEFORE a bucket crossing must roll
    back cleanly: the next sync sees the shape mismatch, resizes (or
    re-uploads) and converges to the live state bit-for-bit."""
    state = _mk_state(8)
    mirror = DeviceClusterMirror(state)
    mirror.sync()
    point = mirror.speculation_point()
    for i in range(8, 11):  # cross 8 -> 16
        state.add_node(_node(f"g-{i}"))
    _assert_mirror_matches(mirror, state)
    assert mirror.grow_syncs == 1
    mirror.rollback(point)  # the speculative batch was invalidated
    # live state unchanged: the re-sync must grow again from the
    # bookmarked 8-row resident and land on the same tensors
    _assert_mirror_matches(mirror, state)
    state.add_pod(make_pod("p").req(cpu_milli=100, mem=MI).obj(), "n-0")
    _assert_mirror_matches(mirror, state)


# -- deferred, bounded compaction ------------------------------------------


def test_drain_compaction_is_amortized():
    """A 10k-node drain does O(live) TOTAL work: every row relocates at
    most ~once (moved rows bounded by the live peak), per-invocation
    moves are bounded by compactionBatchRows, and the watermark lands
    back at the floor."""
    import random
    import time

    state = schema.ClusterState()
    state.configure_elastic_axis(compaction_batch_rows=64)
    n = 10_000
    for i in range(n):
        state.add_node(_node(f"n-{i}"))
    order = list(range(n))
    random.Random(7).shuffle(order)
    t0 = time.perf_counter()
    for i in order:
        state.remove_node(f"n-{i}")
    wall = time.perf_counter() - t0
    assert state.num_nodes == 0
    assert state._high <= state.builder.limits.min_nodes
    # O(live) total: moved rows can never exceed the rows that existed
    assert state.compaction_moved_rows_total <= n
    # amortized, not per-remove O(live): a quadratic drain takes minutes
    assert wall < 30.0, f"10k drain took {wall:.1f}s — O(live^2) regression"
    # surviving arrays still encode cleanly after the full drain
    state.add_node(_node("fresh"))
    t = state.tensors()
    assert t.node_valid[state._rows["fresh"]]


def test_compaction_keeps_mirror_consistent():
    """Bounded compaction moves rows in batches across several
    remove_node calls; every intermediate state must still delta-sync
    exactly (moved rows are ordinary dirty rows, not struct events)."""
    state = _mk_state(48)
    state.configure_elastic_axis(compaction_batch_rows=4, shrink_dwell=2)
    mirror = DeviceClusterMirror(state)
    mirror.sync()
    struct0 = state.struct_generation
    for i in range(40):
        state.remove_node(f"n-{i}")
        if i % 5 == 0:
            _assert_mirror_matches(mirror, state)
    for _ in range(4):  # serve the dwell: generation ticks + syncs
        state.add_pod(
            make_pod(f"t-{_}").req(cpu_milli=1, mem=1).obj(), "n-44"
        )
        _assert_mirror_matches(mirror, state)
    assert state.struct_generation == struct0
    assert state.node_axis_bucket <= 16


# -- config knobs ----------------------------------------------------------


def test_elastic_axis_knobs_thread_through():
    cfg = load_config(
        """
apiVersion: kubescheduler.config.k8s.io/v1
kind: KubeSchedulerConfiguration
nodeAxisHeadroom: 4.0
bucketShrinkDwell: 3
compactionBatchRows: 128
"""
    )
    assert cfg.node_axis_headroom == 4.0
    assert cfg.bucket_shrink_dwell == 3
    assert cfg.compaction_batch_rows == 128
    reg = FrameworkRegistry(cfg)
    assert reg.state.node_axis_headroom == 4.0
    assert reg.state.bucket_shrink_dwell == 3
    assert reg.state.compaction_batch_rows == 128


@pytest.mark.parametrize(
    "field, value",
    [
        ("node_axis_headroom", 0.5),
        ("bucket_shrink_dwell", 0),
        ("compaction_batch_rows", 0),
    ],
)
def test_elastic_axis_knob_validation(field, value):
    cfg = SchedulerConfiguration(**{field: value})
    with pytest.raises(ValueError):
        cfg.validate()
