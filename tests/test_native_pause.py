"""The native pause sandbox holder (the reference's only in-tree C
component, build/pause/linux/pause.c): builds with g++, reaps zombies,
exits on TERM."""

import os
import shutil
import signal
import subprocess
import time

import pytest

NATIVE = os.path.join(os.path.dirname(__file__), "..", "native")


@pytest.fixture(scope="module")
def pause_bin(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("no g++ in this environment")
    # build through the Makefile — one authoritative recipe
    subprocess.run(["make", "-C", NATIVE, "pause"], check=True)
    out = str(tmp_path_factory.mktemp("native") / "pause")
    shutil.copy(os.path.join(NATIVE, "pause"), out)
    subprocess.run(["make", "-C", NATIVE, "clean"], check=True)
    return out


def test_version_flag(pause_bin):
    r = subprocess.run([pause_bin, "-v"], capture_output=True, text=True)
    assert r.returncode == 0
    assert "pause" in r.stdout


def test_exits_on_term(pause_bin):
    p = subprocess.Popen([pause_bin])
    time.sleep(0.2)
    assert p.poll() is None  # parked
    p.send_signal(signal.SIGTERM)
    assert p.wait(timeout=5) == 0


def test_reaps_reparented_orphans(pause_bin):
    """pause sets PR_SET_CHILD_SUBREAPER: an orphaned grandchild
    reparents to it and must be REAPED, not left a zombie (the
    component's actual job)."""
    # shell child of pause double-forks: the intermediate exits, the
    # grandchild reparents to pause (nearest subreaper) and exits 0.3s
    # later — pause's SIGCHLD reap loop must collect it
    # pause is exec'd over a shell that pre-forked the orphan-maker, so
    # the maker's processes are pause's children/reparent targets.
    maker = (
        # the subshell waits 0.2s so pause has installed its subreaper +
        # handlers, THEN forks the grandchild and exits; the grandchild
        # reparents to pause and dies at 0.5s
        "( sleep 0.2; (sleep 0.3; exit 0) & exit 0 ) & "
        f"exec {pause_bin}"
    )
    p = subprocess.Popen(["/bin/sh", "-c", maker])
    try:
        time.sleep(1.0)  # orphan reparented to pause (subreaper) + exited
        assert p.poll() is None, "pause exited early"
        # no zombie children of pause remain
        out = subprocess.run(
            ["ps", "--ppid", str(p.pid), "-o", "stat="],
            capture_output=True, text=True,
        ).stdout
        assert "Z" not in out, f"zombie children linger: {out!r}"
    finally:
        p.send_signal(signal.SIGTERM)
        assert p.wait(timeout=5) == 0
