"""Test configuration.

Forces JAX onto the host CPU platform with 8 virtual devices so
sharding/collective tests exercise a multi-chip mesh without TPU hardware
(the reference's analogue: integration tests create Nodes as API objects
only — test/integration/util/util.go:86).

Note: this image's sitecustomize imports jax at interpreter startup (for
the axon TPU tunnel), so env vars alone are too late; the backend isn't
initialized yet though, so jax.config still wins.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


if os.environ.get("GRAFTLINT_LOCK_ORDER") == "1":
    # opt-in runtime lock-order tracking (docs/static_analysis.md): every
    # threading.Lock/RLock created during the session is wrapped and the
    # session fails if any pair of locks was acquired in both orders.
    @pytest.fixture(autouse=True, scope="session")
    def _graftlint_lock_order():
        from kubernetes_tpu.analysis import runtime as lockorder

        with lockorder.tracked() as tracker:
            yield tracker
        tracker.assert_no_inversions()


if os.environ.get("GRAFTLINT_OBLIGATIONS") == "1":
    # opt-in runtime exactly-once obligation tracking
    # (docs/static_analysis.md obligations section): every popped pod /
    # cache assume / APF seat / arbiter slot / inflight counter / armed
    # fault registry acquisition is recorded with its call chain; a
    # double-discharge raises at the offending call and the session
    # fails on any obligation still held at teardown.
    @pytest.fixture(autouse=True, scope="session")
    def _graftlint_obligations():
        from kubernetes_tpu.analysis import ledger

        with ledger.tracked() as led:
            yield led
        led.assert_clean()

    @pytest.fixture(autouse=True)
    def _graftlint_obligations_boundary(_graftlint_obligations):
        # pod keys recur across tests: reset the double-discharge
        # lookback window at each boundary so one test's retired
        # 'default/p3' never taints the next test's own 'default/p3'
        # (held obligations and recorded violations survive the reset)
        _graftlint_obligations.reset_cycles()
        yield


if os.environ.get("GRAFTLINT_COHERENCE") == "1":
    # opt-in runtime resident-epoch auditing (docs/static_analysis.md
    # coherence section): every resident buffer a solve consumes is
    # checked against the scheduler cache's current generations at
    # consume time, and the session fails on any divergent
    # (resident, field, epoch) triple.
    @pytest.fixture(autouse=True, scope="session")
    def _graftlint_coherence():
        from kubernetes_tpu.analysis import epochs

        with epochs.tracked() as auditor:
            yield auditor
        auditor.assert_clean()


if os.environ.get("GRAFTLINT_SHAPES") == "1":
    # opt-in runtime recompile-discipline tracking (docs/
    # static_analysis.md): every solver jit dispatch reports to the
    # retrace tracker, and the session fails if any executable key was
    # traced twice — the compile cache must hold every key for a whole
    # test session (steady-state windows are a bench concept; tests
    # legitimately visit new buckets all the time).
    @pytest.fixture(autouse=True, scope="session")
    def _graftlint_shapes():
        from kubernetes_tpu.analysis import retrace

        with retrace.tracked() as tracker:
            yield tracker
        tracker.assert_no_duplicate_traces()
