"""Incremental ClusterState: parity with bulk builds, node/pod lifecycle,
and the assume/forget protocol (cache.go:57-260 analogue)."""

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
from kubernetes_tpu.ops import assign, schema
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _nodes(n=8):
    return [
        make_node(f"n{i}")
        .capacity(cpu_milli=8000, mem=16 * GI, pods=10)
        .zone(f"z{i % 3}")
        .obj()
        for i in range(n)
    ]


def _pods(p=12):
    return [
        make_pod(f"p{i}").req(cpu_milli=1000, mem=GI).obj() for i in range(p)
    ]


def test_state_matches_bulk_build():
    nodes, pods = _nodes(), _pods()
    bound = [make_pod("b0").req(cpu_milli=2000).node_name("n3").obj()]

    b1 = schema.SnapshotBuilder()
    snap1, meta1 = b1.build(nodes, pods, bound_pods=bound)

    b2 = schema.SnapshotBuilder()
    st = schema.ClusterState(b2)
    for nd in nodes:
        st.add_node(nd)
    st.add_pod(bound[0])
    snap2, meta2 = b2.build_from_state(st, pods)

    for a1, a2 in zip(snap1.cluster, snap2.cluster):
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    r1 = np.asarray(assign.greedy_assign(snap1).assignment)
    r2 = np.asarray(assign.greedy_assign(snap2).assignment)
    np.testing.assert_array_equal(r1, r2)
    assert meta2.node_name(0) == "n0"


def test_assume_forget_roundtrip():
    st = schema.ClusterState(schema.SnapshotBuilder())
    for nd in _nodes():
        st.add_node(nd)
    before = [a.copy() for a in st.tensors()]
    pod = make_pod("x").req(cpu_milli=1500, mem=2 * GI).host_port(8080).obj()
    st.add_pod(pod, "n2")
    assert st.has_pod(pod)
    changed = st.tensors()
    assert changed.requested[2, schema.RESOURCE_CPU] == 1500
    assert changed.port_bits[2].any()
    st.remove_pod(pod)
    after = st.tensors()
    for b, a in zip(before, after):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(a))


def test_update_node_preserves_usage():
    st = schema.ClusterState(schema.SnapshotBuilder())
    nodes = _nodes()
    for nd in nodes:
        st.add_node(nd)
    st.add_pod(make_pod("x").req(cpu_milli=1000).obj(), "n1")
    updated = (
        make_node("n1")
        .capacity(cpu_milli=16000, mem=32 * GI, pods=20)
        .zone("z9")
        .label("disk", "ssd")
        .obj()
    )
    st.update_node(updated)
    t = st.tensors()
    assert t.allocatable[1, schema.RESOURCE_CPU] == 16000
    assert t.requested[1, schema.RESOURCE_CPU] == 1000  # preserved
    assert t.label_bits[1].any()


def test_remove_node_frees_row_for_reuse():
    st = schema.ClusterState(schema.SnapshotBuilder())
    for nd in _nodes(4):
        st.add_node(nd)
    st.remove_node("n1")
    t = st.tensors()
    assert not t.node_valid[1]
    assert st.num_nodes == 3
    st.add_node(make_node("n9").capacity(cpu_milli=4000, mem=GI).obj())
    t = st.tensors()
    assert t.node_valid[1]  # freed row reused
    assert st.node_names[1] == "n9"


def test_scheduler_incremental_flow():
    """schedule_pending + assume: the second batch sees the first batch's
    placements; forget releases them."""
    sched = TPUBatchScheduler()
    for nd in _nodes(2):
        sched.add_node(nd)
    # Each node fits 8 such pods on cpu (8000/1000).
    first = [make_pod(f"a{i}").req(cpu_milli=1000).obj() for i in range(16)]
    names = sched.schedule_pending(first)
    assert all(n is not None for n in names)
    for p, n in zip(first, names):
        sched.assume(p, n)
    # cluster is now cpu-full: nothing fits
    second = [make_pod("b0").req(cpu_milli=1000).obj()]
    assert sched.schedule_pending(second) == [None]
    # forget one, retry: fits again
    sched.forget(first[0])
    assert sched.schedule_pending(second)[0] is not None


def test_growth_past_initial_capacity():
    st = schema.ClusterState(schema.SnapshotBuilder())
    nodes = _nodes(70)  # > min_nodes default, forces several grows
    for nd in nodes:
        st.add_node(nd)
    t = st.tensors()
    assert st.num_nodes == 70
    assert t.node_valid[:70].all()
    assert t.allocatable.shape[0] >= 70
    # scalar resource widening
    st.add_pod(
        make_pod("gpu").req(cpu_milli=100, **{"example.com/gpu": 2}).obj(), "n0"
    )
    t = st.tensors()
    gi = st.builder.resource_names.index("example.com/gpu")
    assert t.requested[0, gi] == 2
