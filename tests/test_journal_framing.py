"""Batched journal framing (api/framing.py): crash-replay parity with
the per-line journal, upgrade-path interleaving with legacy records,
frame atomicity under corruption/truncation, and native/pure codec
byte-identity.

The frame is the tentpole's durability half: one line + one CRC pass
per commit sub-wave.  Its replay contract is the PR 8 wave-atomicity
contract verbatim — a damaged frame drops WHOLE, never half-applies —
and legacy per-line waves (and pre-CRC lines) must keep replaying
forever, interleaved freely with frames.
"""

import json
import os
import zlib

import pytest

from kubernetes_tpu.api import framing
from kubernetes_tpu.api import store as st
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


def _binder(node):
    def mutate(pod):
        pod.spec.node_name = node
        pod.status.phase = "Running"

    return mutate


def _wave_store(path, n_pods=4, framing_on=True, shards=1):
    s = st.Store(journal_path=path, shards=shards,
                 journal_framing=framing_on)
    s.create(make_node("n0").capacity(cpu_milli=64000, mem=64 * GI).obj())
    for i in range(n_pods):
        s.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    applied, errors = s.update_wave(
        "Pod", [(f"p{i}", "default", _binder("n0")) for i in range(n_pods)]
    )
    assert len(applied) == n_pods and not errors
    return s


def _fp(s):
    return s.state_fingerprint()


# -- crash-replay parity: framed vs per-line ---------------------------------


def test_framed_replay_matches_per_line_oracle(tmp_path):
    """The same write sequence journaled as frames and as per-line wave
    records recovers to the identical store state."""
    pf = str(tmp_path / "framed.jsonl")
    pl = str(tmp_path / "lines.jsonl")
    sf = _wave_store(pf, framing_on=True)
    sl = _wave_store(pl, framing_on=False)
    assert sf.journal_frames >= 1
    assert sl.journal_frames == 0
    want_f, want_l = _fp(sf), _fp(sl)

    def bindings(s):
        return {
            p.meta.name: (p.spec.node_name, p.status.phase)
            for p in s.list("Pod")[0]
        }

    rf = st.Store(journal_path=pf, shards=1)
    rl = st.Store(journal_path=pl, shards=1)
    # each journal recovers to ITS pre-crash state bit-for-bit
    assert _fp(rf) == want_f
    assert _fp(rl) == want_l
    # and the two recoveries agree on the scheduling-visible state
    # (fingerprints differ only in creation timestamps)
    assert bindings(rf) == bindings(rl)
    assert rf._rv == rl._rv


def test_frame_is_one_journal_line(tmp_path):
    """A framed sub-wave is ONE line carrying every record + one crc."""
    path = str(tmp_path / "j.jsonl")
    s = _wave_store(path, n_pods=8)
    s.close()
    waves = [
        json.loads(ln) for ln in open(path)
        if '"f":' in ln or '"w":' in ln
    ]
    frames = [w for w in waves if framing.is_frame(w)]
    assert len(frames) == 1
    assert len(frames[0]["recs"]) == 8
    assert isinstance(frames[0]["crc"], int)


def test_upgrade_path_legacy_then_framed_interleaved(tmp_path):
    """A journal holding legacy per-line waves, pre-CRC lines, AND new
    frames replays completely — the upgrade path never strands an old
    journal."""
    path = str(tmp_path / "j.jsonl")
    s1 = _wave_store(path, n_pods=3, framing_on=False)  # legacy waves
    s1.close()
    # hand-append a pre-CRC record (the oldest format: no crc field)
    rec = {"op": "ADDED", "rv": s1._rv + 1, "kind": "ConfigMap",
           "key": "default/old", "obj": {
               "kind": "ConfigMap",
               "meta": {"name": "old", "namespace": "default"}}}
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    # reopen WITH framing and write a framed wave on top
    s2 = st.Store(journal_path=path, shards=1, journal_framing=True)
    assert s2.get("ConfigMap", "old") is not None  # pre-CRC line applied
    for i in range(3, 6):
        s2.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    applied, errors = s2.update_wave(
        "Pod", [(f"p{i}", "default", _binder("n0")) for i in range(3, 6)]
    )
    assert len(applied) == 3 and not errors
    want = _fp(s2)
    s2.close()
    s3 = st.Store(journal_path=path, shards=1)
    assert _fp(s3) == want
    bound = {p.meta.name for p in s3.list("Pod")[0] if p.spec.node_name}
    assert bound == {f"p{i}" for i in range(6)}


# -- corruption / truncation of the new framing ------------------------------


def test_torn_frame_tail_dropped_whole(tmp_path):
    """A frame torn mid-line (the crash-mid-append case) replays as if
    the wave never happened: nothing half-applied, journal truncated
    back to the frame's start, appends continue cleanly."""
    path = str(tmp_path / "j.jsonl")
    _wave_store(path).close()
    raw = open(path, "rb").read()
    lines = raw.splitlines(keepends=True)
    assert b'"recs"' in lines[-1]
    torn = b"".join(lines[:-1]) + lines[-1][: len(lines[-1]) // 2]
    with open(path, "wb") as f:
        f.write(torn)
    s2 = st.Store(journal_path=path, shards=1)
    assert all(not p.spec.node_name for p in s2.list("Pod")[0])
    assert s2.journal_tail_truncations == 1
    s2.create(make_pod("later").obj())
    s3 = st.Store(journal_path=path, shards=1)
    assert s3.journal_tail_truncations == 0
    assert "later" in {p.meta.name for p in s3.list("Pod")[0]}


def test_corrupt_frame_mid_file_dropped_whole_keeps_later(tmp_path):
    """Mid-file frame damage that still parses as JSON (bit flip inside
    a string) fails the frame CRC: the wave drops WHOLE, is counted as
    a torn wave, and later acknowledged records survive."""
    path = str(tmp_path / "j.jsonl")
    s = _wave_store(path)
    s.create(make_pod("after").obj())
    s.close()
    lines = open(path, "rb").read().splitlines(keepends=True)
    # the frame is the second-to-last line ("after" follows it)
    assert b'"recs"' in lines[-2]
    lines[-2] = lines[-2].replace(b"Running", b"Runnimg", 1)
    with open(path, "wb") as f:
        f.writelines(lines)
    s2 = st.Store(journal_path=path, shards=1)
    names = {p.meta.name for p in s2.list("Pod")[0]}
    assert "after" in names, "record after the corrupt frame was lost"
    assert all(not p.spec.node_name for p in s2.list("Pod")[0]), (
        "corrupt frame was half-applied"
    )
    assert s2.journal_torn_waves == 1


def test_crcless_frame_rejected(tmp_path):
    """`_record_crc_ok`'s crc-less acceptance is an upgrade path for
    PRE-CRC journals only — a frame stripped of its crc must NOT ride
    through that hole (no pre-CRC journal can contain a frame)."""
    path = str(tmp_path / "j.jsonl")
    _wave_store(path).close()
    lines = open(path, "rb").read().splitlines(keepends=True)
    frame = json.loads(lines[-1])
    assert framing.is_frame(frame)
    frame.pop("crc")
    lines[-1] = (json.dumps(frame) + "\n").encode()
    with open(path, "wb") as f:
        f.writelines(lines)
    s2 = st.Store(journal_path=path, shards=1)
    assert all(not p.spec.node_name for p in s2.list("Pod")[0]), (
        "crc-less frame slipped through the legacy acceptance"
    )
    # while plain crc-less records (the real upgrade path) still apply
    assert {p.meta.name for p in s2.list("Pod")[0]} == {
        f"p{i}" for i in range(4)
    }


def test_framed_waves_replay_across_shards(tmp_path):
    """Frames carry per-shard wave ids; a multi-shard store's framed
    journals recover shard-independently to the pre-crash state."""
    path = str(tmp_path / "j.jsonl")
    s = st.Store(journal_path=path, shards=4, journal_framing=True)
    s.create(make_node("n0").capacity(cpu_milli=64000, mem=64 * GI).obj())
    for ns in ("a", "b", "c"):
        for i in range(4):
            s.create(make_pod(f"p{i}", namespace=ns).req(cpu_milli=10).obj())
    for ns in ("a", "b", "c"):
        applied, errors = s.update_wave(
            "Pod", [(f"p{i}", ns, _binder("n0")) for i in range(4)]
        )
        assert len(applied) == 4 and not errors
    want = _fp(s)
    s.close()
    s2 = st.Store(journal_path=path)
    assert _fp(s2) == want


# -- batched fan-out ---------------------------------------------------------


def test_fanout_chunks_deliver_wave_intact(tmp_path):
    """The chunked fan-out (_offer_batch under one Watch._mu) delivers
    every event of a wave in order, and the chunk accounting moves."""
    s = st.Store(shards=1)
    s.create(make_node("n0").capacity(cpu_milli=64000, mem=64 * GI).obj())
    for i in range(16):
        s.create(make_pod(f"p{i}").req(cpu_milli=10).obj())
    w = s.watch("Pod")
    applied, errors = s.update_wave(
        "Pod", [(f"p{i}", "default", _binder("n0")) for i in range(16)]
    )
    assert len(applied) == 16 and not errors
    seen = []
    for _ in range(16):
        ev = w.get(timeout=5.0)
        assert ev is not None
        seen.append(ev.obj.meta.name)
    assert sorted(seen) == sorted(f"p{i}" for i in range(16))
    assert w._last_rv == s._rv
    stats = s.watch_stats()
    assert s.fanout_chunks > 0
    assert s.fanout_chunk_events >= 16
    assert stats["watchers_terminated"] == 0
    w.stop()
    s.close()


# -- codec: native extension vs pure Python ----------------------------------


def test_frame_codec_pure_python_roundtrip():
    recs = [{"op": "ADDED", "rv": i, "kind": "Pod", "key": f"d/p{i}"}
            for i in range(5)]
    line = framing.encode_frame(7, recs)
    assert line.endswith("}\n")
    rec = json.loads(line)
    crc = rec.pop("crc")
    assert framing.is_frame(rec)
    assert framing.frame_crc_ok(rec, crc)
    assert not framing.frame_crc_ok(rec, None)   # crc mandatory on frames
    assert not framing.frame_crc_ok(rec, crc ^ 1)
    assert rec["w"] == 7 and rec["recs"] == recs


def test_native_extension_byte_identity():
    """When _hostplane is importable its outputs must be byte-identical
    to the pure-Python contract (it is a pure accelerator)."""
    if not framing.native_available():
        pytest.skip("_hostplane not built (pure-Python fallback active)")
    import _hostplane

    s = json.dumps({"f": 1, "w": 9, "recs": [{"op": "ADDED", "rv": 1,
                                              "kind": "Pod", "key": "a/b"}]})
    pure = '%s, "crc": %d}\n' % (s[:-1], zlib.crc32(s.encode()))
    assert _hostplane.crc_line(s.encode()).decode() == pure
    assert _hostplane.crc32(s.encode()) == zlib.crc32(s.encode())
    payload = b"\x01\x02\x03\x04payload"
    assert _hostplane.length_prefix(payload) == (
        len(payload).to_bytes(4, "big") + payload
    )


def test_length_prefix_split_roundtrip():
    msgs = [b"alpha", b"", b"x" * 1000]
    buf = b"".join(framing.length_prefix(m) for m in msgs)
    out, rest = framing.split_length_prefixed(buf + b"\x00\x00")
    assert out == msgs
    assert rest == b"\x00\x00"
