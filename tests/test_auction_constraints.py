"""Auction solve with the coupled families (round-3 extension): every
committed placement must satisfy hard topology-spread and required
anti-affinity, with capacity never oversubscribed — validated against
independent numpy recomputation, plus completeness comparisons vs the
exact greedy scan.

Reference criteria mirrored: podtopologyspread/filtering.go:336
(count + self - min <= maxSkew) and interpodaffinity/filtering.go:306-366
(both anti directions).
"""

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import assign, auction, schema
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _zone_nodes(n, zones, cpu=8000, pods_cap=110):
    return [
        make_node(f"n{i}")
        .capacity(cpu_milli=cpu, mem=16 * GI, pods=pods_cap)
        .zone(f"z{i % zones}")
        .obj()
        for i in range(n)
    ]


def _check_spread_valid(nodes, pods, assignment, zones):
    """Recompute final per-(service, zone) counts; all-pairs skew must
    respect each pod's maxSkew (eligible domains = all zones here)."""
    zone_of = {f"n{i}": i % zones for i in range(len(nodes))}
    svc_zone: dict = {}
    for pod, a in zip(pods, assignment):
        if a < 0:
            continue
        svc = pod.meta.labels["app"]
        z = zone_of[f"n{int(a)}"]
        svc_zone.setdefault(svc, np.zeros(zones, int))[z] += 1
    for pod in pods:
        svc = pod.meta.labels["app"]
        cons = pod.spec.topology_spread_constraints
        if not cons or svc not in svc_zone:
            continue
        counts = svc_zone[svc]
        skew = counts.max() - counts.min()
        assert skew <= cons[0].max_skew, (
            f"{svc}: counts={counts.tolist()} skew={skew} > {cons[0].max_skew}"
        )


def test_auction_spread_validity_and_completeness():
    zones = 8
    nodes = _zone_nodes(64, zones)
    pods = [
        make_pod(f"p{i}")
        .req(cpu_milli=250, mem=256 * MI)
        .label("app", f"svc-{i % 4}")
        .spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": f"svc-{i % 4}"})
        .obj()
        for i in range(256)
    ]
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[: len(pods)]
    # 256 pods / 4 services over 8 zones x 8 nodes: all fit under skew 1
    assert (a >= 0).all(), f"unplaced: {(a < 0).sum()}"
    _check_spread_valid(nodes, pods, a, zones)
    # capacity safety
    req = np.asarray(snap.pods.req)[: len(pods)]
    used = np.zeros_like(np.asarray(snap.cluster.requested))
    np.add.at(used, a[a >= 0], req[a >= 0])
    assert (used <= np.asarray(snap.cluster.allocatable) + 1e-5).all()


def test_auction_spread_blocks_infeasible():
    """One tiny zone caps the global distribution: with maxSkew=1 and a
    1-pod z1, at most zones*(1+min...) pods place; the rest must be
    unplaced rather than violating skew."""
    nodes = [
        make_node("big0").capacity(cpu_milli=64000, pods=110).zone("z0").obj(),
        make_node("small").capacity(cpu_milli=250, pods=110).zone("z1").obj(),
    ]
    pods = [
        make_pod(f"p{i}")
        .req(cpu_milli=250)
        .label("app", "s")
        .spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": "s"})
        .obj()
        for i in range(10)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[: len(pods)]
    placed = (a >= 0).sum()
    # z1 fits exactly 1 pod; skew<=1 then allows at most 2 in z0 => 3
    assert placed == 3, (placed, a.tolist())
    _check_spread_valid(nodes, pods, a, 2)
    # matches the exact greedy outcome count
    g = np.asarray(assign.greedy_assign(snap).assignment)[: len(pods)]
    assert placed == (g >= 0).sum()


def test_auction_antiaffinity_validity():
    """Self-anti-affine services on hostname: no two pods of one service
    on one node, all placed when nodes suffice (the c4 shape)."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI).obj()
        for i in range(64)
    ]
    pods = [
        make_pod(f"p{i}")
        .req(cpu_milli=250, mem=256 * MI)
        .label("app", f"svc-{i % 8}")
        .pod_anti_affinity({"app": f"svc-{i % 8}"}, api.LABEL_HOSTNAME)
        .obj()
        for i in range(256)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[: len(pods)]
    # 8 services x 32 pods over 64 nodes: every pod places
    assert (a >= 0).all(), f"unplaced: {(a < 0).sum()}"
    seen = set()
    for pod, ai in zip(pods, a):
        key = (pod.meta.labels["app"], int(ai))
        assert key not in seen, f"anti-affinity violated: {key}"
        seen.add(key)


def test_auction_antiaffinity_against_bound_pods():
    """Filter-level anti-affinity vs already-bound pods still holds on
    the auction route (prep-time blocked/present bits)."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI).obj()
        for i in range(3)
    ]
    bound = [
        make_pod("b0").label("app", "x").node_name("n0").obj(),
        make_pod("b1").label("app", "x").node_name("n1").obj(),
    ]
    pods = [
        make_pod(f"p{i}")
        .req(cpu_milli=100)
        .label("app", "x")
        .pod_anti_affinity({"app": "x"}, api.LABEL_HOSTNAME)
        .obj()
        for i in range(2)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[:2]
    # only n2 is free of app=x pods; exactly one pending pod lands there
    placed = a[a >= 0]
    assert len(placed) == 1 and int(placed[0]) == 2, a.tolist()


def test_auction_mixed_spread_and_anti():
    """Both families in one batch: spread on zone + self-anti on host."""
    zones = 4
    nodes = _zone_nodes(32, zones)
    pods = [
        make_pod(f"p{i}")
        .req(cpu_milli=250, mem=256 * MI)
        .label("app", f"svc-{i % 2}")
        .spread(2, api.LABEL_ZONE, "DoNotSchedule", {"app": f"svc-{i % 2}"})
        .pod_anti_affinity({"app": f"svc-{i % 2}"}, api.LABEL_HOSTNAME)
        .obj()
        for i in range(48)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[: len(pods)]
    assert (a >= 0).all(), f"unplaced: {(a < 0).sum()}"
    _check_spread_valid(nodes, pods, a, zones)
    seen = set()
    for pod, ai in zip(pods, a):
        key = (pod.meta.labels["app"], int(ai))
        assert key not in seen
        seen.add(key)


def test_auction_soft_spread_scores_spread_out():
    """ScheduleAnyway constraints shape scores, not feasibility: pods
    prefer less-loaded zones but never go unplaced over skew."""
    nodes = _zone_nodes(8, 4)
    pods = [
        make_pod(f"p{i}")
        .req(cpu_milli=250, mem=256 * MI)
        .label("app", "s")
        .spread(1, api.LABEL_ZONE, "ScheduleAnyway", {"app": "s"})
        .obj()
        for i in range(16)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[: len(pods)]
    assert (a >= 0).all()
    zone_counts = np.zeros(4, int)
    for ai in a:
        zone_counts[int(ai) % 4] += 1
    # soft spreading keeps zones roughly even (4 each ideally)
    assert zone_counts.max() - zone_counts.min() <= 2, zone_counts.tolist()


def test_auction_spread_nonmatching_carrier_places():
    """A pod whose hard spread constraint selects OTHER pods' labels
    (selfMatch=0, legal) must place whenever the filter admits it — the
    repair's rank criterion gives non-matching carriers the extra admit
    slot (review finding: boundary release loop parked it forever)."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, pods=110).zone(f"z{i % 2}").obj()
        for i in range(4)
    ]
    # bound pods: one "app=x" per zone -> counts (1,1), min=1, skew=0
    bound = [
        make_pod(f"b{i}").label("app", "x").node_name(f"n{i}").obj()
        for i in range(2)
    ]
    # carrier does NOT carry app=x itself; constraint maxSkew=1 over x
    pods = [
        make_pod("carrier")
        .req(cpu_milli=100)
        .spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": "x"})
        .obj()
        # plus enough pods to push the batch onto the auction route
    ] + [
        make_pod(f"f{i}").req(cpu_milli=100).obj() for i in range(7)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[: len(pods)]
    assert a[0] >= 0, "non-matching carrier parked by repair"
