"""Node agent v1: per-pod FSM, probes, restarts, graceful deletion,
checkpoint/resume.

VERDICT r4 #3 acceptance: probe-driven Ready transitions visible to the
disruption controller, restart counts in status, kill-and-resume.
Reference: pkg/kubelet/pod_workers.go (FSM), prober/worker.go (probe
thresholds gate Ready), kubelet.go graceful deletion,
checkpointmanager/checkpoint_manager.go:36.
"""

import time

from kubernetes_tpu.agent import (
    ANN_EXIT_AFTER,
    ANN_EXIT_CODE,
    ANN_FAIL_LIVENESS,
    ANN_FAIL_READINESS,
    FINALIZER,
    NodeAgent,
)
from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.disruption import DisruptionController


def _wait(cond, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


def _pod(name, node="agent-0", policy="Always", ann=None, labels=None):
    return api.Pod(
        meta=api.ObjectMeta(
            name=name,
            labels=dict(labels or {}),
            annotations=dict(ann or {}),
        ),
        spec=api.PodSpec(node_name=node, restart_policy=policy),
    )


def _ready(store, name):
    p = store.get("Pod", name)
    return api.pod_is_ready(p) and p.status.phase == "Running"


def test_start_to_ready_with_ip_and_finalizer():
    store = st.Store()
    agent = NodeAgent(store, "agent-0", register=True).start()
    try:
        store.create(_pod("a"))
        assert _wait(lambda: _ready(store, "a"))
        p = store.get("Pod", "a")
        assert p.status.pod_ip.startswith("10.88.")
        assert p.status.host_ip.startswith("10.64.")
        assert FINALIZER in p.meta.finalizers
        assert any(
            c.get("type") == "Ready" and c.get("status") == "True"
            for c in p.status.conditions
        )
    finally:
        agent.stop()


def test_readiness_probe_gates_ready_and_pdb_sees_it():
    store = st.Store()
    agent = NodeAgent(store, "agent-0", register=True).start()
    mgr = ControllerManager(store, controllers=[DisruptionController]).start()
    try:
        store.create(_pod("a", labels={"app": "web"}))
        store.create(
            api.PodDisruptionBudget(
                meta=api.ObjectMeta(name="pdb"),
                spec=api.PodDisruptionBudgetSpec(
                    selector=api.LabelSelector(match_labels={"app": "web"}),
                    min_available=1,
                ),
            )
        )
        assert _wait(lambda: _ready(store, "a"))
        assert _wait(
            lambda: store.get("PodDisruptionBudget", "pdb").status.current_healthy == 1
        )
        # readiness starts failing (the probe-driven flip)
        p = store.get("Pod", "a")
        p.meta.annotations[ANN_FAIL_READINESS] = "true"
        store.update(p, force=True)
        assert _wait(lambda: not api.pod_is_ready(store.get("Pod", "a")))
        assert _wait(
            lambda: store.get("PodDisruptionBudget", "pdb").status.current_healthy == 0
        )
    finally:
        mgr.stop()
        agent.stop()


def test_liveness_failure_restarts_per_policy():
    store = st.Store()
    agent = NodeAgent(store, "agent-0", register=True, tick=0.02).start()
    try:
        store.create(_pod("a"))
        assert _wait(lambda: _ready(store, "a"))
        p = store.get("Pod", "a")
        p.meta.annotations[ANN_FAIL_LIVENESS] = "true"
        store.update(p, force=True)
        # threshold failures -> restart, count visible in status
        assert _wait(
            lambda: store.get("Pod", "a").status.restart_counts.get("c", 0) >= 1
        )
        # clear the failure; the pod comes back Ready
        p = store.get("Pod", "a")
        del p.meta.annotations[ANN_FAIL_LIVENESS]
        store.update(p, force=True)
        assert _wait(lambda: _ready(store, "a"))

        # restartPolicy=Never: same failure is terminal
        store.create(
            _pod("b", policy="Never", ann={ANN_FAIL_LIVENESS: "true"})
        )
        assert _wait(lambda: store.get("Pod", "b").status.phase == "Failed")
    finally:
        agent.stop()


def test_scripted_exit_succeeds_job_style():
    store = st.Store()
    agent = NodeAgent(store, "agent-0", register=True, tick=0.02).start()
    try:
        store.create(
            _pod("job-pod", policy="Never", ann={ANN_EXIT_AFTER: "0.1"})
        )
        assert _wait(lambda: store.get("Pod", "job-pod").status.phase == "Succeeded")
        store.create(
            _pod(
                "bad-pod",
                policy="Never",
                ann={ANN_EXIT_AFTER: "0.1", ANN_EXIT_CODE: "2"},
            )
        )
        assert _wait(lambda: store.get("Pod", "bad-pod").status.phase == "Failed")
        # terminal pods must not block deletion (finalizer dropped)
        assert FINALIZER not in store.get("Pod", "job-pod").meta.finalizers
    finally:
        agent.stop()


def test_graceful_deletion_two_phase():
    store = st.Store()
    agent = NodeAgent(store, "agent-0", register=True, tick=0.02).start()
    try:
        store.create(
            _pod("a", ann={"agent.kubernetes.io/grace-seconds": "0.3"})
        )
        assert _wait(lambda: _ready(store, "a"))
        store.delete("Pod", "a")
        # phase 1: still present, deletionTimestamp set
        p = store.get("Pod", "a")
        assert p.meta.deletion_timestamp is not None
        # phase 2: gone once the agent releases its finalizer after grace
        def gone():
            try:
                store.get("Pod", "a")
                return False
            except st.NotFound:
                return True
        assert _wait(gone, timeout=5)
    finally:
        agent.stop()


def test_kill_and_resume_checkpoint(tmp_path):
    store = st.Store()
    ckpt = str(tmp_path / "agent.ckpt")
    agent = NodeAgent(store, "agent-0", register=True, tick=0.02,
                      checkpoint_path=ckpt).start()
    store.create(_pod("a"))
    assert _wait(lambda: _ready(store, "a"))
    p = store.get("Pod", "a")
    p.meta.annotations[ANN_FAIL_LIVENESS] = "true"
    store.update(p, force=True)
    assert _wait(
        lambda: store.get("Pod", "a").status.restart_counts.get("c", 0) >= 1
    )
    p = store.get("Pod", "a")
    del p.meta.annotations[ANN_FAIL_LIVENESS]
    store.update(p, force=True)
    counts_before = store.get("Pod", "a").status.restart_counts
    agent.stop()  # "crash"

    agent2 = NodeAgent(store, "agent-0", tick=0.02, checkpoint_path=ckpt).start()
    try:
        assert _wait(lambda: _ready(store, "a"))
        # restart history survived the agent restart
        assert (
            store.get("Pod", "a").status.restart_counts.get("c", 0)
            >= counts_before.get("c", 0) >= 1
        )
    finally:
        agent2.stop()


def test_pressure_eviction_lowest_priority_first():
    """Eviction manager (pkg/kubelet/eviction): memory pressure evicts
    the lowest-priority pod — Failed phase + DisruptionTarget condition,
    the signal controllers recreate from."""
    store = st.Store()
    # heartbeat slow enough that the test observes the first eviction
    # and lifts the pressure before a second sweep could fire
    agent = NodeAgent(
        store, "agent-0", register=True, tick=0.02, heartbeat_interval=0.4
    ).start()
    try:
        low = _pod("low")
        low.spec.priority = 1
        high = _pod("high")
        high.spec.priority = 100
        store.create(low)
        store.create(high)
        assert _wait(lambda: _ready(store, "low") and _ready(store, "high"))
        node = store.get("Node", "agent-0", namespace="")
        node.meta.annotations["agent.kubernetes.io/memory-pressure"] = "true"
        store.update(node, force=True)
        assert _wait(lambda: store.get("Pod", "low").status.phase == "Failed")
        evicted = store.get("Pod", "low")
        assert any(
            c.get("type") == "DisruptionTarget"
            for c in evicted.status.conditions
        )
        # pressure lifted before the next sweep claims the high-prio pod
        node = store.get("Node", "agent-0", namespace="")
        del node.meta.annotations["agent.kubernetes.io/memory-pressure"]
        store.update(node, force=True)
        time.sleep(0.2)
        assert store.get("Pod", "high").status.phase == "Running"
    finally:
        agent.stop()
