"""Host scheduler end-to-end: informer-fed cache/queue, batched cycles,
assume/bind, failure -> unschedulable -> event-driven requeue -> placed.

The integration pattern mirrors the reference's: nodes and pods exist
only as API objects (test/integration/util/util.go:86); the scheduler
watches the store, solves on the (virtual) device, and binds through the
API.
"""

import time

import numpy as np

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler import Scheduler, SchedulingQueue
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _mk_scheduler(store, **kw):
    s = Scheduler(store, **kw)
    s.informers.informer("Node").start()
    s.informers.informer("Pod").start()
    assert s.informers.wait_for_sync(10)
    return s


def _drain(sched, cycles=10, timeout=0.05):
    out = []
    for _ in range(cycles):
        out.append(sched.schedule_batch(timeout=timeout))
    return out


def test_schedules_and_binds_through_api():
    store = st.Store()
    for i in range(4):
        store.create(
            make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=10).obj()
        )
    for i in range(8):
        store.create(make_pod(f"p{i}").req(cpu_milli=500, mem=512 * MI).obj())
    sched = _mk_scheduler(store)
    try:
        stats = sched.schedule_batch(timeout=2)
        assert stats["scheduled"] == 8, stats
        # the binding stage commits waves asynchronously: drain it before
        # reading the store
        assert sched.flush_binds(timeout=30)
        # bound through the API: store shows nodeName on every pod
        pods, _ = store.list("Pod")
        assert all(p.spec.node_name for p in pods)
        # informer echo confirms the assumed pods (no TTL leak)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and sched.cache.assumed_count():
            time.sleep(0.02)
        assert sched.cache.assumed_count() == 0
    finally:
        sched.stop()


def test_unschedulable_requeues_on_node_add_then_places():
    store = st.Store()
    store.create(make_node("small").capacity(cpu_milli=500, mem=GI, pods=10).obj())
    store.create(make_pod("big").req(cpu_milli=4000).obj())
    sched = _mk_scheduler(store)
    try:
        stats = sched.schedule_batch(timeout=2)
        assert stats["unschedulable"] == 1
        assert sched.queue.stats()["unschedulable"] == 1
        # a new big-enough node arrives: the event moves the pod out of
        # the unschedulable tier and the next cycles place it
        store.create(
            make_node("big-node").capacity(cpu_milli=8000, mem=8 * GI, pods=10).obj()
        )
        deadline = time.monotonic() + 10
        placed = False
        while time.monotonic() < deadline and not placed:
            sched.schedule_batch(timeout=0.2)
            placed = bool(store.get("Pod", "big").spec.node_name)
        assert placed
        assert store.get("Pod", "big").spec.node_name == "big-node"
    finally:
        sched.stop()


def test_scheduling_gates_hold_until_cleared():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=4000, mem=8 * GI).obj())
    pod = make_pod("gated").req(cpu_milli=100).obj()
    pod.spec.scheduling_gates = ["wait-for-quota"]
    store.create(pod)
    sched = _mk_scheduler(store)
    try:
        stats = sched.schedule_batch(timeout=0.3)
        assert stats["popped"] == 0
        assert sched.queue.stats()["gated"] == 1
        # clearing the gate releases the pod (PreEnqueue passes)
        cur = store.get("Pod", "gated")
        cur.spec.scheduling_gates = []
        store.update(cur)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            if store.get("Pod", "gated").spec.node_name:
                break
        assert store.get("Pod", "gated").spec.node_name == "n0"
    finally:
        sched.stop()


def test_deleted_assigned_pod_frees_resources_for_pending():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=1000, mem=8 * GI, pods=10).obj())
    store.create(make_pod("first").req(cpu_milli=1000).obj())
    sched = _mk_scheduler(store)
    try:
        assert sched.schedule_batch(timeout=2)["scheduled"] == 1
        assert sched.flush_binds(timeout=30)  # "first" durably bound
        store.create(make_pod("second").req(cpu_milli=1000).obj())
        assert sched.schedule_batch(timeout=2)["unschedulable"] == 1
        store.delete("Pod", "first")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            if store.get("Pod", "second").spec.node_name:
                break
        assert store.get("Pod", "second").spec.node_name == "n0"
    finally:
        sched.stop()


def test_priority_order_in_contended_batch():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=1000, mem=8 * GI, pods=10).obj())
    store.create(make_pod("low").req(cpu_milli=1000).priority(1).obj())
    store.create(make_pod("high").req(cpu_milli=1000).priority(100).obj())
    sched = _mk_scheduler(store)
    try:
        sched.schedule_batch(timeout=2)
        assert sched.flush_binds(timeout=30)
        assert store.get("Pod", "high").spec.node_name == "n0"
        assert not store.get("Pod", "low").spec.node_name
    finally:
        sched.stop()


def test_queue_backoff_and_flush(monkeypatch):
    now = [0.0]
    clock = lambda: now[0]
    q = SchedulingQueue(backoff_base=1.0, backoff_max=10.0,
                        unschedulable_flush_after=300.0, clock=clock)
    pod = make_pod("x").req(cpu_milli=1).obj()
    q.add(pod)
    (info,) = q.pop_batch(10, timeout=0)
    # transient failure: backoff 1s (attempt 1)
    q.requeue_backoff(info)
    assert q.pop_batch(10, timeout=0) == []
    now[0] = 1.1
    (info,) = q.pop_batch(10, timeout=0)
    # unschedulable parks until flush interval
    q.add_unschedulable(info)
    now[0] = 200.0
    assert q.pop_batch(10, timeout=0) == []
    # flush interval moves it to backoff (attempts=2 -> 2s) ...
    now[0] = 302.0
    assert q.pop_batch(10, timeout=0) == []
    # ... and it pops once that backoff expires
    now[0] = 304.2
    (info,) = q.pop_batch(10, timeout=0)
    assert info.attempts == 3
