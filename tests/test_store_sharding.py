"""Cross-shard invariants of the sharded store (ISSUE 9).

The store splits (kind, namespace) keyspaces over N shards — each with
its own lock, journal, checkpoint and watch fan-out — while rv
allocation and the in-memory publish serialize through one small global
lock.  These tests pin the contracts that must survive the split:

  * resourceVersion is strictly monotonic ACROSS shards under
    concurrent commits (publish order == allocation order);
  * a multi-shard bind wave commits as per-shard sub-waves, each
    atomic, each fenced, and every pod binds exactly once even when a
    deposed leader's wave races the successor's;
  * a relist is a point-in-time-consistent cut: a sub-wave is
    all-or-nothing in the snapshot and no item's rv exceeds the cut rv;
  * per-object watch delivery stays rv-monotonic even when one kind's
    events fan out from several shards;
  * recovery is per shard — a torn tail on ONE shard's journal never
    disturbs the surviving shards, and the crashed shard recovers
    snapshot+suffix bit-identical to its full-replay oracle;
  * an explicit shard count that disagrees with the on-disk layout
    reshards losslessly.
"""

import os
import threading
import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.informers import SharedInformer
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import make_pod

NAMESPACES = [f"ns-{i}" for i in range(16)]


def _pod(name, ns, **req):
    pod = make_pod(name).req(cpu_milli=req.get("cpu_milli", 100)).obj()
    pod.meta.namespace = ns
    return pod


def _lease(name, holder, transitions=0, namespace="kube-system"):
    lease = api.Lease()
    lease.meta.name = name
    lease.meta.namespace = namespace
    lease.spec.holder_identity = holder
    lease.spec.lease_transitions = transitions
    return lease


def test_namespaces_spread_across_shards():
    s = st.Store(shards=8)
    indices = {s.shard_index("Pod", ns) for ns in NAMESPACES}
    assert len(indices) > 1, "16 namespaces hashed to one shard"
    # an object's shard is a pure function of (kind, namespace): the
    # same namespace under a different kind may live elsewhere
    assert s.shard_index("Pod", "ns-0") == s.shard_index("Pod", "ns-0")
    # cluster-scoped kinds normalize to namespace "" regardless of what
    # the caller passes — one shard owns all Nodes
    assert s.shard_index("Node", "anything") == s.shard_index("Node", "")


def test_rv_strictly_monotonic_across_shards_under_concurrent_commits():
    """The chaos suite's dispatch-order audit, cross-shard: every
    publish (single-object and wave) must hand its events to the
    dispatch path in strictly ascending rv order even with 8 writer
    threads spread over every shard."""
    s = st.Store(shards=8)
    violations = []
    last = [0]
    orig_dispatch, orig_wave = s._dispatch, s._dispatch_wave

    def check(ev):
        if ev.rv <= last[0]:
            violations.append((ev.rv, last[0]))
        last[0] = max(last[0], ev.rv)

    def dispatch(ev):
        check(ev)
        orig_dispatch(ev)

    def dispatch_wave(kind, events):
        for ev in events:
            check(ev)
        orig_wave(kind, events)

    s._dispatch, s._dispatch_wave = dispatch, dispatch_wave

    per_thread = 40

    def writer(t):
        ns = NAMESPACES[t % len(NAMESPACES)]
        for i in range(per_thread):
            s.create(_pod(f"p{t}-{i}", ns))
            if i % 4 == 3:
                def label(pod, i=i):
                    pod.meta.labels["i"] = str(i)
                s.update_wave(
                    "Pod",
                    [(f"p{t}-{k}", ns, label) for k in range(i - 3, i + 1)],
                )

    threads = [
        threading.Thread(target=writer, args=(t,)) for t in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not violations, f"rv regressions at dispatch: {violations[:5]}"
    # allocation is gapless: the final rv equals the number of commits
    # (per thread: per_thread creates + one 4-update wave per 4 creates)
    writes = 8 * (per_thread + (per_thread // 4) * 4)
    assert s.resource_version == writes


def test_multi_shard_wave_applies_and_splits_errors_per_object():
    s = st.Store(shards=8)
    for i in range(12):
        s.create(_pod(f"p{i}", NAMESPACES[i % 6]))

    def set_node(pod):
        pod.spec.node_name = "n0"

    def boom(pod):
        raise RuntimeError("bad mutate")

    updates = [(f"p{i}", NAMESPACES[i % 6], set_node) for i in range(12)]
    updates.append(("missing", "ns-0", set_node))
    updates.append(("p0", "ns-0", boom))  # second entry for p0: conflict-free mutate error
    applied, errors = s.update_wave("Pod", updates)
    assert len(applied) == 12
    assert isinstance(errors["ns-0/missing"], st.NotFound)
    assert "ns-0/p0" in errors  # the boom entry
    for i in range(12):
        assert s.get("Pod", f"p{i}", NAMESPACES[i % 6]).spec.node_name == "n0"


def test_multi_shard_wave_fenced_commits_nothing():
    """A deposed leader's wave spanning shards is rejected by the
    pre-flight fence check before ANY sub-wave publishes."""
    s = st.Store(shards=8)
    s.create(_lease("sched", holder="old-leader", transitions=3))
    for i in range(8):
        s.create(_pod(f"p{i}", NAMESPACES[i]))
    stale = st.FenceToken(
        "sched", "kube-system", "dead-leader", generation=2
    )

    def bind(pod):
        pod.spec.node_name = "n1"

    with pytest.raises(st.Fenced):
        s.update_wave(
            "Pod",
            [(f"p{i}", NAMESPACES[i], bind) for i in range(8)],
            fence=stale,
        )
    assert s.fenced_writes_total == 1
    for i in range(8):
        assert s.get("Pod", f"p{i}", NAMESPACES[i]).spec.node_name == ""


def test_bound_exactly_once_per_subwave_under_fencing():
    """The takeover race, store-level: an old leader's multi-shard bind
    wave is mid-flight when the lease transitions.  Sub-waves that
    publish BEFORE the transition commit under the old fence; everything
    after is Fenced — and the new leader's wave re-binds only the
    unbound remainder, so no pod is ever moved (bound exactly once per
    sub-wave)."""
    s = st.Store(shards=8)
    s.create(_lease("sched", holder="leader-1", transitions=1))
    pods = [(f"p{i}", NAMESPACES[i]) for i in range(8)]
    for name, ns in pods:
        s.create(_pod(name, ns))
    old_fence = st.FenceToken("sched", "kube-system", "leader-1", 1)
    new_fence = st.FenceToken("sched", "kube-system", "leader-2", 2)

    def binder(node):
        def mutate(pod):
            if pod.spec.node_name and pod.spec.node_name != node:
                raise st.Conflict(
                    f"pod already bound to {pod.spec.node_name}"
                )
            pod.spec.node_name = node
        return mutate

    # the old leader commits the first half of its wave...
    a1, e1 = s.update_wave(
        "Pod", [(n, ns, binder("node-old")) for n, ns in pods[:4]],
        fence=old_fence,
    )
    assert len(a1) == 4 and not e1
    # ...then is deposed (lease transitions to the successor)...
    lease = s.get("Lease", "sched", "kube-system")
    lease.spec.holder_identity = "leader-2"
    lease.spec.lease_transitions = 2
    s.update(lease, force=True)
    # ...and its second half is rejected whole
    with pytest.raises(st.Fenced):
        s.update_wave(
            "Pod", [(n, ns, binder("node-old")) for n, ns in pods[4:]],
            fence=old_fence,
        )
    # the successor binds the remainder; its wave ALSO carries the
    # bound-exactly-once mutator guard, so recommitting the full set
    # moves nothing — the first four stay on node-old
    a2, e2 = s.update_wave(
        "Pod", [(n, ns, binder("node-new")) for n, ns in pods],
        fence=new_fence,
    )
    bound = {
        f"{ns}/{n}": s.get("Pod", n, ns).spec.node_name for n, ns in pods
    }
    for n, ns in pods[:4]:
        assert bound[f"{ns}/{n}"] == "node-old"
        assert f"{ns}/{n}" in e2  # the Conflict split per object
    for n, ns in pods[4:]:
        assert bound[f"{ns}/{n}"] == "node-new"


def test_relist_is_point_in_time_consistent_cut():
    """Concurrent single-shard sub-waves stamp a generation across W
    objects; every relist must observe each namespace's object set at
    ONE generation (a sub-wave is all-or-nothing in the cut) and no
    item newer than the cut rv."""
    s = st.Store(shards=8)
    W = 6
    ns_list = NAMESPACES[:4]
    for ns in ns_list:
        for i in range(W):
            s.create(_pod(f"g{i}", ns))
    stop = threading.Event()
    problems = []

    def waver(ns):
        gen = 0
        while not stop.is_set():
            gen += 1

            def stamp(pod, gen=gen):
                pod.meta.labels["gen"] = str(gen)

            s.update_wave(
                "Pod", [(f"g{i}", ns, stamp) for i in range(W)]
            )

    def churner():
        # create/delete cycles: a delete must never mutate the rv of
        # the committed object a concurrent cut is still copying
        i = 0
        while not stop.is_set():
            i += 1
            s.create(_pod(f"churn-{i % 7}", "ns-churn"))
            s.delete("Pod", f"churn-{i % 7}", "ns-churn")

    writers = [
        threading.Thread(target=waver, args=(ns,)) for ns in ns_list
    ] + [threading.Thread(target=churner)]
    for t in writers:
        t.start()
    try:
        for _ in range(60):
            items, rv = s.list("Pod")
            by_ns = {}
            for p in items:
                if p.meta.resource_version > rv:
                    problems.append(
                        f"item rv {p.meta.resource_version} > cut {rv}"
                    )
                if p.meta.namespace == "ns-churn":
                    continue  # create/delete churn: rv bound only
                by_ns.setdefault(p.meta.namespace, set()).add(
                    p.meta.labels.get("gen")
                )
            for ns, gens in by_ns.items():
                if len(gens) > 1:
                    problems.append(f"{ns}: torn cut {sorted(gens)}")
            if problems:
                break
    finally:
        stop.set()
        for t in writers:
            t.join(timeout=5)
    assert not problems, problems[:5]


def test_informer_relist_sees_consistent_cut():
    """The informer-level half of the cut contract: a SharedInformer
    relisting during cross-shard wave churn lands on a cache whose
    objects all have rv <= its relist bookmark."""
    s = st.Store(shards=8)
    for i, ns in enumerate(NAMESPACES[:4]):
        for k in range(4):
            s.create(_pod(f"p{k}", ns))
    inf = SharedInformer(s, "Pod")
    stop = threading.Event()

    def churner():
        while not stop.is_set():
            for ns in NAMESPACES[:4]:
                def touch(pod):
                    pod.meta.labels["t"] = "x"
                s.update_wave(
                    "Pod", [(f"p{k}", ns, touch) for k in range(4)]
                )

    t = threading.Thread(target=churner)
    t.start()
    try:
        inf.start()
        assert inf.wait_for_sync(10)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and inf.relists < 1:
            time.sleep(0.01)
        cut = inf.last_relist_rv
        assert cut > 0
    finally:
        stop.set()
        t.join(timeout=5)
        inf.stop()
    # every object the relist installed predates (or is) the cut
    assert all(
        p.meta.resource_version <= s.resource_version for p in inf.list()
    )


def test_watch_across_shards_is_per_object_monotonic_and_lossless():
    """One Pod watcher fed by several shards' fan-out threads: per
    object the rv sequence is strictly ascending, and the replayed
    stream converges to the exact final store state (the coalescing
    contract, cross-shard)."""
    s = st.Store(shards=8)
    w = s.watch("Pod")
    n_threads, per_thread = 6, 30

    def writer(t):
        ns = NAMESPACES[t]
        for i in range(per_thread):
            name = f"p{t}-{i}"
            s.create(_pod(name, ns))
            fresh = s.get("Pod", name, ns)
            fresh.meta.labels["v"] = "1"
            s.update(fresh)

    threads = [
        threading.Thread(target=writer, args=(t,))
        for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    state = {}
    last_per_key = {}
    while True:
        ev = w.get(timeout=0.5)
        if ev is None:
            break
        key = f"{ev.obj.meta.namespace}/{ev.obj.meta.name}"
        assert ev.rv > last_per_key.get(key, 0), (
            f"{key}: rv {ev.rv} after {last_per_key.get(key)}"
        )
        last_per_key[key] = ev.rv
        state[key] = ev.obj.meta.resource_version
    w.stop()
    assert not w.expired and s.watchers_terminated == 0
    final = {
        f"{p.meta.namespace}/{p.meta.name}": p.meta.resource_version
        for p in s.list("Pod")[0]
    }
    assert state == final


def test_shard_fault_points_fire_with_shard_context(tmp_path):
    """The per-shard fault points are live: a schedule on
    store.shard.update_wave / store.shard.journal.append fires on the
    first shard reaching the point."""
    path = str(tmp_path / "j.jsonl")
    s = st.Store(journal_path=path, shards=4)
    s.create(_pod("a", "ns-0"))
    reg = faults.FaultRegistry(seed=1)
    reg.fail("store.shard.update_wave", n=1)
    with faults.armed(reg):
        with pytest.raises(faults.FaultInjected):
            def touch(pod):
                pod.meta.labels["x"] = "1"
            s.update_wave("Pod", [("a", "ns-0", touch)])
    assert reg.fired.get("store.shard.update_wave") == 1
    reg2 = faults.FaultRegistry(seed=2)
    reg2.fail("store.shard.journal.append", n=1)
    with faults.armed(reg2):
        s.create(_pod("b", "ns-1"))  # journal degrades, commit stands
    assert reg2.fired.get("store.shard.journal.append") == 1
    assert s.journal_write_errors == 1
    assert s.get("Pod", "b", "ns-1").meta.name == "b"


def test_one_shard_torn_tail_recovers_others_untouched(tmp_path):
    """Crash-one-shard: tear one shard's journal tail mid-record.  The
    surviving shards replay byte-identically; the crashed shard
    truncates the torn tail and recovers its acked prefix — and the
    whole recovered store matches its full-replay oracle."""
    path = str(tmp_path / "j.jsonl")
    s = st.Store(journal_path=path, shards=4)
    for i in range(24):
        s.create(_pod(f"p{i}", NAMESPACES[i % 8]))
    s.close()
    # find a shard journal with content and tear its final record
    victim = None
    for i in range(4):
        p = f"{path}.s{i}"
        if os.path.getsize(p) > 0:
            victim = p
    assert victim is not None
    raw = open(victim, "rb").read()
    open(victim, "wb").write(raw[: len(raw) - 17])
    img = faults.crash_disk_image(path, str(tmp_path / "img"))
    oracle_img = faults.crash_disk_image(path, str(tmp_path / "oracle"))
    faults.remove_snapshots(oracle_img)
    recovered = st.Store(journal_path=img)
    oracle = st.Store(journal_path=oracle_img)
    assert recovered.shard_count == 4
    assert recovered.journal_tail_truncations == 1
    assert recovered.state_fingerprint() == oracle.state_fingerprint()
    # the torn shard lost exactly its final unacked record; the other
    # shards' pods all survived
    assert len(recovered.list("Pod")[0]) == 23


def test_reshard_on_explicit_shard_count_is_lossless(tmp_path):
    path = str(tmp_path / "j.jsonl")
    s = st.Store(journal_path=path, shards=2)
    for i in range(20):
        s.create(_pod(f"p{i}", NAMESPACES[i % 10]))
    def bind(pod):
        pod.spec.node_name = "n0"
    s.update_wave(
        "Pod", [(f"p{i}", NAMESPACES[i % 10], bind) for i in range(20)]
    )
    fp = s.state_fingerprint()
    s.close()
    wide = st.Store(journal_path=path, shards=8)
    assert wide.shard_count == 8
    assert wide.state_fingerprint() == fp
    wide.close()
    # the new layout persists: inference now finds 8 shards
    again = st.Store(journal_path=path)
    assert again.shard_count == 8
    assert again.state_fingerprint() == fp


def test_checkpoint_all_shards_and_suffix_recovery(tmp_path):
    path = str(tmp_path / "j.jsonl")
    s = st.Store(journal_path=path, shards=4)
    for i in range(16):
        s.create(_pod(f"p{i}", NAMESPACES[i % 8]))
    n = s.checkpoint()
    assert n == 16
    for i in range(16, 24):
        s.create(_pod(f"p{i}", NAMESPACES[i % 8]))
    s.close()
    recovered = st.Store(journal_path=path)
    assert recovered.snapshot_records == 16
    assert recovered.journal_suffix_records == 8
    assert recovered.state_fingerprint() == s.state_fingerprint()
