"""Placement parity: greedy_assign (TPU kernels) vs the pure-Python oracle.

This is the round-1 "minimum end-to-end slice" acceptance test from
SURVEY.md section 7: identical placements to a reference-semantics oracle
across randomized and structured workloads.
"""

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import assign, schema
from kubernetes_tpu.testing.oracle import Oracle
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def run_both(nodes, pods, bound=()):
    snap, meta = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    result = assign.greedy_assign_jit()(snap)
    got = [meta.node_name(int(i)) for i in np.asarray(result.assignment)[: len(pods)]]
    want = Oracle(nodes, bound_pods=bound).schedule(pods)
    return got, want


def test_basic_binpack_parity():
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=110).obj()
        for i in range(8)
    ]
    pods = [make_pod(f"p{i}").req(cpu_milli=1000, mem=1 * GI).obj() for i in range(20)]
    got, want = run_both(nodes, pods)
    assert got == want
    assert None not in got


def test_unschedulable_overflow():
    nodes = [make_node("n0").capacity(cpu_milli=1000, mem=2 * GI, pods=110).obj()]
    pods = [make_pod(f"p{i}").req(cpu_milli=600, mem=256 * MI).obj() for i in range(3)]
    got, want = run_both(nodes, pods)
    assert got == want == ["n0", None, None]


def test_spread_via_least_allocated():
    """LeastAllocated drives pods onto the emptiest node each pick."""
    nodes = [
        make_node("a").capacity(cpu_milli=10000, mem=16 * GI, pods=110).obj(),
        make_node("b").capacity(cpu_milli=10000, mem=16 * GI, pods=110).obj(),
    ]
    pods = [make_pod(f"p{i}").req(cpu_milli=2000, mem=2 * GI).obj() for i in range(4)]
    got, want = run_both(nodes, pods)
    assert got == want
    assert got.count("a") == got.count("b") == 2


def test_parity_with_affinity_taints_ports():
    nodes = [
        make_node("gpu0").capacity(cpu_milli=16000, mem=32 * GI, pods=110)
        .zone("z1").taint("dedicated", "ml", api.NO_SCHEDULE).obj(),
        make_node("gen0").capacity(cpu_milli=8000, mem=16 * GI, pods=110).zone("z1").obj(),
        make_node("gen1").capacity(cpu_milli=8000, mem=16 * GI, pods=110).zone("z2").obj(),
    ]
    pods = [
        make_pod("web0").req(cpu_milli=1000, mem=1 * GI).host_port(80).obj(),
        make_pod("web1").req(cpu_milli=1000, mem=1 * GI).host_port(80).obj(),
        make_pod("web2").req(cpu_milli=1000, mem=1 * GI).host_port(80).obj(),
        make_pod("ml0").req(cpu_milli=4000, mem=8 * GI)
        .toleration("dedicated", api.OP_EQUAL, "ml", api.NO_SCHEDULE)
        .preferred_affinity(10, api.LABEL_ZONE, api.OP_IN, ["z1"]).obj(),
        make_pod("zonal").req(cpu_milli=500, mem=512 * MI)
        .node_selector_kv(api.LABEL_ZONE, "z2").obj(),
    ]
    got, want = run_both(nodes, pods)
    assert got == want


@pytest.mark.parametrize("seed", range(4))
def test_randomized_parity(seed):
    rng = np.random.default_rng(seed)
    zones = ["z1", "z2", "z3"]
    nodes = []
    for i in range(24):
        nw = (
            make_node(f"n{i}")
            .capacity(
                cpu_milli=int(rng.choice([2000, 4000, 8000, 16000])),
                mem=int(rng.choice([4, 8, 16, 32])) * GI,
                pods=int(rng.choice([5, 10, 110])),
            )
            .zone(str(rng.choice(zones)))
        )
        if rng.random() < 0.2:
            nw.taint("dedicated", "batch", api.NO_SCHEDULE)
        if rng.random() < 0.15:
            nw.taint("flaky", "true", api.PREFER_NO_SCHEDULE)
        if rng.random() < 0.1:
            nw.unschedulable()
        nodes.append(nw.obj())

    pods = []
    for i in range(60):
        pw = make_pod(f"p{i}").req(
            cpu_milli=int(rng.choice([0, 100, 500, 1000, 2000])),
            mem=int(rng.choice([0, 128, 512, 1024, 4096])) * MI,
        )
        if rng.random() < 0.3:
            pw.node_selector_kv(api.LABEL_ZONE, str(rng.choice(zones)))
        if rng.random() < 0.2:
            pw.toleration("dedicated", api.OP_EQUAL, "batch", api.NO_SCHEDULE)
        if rng.random() < 0.2:
            pw.preferred_affinity(
                int(rng.integers(1, 100)), api.LABEL_ZONE, api.OP_IN, [str(rng.choice(zones))]
            )
        if rng.random() < 0.15:
            pw.host_port(int(rng.choice([80, 443, 8080])))
        pods.append(pw.obj())

    got, want = run_both(nodes, pods)
    assert got == want


def test_bound_pods_respected():
    nodes = [
        make_node("a").capacity(cpu_milli=4000, mem=8 * GI, pods=110).obj(),
        make_node("b").capacity(cpu_milli=4000, mem=8 * GI, pods=110).obj(),
    ]
    bound = [make_pod("old").req(cpu_milli=3000, mem=6 * GI).node_name("a").obj()]
    pods = [make_pod("new").req(cpu_milli=2000, mem=2 * GI).obj()]
    got, want = run_both(nodes, pods, bound=bound)
    assert got == want == ["b"]
