"""DeviceClusterMirror: delta sync must equal a fresh full upload after
any mutation sequence (the generation-protocol analogue of the
reference's cache_test.go snapshot-consistency cases around
internal/cache/cache.go:185-260)."""

import numpy as np
import jax
import pytest

from kubernetes_tpu.models.mirror import DeviceClusterMirror
from kubernetes_tpu.ops import schema
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _mk_state(n=12):
    state = schema.ClusterState()
    for i in range(n):
        state.add_node(
            make_node(f"n-{i}")
            .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .zone(f"z-{i % 3}")
            .obj()
        )
    return state


def _assert_mirror_matches(mirror, state):
    dev = mirror.sync()
    want = state.tensors()
    for name in want._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(dev, name)),
            np.asarray(getattr(want, name)),
            err_msg=f"leaf {name} diverged",
        )


def test_initial_sync_and_noop_resync():
    state = _mk_state()
    mirror = DeviceClusterMirror(state)
    _assert_mirror_matches(mirror, state)
    dev1 = mirror.sync()
    dev2 = mirror.sync()  # no mutations: must return the same arrays
    assert dev1.allocatable is dev2.allocatable


def test_pod_usage_deltas():
    state = _mk_state()
    mirror = DeviceClusterMirror(state)
    mirror.sync()
    pods = [
        make_pod(f"p-{i}").req(cpu_milli=500, mem=256 * MI).obj()
        for i in range(5)
    ]
    for i, p in enumerate(pods):
        state.add_pod(p, f"n-{i % 3}")
    _assert_mirror_matches(mirror, state)
    state.remove_pod(pods[0])
    state.remove_pod(pods[3])
    _assert_mirror_matches(mirror, state)


def test_node_lifecycle_deltas():
    state = _mk_state()
    mirror = DeviceClusterMirror(state)
    mirror.sync()
    state.update_node(
        make_node("n-1").capacity(cpu_milli=32000, mem=64 * GI, pods=200)
        .zone("z-9").label("disk", "ssd").obj()
    )
    _assert_mirror_matches(mirror, state)
    state.remove_node("n-2")
    _assert_mirror_matches(mirror, state)
    state.add_node(
        make_node("n-new").capacity(cpu_milli=1000, mem=GI, pods=10)
        .taint("dedicated", "gpu", "NoSchedule").obj()
    )
    _assert_mirror_matches(mirror, state)


def test_growth_is_not_a_struct_event():
    """Backing-array growth preserves row indices, so it must NOT move
    the struct generation (the elastic-node-axis contract): the mirror
    absorbs bucket crossings with an in-place resident grow — or, for a
    bulk load like this one, the over-fraction full upload — and still
    matches a fresh encode bit-for-bit."""
    state = _mk_state(4)
    mirror = DeviceClusterMirror(state)
    mirror.sync()
    gen0 = state.struct_generation
    for i in range(200):  # cross several growth buckets
        state.add_node(
            make_node(f"g-{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=50)
            .obj()
        )
    assert state.struct_generation == gen0
    _assert_mirror_matches(mirror, state)


def test_resource_widen_forces_struct_resync():
    state = _mk_state()
    mirror = DeviceClusterMirror(state)
    mirror.sync()
    state.add_node(
        make_node("tpu-node")
        .capacity(cpu_milli=8000, mem=16 * GI, pods=110,
                  **{"google.com/tpu": 8})
        .obj()
    )
    _assert_mirror_matches(mirror, state)


def test_compaction_deltas():
    state = _mk_state(40)
    mirror = DeviceClusterMirror(state)
    mirror.sync()
    for i in range(5, 40):
        state.remove_node(f"n-{i}")  # triggers _maybe_compact
    _assert_mirror_matches(mirror, state)


def test_two_mirrors_one_state():
    """Profiles: two consumers sync independently through the shared
    generation counters."""
    state = _mk_state()
    m1 = DeviceClusterMirror(state)
    m2 = DeviceClusterMirror(state)
    m1.sync()
    state.add_pod(make_pod("p").req(cpu_milli=100, mem=MI).obj(), "n-0")
    m2.sync()
    state.add_pod(make_pod("q").req(cpu_milli=100, mem=MI).obj(), "n-1")
    _assert_mirror_matches(m1, state)
    _assert_mirror_matches(m2, state)


def _mesh():
    from kubernetes_tpu.parallel.sharded import make_mesh

    return make_mesh(8)


@pytest.mark.multichip
def test_mesh_mirror_parity_and_sharding():
    """Under a mesh the resident tensors must (a) stay value-identical
    to a full re-encode of the state (the oracle) across every mutation
    family, and (b) carry the node-axis NamedSharding the sharded
    solvers' shard_map specs expect — no per-batch resharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh()
    state = _mk_state(12)
    mirror = DeviceClusterMirror(state, mesh=mesh)
    _assert_mirror_matches(mirror, state)
    dev = mirror.sync()
    assert dev.allocatable.sharding == NamedSharding(mesh, P("nodes"))
    assert dev.taint_bits.sharding == NamedSharding(mesh, P(None, "nodes"))

    # usage deltas scatter into the owning shard
    pods = [
        make_pod(f"p-{i}").req(cpu_milli=500, mem=256 * MI).obj()
        for i in range(5)
    ]
    for i, p in enumerate(pods):
        state.add_pod(p, f"n-{i % 3}")
    _assert_mirror_matches(mirror, state)
    assert mirror.delta_syncs >= 1 and mirror.delta_rows_total >= 3
    # the delta result keeps the sharded layout (a sharding flip would
    # retrace the scatter AND reshard the next solve)
    assert mirror.sync().requested.sharding == NamedSharding(
        mesh, P("nodes")
    )

    # static deltas + node lifecycle
    state.update_node(
        make_node("n-1").capacity(cpu_milli=32000, mem=64 * GI, pods=200)
        .zone("z-9").label("disk", "ssd").obj()
    )
    state.remove_node("n-2")
    _assert_mirror_matches(mirror, state)

    # growth across buckets forces a full RESHARDED re-upload
    resyncs0 = mirror.resync_total
    for i in range(200):
        state.add_node(
            make_node(f"g-{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=50)
            .obj()
        )
    _assert_mirror_matches(mirror, state)
    assert mirror.resync_total > resyncs0
    assert mirror.sync().allocatable.sharding == NamedSharding(
        mesh, P("nodes")
    )


@pytest.mark.multichip
def test_mesh_mirror_small_bucket_replicates():
    """A padded bucket smaller than the mesh cannot shard: the mirror
    replicates it (these batches solve single-chip anyway) and still
    matches the re-encode oracle."""
    state = schema.ClusterState(
        schema.SnapshotBuilder(schema.SnapshotLimits(min_nodes=4))
    )
    for i in range(3):
        state.add_node(
            make_node(f"n-{i}").capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .obj()
        )
    mirror = DeviceClusterMirror(state, mesh=_mesh())
    _assert_mirror_matches(mirror, state)
    state.add_pod(make_pod("p").req(cpu_milli=100, mem=MI).obj(), "n-0")
    _assert_mirror_matches(mirror, state)


def test_scheduler_steps_use_mirror():
    """End-to-end: repeated schedule_pending steps with assumes between
    them stay correct (the steady-state loop the mirror accelerates)."""
    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler

    sched = TPUBatchScheduler()
    for i in range(8):
        sched.add_node(
            make_node(f"n-{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=20)
            .obj()
        )
    placed = {}
    for step in range(4):
        pods = [
            make_pod(f"s{step}-p{i}").req(cpu_milli=1000, mem=GI).obj()
            for i in range(6)
        ]
        names = sched.schedule_pending(pods)
        for p, nm in zip(pods, names):
            assert nm is not None
            sched.assume(p, nm)
            placed[p.meta.name] = nm
    # every node's accumulated usage is visible: a final over-ask fails
    big = [make_pod("big").req(cpu_milli=4000, mem=GI).obj()]
    assert sched.schedule_pending(big) == [None]
