"""Controller wave 2: HPA (with the PodMetrics pipeline), ResourceQuota
(admission + status), ServiceAccount, TTL-after-finished.

VERDICT r4 #5 acceptance: an HPA scales a Deployment up under synthetic
load and back down; quota rejects over-budget creates.
Reference: pkg/controller/podautoscaler/horizontal.go:125,
plugin/pkg/admission/resourcequota, pkg/controller/serviceaccount,
pkg/controller/ttlafterfinished.
"""

import time

import pytest

from kubernetes_tpu.api import admission as adm
from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.podautoscaler import (
    HorizontalPodAutoscalerController,
)
from kubernetes_tpu.controllers.replicaset import ReplicaSetController
from kubernetes_tpu.controllers.resourcequota import ResourceQuotaController
from kubernetes_tpu.controllers.serviceaccount import (
    ServiceAccountController,
    TTLAfterFinishedController,
)
from kubernetes_tpu.testing.wrappers import MI, make_pod


def _wait(cond, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


def _deployment(name="web", replicas=2, cpu=100):
    labels = {"app": name}
    return api.Deployment(
        meta=api.ObjectMeta(name=name),
        spec=api.DeploymentSpec(
            replicas=replicas,
            selector=api.LabelSelector(match_labels=labels),
            template=api.PodTemplateSpec(
                meta=api.ObjectMeta(labels=labels),
                spec=api.PodSpec(
                    containers=[
                        api.Container(requests={api.CPU: cpu, api.MEMORY: 64 * MI})
                    ]
                ),
            ),
        ),
    )


def _metrics(store, pod, cpu):
    m = api.PodMetrics(
        meta=api.ObjectMeta(name=pod.meta.name, namespace=pod.meta.namespace),
        usage={api.CPU: cpu},
        timestamp=time.time(),
    )
    try:
        store.create(m)
    except st.AlreadyExists:
        cur = store.get("PodMetrics", pod.meta.name, pod.meta.namespace)
        cur.usage = m.usage
        store.update(cur, force=True)


def test_hpa_scales_up_and_down():
    store = st.Store()

    def hpa_factory(*args, **kw):
        return HorizontalPodAutoscalerController(
            *args, downscale_stabilization_s=0.2, **kw
        )

    hpa_factory.KIND = "HorizontalPodAutoscaler"
    mgr = ControllerManager(
        store,
        controllers=[DeploymentController, ReplicaSetController, hpa_factory],
    ).start()
    try:
        store.create(_deployment("web", replicas=2, cpu=100))
        assert _wait(lambda: len(store.list("Pod")[0]) == 2)
        for p in store.list("Pod")[0]:
            p.status.phase = "Running"
            store.update(p, force=True)
        store.create(
            api.HorizontalPodAutoscaler(
                meta=api.ObjectMeta(name="web-hpa"),
                spec=api.HorizontalPodAutoscalerSpec(
                    scale_target_ref=api.ScaleTargetRef("Deployment", "web"),
                    min_replicas=1,
                    max_replicas=6,
                    target_cpu_utilization_percentage=50,
                ),
            )
        )
        # synthetic load: both pods at 100m usage vs 100m request = 100%
        # utilization against a 50% target -> desired = ceil(2*2) = 4
        for p in store.list("Pod")[0]:
            _metrics(store, p, 100)
        assert _wait(
            lambda: store.get("Deployment", "web").spec.replicas == 4
        )
        # new pods must be Running with metrics for the next pass
        assert _wait(lambda: len(store.list("Pod")[0]) == 4)
        for p in store.list("Pod")[0]:
            if p.status.phase != "Running":
                p.status.phase = "Running"
                store.update(p, force=True)
        # load drops to 10% -> desired shrinks to minReplicas after the
        # stabilization window
        def drop():
            for p in store.list("Pod")[0]:
                _metrics(store, p, 10)
        drop()
        time.sleep(0.3)  # past downscale stabilization
        drop()
        assert _wait(
            lambda: store.get("Deployment", "web").spec.replicas == 1,
            timeout=15,
        )
        hpa = store.get("HorizontalPodAutoscaler", "web-hpa")
        assert hpa.status.last_scale_time is not None
        assert hpa.status.current_cpu_utilization_percentage is not None
    finally:
        mgr.stop()


def test_quota_rejects_over_budget_creates():
    store = st.Store(admission=adm.default_chain())
    mgr = ControllerManager(store, controllers=[ResourceQuotaController]).start()
    try:
        store.create(
            api.ResourceQuota(
                meta=api.ObjectMeta(name="budget"),
                spec=api.ResourceQuotaSpec(
                    hard={"pods": 2, api.CPU: 500}
                ),
            )
        )
        store.create(make_pod("a").req(cpu_milli=200).obj())
        store.create(make_pod("b").req(cpu_milli=200).obj())
        # pod count exceeded
        with pytest.raises(adm.AdmissionError, match="exceeded quota"):
            store.create(make_pod("c").req(cpu_milli=50).obj())
        # delete one -> cpu budget now allows only 100m more
        store.delete("Pod", "b")
        with pytest.raises(adm.AdmissionError, match="exceeded quota"):
            store.create(make_pod("d").req(cpu_milli=400).obj())
        store.create(make_pod("e").req(cpu_milli=100).obj())
        # controller reconciles status.used.  Wait on BOTH dimensions:
        # pods==2 alone also matches the stale pre-delete {a, b} state
        # (2 pods, 400m), so asserting cpu right after that wait raced
        # the reconcile of b's delete.
        assert _wait(
            lambda: (
                store.get("ResourceQuota", "budget").status.used.get("pods")
                == 2
                and store.get(
                    "ResourceQuota", "budget"
                ).status.used.get(api.CPU) == 300
            )
        )
        # other namespaces are not constrained
        store.create(make_pod("f", namespace="other").req(cpu_milli=900).obj())
    finally:
        mgr.stop()


def test_default_service_account_created_and_pods_defaulted():
    store = st.Store(admission=adm.default_chain())
    mgr = ControllerManager(store, controllers=[ServiceAccountController]).start()
    try:
        store.create(api.Namespace(meta=api.ObjectMeta(name="team-a", namespace="")))
        assert _wait(
            lambda: any(
                sa.meta.namespace == "team-a"
                for sa in store.list("ServiceAccount")[0]
            )
        )
        pod = store.create(make_pod("p", namespace="team-a").obj())
        assert pod.spec.service_account == "default"
    finally:
        mgr.stop()


def test_ttl_after_finished_deletes_job():
    store = st.Store()
    mgr = ControllerManager(
        store, controllers=[TTLAfterFinishedController]
    ).start()
    try:
        job = api.Job(
            meta=api.ObjectMeta(name="j"),
            spec=api.JobSpec(completions=1, ttl_seconds_after_finished=0.3),
        )
        job.status.succeeded = 1
        job.status.completion_time = time.time()
        store.create(job)
        time.sleep(0.1)
        assert any(j.meta.name == "j" for j in store.list("Job")[0])
        assert _wait(
            lambda: not any(j.meta.name == "j" for j in store.list("Job")[0]),
            timeout=5,
        )
    finally:
        mgr.stop()


def test_podgc_reaps_orphans_and_bounded_terminated():
    """pkg/controller/podgc: pods on vanished nodes are reaped; the
    terminated-pod population is bounded oldest-first."""
    from kubernetes_tpu.controllers.podgc import PodGCController
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    store = st.Store()

    def gc_factory(*args, **kw):
        c = PodGCController(*args, **kw)
        c.TERMINATED_THRESHOLD = 3
        c.RESYNC_S = 0.1
        return c
    gc_factory.KIND = "Pod"
    gc_factory.NAME = "PodGC"

    store.create(make_node("n0").capacity(cpu_milli=4000).obj())
    store.create(make_node("gone").capacity(cpu_milli=4000).obj())
    orphan = make_pod("orphan").obj()
    orphan.spec.node_name = "gone"
    store.create(orphan)
    for i in range(5):
        p = make_pod(f"done-{i}").obj()
        p.spec.node_name = "n0"
        p.status.phase = "Succeeded"
        store.create(p)
        time.sleep(0.01)  # distinct creation timestamps
    mgr = ControllerManager(store, controllers=[gc_factory]).start()
    try:
        store.delete("Node", "gone", namespace="")
        def orphan_gone():
            try:
                store.get("Pod", "orphan")
                return False
            except KeyError:
                return True
        assert _wait(orphan_gone)
        # oldest terminated pods reaped down to the threshold
        assert _wait(lambda: sum(
            1 for p in store.list("Pod")[0]
            if p.status.phase == "Succeeded"
        ) == 3)
        remaining = {
            p.meta.name for p in store.list("Pod")[0]
            if p.status.phase == "Succeeded"
        }
        assert remaining == {"done-2", "done-3", "done-4"}
    finally:
        mgr.stop()


def test_configmap_secret_round_trip():
    import base64

    from kubernetes_tpu.api import kubeyaml, wire

    store = st.Store(admission=adm.default_chain())
    cm = kubeyaml.configmap_from_dict({
        "kind": "ConfigMap",
        "metadata": {"name": "settings"},
        "data": {"mode": "fast", "replicas": "3"},
    })
    store.create(cm)
    got = store.get("ConfigMap", "settings")
    assert got.data["mode"] == "fast"
    sec = kubeyaml.secret_from_dict({
        "kind": "Secret",
        "metadata": {"name": "creds"},
        "type": "Opaque",
        "stringData": {"password": "hunter2"},
    })
    store.create(sec)
    # stringData is write-only: folded into data (b64) at admission
    stored = store.get("Secret", "creds")
    assert stored.string_data == {}
    assert base64.b64decode(stored.data["password"]).decode() == "hunter2"
    doc = wire.to_wire(stored)
    assert wire.from_wire(doc).data["password"] == stored.data["password"]
