"""Dynamic resource allocation: ResourceClaim/DeviceClass scheduling.

Reference: pkg/scheduler/framework/plugins/dynamicresources/
dynamicresources.go:275 (the claim-driven Filter/Reserve/PreBind
protocol) — re-designed so capacity rides the resource-fit kernel and
allocation pins ride hostname selector terms
(kubernetes_tpu/scheduler/deviceclaims.py).
"""

import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _claim(name, device_class, count=1):
    return api.ResourceClaim(
        meta=api.ObjectMeta(name=name),
        spec=api.ResourceClaimSpec(
            device_class_name=device_class, count=count
        ),
    )


def _gpu_nodes(store, n, per_node=1):
    for i in range(n):
        store.create(
            make_node(f"n{i}")
            .capacity(
                cpu_milli=8000, mem=16 * GI, pods=32,
                **{api.device_resource("gpu"): per_node},
            )
            .obj()
        )


def _wait(cond, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def sched_store():
    store = st.Store()
    sched = Scheduler(store, batch_size=32)
    sched.start()
    yield sched, store
    sched.stop()


def test_claims_consume_device_capacity(sched_store):
    sched, store = sched_store
    _gpu_nodes(store, 2, per_node=1)
    store.create(api.DeviceClass(meta=api.ObjectMeta(name="gpu")))
    for i in range(3):
        store.create(_claim(f"c{i}", "gpu"))
        p = make_pod(f"p{i}").req(cpu_milli=100, mem=MI).obj()
        p.spec.resource_claims = [f"c{i}"]
        store.create(p)
    # two claims fit (one device per node); the third parks
    assert _wait(lambda: sum(
        1 for p in store.list("Pod")[0] if p.spec.node_name
    ) == 2)
    time.sleep(1.0)
    bound = {
        p.meta.name: p.spec.node_name
        for p in store.list("Pod")[0] if p.spec.node_name
    }
    assert len(set(bound.values())) == 2, bound  # one per node
    # allocations written through the API at PreBind
    allocated = [
        c for c in store.list("ResourceClaim")[0]
        if c.status.phase == "Allocated"
    ]
    assert len(allocated) == 2
    # the parked pod's claim frees up when a consumer dies
    victim = next(iter(bound))
    store.delete("Pod", victim)
    assert _wait(lambda: sum(
        1 for p in store.list("Pod")[0] if p.spec.node_name
    ) == 2, timeout=30)


def test_shared_claim_colocates_pods(sched_store):
    sched, store = sched_store
    _gpu_nodes(store, 3, per_node=2)
    store.create(api.DeviceClass(meta=api.ObjectMeta(name="gpu")))
    store.create(_claim("shared", "gpu", count=2))
    a = make_pod("a").req(cpu_milli=100, mem=MI).obj()
    a.spec.resource_claims = ["shared"]
    store.create(a)
    assert _wait(lambda: store.get("Pod", "a").spec.node_name)
    node = store.get("Pod", "a").spec.node_name
    # a second consumer of the SAME claim must land on the SAME node
    b = make_pod("b").req(cpu_milli=100, mem=MI).obj()
    b.spec.resource_claims = ["shared"]
    store.create(b)
    assert _wait(lambda: store.get("Pod", "b").spec.node_name)
    assert store.get("Pod", "b").spec.node_name == node


def test_missing_device_class_parks_until_created(sched_store):
    sched, store = sched_store
    _gpu_nodes(store, 1)
    store.create(_claim("c", "gpu"))
    p = make_pod("p").req(cpu_milli=100, mem=MI).obj()
    p.spec.resource_claims = ["c"]
    store.create(p)
    time.sleep(2.0)
    assert not store.get("Pod", "p").spec.node_name
    store.create(api.DeviceClass(meta=api.ObjectMeta(name="gpu")))
    assert _wait(lambda: store.get("Pod", "p").spec.node_name)
    claim = store.get("ResourceClaim", "c")
    assert claim.status.allocated_node == store.get("Pod", "p").spec.node_name


def test_allocated_devices_stay_accounted(sched_store):
    """Review repro 1: after a claim allocates, its devices must remain
    accounted on the node — a later claim must NOT overcommit."""
    sched, store = sched_store
    _gpu_nodes(store, 1, per_node=1)
    store.create(api.DeviceClass(meta=api.ObjectMeta(name="gpu")))
    store.create(_claim("c0", "gpu"))
    p0 = make_pod("p0").req(cpu_milli=100, mem=MI).obj()
    p0.spec.resource_claims = ["c0"]
    store.create(p0)
    assert _wait(lambda: store.get("Pod", "p0").spec.node_name)
    assert _wait(lambda: store.get(
        "ResourceClaim", "c0"
    ).status.phase == "Allocated")
    # second claim on the SAME (only) node: device is taken -> must park
    store.create(_claim("c1", "gpu"))
    p1 = make_pod("p1").req(cpu_milli=100, mem=MI).obj()
    p1.spec.resource_claims = ["c1"]
    store.create(p1)
    time.sleep(2.5)
    assert not store.get("Pod", "p1").spec.node_name, \
        "device overcommit: allocated claim's capacity was not accounted"


def test_batch_sharers_end_on_one_node(sched_store):
    """Review repro 2: two sharers of one claim solved in the SAME batch
    must both land on the allocation's node (the loser re-solves under
    the pin instead of binding elsewhere)."""
    sched, store = sched_store
    _gpu_nodes(store, 4, per_node=1)
    store.create(api.DeviceClass(meta=api.ObjectMeta(name="gpu")))
    store.create(_claim("shared", "gpu"))
    pods = []
    for name in ("a", "b"):
        p = make_pod(name).req(cpu_milli=100, mem=MI).obj()
        p.spec.resource_claims = ["shared"]
        pods.append(p)
        store.create(p)
    assert _wait(lambda: all(
        store.get("Pod", n).spec.node_name for n in ("a", "b")
    ), timeout=45)
    nodes = {store.get("Pod", n).spec.node_name for n in ("a", "b")}
    assert len(nodes) == 1, f"shared-claim consumers split: {nodes}"


def test_carrier_death_hands_off_to_sharer(sched_store):
    """dynamicresources.go:275 semantics: the allocation's devices stay
    charged while ANY consumer lives.  The carrier dies; a sharer
    inherits the accounting; a competing claim still can't take the
    device until the last sharer is gone."""
    sched, store = sched_store
    _gpu_nodes(store, 1, per_node=1)
    store.create(api.DeviceClass(meta=api.ObjectMeta(name="gpu")))
    store.create(_claim("shared", "gpu"))
    for name in ("carrier", "sharer"):
        p = make_pod(name).req(cpu_milli=100, mem=MI).obj()
        p.spec.resource_claims = ["shared"]
        store.create(p)
    assert _wait(lambda: sum(
        1 for p in store.list("Pod")[0] if p.spec.node_name
    ) == 2)
    claim = store.get("ResourceClaim", "shared")
    assert claim.status.allocated_node == "n0"
    carrier_key = f"default/{claim.status.carrier.split('/', 1)[1]}"
    dead, surviving = (
        ("carrier", "sharer")
        if claim.status.carrier.endswith("carrier")
        else ("sharer", "carrier")
    )
    # a competitor wants the only device
    store.create(_claim("rival", "gpu"))
    rp = make_pod("rival-pod").req(cpu_milli=100, mem=MI).obj()
    rp.spec.resource_claims = ["rival"]
    store.create(rp)
    time.sleep(0.5)

    # kill the CARRIER: accounting must hand off to the survivor
    store.delete("Pod", dead)
    assert _wait(
        lambda: store.get("ResourceClaim", "shared").status.carrier
        == f"default/{surviving}"
    )
    # the device is still held: the rival stays pending
    time.sleep(1.0)
    assert not store.get("Pod", "rival-pod").spec.node_name
    assert store.get("ResourceClaim", "shared").status.allocated_node == "n0"

    # last consumer gone -> deallocate -> rival finally lands
    store.delete("Pod", surviving)
    assert _wait(
        lambda: store.get("Pod", "rival-pod").spec.node_name == "n0",
        timeout=60,
    )


def test_carrier_handoff_across_store_shards():
    """Regression (sharded store, ISSUE 13 satellite): the carrier dies
    with surviving sharers while the claim-status write and the cache
    re-account land on a DIFFERENT (kind, namespace) shard than the
    pods.  The hand-off must still promote a survivor, keep the devices
    charged, and deallocate only when the last consumer is gone —
    per-shard locks/journals must not tear the carrier transfer."""
    store = st.Store(shards=4)
    # pick a namespace whose Pod shard differs from its ResourceClaim
    # shard (crc32 over (kind, namespace) — kinds split them)
    namespace = next(
        ns
        for ns in (f"ns-{i}" for i in range(64))
        if store.shard_index("Pod", ns) != store.shard_index(
            "ResourceClaim", ns
        )
    )
    sched = Scheduler(store, batch_size=32)
    sched.start()
    try:
        for i in range(1):
            store.create(
                make_node("n0")
                .capacity(
                    cpu_milli=8000, mem=16 * GI, pods=32,
                    **{api.device_resource("gpu"): 1},
                )
                .obj()
            )
        store.create(api.DeviceClass(meta=api.ObjectMeta(name="gpu")))
        claim = _claim("shared", "gpu")
        claim.meta.namespace = namespace
        store.create(claim)
        for name in ("carrier", "sharer"):
            p = make_pod(name, namespace=namespace).req(
                cpu_milli=100, mem=MI
            ).obj()
            p.spec.resource_claims = ["shared"]
            store.create(p)
        assert _wait(lambda: sum(
            1 for p in store.list("Pod", namespace=namespace)[0]
            if p.spec.node_name
        ) == 2)
        got = store.get("ResourceClaim", "shared", namespace)
        assert got.status.allocated_node == "n0"
        dead = got.status.carrier.split("/", 1)[1]
        surviving = "sharer" if dead == "carrier" else "carrier"
        # a rival wants the only device — must stay parked through the
        # cross-shard hand-off
        rival_claim = _claim("rival", "gpu")
        rival_claim.meta.namespace = namespace
        store.create(rival_claim)
        rp = make_pod("rival-pod", namespace=namespace).req(
            cpu_milli=100, mem=MI
        ).obj()
        rp.spec.resource_claims = ["rival"]
        store.create(rp)
        time.sleep(0.5)

        store.delete("Pod", dead, namespace)
        assert _wait(
            lambda: store.get(
                "ResourceClaim", "shared", namespace
            ).status.carrier == f"{namespace}/{surviving}"
        )
        # devices still charged on the claim's shard while the pod
        # lives on another shard: the rival must not land
        time.sleep(1.0)
        assert not store.get("Pod", "rival-pod", namespace).spec.node_name
        assert store.get(
            "ResourceClaim", "shared", namespace
        ).status.allocated_node == "n0"
        # last consumer gone -> cross-shard deallocate -> rival lands
        store.delete("Pod", surviving, namespace)
        assert _wait(
            lambda: store.get(
                "Pod", "rival-pod", namespace
            ).spec.node_name == "n0",
            timeout=60,
        )
    finally:
        sched.stop()
