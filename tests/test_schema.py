"""Snapshot encoder unit tests + the schema-drift contract gate."""

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import schema
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def test_basic_shapes_and_units():
    nodes = [
        make_node("n0").capacity(cpu_milli=4000, mem=8 * GI, pods=10).obj(),
        make_node("n1").capacity(cpu_milli=8000, mem=16 * GI, pods=20).obj(),
    ]
    pods = [make_pod("p0").req(cpu_milli=500, mem=512 * MI).obj()]
    b = schema.SnapshotBuilder()
    snap, meta = b.build(nodes, pods)

    n = snap.cluster.allocatable.shape[0]
    assert n >= 2 and (n & (n - 1)) == 0  # power-of-two padded
    assert meta.num_nodes == 2 and meta.num_pods == 1
    # device units: cpu milli, memory MiB
    assert snap.cluster.allocatable[0, schema.RESOURCE_CPU] == 4000
    assert snap.cluster.allocatable[0, schema.RESOURCE_MEMORY] == 8 * 1024
    assert snap.cluster.allocatable[1, schema.RESOURCE_PODS] == 20
    assert snap.pods.req[0, schema.RESOURCE_CPU] == 500
    assert snap.pods.req[0, schema.RESOURCE_MEMORY] == 512
    assert snap.pods.req[0, schema.RESOURCE_PODS] == 1
    assert snap.cluster.node_valid[:2].all() and not snap.cluster.node_valid[2:].any()


def test_nonzero_defaults():
    """Pods with no requests get 100m / 200Mi for scoring only
    (reference: pkg/scheduler/util/pod_resources.go:33-36)."""
    nodes = [make_node("n0").obj()]
    pods = [make_pod("p0").obj()]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    assert snap.pods.req[0, schema.RESOURCE_CPU] == 0
    assert snap.pods.nonzero_req[0, schema.RESOURCE_CPU] == 100
    assert snap.pods.nonzero_req[0, schema.RESOURCE_MEMORY] == 200


def test_bound_pods_accumulate_requested():
    nodes = [make_node("n0").obj()]
    bound = [
        make_pod("b0").req(cpu_milli=1000, mem=1 * GI).node_name("n0").obj(),
        make_pod("b1").req(cpu_milli=500).node_name("n0").obj(),
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, [], bound_pods=bound)
    assert snap.cluster.requested[0, schema.RESOURCE_CPU] == 1500
    assert snap.cluster.requested[0, schema.RESOURCE_MEMORY] == 1024
    assert snap.cluster.requested[0, schema.RESOURCE_PODS] == 2
    # b1 declares no memory -> nonzero default 200Mi applies
    assert snap.cluster.nonzero_requested[0, schema.RESOURCE_MEMORY] == 1024 + 200


def test_taint_and_toleration_encoding():
    nodes = [
        make_node("n0").taint("gpu", "true", api.NO_SCHEDULE).obj(),
        make_node("n1").unschedulable().obj(),
    ]
    pods = [
        make_pod("p0").toleration("gpu", api.OP_EQUAL, "true", api.NO_SCHEDULE).obj(),
    ]
    b = schema.SnapshotBuilder()
    snap, _ = b.build(nodes, pods)
    e = schema.EFFECT_INDEX[api.NO_SCHEDULE]
    assert snap.cluster.taint_bits[e, 0].any()
    # cordoned node got the synthetic unschedulable taint
    assert snap.cluster.taint_bits[e, 1].any()
    assert snap.pods.tol_bits[e, 0].any()


def test_selector_dedup():
    nodes = [make_node("n0").zone("a").obj()]
    pods = [
        make_pod(f"p{i}").node_selector_kv(api.LABEL_ZONE, "a").obj() for i in range(5)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    # five identical selectors -> one table row, all pods point at it
    assert (snap.pods.sel_idx[:5] == 0).all()
    assert snap.selectors.term_valid[0, 0]
    assert not snap.selectors.term_valid[1:].any()


def test_scalar_resource_discovery():
    nodes = [make_node("n0").capacity(**{"example.com/gpu": 4}).obj()]
    pods = [make_pod("p0").req(**{"example.com/gpu": 2}).obj()]
    b = schema.SnapshotBuilder()
    snap, meta = b.build(nodes, pods)
    assert "example.com/gpu" in meta.resource_names
    idx = meta.resource_names.index("example.com/gpu")
    assert snap.cluster.allocatable[0, idx] == 4
    assert snap.pods.req[0, idx] == 2


# -- schema-drift gate: every field carries a machine-readable contract ------

def _schema_contracts():
    from kubernetes_tpu.analysis import SourceFile
    from kubernetes_tpu.analysis import contracts as ct

    path = schema.__file__
    with open(path, "r", encoding="utf-8") as f:
        src = SourceFile(path, "kubernetes_tpu/ops/schema.py", f.read())
    return ct.collect(src)


def test_every_schema_field_parses_to_a_contract():
    """ISSUE acceptance: every NamedTuple array field in ops/schema.py
    carries a parseable `# <dtype>[<axes>]` contract — a new field
    without one fails here before it fails `make lint`."""
    contracts, issues = _schema_contracts()
    assert issues == [], [f"{i.cls}.{i.field}: {i.reason}" for i in issues]
    assert contracts, "no contracts parsed from schema.py at all"


def test_contracts_cover_every_snapshot_component_field():
    """Every field of every Snapshot component class is an array and
    must therefore have a contract (Snapshot itself composes the
    tables and carries none)."""
    from kubernetes_tpu.analysis import contracts as ct

    contracts, _ = _schema_contracts()
    byclass = ct.index_by_class(contracts)
    for cls in (
        schema.ClusterTensors, schema.PodBatch, schema.SelectorTable,
        schema.PreferredTable, schema.SpreadTable, schema.TermTable,
        schema.PrefPodTable, schema.ImageTable,
    ):
        got = set(byclass.get(cls.__name__, {}))
        want = set(cls._fields)
        assert got == want, (
            f"{cls.__name__}: contract drift — missing {want - got}, "
            f"orphaned {got - want}"
        )


def test_contract_dtypes_match_encoded_arrays():
    """The declared dtypes are what the encoder actually produces (the
    cheap static half of the --shapes encode validation)."""
    from kubernetes_tpu.analysis import contracts as ct

    contracts, _ = _schema_contracts()
    byclass = ct.index_by_class(contracts)
    nodes = [make_node("n0").zone("a").obj()]
    pods = [make_pod("p0").req(cpu_milli=100, mem=128 * MI).obj()]
    snap, _meta = schema.SnapshotBuilder().build(nodes, pods)
    for table in snap:
        cfields = byclass[type(table).__name__]
        for f in type(table)._fields:
            arr = np.asarray(getattr(table, f))
            assert str(arr.dtype) == cfields[f].dtype, (
                f"{type(table).__name__}.{f}: encoded {arr.dtype} != "
                f"contract {cfields[f].render()}"
            )
