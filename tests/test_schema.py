"""Snapshot encoder unit tests."""

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import schema
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def test_basic_shapes_and_units():
    nodes = [
        make_node("n0").capacity(cpu_milli=4000, mem=8 * GI, pods=10).obj(),
        make_node("n1").capacity(cpu_milli=8000, mem=16 * GI, pods=20).obj(),
    ]
    pods = [make_pod("p0").req(cpu_milli=500, mem=512 * MI).obj()]
    b = schema.SnapshotBuilder()
    snap, meta = b.build(nodes, pods)

    n = snap.cluster.allocatable.shape[0]
    assert n >= 2 and (n & (n - 1)) == 0  # power-of-two padded
    assert meta.num_nodes == 2 and meta.num_pods == 1
    # device units: cpu milli, memory MiB
    assert snap.cluster.allocatable[0, schema.RESOURCE_CPU] == 4000
    assert snap.cluster.allocatable[0, schema.RESOURCE_MEMORY] == 8 * 1024
    assert snap.cluster.allocatable[1, schema.RESOURCE_PODS] == 20
    assert snap.pods.req[0, schema.RESOURCE_CPU] == 500
    assert snap.pods.req[0, schema.RESOURCE_MEMORY] == 512
    assert snap.pods.req[0, schema.RESOURCE_PODS] == 1
    assert snap.cluster.node_valid[:2].all() and not snap.cluster.node_valid[2:].any()


def test_nonzero_defaults():
    """Pods with no requests get 100m / 200Mi for scoring only
    (reference: pkg/scheduler/util/pod_resources.go:33-36)."""
    nodes = [make_node("n0").obj()]
    pods = [make_pod("p0").obj()]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    assert snap.pods.req[0, schema.RESOURCE_CPU] == 0
    assert snap.pods.nonzero_req[0, schema.RESOURCE_CPU] == 100
    assert snap.pods.nonzero_req[0, schema.RESOURCE_MEMORY] == 200


def test_bound_pods_accumulate_requested():
    nodes = [make_node("n0").obj()]
    bound = [
        make_pod("b0").req(cpu_milli=1000, mem=1 * GI).node_name("n0").obj(),
        make_pod("b1").req(cpu_milli=500).node_name("n0").obj(),
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, [], bound_pods=bound)
    assert snap.cluster.requested[0, schema.RESOURCE_CPU] == 1500
    assert snap.cluster.requested[0, schema.RESOURCE_MEMORY] == 1024
    assert snap.cluster.requested[0, schema.RESOURCE_PODS] == 2
    # b1 declares no memory -> nonzero default 200Mi applies
    assert snap.cluster.nonzero_requested[0, schema.RESOURCE_MEMORY] == 1024 + 200


def test_taint_and_toleration_encoding():
    nodes = [
        make_node("n0").taint("gpu", "true", api.NO_SCHEDULE).obj(),
        make_node("n1").unschedulable().obj(),
    ]
    pods = [
        make_pod("p0").toleration("gpu", api.OP_EQUAL, "true", api.NO_SCHEDULE).obj(),
    ]
    b = schema.SnapshotBuilder()
    snap, _ = b.build(nodes, pods)
    e = schema.EFFECT_INDEX[api.NO_SCHEDULE]
    assert snap.cluster.taint_bits[e, 0].any()
    # cordoned node got the synthetic unschedulable taint
    assert snap.cluster.taint_bits[e, 1].any()
    assert snap.pods.tol_bits[e, 0].any()


def test_selector_dedup():
    nodes = [make_node("n0").zone("a").obj()]
    pods = [
        make_pod(f"p{i}").node_selector_kv(api.LABEL_ZONE, "a").obj() for i in range(5)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    # five identical selectors -> one table row, all pods point at it
    assert (snap.pods.sel_idx[:5] == 0).all()
    assert snap.selectors.term_valid[0, 0]
    assert not snap.selectors.term_valid[1:].any()


def test_scalar_resource_discovery():
    nodes = [make_node("n0").capacity(**{"example.com/gpu": 4}).obj()]
    pods = [make_pod("p0").req(**{"example.com/gpu": 2}).obj()]
    b = schema.SnapshotBuilder()
    snap, meta = b.build(nodes, pods)
    assert "example.com/gpu" in meta.resource_names
    idx = meta.resource_names.index("example.com/gpu")
    assert snap.cluster.allocatable[0, idx] == 4
    assert snap.pods.req[0, idx] == 2
