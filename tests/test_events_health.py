"""Events API + health/metrics endpoints (SURVEY §5.5: the scheduler
emits observable Events; healthz/readyz + Prometheus /metrics —
app/server.go:169-209, schedule_one.go:1003)."""

import time
import urllib.request

from kubernetes_tpu.api import store as st
from kubernetes_tpu.client.events import EventRecorder
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.http import HealthServer, render_prometheus
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


def test_event_recorder_aggregates():
    store = st.Store()
    rec = EventRecorder(store)
    pod = make_pod("p").obj()
    store.create(pod)
    for _ in range(3):
        rec.eventf(pod, "Warning", "FailedScheduling", "0 nodes available")
    events, _ = store.list("Event")
    assert len(events) == 1
    assert events[0].count == 3
    assert events[0].involved_object.name == "p"
    rec.eventf(pod, "Normal", "Scheduled", "assigned")
    events, _ = store.list("Event")
    assert len(events) == 2


def test_scheduler_emits_events():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=1000, mem=8 * GI).obj())
    sched = Scheduler(store)
    sched.informers.informer("Node").start()
    sched.informers.informer("Pod").start()
    assert sched.informers.wait_for_sync(10)
    try:
        store.create(make_pod("fits").req(cpu_milli=100).obj())
        store.create(make_pod("big").req(cpu_milli=64000).obj())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            if store.get("Pod", "fits").spec.node_name:
                break
        # the recorder is async (broadcaster thread): poll for the drain
        by_reason = {}
        while time.monotonic() < deadline:
            events, _ = store.list("Event")
            by_reason = {e.reason: e for e in events}
            if "Scheduled" in by_reason and "FailedScheduling" in by_reason:
                break
            time.sleep(0.02)
        assert "Scheduled" in by_reason
        assert "FailedScheduling" in by_reason
        assert "insufficient resources" in by_reason["FailedScheduling"].message
    finally:
        sched.stop()


def test_health_and_metrics_endpoints():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=4000, mem=8 * GI).obj())
    sched = Scheduler(store)
    sched.informers.informer("Node").start()
    sched.informers.informer("Pod").start()
    assert sched.informers.wait_for_sync(10)
    srv = HealthServer(sched).start()
    try:
        url = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(url + "/healthz") as r:
            assert r.read() == b"ok"
        with urllib.request.urlopen(url + "/readyz") as r:
            assert r.status == 200
        store.create(make_pod("p").req(cpu_milli=100).obj())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            if store.get("Pod", "p").spec.node_name:
                break
        with urllib.request.urlopen(url + "/metrics") as r:
            body = r.read().decode()
        assert "scheduler_schedule_attempts_total" in body
        assert "scheduler_scheduling_attempt_duration_seconds_count" in body
    finally:
        srv.stop()
        sched.stop()


def test_prometheus_rendering_shape():
    from kubernetes_tpu.scheduler.metrics import Registry

    reg = Registry()
    reg.schedule_attempts.inc("scheduled")
    reg.scheduling_attempt_duration.observe(0.005)
    text = render_prometheus(reg)
    assert "# TYPE scheduler_schedule_attempts_total counter" in text
    assert "# TYPE scheduler_scheduling_attempt_duration_seconds histogram" in text
    assert "_bucket{le=" in text


def test_debug_endpoints():
    """/debug/threads (goroutine-dump analogue) + /debug/profile
    (sampling profile across ALL threads) on the health server."""
    import urllib.request

    from kubernetes_tpu.scheduler.http import HealthServer

    store = st.Store()
    sched = Scheduler(store)
    hs = HealthServer(sched).start()
    try:
        base = f"http://127.0.0.1:{hs.port}"
        body = urllib.request.urlopen(f"{base}/debug/threads", timeout=5).read()
        assert b"Thread" in body or b"File" in body
        body = urllib.request.urlopen(
            f"{base}/debug/profile?seconds=0.2", timeout=10
        ).read().decode()
        assert body.startswith("samples:")
    finally:
        hs.stop()
        sched.stop()
