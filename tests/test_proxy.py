"""Service proxy: the VIP -> backend table (kube-proxy's artifact).

Reference: pkg/proxy/iptables/proxier.go syncProxyRules — compiled
rules track Service/EndpointSlice changes; lookups round-robin ready
backends, honor ClientIP affinity, and reject when nothing backs the
VIP.
"""

import time

from kubernetes_tpu.api import admission as adm
from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.endpointslice import EndpointSliceController
from kubernetes_tpu.proxy import ServiceProxy


def _wait(cond, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


def _pod(name, ip, ready=True, node="n0"):
    p = api.Pod(
        meta=api.ObjectMeta(name=name, labels={"app": "web"}),
        spec=api.PodSpec(node_name=node),
    )
    p.status.phase = "Running"
    p.status.pod_ip = ip
    if not ready:
        p.status.conditions = [{"type": "Ready", "status": "False"}]
    return p


def test_vip_resolution_round_robin_and_updates():
    store = st.Store(admission=adm.default_chain())
    mgr = ControllerManager(store, controllers=[EndpointSliceController]).start()
    proxy = ServiceProxy(store).start()
    try:
        store.create(_pod("a", "10.1.0.1"))
        store.create(_pod("b", "10.1.0.2"))
        svc = store.create(api.Service(
            meta=api.ObjectMeta(name="web"),
            spec=api.ServiceSpec(
                selector={"app": "web"},
                ports=[api.ServicePort(name="http", port=80, target_port=8080)],
            ),
        ))
        vip = svc.spec.cluster_ip
        assert _wait(lambda: proxy.resolve(vip, 80) is not None)
        assert _wait(
            lambda: len(proxy.rules().get(f"{vip}:80", [])) == 2
        )
        # round robin covers both backends on the target port
        seen = {proxy.resolve(vip, 80) for _ in range(4)}
        assert seen == {("10.1.0.1", 8080), ("10.1.0.2", 8080)}
        # unknown VIP / port rejects
        assert proxy.resolve("10.0.0.99", 80) is None
        assert proxy.resolve(vip, 81) is None
        # backend turns unready -> drops from the table
        p = store.get("Pod", "a")
        p.status.conditions = [{"type": "Ready", "status": "False"}]
        store.update(p, force=True)
        assert _wait(
            lambda: len(proxy.rules().get(f"{vip}:80", [])) == 1
        )
        assert proxy.resolve(vip, 80) == ("10.1.0.2", 8080)
    finally:
        proxy.stop()
        mgr.stop()


def test_client_ip_session_affinity():
    store = st.Store(admission=adm.default_chain())
    mgr = ControllerManager(store, controllers=[EndpointSliceController]).start()
    proxy = ServiceProxy(store).start()
    try:
        store.create(_pod("a", "10.1.0.1"))
        store.create(_pod("b", "10.1.0.2"))
        svc = store.create(api.Service(
            meta=api.ObjectMeta(name="web"),
            spec=api.ServiceSpec(
                selector={"app": "web"},
                ports=[api.ServicePort(name="http", port=80, target_port=8080)],
                session_affinity="ClientIP",
            ),
        ))
        vip = svc.spec.cluster_ip
        assert _wait(
            lambda: len(proxy.rules().get(f"{vip}:80", [])) == 2
        )
        first = proxy.resolve(vip, 80, client_ip="192.168.0.7")
        for _ in range(5):
            assert proxy.resolve(vip, 80, client_ip="192.168.0.7") == first
        # a different client may land elsewhere but also sticks
        other = proxy.resolve(vip, 80, client_ip="192.168.0.8")
        assert proxy.resolve(vip, 80, client_ip="192.168.0.8") == other
    finally:
        proxy.stop()
        mgr.stop()
