"""RBAC objects + namespace-scoped authz, and APF-lite flow control.

VERDICT r4 #6 acceptance: a namespaced Role grants only in-namespace
access; #4 acceptance: a flood from one flow cannot starve another
level's writes, /metrics exports per-level state.
Reference: plugin/pkg/auth/authorizer/rbac/rbac.go:75,
apiserver/pkg/util/flowcontrol/apf_controller.go.
"""

import threading
import time

import pytest

from kubernetes_tpu.api import auth, flowcontrol
from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.server import APIServer
from kubernetes_tpu.client.rest import RestClient
from kubernetes_tpu.testing.wrappers import make_pod


def _role(name, ns, verbs, resources):
    return api.Role(
        meta=api.ObjectMeta(name=name, namespace=ns),
        rules=[api.PolicyRule(verbs=list(verbs), resources=list(resources))],
    )


def _binding(name, ns, user, role, role_kind="Role"):
    return api.RoleBinding(
        meta=api.ObjectMeta(name=name, namespace=ns),
        subjects=[api.RbacSubject(kind="User", name=user)],
        role_ref=api.RoleRef(kind=role_kind, name=role),
    )


def test_rbac_namespace_scoping():
    store = st.Store()
    store.create(_role("pod-reader", "team-a", ["get", "list"], ["Pod"]))
    store.create(_binding("alice-reads", "team-a", "alice", "pod-reader"))
    store.create(
        api.ClusterRole(
            meta=api.ObjectMeta(name="admin", namespace=""),
            rules=[api.PolicyRule()],
        )
    )
    store.create(
        api.ClusterRoleBinding(
            meta=api.ObjectMeta(name="root-admin", namespace=""),
            subjects=[api.RbacSubject(kind="Group", name="system:masters")],
            role_ref=api.RoleRef(kind="ClusterRole", name="admin"),
        )
    )
    rbac = auth.RBACAuthorizer(store, ttl=0)
    alice = auth.Subject("alice")
    root = auth.Subject("root", ("system:masters",))

    assert rbac.allowed(alice, "list", "Pod", "team-a")
    assert rbac.allowed(alice, "get", "Pod", "team-a")
    assert not rbac.allowed(alice, "create", "Pod", "team-a")   # verb
    assert not rbac.allowed(alice, "list", "Pod", "team-b")     # namespace
    assert not rbac.allowed(alice, "list", "Node", "team-a")    # kind
    assert not rbac.allowed(alice, "list", "Pod", "")           # cluster-wide
    assert rbac.allowed(root, "delete", "Node", "")             # cluster admin
    assert rbac.allowed(root, "create", "Pod", "team-b")


def test_rolebinding_to_clusterrole_is_namespace_scoped():
    store = st.Store()
    store.create(
        api.ClusterRole(
            meta=api.ObjectMeta(name="pod-admin", namespace=""),
            rules=[api.PolicyRule(verbs=["*"], resources=["Pod"])],
        )
    )
    store.create(
        _binding("bob-pods", "team-b", "bob", "pod-admin", "ClusterRole")
    )
    rbac = auth.RBACAuthorizer(store, ttl=0)
    bob = auth.Subject("bob")
    assert rbac.allowed(bob, "create", "Pod", "team-b")
    assert not rbac.allowed(bob, "create", "Pod", "team-a")
    assert not rbac.allowed(bob, "create", "Pod", "")


def test_rbac_through_api_server_restricted_cli_user():
    store = st.Store()
    store.create(_role("pod-reader", "team-a", ["get", "list"], ["Pod"]))
    store.create(_binding("alice-reads", "team-a", "alice", "pod-reader"))
    authn = auth.TokenAuthenticator({
        "alice-token": auth.Subject("alice"),
        "root-token": auth.Subject("root", ("system:masters",)),
    })
    store.create(
        api.ClusterRole(meta=api.ObjectMeta(name="admin", namespace=""),
                        rules=[api.PolicyRule()])
    )
    store.create(
        api.ClusterRoleBinding(
            meta=api.ObjectMeta(name="root-admin", namespace=""),
            subjects=[api.RbacSubject(kind="Group", name="system:masters")],
            role_ref=api.RoleRef(kind="ClusterRole", name="admin"),
        )
    )
    srv = APIServer(
        store, authn=authn, authz=auth.RBACAuthorizer(store, ttl=0)
    ).start()
    try:
        root = RestClient(srv.url, token="root-token")
        alice = RestClient(srv.url, token="alice-token")
        p = make_pod("p", namespace="team-a").obj()
        root.create(p)
        root.create(make_pod("q", namespace="team-b").obj())

        assert alice.get("Pod", "p", namespace="team-a").meta.name == "p"
        assert len(alice.list("Pod", namespace="team-a")[0]) == 1
        with pytest.raises(RuntimeError):
            alice.get("Pod", "q", namespace="team-b")
        with pytest.raises(RuntimeError):
            alice.create(make_pod("r", namespace="team-a").obj())
        with pytest.raises(RuntimeError):
            alice.list("Pod")  # cluster-wide list needs a cluster grant
    finally:
        srv.stop()


# -- APF ---------------------------------------------------------------------


def _apf_server(store, *, catch_all=(1, 0)):
    authn = auth.TokenAuthenticator({
        "sched-token": auth.Subject(
            "system:kube-scheduler", ("system:schedulers",)
        ),
        # no groups: matches no schema until the catch-all
        "viewer-token": auth.Subject("viewer"),
    })
    apf = flowcontrol.APFGate(
        levels={
            "system": (8, 32),
            "workload-high": (8, 32),
            "catch-all": catch_all,
        },
        queue_wait_s=0.2,
    )
    return APIServer(store, authn=authn, apf=apf).start(), apf


def test_apf_watch_releases_seat_after_initialization():
    """The APF seat gates watch INITIALIZATION only (apf_filter.go
    forgetWatch): a long-lived watch on the catch-all level's single
    seat must NOT pin it — later catch-all requests are admitted, and
    the scheduler's own flow is untouched."""
    store = st.Store()
    srv, apf = _apf_server(store)
    try:
        sched = RestClient(srv.url, token="sched-token")
        viewer = RestClient(srv.url, token="viewer-token")
        import urllib.request

        req = urllib.request.Request(
            f"{srv.url}/api/v1/watch/Pod",
            headers={"Authorization": "Bearer viewer-token"},
        )
        stream = urllib.request.urlopen(req, timeout=5)
        time.sleep(0.1)
        # catch-all has 1 seat and 0 queue slots: were the stream still
        # holding its seat, this list would shed with 429 — it must not
        viewer.list("Pod")
        sched.create(make_pod("p").obj())
        assert sched.get("Pod", "p").meta.name == "p"
        assert apf.levels["catch-all"].rejected_total == 0
        stream.close()
    finally:
        srv.stop()


def test_apf_flood_does_not_starve_system_writes():
    store = st.Store()
    srv, apf = _apf_server(store, catch_all=(2, 4))
    try:
        sched = RestClient(srv.url, token="sched-token")
        stop = threading.Event()

        def flood():
            viewer = RestClient(srv.url, token="viewer-token")
            while not stop.is_set():
                try:
                    viewer.list("Pod")
                except Exception:
                    pass

        threads = [threading.Thread(target=flood, daemon=True)
                   for _ in range(12)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        # scheduler writes complete promptly under the flood
        t0 = time.monotonic()
        for i in range(20):
            sched.create(make_pod(f"p-{i}").obj())
        dt = time.monotonic() - t0
        stop.set()
        for t in threads:
            t.join(timeout=2)
        assert dt < 5.0, f"system writes starved: {dt:.1f}s for 20 creates"
        assert len(store.list("Pod")[0]) == 20
    finally:
        srv.stop()


def test_apf_levels_are_config_knobs():
    """Per-level seat counts are deployment configuration now, not
    compile-time constants: a YAML-shaped document tunes one level's
    seats/queue, merges onto the defaults, and the knob demonstrably
    takes effect (a 1-seat 0-queue catch-all sheds the second
    concurrent request with 429)."""
    gate = flowcontrol.APFGate.from_config(
        {
            "apfLevels": {
                "catch-all": {"seats": 1, "queueLimit": 0},
                "workload-high": {"seats": 64},
            },
            "queueWaitSeconds": 0.05,
        }
    )
    # tuned levels took effect; untouched defaults survived the merge
    assert gate.levels["catch-all"].seats == 1
    assert gate.levels["catch-all"].queue_limit == 0
    assert gate.levels["workload-high"].seats == 64
    assert gate.levels["workload-high"].queue_limit == (
        flowcontrol.DEFAULT_LEVELS["workload-high"][1]
    )
    assert gate.levels["system"].seats == (
        flowcontrol.DEFAULT_LEVELS["system"][0]
    )
    nobody = auth.ANONYMOUS
    first = gate.acquire(nobody, "list")
    assert first is not None
    # one seat, zero queue: the concurrent second request sheds
    assert gate.acquire(nobody, "list") is None
    assert gate.levels["catch-all"].rejected_total == 1
    first.release()
    assert gate.acquire(nobody, "list") is not None


def test_apf_config_served_end_to_end():
    """APIServer accepts the APF config document directly and the tuned
    seat counts govern the serving path."""
    store = st.Store()
    srv = APIServer(
        store,
        apf={"apfLevels": {"catch-all": {"seats": 2, "queueLimit": 1}}},
    ).start()
    try:
        client = RestClient(srv.url)
        client.create(make_pod("p").obj())
        assert client.get("Pod", "p").meta.name == "p"
        gate = srv.httpd.RequestHandlerClass.apf
        assert gate.levels["catch-all"].seats == 2
        assert gate.levels["catch-all"].queue_limit == 1
    finally:
        srv.stop()


def test_apf_config_validation_rejects_bad_knobs():
    with pytest.raises(ValueError, match="seats must be >= 1"):
        flowcontrol.levels_from_config({"catch-all": {"seats": 0}})
    with pytest.raises(ValueError, match="queueLimit"):
        flowcontrol.levels_from_config(
            {"system": {"seats": 4, "queueLimit": -1}}
        )
    with pytest.raises(ValueError, match="unknown keys"):
        flowcontrol.levels_from_config({"system": {"seat": 4}})
    with pytest.raises(ValueError, match="unknown APF configuration"):
        flowcontrol.APFGate.from_config({"levels": {}})


def test_apf_fifo_within_level_no_barging():
    """Queue-drain fairness, half 1: FIFO within a level.  Two queued
    waiters on a 1-seat level are served in arrival order, and a fresh
    arrival never barges past them when the seat frees."""
    gate = flowcontrol.APFGate(
        levels={
            "system": (1, 8), "workload-high": (1, 8), "catch-all": (1, 8),
        },
        queue_wait_s=5.0,
    )
    nobody = auth.ANONYMOUS
    hold = gate.acquire(nobody, "list")
    assert hold is not None
    # exhaust every borrowable donor so catch-all arrivals must queue
    # (catch-all is the lowest level, so there is nothing below it —
    # but keep the gate saturated for symmetry with the cross-level pin)
    order = []

    def waiter(tag):
        seat = gate.acquire(nobody, "list")
        assert seat is not None, f"waiter {tag} timed out"
        order.append(tag)
        time.sleep(0.02)
        seat.release()

    t_a = threading.Thread(target=waiter, args=("A",), daemon=True)
    t_a.start()
    deadline = time.monotonic() + 2
    while gate.levels["catch-all"].queued < 1:
        assert time.monotonic() < deadline, "waiter A never queued"
        time.sleep(0.005)
    t_b = threading.Thread(target=waiter, args=("B",), daemon=True)
    t_b.start()
    while gate.levels["catch-all"].queued < 2:
        assert time.monotonic() < deadline, "waiter B never queued"
        time.sleep(0.005)
    # a fresh arrival with waiters queued must not barge: it joins the
    # queue behind B (granted == False until the scan reaches it)
    t_c = threading.Thread(target=waiter, args=("C",), daemon=True)
    t_c.start()
    while gate.levels["catch-all"].queued < 3:
        assert time.monotonic() < deadline, "waiter C never queued"
        time.sleep(0.005)
    hold.release()
    for t in (t_a, t_b, t_c):
        t.join(timeout=5)
    assert order == ["A", "B", "C"]


def test_apf_priority_across_levels_and_borrow_downward():
    """Queue-drain fairness, half 2: priority across levels.  When a
    seat frees, the dispatch scan serves the HIGHEST-priority waiting
    level first (system before workload-high), and capacity is borrowed
    DOWNWARD only — the system waiter executes on the idle catch-all
    seat while the workload-high waiter keeps waiting."""
    gate = flowcontrol.APFGate(
        levels={
            "system": (1, 8), "workload-high": (1, 8), "catch-all": (1, 8),
        },
        queue_wait_s=5.0,
    )
    sys_subj = auth.Subject("system:kube-scheduler", ("system:schedulers",))
    wh_subj = auth.Subject("dev", ("system:authenticated",))
    nobody = auth.ANONYMOUS
    s_hold = gate.acquire(sys_subj, "update")
    w_hold = gate.acquire(wh_subj, "list")
    c_hold = gate.acquire(nobody, "list")
    assert (s_hold, w_hold, c_hold) != (None, None, None)
    grants = []

    def queued_acquire(subject, tag):
        seat = gate.acquire(subject, "list")
        assert seat is not None, f"{tag} timed out"
        grants.append((tag, seat.donor.name))

    # workload-high waiter queues FIRST, system waiter second: the scan
    # must still serve system first when capacity appears
    t_w = threading.Thread(
        target=queued_acquire, args=(wh_subj, "wh"), daemon=True
    )
    t_w.start()
    deadline = time.monotonic() + 2
    while gate.levels["workload-high"].queued < 1:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    t_s = threading.Thread(
        target=queued_acquire, args=(sys_subj, "system"), daemon=True
    )
    t_s.start()
    while gate.levels["system"].queued < 1:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    # free the CATCH-ALL seat (no catch-all waiters): the system waiter
    # takes it via borrow-downward; workload-high stays queued
    c_hold.release()
    t_s.join(timeout=5)
    assert grants == [("system", "catch-all")]
    assert gate.levels["workload-high"].queued == 1
    # freeing the SYSTEM seat does not help the workload-high waiter —
    # borrowing never goes upward, so it keeps waiting
    s_hold.release()
    time.sleep(0.05)
    assert gate.levels["workload-high"].queued == 1
    # its own seat freeing is what serves it
    w_hold.release()
    t_w.join(timeout=5)
    assert grants == [("system", "catch-all"), ("wh", "workload-high")]


def test_apf_catch_all_never_borrows_system_seats():
    """Borrow-downward only: with every system seat idle, a saturated
    catch-all level sheds rather than touching higher-priority
    capacity (the flood-isolation property)."""
    gate = flowcontrol.APFGate(
        levels={
            "system": (4, 8), "workload-high": (1, 0), "catch-all": (1, 0),
        },
        queue_wait_s=0.05,
    )
    nobody = auth.ANONYMOUS
    a = gate.acquire(nobody, "list")
    assert a is not None and a.donor.name == "catch-all"
    assert gate.acquire(nobody, "list") is None
    assert gate.levels["system"].seats_used == 0
    assert gate.levels["workload-high"].seats_used == 0
    assert gate.levels["catch-all"].rejected_total == 1
    a.release()


def test_adaptive_apf_sheds_and_recovers_with_hysteresis():
    """The adaptive ladder: overload level 2 shrinks every non-system
    level's effective seats/queue immediately (system keeps full
    headroom), Retry-After widens with pressure, and recovery needs
    `recover_after` consecutive calm observations per single step
    down — the hysteresis that keeps a flapping signal from thrashing
    the seat limits."""
    gate = flowcontrol.APFGate(
        levels={
            "system": (8, 16), "workload-high": (8, 16), "catch-all": (4, 8),
        },
        queue_wait_s=0.05,
    )
    adaptive = flowcontrol.AdaptiveAPF(gate, recover_after=3)
    base = gate.seats_current()
    assert base == 20
    assert gate.retry_after_s() == 1.0

    # rising pressure applies immediately
    assert adaptive.note(overload_level=2) == 2
    assert gate.levels["system"].seats_effective == 8        # untouched
    assert gate.levels["workload-high"].seats_effective == 2  # 8 >> 2
    assert gate.levels["catch-all"].seats_effective == 1      # floor 1
    assert gate.levels["catch-all"].queue_limit_effective == 2
    assert gate.seats_current() == 11
    assert gate.retry_after_s() == 4.0

    # the shrunken level demonstrably sheds: 1 effective seat + queue 2
    nobody = auth.ANONYMOUS
    held = [gate.acquire(nobody, "list")]
    assert held[0] is not None
    # no free seat, and the 0.05s queue wait expires -> shed
    assert gate.acquire(nobody, "list") is None
    assert gate.levels["catch-all"].rejected_total >= 1

    # recovery: three calm observations per downward step, one step at
    # a time; a blip in between resets the streak
    assert adaptive.note(0) == 2
    assert adaptive.note(0) == 2
    assert adaptive.note(overload_level=2) == 2  # blip: streak resets
    assert adaptive.note(0) == 2
    assert adaptive.note(0) == 2
    assert adaptive.note(0) == 1                 # step down ONE level
    assert gate.levels["workload-high"].seats_effective == 4
    assert gate.retry_after_s() == 2.0
    assert adaptive.note(0) == 1
    assert adaptive.note(0) == 1
    assert adaptive.note(0) == 0                 # fully recovered
    assert gate.seats_current() == base
    assert gate.levels["catch-all"].seats_effective == 4
    assert gate.levels["catch-all"].queue_limit_effective == 8
    assert gate.retry_after_s() == 1.0
    held[0].release()


def test_adaptive_apf_depth_ladder():
    """The store's watch/dispatch backlog depth drives pressure too:
    >= threshold is one step, >= 4x threshold is two, and the larger of
    (overload level, depth step) wins."""
    gate = flowcontrol.APFGate(queue_wait_s=0.05)
    adaptive = flowcontrol.AdaptiveAPF(
        gate, depth_threshold=256, recover_after=2
    )
    assert adaptive.note(watch_depth=255) == 0
    assert adaptive.note(watch_depth=256) == 1
    assert adaptive.note(dispatch_depth=1024) == 2
    assert adaptive.note(overload_level=1, watch_depth=0) == 2  # falling: 1st
    assert adaptive.note(overload_level=1) == 1  # 2nd calm step: down one
    assert gate.levels["catch-all"].seats_effective == (
        flowcontrol.DEFAULT_LEVELS["catch-all"][0] >> 1
    )


def test_apf_shed_carries_adaptive_retry_after():
    """End to end through the HTTP path: under pressure 2 a shed
    catch-all request answers 429 with the WIDENED Retry-After (2^p
    seconds), and recovery restores the 1s floor."""
    import urllib.error
    import urllib.request

    store = st.Store()
    srv, apf = _apf_server(store, catch_all=(1, 4))
    try:
        apf.set_pressure(2)
        seat = apf.acquire(auth.ANONYMOUS, "list")
        assert seat is not None
        req = urllib.request.Request(
            f"{srv.url}/api/v1/Pod",
            headers={"Authorization": "Bearer viewer-token"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 429
        assert ei.value.headers["Retry-After"] == "4"
        seat.release()
        apf.set_pressure(0)
        # recovered: the same request is admitted again
        body = urllib.request.urlopen(req, timeout=5).read()
        assert b"items" in body
    finally:
        srv.stop()


def test_apf_metrics_endpoint():
    store = st.Store()
    srv, apf = _apf_server(store)
    try:
        import urllib.request

        RestClient(srv.url, token="sched-token").list("Pod")
        # /metrics rides the full authn chain (only healthz is exempt)
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{srv.url}/metrics", timeout=5)
        req = urllib.request.Request(
            f"{srv.url}/metrics",
            headers={"Authorization": "Bearer sched-token"},
        )
        body = urllib.request.urlopen(req, timeout=5).read()
        text = body.decode()
        assert "apiserver_flowcontrol_current_inqueue_requests" in text
        assert 'priority_level="system"' in text
        assert "apiserver_flowcontrol_dispatched_requests_total" in text
    finally:
        srv.stop()
