"""Concurrency stress: the lock-based store/informer/cache/queue stack
under multi-writer interleavings, checked by the CacheComparer's
dual-bookkeeping invariant (VERDICT weak #8; the reference runs all of
this under -race, hack/make-rules/test.sh:75)."""

import random
import threading
import time

from kubernetes_tpu.api import store as st
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.debugger import CacheComparer
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def test_multi_writer_store_consistency():
    """Many threads doing create/update/delete with optimistic
    concurrency: final state is exact and the event stream is gapless."""
    store = st.Store()
    n_threads, per_thread = 8, 50
    errors = []

    def writer(t):
        rng = random.Random(t)
        for i in range(per_thread):
            name = f"p{t}-{i}"
            pod = make_pod(name).req(cpu_milli=100).obj()
            store.create(pod)
            for _ in range(rng.randint(0, 3)):
                # optimistic update with retry-on-conflict
                while True:
                    fresh = store.get("Pod", name)
                    fresh.meta.labels["v"] = str(rng.random())
                    try:
                        store.update(fresh)
                        break
                    except st.Conflict:
                        continue
            if rng.random() < 0.3:
                store.delete("Pod", name)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(n_threads)]
    w = store.watch("Pod")
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    pods, rv = store.list("Pod")
    # Coalescing contract: delivery stays strictly rv-monotonic, and
    # replaying the (possibly compacted) stream reproduces the store's
    # exact final per-key state — latest-wins compaction may drop
    # intermediate revisions, never the final one.  The un-drained
    # watcher must survive the whole run without being terminated.
    last = 0
    state = {}
    while True:
        ev = w.get(timeout=0.5)
        if ev is None:
            break
        assert ev.rv > last, f"rv regression {ev.rv} after {last}"
        last = ev.rv
        key = f"{ev.obj.meta.namespace}/{ev.obj.meta.name}"
        if ev.type == st.DELETED:
            state.pop(key, None)
        else:
            state[key] = ev.obj.meta.resource_version
    w.stop()
    assert not w.expired and store.watchers_terminated == 0
    final = {
        f"{p.meta.namespace}/{p.meta.name}": p.meta.resource_version
        for p in pods
    }
    assert state == final
    assert all(p.meta.resource_version <= rv for p in pods)


def test_cache_comparer_consistent_under_churn():
    """Scheduler loop + informer threads + an external chaos writer all
    mutating concurrently: the dual bookkeeping must converge to exact
    agreement (the CacheComparer invariant, comparer.go:135)."""
    store = st.Store()
    for i in range(16):
        store.create(
            make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI, pods=30).obj()
        )
    sched = Scheduler(store, batch_size=64)
    sched.informers.informer("Node").start()
    sched.informers.informer("Pod").start()
    assert sched.informers.wait_for_sync(10)
    comparer = CacheComparer(store, sched.cache)
    stop = threading.Event()

    def chaos():
        rng = random.Random(7)
        i = 0
        while not stop.is_set():
            i += 1
            op = rng.random()
            if op < 0.5:
                try:
                    store.create(
                        make_pod(f"c{i}").req(cpu_milli=rng.choice([100, 500])).obj()
                    )
                except st.AlreadyExists:
                    pass
            elif op < 0.75:
                pods, _ = store.list("Pod")
                bound = [p for p in pods if p.spec.node_name]
                if bound:
                    try:
                        store.delete("Pod", rng.choice(bound).meta.name)
                    except st.NotFound:
                        pass
            else:
                name = f"n{rng.randrange(16)}"
                try:
                    node = store.get("Node", name, namespace="")
                    node.meta.annotations["hb"] = str(i)
                    store.update(node, force=True)
                except st.NotFound:
                    pass
            time.sleep(0.002)

    chaos_threads = [threading.Thread(target=chaos, daemon=True) for _ in range(3)]
    for t in chaos_threads:
        t.start()
    try:
        deadline = time.monotonic() + 6
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.1)
    finally:
        stop.set()
        for t in chaos_threads:
            t.join(timeout=5)
    # drain: let informers deliver everything, run a last cycle
    deadline = time.monotonic() + 10
    problems = ["unchecked"]
    while time.monotonic() < deadline and problems:
        sched.schedule_batch(timeout=0.1)
        time.sleep(0.2)
        problems = comparer.compare()
    assert problems == [], problems
    dump = comparer.dump()
    assert dump["nodes"] == 16
    sched.stop()


def test_queue_concurrent_producers_and_consumer():
    """Gang staging + event moves + pop_batch from concurrent threads:
    nothing deadlocks, nothing is lost, nothing double-pops."""
    from kubernetes_tpu.scheduler.queue import SchedulingQueue

    q = SchedulingQueue(backoff_base=0.01, backoff_max=0.05)
    total = 300
    popped = []
    popped_lock = threading.Lock()
    stop = threading.Event()

    def producer(t):
        for i in range(total // 3):
            q.add(make_pod(f"p{t}-{i}").obj())
            if i % 7 == 0:
                q.move_for_event("NodeAdd")

    def consumer():
        while not stop.is_set():
            batch = q.pop_batch(16, timeout=0.1)
            with popped_lock:
                for info in batch:
                    popped.append(info.pod.meta.name)
                    q.done(info.pod)

    producers = [threading.Thread(target=producer, args=(t,)) for t in range(3)]
    consumers = [threading.Thread(target=consumer) for _ in range(2)]
    for t in producers + consumers:
        t.start()
    for t in producers:
        t.join()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(popped) < total:
        time.sleep(0.05)
    stop.set()
    for t in consumers:
        t.join(timeout=5)
    assert len(popped) == total, f"{len(popped)}/{total} popped"
    assert len(set(popped)) == total, "double-pop detected"
