"""Op tracing (utiltrace LogIfLong analogue)."""

import logging

from kubernetes_tpu.utils.trace import Trace


def test_trace_logs_only_when_slow(caplog):
    t = [0.0]

    def clock():
        return t[0]

    with caplog.at_level(logging.WARNING, logger="kubernetes_tpu.trace"):
        with Trace("fast", threshold=1.0, clock=clock) as tr:
            t[0] += 0.1
            tr.step("a")
        assert caplog.records == []
        with Trace("slow", threshold=1.0, clock=clock, pods=7) as tr:
            t[0] += 0.4
            tr.step("solve")
            t[0] += 0.8
            tr.step("bind")
        assert len(caplog.records) == 1
        msg = caplog.records[0].getMessage()
        assert "slow" in msg and "pods=7" in msg
        assert "solve: 400.0ms" in msg and "bind: 800.0ms" in msg
