"""Op tracing (utiltrace LogIfLong analogue)."""

import logging

from kubernetes_tpu.utils.trace import Trace, drain_overruns


def test_trace_logs_only_when_slow(caplog):
    t = [0.0]

    def clock():
        return t[0]

    with caplog.at_level(logging.WARNING, logger="kubernetes_tpu.trace"):
        with Trace("fast", threshold=1.0, clock=clock) as tr:
            t[0] += 0.1
            tr.step("a")
        assert caplog.records == []
        with Trace("slow", threshold=1.0, clock=clock, pods=7) as tr:
            t[0] += 0.4
            tr.step("solve")
            t[0] += 0.8
            tr.step("bind")
        assert len(caplog.records) == 1
        msg = caplog.records[0].getMessage()
        assert "slow" in msg and "pods=7" in msg
        assert "solve: 400.0ms" in msg and "bind: 800.0ms" in msg


def test_over_threshold_trace_emits_exactly_once(caplog):
    """Regression: the r05 bench tail showed every over-threshold
    schedule_batch trace TWICE (e.g. `took 1162.2ms` then `1162.4ms`) —
    an explicit exit-path log_if_long call followed by the with-block
    exit, each computing its own total.  However many times the caller
    finalizes, one trace must produce one log line and one overrun
    entry."""
    t = [0.0]
    drain_overruns()
    with caplog.at_level(logging.WARNING, logger="kubernetes_tpu.trace"):
        with Trace("schedule_batch", threshold=1.0, clock=lambda: t[0],
                   pods=1024) as tr:
            t[0] += 1.2
            tr.step("solve[default-scheduler]")
            tr.log_if_long()  # the old explicit exit-path call
            t[0] += 0.0002    # the with-exit recomputes a later total
        tr.log_if_long()      # a stray post-exit finalize
    assert len(caplog.records) == 1
    overruns = drain_overruns()
    assert len(overruns) == 1
    assert overruns[0]["name"] == "schedule_batch"


def test_scheduler_cycle_traces_emit_once(caplog):
    """End-to-end: a slow schedule_batch cycle through the real
    Scheduler produces exactly one trace line."""
    import time as _time

    from kubernetes_tpu.api import store as st
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod

    store = st.Store()
    sched = Scheduler(store, batch_size=16)
    for i in range(2):
        sched.cache.add_node(
            make_node(f"n{i}")
            .capacity(cpu_milli=32000, mem=64 * GI, pods=110)
            .obj()
        )
    orig = sched.tpu.schedule_pending_async

    def slow(*a, **k):
        _time.sleep(1.1)
        return orig(*a, **k)

    sched.tpu.schedule_pending_async = slow
    pods = [
        make_pod(f"p{i}").req(cpu_milli=10, mem=16 * MI).obj()
        for i in range(4)
    ]
    for p in pods:
        store.create(p)
        sched.queue.add(p)
    with caplog.at_level(logging.WARNING, logger="kubernetes_tpu.trace"):
        stats = sched.schedule_batch(timeout=0.5)
    assert stats["scheduled"] == 4
    traces = [
        r for r in caplog.records if "schedule_batch" in r.getMessage()
    ]
    assert len(traces) == 1, [r.getMessage() for r in traces]
