"""Crash-restart recovery: store checkpoints + journal-suffix replay,
wave atomicity, stale-leader write fencing, graceful close semantics,
and warm leader-failover reconciliation (ISSUE 8).

Tier-1 (fast) coverage; the randomized kill-restart schedules live in
tests/test_chaos.py (`restart` marker, `make chaos-restart`).
"""

import json
import os
import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.leaderelection import LeaderElector
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.config import SchedulerConfiguration
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


def _fp(store):
    return json.dumps(store.state_fingerprint(), sort_keys=True)


# -- snapshot + suffix recovery ----------------------------------------------


def test_checkpoint_snapshot_suffix_recovery(tmp_path):
    """checkpoint() writes a snapshot and truncates the journal; a
    restart recovers snapshot + suffix to the exact pre-restart state
    and reports the recovery split."""
    path = str(tmp_path / "j.jsonl")
    s1 = st.Store(journal_path=path, shards=1)
    for i in range(6):
        s1.create(make_pod(f"pre{i}").req(cpu_milli=100).obj())
    assert s1.checkpoint() == 6
    assert os.path.exists(path + ".snap")
    assert os.path.getsize(path) == 0  # journal truncated past the rv
    for i in range(3):
        s1.create(make_pod(f"post{i}").req(cpu_milli=100).obj())
    want = _fp(s1)

    s2 = st.Store(journal_path=path, shards=1)
    assert _fp(s2) == want
    assert s2.snapshot_records == 6
    assert s2.journal_suffix_records == 3
    assert s2.recovery_duration_ms >= 0.0
    assert s2.snapshot_fallbacks == 0
    # writes continue and survive another restart
    s2.create(make_pod("after").obj())
    s3 = st.Store(journal_path=path, shards=1)
    assert {p.meta.name for p in s3.list("Pod")[0]} == (
        {f"pre{i}" for i in range(6)}
        | {f"post{i}" for i in range(3)}
        | {"after"}
    )


def test_snapshot_suffix_bit_identical_to_full_replay_oracle(tmp_path):
    """The acceptance-criterion oracle: with the journal retained
    (checkpoint(truncate=False)), recovery through snapshot+suffix must
    be BIT-IDENTICAL to a full-journal replay of the same history."""
    path = str(tmp_path / "j.jsonl")
    s1 = st.Store(journal_path=path, shards=1)
    s1.create(make_node("n0").capacity(cpu_milli=8000, mem=16 * GI).obj())
    for i in range(8):
        s1.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    s1.checkpoint(truncate=False)
    # post-checkpoint suffix: wave binds + a delete + an update
    s1.update_wave(
        "Pod",
        [(f"p{i}", "default", _binder("n0")) for i in range(4)],
    )
    s1.delete("Pod", "p7")
    fresh = s1.get("Pod", "p6")
    fresh.spec.node_name = "n0"
    s1.update(fresh)

    img = str(tmp_path / "copy")
    j2 = faults.crash_disk_image(path, img)
    recovered = st.Store(journal_path=j2)       # snapshot + suffix
    assert recovered.snapshot_records > 0
    oracle_dir = str(tmp_path / "oracle")
    j3 = faults.crash_disk_image(path, oracle_dir)
    os.remove(j3 + ".snap")
    oracle = st.Store(journal_path=j3)          # full journal replay
    assert oracle.snapshot_records == 0
    assert _fp(recovered) == _fp(oracle)
    assert recovered.resource_version == s1.resource_version


def test_auto_checkpoint_bounds_journal_growth(tmp_path):
    """The growth trigger checkpoints instead of rewriting the journal:
    churny single-object writers leave a snapshot + tiny suffix."""
    path = str(tmp_path / "j.jsonl")
    s = st.Store(journal_path=path, checkpoint_records=64, shards=1)
    lease = api.Lease(meta=api.ObjectMeta(name="l", namespace="kube-system"))
    s.create(lease)
    for _ in range(500):
        fresh = s.get("Lease", "l", "kube-system")
        fresh.spec.renew_time += 1
        s.update(fresh)
    assert s.checkpoints_total >= 1
    with open(path) as f:
        assert sum(1 for _ in f) <= 64
    s2 = st.Store(journal_path=path, shards=1)
    assert s2.get("Lease", "l", "kube-system").spec.renew_time >= 499
    assert s2.snapshot_records == 1


def test_periodic_checkpoint_interval(tmp_path):
    path = str(tmp_path / "j.jsonl")
    s = st.Store(journal_path=path, checkpoint_interval_seconds=0.05, shards=1)
    s.create(make_pod("a").obj())
    time.sleep(0.08)
    s.create(make_pod("b").obj())  # commit past the interval triggers
    assert s.checkpoints_total >= 1


# -- wave atomicity -----------------------------------------------------------


def _binder(node):
    def mutate(pod):
        pod.spec.node_name = node
        pod.status.phase = "Running"

    return mutate


def _setup_wave_journal(path, n_pods=4):
    # legacy per-line wave format: these tests perform line-level
    # surgery on the wave's individual records, which only exist
    # pre-framing (framed waves are one line; tests/test_journal_framing
    # covers their torn/corrupt variants).  Replay must accept this
    # format forever regardless of the writer's framing flag.
    s = st.Store(journal_path=path, shards=1, journal_framing=False)
    s.create(make_node("n0").capacity(cpu_milli=8000, mem=16 * GI).obj())
    for i in range(n_pods):
        s.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    applied, errors = s.update_wave(
        "Pod", [(f"p{i}", "default", _binder("n0")) for i in range(n_pods)]
    )
    assert len(applied) == n_pods and not errors
    return s


def test_torn_final_wave_dropped_whole(tmp_path):
    """A wave whose tail is torn mid-record replays as if it never
    happened: no half-applied binds, journal truncated to the wave's
    start, and appends continue cleanly."""
    path = str(tmp_path / "j.jsonl")
    _setup_wave_journal(path)
    raw = open(path, "rb").read()
    # tear INSIDE the final wave: cut the last record in half, leaving
    # the wave's earlier records as valid CRC'd lines
    lines = raw.splitlines(keepends=True)
    torn = b"".join(lines[:-1]) + lines[-1][: len(lines[-1]) // 2]
    with open(path, "wb") as f:
        f.write(torn)
    s2 = st.Store(journal_path=path, shards=1)
    bound = [p.meta.name for p in s2.list("Pod")[0] if p.spec.node_name]
    assert bound == [], f"half-applied wave: {bound}"
    assert s2.journal_torn_waves == 1
    # the wave's valid-prefix records were truncated away too
    s2.create(make_pod("later").obj())
    s3 = st.Store(journal_path=path, shards=1)
    assert s3.journal_torn_waves == 0
    assert "later" in {p.meta.name for p in s3.list("Pod")[0]}


def test_wave_without_terminator_dropped_whole(tmp_path):
    """Losing ONLY the wave's final (terminator) record — every
    remaining line valid — still drops the whole wave: atomicity comes
    from the terminator, not from line-level CRCs."""
    path = str(tmp_path / "j.jsonl")
    _setup_wave_journal(path)
    lines = open(path, "rb").read().splitlines(keepends=True)
    with open(path, "wb") as f:
        f.writelines(lines[:-1])  # drop the "wz" terminator record
    s2 = st.Store(journal_path=path, shards=1)
    assert all(not p.spec.node_name for p in s2.list("Pod")[0])
    assert s2.journal_torn_waves == 1


def test_wave_holed_mid_file_dropped_whole(tmp_path):
    """Corruption INSIDE a wave that is followed by later valid records
    (mid-file, not tail) drops the wave whole but keeps the later
    acknowledged records."""
    path = str(tmp_path / "j.jsonl")
    s = _setup_wave_journal(path)
    s.create(make_pod("after").obj())  # valid record AFTER the wave
    lines = open(path, "rb").read().splitlines(keepends=True)
    # corrupt a record in the middle of the wave (lines: node, 4 pods,
    # then 4 wave records, then "after")
    lines[-3] = b'{"op": "MODIFIED", "rv": 0, "corrupt\xff\n'
    with open(path, "wb") as f:
        f.writelines(lines)
    s2 = st.Store(journal_path=path, shards=1)
    names = {p.meta.name for p in s2.list("Pod")[0]}
    assert "after" in names, "record after the holed wave was lost"
    assert all(not p.spec.node_name for p in s2.list("Pod")[0]), (
        "holed wave was half-applied"
    )
    assert s2.journal_torn_waves == 1


def test_complete_waves_replay_applied(tmp_path):
    """The non-degraded case: intact update_wave journals replay fully
    (terminator present), including delete-completing waves."""
    path = str(tmp_path / "j.jsonl")
    s1 = _setup_wave_journal(path)
    want = _fp(s1)
    s2 = st.Store(journal_path=path, shards=1)
    assert _fp(s2) == want
    assert s2.journal_torn_waves == 0
    assert all(p.spec.node_name == "n0" for p in s2.list("Pod")[0])


# -- corrupt snapshot fallback ------------------------------------------------


def test_corrupt_snapshot_falls_back_to_full_journal(tmp_path):
    path = str(tmp_path / "j.jsonl")
    s1 = st.Store(journal_path=path, shards=1)
    for i in range(5):
        s1.create(make_pod(f"p{i}").obj())
    s1.checkpoint(truncate=False)  # journal retains full history
    s1.create(make_pod("tail").obj())
    want = _fp(s1)
    # flip bytes inside the snapshot: CRC must catch it
    raw = bytearray(open(path + ".snap", "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(path + ".snap", "wb") as f:
        f.write(raw)
    s2 = st.Store(journal_path=path, shards=1)
    assert s2.snapshot_fallbacks == 1
    assert s2.snapshot_records == 0
    assert _fp(s2) == want, "fallback replay lost state"


def test_truncated_snapshot_falls_back(tmp_path):
    """A snapshot missing records (count mismatch vs header) is treated
    as corrupt even when every remaining line is CRC-valid."""
    path = str(tmp_path / "j.jsonl")
    s1 = st.Store(journal_path=path, shards=1)
    for i in range(4):
        s1.create(make_pod(f"p{i}").obj())
    s1.checkpoint(truncate=False)
    want = _fp(s1)
    lines = open(path + ".snap", "rb").read().splitlines(keepends=True)
    with open(path + ".snap", "wb") as f:
        f.writelines(lines[:-1])
    s2 = st.Store(journal_path=path, shards=1)
    assert s2.snapshot_fallbacks == 1
    assert _fp(s2) == want


# -- graceful close -----------------------------------------------------------


def test_close_interval_sync_flushes_final_batch(tmp_path):
    """journal_sync="interval" group-commits with a bounded loss
    window; Store.close() must flush+fsync the final dirty batch so a
    GRACEFUL shutdown loses nothing."""
    path = str(tmp_path / "j.jsonl")
    s = st.Store(journal_path=path, journal_sync="interval", shards=1)
    for i in range(5):
        s.create(make_pod(f"p{i}").obj())
    s.close()
    s2 = st.Store(journal_path=path, shards=1)
    assert {p.meta.name for p in s2.list("Pod")[0]} == {
        f"p{i}" for i in range(5)
    }


def test_close_drains_watch_dispatch_backlog(tmp_path):
    """close() returns only after pending committed event batches have
    fanned out to their watchers."""
    s = st.Store(journal_path=str(tmp_path / "j.jsonl"))
    w = s.watch("Pod")
    for i in range(20):
        s.create(make_pod(f"p{i}").obj())
    s.close()
    got = []
    while True:
        ev = w.get(timeout=0.2)
        if ev is None:
            break
        got.append(ev.obj.meta.name)
    assert set(got) == {f"p{i}" for i in range(20)}


# -- stale-leader write fencing ----------------------------------------------


def _acquire(store, lease, ident):
    e = LeaderElector(store, lease, ident, lease_duration=0.4,
                      renew_period=0.05)
    assert e.try_acquire_or_renew()
    e._leading.set()
    return e


def test_fenced_wave_rejected_after_takeover(tmp_path):
    """A deposed leader's late bind wave is rejected whole (Fenced,
    counted) instead of silently double-binding."""
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=8000, mem=16 * GI).obj())
    store.create(make_pod("p0").req(cpu_milli=100).obj())
    a = _acquire(store, "sched-lease", "holder-a")
    token_a = a.fence_token()
    assert token_a is not None and token_a.generation == 0
    # b takes over after a's lease lapses (clock: zero the renew time)
    lease = store.get("Lease", "sched-lease", "kube-system")
    lease.spec.renew_time = -1e9
    store.update(lease, force=True)
    b = _acquire(store, "sched-lease", "holder-b")
    assert b.fence_token().generation == 1
    # a's late wave carries the stale token -> fenced, nothing applied
    with pytest.raises(st.Fenced):
        store.update_wave(
            "Pod", [("p0", "default", _binder("n0"))], fence=token_a
        )
    assert store.fenced_writes_total == 1
    assert store.get("Pod", "p0").spec.node_name == ""
    # b's wave commits under its own token
    applied, errors = store.update_wave(
        "Pod", [("p0", "default", _binder("n0"))], fence=b.fence_token()
    )
    assert applied == ["default/p0"] and not errors


def test_fence_token_refreshes_on_reacquisition(tmp_path):
    """An identity that is deposed and later REACQUIRES mints a fresh
    generation; its new waves commit while pre-deposition waves stay
    fenced."""
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=8000, mem=16 * GI).obj())
    store.create(make_pod("p0").req(cpu_milli=100).obj())
    a = _acquire(store, "l", "a")
    stale = a.fence_token()
    lease = store.get("Lease", "l", "kube-system")
    lease.spec.renew_time = -1e9
    store.update(lease, force=True)
    _acquire(store, "l", "b")
    lease = store.get("Lease", "l", "kube-system")
    lease.spec.renew_time = -1e9
    store.update(lease, force=True)
    assert a.try_acquire_or_renew()  # a reacquires: generation 2
    assert a.fence_token().generation == 2
    with pytest.raises(st.Fenced):
        store.update_wave(
            "Pod", [("p0", "default", _binder("n0"))], fence=stale
        )
    applied, errors = store.update_wave(
        "Pod", [("p0", "default", _binder("n0"))], fence=a.fence_token()
    )
    assert applied and not errors


# -- scheduler reconciliation -------------------------------------------------


def _mk_scheduler(store, **kw):
    s = Scheduler(store, **kw)
    s.informers.informer("Node").start()
    s.informers.informer("Pod").start()
    assert s.informers.wait_for_sync(10)
    return s


def test_reconcile_requeues_uncommitted_assume_and_resets_device_state():
    """_reconcile_leadership: an assume with no durable bind behind it
    is forgotten and the pod re-queued; the breaker snaps closed and
    the mirror is invalidated for a full re-upload."""
    store = st.Store()
    store.create(
        make_node("n0").capacity(cpu_milli=8000, mem=16 * GI, pods=10).obj()
    )
    store.create(make_pod("ghost").req(cpu_milli=100).obj())
    sched = _mk_scheduler(store)
    try:
        pod = store.get("Pod", "ghost")
        # the crashed predecessor's footprint: assumed, never committed
        sched.cache.assume(pod, "n0")
        sched.queue.done(pod)  # and gone from the queue
        sched.tpu.breaker.record_failure()
        assert sched.tpu.breaker.state == sched.tpu.breaker.OPEN
        mirror = getattr(sched.tpu, "_mirror", None)
        sched._reconcile_leadership()
        assert sched.cache.assumed_count() == 0
        assert sched.queue.contains("default/ghost")
        assert sched.tpu.breaker.state == sched.tpu.breaker.CLOSED
        if mirror is not None:
            assert mirror._dev is None
        assert sched.metrics.leader_reconcile_total.total == 1.0
        # the requeued pod schedules normally afterwards
        stats = sched.schedule_batch(timeout=2)
        assert stats["scheduled"] == 1
        assert sched.flush_binds(30)
        assert store.get("Pod", "ghost").spec.node_name == "n0"
    finally:
        sched.stop()


def test_reconcile_keeps_assume_matching_durable_bind():
    """An assume the store already confirms (the predecessor's wave DID
    commit) survives reconciliation — no spurious forget/requeue."""
    store = st.Store()
    store.create(
        make_node("n0").capacity(cpu_milli=8000, mem=16 * GI, pods=10).obj()
    )
    store.create(make_pod("done").req(cpu_milli=100).obj())
    sched = _mk_scheduler(store)
    try:
        pod = store.get("Pod", "done")
        sched.cache.assume(pod, "n0")
        sched.queue.done(pod)
        bound = store.get("Pod", "done")
        bound.spec.node_name = "n0"
        store.update(bound)
        sched._reconcile_leadership()
        assert sched.cache.assumed_count() == 1  # informer will confirm
        assert not sched.queue.contains("default/done")
    finally:
        sched.stop()


def test_warm_failover_standby_takes_over_and_binds(tmp_path):
    """Two schedulers, one store: kill the leader ungracefully mid-run;
    the standby acquires within the lease window, reconciles, and every
    pod still binds exactly once."""
    store = st.Store(journal_path=str(tmp_path / "j.jsonl"))
    for i in range(4):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .obj()
        )
    config = SchedulerConfiguration(
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        unschedulable_flush_seconds=0.5,
    )
    ea = LeaderElector(store, "ha", "holder-a",
                       lease_duration=0.6, renew_period=0.05).start()
    a = Scheduler(store, assume_ttl=1.0, leader_elector=ea, config=config)
    a.start()
    assert ea.wait_for_leadership(10)
    for i in range(6):
        store.create(make_pod(f"w1-{i}").req(cpu_milli=100).obj())
    eb = None
    b = None
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            pods, _ = store.list("Pod")
            if all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        first = {
            p.meta.name: p.spec.node_name for p in store.list("Pod")[0]
        }
        assert all(first.values())
        # the standby is warm before the leader dies
        eb = LeaderElector(store, "ha", "holder-b",
                           lease_duration=0.6, renew_period=0.05).start()
        b = Scheduler(store, assume_ttl=1.0, leader_elector=eb,
                      config=config)
        b.start()
        a.kill()
        ea.stop(release=False)  # death, not a graceful release
        assert eb.wait_for_leadership(10), "standby never took over"
        for i in range(6):
            store.create(make_pod(f"w2-{i}").req(cpu_milli=100).obj())
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            pods, _ = store.list("Pod")
            if all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        final = {
            p.meta.name: p.spec.node_name for p in store.list("Pod")[0]
        }
        assert all(final.values()), (
            f"pods unbound after failover: "
            f"{[k for k, v in final.items() if not v]}"
        )
        # bound-exactly-once across the handoff: the first leader's
        # durable binds never move
        for name, node in first.items():
            assert final[name] == node, (
                f"{name} moved {node} -> {final[name]} across failover"
            )
        assert b.metrics.leader_reconcile_total.total >= 1.0
    finally:
        if b is not None:
            b.stop()
        if eb is not None:
            eb.stop()


@pytest.mark.multichip
def test_restart_under_mesh_mirror_resync():
    """Mesh mode survives a leadership reconcile: the mirror performs a
    full RESHARDED re-upload (resync counter) and subsequent sharded
    solves still place every pod."""
    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
    from kubernetes_tpu.parallel.sharded import make_mesh

    store = st.Store()
    for i in range(16):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=16000, mem=32 * GI, pods=110)
            .obj()
        )
    tpu = TPUBatchScheduler(mesh=make_mesh(8))
    sched = _mk_scheduler(store, tpu=tpu)
    try:
        for i in range(8):
            store.create(make_pod(f"a{i}").req(cpu_milli=100).obj())
        assert sched.schedule_batch(timeout=2)["scheduled"] == 8
        assert sched.flush_binds(30)
        mirror = tpu._mirror
        partials = tpu._partials
        assert partials is not None and partials._store is not None
        p_fulls0 = partials.full_recomputes
        resyncs0 = mirror.resync_total
        sched._reconcile_leadership()
        assert mirror._dev is None  # invalidated: next sync re-uploads
        # the resident partials invalidate WITH the mirror (warm rows
        # must never outlive the tensors they were evaluated against)
        assert partials._store is None and not partials._slots
        for i in range(8):
            store.create(make_pod(f"b{i}").req(cpu_milli=100).obj())
        assert sched.schedule_batch(timeout=2)["scheduled"] == 8
        assert sched.flush_binds(30)
        assert mirror.resync_total == resyncs0 + 1, (
            "reconcile did not force a full mirror re-upload"
        )
        assert partials.full_recomputes == p_fulls0 + 1, (
            "reconcile did not force a full partials recompute"
        )
        assert all(p.spec.node_name for p in store.list("Pod")[0])
    finally:
        sched.stop()


def test_reconcile_invalidates_partials_cache():
    """Warm failover regression (ISSUE 14): _reconcile_leadership drops
    the resident Filter/Score partials alongside the mirror — a new
    leader must not inherit warm rows from the predecessor's generation
    history — and the next solve performs a full recompute yet still
    places every pod."""
    store = st.Store()
    for i in range(4):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .obj()
        )
    sched = _mk_scheduler(store)
    try:
        for i in range(6):
            store.create(make_pod(f"a{i}").req(cpu_milli=100).obj())
        assert sched.schedule_batch(timeout=2)["scheduled"] == 6
        assert sched.flush_binds(30)
        partials = sched.tpu._partials
        assert partials is not None and partials._store is not None
        assert partials._slots
        fulls0 = partials.full_recomputes
        sched._reconcile_leadership()
        assert partials._store is None and not partials._slots
        for i in range(6):
            store.create(make_pod(f"b{i}").req(cpu_milli=100).obj())
        assert sched.schedule_batch(timeout=2)["scheduled"] == 6
        assert sched.flush_binds(30)
        assert partials.full_recomputes == fulls0 + 1
        assert all(p.spec.node_name for p in store.list("Pod")[0])
    finally:
        sched.stop()
