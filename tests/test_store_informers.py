"""API store + watch + informer layer (the integration-test tier's
foundation: nodes/pods as API objects only, test/integration/util/util.go:86)."""

import threading
import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import InformerFactory, WorkQueue
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


def test_crud_and_versions():
    s = st.Store()
    pod = make_pod("a").obj()
    created = s.create(pod)
    assert created.meta.resource_version == 1
    got = s.get("Pod", "a")
    assert got.meta.name == "a"
    got.spec.node_name = "n1"
    updated = s.update(got)
    assert updated.meta.resource_version == 2
    # stale rv conflicts
    got2 = s.get("Pod", "a")
    got2.meta.resource_version = 1
    with pytest.raises(st.Conflict):
        s.update(got2)
    with pytest.raises(st.AlreadyExists):
        s.create(pod)
    s.delete("Pod", "a")
    with pytest.raises(st.NotFound):
        s.get("Pod", "a")


def test_list_returns_rv_for_watch_resume():
    s = st.Store()
    s.create(make_pod("a").obj())
    items, rv = s.list("Pod")
    assert len(items) == 1
    w = s.watch("Pod", from_rv=rv)
    s.create(make_pod("b").obj())
    ev = w.get(timeout=2)
    assert ev.type == st.ADDED and ev.obj.meta.name == "b"
    w.stop()


def test_watch_replays_buffered_events():
    s = st.Store()
    s.create(make_pod("a").obj())   # rv 1
    s.create(make_pod("b").obj())   # rv 2
    w = s.watch("Pod", from_rv=1)   # should replay b's ADDED
    ev = w.get(timeout=2)
    assert ev.obj.meta.name == "b" and ev.rv == 2
    w.stop()


def test_watch_expired_when_too_old():
    s = st.Store(buffer_size=8)
    for i in range(40):  # trims buffer
        s.create(make_pod(f"p{i}").obj())
    with pytest.raises(st.Expired):
        s.watch("Pod", from_rv=1)


def test_informer_sync_and_stream():
    s = st.Store()
    s.create(make_node("n0").capacity(cpu_milli=1000, mem=GI).obj())
    factory = InformerFactory(s)
    inf = factory.informer("Node")
    events = []
    inf.add_handler(lambda t, o, old: events.append((t, o.meta.name)))
    inf.start()
    assert inf.wait_for_sync(5)
    assert inf.get("n0", namespace="") is not None
    s.create(make_node("n1").capacity(cpu_milli=1000, mem=GI).obj())
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(inf.list()) < 2:
        time.sleep(0.01)
    assert {n for _, n in events} >= {"n0", "n1"}
    # delete propagates
    s.delete("Node", "n0", namespace="")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and inf.get("n0", namespace="") is not None:
        time.sleep(0.01)
    assert inf.get("n0", namespace="") is None
    factory.stop()


def test_workqueue_dedup_and_backoff():
    q = WorkQueue(base_delay=0.01, max_delay=1.0)
    q.add("x"); q.add("x")
    assert q.get(timeout=1) == "x"
    assert len(q) == 0
    # re-add while processing: comes back after done
    q.add("x")
    q.done("x")
    assert q.get(timeout=1) == "x"
    q.done("x")
    # rate-limited: backoff grows, forget resets
    q.add_rate_limited("y")
    assert q.num_requeues("y") == 1
    item = q.get(timeout=2)
    assert item == "y"
    q.done("y")
    q.forget("y")
    assert q.num_requeues("y") == 0
    q.shutdown()
    assert q.get(timeout=0.1) is None
