"""ImageLocality scoring (imagelocality/image_locality.go): image
presence bitsets on nodes, size x spread-ratio scaling, 0..100 band."""

import numpy as np

from kubernetes_tpu.ops import assign, auction, schema
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod

BIG = 800 * 1024 * 1024  # well above the 23MB threshold


def test_feature_flag():
    nodes = [make_node("n0").image("nginx:1", BIG).obj(), make_node("n1").obj()]
    pods = [make_pod("p").req(cpu_milli=100).image("nginx:1").obj()]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    f = assign.features_of(snap)
    assert f.images
    # pods without images on the same cluster: flag off
    snap2, _ = schema.SnapshotBuilder().build(nodes, [make_pod("q").obj()])
    assert not assign.features_of(snap2).images


def test_prefers_node_with_image():
    nodes = [
        make_node("warm").image("ml:v1", BIG).obj(),
        make_node("cold").obj(),
    ]
    pods = [make_pod("p").req(cpu_milli=100).image("ml:v1").obj()]
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    r = assign.greedy_assign(snap)
    assert meta.node_name(int(r.assignment[0])) == "warm"


def test_image_aliases_share_identity():
    """Tag + digest of one image intern to one id (ContainerImage.names)."""
    from kubernetes_tpu.api import types as api

    node = make_node("warm").obj()
    node.status.images.append(
        api.ContainerImage(names=["app@sha256:abc", "app:latest"], size_bytes=BIG)
    )
    nodes = [node, make_node("cold").obj()]
    pods = [make_pod("p").req(cpu_milli=100).image("app:latest").obj()]
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    r = assign.greedy_assign(snap)
    assert meta.node_name(int(r.assignment[0])) == "warm"


def test_tiny_image_below_threshold_no_preference():
    """Images under the 23MB minThreshold score 0 everywhere — ties break
    by node order, not image presence."""
    nodes = [
        make_node("n0").obj(),
        make_node("warm").image("tiny:v1", 1 * 1024 * 1024).obj(),
    ]
    pods = [make_pod("p").req(cpu_milli=100).image("tiny:v1").obj()]
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    r = assign.greedy_assign(snap)
    assert meta.node_name(int(r.assignment[0])) == "n0"  # first-index tie


def test_auction_route_scores_images():
    nodes = [make_node("warm").image("ml:v1", BIG).obj()] + [
        make_node(f"cold{i}").obj() for i in range(7)
    ]
    pods = [make_pod(f"p{i}").req(cpu_milli=100).image("ml:v1").obj() for i in range(2)]
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[:2]
    # first pod lands warm; second may also (capacity allows)
    assert meta.node_name(int(a[0])) == "warm" or meta.node_name(int(a[1])) == "warm"


def test_incremental_state_tracks_images():
    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler

    sched = TPUBatchScheduler()
    sched.add_node(make_node("warm").image("ml:v1", BIG).obj())
    sched.add_node(make_node("cold").obj())
    out = sched.schedule_pending(
        [make_pod("p").req(cpu_milli=100).image("ml:v1").obj()]
    )
    assert out == ["warm"]
