"""Service / Endpoints / EndpointSlice model + endpointslice controller.

VERDICT r4 #2 acceptance: creating a Service over labeled pods yields
slices that track pod add/delete/readiness.  Reference behaviours:
pkg/controller/endpointslice (reconciler packing, service-name label),
pkg/controller/endpoint (legacy Endpoints object), FindPort
(pkg/api/v1/pod/util.go) for named targetPorts.
"""

import time

from kubernetes_tpu.api import admission as adm
from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.endpointslice import (
    MAX_ENDPOINTS_PER_SLICE,
    EndpointSliceController,
)


def _wait(cond, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


def _svc(name="web", selector=None, port=80, **spec_kw):
    return api.Service(
        meta=api.ObjectMeta(name=name),
        spec=api.ServiceSpec(
            selector=dict(selector or {"app": "web"}),
            ports=[api.ServicePort(name="http", port=port, target_port=8080)],
            **spec_kw,
        ),
    )


def _pod(name, labels=None, ip="", ready=True, node="n0"):
    p = api.Pod(
        meta=api.ObjectMeta(name=name, labels=dict(labels or {"app": "web"})),
        spec=api.PodSpec(node_name=node),
    )
    p.status.phase = "Running" if ready else "Pending"
    p.status.pod_ip = ip
    return p


def _mgr(store):
    return ControllerManager(
        store, controllers=[EndpointSliceController]
    ).start()


def _slices(store, svc="web"):
    items, _ = store.list("EndpointSlice")
    return [
        s for s in items
        if s.meta.labels.get(api.LABEL_SERVICE_NAME) == svc
    ]


def test_slices_track_pod_lifecycle():
    store = st.Store()
    mgr = _mgr(store)
    try:
        store.create(_pod("a", ip="10.1.0.1"))
        store.create(_pod("b", ip="10.1.0.2"))
        store.create(_pod("other", labels={"app": "db"}, ip="10.1.0.9"))
        store.create(_svc())
        assert _wait(
            lambda: sum(len(s.endpoints) for s in _slices(store)) == 2
        )
        s = _slices(store)[0]
        assert {e.addresses[0] for e in s.endpoints} == {"10.1.0.1", "10.1.0.2"}
        assert s.ports[0].port == 8080  # targetPort, not front port
        assert all(e.conditions.ready for e in s.endpoints)

        # pod delete shrinks the slice
        store.delete("Pod", "a")
        assert _wait(
            lambda: sum(len(s.endpoints) for s in _slices(store)) == 1
        )

        # a pod matching the selector later joins
        store.create(_pod("c", ip="10.1.0.3"))
        assert _wait(
            lambda: sum(len(s.endpoints) for s in _slices(store)) == 2
        )

        # legacy Endpoints object mirrors the ready set
        ep = store.get("Endpoints", "web")
        assert {a.ip for a in ep.subsets[0].addresses} == {
            "10.1.0.2", "10.1.0.3",
        }
    finally:
        mgr.stop()


def test_readiness_flip_updates_conditions():
    store = st.Store()
    mgr = _mgr(store)
    try:
        store.create(_pod("a", ip="10.1.0.1"))
        store.create(_svc())
        assert _wait(lambda: len(_slices(store)) == 1)
        # flip readiness via the Ready condition (node-agent style)
        p = store.get("Pod", "a")
        p.status.conditions = [{"type": "Ready", "status": "False"}]
        store.update(p, force=True)
        assert _wait(
            lambda: _slices(store)
            and _slices(store)[0].endpoints
            and not _slices(store)[0].endpoints[0].conditions.ready
        )
        # legacy object moves the address to notReadyAddresses
        ep = store.get("Endpoints", "web")
        assert not ep.subsets[0].addresses
        assert [a.ip for a in ep.subsets[0].not_ready_addresses] == ["10.1.0.1"]
    finally:
        mgr.stop()


def test_slice_packing_and_service_delete():
    store = st.Store()
    mgr = _mgr(store)
    try:
        n = MAX_ENDPOINTS_PER_SLICE + 5
        for i in range(n):
            store.create(_pod(f"p-{i}", ip=f"10.2.{i // 256}.{i % 256}"))
        store.create(_svc())
        assert _wait(
            lambda: sum(len(s.endpoints) for s in _slices(store)) == n
        )
        assert len(_slices(store)) == 2  # packed at <=100 per slice
        store.delete("Service", "web")
        assert _wait(lambda: not _slices(store))
        assert _wait(
            lambda: not [
                e for e in store.list("Endpoints")[0]
                if e.meta.name == "web"
            ]
        )
    finally:
        mgr.stop()


def test_cluster_ip_allocation_and_validation():
    store = st.Store(admission=adm.default_chain())
    created = store.create(_svc("web"))
    assert created.spec.cluster_ip.startswith("10.")
    # deterministic: same name → same VIP
    octets = created.spec.cluster_ip.split(".")
    assert 96 <= int(octets[1]) <= 111
    # headless passes through
    headless = _svc("hl", cluster_ip="None")
    assert store.create(headless).spec.cluster_ip == "None"
    # validation: no ports
    bad = api.Service(meta=api.ObjectMeta(name="bad"))
    bad.spec.selector = {"a": "b"}
    try:
        store.create(bad)
        assert False, "expected AdmissionError"
    except adm.AdmissionError:
        pass


def test_named_target_port_resolution():
    store = st.Store()
    mgr = _mgr(store)
    try:
        pod = _pod("a", ip="10.1.0.1")
        pod.spec.containers = [
            api.Container(
                name="main",
                ports=[api.ContainerPort(name="metrics", container_port=9090)],
            )
        ]
        store.create(pod)
        svc = api.Service(
            meta=api.ObjectMeta(name="web"),
            spec=api.ServiceSpec(
                selector={"app": "web"},
                ports=[
                    api.ServicePort(
                        name="m", port=80, target_port_name="metrics"
                    )
                ],
            ),
        )
        store.create(svc)
        assert _wait(
            lambda: _slices(store)
            and _slices(store)[0].ports
            and _slices(store)[0].ports[0].port == 9090
        )
    finally:
        mgr.stop()
