"""Incremental O(changes) solving: warm-started solves must be
BIT-IDENTICAL to cold solves and to the host oracle on randomized churn
snapshots — the parity gate ISSUE 14's warm start rests on — plus the
cache's resync/rollback/invalidation discipline."""

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
from kubernetes_tpu.models.partials import PartialsCache
from kubernetes_tpu.ops import assign as assign_ops
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _mk_sched(use_partials, mesh=None, **kw):
    return TPUBatchScheduler(
        mode="greedy", use_partials=use_partials, mesh=mesh, **kw
    )


def _add_nodes(scheds, n=16, seed=0):
    rng = np.random.default_rng(seed)
    for i in range(n):
        nd = (
            make_node(f"n-{i}")
            .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .zone(f"z-{i % 3}")
        )
        if rng.random() < 0.3:
            nd.label("disk", "ssd")
        if rng.random() < 0.2:
            nd.taint("dedicated", "gpu", api.PREFER_NO_SCHEDULE)
        if rng.random() < 0.1:
            nd.taint("maint", "true", api.NO_SCHEDULE)
        node = nd.obj()
        for s in scheds:
            s.add_node(node)


def _mk_pods(step, p, seed):
    """Mixed static specs: selectors, preferred terms, tolerations,
    host ports, NodeName — every input of the partials triple."""
    rng = np.random.default_rng(seed)
    pods = []
    for i in range(p):
        pw = make_pod(f"s{step}-p{i}").req(
            cpu_milli=int(rng.choice([100, 250, 500])), mem=256 * MI
        )
        r = i % 6
        if r == 0:
            pw.required_affinity(
                api.LABEL_ZONE, api.OP_IN, [f"z-{i % 3}"]
            )
        elif r == 1:
            pw.preferred_affinity(10, "disk", api.OP_IN, ["ssd"])
        elif r == 2:
            pw.toleration("dedicated", "gpu", effect=api.PREFER_NO_SCHEDULE)
        elif r == 3:
            pw.toleration("maint", "true", effect=api.NO_SCHEDULE)
        elif r == 4:
            pw.host_port(7000 + (i % 4))
        pods.append(pw.obj())
    return pods


def _churn(scheds, rng, placed):
    """Dirty a handful of rows: assumes, forgets, node updates."""
    for p, nm in placed:
        if rng.random() < 0.6:
            for s in scheds:
                s.assume(p, nm)
    if rng.random() < 0.5:
        node = (
            make_node(f"n-{int(rng.integers(0, 8))}")
            .capacity(cpu_milli=16000, mem=32 * GI, pods=200)
            .zone(f"z-{int(rng.integers(0, 3))}")
            .obj()
        )
        for s in scheds:
            s.update_node(node)


def _solve_both(warm, cold, pods):
    names_w = warm.schedule_pending(pods)
    res_w = warm.last_result
    names_c = cold.schedule_pending(pods)
    res_c = cold.last_result
    assert names_w == names_c
    # bit-identical: the full result surface, not just the names
    if res_w is not None and res_c is not None:
        np.testing.assert_array_equal(
            np.asarray(res_w.assignment), np.asarray(res_c.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(res_w.scores), np.asarray(res_c.scores)
        )
        np.testing.assert_array_equal(
            np.asarray(res_w.reasons), np.asarray(res_c.reasons)
        )
    return names_w


def test_randomized_churn_parity_and_oracle():
    """Warm == cold == host oracle across randomized churn snapshots,
    with the cache actually serving warm rows (delta syncs > 0)."""
    from kubernetes_tpu.testing.oracle import Oracle

    warm, cold = _mk_sched(True), _mk_sched(False)
    _add_nodes((warm, cold), 16, seed=3)
    rng = np.random.default_rng(7)
    for step in range(6):
        pods = _mk_pods(step, 12, seed=step)
        names = _solve_both(warm, cold, pods)
        # host-oracle parity on the same live state
        state = warm.state
        nodes = [state._node_objs[nm] for nm in state._rows]
        oracle = Oracle(nodes)
        by_name = {s.node.meta.name: s for s in oracle.states}
        for key, bp in state._pods.items():
            ns = by_name.get(state._pod_node.get(key))
            if ns is not None:
                ns.add_pod(bp)
        assert names == oracle.schedule(list(pods))
        _churn((warm, cold), rng, [
            (p, nm) for p, nm in zip(pods, names) if nm is not None
        ])
    stats = warm._partials.stats()
    assert stats["delta_syncs"] >= 3
    assert stats["hit_rows_total"] > 0


def test_statics_match_cold_class_statics():
    """The gathered warm triple equals class_statics on the same
    resident tensors, array-for-array (stronger than placement
    parity)."""
    import jax
    import jax.numpy as jnp

    from kubernetes_tpu.ops.filters import preferred_match, selector_match

    warm = _mk_sched(True)
    _add_nodes((warm,), 12, seed=1)
    for step in range(3):
        pods = _mk_pods(step, 10, seed=step + 20)
        snap, meta = warm.encode_pending(pods)
        assert meta.statics is not None
        # meta.statics leaves were gathered from the resident store;
        # snap.cluster after the packed transfer is the same resident
        # tensors.  Recompute the cold triple from them.
        host_snap, _ = warm.builder.build_from_state(warm.state, pods)
        cluster = snap.cluster
        pods_t = jax.tree.map(jnp.asarray, host_snap.pods)
        sm = selector_match(cluster, jax.tree.map(
            jnp.asarray, host_snap.selectors))
        pm = preferred_match(cluster, jax.tree.map(
            jnp.asarray, host_snap.preferred))
        sfeas, aff, taint = assign_ops.class_statics(
            cluster, pods_t, sm, pm
        )
        np.testing.assert_array_equal(
            np.asarray(meta.statics.sfeas), np.asarray(sfeas)
        )
        np.testing.assert_array_equal(
            np.asarray(meta.statics.aff), np.asarray(aff)
        )
        np.testing.assert_array_equal(
            np.asarray(meta.statics.taint), np.asarray(taint)
        )
        for i, p in enumerate(pods):
            if i % 3 == 0:
                warm.assume(p, f"n-{i % 12}")
        assert warm._partials.verify(cluster, host_snap)


def test_gang_retry_and_ports_parity():
    """Gang batches (all-or-nothing + admission retry) and in-batch
    host-port conflicts ride the warm path unchanged."""
    warm, cold = _mk_sched(True), _mk_sched(False)
    _add_nodes((warm, cold), 8, seed=5)
    for step in range(2):
        pods = []
        for i in range(8):
            pods.append(
                make_pod(f"g{step}-{i}")
                .req(cpu_milli=500, mem=256 * MI)
                .group(f"gang-{i % 2}")
                .obj()
            )
        for i in range(4):
            pods.append(
                make_pod(f"hp{step}-{i}")
                .req(cpu_milli=100, mem=128 * MI)
                .host_port(9000 + (i % 2))
                .obj()
            )
        _solve_both(warm, cold, pods)


def test_vocab_growth_flushes_cache():
    """A selector-relevant vocabulary growing between batches flushes
    the cache whole (stale expansions must never be served warm) — and
    parity holds across the flush."""
    warm, cold = _mk_sched(True), _mk_sched(False)
    _add_nodes((warm, cold), 8, seed=9)
    pods = _mk_pods(0, 8, seed=0)
    _solve_both(warm, cold, pods)
    full0 = warm._partials.full_recomputes
    # a NEW label value: the In-expansion of any selector over that key
    # could now differ from the cached rows' expansion
    node = (
        make_node("n-1").capacity(cpu_milli=8000, mem=16 * GI, pods=110)
        .zone("z-0").label("disk", "nvme").obj()
    )
    for s in (warm, cold):
        s.update_node(node)
    pods2 = [
        make_pod("nv-0").req(cpu_milli=100, mem=128 * MI)
        .required_affinity("disk", api.OP_IN, ["nvme"]).obj()
    ] + _mk_pods(1, 6, seed=1)
    _solve_both(warm, cold, pods2)
    assert warm._partials.full_recomputes > full0


def test_struct_growth_invalidates():
    """A BULK load crossing the padded node bucket forces a full
    recompute through the over-fraction path (most rows dirtied at
    once).  Incremental crossings — few dirty rows — are absorbed in
    place instead (tests/test_elastic_axis.py); the elastic node axis
    reserves the full reseed for genuine struct events and bulk
    loads."""
    warm, cold = _mk_sched(True), _mk_sched(False)
    _add_nodes((warm, cold), 8, seed=11)
    _solve_both(warm, cold, _mk_pods(0, 8, seed=2))
    full0 = warm._partials.full_recomputes
    for i in range(24):  # crosses the growth bucket
        node = (
            make_node(f"grow-{i}")
            .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .zone(f"z-{i % 3}").obj()
        )
        for s in (warm, cold):
            s.add_node(node)
    _solve_both(warm, cold, _mk_pods(1, 8, seed=3))
    assert warm._partials.full_recomputes > full0


def test_speculation_rollback_parity():
    """rollback() restores the bookmarked residents; the next sync
    re-refreshes everything dirtied since the bookmark and parity
    holds (the mirror's speculation contract, applied to partials)."""
    warm, cold = _mk_sched(True), _mk_sched(False)
    _add_nodes((warm, cold), 12, seed=13)
    pods0 = _mk_pods(0, 10, seed=4)
    names0 = _solve_both(warm, cold, pods0)
    point = warm._partials.speculation_point()
    mpoint = warm._mirror.speculation_point()
    # speculative progress on the WARM side only: assumes + a batch
    # carrying a first-seen class (allocates a slot the rollback drops)
    for p, nm in zip(pods0, names0):
        if nm is not None:
            warm.assume(p, nm)
    spec = [
        make_pod("spec-0").req(cpu_milli=100, mem=128 * MI)
        .required_affinity(api.LABEL_ZONE, api.OP_NOT_IN, ["z-1"]).obj()
    ]
    warm.schedule_pending(spec)
    # invalidation: drop the speculative deltas whole
    for p, nm in zip(pods0, names0):
        if nm is not None:
            warm.forget(p)
    warm._mirror.rollback(mpoint)
    warm._partials.rollback(point)
    assert warm._partials.rollbacks == 1
    # durable churn applied to BOTH sides, then parity
    rng = np.random.default_rng(17)
    pods1 = _mk_pods(1, 10, seed=5)
    for p in pods0[:3]:
        for s in (warm, cold):
            s.assume(p, "n-2")
    _solve_both(warm, cold, pods1)


def test_corrupt_partials_trips_parity_gate():
    """A CORRUPT solve.partials fault poisons the resident score rows:
    the decode health check must trip, the retry must invalidate +
    fully recompute, and the batch still places correctly."""
    warm, cold = _mk_sched(True), _mk_sched(False)
    _add_nodes((warm, cold), 8, seed=15)
    _solve_both(warm, cold, _mk_pods(0, 8, seed=6))
    full0 = warm._partials.full_recomputes
    reg = faults.FaultRegistry(seed=1)
    reg.corrupt("solve.partials", n=1)
    pods = _mk_pods(1, 8, seed=7)
    with faults.armed(reg):
        names_w = warm.schedule_pending(pods)
    assert reg.fired.get("solve.partials")
    names_c = cold.schedule_pending(pods)
    assert names_w == names_c
    # the gate tripped to a full recompute (or the breaker's host
    # fallback produced the placements)
    assert (
        warm._partials.full_recomputes > full0
        or warm.breaker.fallback_count() > 0
    )
    # and the cache is healthy again afterwards
    _solve_both(warm, cold, _mk_pods(2, 8, seed=8))


def test_fail_grade_fault_degrades_to_cold():
    """A fail-grade solve.partials fault must not kill the encode: the
    batch solves cold and later batches warm again."""
    warm, cold = _mk_sched(True), _mk_sched(False)
    _add_nodes((warm, cold), 8, seed=19)
    reg = faults.FaultRegistry(seed=2)
    reg.fail("solve.partials", n=1)
    with faults.armed(reg):
        _solve_both(warm, cold, _mk_pods(0, 8, seed=9))
    assert reg.fired.get("solve.partials")
    _solve_both(warm, cold, _mk_pods(1, 8, seed=10))
    assert warm._partials.stats()["slots"] > 0


def test_periodic_resync_discipline():
    """Every `resync_interval` delta syncs the cache forces a full
    recompute (the periodic half of the parity discipline)."""
    warm = _mk_sched(True, partials_resync_interval=2)
    cold = _mk_sched(False)
    _add_nodes((warm, cold), 8, seed=21)
    fulls = []
    for step in range(6):
        pods = _mk_pods(step, 8, seed=30 + step)
        _solve_both(warm, cold, pods)
        fulls.append(warm._partials.full_recomputes)
        for i, p in enumerate(pods[:2]):
            for s in (warm, cold):
                s.assume(p, f"n-{(step * 2 + i) % 8}")
    assert fulls[-1] >= 2  # first sync + at least one periodic resync


@pytest.mark.multichip
def test_mesh_warm_parity():
    """Sharded mesh: warm == cold == single-chip on churn snapshots,
    and the resident store carries the node-axis sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubernetes_tpu.parallel.sharded import make_mesh

    mesh = make_mesh(8)
    warm = _mk_sched(True, mesh=mesh)
    cold = _mk_sched(False, mesh=mesh)
    single = _mk_sched(False)
    _add_nodes((warm, cold, single), 16, seed=23)
    rng = np.random.default_rng(29)
    for step in range(4):
        pods = _mk_pods(step, 12, seed=40 + step)
        names = _solve_both(warm, cold, pods)
        assert names == single.schedule_pending(pods)
        _churn((warm, cold, single), rng, [
            (p, nm) for p, nm in zip(pods, names) if nm is not None
        ])
    store = warm._partials._store
    assert store.sfeas.sharding == NamedSharding(mesh, P(None, "nodes"))
    assert warm._partials.stats()["delta_syncs"] >= 1


@pytest.mark.multichip
def test_mesh_small_bucket_replicates():
    """A padded bucket smaller than the mesh keeps the partials
    replicated (these batches solve single-chip) and parity holds."""
    from kubernetes_tpu.ops import schema
    from kubernetes_tpu.parallel.sharded import make_mesh

    mesh = make_mesh(8)
    limits = schema.SnapshotLimits(min_nodes=4)
    warm = TPUBatchScheduler(
        mode="greedy", use_partials=True, mesh=mesh, limits=limits
    )
    cold = TPUBatchScheduler(
        mode="greedy", use_partials=False, mesh=mesh, limits=limits
    )
    for s in (warm, cold):
        for i in range(3):
            s.add_node(
                make_node(f"n-{i}")
                .capacity(cpu_milli=8000, mem=16 * GI, pods=110).obj()
            )
    pods = [
        make_pod(f"p-{i}").req(cpu_milli=100, mem=128 * MI).obj()
        for i in range(4)
    ]
    assert warm.schedule_pending(pods) == cold.schedule_pending(pods)
