"""Versioned config loading + feature gates.

References: pkg/scheduler/apis/config/types.go:37 (the versioned
KubeSchedulerConfiguration pipeline), component-base/featuregate/
feature_gate.go + pkg/features/kube_features.go (gates consulted at
registry build time, plugins/registry.go:58-70).
"""

import pytest

from kubernetes_tpu.scheduler.config import (
    SchedulerConfiguration,
    load_config,
)
from kubernetes_tpu.scheduler.framework import FrameworkRegistry
from kubernetes_tpu.utils.featuregate import FeatureGate

CONFIG_YAML = """
apiVersion: kubescheduler.config.k8s.io/v1
kind: KubeSchedulerConfiguration
parallelism: 8
podInitialBackoffSeconds: 2
podMaxBackoffSeconds: 30
featureGates:
  AuctionSolver: false
profiles:
  - schedulerName: default-scheduler
    plugins:
      score:
        disabled:
          - name: ImageLocality
        enabled:
          - name: NodeAffinity
            weight: 3
    pluginConfig:
      - name: NodeResourcesFit
        args:
          scoringStrategy:
            type: MostAllocated
  - schedulerName: batch-scheduler
"""


def test_load_config_round_trip():
    cfg = load_config(CONFIG_YAML)
    assert cfg.parallelism == 8
    assert cfg.pod_initial_backoff_seconds == 2.0
    assert cfg.pod_max_backoff_seconds == 30.0
    assert cfg.feature_gates == {"AuctionSolver": False}
    assert [p.scheduler_name for p in cfg.profiles] == [
        "default-scheduler", "batch-scheduler",
    ]
    prof = cfg.profiles[0]
    assert prof.disabled_score_plugins == ("ImageLocality",)
    eff = prof.effective_score_config()
    assert eff.image_weight == 0.0
    assert eff.node_affinity_weight == 3.0
    assert eff.fit_strategy == "MostAllocated"


def test_load_config_from_file(tmp_path):
    p = tmp_path / "sched.yaml"
    p.write_text(CONFIG_YAML)
    cfg = load_config(str(p))
    assert cfg.profiles[0].disabled_score_plugins == ("ImageLocality",)


def test_load_config_rejects_unknown_fields():
    with pytest.raises(ValueError, match="unknown configuration fields"):
        load_config({"bogusKnob": 1})
    with pytest.raises(ValueError, match="unsupported apiVersion"):
        load_config({"apiVersion": "v999"})
    with pytest.raises(ValueError, match="unknown profile fields"):
        load_config({"profiles": [{"schedulerName": "x", "oops": 1}]})


def test_feature_gate_validation():
    g = FeatureGate()
    assert g.enabled("AuctionSolver")
    assert g.enabled("GangScheduling")
    with pytest.raises(ValueError, match="unknown feature gate"):
        FeatureGate(overrides={"Bogus": True})
    # GA + locked: overriding off is rejected (LockToDefault)
    with pytest.raises(ValueError, match="locked"):
        FeatureGate(overrides={"GangScheduling": False})
    g2 = FeatureGate.from_flag("AuctionSolver=false,VolumeBinding=true")
    assert not g2.enabled("AuctionSolver")
    assert g2.enabled("VolumeBinding")
    with pytest.raises(ValueError, match="true|false"):
        FeatureGate.from_flag("AuctionSolver=maybe")


def test_auction_gate_flips_router():
    """The gate changes REAL behavior at registry build time: with
    AuctionSolver off every profile's solver routes greedy, even for
    auction-shaped (gang) batches."""
    from kubernetes_tpu.ops import assign as assign_ops
    from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod

    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI, pods=20)
        .obj()
        for i in range(8)
    ]
    pods = [
        make_pod(f"p{i}").req(cpu_milli=500, mem=MI)
        .group("g", size=4).obj()
        for i in range(4)
    ]

    reg_on = FrameworkRegistry(SchedulerConfiguration())
    assert reg_on.default.tpu.mode == "auto"
    reg_off = FrameworkRegistry(
        SchedulerConfiguration(feature_gates={"AuctionSolver": False})
    )
    assert reg_off.default.tpu.mode == "greedy"

    # both place the gang; the routes differ
    tpu_off = reg_off.default.tpu
    for nd in nodes:
        tpu_off.add_node(nd)
    names = tpu_off.schedule_pending(pods)
    assert all(n is not None for n in names)
    assert type(tpu_off.last_result).__name__ == "SolveResult"  # greedy

    tpu_on = reg_on.default.tpu
    for nd in nodes:
        tpu_on.add_node(nd)
    names = tpu_on.schedule_pending(pods)
    assert all(n is not None for n in names)
    assert type(tpu_on.last_result).__name__ == "AuctionResult"

    _ = assign_ops  # imported for clarity of the result types' origin


def test_validate_catches_bad_gates_in_config():
    cfg = SchedulerConfiguration(feature_gates={"Nope": True})
    with pytest.raises(ValueError, match="unknown feature gate"):
        cfg.validate()


def test_mesh_devices_knob_loads_and_validates():
    """meshDevices: YAML key -> SchedulerConfiguration.mesh_devices,
    power-of-two validated (padded node buckets must split across the
    mesh), gated by ShardedSolve."""
    cfg = load_config(
        """
apiVersion: kubescheduler.config.k8s.io/v1
kind: KubeSchedulerConfiguration
meshDevices: 8
"""
    )
    assert cfg.mesh_devices == 8
    assert SchedulerConfiguration().mesh_devices == 0  # default: single chip
    with pytest.raises(ValueError, match="power of two"):
        SchedulerConfiguration(mesh_devices=3).validate()
    with pytest.raises(ValueError, match=">= 0"):
        SchedulerConfiguration(mesh_devices=-1).validate()
    # the gate itself is a known, overridable BETA feature
    g = FeatureGate()
    assert g.enabled("ShardedSolve")
    assert not FeatureGate(
        overrides={"ShardedSolve": False}
    ).enabled("ShardedSolve")


def test_mesh_registry_build_respects_gate():
    """Registry build consults meshDevices + ShardedSolve: on -> every
    profile shares one mesh; off -> single chip.  An oversubscribed
    mesh (more devices than visible) is rejected loudly."""
    import jax

    from kubernetes_tpu.scheduler.framework import FrameworkRegistry

    n_dev = len(jax.devices())
    if n_dev >= 8:
        reg = FrameworkRegistry(SchedulerConfiguration(mesh_devices=8))
        tpus = [f.tpu for f in reg]
        assert all(t.mesh is not None and t.shard_count == 8 for t in tpus)
        assert len({id(t.mesh) for t in tpus}) == 1  # one shared mesh
    off = FrameworkRegistry(
        SchedulerConfiguration(
            mesh_devices=8, feature_gates={"ShardedSolve": False}
        )
    )
    assert off.default.tpu.mesh is None
    with pytest.raises(ValueError, match="JAX devices"):
        FrameworkRegistry(
            SchedulerConfiguration(mesh_devices=max(n_dev * 2, 16))
        )


def test_mirror_gate_off_still_schedules():
    """DeviceClusterMirror=false routes encode through the full-copy
    path (the rollback knob) with identical placements."""
    from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod

    reg = FrameworkRegistry(
        SchedulerConfiguration(feature_gates={"DeviceClusterMirror": False})
    )
    tpu = reg.default.tpu
    assert not tpu.use_mirror
    for i in range(4):
        tpu.add_node(
            make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=10)
            .obj()
        )
    names = tpu.schedule_pending(
        [make_pod(f"p{i}").req(cpu_milli=1000, mem=MI).obj() for i in range(4)]
    )
    assert all(n is not None for n in names)
