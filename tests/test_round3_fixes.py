"""Round-3 robustness fixes: pod-before-node buffering, update_pod
re-accounting, min_domains / match_label_keys semantics, encode-time
strictness, watermark compaction, histogram bounds, handler isolation."""

import threading
import warnings

import numpy as np
import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.informers import SharedInformer
from kubernetes_tpu.ops import assign, schema
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.metrics import Histogram
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _node(name, zone="z1", cpu=8000):
    return make_node(name).capacity(cpu_milli=cpu, mem=16 * GI, pods=20).zone(zone).obj()


# -- pod-before-node buffering (ADVICE: add_pod KeyError) -----------------


def test_pod_delivered_before_node_is_buffered_then_accounted():
    state = schema.ClusterState(schema.SnapshotBuilder())
    cache = SchedulerCache(state)
    pod = make_pod("early").req(cpu_milli=1000).node_name("n-late").obj()
    cache.add_pod(pod)  # must not raise
    assert not state.has_pod(pod)
    cache.add_node(_node("n-late"))
    assert state.has_pod(pod)
    row = state._rows["n-late"]
    assert state.requested[row, schema.RESOURCE_CPU] == 1000


def test_buffered_pod_dropped_on_delete():
    state = schema.ClusterState(schema.SnapshotBuilder())
    cache = SchedulerCache(state)
    pod = make_pod("early").req(cpu_milli=1000).node_name("n-late").obj()
    cache.add_pod(pod)
    cache.remove_pod(pod)
    cache.add_node(_node("n-late"))
    assert not state.has_pod(pod)


# -- update_pod re-accounting (ADVICE: bound-pod resize drift) ------------


def test_update_pod_reaccounts_requests():
    state = schema.ClusterState(schema.SnapshotBuilder())
    cache = SchedulerCache(state)
    cache.add_node(_node("n0"))
    old = make_pod("p").req(cpu_milli=1000).node_name("n0").obj()
    cache.add_pod(old)
    row = state._rows["n0"]
    assert state.requested[row, schema.RESOURCE_CPU] == 1000
    new = make_pod("p").req(cpu_milli=3000).node_name("n0").obj()
    cache.update_pod(old, new)
    assert state.requested[row, schema.RESOURCE_CPU] == 3000


# -- minDomains (filtering.go minMatchNum) --------------------------------


def _spread_cluster():
    nodes = [_node("a1", "z1"), _node("a2", "z2")]
    bound = []
    for z, n in (("z1", "a1"), ("z2", "a2")):
        for j in range(2):
            bound.append(
                make_pod(f"b-{z}-{j}").labels(app="web").node_name(n).obj()
            )
    return nodes, bound


def _spread_pod(min_domains=None):
    p = make_pod("incoming").labels(app="web").req(cpu_milli=100)
    p.pod.spec.topology_spread_constraints.append(
        api.TopologySpreadConstraint(
            max_skew=1,
            topology_key=api.LABEL_ZONE,
            when_unsatisfiable="DoNotSchedule",
            label_selector=api.LabelSelector(match_labels={"app": "web"}),
            min_domains=min_domains,
        )
    )
    return p.obj()


def test_min_domains_unset_allows_placement():
    nodes, bound = _spread_cluster()
    snap, meta = schema.SnapshotBuilder().build(nodes, [_spread_pod()], bound)
    res = assign.greedy_assign(snap)
    assert int(np.asarray(res.assignment)[0]) >= 0


def test_min_domains_unmet_zeroes_global_min():
    # 2 eligible domains < min_domains=3 => global min treated as 0 =>
    # skew = 2 + 1 - 0 = 3 > maxSkew=1 on every node => unschedulable.
    nodes, bound = _spread_cluster()
    snap, meta = schema.SnapshotBuilder().build(
        nodes, [_spread_pod(min_domains=3)], bound
    )
    res = assign.greedy_assign(snap)
    assert int(np.asarray(res.assignment)[0]) == -1


# -- matchLabelKeys merge -------------------------------------------------


def test_spread_match_label_keys_scopes_counts_to_own_version():
    nodes = [_node("a1", "z1"), _node("a2", "z2")]
    bound = [
        make_pod("b1").labels(app="web", version="v1").node_name("a1").obj(),
        make_pod("b2").labels(app="web", version="v2").node_name("a1").obj(),
    ]
    p = make_pod("inc").labels(app="web", version="v1").req(cpu_milli=100)
    p.pod.spec.topology_spread_constraints.append(
        api.TopologySpreadConstraint(
            max_skew=1,
            topology_key=api.LABEL_ZONE,
            when_unsatisfiable="DoNotSchedule",
            label_selector=api.LabelSelector(match_labels={"app": "web"}),
            match_label_keys=["version"],
        )
    )
    snap, meta = schema.SnapshotBuilder().build(nodes, [p.obj()], bound)
    # only the v1 bound pod counts for the merged selector
    row = np.asarray(snap.spread.node_matches)[0]
    assert row[0] == 1.0 and row[1] == 0.0


def test_anti_affinity_match_label_keys():
    nodes = [_node("a1", "z1"), _node("a2", "z2")]
    bound = [
        make_pod("b1").labels(app="web", version="v1").node_name("a1").obj(),
        make_pod("b2").labels(app="web", version="v2").node_name("a2").obj(),
    ]
    p = make_pod("inc").labels(app="web", version="v1").req(cpu_milli=100)
    p.pod.spec.affinity = api.Affinity(
        pod_anti_affinity=api.PodAntiAffinity(
            required=[
                api.PodAffinityTerm(
                    label_selector=api.LabelSelector(match_labels={"app": "web"}),
                    topology_key=api.LABEL_HOSTNAME,
                    match_label_keys=["version"],
                )
            ]
        )
    )
    snap, meta = schema.SnapshotBuilder().build(nodes, [p.obj()], bound)
    res = assign.greedy_assign(snap)
    # v1 conflict lives on a1 only; the pod must land on a2
    assert meta.node_name(int(np.asarray(res.assignment)[0])) == "a2"


# -- encode-time strictness ----------------------------------------------


def test_namespace_selector_raises():
    p = make_pod("x").req(cpu_milli=100)
    p.pod.spec.affinity = api.Affinity(
        pod_anti_affinity=api.PodAntiAffinity(
            required=[
                api.PodAffinityTerm(
                    label_selector=api.LabelSelector(match_labels={"a": "b"}),
                    namespace_selector=api.LabelSelector(match_labels={"team": "x"}),
                )
            ]
        )
    )
    with pytest.raises(OverflowError, match="namespace_selector"):
        schema.SnapshotBuilder().build([_node("n0")], [p.obj()])


def test_node_inclusion_policy_raises():
    p = make_pod("x").req(cpu_milli=100)
    p.pod.spec.topology_spread_constraints.append(
        api.TopologySpreadConstraint(node_taints_policy="Honor")
    )
    with pytest.raises(OverflowError, match="nodeInclusionPolicies"):
        schema.SnapshotBuilder().build([_node("n0")], [p.obj()])


def test_f32_envelope_warns_on_huge_node():
    b = schema.SnapshotBuilder()
    state = schema.ClusterState(b)
    huge = make_node("big").capacity(cpu_milli=4000, mem=512 * GI, pods=10).obj()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state.add_node(huge)
    assert any("f32" in str(x.message) for x in w)


# -- watermark compaction (ADVICE low) ------------------------------------


def test_high_watermark_shrinks_after_mass_removal():
    state = schema.ClusterState(schema.SnapshotBuilder())
    for i in range(64):
        state.add_node(_node(f"n{i}", zone=f"z{i % 3}"))
    assert state._high == 64
    for i in range(60):
        state.remove_node(f"n{i}")
    assert state.num_nodes == 4
    assert state._high < 16
    # surviving rows keep their identity and a solve still places pods
    survivors = {state.node_names[i] for i in state._rows.values()}
    assert survivors == {f"n{i}" for i in range(60, 64)}
    b = state.builder
    snap, meta = b.build_from_state(state, [make_pod("p").req(cpu_milli=500).obj()])
    res = assign.greedy_assign(snap)
    assert meta.node_name(int(np.asarray(res.assignment)[0])) in survivors


# -- histogram +Inf bucket (VERDICT weak #7) ------------------------------


def test_histogram_percentile_bounded_by_max():
    h = Histogram("t", buckets=(0.1, 1.0))
    for v in (5.0, 6.0, 7.0):
        h.observe(v)
    assert h.percentile(0.99) <= 7.0
    assert h.max == 7.0


# -- unencodable pod must not kill the scheduling loop --------------------


def test_unencodable_pod_parks_without_killing_batch():
    from kubernetes_tpu.scheduler import Scheduler

    store = st.Store()
    for i in range(2):
        store.create(_node(f"n{i}"))
    bad = make_pod("bad").req(cpu_milli=100)
    bad.pod.spec.affinity = api.Affinity(
        pod_anti_affinity=api.PodAntiAffinity(
            required=[
                api.PodAffinityTerm(
                    label_selector=api.LabelSelector(match_labels={"a": "b"}),
                    namespace_selector=api.LabelSelector(match_labels={"t": "x"}),
                )
            ]
        )
    )
    store.create(bad.obj())
    for i in range(3):
        store.create(make_pod(f"ok{i}").req(cpu_milli=100).obj())
    sched = Scheduler(store)
    sched.informers.informer("Node").start()
    sched.informers.informer("Pod").start()
    assert sched.informers.wait_for_sync(10)
    try:
        total = 0
        for _ in range(10):
            total += sched.schedule_batch(timeout=0.2)["scheduled"]
            if total == 3:
                break
        assert total == 3
        assert sched.queue.stats()["unschedulable"] == 1
    finally:
        sched.stop()


# -- informer handler isolation (ADVICE medium) ---------------------------


def test_handler_exception_does_not_kill_stream_or_other_handlers():
    store = st.Store()
    inf = SharedInformer(store, "Node")
    seen = []

    def bad(typ, obj, old):
        raise RuntimeError("boom")

    inf.add_handler(bad)
    inf.add_handler(lambda typ, obj, old: seen.append((typ, obj.meta.name)))
    inf.start()
    try:
        assert inf.wait_for_sync(5)
        store.create(_node("n1"))
        store.create(_node("n2"))
        deadline = threading.Event()
        for _ in range(100):
            if len(seen) >= 2:
                break
            deadline.wait(0.05)
        names = {n for _, n in seen}
        assert names == {"n1", "n2"}
    finally:
        inf.stop()
