"""PV controller (bind/reclaim/repair) + CLI apply/edit/logs.

Reference: pkg/controller/volume/persistentvolume/pv_controller.go
(syncClaim/syncVolume), kubectl apply/edit/logs verb family.
"""

import io
import json
import sys
import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.server import APIServer
from kubernetes_tpu.cli import main as cli_main
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.pvcontroller import PersistentVolumeController
from kubernetes_tpu.testing.wrappers import GI, make_pod


def _wait(cond, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


def _pv(name, size_gi=10, sc="standard", reclaim="Retain"):
    return api.PersistentVolume(
        meta=api.ObjectMeta(name=name),
        spec=api.PersistentVolumeSpec(
            capacity={api.STORAGE: size_gi * GI},
            access_modes=["ReadWriteOnce"],
            storage_class_name=sc,
            reclaim_policy=reclaim,
        ),
    )


def _pvc(name, size_gi=5, sc="standard"):
    return api.PersistentVolumeClaim(
        meta=api.ObjectMeta(name=name),
        spec=api.PersistentVolumeClaimSpec(
            access_modes=["ReadWriteOnce"],
            storage_class_name=sc,
            resources={api.STORAGE: size_gi * GI},
        ),
    )


def test_immediate_claim_binds_smallest_fit_and_reclaims():
    store = st.Store()
    mgr = ControllerManager(
        store, controllers=[PersistentVolumeController]
    ).start()
    try:
        store.create(_pv("big", size_gi=100))
        store.create(_pv("small", size_gi=10))
        store.create(_pv("tiny", size_gi=1))
        store.create(_pvc("data", size_gi=5))
        # binds the SMALLEST satisfying volume
        assert _wait(
            lambda: store.get("PersistentVolumeClaim", "data").spec.volume_name
            == "small"
        )
        pv = store.get("PersistentVolume", "small")
        assert pv.spec.claim_ref == "default/data"
        assert pv.status.phase == api.PV_BOUND

        # claim deleted -> Retain policy: volume goes Released, not away
        store.delete("PersistentVolumeClaim", "data")
        assert _wait(
            lambda: store.get("PersistentVolume", "small").status.phase
            == api.PV_RELEASED
        )

        # Delete policy volume disappears with its claim
        store.create(_pv("ephemeral", size_gi=5, reclaim="Delete"))
        store.create(_pvc("scratch", size_gi=2))
        assert _wait(
            lambda: store.get(
                "PersistentVolumeClaim", "scratch"
            ).spec.volume_name == "ephemeral"
        )
        store.delete("PersistentVolumeClaim", "scratch")

        def gone():
            try:
                store.get("PersistentVolume", "ephemeral")
                return False
            except KeyError:
                return True
        assert _wait(gone)
    finally:
        mgr.stop()


def test_half_bound_repair_and_wfc_left_alone():
    store = st.Store()
    # crash artifact: PV claims the PVC, PVC side never written
    pv = _pv("pv0", size_gi=10)
    pv.spec.claim_ref = "default/data"
    pv.status.phase = api.PV_BOUND
    store.create(pv)
    store.create(_pvc("data", size_gi=5))
    # a WaitForFirstConsumer claim must NOT be touched
    store.create(api.StorageClass(
        meta=api.ObjectMeta(name="wfc", namespace=""),
        provisioner="x", volume_binding_mode=api.VOLUME_BINDING_WAIT,
    ))
    store.create(_pvc("later", size_gi=1, sc="wfc"))
    mgr = ControllerManager(
        store, controllers=[PersistentVolumeController]
    ).start()
    try:
        assert _wait(
            lambda: store.get("PersistentVolumeClaim", "data").spec.volume_name
            == "pv0"
        )
        time.sleep(0.3)
        assert not store.get("PersistentVolumeClaim", "later").spec.volume_name
    finally:
        mgr.stop()


# -- CLI ----------------------------------------------------------------------


def _run_cli(argv):
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        cli_main(argv)
    finally:
        sys.stdout = old
    return out.getvalue()


@pytest.fixture
def server():
    store = st.Store()
    srv = APIServer(store).start()
    yield store, srv
    srv.stop()


def test_cli_apply_create_then_configure(server, tmp_path):
    store, srv = server
    base = ["--server", srv.url]
    f = tmp_path / "pod.yaml"
    f.write_text(
        "kind: Pod\nmetadata: {name: web, labels: {v: '1'}}\n"
        "spec:\n  containers:\n  - resources: {requests: {cpu: 500m}}\n"
    )
    out = _run_cli(base + ["apply", "-f", str(f)])
    assert "pod/web created" in out
    # second apply with a changed label patches in place
    f.write_text(
        "kind: Pod\nmetadata: {name: web, labels: {v: '2'}}\n"
        "spec:\n  containers:\n  - resources: {requests: {cpu: 500m}}\n"
    )
    out = _run_cli(base + ["apply", "-f", str(f)])
    assert "pod/web configured" in out
    assert store.get("Pod", "web").meta.labels["v"] == "2"


def test_cli_edit_applies_buffer(server, tmp_path, monkeypatch):
    store, srv = server
    store.create(make_pod("web").req(cpu_milli=100).obj())
    # "editor": a script that sets a label in the JSON buffer
    editor = tmp_path / "ed.py"
    editor.write_text(
        "import json, sys\n"
        "p = sys.argv[1]\n"
        "d = json.load(open(p))\n"
        "d['meta']['labels']['edited'] = 'yes'\n"
        "json.dump(d, open(p, 'w'))\n"
    )
    monkeypatch.setenv("EDITOR", f"{sys.executable} {editor}")
    # EDITOR with args: subprocess.run([editor, path]) needs a single
    # executable — wrap via env shim
    import os
    wrapper = tmp_path / "ed.sh"
    wrapper.write_text(f"#!/bin/sh\nexec {sys.executable} {editor} \"$1\"\n")
    os.chmod(wrapper, 0o755)
    monkeypatch.setenv("EDITOR", str(wrapper))
    out = _run_cli(["--server", srv.url, "edit", "pod", "web"])
    assert "edited" in out
    assert store.get("Pod", "web").meta.labels.get("edited") == "yes"


def test_cli_logs_lifecycle(server):
    store, srv = server
    p = make_pod("web").req(cpu_milli=100).obj()
    p.spec.node_name = "n0"
    p.status.phase = "Running"
    p.status.pod_ip = "10.88.0.1"
    p.status.restart_counts = {"c": 2}
    store.create(p)
    store.create(api.Event(
        meta=api.ObjectMeta(name="web.scheduled"),
        involved_object=api.ObjectReference(kind="Pod", name="web"),
        reason="Scheduled", message="assigned default/web to n0",
        type="Normal", last_timestamp=time.time(),
    ))
    out = _run_cli(["--server", srv.url, "logs", "web"])
    assert "Scheduled" in out
    assert "restarts: {'c': 2}" in out
    assert "phase: Running on n0 ip 10.88.0.1" in out


def test_recreated_claim_does_not_inherit_volume():
    """pv_controller.go's claimRef.UID check: a deleted-then-recreated
    same-name PVC must trigger reclaim, not silently inherit the data."""
    store = st.Store()
    mgr = ControllerManager(
        store, controllers=[PersistentVolumeController]
    ).start()
    try:
        store.create(_pv("pv1", size_gi=10, reclaim="Delete"))
        store.create(_pvc("data", size_gi=5))
        assert _wait(
            lambda: store.get("PersistentVolumeClaim", "data").spec.volume_name
            == "pv1"
        )
        # delete + immediately recreate under the same name
        store.delete("PersistentVolumeClaim", "data")
        store.create(_pvc("data", size_gi=5))

        # the Delete-policy volume goes away (new claim has a new uid)
        def pv_gone():
            try:
                store.get("PersistentVolume", "pv1")
                return False
            except KeyError:
                return True
        assert _wait(pv_gone)
        assert store.get(
            "PersistentVolumeClaim", "data"
        ).spec.volume_name != "pv1"
    finally:
        mgr.stop()
