"""Wavefront/scan parity: wavefront_assign must place *identically* to
greedy_assign — same assignments, same failure reasons, same feasible
counts, same winning scores — across every constraint family, including
its forced-serialization and per-pod re-evaluation fallbacks.

The wavefront contract is stronger than "the planner produces good
waves": ANY contiguous partition of the solve order must solve exactly
(the device re-verifies coupling and serializes unsafe waves), so these
tests also drive hostile hand-built partitions.
"""

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import assign, schema
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def run_both(nodes, pods, bound=(), wave_cap=8, members=None):
    snap, meta = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    scan = assign.greedy_assign_jit()(snap)
    wave = assign.wavefront_assign_jit()(
        snap, wave_members=members, wave_cap=wave_cap
    )
    return snap, meta, scan, wave


def assert_parity(scan, wave, n_pods):
    assert (
        np.asarray(scan.assignment)[:n_pods]
        == np.asarray(wave.assignment)[:n_pods]
    ).all(), "placements diverge"
    assert (
        np.asarray(scan.reasons)[:n_pods]
        == np.asarray(wave.reasons)[:n_pods]
    ).all(), "failure reasons diverge"
    assert (
        np.asarray(scan.feasible_counts)[:n_pods]
        == np.asarray(wave.feasible_counts)[:n_pods]
    ).all(), "feasible counts diverge"
    s1 = np.asarray(scan.scores)[:n_pods]
    s2 = np.asarray(wave.scores)[:n_pods]
    placed = np.asarray(scan.assignment)[:n_pods] >= 0
    assert np.allclose(s1[placed], s2[placed]), "winning scores diverge"
    # the post-solve cluster usage must agree too (it seeds later batches)
    np.testing.assert_allclose(
        np.asarray(scan.cluster.requested),
        np.asarray(wave.cluster.requested),
    )


def one_wave_members(snap):
    """A hostile plan: the whole batch in a single wave."""
    prio = np.asarray(snap.pods.priority)
    p = prio.shape[0]
    order = np.argsort(-prio, kind="stable").astype(np.int32)
    k = max(8, 1 << (p - 1).bit_length())
    members = np.full((8, k), -1, dtype=np.int32)
    members[0, :p] = order
    return members


def test_resources_only_identical_pods():
    """Identical pods all argmax to the same node — the mini-scan must
    reproduce the scan's node-by-node stacking exactly."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=110).obj()
        for i in range(6)
    ]
    pods = [
        make_pod(f"p{i}").req(cpu_milli=900, mem=1 * GI).obj()
        for i in range(20)
    ]
    _, _, scan, wave = run_both(nodes, pods)
    assert_parity(scan, wave, len(pods))
    assert int(wave.wave_count) >= 1


def test_fit_flip_forces_full_reeval():
    """Nearly-full nodes: placements inside one wave flip later members'
    resource fit — the per-pod exact fallback must fire and match."""
    nodes = [
        make_node("n0").capacity(cpu_milli=1000, mem=2 * GI, pods=110).obj(),
        make_node("n1").capacity(cpu_milli=700, mem=2 * GI, pods=110).obj(),
    ]
    pods = [
        make_pod(f"p{i}").req(cpu_milli=600, mem=256 * MI).obj()
        for i in range(4)
    ]
    snap, _, scan, _ = run_both(nodes, pods)
    wave = assign.wavefront_assign_jit()(
        snap, wave_members=one_wave_members(snap)
    )
    assert_parity(scan, wave, len(pods))
    assert int(wave.wave_fallbacks) > 0  # the flips were detected


def test_ports_conflict_parity():
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI, pods=110).obj()
        for i in range(3)
    ]
    pods = [
        make_pod(f"w{i}").req(cpu_milli=500, mem=256 * MI).host_port(80).obj()
        for i in range(5)
    ]
    _, _, scan, wave = run_both(nodes, pods)
    assert_parity(scan, wave, len(pods))


def test_spread_coupling_serializes_wave():
    """Same-service spread pods crammed into one wave couple through the
    count rows — the device must detect it and serialize that wave."""
    nodes = [
        make_node(f"n{i}")
        .capacity(cpu_milli=32000, mem=64 * GI, pods=110)
        .zone(f"z{i % 3}")
        .obj()
        for i in range(9)
    ]
    pods = [
        make_pod(f"s{i}")
        .req(cpu_milli=500, mem=256 * MI)
        .label("app", "svc")
        .spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": "svc"})
        .obj()
        for i in range(9)
    ]
    snap, _, scan, _ = run_both(nodes, pods)
    wave = assign.wavefront_assign_jit()(
        snap, wave_members=one_wave_members(snap)
    )
    assert_parity(scan, wave, len(pods))
    assert int(wave.wave_fallbacks) > 0  # wave went serial
    # and the planner keeps them apart, so the planned path stays fast
    planned = assign.wavefront_assign_jit()(snap, wave_cap=8)
    assert_parity(scan, planned, len(pods))
    assert int(planned.wave_fallbacks) == 0


def test_soft_spread_score_parity():
    nodes = [
        make_node(f"n{i}")
        .capacity(cpu_milli=32000, mem=64 * GI, pods=110)
        .zone(f"z{i % 4}")
        .obj()
        for i in range(8)
    ]
    pods = [
        make_pod(f"s{i}")
        .req(cpu_milli=500, mem=256 * MI)
        .label("app", f"svc{i % 3}")
        .spread(2, api.LABEL_ZONE, "ScheduleAnyway", {"app": f"svc{i % 3}"})
        .obj()
        for i in range(12)
    ]
    _, _, scan, wave = run_both(nodes, pods)
    assert_parity(scan, wave, len(pods))


def test_interpod_anti_affinity_parity():
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=32000, mem=64 * GI, pods=110).obj()
        for i in range(10)
    ]
    pods = []
    for i in range(20):
        svc = i % 4
        pods.append(
            make_pod(f"a{i}")
            .req(cpu_milli=500, mem=256 * MI)
            .label("app", f"s{svc}")
            .pod_anti_affinity({"app": f"s{svc}"}, api.LABEL_HOSTNAME)
            .obj()
        )
    snap, _, scan, wave = run_both(nodes, pods)
    assert_parity(scan, wave, len(pods))
    # hostile single-wave partition: coupling detected, wave serialized
    forced = assign.wavefront_assign_jit()(
        snap, wave_members=one_wave_members(snap)
    )
    assert_parity(scan, forced, len(pods))


def test_interpod_affinity_first_pod_escape():
    """Required affinity with the first-pod-of-group escape: later pods
    must see the first placement's presence bits at wave boundaries."""
    nodes = [
        make_node(f"n{i}")
        .capacity(cpu_milli=32000, mem=64 * GI, pods=110)
        .zone(f"z{i % 2}")
        .obj()
        for i in range(6)
    ]
    pods = [
        make_pod(f"co{i}")
        .req(cpu_milli=500, mem=256 * MI)
        .label("app", "web")
        .pod_affinity({"app": "web"}, api.LABEL_ZONE)
        .obj()
        for i in range(6)
    ]
    snap, _, scan, wave = run_both(nodes, pods, wave_cap=4)
    assert_parity(scan, wave, len(pods))


def test_gang_release_parity():
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=2000, mem=4 * GI, pods=110).obj()
        for i in range(4)
    ]
    pods = [
        make_pod(f"g{i}")
        .req(cpu_milli=900, mem=512 * MI)
        .group(f"gang-{i // 3}")
        .obj()
        for i in range(9)
    ]
    _, _, scan, wave = run_both(nodes, pods, wave_cap=4)
    assert_parity(scan, wave, len(pods))
    got = np.asarray(wave.reasons)[:9]
    assert (got == np.asarray(scan.reasons)[:9]).all()


@pytest.mark.parametrize("seed", range(6))
def test_randomized_mixed_constraints(seed):
    """Randomized mixes of every family + mixed priorities, solved with
    a random wave cap — the strongest drift detector."""
    rng = np.random.default_rng(seed)
    zones = ["z1", "z2", "z3"]
    nodes = []
    for i in range(16):
        nw = (
            make_node(f"n{i}")
            .capacity(
                cpu_milli=int(rng.choice([2000, 4000, 8000])),
                mem=int(rng.choice([4, 8, 16])) * GI,
                pods=int(rng.choice([5, 110])),
            )
            .zone(str(rng.choice(zones)))
        )
        if rng.random() < 0.2:
            nw.taint("dedicated", "batch", api.NO_SCHEDULE)
        nodes.append(nw.obj())

    pods = []
    for i in range(40):
        pw = make_pod(f"p{i}").req(
            cpu_milli=int(rng.choice([100, 500, 1000, 2000])),
            mem=int(rng.choice([128, 512, 1024])) * MI,
        )
        pw.priority(int(rng.integers(-2, 3)))
        r = rng.random()
        if r < 0.2:
            pw.label("app", f"svc{i % 4}").spread(
                2, api.LABEL_ZONE, "DoNotSchedule", {"app": f"svc{i % 4}"}
            )
        elif r < 0.4:
            pw.label("app", f"svc{i % 4}").pod_anti_affinity(
                {"app": f"svc{i % 4}"}, api.LABEL_HOSTNAME
            )
        elif r < 0.5:
            pw.host_port(int(rng.choice([80, 443])))
        elif r < 0.6:
            pw.node_selector_kv(api.LABEL_ZONE, str(rng.choice(zones)))
        if rng.random() < 0.15:
            pw.group(f"gang-{i % 3}")
        pods.append(pw.obj())

    cap = int(rng.choice([4, 8, 16]))
    _, _, scan, wave = run_both(nodes, pods, wave_cap=cap)
    assert_parity(scan, wave, len(pods))


@pytest.mark.parametrize("seed", range(3))
def test_random_partitions_are_exact(seed):
    """Device-side safety: an arbitrary (not planner-produced) contiguous
    partition of the solve order must still match the scan."""
    rng = np.random.default_rng(100 + seed)
    nodes = [
        make_node(f"n{i}")
        .capacity(cpu_milli=4000, mem=8 * GI, pods=110)
        .zone(f"z{i % 2}")
        .obj()
        for i in range(6)
    ]
    pods = []
    for i in range(18):
        pw = make_pod(f"p{i}").req(
            cpu_milli=int(rng.choice([500, 1000])), mem=512 * MI
        )
        if i % 3 == 0:
            pw.label("app", "x").spread(
                1, api.LABEL_ZONE, "DoNotSchedule", {"app": "x"}
            )
        pods.append(pw.obj())
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    scan = assign.greedy_assign_jit()(snap)

    prio = np.asarray(snap.pods.priority)
    p = prio.shape[0]
    order = np.argsort(-prio, kind="stable").astype(np.int32)
    # random contiguous split into waves of random widths, K=8
    k = 8
    cuts = sorted(rng.choice(np.arange(1, p), size=4, replace=False).tolist())
    chunks, start = [], 0
    for c in cuts + [p]:
        while c - start > k:
            chunks.append(order[start : start + k])
            start += k
        chunks.append(order[start:c])
        start = c
    chunks = [c for c in chunks if len(c)]
    w_pad = max(8, 1 << (len(chunks) - 1).bit_length())
    members = np.full((w_pad, k), -1, dtype=np.int32)
    for wi, ch in enumerate(chunks):
        members[wi, : len(ch)] = ch
    wave = assign.wavefront_assign_jit()(snap, wave_members=members)
    assert_parity(scan, wave, len(pods))
