"""Hollow-node scale simulation: the store/informer/queue path under a
kubemark-style cluster with heartbeat churn (pkg/kubemark analogue)."""

import time

from kubernetes_tpu.api import store as st
from kubernetes_tpu.kubemark import FleetHarness, HollowCluster, percentiles
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import MI, make_pod


def test_hollow_cluster_schedules_through_full_path():
    store = st.Store()
    hollow = HollowCluster(
        store, n_nodes=500, heartbeat_interval=0.5
    ).start()
    sched = Scheduler(store, batch_size=512)
    sched.informers.informer("Node").start()
    sched.informers.informer("Pod").start()
    assert sched.informers.wait_for_sync(20)
    try:
        for i in range(300):
            store.create(make_pod(f"w{i}").req(cpu_milli=500, mem=256 * MI).obj())
        deadline = time.monotonic() + 60
        bound = 0
        while time.monotonic() < deadline and bound < 300:
            sched.schedule_batch(timeout=0.2)
            pods, _ = store.list("Pod")
            bound = sum(1 for p in pods if p.spec.node_name)
        assert bound == 300, f"only {bound}/300 bound"
        # the hollow kubelets ran them
        deadline = time.monotonic() + 15
        running = 0
        while time.monotonic() < deadline and running < 300:
            pods, _ = store.list("Pod")
            running = sum(1 for p in pods if p.status.phase == "Running")
            time.sleep(0.1)
        assert running == 300, f"only {running}/300 running"
        # heartbeat churn flowed through the informer path without
        # destabilizing the cache
        assert sched.tpu.state.num_nodes == 500
    finally:
        sched.stop()
        hollow.stop()


def test_heartbeats_are_wave_committed_batches():
    """The heartbeat loop must commit its node slice through
    update_wave (one coalesced transaction per tick), never O(batch)
    single-object writes — asserted by counting Node write events per
    heartbeat wave."""
    store = st.Store(shards=4)
    hollow = HollowCluster(
        store, n_nodes=50, heartbeat_interval=0.2, run_pods=False
    )
    w = store.watch("Node")
    hollow.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and hollow.heartbeat_waves < 3:
            time.sleep(0.02)
        assert hollow.heartbeat_waves >= 3
    finally:
        hollow.stop()
        w.stop()
    # every beat flowed through a wave: the wave/beat accounting matches
    # the per-tick batch size (read after the loop thread joined)
    assert hollow.heartbeats == 5 * hollow.heartbeat_waves
    beats = 0
    while True:
        ev = w.get(timeout=0.1)
        if ev is None:
            break
        # an un-drained ADDED compacts with later MODIFIEDs and stays
        # ADDED (latest-wins with the newest object) — either type
        # carrying the annotation proves the wave flowed through watch
        if "hollow/heartbeat" in (ev.obj.meta.annotations or {}):
            beats += 1
    assert beats > 0


def test_fleet_harness_soak_lossless_with_percentiles():
    """The 100k-fleet harness at test scale: the sustained lifecycle
    soak loses no pod, double-binds no pod, reports SLO percentiles,
    and spreads its bind sub-waves over the store shards."""
    store = st.Store(shards=8)
    fleet = FleetHarness(
        store, n_nodes=60, namespaces=6, heartbeat_interval=0.5
    ).start()
    try:
        report = fleet.soak(total_pods=90, round_pods=30, round_timeout=30)
    finally:
        fleet.stop()
    assert report["pods"] == 90 and report["rounds"] == 3
    assert report["lost_pods"] == 0
    assert report["double_bound_pods"] == 0
    assert report["lifecycle_p99_ms"] >= report["lifecycle_p50_ms"] > 0
    assert 0.0 <= report["commit_share_per_step"] <= 1.0
    assert store.watchers_terminated == 0
    # the soak's namespaces hash onto more than one shard, so the bind
    # rounds really exercised concurrent sub-wave commits
    shards = {store.shard_index("Pod", f"fleet-{i}") for i in range(6)}
    assert len(shards) > 1


def test_percentiles_nearest_rank():
    samples = [float(i) for i in range(1, 101)]
    pct = percentiles(samples)
    assert pct["p50"] == 50.0
    assert pct["p90"] == 90.0
    assert pct["p99"] == 99.0
    assert percentiles([]) == {"p50": 0.0, "p90": 0.0, "p99": 0.0}
