"""Hollow-node scale simulation: the store/informer/queue path under a
kubemark-style cluster with heartbeat churn (pkg/kubemark analogue)."""

import time

from kubernetes_tpu.api import store as st
from kubernetes_tpu.kubemark import HollowCluster
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import MI, make_pod


def test_hollow_cluster_schedules_through_full_path():
    store = st.Store()
    hollow = HollowCluster(
        store, n_nodes=500, heartbeat_interval=0.5
    ).start()
    sched = Scheduler(store, batch_size=512)
    sched.informers.informer("Node").start()
    sched.informers.informer("Pod").start()
    assert sched.informers.wait_for_sync(20)
    try:
        for i in range(300):
            store.create(make_pod(f"w{i}").req(cpu_milli=500, mem=256 * MI).obj())
        deadline = time.monotonic() + 60
        bound = 0
        while time.monotonic() < deadline and bound < 300:
            sched.schedule_batch(timeout=0.2)
            pods, _ = store.list("Pod")
            bound = sum(1 for p in pods if p.spec.node_name)
        assert bound == 300, f"only {bound}/300 bound"
        # the hollow kubelets ran them
        deadline = time.monotonic() + 15
        running = 0
        while time.monotonic() < deadline and running < 300:
            pods, _ = store.list("Pod")
            running = sum(1 for p in pods if p.status.phase == "Running")
            time.sleep(0.1)
        assert running == 300, f"only {running}/300 running"
        # heartbeat churn flowed through the informer path without
        # destabilizing the cache
        assert sched.tpu.state.num_nodes == 500
    finally:
        sched.stop()
        hollow.stop()
