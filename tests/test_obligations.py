"""graftobl runtime half — the exactly-once obligation ledger
(analysis/ledger.py).

Proves the ledger's semantics (leaks carry acquiring call chains, a
double-discharge raises at the offending call, mid-flight arming stays
silent on unknown keys), that the production hooks observe the real
lifecycles (queue pod tiers, cache assumes, APF seats, arbiter slots,
fault-registry arming) with zero false positives on the legitimate
idempotent paths, and pins the true positives the obligations work
surfaced:

  * requeue_backoff / add_unschedulable clobbered a mid-cycle re-gate:
    a pod popped inflight, re-gated by an update that added scheduling
    gates, then requeued by the failing cycle landed in backoff/unsched
    — from where a GATED pod could pop straight into a solve.  Both
    methods now treat tier=="gated" as the pod's disposition;
  * _dispatch_batch dropped no-framework groups silently, stranding
    popped pods on the inflight tier with no disposition (unreachable
    through the filtered informer paths, pinned as hardening);
  * DispatchArbiter.release() swallows below-zero releases to keep the
    production counter sane — the ledger hook sits BEFORE that guard,
    so a masked double-release surfaces as a double-discharge.

The smoke subset rides tier-1 ('obligations and not slow'); chaos runs
arm the ledger session-wide via GRAFTLINT_OBLIGATIONS=1 (conftest) and
the quiesce blocks call assert_quiesced per seed.
"""

import contextlib

import pytest

from kubernetes_tpu.analysis import ledger
from kubernetes_tpu.api import auth
from kubernetes_tpu.api import flowcontrol
from kubernetes_tpu.models.batch_scheduler import DispatchArbiter
from kubernetes_tpu.ops import schema
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.queue import SchedulingQueue, pod_key
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod

pytestmark = pytest.mark.obligations


@contextlib.contextmanager
def _isolated():
    """A private armed ledger, even when the GRAFTLINT_OBLIGATIONS=1
    session ledger is active — the injected-violation tests must not
    poison the session-teardown assert_clean()."""
    prev = ledger._active
    ledger._active = None
    try:
        with ledger.tracked() as led:
            yield led
    finally:
        ledger._active = prev


# -- ledger semantics --------------------------------------------------------

def test_acquire_discharge_exactly_once_is_clean():
    with _isolated() as led:
        led.acquire("pod", "default/p0")
        led.discharge("pod", "default/p0")
        led.assert_clean()
        assert led.tracked_total == 1
        assert led.leaks_total == 0


def test_leak_reports_acquiring_call_chain():
    with _isolated() as led:
        led.acquire("assume", "default/p0")
        leaks = led.outstanding()
        assert len(leaks) == 1
        assert "leaked assume 'default/p0'" in leaks[0]
        # the chain names THIS test as the acquirer
        assert "test_obligations.py" in leaks[0]
        with pytest.raises(ledger.ObligationViolation):
            led.assert_clean()


def test_double_discharge_raises_immediately_and_is_recorded():
    with _isolated() as led:
        led.acquire("seat", 42)
        led.discharge("seat", 42)
        with pytest.raises(ledger.ObligationViolation, match="double-discharge"):
            led.discharge("seat", 42)
        assert led.double_discharge_total == 1
        # the record names both discharge sites
        assert "already discharged at" in led.double[0]


def test_unknown_key_discharge_is_silent():
    """Arming mid-flight (a session fixture around a warm process) must
    not misattribute pre-arming acquisitions."""
    with _isolated() as led:
        led.discharge("pod", "default/never-seen")
        led.assert_clean()
        assert led.double_discharge_total == 0


def test_reacquire_retires_previous_cycle():
    """A requeued pod popped again starts a fresh obligation: its new
    discharge is not a double against the previous cycle's."""
    with _isolated() as led:
        led.acquire("pod", "default/p0")
        led.discharge("pod", "default/p0")
        led.acquire("pod", "default/p0")
        led.discharge("pod", "default/p0")
        led.assert_clean()


def test_reset_cycles_clears_double_discharge_lookback():
    """Pod keys recur across tests in a session-armed run: the per-test
    conftest boundary calls reset_cycles() so a key retired by one test
    never turns the next test's discharge-without-acquire of the SAME
    key (informer delete of a never-assumed pod) into a false double."""
    with _isolated() as led:
        led.acquire("pod", "default/p0")
        led.discharge("pod", "default/p0")
        led.reset_cycles()
        # same key, next "test": never acquired here, so silent
        led.discharge("pod", "default/p0")
        led.assert_clean()
        assert led.double_discharge_total == 0


def test_abandon_drops_held_state_without_discharging():
    """Scheduler.kill() (the SIGKILL analogue) abandons the ledger:
    held obligations vanish without counting as discharged, a late
    discharge from a half-dead thread is silent, and a successor's
    fresh cycle on the same key tracks normally."""
    with _isolated() as led:
        led.acquire("assume", "default/p0")
        led.acquire("pod", "default/p1")
        led.discharge("pod", "default/p1")
        led.push("slot", 0xA)
        led.push("stream_inflight", 0xB)
        led.abandon()
        led.assert_clean()
        # late stragglers from half-dead threads: all silent — kill()
        # shuts the commit pool down without waiting, so a hand-off's
        # finally-decrement can land after the abandon
        led.discharge("assume", "default/p0")
        led.discharge("pod", "default/p1")
        led.pop("slot", 0xA)
        led.pop("stream_inflight", 0xB)
        assert led.double_discharge_total == 0
        led.acquire("assume", "default/p0")  # successor's fresh cycle
        led.discharge("assume", "default/p0")
        led.assert_clean()


def test_counter_push_pop_balanced_is_clean():
    with _isolated() as led:
        led.push("slot", 0xA)
        led.push("slot", 0xA)
        led.pop("slot", 0xA)
        led.pop("slot", 0xA)
        led.assert_clean()


def test_counter_pop_below_zero_raises():
    with _isolated() as led:
        led.push("slot", 0xA)
        led.pop("slot", 0xA)
        with pytest.raises(ledger.ObligationViolation, match="below zero"):
            led.pop("slot", 0xA)
        assert led.double_discharge_total == 1


def test_counter_pop_unknown_owner_is_silent():
    with _isolated() as led:
        led.pop("dispatch_inflight", 0xBEEF)
        led.assert_clean()


def test_assert_quiesced_filters_by_kind():
    with _isolated() as led:
        led.acquire("seat", 1)        # still legitimately in flight
        led.acquire("assume", "default/p0")
        with pytest.raises(ledger.ObligationViolation, match="assume"):
            led.assert_quiesced(("pod", "assume"), context="t")
        led.discharge("assume", "default/p0")
        led.assert_quiesced(("pod", "assume"), context="t")  # seat excluded
        led.discharge("seat", 1)


def test_disarmed_hooks_are_noops():
    prev = ledger._active
    ledger._active = None
    try:
        ledger.acquire("pod", "x")
        ledger.discharge("pod", "x")
        ledger.push("slot", 1)
        ledger.pop("slot", 1)
        assert ledger.tracked_total() == 0
        assert ledger.leaks_total() == 0
        assert ledger.double_discharge_total() == 0
    finally:
        ledger._active = prev


def test_nested_tracked_shares_outer_ledger():
    with _isolated() as outer:
        with ledger.tracked() as inner:
            assert inner is outer
            inner.acquire("pod", "x")
        # inner exit must not disarm the outer extent
        assert ledger.active() is outer
        outer.discharge("pod", "x")


# -- queue pod-tier hooks ----------------------------------------------------

def _pop_one(q, name="p0"):
    q.add(make_pod(name).req(cpu_milli=100).obj())
    batch = q.pop_batch(10, timeout=0.5)
    assert len(batch) == 1
    return batch[0]


def test_pod_pop_then_each_disposition_is_clean():
    for disposition in ("done", "delete", "requeue", "unsched"):
        q = SchedulingQueue(backoff_base=0.01, backoff_max=0.02)
        with _isolated() as led:
            info = _pop_one(q)
            if disposition == "done":
                q.done(info.pod)
            elif disposition == "delete":
                q.delete(info.pod)
            elif disposition == "requeue":
                q.requeue_backoff(info)
            else:
                q.add_unschedulable(info, reason=-1)
            led.assert_quiesced(("pod",), context=disposition)
            assert led.tracked_total == 1, disposition
        q.close()


def test_pod_without_disposition_leaks():
    q = SchedulingQueue()
    with _isolated() as led:
        info = _pop_one(q)
        leaks = led.outstanding(("pod",))
        assert len(leaks) == 1
        assert pod_key(info.pod) in leaks[0]
    q.close()


def test_idempotent_done_after_requeue_is_not_a_double():
    """The production guards make a second disposition a no-op (the pod
    already left the inflight tier) — the tier-guarded hooks must agree
    and never report it as a double-discharge."""
    q = SchedulingQueue(backoff_base=0.01, backoff_max=0.02)
    with _isolated() as led:
        info = _pop_one(q)
        q.requeue_backoff(info)
        q.done(info.pod)          # informer-driven done after requeue
        q.delete(info.pod)        # and a delete on top
        assert led.double_discharge_total == 0
        led.assert_quiesced(("pod",), context="idempotent")
    q.close()


def test_regate_mid_cycle_is_the_pods_disposition():
    """Regression pin (true positive): a pod popped inflight then
    re-gated by an update must (1) count the re-gate as its disposition
    and (2) NOT be clobbered back to backoff/unsched by the failing
    cycle's later callbacks — tier stays 'gated' and the pod cannot pop
    into a solve."""
    for callback in ("requeue", "unsched"):
        q = SchedulingQueue(backoff_base=0.01, backoff_max=0.02)
        with _isolated() as led:
            info = _pop_one(q)
            key = pod_key(info.pod)
            # an update adds scheduling gates while the pod is mid-cycle
            gated = make_pod("p0").req(cpu_milli=100).obj()
            gated.spec.scheduling_gates = ["hold"]
            q.add(gated)
            assert q._tier.get(key) == "gated"
            led.assert_quiesced(("pod",), context="regate")
            # the cycle fails afterwards and fires its park callback
            if callback == "requeue":
                q.requeue_backoff(info)
            else:
                q.add_unschedulable(info, reason=-1)
            assert q._tier.get(key) == "gated", (
                f"{callback} clobbered the re-gate"
            )
            assert q.pop_batch(10, timeout=0.05) == []
            assert led.double_discharge_total == 0
        q.close()


# -- cache assume hooks ------------------------------------------------------

def _cache(ttl=30.0, clock=None):
    state = schema.ClusterState(schema.SnapshotBuilder())
    kw = {"ttl": ttl}
    if clock is not None:
        kw["clock"] = clock
    cache = SchedulerCache(state, **kw)
    cache.add_node(
        make_node("n0").capacity(cpu_milli=8000, mem=16 * GI, pods=110).obj()
    )
    return cache


def test_assume_then_forget_confirm_expire_are_clean():
    # forget
    cache = _cache()
    pod = make_pod("p0").req(cpu_milli=100).obj()
    with _isolated() as led:
        cache.assume(pod, "n0")
        assert cache.forget(pod)
        led.assert_quiesced(("assume",), context="forget")
    # confirm via informer add_pod
    cache = _cache()
    with _isolated() as led:
        cache.assume(pod, "n0")
        cache.add_pod(make_pod("p0").req(cpu_milli=100).node_name("n0").obj())
        led.assert_quiesced(("assume",), context="confirm")
    # TTL expiry
    now = [0.0]
    cache = _cache(ttl=0.5, clock=lambda: now[0])
    with _isolated() as led:
        cache.assume(pod, "n0")
        cache.finish_binding(pod)
        now[0] = 10.0
        expired = cache.cleanup_expired()
        assert [p.meta.name for p in expired] == ["p0"]
        led.assert_quiesced(("assume",), context="expire")
        assert led.double_discharge_total == 0


def test_assume_without_disposition_leaks_with_chain():
    cache = _cache()
    with _isolated() as led:
        cache.assume(make_pod("p0").req(cpu_milli=100).obj(), "n0")
        leaks = led.outstanding(("assume",))
        assert len(leaks) == 1
        assert "default/p0" in leaks[0]
        assert "cache.py" in leaks[0]  # the chain names the acquire site


def test_forget_then_remove_pod_is_not_a_double():
    """remove_pod after a forget finds no assumed entry — the guarded
    hook must not fire a second discharge."""
    cache = _cache()
    pod = make_pod("p0").req(cpu_milli=100).obj()
    with _isolated() as led:
        cache.assume(pod, "n0")
        cache.forget(pod)
        cache.remove_pod(pod)
        assert led.double_discharge_total == 0
        led.assert_quiesced(("assume",), context="forget+remove")


# -- arbiter slot hooks ------------------------------------------------------

def test_arbiter_acquire_release_is_clean():
    arb = DispatchArbiter(depth=2, timeout=0.1)
    with _isolated() as led:
        assert arb.acquire()
        assert arb.acquire()
        arb.release()
        arb.release()
        led.assert_quiesced(("slot",), context="arbiter")
        assert led.tracked_total == 2


def test_arbiter_forced_admission_still_tracks_the_slot():
    arb = DispatchArbiter(depth=1, timeout=0.0)
    with _isolated() as led:
        assert arb.acquire()
        assert arb.acquire() is False  # deadline expired: forced
        assert led.outstanding(("slot",))  # both held
        arb.release()
        arb.release()
        led.assert_quiesced(("slot",), context="forced")


def test_arbiter_masked_double_release_surfaces():
    """Regression pin: release() swallows below-zero releases to keep
    the production counter sane; the ledger hook sits BEFORE that
    guard, so the armed ledger turns the masked double-release into an
    immediate ObligationViolation."""
    arb = DispatchArbiter(depth=2, timeout=0.1)
    with _isolated() as led:
        assert arb.acquire()
        arb.release()
        with pytest.raises(ledger.ObligationViolation, match="below zero"):
            arb.release()
        assert led.double_discharge_total == 1
    # disarmed, the same double-release stays a production no-op
    arb2 = DispatchArbiter(depth=2, timeout=0.1)
    assert arb2.acquire()
    arb2.release()
    arb2.release()
    assert arb2.inflight() == 0


# -- APF seat hooks ----------------------------------------------------------

def test_seat_grant_release_is_clean_and_idempotent():
    gate = flowcontrol.APFGate(queue_wait_s=0.1)
    subject = auth.Subject("system:kube-scheduler", ("system:schedulers",))
    with _isolated() as led:
        seat = gate.acquire(subject, "list")
        assert seat is not None
        assert led.outstanding(("seat",))
        seat.release()
        led.assert_quiesced(("seat",), context="seat")
        # Seat.release is deliberately idempotent: the _released guard
        # sits ahead of the ledger hook, so a second release is silent
        seat.release()
        assert led.double_discharge_total == 0


# -- fault-registry hooks ----------------------------------------------------

def test_fault_arm_disarm_and_rearm_are_clean():
    with _isolated() as led:
        faults.arm(faults.FaultRegistry(seed=1))
        try:
            faults.arm(faults.FaultRegistry(seed=2))  # re-arm overwrites
        finally:
            faults.disarm()
        faults.disarm()  # idempotent
        led.assert_quiesced(("fault",), context="faults")
        assert led.double_discharge_total == 0


def test_fault_armed_context_discharges_on_exception():
    with _isolated() as led:
        with pytest.raises(RuntimeError):
            with faults.armed(faults.FaultRegistry(seed=3)):
                raise RuntimeError("boom")
        led.assert_quiesced(("fault",), context="armed-ctx")


def test_fault_left_armed_leaks():
    with _isolated() as led:
        faults.arm(faults.FaultRegistry(seed=4))
        try:
            leaks = led.outstanding(("fault",))
            assert len(leaks) == 1
            assert "faults.py" in leaks[0]
        finally:
            faults.disarm()
