"""Admission chain: mutate-then-validate on store writes (apiserver/pkg/
admission's position in the write path, reduced to the slice that
protects the scheduler from malformed objects)."""

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.admission import (
    AdmissionChain,
    AdmissionError,
    default_chain,
)
from kubernetes_tpu.testing.wrappers import make_node, make_pod


@pytest.fixture
def store():
    return st.Store(admission=default_chain())


def test_defaulting_fills_containers(store):
    pod = api.Pod(meta=api.ObjectMeta(name="bare"))
    pod.spec.containers = []
    created = store.create(pod)
    assert len(created.spec.containers) == 1


def test_rejects_negative_requests(store):
    with pytest.raises(AdmissionError, match="negative request"):
        store.create(make_pod("bad").req(cpu_milli=-5).obj())


def test_rejects_bad_names(store):
    with pytest.raises(AdmissionError, match="invalid name"):
        store.create(make_pod("has space").obj())
    with pytest.raises(AdmissionError, match="required"):
        store.create(make_pod("").obj())


def test_rejects_invalid_spread_and_gang(store):
    pod = make_pod("p").obj()
    pod.spec.topology_spread_constraints.append(
        api.TopologySpreadConstraint(max_skew=0)
    )
    with pytest.raises(AdmissionError, match="maxSkew"):
        store.create(pod)
    pod2 = make_pod("q").obj()
    pod2.spec.scheduling_group_size = 3  # size without group
    with pytest.raises(AdmissionError, match="without schedulingGroup"):
        store.create(pod2)


def test_rejects_invalid_node_taint(store):
    node = make_node("n").obj()
    node.spec.taints.append(api.Taint("k", "v", "Sometimes"))
    with pytest.raises(AdmissionError, match="taint effect"):
        store.create(node)


def test_update_also_admitted(store):
    store.create(make_pod("p").req(cpu_milli=100).obj())
    fresh = store.get("Pod", "p")
    fresh.spec.containers[0].requests[api.CPU] = -1
    with pytest.raises(AdmissionError):
        store.update(fresh)


def test_custom_webhook_style_plugin():
    chain = default_chain()
    chain.register_validator(
        lambda obj, op: (_ for _ in ()).throw(AdmissionError("quota"))
        if getattr(obj, "KIND", "") == "Pod"
        and obj.resource_requests().get(api.CPU, 0) > 1000
        else None
    )
    store = st.Store(admission=chain)
    store.create(make_pod("small").req(cpu_milli=500).obj())
    with pytest.raises(AdmissionError, match="quota"):
        store.create(make_pod("big").req(cpu_milli=8000).obj())
