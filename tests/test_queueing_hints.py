"""QueueingHints-lite: solver failure-reason attribution + event-scoped
requeue (VERDICT weak #3: every cluster event rescanned ALL
unschedulable pods; now only plausibly-affected ones wake).

Reference shape: internal/queue/events.go:25-89 event→plugin gvkMap,
reduced to the solver's filter stages.
"""

import numpy as np

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import assign, auction, schema
from kubernetes_tpu.scheduler.queue import QueuedPodInfo, SchedulingQueue
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _solve(nodes, pods, bound=()):
    snap, _ = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    return assign.greedy_assign(snap)


def test_reason_static():
    nodes = [make_node("n0").capacity(cpu_milli=4000).taint("k", "v").obj()]
    pods = [make_pod("p").req(cpu_milli=100).obj()]
    r = _solve(nodes, pods)
    assert int(r.reasons[0]) == assign.REASON_STATIC


def test_reason_resources():
    nodes = [make_node("n0").capacity(cpu_milli=100).obj()]
    pods = [make_pod("p").req(cpu_milli=4000).obj()]
    r = _solve(nodes, pods)
    assert int(r.reasons[0]) == assign.REASON_RESOURCES


def test_reason_spread():
    nodes = [
        make_node("n0").capacity(cpu_milli=8000, pods=110).zone("z0").obj(),
        make_node("n1").capacity(cpu_milli=100, pods=110).zone("z1").obj(),
    ]
    pods = [
        make_pod(f"p{i}")
        .req(cpu_milli=500)
        .label("app", "s")
        .spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": "s"})
        .obj()
        for i in range(4)
    ]
    r = _solve(nodes, pods)
    a = np.asarray(r.assignment)[:4]
    rs = np.asarray(r.reasons)[:4]
    assert (rs[a < 0] == assign.REASON_SPREAD).all(), rs.tolist()


def test_reason_interpod():
    nodes = [make_node("n0").capacity(cpu_milli=8000).obj()]
    bound = [make_pod("b").label("app", "x").node_name("n0").obj()]
    pods = [
        make_pod("p")
        .req(cpu_milli=100)
        .label("app", "x")
        .pod_anti_affinity({"app": "x"})
        .obj()
    ]
    r = _solve(nodes, pods, bound)
    assert int(r.reasons[0]) == assign.REASON_INTERPOD


def test_reason_placed_is_none():
    nodes = [make_node("n0").capacity(cpu_milli=4000).obj()]
    pods = [make_pod("p").req(cpu_milli=100).obj()]
    r = _solve(nodes, pods)
    assert int(r.reasons[0]) == assign.REASON_NONE


def test_auction_reasons():
    nodes = [make_node("n0").capacity(cpu_milli=1000, pods=110).obj()]
    pods = [
        make_pod(f"p{i}").req(cpu_milli=800).obj() for i in range(2)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[:2]
    rs = np.asarray(r.reasons)[:2]
    assert (a >= 0).sum() == 1
    assert rs[a < 0][0] == assign.REASON_RESOURCES


def test_event_scoped_wake():
    """AssignedPodDelete must wake resource-failed pods but NOT
    static-failed (affinity/taint) ones; NodeAdd wakes everything."""
    q = SchedulingQueue()
    res_pod = make_pod("res").obj()
    static_pod = make_pod("static").obj()
    for p in (res_pod, static_pod):
        q.add(p)
    infos = {i.pod.meta.name: i for i in q.pop_batch(10, timeout=0.2)}
    q.add_unschedulable(infos["res"], reason=assign.REASON_RESOURCES)
    q.add_unschedulable(infos["static"], reason=assign.REASON_STATIC)
    moved = q.move_for_event("AssignedPodDelete")
    assert moved == 1
    assert q.stats()["unschedulable"] == 1  # static stays parked
    moved = q.move_for_event("NodeAdd")
    assert moved == 1  # now the static one wakes too


def test_unknown_reason_always_wakes():
    q = SchedulingQueue()
    p = make_pod("u").obj()
    q.add(p)
    (info,) = q.pop_batch(10, timeout=0.2)
    q.add_unschedulable(info)  # no reason recorded
    assert q.move_for_event("AssignedPodAdd") == 1


def test_scheduler_records_reasons_end_to_end():
    """Host path: a static-failed pod parks with REASON_STATIC and pod
    churn does not wake it (bounded host work under churn)."""
    from kubernetes_tpu.scheduler import Scheduler

    store = st.Store()
    store.create(
        make_node("tainted")
        .capacity(cpu_milli=8000, mem=8 * GI, pods=10)
        .taint("dedicated", "x")
        .obj()
    )
    sched = Scheduler(store)
    sched.informers.informer("Node").start()
    sched.informers.informer("Pod").start()
    assert sched.informers.wait_for_sync(10)
    try:
        store.create(make_pod("blocked").req(cpu_milli=100).obj())
        stats = sched.schedule_batch(timeout=2)
        assert stats["unschedulable"] == 1
        info = sched.queue._unschedulable["default/blocked"]
        assert info.unschedulable_reason == assign.REASON_STATIC
        # churn: a bound pod appears and dies — the static pod stays parked
        churn = make_pod("churn").req(cpu_milli=100).node_name("tainted").obj()
        store.create(churn)
        store.delete("Pod", "churn")
        deadline = __import__("time").monotonic() + 2
        while __import__("time").monotonic() < deadline:
            if sched.queue.stats()["unschedulable"] == 1:
                pass
            __import__("time").sleep(0.05)
        assert sched.queue.stats()["unschedulable"] == 1, "static pod woke on churn"
    finally:
        sched.stop()
