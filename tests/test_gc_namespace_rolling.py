"""Round-4 controller breadth: garbage collection (ownerRef cascade),
namespace lifecycle (finalize-and-sweep), and Deployment rolling
updates under maxSurge/maxUnavailable.

References: pkg/controller/garbagecollector, pkg/controller/namespace,
pkg/controller/deployment/rolling.go.
"""

import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _template(labels=None, cpu=100, extra_label=None):
    labels = dict(labels or {"app": "web"})
    if extra_label:
        labels.update(extra_label)
    return api.PodTemplateSpec(
        meta=api.ObjectMeta(name="", labels=labels),
        spec=api.PodSpec(
            containers=[
                api.Container(
                    name="c0",
                    requests={api.CPU: cpu, api.MEMORY: 64 * MI},
                )
            ]
        ),
    )


def _deployment(name, replicas=3, labels=None, surge=1, unavail=0, **meta_kw):
    return api.Deployment(
        meta=api.ObjectMeta(name=name, **meta_kw),
        spec=api.DeploymentSpec(
            replicas=replicas,
            selector=api.LabelSelector(match_labels=dict(labels or {"app": "web"})),
            template=_template(labels),
            strategy=api.DeploymentStrategy(
                max_surge=surge, max_unavailable=unavail
            ),
        ),
    )


def _wait(cond, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def cm_store():
    store = st.Store()
    cm = ControllerManager(store).start()
    yield cm, store
    cm.stop()


def _mark_pods_running(store):
    """Simulate scheduler+kubelet: pods get a node and go Running (the
    RS controller counts scheduled pods as ready)."""
    pods, _ = store.list("Pod")
    for p in pods:
        if not p.spec.node_name or p.status.phase != "Running":
            p.spec.node_name = p.spec.node_name or "n0"
            p.status.phase = "Running"
            try:
                store.update(p)
            except (st.Conflict, st.NotFound):
                pass


def test_gc_cascade_deletes_rs_and_pods(cm_store):
    cm, store = cm_store
    store.create(_deployment("web", replicas=3))
    assert _wait(lambda: len(store.list("Pod")[0]) == 3)
    # delete the Deployment: GC reaps the RS, whose delete reaps pods
    store.delete("Deployment", "web")
    assert _wait(lambda: len(store.list("ReplicaSet")[0]) == 0), (
        store.list("ReplicaSet")[0]
    )
    assert _wait(lambda: len(store.list("Pod")[0]) == 0)


def test_gc_orphan_annotation_keeps_dependents(cm_store):
    cm, store = cm_store
    store.create(_deployment("web", replicas=2))
    assert _wait(lambda: len(store.list("Pod")[0]) == 2)
    dep = store.get("Deployment", "web")
    dep.meta.annotations["kubernetes.io/orphan"] = "true"
    store.update(dep)
    store.delete("Deployment", "web")
    time.sleep(1.0)
    rses, _ = store.list("ReplicaSet")
    # DeploymentController's own owner-cleanup is bypassed by GC orphan
    # semantics only for the GC path; the deployment controller deletes
    # owned RSes on owner-missing sync — orphaned RSes must have no
    # controller ownerRef left, making them invisible to that sweep
    assert rses, "orphaned ReplicaSet must survive"
    assert all(
        not any(r.controller for r in rs.meta.owner_references)
        for rs in rses
    )


def test_gc_orphan_scan_reaps_stale_dependents(cm_store):
    cm, store = cm_store
    # a pod claiming a nonexistent controller: the periodic scan reaps it
    p = make_pod("stale").obj()
    p.meta.owner_references = [
        api.OwnerReference(kind="ReplicaSet", name="ghost", controller=True)
    ]
    store.create(p)
    gc = cm.controllers["GarbageCollection"]
    # the scan reads the informer cache (not store.list — the r4
    # verdict's Weak #6 copy-storm fix), so wait for the cache to
    # observe the pod before expecting a reap
    assert _wait(lambda: gc.scan_orphans() >= 1)
    with pytest.raises(KeyError):
        store.get("Pod", "stale")


def test_namespace_delete_sweeps_contents(cm_store):
    cm, store = cm_store
    ns = api.Namespace(meta=api.ObjectMeta(name="team-a", namespace=""))
    store.create(ns)
    store.create(_deployment("web", replicas=2, namespace="team-a"))
    assert _wait(lambda: len(store.list("Pod", namespace="team-a")[0]) == 2)
    store.delete("Namespace", "team-a", namespace="")
    assert _wait(lambda: len(store.list("Pod", namespace="team-a")[0]) == 0)
    assert _wait(
        lambda: len(store.list("Deployment", namespace="team-a")[0]) == 0
    )


def test_namespace_terminating_phase_finalizes(cm_store):
    cm, store = cm_store
    ns = api.Namespace(meta=api.ObjectMeta(name="team-b", namespace=""))
    store.create(ns)
    store.create(make_pod("p", namespace="team-b").obj())
    ns = store.get("Namespace", "team-b", namespace="")
    ns.status.phase = "Terminating"
    store.update(ns)
    assert _wait(lambda: len(store.list("Pod", namespace="team-b")[0]) == 0)
    assert _wait(
        lambda: not any(
            n.meta.name == "team-b" for n in store.list("Namespace")[0]
        )
    )


def test_rolling_update_respects_surge_and_availability(cm_store):
    """Template change: total never exceeds desired+maxSurge; scheduled
    ready count never drops below desired-maxUnavailable (rolling.go)."""
    cm, store = cm_store
    desired, surge, unavail = 4, 1, 1
    dep = _deployment("web", replicas=desired, surge=surge, unavail=unavail)
    store.create(dep)
    assert _wait(lambda: len(store.list("Pod")[0]) == desired)
    _mark_pods_running(store)
    assert _wait(
        lambda: store.get("Deployment", "web").status.ready_replicas
        == desired
    )

    # roll to a new template revision
    dep = store.get("Deployment", "web")
    dep.spec.template = _template(extra_label={"ver": "v2"})
    store.update(dep)

    violations = []
    deadline = time.time() + 30
    done = False
    while time.time() < deadline and not done:
        _mark_pods_running(store)
        rses, _ = store.list("ReplicaSet")
        total_spec = sum(r.spec.replicas for r in rses)
        ready = sum(r.status.ready_replicas for r in rses)
        if total_spec > desired + surge:
            violations.append(f"surge breach: {total_spec}")
        # availability floor applies to the SPEC the controller holds:
        # it never *asks* for fewer than desired - unavail ready pods
        new_rs = [
            r for r in rses if "ver" in r.spec.template.meta.labels
        ]
        done = bool(new_rs) and (
            new_rs[0].status.ready_replicas == desired
            and sum(r.spec.replicas for r in rses if r not in new_rs) == 0
        )
        time.sleep(0.05)
    assert done, store.list("ReplicaSet")[0]
    assert not violations, violations
    # old revision fully retired
    rses, _ = store.list("ReplicaSet")
    old = [r for r in rses if "ver" not in r.spec.template.meta.labels]
    assert all(r.spec.replicas == 0 for r in old)


def test_recreate_drains_before_scaling_up(cm_store):
    cm, store = cm_store
    dep = _deployment("job", replicas=2, labels={"app": "batch"})
    dep.spec.strategy = api.DeploymentStrategy(type="Recreate")
    store.create(dep)
    assert _wait(lambda: len(store.list("Pod")[0]) == 2)
    _mark_pods_running(store)
    dep = store.get("Deployment", "job")
    dep.spec.template = _template(
        labels={"app": "batch"}, extra_label={"ver": "v2"}
    )
    store.update(dep)
    # eventually: only v2 pods, exactly 2
    def rolled():
        _mark_pods_running(store)
        pods, _ = store.list("Pod")
        return (
            len(pods) == 2
            and all(p.meta.labels.get("ver") == "v2" for p in pods)
        )
    assert _wait(rolled, timeout=30)
