"""Workload controllers on the informer/workqueue substrate.

Reference pattern: controllermanager.go worker loops; the e2e here is
VERDICT's acceptance: create Deployment → pods appear → scheduler binds
them → scale down → pods deleted, with workqueue backoff exercised on
injected conflicts.
"""

import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers import (
    ControllerManager,
    ReplicaSetController,
)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import GI, MI, make_node


def _template(labels=None, cpu=100):
    return api.PodTemplateSpec(
        meta=api.ObjectMeta(name="", labels=dict(labels or {"app": "web"})),
        spec=api.PodSpec(
            containers=[api.Container(requests={api.CPU: cpu, api.MEMORY: 64 * MI})]
        ),
    )


def _rs(name, replicas, labels=None):
    labels = dict(labels or {"app": "web"})
    return api.ReplicaSet(
        meta=api.ObjectMeta(name=name),
        spec=api.ReplicaSetSpec(
            replicas=replicas,
            selector=api.LabelSelector(match_labels=labels),
            template=_template(labels),
        ),
    )


def _wait(cond, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


def _owned_pods(store, kind, name):
    pods, _ = store.list("Pod")
    return [
        p
        for p in pods
        if any(
            r.controller and r.kind == kind and r.name == name
            for r in p.meta.owner_references
        )
    ]


@pytest.fixture
def manager_store():
    store = st.Store()
    mgr = ControllerManager(store).start()
    yield store, mgr
    mgr.stop()


def test_replicaset_scales_up_and_down(manager_store):
    store, _ = manager_store
    store.create(_rs("web", 3))
    assert _wait(lambda: len(_owned_pods(store, "ReplicaSet", "web")) == 3)
    rs = store.get("ReplicaSet", "web")
    rs.spec.replicas = 1
    store.update(rs)
    assert _wait(lambda: len(_owned_pods(store, "ReplicaSet", "web")) == 1)


def test_replicaset_replaces_deleted_pod(manager_store):
    store, _ = manager_store
    store.create(_rs("web", 2))
    assert _wait(lambda: len(_owned_pods(store, "ReplicaSet", "web")) == 2)
    victim = _owned_pods(store, "ReplicaSet", "web")[0]
    store.delete("Pod", victim.meta.name)
    assert _wait(
        lambda: len(_owned_pods(store, "ReplicaSet", "web")) == 2
        and all(
            p.meta.name != victim.meta.name
            for p in _owned_pods(store, "ReplicaSet", "web")
        )
    )


def test_replicaset_delete_cascades(manager_store):
    store, _ = manager_store
    store.create(_rs("web", 2))
    assert _wait(lambda: len(_owned_pods(store, "ReplicaSet", "web")) == 2)
    store.delete("ReplicaSet", "web")
    assert _wait(lambda: len(_owned_pods(store, "ReplicaSet", "web")) == 0)


def test_deployment_rollout_and_revision_change(manager_store):
    store, _ = manager_store
    dep = api.Deployment(
        meta=api.ObjectMeta(name="front"),
        spec=api.DeploymentSpec(
            replicas=2,
            selector=api.LabelSelector(match_labels={"app": "front"}),
            template=_template({"app": "front"}, cpu=100),
        ),
    )
    store.create(dep)
    assert _wait(lambda: len(_owned_pods_by_dep(store, "front")) == 2)
    rs_v1 = _deployment_rs(store, "front")
    assert len(rs_v1) == 1

    # template change → new revision RS; the rollout steps under
    # maxSurge/maxUnavailable, advancing as pods become ready — pump
    # readiness (scheduled + Running) like a kubelet would
    fresh = store.get("Deployment", "front")
    fresh.spec.template = _template({"app": "front"}, cpu=200)
    store.update(fresh)
    assert _wait(lambda: len(_deployment_rs(store, "front")) == 2)

    def _pump_ready():
        pods, _ = store.list("Pod")
        for p in pods:
            if not p.spec.node_name or p.status.phase != "Running":
                p.spec.node_name = "n0"
                p.status.phase = "Running"
                try:
                    store.update(p)
                except (st.Conflict, st.NotFound):
                    pass

    def _rolled():
        _pump_ready()
        return sorted(
            rs.spec.replicas for rs in _deployment_rs(store, "front")
        ) == [0, 2]

    assert _wait(_rolled, timeout=20)
    # pods converge to the new revision's template
    assert _wait(
        lambda: (_pump_ready() or True)
        and len(_owned_pods_by_dep(store, "front")) == 2
        and all(
            p.resource_requests()[api.CPU] == 200
            for p in _owned_pods_by_dep(store, "front")
        ),
        timeout=15,
    )


def _deployment_rs(store, name):
    rss, _ = store.list("ReplicaSet")
    return [
        r
        for r in rss
        if any(
            ref.controller and ref.kind == "Deployment" and ref.name == name
            for ref in r.meta.owner_references
        )
    ]


def _owned_pods_by_dep(store, name):
    out = []
    for rs in _deployment_rs(store, name):
        out.extend(_owned_pods(store, "ReplicaSet", rs.meta.name))
    return out


def test_job_runs_to_completion(manager_store):
    store, _ = manager_store
    job = api.Job(
        meta=api.ObjectMeta(name="batch1"),
        spec=api.JobSpec(
            parallelism=2, completions=4, template=_template({"job": "batch1"})
        ),
    )
    store.create(job)
    # at most `parallelism` active at a time
    assert _wait(lambda: len(_owned_pods(store, "Job", "batch1")) >= 2)
    for _ in range(4):
        # simulate the node agent finishing whatever is active
        assert _wait(
            lambda: any(
                p.status.phase == "Pending"
                for p in _owned_pods(store, "Job", "batch1")
            ),
            timeout=10,
        )
        active = [
            p
            for p in _owned_pods(store, "Job", "batch1")
            if p.status.phase == "Pending"
        ]
        p = active[0]
        p.status.phase = "Succeeded"
        store.update(p, force=True)
        time.sleep(0.05)
    assert _wait(
        lambda: store.get("Job", "batch1").status.succeeded >= 4, timeout=10
    )
    assert store.get("Job", "batch1").status.completion_time is not None


def test_e2e_deployment_scheduler_binds_then_scales_down():
    """The VERDICT acceptance: Deployment → pods appear → host scheduler
    binds them through the API → scale down deletes pods and the
    scheduler cache unaccounts them."""
    store = st.Store()
    for i in range(4):
        store.create(
            make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=10).obj()
        )
    mgr = ControllerManager(store).start()
    sched = Scheduler(store)
    sched.informers.informer("Node").start()
    sched.informers.informer("Pod").start()
    assert sched.informers.wait_for_sync(10)
    try:
        store.create(
            api.Deployment(
                meta=api.ObjectMeta(name="api"),
                spec=api.DeploymentSpec(
                    replicas=6,
                    selector=api.LabelSelector(match_labels={"app": "api"}),
                    template=_template({"app": "api"}, cpu=500),
                ),
            )
        )
        deadline = time.monotonic() + 20
        bound = []
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            bound = [p for p in _owned_pods_by_dep(store, "api") if p.spec.node_name]
            if len(bound) == 6:
                break
        assert len(bound) == 6, f"only {len(bound)} bound"
        # scale down; controller deletes pods; cache unaccounts them
        fresh = store.get("Deployment", "api")
        fresh.spec.replicas = 2
        store.update(fresh)
        assert _wait(
            lambda: len(_owned_pods_by_dep(store, "api")) == 2, timeout=10
        )
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.05)
            if len(sched.tpu.state._pods) == 2:
                break
        assert len(sched.tpu.state._pods) == 2
    finally:
        sched.stop()
        mgr.stop()


def test_workqueue_backoff_on_conflict():
    """Injected Conflict from the store exercises the rate-limited
    requeue path: the sync eventually succeeds."""
    store = st.Store()
    informers_calls = {"n": 0}

    class FlakyRS(ReplicaSetController):
        def sync(self, key):
            informers_calls["n"] += 1
            if informers_calls["n"] < 3:
                raise st.Conflict("injected")
            return super().sync(key)

    from kubernetes_tpu.client.informers import InformerFactory

    factory = InformerFactory(store)
    ctrl = FlakyRS(store, factory)
    for kind in ("Pod", "ReplicaSet"):
        factory.informer(kind).start()
    factory.wait_for_sync()
    ctrl.start()
    try:
        store.create(_rs("flaky", 1))
        assert _wait(
            lambda: len(_owned_pods(store, "ReplicaSet", "flaky")) == 1,
            timeout=10,
        )
        assert informers_calls["n"] >= 3
    finally:
        ctrl.stop()
        factory.stop()


def test_no_reconcile_hot_loop(manager_store):
    """Status writes are change-gated: a converged workload must not
    MODIFIED-event itself into a permanent reconcile loop (review
    finding).  After convergence the store's resourceVersion settles."""
    store, _ = manager_store
    store.create(_rs("calm", 2))
    assert _wait(lambda: len(_owned_pods(store, "ReplicaSet", "calm")) == 2)
    time.sleep(0.3)  # let status writes settle
    rv1 = store.get("ReplicaSet", "calm").meta.resource_version
    time.sleep(1.0)
    rv2 = store.get("ReplicaSet", "calm").meta.resource_version
    assert rv1 == rv2, "ReplicaSet kept self-updating after convergence"


def test_no_overcreation_under_informer_lag(manager_store):
    """Expectations hold back re-creation until informer observation —
    the pod count must never overshoot replicas (review finding)."""
    store, _ = manager_store
    store.create(_rs("burst", 5))
    peak = 0
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        n = len(_owned_pods(store, "ReplicaSet", "burst"))
        peak = max(peak, n)
        if n == 5 and time.monotonic() > deadline - 3:
            break
        time.sleep(0.005)
    assert peak <= 5, f"over-created: peak={peak}"
    assert len(_owned_pods(store, "ReplicaSet", "burst")) == 5


def test_rs_ready_replicas_updates_after_binding(manager_store):
    """ready_replicas must refresh when pods get scheduled AFTER the
    replica count already converged (review finding)."""
    store, _ = manager_store
    store.create(_rs("ready", 2))
    assert _wait(lambda: len(_owned_pods(store, "ReplicaSet", "ready")) == 2)
    for p in _owned_pods(store, "ReplicaSet", "ready"):
        fresh = store.get("Pod", p.meta.name)
        fresh.spec.node_name = "n0"
        store.update(fresh)
    assert _wait(
        lambda: store.get("ReplicaSet", "ready").status.ready_replicas == 2
    )


def test_nodelifecycle_taints_and_evicts_silent_node():
    """monitorNodeHealth analogue: a node that stops heartbeating gets
    the unreachable:NoExecute taint, its pods are evicted and reschedule
    elsewhere; a resumed heartbeat clears the taint."""
    from kubernetes_tpu.client.informers import InformerFactory
    from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing.wrappers import GI

    store = st.Store()
    for name in ("alive", "silent"):
        store.create(
            make_node(name).capacity(cpu_milli=4000, mem=8 * GI, pods=10).obj()
        )
    factory = InformerFactory(store)
    ctrl = NodeLifecycleController(
        store, factory, grace_period=0.5, sweep_interval=0.1
    )
    for kind in ("Node", "Pod"):
        factory.informer(kind).start()
    factory.wait_for_sync()
    ctrl.start()
    sched = Scheduler(store)
    sched.informers.informer("Node").start()
    sched.informers.informer("Pod").start()
    assert sched.informers.wait_for_sync(10)
    try:
        # pin a pod to 'silent' via the RS-free path: bind directly
        victim = api.Pod(
            meta=api.ObjectMeta(name="victim"),
            spec=api.PodSpec(
                containers=[api.Container(requests={api.CPU: 100})],
                node_name="silent",
            ),
        )
        store.create(victim)
        # keep 'alive' heartbeating; let 'silent' go stale
        deadline = time.monotonic() + 10
        tainted = False
        while time.monotonic() < deadline and not tainted:
            n = store.get("Node", "alive", namespace="")
            n.meta.annotations["hb"] = str(time.monotonic())  # heartbeat
            store.update(n, force=True)
            node = store.get("Node", "silent", namespace="")
            tainted = any(
                t.key == api.TAINT_NODE_UNREACHABLE for t in node.spec.taints
            )
            time.sleep(0.1)
        assert tainted, "silent node never tainted"
        # the pod was evicted
        assert _wait(
            lambda: not any(
                p.meta.name == "victim" for p in store.list("Pod")[0]
            ),
            timeout=5,
        )
        # heartbeat resumes: taint clears
        deadline = time.monotonic() + 10
        cleared = False
        while time.monotonic() < deadline and not cleared:
            n = store.get("Node", "silent", namespace="")
            n.meta.annotations["hb"] = str(time.monotonic())
            store.update(n, force=True)  # resumed heartbeat
            n = store.get("Node", "silent", namespace="")
            cleared = not any(
                t.key == api.TAINT_NODE_UNREACHABLE for t in n.spec.taints
            )
            time.sleep(0.1)
        assert cleared, "taint never cleared after heartbeat resumed"
    finally:
        sched.stop()
        ctrl.stop()
        factory.stop()


def test_nodelifecycle_taint_does_not_flap():
    """The controller's own taint write must not count as a heartbeat —
    a silent node stays tainted (review finding: taint flapped on/off)."""
    from kubernetes_tpu.client.informers import InformerFactory
    from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController

    store = st.Store()
    store.create(make_node("dead").capacity(cpu_milli=4000).obj())
    factory = InformerFactory(store)
    ctrl = NodeLifecycleController(
        store, factory, grace_period=0.3, sweep_interval=0.05
    )
    for kind in ("Node", "Pod"):
        factory.informer(kind).start()
    factory.wait_for_sync()
    ctrl.start()
    try:
        assert _wait(
            lambda: any(
                t.key == api.TAINT_NODE_UNREACHABLE
                for t in store.get("Node", "dead", namespace="").spec.taints
            ),
            timeout=5,
        )
        # stays tainted across many sweeps
        for _ in range(10):
            time.sleep(0.1)
            assert any(
                t.key == api.TAINT_NODE_UNREACHABLE
                for t in store.get("Node", "dead", namespace="").spec.taints
            ), "taint flapped off a silent node"
    finally:
        ctrl.stop()
        factory.stop()


def test_disruption_controller_maintains_pdb_status():
    """pkg/controller/disruption: status tracks matching pods' health;
    disruptionsAllowed = healthy - desired."""
    from kubernetes_tpu.client.informers import InformerFactory
    from kubernetes_tpu.controllers.disruption import DisruptionController
    from kubernetes_tpu.testing.wrappers import make_pod

    store = st.Store()
    informers = InformerFactory(store)
    ctrl = DisruptionController(store, informers, workers=1)
    for kind in ("Pod", "PodDisruptionBudget"):
        informers.informer(kind).start()
    assert informers.wait_for_sync(10)
    ctrl.start()
    try:
        pdb = api.PodDisruptionBudget(
            meta=api.ObjectMeta(name="web-pdb"),
            spec=api.PodDisruptionBudgetSpec(
                selector=api.LabelSelector(match_labels={"app": "web"}),
                min_available=2,
            ),
        )
        store.create(pdb)
        for i in range(3):
            p = make_pod(f"w{i}").labels(app="web").node_name("n0").obj()
            p.status.phase = "Running"
            store.create(p)
        deadline = time.time() + 10
        got = None
        while time.time() < deadline:
            got = store.get("PodDisruptionBudget", "web-pdb")
            if got.status.expected_pods == 3:
                break
            time.sleep(0.05)
        assert got.status.expected_pods == 3
        assert got.status.current_healthy == 3
        assert got.status.desired_healthy == 2
        assert got.status.disruptions_allowed == 1
        # one pod dies: allowance drops to 0
        store.delete("Pod", "w0")
        deadline = time.time() + 10
        while time.time() < deadline:
            got = store.get("PodDisruptionBudget", "web-pdb")
            if got.status.disruptions_allowed == 0 and got.status.expected_pods == 2:
                break
            time.sleep(0.05)
        assert got.status.disruptions_allowed == 0
        assert got.status.current_healthy == 2
    finally:
        ctrl.stop()
        informers.stop()
