"""graftsched (kubernetes_tpu/analysis/interleave.py + scenarios.py) —
the deterministic interleaving explorer and its scenario library.

Three layers:

  * explorer unit tests: seed-replay determinism, virtual-timeout
    semantics, deadlock detection, managed thread spawn/join, policy
    behavior;
  * scenario smoke (tier-1, `interleave and not slow`): a few seeded
    schedules per scenario with every invariant oracle armed;
  * deep sweeps (`make race`, marked slow): 200+ distinct schedules
    per scenario across both policies, plus full-trace replay checks.

Plus the regression pins for the true positives graftsched surfaced:
the silent watch-fan-out batch drop (fixed in Store._fan_out) and the
if-guarded dispatcher cv-wait (fixed in _watch_dispatch_loop; the
static pin lives in tests/test_static_analysis.py).
"""

import logging
import threading

import pytest

from kubernetes_tpu.analysis import interleave as il
from kubernetes_tpu.analysis import scenarios as scn
from kubernetes_tpu.testing import faults

pytestmark = pytest.mark.interleave

SMOKE_SEEDS = range(3)


# -- explorer unit tests -----------------------------------------------------


def _counter_scenario(seed, policy="random"):
    ex = il.Explorer(seed=seed, policy=policy)
    with ex.installed():
        lock = threading.Lock()
        state = {"n": 0}

        def worker():
            for _ in range(3):
                with lock:
                    state["n"] += 1

        ex.spawn(worker, name="w1")
        ex.spawn(worker, name="w2")
        ex.drive()
        assert state["n"] == 6
    return ex.trace


def test_seed_replay_identical_trace():
    assert _counter_scenario(1) == _counter_scenario(1)
    assert _counter_scenario(5, "pct") == _counter_scenario(5, "pct")


def test_seeds_explore_distinct_schedules():
    traces = {tuple(_counter_scenario(s)) for s in range(8)}
    assert len(traces) > 1, "every seed produced the same schedule"


def test_timed_wait_can_fire_as_timeout_and_as_notify():
    """Across seeds, the explorer must exercise BOTH outcomes of a
    timed Condition.wait: notified (True) and timed out (False)."""
    outcomes = set()
    for seed in range(20):
        ex = il.Explorer(seed=seed)
        with ex.installed():
            cv = threading.Condition()
            got = {}

            def waiter():
                with cv:
                    got["r"] = cv.wait(0.25)

            def notifier():
                with cv:
                    cv.notify()

            ex.spawn(waiter, name="waiter")
            ex.spawn(notifier, name="notifier")
            ex.drive()
        outcomes.add(got["r"])
    assert outcomes == {True, False}, outcomes


def test_untimed_wait_without_notifier_is_deadlock():
    ex = il.Explorer(seed=0)
    with pytest.raises(il.DeadlockError):
        with ex.installed():
            cv = threading.Condition()

            def waiter():
                with cv:
                    cv.wait()  # untimed, nobody will notify

            ex.spawn(waiter, name="waiter")
            ex.drive()


def test_abba_deadlock_detected_with_trace():
    import time

    found = 0
    for seed in range(20):
        ex = il.Explorer(seed=seed)
        try:
            with ex.installed():
                a, b = threading.Lock(), threading.Lock()

                def one():
                    with a:
                        time.sleep(0.01)
                        with b:
                            pass

                def two():
                    with b:
                        time.sleep(0.01)
                        with a:
                            pass

                ex.spawn(one, name="t1")
                ex.spawn(two, name="t2")
                ex.drive()
        except il.DeadlockError as e:
            assert "acquire" in str(e)
            found += 1
    assert found > 0, "no schedule drove the AB/BA window"


def test_managed_thread_spawn_and_cooperative_join():
    ex = il.Explorer(seed=3)
    with ex.installed():
        order = []
        lock = threading.Lock()

        def child():
            with lock:
                order.append("child")

        def parent():
            t = threading.Thread(target=child, daemon=True)
            t.start()
            t.join()
            with lock:
                order.append("parent")

        ex.spawn(parent, name="parent")
        ex.drive()
        assert order == ["child", "parent"]


def test_faults_fire_sites_are_yield_points():
    ex = scn.run_schedule(scn.SCENARIOS["writers_vs_dispatch"], seed=0)
    labels = {lbl for _, _, lbl in ex.trace}
    assert any(lbl.startswith("fault:") for lbl in labels), labels


def test_virtual_clock_advances_on_sleep_and_timeout():
    ex = il.Explorer(seed=0)
    with ex.installed():
        import time

        stamps = {}

        def sleeper():
            t0 = time.monotonic()
            time.sleep(1.5)
            stamps["dt"] = time.monotonic() - t0

        ex.spawn(sleeper, name="sleeper")
        ex.drive()
    assert stamps["dt"] >= 1.5


def test_mirror_metrics_reconciles_with_collectors():
    from kubernetes_tpu.perf.collectors import MetricsCollector
    from kubernetes_tpu.scheduler.metrics import Registry

    # ensure at least one schedule has been counted in this session
    scn.run_schedule(scn.SCENARIOS["subwave_vs_fencing"], seed=0)
    reg = Registry()
    il.mirror_metrics(reg, atomicity_findings=0)
    assert reg.interleave_schedules_total.total >= 1
    assert reg.interleave_yield_points.total >= 1
    names = {
        item["labels"]["Metric"] for item in MetricsCollector(reg).collect()
    }
    assert "scheduler_interleave_schedules_total" in names
    assert "scheduler_interleave_yield_points" in names


# -- scenario smoke (tier-1) -------------------------------------------------


@pytest.mark.parametrize("name", sorted(scn.SCENARIOS))
def test_scenario_smoke(name):
    logging.disable(logging.ERROR)
    try:
        for seed in SMOKE_SEEDS:
            scn.run_schedule(scn.SCENARIOS[name], seed)
    finally:
        logging.disable(logging.NOTSET)


def test_scenario_replay_on_real_store():
    a = scn.run_schedule(scn.SCENARIOS["writers_vs_dispatch"], seed=7)
    b = scn.run_schedule(scn.SCENARIOS["writers_vs_dispatch"], seed=7)
    assert a.trace == b.trace
    assert a.steps == b.steps


# -- regression pins ---------------------------------------------------------


def test_fanout_poison_offer_expires_watcher_not_silent_loss():
    """True positive pinned: a fail-grade fault inside Watch._offer used
    to unwind the whole fan-out batch — every remaining watcher lost the
    rest of the batch with NO Expired signal, so informer caches went
    stale forever.  Post-fix the poisoned watcher expires (bookmark +
    relist) and every schedule converges; pre-fix no seed here did."""
    logging.disable(logging.ERROR)
    try:
        for seed in SMOKE_SEEDS:
            ex = scn.run_schedule(
                scn.SCENARIOS["writers_vs_dispatch_faulted"], seed
            )
            assert ex.steps > 0
    finally:
        logging.disable(logging.NOTSET)


def test_fanout_poison_offer_direct_real_threads():
    """The same pin without the explorer: real store, real fan-out
    thread, one fail(watch.offer) — the watcher must EXPIRE, not stay
    silently starved."""
    import time

    from kubernetes_tpu.api import store as st
    from kubernetes_tpu.api import types as api

    logging.disable(logging.ERROR)
    try:
        with faults.armed(faults.FaultRegistry(0).fail("watch.offer", n=1)):
            store = st.Store(shards=1)
            w = store.watch("Pod")
            store.create(api.Pod(meta=api.ObjectMeta(name="p0")))
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                with w._mu:
                    if w.expired:
                        break
                time.sleep(0.01)
            with w._mu:
                assert w.expired, (
                    "poisoned offer neither delivered nor expired the "
                    "watcher — silent event loss"
                )
    finally:
        logging.disable(logging.NOTSET)


def test_notify_consumed_by_timed_out_waiter_is_survivable():
    """The explorer models CPython's lost-wakeup window (a notify landing
    on a waiter that already timed out internally is WASTED).  A
    predicate-loop consumer must survive it; this drives the window
    explicitly across seeds."""
    for seed in range(10):
        ex = il.Explorer(seed=seed)
        with ex.installed():
            cv = threading.Condition()
            box = {"ready": False, "woke": 0}

            def producer():
                with cv:
                    box["ready"] = True
                    cv.notify()  # may land on a timed-out waiter

            def consumer():
                with cv:
                    while not box["ready"]:
                        cv.wait(0.2)
                box["woke"] += 1

            ex.spawn(consumer, name="c1")
            ex.spawn(consumer, name="c2")
            ex.spawn(producer, name="p")
            ex.drive()
            assert box["woke"] == 2


# -- deep sweeps (make race) -------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(scn.SCENARIOS))
def test_scenario_deep_sweep(name):
    """ISSUE acceptance: >= 200 distinct schedules per scenario with
    every invariant oracle green (100 seeds x random/pct)."""
    logging.disable(logging.ERROR)
    try:
        stats = scn.explore(
            scn.SCENARIOS[name], seeds=range(100),
            policies=("random", "pct"),
        )
    finally:
        logging.disable(logging.NOTSET)
    assert stats["schedules"] == 200
    assert stats["yield_points"] > 0


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(scn.SCENARIOS))
def test_scenario_deep_seed_replay(name):
    """Full seed-replay determinism on sampled seeds of every scenario:
    same seed + policy => byte-identical schedule trace."""
    logging.disable(logging.ERROR)
    try:
        for policy in ("random", "pct"):
            for seed in (0, 13):
                a = scn.run_schedule(
                    scn.SCENARIOS[name], seed, policy=policy
                )
                b = scn.run_schedule(
                    scn.SCENARIOS[name], seed, policy=policy
                )
                assert a.trace == b.trace, (
                    f"{name} seed={seed} policy={policy} diverged"
                )
    finally:
        logging.disable(logging.NOTSET)
