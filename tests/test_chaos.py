"""Chaos suite: randomized seeded fault schedules over full cluster runs.

Each seed derives a bounded fault plan covering EVERY registered fault
point (testing/faults.py) — device-solve failures and score corruption,
binder commit failures/crashes, wave-transaction faults, journal
torn/failed appends and fsyncs, watch-queue drops, lease-renew failures,
latency — then runs a live Scheduler (informers + hot loop + leader
election + journal) through a pod burst and asserts the pipeline
invariants:

  * no pod lost: every pod ends bound within the bounded quiesce window
    (faults are bounded, so the system must heal);
  * bound exactly once: no pod is ever committed to two different nodes;
  * resourceVersion stays strictly monotonic across every committed
    event (per-object and wave paths both);
  * the assume set drains to empty at quiesce (no phantom usage);
  * the journal replays without error and is prefix-consistent with the
    live store (a replayed binding never disagrees with the final one).

Marked `chaos` (and `slow`): excluded from tier-1, run via `make chaos`
or `python -m pytest -m chaos`.
"""

import random
import threading
import time
from collections import defaultdict

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.client.leaderelection import LeaderElector
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.config import SchedulerConfiguration
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

SEEDS = list(range(20))  # the fixed seed matrix (make chaos)


def _fault_plan(rng: random.Random) -> faults.FaultRegistry:
    """A bounded randomized schedule at every registered point: the
    system must absorb all of it and still satisfy the invariants."""
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    reg.fail("batch.solve", n=rng.randint(1, 2))
    if rng.random() < 0.5:
        reg.corrupt("batch.solve", n=1)
    reg.fail("binder.commit_wave", n=rng.randint(1, 2))
    if rng.random() < 0.5:
        reg.crash("binder.commit_wave", n=1)
    reg.delay("binder.commit_wave", seconds=0.01, n=2)
    reg.fail("store.update_wave", n=rng.randint(1, 2))
    reg.fail("store.journal.append", n=rng.randint(1, 2), probability=0.5)
    reg.torn_write("store.journal.append", frac=rng.random(), n=1)
    reg.fail("store.journal.fsync", n=1)
    reg.drop("watch.offer", n=rng.randint(1, 3), probability=0.5)
    reg.fail("leader.renew", n=rng.randint(1, 2))
    return reg


class _EventAudit:
    """Shims the store's two dispatch paths to audit every committed
    event: rv monotonicity and per-pod bound-node history."""

    def __init__(self, store: st.Store):
        self.violations = []
        self.bound_nodes = defaultdict(set)
        self._last_rv = 0
        self._lock = threading.Lock()
        orig_dispatch = store._dispatch
        orig_wave = store._dispatch_wave

        def check(ev):
            with self._lock:
                if ev.rv <= self._last_rv:
                    self.violations.append(
                        f"rv {ev.rv} after {self._last_rv} not monotonic"
                    )
                self._last_rv = max(self._last_rv, ev.rv)
                if ev.kind == "Pod" and ev.obj.spec.node_name:
                    key = f"{ev.obj.meta.namespace}/{ev.obj.meta.name}"
                    self.bound_nodes[key].add(ev.obj.spec.node_name)

        def dispatch(ev):
            check(ev)
            orig_dispatch(ev)

        def dispatch_wave(kind, events):
            for ev in events:
                check(ev)
            orig_wave(kind, events)

        store._dispatch = dispatch
        store._dispatch_wave = dispatch_wave


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_pipeline_invariants(seed, tmp_path):
    rng = random.Random(seed)
    reg = _fault_plan(rng)
    path = str(tmp_path / "journal.jsonl")
    store = st.Store(journal_path=path)
    audit = _EventAudit(store)

    n_nodes = rng.randint(4, 8)
    for i in range(n_nodes):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .zone(f"z{i % 3}")
            .obj()
        )
    elector = LeaderElector(
        store, "chaos-sched", f"holder-{seed}",
        lease_duration=1.0, renew_period=0.05,
    ).start()
    config = SchedulerConfiguration(
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        unschedulable_flush_seconds=0.5,
    )
    sched = Scheduler(
        store, assume_ttl=1.0, leader_elector=elector, config=config
    )
    n_pods = rng.randint(20, 40)
    try:
        with faults.armed(reg):
            sched.start()
            assert elector.wait_for_leadership(10)
            for i in range(n_pods):
                spec = make_pod(f"p{i}").req(
                    cpu_milli=rng.choice([50, 100, 200]),
                    mem=rng.choice([GI // 4, GI // 2]),
                )
                if rng.random() < 0.2:
                    spec = spec.label("app", f"g{i % 3}")
                store.create(spec.obj())
                if rng.random() < 0.3:
                    time.sleep(rng.random() * 0.01)
            # bounded quiesce: the plan is bounded, so the pipeline must
            # heal and place every pod well inside the deadline
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                pods, _ = store.list("Pod")
                if pods and all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.1)

        # -- invariants (faults disarmed; residual schedules drained) ----
        assert reg.fired, f"seed {seed}: no fault ever fired"
        pods, _ = store.list("Pod")
        assert len(pods) == n_pods
        unbound = [p.meta.name for p in pods if not p.spec.node_name]
        assert not unbound, (
            f"seed {seed}: pods lost/wedged past bounded quiesce: {unbound}\n"
            f"  tiers: {({n: sched.queue._tier.get(f'default/{n}') for n in unbound})}\n"
            f"  queue: {sched.queue.stats()}\n"
            f"  assumed: {list(sched.cache._assumed)}\n"
            f"  breaker: {sched.tpu.breaker.state} "
            f"fallbacks={sched.tpu.breaker.fallbacks}\n"
            f"  binder alive={sched._bind_thread.is_alive()} "
            f"waves={len(sched._waves)} active={sched._wave_active}\n"
            f"  sched alive={sched._thread.is_alive()} "
            f"leader={elector.is_leader()}\n"
            f"  fired={reg.fired} pending={reg.pending()}\n"
            f"  watchers_terminated={store.watchers_terminated}"
        )
        assert not audit.violations, f"seed {seed}: {audit.violations[:5]}"
        rebound = {
            k: nodes for k, nodes in audit.bound_nodes.items()
            if len(nodes) > 1
        }
        assert not rebound, f"seed {seed}: double binds {rebound}"
        assert sched.flush_binds(15)
        # assume set drains once the informer confirms every bind
        deadline = time.monotonic() + 10
        while sched.cache.assumed_count() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sched.cache.assumed_count() == 0, (
            f"seed {seed}: assume set not empty at quiesce"
        )
    finally:
        faults.disarm()
        sched.stop()
        elector.stop()

    # -- journal: replays clean and prefix-consistent with the live store
    live = {
        f"{p.meta.namespace}/{p.meta.name}": p.spec.node_name
        for p in store.list("Pod")[0]
    }
    replayed = st.Store(journal_path=path)  # must not raise
    for p in replayed.list("Pod")[0]:
        key = f"{p.meta.namespace}/{p.meta.name}"
        assert key in live, f"seed {seed}: journal invented pod {key}"
        assert p.spec.node_name in ("", live[key]), (
            f"seed {seed}: journal binding {p.spec.node_name!r} "
            f"contradicts live {live[key]!r} for {key}"
        )
