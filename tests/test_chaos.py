"""Chaos suite: randomized seeded fault schedules over full cluster runs.

Each seed derives a bounded fault plan covering EVERY registered fault
point (testing/faults.py) — device-solve failures and score corruption,
binder commit failures/crashes, wave-transaction faults, journal
torn/failed appends and fsyncs, watch-queue drops, lease-renew failures,
latency — then runs a live Scheduler (informers + hot loop + leader
election + journal) through a pod burst and asserts the pipeline
invariants:

  * no pod lost: every pod ends bound within the bounded quiesce window
    (faults are bounded, so the system must heal);
  * bound exactly once: no pod is ever committed to two different nodes;
  * resourceVersion stays strictly monotonic across every committed
    event (per-object and wave paths both);
  * the assume set drains to empty at quiesce (no phantom usage);
  * the journal replays without error and is prefix-consistent with the
    live store (a replayed binding never disagrees with the final one).

Marked `chaos` (and `slow`): excluded from tier-1, run via `make chaos`
or `python -m pytest -m chaos`.
"""

import random
import threading
import time
from collections import defaultdict

import pytest

from kubernetes_tpu.analysis import ledger
from kubernetes_tpu.api import store as st
from kubernetes_tpu.client.leaderelection import LeaderElector
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.config import SchedulerConfiguration
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

SEEDS = list(range(20))  # the fixed seed matrix (make chaos)


def _fault_plan(rng: random.Random) -> faults.FaultRegistry:
    """A bounded randomized schedule at every registered point: the
    system must absorb all of it and still satisfy the invariants."""
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    reg.fail("batch.solve", n=rng.randint(1, 2))
    if rng.random() < 0.5:
        reg.corrupt("batch.solve", n=1)
    reg.fail("binder.commit_wave", n=rng.randint(1, 2))
    if rng.random() < 0.5:
        reg.crash("binder.commit_wave", n=1)
    reg.delay("binder.commit_wave", seconds=0.01, n=2)
    reg.fail("store.update_wave", n=rng.randint(1, 2))
    reg.fail("store.journal.append", n=rng.randint(1, 2), probability=0.5)
    reg.torn_write("store.journal.append", frac=rng.random(), n=1)
    reg.fail("store.journal.fsync", n=1)
    # the per-shard twins: land on whichever shard reaches the point
    # first (the store is sharded by default, so every base seed also
    # exercises single-shard fault containment)
    reg.fail("store.shard.update_wave", n=1, probability=0.5)
    reg.fail("store.shard.journal.append", n=1, probability=0.5)
    reg.drop("watch.offer", n=rng.randint(1, 3), probability=0.5)
    reg.delay("watch.consume", seconds=0.002, n=5, probability=0.5)
    reg.delay("store.list", seconds=0.005, n=3, probability=0.5)
    reg.fail("leader.renew", n=rng.randint(1, 2))
    # the batched-preemption point is registered here for coverage; base
    # seeds never reach PostFilter (every pod fits), so the dedicated
    # PREEMPT_SEEDS below are where it actually fires
    reg.fail("batch.preemption", n=1, probability=0.5)
    # likewise the gang carve-out point: base seeds carry no shaped
    # gangs, so CARVEOUT_SEEDS (600-604) are where it actually fires
    reg.fail("solve.carveout", n=1, probability=0.5)
    # the incremental-solve partials sync fires on every warm encode —
    # a fail-grade fault here degrades that batch to a cold solve
    # (contained inside encode); the CORRUPT poison-and-heal family is
    # PARTIALS_SEEDS (700-704)
    reg.fail("solve.partials", n=1, probability=0.5)
    # the elastic-axis resident resize: base seeds hold a fixed node set
    # (no pad-bucket crossings), so NODE_CHURN_SEEDS (800-804) are where
    # it actually fires; registered here for point coverage
    reg.fail("mirror.grow", n=1, probability=0.5)
    return reg


def _ledger_quiesced(seed) -> None:
    """GRAFTLINT_OBLIGATIONS=1 upgrade of the end-state assertions: at
    this point every pod is bound, binds are flushed and the assume set
    has drained, so the scheduler-side obligation kinds must all be
    discharged — and a failure names the acquiring call chain instead
    of a bare nonzero count.  Seats and store fan-out are excluded on
    purpose: the serving plane is still live here (lease renewals keep
    dispatching), so those kinds quiesce only at session teardown
    (conftest assert_clean) and under bench's full-drain gates."""
    led = ledger.active()
    if led is None:
        return
    led.assert_quiesced(
        ("pod", "assume", "slot", "stream_inflight"),
        context=f"seed {seed}",
    )


class _EventAudit:
    """Shims the store's two dispatch paths to audit every committed
    event: rv monotonicity and per-pod bound-node history."""

    def __init__(self, store: st.Store):
        self.violations = []
        self.bound_nodes = defaultdict(set)
        self._last_rv = 0
        self._lock = threading.Lock()
        orig_dispatch = store._dispatch
        orig_wave = store._dispatch_wave

        def check(ev):
            with self._lock:
                if ev.rv <= self._last_rv:
                    self.violations.append(
                        f"rv {ev.rv} after {self._last_rv} not monotonic"
                    )
                self._last_rv = max(self._last_rv, ev.rv)
                if ev.kind == "Pod" and ev.obj.spec.node_name:
                    key = f"{ev.obj.meta.namespace}/{ev.obj.meta.name}"
                    self.bound_nodes[key].add(ev.obj.spec.node_name)

        def dispatch(ev):
            check(ev)
            orig_dispatch(ev)

        def dispatch_wave(kind, events):
            for ev in events:
                check(ev)
            orig_wave(kind, events)

        store._dispatch = dispatch
        store._dispatch_wave = dispatch_wave


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_pipeline_invariants(seed, tmp_path):
    rng = random.Random(seed)
    reg = _fault_plan(rng)
    path = str(tmp_path / "journal.jsonl")
    store = st.Store(journal_path=path)
    audit = _EventAudit(store)

    n_nodes = rng.randint(4, 8)
    for i in range(n_nodes):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .zone(f"z{i % 3}")
            .obj()
        )
    elector = LeaderElector(
        store, "chaos-sched", f"holder-{seed}",
        lease_duration=1.0, renew_period=0.05,
    ).start()
    config = SchedulerConfiguration(
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        unschedulable_flush_seconds=0.5,
    )
    sched = Scheduler(
        store, assume_ttl=1.0, leader_elector=elector, config=config
    )
    n_pods = rng.randint(20, 40)
    try:
        with faults.armed(reg):
            sched.start()
            assert elector.wait_for_leadership(10)
            for i in range(n_pods):
                spec = make_pod(f"p{i}").req(
                    cpu_milli=rng.choice([50, 100, 200]),
                    mem=rng.choice([GI // 4, GI // 2]),
                )
                if rng.random() < 0.2:
                    spec = spec.label("app", f"g{i % 3}")
                store.create(spec.obj())
                if rng.random() < 0.3:
                    time.sleep(rng.random() * 0.01)
            # bounded quiesce: the plan is bounded, so the pipeline must
            # heal and place every pod well inside the deadline
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                pods, _ = store.list("Pod")
                if pods and all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.1)

        # -- invariants (faults disarmed; residual schedules drained) ----
        assert reg.fired, f"seed {seed}: no fault ever fired"
        pods, _ = store.list("Pod")
        assert len(pods) == n_pods
        unbound = [p.meta.name for p in pods if not p.spec.node_name]
        assert not unbound, (
            f"seed {seed}: pods lost/wedged past bounded quiesce: {unbound}\n"
            f"  tiers: {({n: sched.queue._tier.get(f'default/{n}') for n in unbound})}\n"
            f"  queue: {sched.queue.stats()}\n"
            f"  assumed: {list(sched.cache._assumed)}\n"
            f"  breaker: {sched.tpu.breaker.state} "
            f"fallbacks={sched.tpu.breaker.fallbacks}\n"
            f"  binder alive={sched._bind_thread.is_alive()} "
            f"waves={len(sched._waves)} active={sched._wave_active}\n"
            f"  sched alive={sched._thread.is_alive()} "
            f"leader={elector.is_leader()}\n"
            f"  fired={reg.fired} pending={reg.pending()}\n"
            f"  watchers_terminated={store.watchers_terminated}"
        )
        assert not audit.violations, f"seed {seed}: {audit.violations[:5]}"
        rebound = {
            k: nodes for k, nodes in audit.bound_nodes.items()
            if len(nodes) > 1
        }
        assert not rebound, f"seed {seed}: double binds {rebound}"
        assert sched.flush_binds(15)
        # assume set drains once the informer confirms every bind
        deadline = time.monotonic() + 10
        while sched.cache.assumed_count() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sched.cache.assumed_count() == 0, (
            f"seed {seed}: assume set not empty at quiesce"
        )
        _ledger_quiesced(seed)
    finally:
        faults.disarm()
        sched.stop()
        elector.stop()

    # -- journal: replays clean and prefix-consistent with the live store
    live = {
        f"{p.meta.namespace}/{p.meta.name}": p.spec.node_name
        for p in store.list("Pod")[0]
    }
    replayed = st.Store(journal_path=path)  # must not raise
    for p in replayed.list("Pod")[0]:
        key = f"{p.meta.namespace}/{p.meta.name}"
        assert key in live, f"seed {seed}: journal invented pod {key}"
        assert p.spec.node_name in ("", live[key]), (
            f"seed {seed}: journal binding {p.spec.node_name!r} "
            f"contradicts live {live[key]!r} for {key}"
        )


# -- overload-protection chaos: slow consumers + relist storms ---------------
#
# These seeds drive the backpressured watch fan-out (per-watcher
# coalescing, Expired-instead-of-terminate) and the relist-storm
# containment (reflector backoff + shared RelistGate) and assert the
# PR 3 invariants PLUS the overload ones: no watcher terminated, bounded
# event staleness (caches converge on the store at quiesce), and
# rv-monotonic delivery through coalescing.

SLOW_CONSUMER_SEEDS = list(range(100, 105))
RELIST_STORM_SEEDS = list(range(200, 205))


def _overload_cluster(seed, store, n_pods, pace=0.0):
    """Shared harness: nodes + scheduler + paced pod burst + quiesce.
    Returns (sched, audit)."""
    rng = random.Random(seed)
    audit = _EventAudit(store)
    for i in range(rng.randint(4, 8)):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=16000, mem=32 * GI, pods=110)
            .zone(f"z{i % 3}")
            .obj()
        )
    config = SchedulerConfiguration(
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        batch_window_min_seconds=0.005,
        batch_window_max_seconds=0.05,
        unschedulable_flush_seconds=0.5,
    )
    sched = Scheduler(store, assume_ttl=1.0, config=config)
    sched.start()
    for i in range(n_pods):
        store.create(
            make_pod(f"p{i}")
            .req(cpu_milli=rng.choice([50, 100]), mem=GI // 8)
            .obj()
        )
        if pace and rng.random() < 0.5:
            time.sleep(rng.random() * pace)
    return sched, audit


def _quiesce_all_bound(store, seed, deadline_s=90):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        pods, _ = store.list("Pod")
        if pods and all(p.spec.node_name for p in pods):
            return pods
        time.sleep(0.1)
    pods, _ = store.list("Pod")
    unbound = [p.meta.name for p in pods if not p.spec.node_name]
    assert not unbound, f"seed {seed}: pods unbound past quiesce: {unbound}"
    return pods


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", SLOW_CONSUMER_SEEDS)
def test_chaos_slow_consumer(seed):
    """Injected consumer stalls + offer drops against a tight watch
    capacity: coalescing and Expired-relist must carry the load — no pod
    lost, no double bind, NO watcher terminated, delivery rv-monotonic,
    and an independent slow reflector converges on the store's final
    state (bounded staleness)."""
    rng = random.Random(seed)
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    reg.delay("watch.consume", seconds=0.005, n=100, probability=0.3)
    reg.drop("watch.offer", n=rng.randint(1, 3), probability=0.3)
    reg.delay("store.list", seconds=0.01, n=10, probability=0.5)
    store = st.Store(watch_capacity=64)

    # an independent slow mini-reflector: consumes with delays, relists
    # on Expired, and must end exactly consistent with the store
    state = {}
    state_lock = threading.Lock()
    stop = threading.Event()
    monotonic_violations = []

    def consumer():
        w = None
        last_rv = 0
        while not stop.is_set():
            try:
                if w is None:
                    items, rv = store.list("Pod")
                    with state_lock:
                        state.clear()
                        state.update(
                            {p.meta.name: p.spec.node_name for p in items}
                        )
                    last_rv = rv
                    w = store.watch("Pod", from_rv=rv)
                ev = w.get(timeout=0.2)
                if ev is None:
                    if w.expired:
                        w = None  # forced relist (the 410 path)
                    continue
                if ev.rv <= last_rv:
                    monotonic_violations.append((ev.rv, last_rv))
                last_rv = ev.rv
                with state_lock:
                    if ev.type == st.DELETED:
                        state.pop(ev.obj.meta.name, None)
                    else:
                        state[ev.obj.meta.name] = ev.obj.spec.node_name
                time.sleep(0.002)  # deliberately slow
            except st.Expired:
                w = None

    t = threading.Thread(target=consumer, daemon=True)
    sched = None
    try:
        with faults.armed(reg):
            t.start()
            sched, audit = _overload_cluster(
                seed, store, n_pods=rng.randint(30, 50), pace=0.005
            )
            pods = _quiesce_all_bound(store, seed)
        assert reg.fired, f"seed {seed}: no fault ever fired"
        assert not audit.violations, f"seed {seed}: {audit.violations[:5]}"
        rebound = {
            k: v for k, v in audit.bound_nodes.items() if len(v) > 1
        }
        assert not rebound, f"seed {seed}: double binds {rebound}"
        assert store.watchers_terminated == 0, (
            f"seed {seed}: watcher terminated under backpressure"
        )
        assert not monotonic_violations, (
            f"seed {seed}: rv regressions {monotonic_violations[:5]}"
        )
        # bounded staleness: once the event stream quiesces, the slow
        # reflector's replayed state equals the store's final bindings
        want = {p.meta.name: p.spec.node_name for p in pods
                if p.meta.name.startswith("p")}
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            with state_lock:
                got = {k: v for k, v in state.items()
                       if k.startswith("p")}
            if got == want:
                break
            time.sleep(0.1)
        assert got == want, (
            f"seed {seed}: stale consumer state "
            f"(missing={set(want) - set(got)}, "
            f"extra={set(got) - set(want)})"
        )
    finally:
        stop.set()
        t.join(timeout=5)
        faults.disarm()
        if sched is not None:
            sched.stop()


# -- mixed-priority preemption churn under batched-dry-run faults ------------
#
# These seeds arm the batch.preemption point (fail / latency / NaN-grade
# corruption of the [P, N, K] dry-run result) while a mixed-priority
# preemptor stream forces sustained PostFilter work against PDB-guarded
# victims.  Invariants on top of the PR 3 set:
#
#   * every preemptor ends bound (a failed batched dispatch falls the
#     pass back to the per-pod parity path — liveness never depends on
#     the batched kernel);
#   * no victim is evicted without its preemptor binding: every pod
#     MISSING from the store at quiesce was deleted by a Preempted
#     eviction (the event trail proves it), never lost;
#   * PDB-guarded victims survive while unguarded alternatives exist;
#   * bound-exactly-once for preemptors AND victims (the event audit).

PREEMPT_SEEDS = list(range(400, 405))


def _preempt_fault_plan(rng: random.Random) -> faults.FaultRegistry:
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    reg.fail("batch.preemption", n=rng.randint(1, 2))
    if rng.random() < 0.5:
        # NaN-grade: the decoded min_k tensor is poisoned; the health
        # check must trip and the pass degrade with parity
        reg.corrupt("batch.preemption", n=1)
    reg.delay("batch.preemption", seconds=0.01, n=2)
    reg.fail("batch.solve", n=1, probability=0.5)
    reg.fail("binder.commit_wave", n=1, probability=0.5)
    reg.drop("watch.offer", n=1, probability=0.5)
    return reg


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", PREEMPT_SEEDS)
def test_chaos_preemption_churn(seed):
    from kubernetes_tpu.api import types as api

    rng = random.Random(seed)
    reg = _preempt_fault_plan(rng)
    store = st.Store()
    audit = _EventAudit(store)
    n_nodes = rng.randint(4, 6)
    for i in range(n_nodes):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=2000, mem=16 * GI, pods=110)
            .zone(f"z{i % 3}")
            .obj()
        )
    # two 1000m victims fill every node; node n0's victims are guarded
    # by a zero-budget PDB — preemptors must rank them last and, while
    # unguarded nodes remain, never evict them
    victim_names = []
    for i in range(n_nodes):
        for j in range(2):
            name = f"victim-{i}-{j}"
            pw = (
                make_pod(name)
                .req(cpu_milli=1000, mem=GI // 4)
                .priority(rng.randint(0, 4))
                .node_name(f"n{i}")
            )
            if i == 0:
                pw = pw.labels(app="guarded")
            p = pw.obj()
            p.status.phase = "Running"
            store.create(p)
            victim_names.append(name)
    pdb = api.PodDisruptionBudget(
        meta=api.ObjectMeta(name="guard", namespace="default"),
        spec=api.PodDisruptionBudgetSpec(
            selector=api.LabelSelector(match_labels={"app": "guarded"})
        ),
    )
    pdb.status.disruptions_allowed = 0
    store.create(pdb)
    config = SchedulerConfiguration(
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        unschedulable_flush_seconds=0.5,
    )
    sched = Scheduler(store, assume_ttl=1.0, config=config)
    # leave the guarded node out of the count: every preemptor must be
    # satisfiable WITHOUT violating the budget
    n_preempt = rng.randint(2, n_nodes - 1)
    preempt_names = [f"preemptor-{i}" for i in range(n_preempt)]
    try:
        with faults.armed(reg):
            sched.start()
            for i, name in enumerate(preempt_names):
                store.create(
                    make_pod(name)
                    .req(cpu_milli=1500, mem=GI // 4)
                    .priority(rng.choice([50, 100, 200]))
                    .obj()
                )
                time.sleep(rng.random() * 0.05)
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                pods, _ = store.list("Pod")
                if pods and all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.1)

        # -- invariants (faults disarmed) --------------------------------
        assert reg.fired.get("batch.preemption"), (
            f"seed {seed}: the batched-preemption fault never fired"
        )
        pods, _ = store.list("Pod")
        by_name = {p.meta.name: p for p in pods}
        unbound = [p.meta.name for p in pods if not p.spec.node_name]
        assert not unbound, (
            f"seed {seed}: pods unbound past quiesce: {unbound}\n"
            f"  queue: {sched.queue.stats()}\n"
            f"  breaker: {sched.tpu.breaker.state}\n"
            f"  preemption: attempted="
            f"{sched.metrics.preemption_attempts.get('attempted')} "
            f"nominated={sched.metrics.preemption_attempts.get('nominated')}"
        )
        for name in preempt_names:
            assert name in by_name, f"seed {seed}: preemptor {name} lost"
        # preemption actually ran (the stream cannot fit without it)
        assert sched.metrics.preemption_attempts.get("nominated") >= 1
        assert sched.metrics.preemption_victims.n >= 1
        # no victim lost: every missing victim has a Preempted event
        # naming it (eviction, not loss)
        sched.events.stop()  # flush the async event writer
        events, _ = store.list("Event")
        evicted = {
            e.involved_object.name
            for e in events
            if e.reason == "Preempted"
        }
        for name in victim_names:
            if name not in by_name:
                assert name in evicted, (
                    f"seed {seed}: victim {name} vanished without eviction"
                )
        # PDB-guarded victims survive while unguarded nodes sufficed
        for i in range(2):
            assert f"victim-0-{i}" in by_name, (
                f"seed {seed}: guarded victim evicted despite unguarded "
                "alternatives"
            )
        # bound-exactly-once across preemptors AND victims
        assert not audit.violations, f"seed {seed}: {audit.violations[:5]}"
        rebound = {
            k: v for k, v in audit.bound_nodes.items() if len(v) > 1
        }
        assert not rebound, f"seed {seed}: double binds {rebound}"
        assert sched.flush_binds(15)
    finally:
        faults.disarm()
        sched.stop()


# -- kill-restart chaos: crash a component, restart it, prove parity ---------
#
# Each seed tears a component down at one of the registered crash
# families and brings the control plane back:
#
#   family 0 (store)  — FaultCrash mid-fsync (plus an optional torn wave
#       append): the whole control plane is killed ungracefully, the
#       store "restarts" from its post-SIGKILL disk image
#       (faults.crash_disk_image), and recovery = snapshot + journal
#       suffix;
#   family 1 (binder) — FaultCrash mid-bind-wave, then the same full
#       kill + disk-image restart;
#   family 2 (leader) — renew failures while the LEADER scheduler is
#       killed mid-pop-window; a warm standby takes over on the live
#       store (no restart) and reconciles.
#
# Invariants on top of the PR 3 set: no pod lost (a create whose ack
# died with the process is retried by the client, as a real writer
# would), no durable bind ever moves across the boundary, rv stays
# monotonic across the restart, recovered state never contradicts the
# acked state, and snapshot+suffix recovery is BIT-IDENTICAL to a
# full-journal-replay oracle over the same disk image.

RESTART_SEEDS = list(range(300, 310))


def _restart_fault_plan(rng: random.Random, family: int) -> faults.FaultRegistry:
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    if family == 0:
        reg.crash("store.journal.fsync", n=1)
        if rng.random() < 0.5:
            reg.torn_write("store.journal.append", frac=rng.random(), n=1)
    elif family == 1:
        reg.crash("binder.commit_wave", n=1)
        reg.delay("binder.commit_wave", seconds=0.005, n=2)
    else:
        reg.fail("leader.renew", n=rng.randint(1, 2))
        reg.delay("binder.commit_wave", seconds=0.005, n=2)
    return reg


def _restart_config():
    return SchedulerConfiguration(
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        unschedulable_flush_seconds=0.5,
    )


def _create_pods(store, rng, names, pace=0.01):
    """Paced creates; a FaultCrash landing on the creating thread (the
    injected process death) stops the stream — the caller kills the
    control plane and the restarted run's client retries the remainder."""
    created = []
    for name in names:
        try:
            store.create(
                make_pod(name).req(
                    cpu_milli=rng.choice([50, 100, 200]),
                    mem=rng.choice([GI // 4, GI // 2]),
                ).obj()
            )
        except BaseException:  # noqa: BLE001 — injected crash/fault
            break
        created.append(name)
        if rng.random() < 0.3:
            time.sleep(rng.random() * pace)
    return created


def _retry_missing_pods(store, rng, names):
    """The client half of ack-loss recovery: re-create any pod whose
    acknowledged create did not survive the crash (a real writer's
    retry-on-timeout loop)."""
    have = {p.meta.name for p in store.list("Pod")[0]}
    for name in names:
        if name not in have:
            store.create(
                make_pod(name).req(
                    cpu_milli=rng.choice([50, 100, 200]),
                    mem=rng.choice([GI // 4, GI // 2]),
                ).obj()
            )


def _wait_all_bound(store, seed, deadline_s=90, label=""):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        pods, _ = store.list("Pod")
        if pods and all(p.spec.node_name for p in pods):
            return pods
        time.sleep(0.1)
    pods, _ = store.list("Pod")
    unbound = [p.meta.name for p in pods if not p.spec.node_name]
    assert not unbound, (
        f"seed {seed}: pods unbound past quiesce{label}: {unbound}"
    )
    return pods


def _fingerprint_json(store):
    import json

    return json.dumps(store.state_fingerprint(), sort_keys=True)


def _wait_reconciled(sched, seed, timeout=10.0):
    """The takeover reconcile runs on the scheduling thread's first
    LEADING pass — an instantly-quiescent cluster can reach the
    assertions before that pass happens, so poll."""
    deadline = time.monotonic() + timeout
    while (
        sched.metrics.leader_reconcile_total.total < 1.0
        and time.monotonic() < deadline
    ):
        time.sleep(0.05)
    assert sched.metrics.leader_reconcile_total.total >= 1.0, (
        f"seed {seed}: takeover reconciliation never ran"
    )


@pytest.mark.restart
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", RESTART_SEEDS)
def test_chaos_kill_restart(seed, tmp_path):
    rng = random.Random(seed)
    family = seed % 3
    reg = _restart_fault_plan(rng, family)
    path = str(tmp_path / "journal.jsonl")
    store = st.Store(journal_path=path)
    audit = _EventAudit(store)
    for i in range(rng.randint(4, 8)):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .zone(f"z{i % 3}")
            .obj()
        )
    elector = LeaderElector(
        store, "restart-sched", f"holder-{seed}-a",
        lease_duration=0.8, renew_period=0.05,
    ).start()
    sched = Scheduler(
        store, assume_ttl=1.0, leader_elector=elector,
        config=_restart_config(),
    )
    n_pods = rng.randint(24, 40)
    all_names = [f"p{i}" for i in range(n_pods)]
    cut = rng.randint(8, n_pods - 8)
    standby = standby_elector = None
    try:
        sched.start()
        assert elector.wait_for_leadership(10)
        # phase 1 (unarmed): a healthy prefix binds, then a checkpoint
        # so recovery exercises snapshot + suffix (truncate=False keeps
        # the full journal for the bit-parity oracle)
        _create_pods(store, rng, all_names[:cut])
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            pods, _ = store.list("Pod")
            if pods and all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        store.checkpoint(truncate=False)
        # phase 2 (armed): the crash schedule fires somewhere in the
        # second half of the stream
        with faults.armed(reg):
            created = _create_pods(store, rng, all_names[cut:])
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not reg.fired:
                time.sleep(0.05)
            # give the wounded pipeline a beat so the kill lands on
            # mid-flight state (popped batches, staged waves)
            time.sleep(rng.random() * 0.3)

            if family == 2:
                # warm leader failover: standby on the SAME store; kill
                # the leader mid-pop-window (creates still arriving)
                standby_elector = LeaderElector(
                    store, "restart-sched", f"holder-{seed}-b",
                    lease_duration=0.8, renew_period=0.05,
                ).start()
                standby = Scheduler(
                    store, assume_ttl=1.0, leader_elector=standby_elector,
                    config=_restart_config(),
                )
                standby.start()
                durable = {
                    f"{p.meta.namespace}/{p.meta.name}": p.spec.node_name
                    for p in store.list("Pod")[0]
                    if p.spec.node_name
                }
                sched.kill()
                elector.stop(release=False)
                assert standby_elector.wait_for_leadership(15), (
                    f"seed {seed}: standby never took over"
                )
                _retry_missing_pods(store, rng, all_names)
                pods = _wait_all_bound(store, seed, label=" (failover)")
                final = {
                    f"{p.meta.namespace}/{p.meta.name}": p.spec.node_name
                    for p in pods
                }
                for key, node in durable.items():
                    assert final.get(key) == node, (
                        f"seed {seed}: durable bind moved across "
                        f"failover: {key} {node} -> {final.get(key)}"
                    )
                _wait_reconciled(standby, seed)
                assert not audit.violations, (
                    f"seed {seed}: {audit.violations[:5]}"
                )
                rebound = {
                    k: v for k, v in audit.bound_nodes.items()
                    if len(v) > 1
                }
                assert not rebound, f"seed {seed}: double binds {rebound}"
                return

        # families 0/1: full kill + disk-image restart -------------------
        sched.kill()
        elector.stop(release=False)
        # kill() abandons the commit pool without waiting — join its
        # threads before fingerprinting, or an in-flight wave commit
        # can append to the journal AFTER the acked capture and the
        # recovered rv legitimately overshoots the bound below
        if sched._commit_pool is not None:
            sched._commit_pool.shutdown(wait=True)
        # the control plane is dead: the acked in-memory state is now
        # frozen — capture it for the never-contradicts check
        acked = store.state_fingerprint()
        acked_rv = store.resource_version
        img = faults.crash_disk_image(path, str(tmp_path / "img"))
        oracle_img = faults.crash_disk_image(
            path, str(tmp_path / "oracle")
        )
        recovered = st.Store(journal_path=img)
        # bit-parity oracle: same disk image, full-journal replay
        # (every shard's snapshot removed — full history per shard)
        faults.remove_snapshots(oracle_img)
        oracle = st.Store(journal_path=oracle_img)
        assert oracle.snapshot_records == 0
        assert recovered.snapshot_records > 0, (
            f"seed {seed}: recovery never used the snapshot"
        )
        assert _fingerprint_json(recovered) == _fingerprint_json(oracle), (
            f"seed {seed}: snapshot+suffix recovery diverged from the "
            f"full-replay oracle"
        )
        # recovered state never contradicts the acked state: rv bounded,
        # recovered bindings (when present) match the ack
        assert recovered.resource_version <= acked_rv
        acked_bindings = {
            kind_key: rec[1]["spec"]["node_name"]
            for kind_key, rec in acked["objects"].get("Pod", {}).items()
            if rec[1]["spec"].get("node_name")
        }
        recovered_initial = {
            f"{p.meta.namespace}/{p.meta.name}": p.spec.node_name
            for p in recovered.list("Pod")[0]
            if p.spec.node_name
        }
        for key, node in recovered_initial.items():
            assert acked_bindings.get(key) == node, (
                f"seed {seed}: recovery invented binding {key}->{node}"
            )
        # restart the control plane on the recovered store
        audit2 = _EventAudit(recovered)
        audit2._last_rv = recovered.resource_version
        for key, node in recovered_initial.items():
            audit2.bound_nodes[key].add(node)
        elector2 = LeaderElector(
            recovered, "restart-sched", f"holder-{seed}-r",
            lease_duration=0.8, renew_period=0.05,
        ).start()
        sched2 = Scheduler(
            recovered, assume_ttl=1.0, leader_elector=elector2,
            config=_restart_config(),
        )
        try:
            sched2.start()
            assert elector2.wait_for_leadership(10)
            _retry_missing_pods(recovered, rng, all_names)
            pods = _wait_all_bound(recovered, seed, label=" (restart)")
            assert len(pods) == n_pods, (
                f"seed {seed}: {n_pods - len(pods)} pod(s) lost"
            )
            assert not audit2.violations, (
                f"seed {seed}: rv regressed across restart: "
                f"{audit2.violations[:5]}"
            )
            rebound = {
                k: v for k, v in audit2.bound_nodes.items()
                if len(v) > 1
            }
            assert not rebound, (
                f"seed {seed}: double binds across restart {rebound}"
            )
            # durable pre-kill binds that SURVIVED recovery never move
            final = {
                f"{p.meta.namespace}/{p.meta.name}": p.spec.node_name
                for p in pods
            }
            for key, node in recovered_initial.items():
                assert final[key] == node, (
                    f"seed {seed}: recovered bind moved: {key} "
                    f"{node} -> {final[key]}"
                )
            _wait_reconciled(sched2, seed)
        finally:
            sched2.stop()
            elector2.stop()
            recovered.close()
        del created
    finally:
        faults.disarm()
        if standby is not None:
            standby.stop()
        if standby_elector is not None:
            standby_elector.stop()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", RELIST_STORM_SEEDS)
def test_chaos_relist_storm(seed):
    """Repeated injected expiries across the scheduler's informers plus
    list latency: the jittered backoff + shared RelistGate must contain
    the storm — every informer converges, every pod binds, no watcher
    terminated, no double bind."""
    rng = random.Random(seed)
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    reg.drop("watch.offer", n=rng.randint(4, 8), probability=0.7)
    reg.delay("store.list", seconds=0.02, n=40, probability=0.7)
    reg.fail("store.update_wave", n=1)
    store = st.Store(watch_capacity=32)
    sched = None
    try:
        with faults.armed(reg):
            sched, audit = _overload_cluster(
                seed, store, n_pods=rng.randint(30, 50)
            )
            pods = _quiesce_all_bound(store, seed)
        assert reg.fired.get("watch.offer"), (
            f"seed {seed}: no expiry was ever injected"
        )
        assert not audit.violations, f"seed {seed}: {audit.violations[:5]}"
        rebound = {
            k: v for k, v in audit.bound_nodes.items() if len(v) > 1
        }
        assert not rebound, f"seed {seed}: double binds {rebound}"
        assert store.watchers_terminated == 0, (
            f"seed {seed}: watcher terminated under relist storm"
        )
        # bounded staleness: the Pod informer cache converges on the
        # store after the storm (relist recovered every expiry)
        want = {
            (p.meta.name, p.spec.node_name) for p in pods
        }
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            got = {
                (p.meta.name, p.spec.node_name)
                for p in sched.informers.informer("Pod").list()
            }
            if got == want:
                break
            time.sleep(0.1)
        assert got == want, (
            f"seed {seed}: informer cache stale after storm "
            f"(missing={want - got}, extra={got - want})"
        )
    finally:
        faults.disarm()
        if sched is not None:
            sched.stop()


# -- speculative multi-lane pipeline under commit failure / fence ------------
#
# Seeds 500-509 drive the PR 12 pipeline: TWO profile lanes popping
# disjoint pod classes concurrently, STREAMED per-shard sub-wave commits
# on a 4-shard store, and SPECULATIVE solves dispatched while earlier
# waves are still committing — with faults at the new points
# (solve.speculate kills speculative dispatches, binder.stream_subwave
# kills streamed hand-offs) layered over commit failures, crash-grade
# binder faults, shard-wave failures and leader-renew failures (the
# fence-mid-wave shape).  Invariants on top of the PR 3 set:
#
#   * a mis-speculation requeues EXACTLY the speculative batch — every
#     pod still ends bound within the bounded quiesce (requeue+backoff,
#     never a loss);
#   * bound-exactly-once per streamed sub-wave (the event audit);
#   * the assume set drains to empty at quiesce.

SPECULATE_SEEDS = list(range(500, 510))


def _speculate_fault_plan(rng: random.Random) -> faults.FaultRegistry:
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    reg.fail("solve.speculate", n=rng.randint(1, 2), probability=0.7)
    reg.delay("solve.speculate", seconds=0.002, n=3, probability=0.5)
    reg.fail("binder.stream_subwave", n=rng.randint(1, 2), probability=0.7)
    # commit failures AFTER speculative dispatches: the mis-speculation
    # invalidation path.  The commit delays are deliberately HEAVY
    # (~50ms x 60 sub-waves) so waves are reliably still in flight when
    # the next batch dispatches — every seed genuinely speculates.  The
    # budget is 60, not 20: a leader.renew fault can pause dispatch
    # while delayed commits drain, and a 20-wave budget occasionally
    # burned out before the re-acquired leader overlapped a dispatch
    # ("no dispatch ever speculated" flakes on seeds 502/507).
    reg.delay("binder.commit_wave", seconds=0.05, n=60)
    reg.fail("binder.commit_wave", n=rng.randint(1, 2))
    if rng.random() < 0.5:
        reg.crash("binder.commit_wave", n=1)
    reg.fail("store.shard.update_wave", n=1, probability=0.7)
    reg.fail("leader.renew", n=rng.randint(1, 2))
    reg.drop("watch.offer", n=1, probability=0.3)
    return reg


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", SPECULATE_SEEDS)
def test_chaos_speculative_lanes(seed, tmp_path):
    from kubernetes_tpu.scheduler.config import ProfileConfig

    rng = random.Random(seed)
    reg = _speculate_fault_plan(rng)
    store = st.Store(
        journal_path=str(tmp_path / "journal.jsonl"), shards=4
    )
    audit = _EventAudit(store)
    for i in range(rng.randint(4, 8)):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .zone(f"z{i % 3}")
            .obj()
        )
    elector = LeaderElector(
        store, "spec-sched", f"holder-{seed}",
        lease_duration=1.0, renew_period=0.05,
    ).start()
    config = SchedulerConfiguration(
        profiles=[
            ProfileConfig(),
            ProfileConfig(scheduler_name="batch-scheduler"),
        ],
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        unschedulable_flush_seconds=0.5,
    )
    sched = Scheduler(
        store, assume_ttl=1.0, leader_elector=elector, config=config
    )
    assert sched._stream_enabled  # the streamed path is under test
    assert len(sched._lane_profiles) == 2
    n_pods = rng.randint(24, 40)
    namespaces = [f"ns-{i}" for i in range(4)]
    try:
        with faults.armed(reg):
            sched.start()
            assert elector.wait_for_leadership(10)
            for i in range(n_pods):
                spec = make_pod(
                    f"p{i}", namespace=namespaces[i % 4]
                ).req(
                    cpu_milli=rng.choice([50, 100, 200]),
                    mem=rng.choice([GI // 4, GI // 2]),
                )
                pod = spec.obj()
                if i % 2:
                    pod.spec.scheduler_name = "batch-scheduler"
                store.create(pod)
                if rng.random() < 0.4:
                    time.sleep(rng.random() * 0.01)
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                pods, _ = store.list("Pod")
                if pods and all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.1)

        # -- invariants (faults disarmed) --------------------------------
        assert reg.fired, f"seed {seed}: no fault ever fired"
        pods, _ = store.list("Pod")
        assert len(pods) == n_pods
        unbound = [p.meta.name for p in pods if not p.spec.node_name]
        assert not unbound, (
            f"seed {seed}: pods lost/wedged past bounded quiesce: {unbound}\n"
            f"  queue: {sched.queue.stats()}\n"
            f"  assumed: {list(sched.cache._assumed)}\n"
            f"  speculative={sched.metrics.speculative_solves_total.total} "
            f"misspec={sched.metrics.misspeculation_total.total}\n"
            f"  stream_inflight={sched._stream_inflight} "
            f"waves={len(sched._waves)}\n"
            f"  fired={reg.fired} pending={reg.pending()}"
        )
        # the overlap genuinely happened: commits were delayed, so at
        # least one dispatch should have been speculative.  Leadership
        # churn can defeat the forcing, though — a leader.renew fault
        # pauses dispatch while every delayed wave drains, and the
        # re-acquired leader's one remaining batch dispatches over an
        # empty wave ring.  When THIS run never overlapped, drive a
        # deterministic paced epilogue burst under commit delays alone
        # so the speculative path the matrix exists to exercise
        # genuinely runs before the invariants below are asserted.
        if sched.metrics.speculative_solves_total.total < 1:
            # plug-and-chase: create one pod, WAIT until its delayed
            # commit is observably in flight, then create a chaser —
            # the chaser's dispatch lands inside the 250ms hold, so
            # its _waves_in_flight() check is true by construction
            # (paced bursts alone are marginal: a lane finalizes the
            # prior cycle in the same iteration only when pods pop
            # back-to-back, and a 50ms hold drains in the idle-pop gap)
            reg2 = faults.FaultRegistry(seed=seed)
            reg2.delay("binder.commit_wave", seconds=0.25, n=200)
            with faults.armed(reg2):
                extra_i = n_pods
                epi_deadline = time.monotonic() + 45
                while (
                    sched.metrics.speculative_solves_total.total < 1
                    and time.monotonic() < epi_deadline
                ):
                    for role in ("plug", "chase"):
                        extra = make_pod(
                            f"p{extra_i}",
                            namespace=namespaces[extra_i % 4],
                        ).req(cpu_milli=50, mem=GI // 4).obj()
                        if extra_i % 2:
                            extra.spec.scheduler_name = "batch-scheduler"
                        store.create(extra)
                        extra_i += 1
                        t0 = time.monotonic()
                        if role == "plug":
                            # wait for the plug's wave to be held
                            while (
                                not sched._waves_in_flight()
                                and time.monotonic() - t0 < 5
                            ):
                                time.sleep(0.005)
                        else:
                            # give the chaser's dispatch a beat to run
                            while (
                                sched.metrics.speculative_solves_total.total < 1
                                and time.monotonic() - t0 < 2
                            ):
                                time.sleep(0.01)
                n_pods = extra_i
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    pods, _ = store.list("Pod")
                    if (
                        len(pods) == n_pods
                        and all(p.spec.node_name for p in pods)
                    ):
                        break
                    time.sleep(0.1)
            pods, _ = store.list("Pod")
            assert len(pods) == n_pods
            unbound = [p.meta.name for p in pods if not p.spec.node_name]
            assert not unbound, (
                f"seed {seed}: epilogue pods wedged: {unbound}"
            )
        assert sched.metrics.speculative_solves_total.total >= 1, (
            f"seed {seed}: no dispatch ever speculated"
        )
        assert not audit.violations, f"seed {seed}: {audit.violations[:5]}"
        rebound = {
            k: nodes for k, nodes in audit.bound_nodes.items()
            if len(nodes) > 1
        }
        assert not rebound, f"seed {seed}: double binds {rebound}"
        assert sched.flush_binds(15)
        deadline = time.monotonic() + 10
        while sched.cache.assumed_count() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sched.cache.assumed_count() == 0, (
            f"seed {seed}: assume set not empty at quiesce"
        )
        _ledger_quiesced(seed)
    finally:
        faults.disarm()
        sched.stop()
        elector.stop()


# -- sharded-store kill-restart: crash ONE shard mid-fsync -------------------
#
# The store is sharded (per-shard locks/journals/checkpoints, ISSUE 9);
# these seeds crash the journal path of whichever shard reaches it first
# — a FaultCrash out of store.journal.fsync (and, on some seeds, a torn
# store.shard.journal.append) kills the writer mid-commit — then the
# whole store is abandoned and restarted from its post-SIGKILL disk
# image.  Invariants: the SURVIVING shards (no tail truncation) recover
# every acked object bit-identically; the crashed shard recovers its
# snapshot + journal suffix BIT-IDENTICAL to a full-replay oracle over
# the same image; nothing recovered ever contradicts the acked state.

SHARD_RESTART_SEEDS = list(range(310, 315))


@pytest.mark.restart
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", SHARD_RESTART_SEEDS)
def test_chaos_shard_crash_restart(seed, tmp_path):
    rng = random.Random(seed)
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    reg.crash("store.journal.fsync", n=1)
    if rng.random() < 0.5:
        reg.torn_write(
            "store.shard.journal.append", frac=rng.random(), n=1
        )
    path = str(tmp_path / "journal.jsonl")
    store = st.Store(journal_path=path, shards=4)
    namespaces = [f"ns-{i}" for i in range(8)]

    def mk(name, ns):
        pod = make_pod(name).req(cpu_milli=rng.choice([50, 100])).obj()
        pod.meta.namespace = ns
        return pod

    # phase 1 (unarmed): a healthy prefix lands on every shard, then a
    # checkpoint so recovery exercises per-shard snapshot + suffix
    # (truncate=False keeps full journals for the bit-parity oracle)
    for i in range(24):
        store.create(mk(f"warm-{i}", namespaces[i % 8]))

    def bind(node):
        def mutate(pod):
            pod.spec.node_name = node
        return mutate

    store.update_wave(
        "Pod",
        [(f"warm-{i}", namespaces[i % 8], bind(f"n{i % 4}"))
         for i in range(24)],
    )
    store.checkpoint(truncate=False)

    # phase 2 (armed): concurrent writers over every namespace; the
    # crash kills one writer mid-commit on one shard — the rest of the
    # store keeps serving until the harness stops the survivors
    crashed = threading.Event()

    def writer(t):
        for i in range(200):
            if crashed.is_set():
                return
            try:
                store.create(mk(f"hot-{t}-{i}", namespaces[t]))
                if i % 5 == 4:
                    store.update_wave(
                        "Pod",
                        [(f"hot-{t}-{k}", namespaces[t], bind("nx"))
                         for k in range(i - 4, i + 1)],
                    )
            except BaseException:  # noqa: BLE001 — the injected death
                crashed.set()
                return
            time.sleep(rng.random() * 0.002)

    with faults.armed(reg):
        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(8)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not reg.fired.get(
            "store.journal.fsync"
        ):
            time.sleep(0.01)
        crashed.set()
        for t in threads:
            t.join(timeout=10)
    assert reg.fired.get("store.journal.fsync"), (
        f"seed {seed}: the shard crash never fired"
    )

    # the control plane is dead: freeze the acked in-memory state and
    # capture the post-SIGKILL disk image (userspace buffers excluded
    # by construction)
    acked = store.state_fingerprint()
    acked_rv = store.resource_version
    img = faults.crash_disk_image(path, str(tmp_path / "img"))
    oracle_img = faults.crash_disk_image(path, str(tmp_path / "oracle"))
    faults.remove_snapshots(oracle_img)

    recovered = st.Store(journal_path=img)
    oracle = st.Store(journal_path=oracle_img)
    assert recovered.shard_count == 4
    assert oracle.snapshot_records == 0
    assert recovered.snapshot_records > 0, (
        f"seed {seed}: recovery never used the shard snapshots"
    )
    # bit-parity: snapshot+suffix recovery == full-replay oracle,
    # crashed shard included
    assert _fingerprint_json(recovered) == _fingerprint_json(oracle), (
        f"seed {seed}: sharded recovery diverged from the oracle"
    )
    # recovered never contradicts acked: rv bounded, every recovered
    # object matches the acked copy exactly (the lost tail is the only
    # permitted difference)
    assert recovered.resource_version <= acked_rv
    acked_objs = acked["objects"]
    rec = recovered.state_fingerprint()["objects"]
    for kind, entries in rec.items():
        for key, (rv, wire_obj) in entries.items():
            assert acked_objs.get(kind, {}).get(key) == (rv, wire_obj), (
                f"seed {seed}: recovery invented/altered {kind} {key}"
            )
    # surviving shards are CONSISTENT: every shard the fault schedule
    # never touched (the crash ctx names the fsync victim; a torn
    # append names its shard too) recovered every acked object it owns
    wounded = {
        reg.last_ctx.get(point, {}).get("shard")
        for point in ("store.journal.fsync", "store.shard.journal.append")
    }
    for i in range(recovered.shard_count):
        if i in wounded:
            continue  # the crashed shard: its lost tail is legitimate
        for kind, entries in acked_objs.items():
            for key, (rv, wire_obj) in entries.items():
                ns = wire_obj.get("meta", {}).get("namespace", "")
                if recovered.shard_index(kind, ns or "") != i:
                    continue
                assert rec.get(kind, {}).get(key) == (rv, wire_obj), (
                    f"seed {seed}: surviving shard {i} lost {kind} {key}"
                )


# -- gang carve-out chaos: slice topology under solve/commit faults ----------
#
# Seeds 600-604 drive the TPU slice subsystem (ops/slices.py,
# docs/scheduler_loop.md "TPU slice topology"): shaped gangs
# (scheduling_group_size + tpu_topology) scheduling onto slice-labelled
# nodes while faults land on the NEW solve.carveout point (the gang
# carve-out dispatch) layered over batch.solve corruption, binder
# commit failures/crashes, wave-transaction faults and leader-renew
# failures.  Invariants on top of the PR 3 set:
#
#   * carve-out all-or-nothing holds: at quiesce every gang is FULLY
#     bound — no partially occupied carve-out survives (a gang the
#     faults broke mid-flight must have been released whole and
#     retried);
#   * each gang's members occupy pairwise-distinct devices of ONE
#     slice; under the require policy the occupied set is a contiguous
#     sub-cuboid (bounding-box volume == member count);
#   * bound exactly once (the event audit) and the assume set drains
#     to empty at quiesce.

CARVEOUT_SEEDS = list(range(600, 605))


def _carveout_fault_plan(rng: random.Random) -> faults.FaultRegistry:
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    # the carve-out dispatch point itself: kill the solve, then latency
    reg.fail("solve.carveout", n=rng.randint(1, 2))
    reg.delay("solve.carveout", seconds=0.005, n=2, probability=0.5)
    reg.fail("batch.solve", n=1, probability=0.5)
    if rng.random() < 0.5:
        reg.corrupt("batch.solve", n=1)
    reg.fail("binder.commit_wave", n=rng.randint(1, 2))
    if rng.random() < 0.5:
        reg.crash("binder.commit_wave", n=1)
    reg.fail("store.update_wave", n=1, probability=0.5)
    reg.fail("store.journal.append", n=1, probability=0.5)
    reg.fail("leader.renew", n=1, probability=0.5)
    return reg


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", CARVEOUT_SEEDS)
def test_chaos_gang_carveouts(seed, tmp_path):
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.testing.wrappers import make_node as _mk_node

    rng = random.Random(seed)
    reg = _carveout_fault_plan(rng)
    policy = "require" if seed % 2 else "prefer"
    store = st.Store(journal_path=str(tmp_path / "journal.jsonl"))
    audit = _EventAudit(store)

    # 2 slices of 2x2x2 = 16 devices; 4 gangs of 4 fill them exactly
    dims = (2, 2, 2)
    for s in range(2):
        for z in range(dims[2]):
            for y in range(dims[1]):
                for x in range(dims[0]):
                    store.create(
                        _mk_node(f"s{s}-{x}{y}{z}")
                        .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
                        .label(api.LABEL_TPU_SLICE, f"slice-{s}")
                        .label(api.LABEL_TPU_TOPOLOGY, "2x2x2")
                        .label(api.LABEL_TPU_COORDS, f"{x},{y},{z}")
                        .obj()
                    )
    elector = LeaderElector(
        store, "carve-sched", f"holder-{seed}",
        lease_duration=1.0, renew_period=0.05,
    ).start()
    config = SchedulerConfiguration(
        slice_carveout_policy=policy,
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        unschedulable_flush_seconds=0.5,
    )
    sched = Scheduler(
        store, assume_ttl=1.0, leader_elector=elector, config=config
    )
    gangs = {f"gang-{g}": 4 for g in range(4)}
    try:
        with faults.armed(reg):
            sched.start()
            assert elector.wait_for_leadership(10)
            for g, (gname, size) in enumerate(gangs.items()):
                for i in range(size):
                    pod = (
                        make_pod(f"{gname}-m{i}")
                        .req(cpu_milli=rng.choice([50, 100]))
                        .group(gname, size)
                        .obj()
                    )
                    pod.spec.tpu_topology = "2x2x1"
                    store.create(pod)
                    if rng.random() < 0.3:
                        time.sleep(rng.random() * 0.01)
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                pods, _ = store.list("Pod")
                if pods and all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.1)

        # -- invariants (faults disarmed; residual schedules drained) ----
        assert reg.fired.get("solve.carveout"), (
            f"seed {seed}: the carve-out fault never fired "
            f"(fired={reg.fired})"
        )
        pods, _ = store.list("Pod")
        assert len(pods) == sum(gangs.values())
        by_gang = {}
        for p in pods:
            by_gang.setdefault(p.spec.scheduling_group, []).append(p)
        # no partially occupied carve-out survives quiesce: every gang
        # fully bound (bounded faults => the pipeline must heal)
        for gname, members in by_gang.items():
            bound = [p for p in members if p.spec.node_name]
            assert len(bound) == gangs[gname], (
                f"seed {seed}: gang {gname} partially occupied past "
                f"quiesce: {len(bound)}/{gangs[gname]} bound\n"
                f"  queue: {sched.queue.stats()}\n"
                f"  fired={reg.fired} pending={reg.pending()}"
            )
            nodes = [store.get("Node", p.spec.node_name) for p in bound]
            slices_used = {
                n.meta.labels[api.LABEL_TPU_SLICE] for n in nodes
            }
            assert len(slices_used) == 1, (
                f"seed {seed}: gang {gname} spans slices {slices_used}"
            )
            coords = [
                api.parse_coords(n.meta.labels[api.LABEL_TPU_COORDS])
                for n in nodes
            ]
            assert len(set(coords)) == len(coords), (
                f"seed {seed}: gang {gname} double-occupied a device"
            )
            if policy == "require":
                vol = 1
                for axis in range(3):
                    vals = [c[axis] for c in coords]
                    vol *= max(vals) - min(vals) + 1
                assert vol == len(coords), (
                    f"seed {seed}: gang {gname} not contiguous under "
                    f"require: {sorted(coords)}"
                )
        assert not audit.violations, f"seed {seed}: {audit.violations[:5]}"
        rebound = {
            k: nodes for k, nodes in audit.bound_nodes.items()
            if len(nodes) > 1
        }
        assert not rebound, f"seed {seed}: double binds {rebound}"
        assert sched.flush_binds(15)
        deadline = time.monotonic() + 10
        while sched.cache.assumed_count() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sched.cache.assumed_count() == 0, (
            f"seed {seed}: assume set not empty at quiesce"
        )
        _ledger_quiesced(seed)
    finally:
        faults.disarm()
        sched.stop()
        elector.stop()


# -- incremental-solve partials: poison-and-heal (ISSUE 14) ----------------
#
# The PartialsCache warm-starts every greedy/wavefront solve from
# device-resident Filter/Score partials.  These seeds CORRUPT the
# resident store (solve.partials poisons the raw score rows) and mix in
# fail-grade partials/solve/commit faults: the parity gate must trip —
# the poisoned solve's NaN scores hit the decode health check, the
# retry invalidates the cache and fully recomputes (or the breaker's
# host fallback places the batch) — and the pipeline must heal to the
# standing invariants: every pod bound, bound-exactly-once, assume set
# empty at quiesce.

PARTIALS_SEEDS = list(range(700, 705))


def _partials_fault_plan(rng: random.Random) -> faults.FaultRegistry:
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    # the poison: CORRUPT leaves NaN-generating score rows resident
    reg.corrupt("solve.partials", n=rng.randint(1, 2))
    # fail-grade partials faults degrade a batch to a cold solve
    reg.fail("solve.partials", n=1, probability=0.5)
    reg.fail("batch.solve", n=1, probability=0.5)
    reg.fail("binder.commit_wave", n=rng.randint(1, 2))
    reg.fail("store.update_wave", n=1, probability=0.5)
    reg.fail("store.journal.append", n=1, probability=0.5)
    reg.fail("leader.renew", n=1, probability=0.5)
    return reg


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", PARTIALS_SEEDS)
def test_chaos_partials_poison(seed, tmp_path):
    rng = random.Random(seed)
    reg = _partials_fault_plan(rng)
    store = st.Store(journal_path=str(tmp_path / "journal.jsonl"))
    audit = _EventAudit(store)
    for i in range(24):
        store.create(
            make_node(f"n-{i}")
            .capacity(cpu_milli=16000, mem=32 * GI, pods=110)
            .zone(f"z-{i % 3}")
            .obj()
        )
    elector = LeaderElector(
        store, "partials-sched", f"holder-{seed}",
        lease_duration=1.0, renew_period=0.05,
    ).start()
    config = SchedulerConfiguration(
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        unschedulable_flush_seconds=0.5,
    )
    sched = Scheduler(
        store, assume_ttl=1.0, leader_elector=elector, config=config
    )
    n_pods = 48
    try:
        with faults.armed(reg):
            sched.start()
            assert elector.wait_for_leadership(10)
            for i in range(n_pods):
                pod = (
                    make_pod(f"p-{i}", namespace=f"team-{i % 2}")
                    .req(cpu_milli=rng.choice([50, 100, 200]))
                )
                if i % 4 == 0:
                    pod.node_selector_kv(
                        "topology.kubernetes.io/zone", f"z-{i % 3}"
                    )
                store.create(pod.obj())
                if rng.random() < 0.3:
                    time.sleep(rng.random() * 0.01)
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                pods, _ = store.list("Pod")
                if pods and all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.1)

        # -- invariants (faults disarmed; residual schedules drained) ----
        assert reg.fired.get("solve.partials"), (
            f"seed {seed}: the partials fault never fired "
            f"(fired={reg.fired})"
        )
        pods, _ = store.list("Pod")
        assert len(pods) == n_pods
        unbound = [p.meta.name for p in pods if not p.spec.node_name]
        assert not unbound, (
            f"seed {seed}: pods never bound past quiesce: {unbound[:5]}\n"
            f"  queue: {sched.queue.stats()}\n"
            f"  fired={reg.fired} pending={reg.pending()}"
        )
        # the parity gate tripped to a full recompute (invalidate +
        # reseed) or the breaker's host fallback placed the batch —
        # the CORRUPT poison must never be absorbed silently
        gate_evidence = sum(
            fwk.tpu._partials.full_recomputes
            for fwk in sched.profiles
            if getattr(fwk.tpu, "_partials", None) is not None
        ) + sum(
            fwk.tpu.breaker.fallback_count() for fwk in sched.profiles
        )
        assert gate_evidence >= 2, (  # >= first sync + the recovery
            f"seed {seed}: no parity-gate trip after CORRUPT "
            f"(evidence={gate_evidence}, fired={reg.fired})"
        )
        assert not audit.violations, f"seed {seed}: {audit.violations[:5]}"
        rebound = {
            k: nodes for k, nodes in audit.bound_nodes.items()
            if len(nodes) > 1
        }
        assert not rebound, f"seed {seed}: double binds {rebound}"
        assert sched.flush_binds(15)
        deadline = time.monotonic() + 10
        while sched.cache.assumed_count() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sched.cache.assumed_count() == 0, (
            f"seed {seed}: assume set not empty at quiesce"
        )
        _ledger_quiesced(seed)
    finally:
        faults.disarm()
        sched.stop()
        elector.stop()


# -- elastic node axis: autoscaler churn mid-solve (ISSUE 15) --------------
#
# The node axis is elastic: a pad-bucket crossing is absorbed by an
# in-place resident resize (mirror.grow) instead of a full re-upload,
# remove_node compaction is deferred and bounded, and the exposed bucket
# follows shrink-dwell hysteresis.  These seeds drive sustained node
# add/remove ACROSS a bucket boundary while a pod burst schedules, with
# grow faults injected: fail-grade declines the resize (the mirror must
# take the full-resync safety path), CORRUPT poisons the carried rows
# (the decode health check must trip and the retry's invalidation heal
# via full resync).  Churn nodes are NoSchedule-tainted so the solver
# must never place a pod on them — which also pins the "no placement on
# a removed node's row" invariant exactly: any pod observed on a churn-*
# node would be a placement onto a row whose node was (or is about to
# be) removed.  Standing invariants ride along: every pod binds, bound
# exactly once, rv monotonic, assume set empty at quiesce.

NODE_CHURN_SEEDS = list(range(800, 805))


def _node_churn_fault_plan(rng: random.Random) -> faults.FaultRegistry:
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    # the poison first (consumed by the first crossing), then a decline
    reg.corrupt("mirror.grow", n=1)
    reg.fail("mirror.grow", n=1)
    reg.fail("batch.solve", n=1, probability=0.5)
    reg.fail("binder.commit_wave", n=rng.randint(1, 2))
    reg.fail("store.update_wave", n=1, probability=0.5)
    reg.fail("store.journal.append", n=1, probability=0.5)
    reg.fail("leader.renew", n=1, probability=0.5)
    return reg


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", NODE_CHURN_SEEDS)
def test_chaos_node_churn(seed, tmp_path):
    from kubernetes_tpu.kubemark import NodeGroupScaler

    rng = random.Random(seed)
    reg = _node_churn_fault_plan(rng)
    store = st.Store(journal_path=str(tmp_path / "journal.jsonl"))
    audit = _EventAudit(store)
    n_base = 24
    for i in range(n_base):
        store.create(
            make_node(f"n-{i}")
            .capacity(cpu_milli=16000, mem=32 * GI, pods=110)
            .zone(f"z-{i % 3}")
            .obj()
        )
    # the churn group: tainted so nothing ever lands on its rows
    scaler = NodeGroupScaler(
        store, group="churn", zones=3,
        taints=[("dedicated", "churn", "NoSchedule")],
    )
    elector = LeaderElector(
        store, "churn-sched", f"holder-{seed}",
        lease_duration=1.0, renew_period=0.05,
    ).start()
    config = SchedulerConfiguration(
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        unschedulable_flush_seconds=0.5,
        # a short dwell so the oscillation produces several crossings
        # (several mirror.grow fire opportunities) inside the run
        bucket_shrink_dwell=2,
    )
    sched = Scheduler(
        store, assume_ttl=1.0, leader_elector=elector, config=config
    )
    n_pods = 48
    stop_churn = threading.Event()

    def churn_loop():
        # oscillate the group across the 32-node pad bucket boundary
        # (24 base + 0..20 churn members: buckets 32 <-> 64) until the
        # grow point fired twice (poison + decline) or the run ends
        hi, lo = 20, 0
        while not stop_churn.wait(0.05):
            try:
                scaler.scale_to(hi if scaler.size() <= lo else lo)
            except Exception:  # noqa: BLE001 — store faults are in play
                pass
            for _ in range(40):
                if stop_churn.wait(0.05):
                    return
                if reg.fired.get("mirror.grow", 0) >= 2:
                    return

    churn = threading.Thread(target=churn_loop, daemon=True)
    try:
        with faults.armed(reg):
            sched.start()
            assert elector.wait_for_leadership(10)
            churn.start()
            for i in range(n_pods):
                pod = (
                    make_pod(f"p-{i}", namespace=f"team-{i % 2}")
                    .req(cpu_milli=rng.choice([50, 100, 200]))
                )
                if i % 4 == 0:
                    pod.node_selector_kv(
                        "topology.kubernetes.io/zone", f"z-{i % 3}"
                    )
                store.create(pod.obj())
                if rng.random() < 0.3:
                    time.sleep(rng.random() * 0.01)
            # an encode only observes a crossing when a batch solves:
            # while the grow faults haven't both fired, keep feeding
            # tiny driver pods so the oscillating node set keeps being
            # re-encoded (bounded — the churn loop stops at 2 fires)
            extras = 0
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                pods, _ = store.list("Pod")
                quiesced = pods and all(p.spec.node_name for p in pods)
                fired = reg.fired.get("mirror.grow", 0)
                if quiesced and (fired >= 2 or extras >= 40):
                    break
                if quiesced and fired < 2:
                    store.create(
                        make_pod(
                            f"drv-{extras}", namespace=f"team-{extras % 2}"
                        ).req(cpu_milli=10).obj()
                    )
                    extras += 1
                    time.sleep(0.25)
                    continue
                time.sleep(0.1)
            stop_churn.set()
            churn.join(timeout=10)

        # -- invariants (faults disarmed; residual schedules drained) ----
        assert reg.fired.get("mirror.grow"), (
            f"seed {seed}: the grow fault never fired "
            f"(fired={reg.fired}, scaler size={scaler.size()}, "
            f"extras={extras})"
        )
        pods, _ = store.list("Pod")
        assert len(pods) == n_pods + extras
        unbound = [p.meta.name for p in pods if not p.spec.node_name]
        assert not unbound, (
            f"seed {seed}: pods never bound past quiesce: {unbound[:5]}\n"
            f"  queue: {sched.queue.stats()}\n"
            f"  fired={reg.fired} pending={reg.pending()}"
        )
        # no placement on a removed (or removable) node's row: churn
        # members are NoSchedule-tainted, so ANY pod on one means the
        # solver consumed a stale/corrupt resident row
        on_churn = [
            p.meta.name for p in pods
            if p.spec.node_name and p.spec.node_name.startswith("churn-")
        ]
        assert not on_churn, (
            f"seed {seed}: pods placed on churn rows: {on_churn[:5]}"
        )
        # the declined/poisoned grows healed through the full-resync
        # safety path: the mirror re-uploaded at least once past the
        # initial sync (the fail-grade decline and the CORRUPT heal
        # both land there)
        resyncs = sum(
            fwk.tpu._mirror.resync_total for fwk in sched.profiles
        )
        assert resyncs >= 2, (
            f"seed {seed}: no full-resync heal after grow faults "
            f"(resyncs={resyncs}, fired={reg.fired})"
        )
        assert not audit.violations, f"seed {seed}: {audit.violations[:5]}"
        rebound = {
            k: nodes for k, nodes in audit.bound_nodes.items()
            if len(nodes) > 1
        }
        assert not rebound, f"seed {seed}: double binds {rebound}"
        assert sched.flush_binds(15)
        deadline = time.monotonic() + 10
        while sched.cache.assumed_count() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sched.cache.assumed_count() == 0, (
            f"seed {seed}: assume set not empty at quiesce"
        )
        _ledger_quiesced(seed)
    finally:
        stop_churn.set()
        faults.disarm()
        sched.stop()
        elector.stop()


# -- serving-plane chaos: HTTP faults + replica failover (PR 18) -------------
#
# These seeds drive the WHOLE serving path under fault load: pods are
# created THROUGH the read-replica HTTP plane (injected 5xx/latency on
# server.request, torn/failed chunk frames on server.watch.write,
# admission stalls on apf.admit), a replica is killed and restarted
# mid-run, and a multiplexed informer fleet (client/watchmux.py) must
# fail over and converge.  Invariants on top of the pipeline ones: no
# watcher destructively terminated, no pinned server handler thread at
# quiesce, per-namespace rv-monotonic delivery across the failover
# (mux.violations), and bound-exactly-once AS SEEN THROUGH HTTP — every
# informer cache converges on the store's bindings.

SERVING_SEEDS = list(range(900, 910))


def _serving_fault_plan(rng: random.Random) -> faults.FaultRegistry:
    reg = faults.FaultRegistry(seed=rng.randint(0, 2 ** 31))
    reg.fail("server.request", n=rng.randint(1, 3), probability=0.5)
    reg.delay("server.request", seconds=0.002, n=5, probability=0.5)
    reg.torn_write("server.watch.write", frac=rng.random(), n=1)
    reg.fail("server.watch.write", n=rng.randint(1, 2), probability=0.5)
    reg.delay("server.watch.write", seconds=0.002, n=5, probability=0.5)
    reg.delay("apf.admit", seconds=0.002, n=5, probability=0.5)
    # a light dose of the pipeline plan: the serving plane must stay
    # healthy while the scheduler is healing its own faults
    reg.fail("batch.solve", n=1, probability=0.5)
    reg.fail("binder.commit_wave", n=1, probability=0.5)
    return reg


@pytest.mark.serving
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
@pytest.mark.parametrize("seed", SERVING_SEEDS)
def test_chaos_serving_plane(seed):
    from kubernetes_tpu.api.server import APIServerReplicaSet
    from kubernetes_tpu.client.rest import RestClient
    from kubernetes_tpu.client.watchmux import HttpWatchMux

    rng = random.Random(seed)
    reg = _serving_fault_plan(rng)
    store = st.Store()
    audit = _EventAudit(store)
    terminated0 = store.watchers_terminated
    for i in range(4):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=16000, mem=32 * GI, pods=110)
            .obj()
        )
    plane = APIServerReplicaSet(store, replicas=2)
    mux = HttpWatchMux(plane.urls(), threads=2)
    config = SchedulerConfiguration(
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        unschedulable_flush_seconds=0.5,
    )
    sched = Scheduler(store, assume_ttl=1.0, config=config)
    n_pods = rng.randint(24, 40)
    kill_at = rng.randint(n_pods // 3, 2 * n_pods // 3)
    try:
        infs = [mux.add_informer("Pod") for _ in range(6)]
        mux.start()
        with faults.armed(reg):
            sched.start()
            for i in range(n_pods):
                if i == kill_at:
                    victim = rng.randint(0, 1)
                    plane.kill(victim)
                    plane.restart(victim)
                    mux.set_urls(plane.urls())
                # create THROUGH the HTTP plane; injected 5xx and the
                # mid-run kill surface as client errors — retry, and
                # treat AlreadyExists as success (the failure can land
                # after the store committed)
                urls = plane.urls()
                for _ in range(50):
                    try:
                        RestClient(urls[i % len(urls)], timeout=5).create(
                            make_pod(f"sp{i}").req(
                                cpu_milli=rng.choice([50, 100, 200]),
                                mem=rng.choice([GI // 4, GI // 2]),
                            ).obj()
                        )
                        break
                    except st.AlreadyExists:
                        break
                    except Exception:  # noqa: BLE001 — injected 5xx
                        time.sleep(0.02)
                else:
                    raise AssertionError(f"seed {seed}: create sp{i} stuck")
                if rng.random() < 0.3:
                    time.sleep(rng.random() * 0.005)
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                pods, _ = store.list("Pod")
                if len(pods) == n_pods and all(
                    p.spec.node_name for p in pods
                ):
                    break
                time.sleep(0.1)

        # -- invariants (faults disarmed; the mux keeps converging) ------
        assert reg.fired, f"seed {seed}: no fault ever fired"
        pods, _ = store.list("Pod")
        assert len(pods) == n_pods
        unbound = [p.meta.name for p in pods if not p.spec.node_name]
        assert not unbound, (
            f"seed {seed}: pods never bound: {unbound[:5]}\n"
            f"  queue: {sched.queue.stats()}\n"
            f"  fired={reg.fired} pending={reg.pending()}"
        )
        # bound-exactly-once through the HTTP path: every informer's
        # cache converges on the store's bindings despite the torn
        # frames and the replica failover
        want = {
            f"{p.meta.namespace}/{p.meta.name}": p.spec.node_name
            for p in pods
        }

        def _converged():
            for inf in infs:
                cache = dict(inf.cache)
                if len(cache) != len(want):
                    return False
                for key, obj in cache.items():
                    if obj.spec.node_name != want.get(key):
                        return False
            return True

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not _converged():
            time.sleep(0.1)
        assert _converged(), (
            f"seed {seed}: informer caches diverged from store\n"
            f"  sizes={[len(i.cache) for i in infs]} want={len(want)}\n"
            f"  failovers={[i.failovers for i in infs]} "
            f"relists={[i.relists for i in infs]}"
        )
        assert mux.violations() == [], (
            f"seed {seed}: {mux.violations()[:5]}"
        )
        assert not audit.violations, f"seed {seed}: {audit.violations[:5]}"
        rebound = {
            k: nodes for k, nodes in audit.bound_nodes.items()
            if len(nodes) > 1
        }
        assert not rebound, f"seed {seed}: double binds {rebound}"
        # overload protection never tore a watcher down destructively
        assert store.watchers_terminated == terminated0, (
            f"seed {seed}: {store.watchers_terminated - terminated0} "
            f"watchers terminated"
        )
    finally:
        faults.disarm()
        mux.stop()
        sched.stop()
        plane.stop()
    # no pinned server handler thread once the clients are gone
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and plane.active_handlers():
        time.sleep(0.05)
    assert plane.active_handlers() == 0, (
        f"seed {seed}: server handler threads pinned at shutdown"
    )


# -- journal frame corruption: native vs pure-Python parity ------------------


def _frame_recovery(tmp_path, tag, native):
    """Bind three fixed 4-pod waves with the first two journal frames
    poisoned (CORRUPT flips one mid-frame byte), then replay.  Returns
    the recovery fingerprint; the parity test runs it against the
    native _hostplane CRC path and the pure-Python fallback and demands
    byte-identical outcomes."""
    from kubernetes_tpu.api import framing

    path = str(tmp_path / f"journal-{tag}.jsonl")
    saved = framing._hostplane
    if not native:
        framing._hostplane = None
    try:
        reg = faults.FaultRegistry(seed=7)
        reg.corrupt("journal.frame", n=2)
        store = st.Store(journal_path=path, journal_framing=True)
        names = [f"p{i}" for i in range(12)]
        for n in names:
            store.create(make_pod(n).obj())
        with faults.armed(reg):
            for w in range(3):
                batch = names[w * 4:(w + 1) * 4]

                def _bind(node):
                    def mutate(obj):
                        obj.spec.node_name = node
                    return mutate

                applied, errors = store.update_wave(
                    "Pod",
                    [(n, "default", _bind(f"n{j}"))
                     for j, n in enumerate(batch)],
                )
                assert not errors and len(applied) == 4
        assert reg.fired.get("journal.frame") == 2
        replayed = st.Store(journal_path=path)
        # each poisoned frame is rejected on exactly one of two paths:
        # flip landed in a string -> JSON still parses, the frame CRC
        # trips (torn wave); flip broke the JSON -> corrupt-record skip
        # (recovered).  Either way the wave drops WHOLE.
        dropped = (
            replayed.journal_torn_waves
            + replayed.journal_recovered_records
        )
        assert dropped == 2, (
            f"poisoned frames not rejected: torn="
            f"{replayed.journal_torn_waves} recovered="
            f"{replayed.journal_recovered_records}"
        )
        return {
            "bound": sorted(
                (p.meta.name, p.spec.node_name)
                for p in replayed.list("Pod")[0]
            ),
        }
    finally:
        faults.disarm()
        framing._hostplane = saved


@pytest.mark.serving
def test_chaos_journal_frame_native_fallback_parity(tmp_path):
    from kubernetes_tpu.api import framing

    native = _frame_recovery(tmp_path, "native", native=True)
    fallback = _frame_recovery(tmp_path, "fallback", native=False)
    # identical recovery either way: both drop EXACTLY the two poisoned
    # waves atomically (no half-applied bind) and keep the third
    assert native == fallback, f"native {native} != fallback {fallback}"
    bound = dict(native["bound"])
    for n in [f"p{i}" for i in range(8)]:
        assert bound[n] == "", f"poisoned-wave bind {n} leaked into replay"
    for j, n in enumerate(f"p{i}" for i in range(8, 12)):
        assert bound[n] == f"n{j}"
    if framing._hostplane is not None:
        # cross-compatibility: a native-encoded journal replays to the
        # same state through the pure-Python decode path
        saved = framing._hostplane
        framing._hostplane = None
        try:
            replayed = st.Store(
                journal_path=str(tmp_path / "journal-native.jsonl")
            )
            assert sorted(
                (p.meta.name, p.spec.node_name)
                for p in replayed.list("Pod")[0]
            ) == native["bound"]
            assert (
                replayed.journal_torn_waves
                + replayed.journal_recovered_records
            ) == 2
        finally:
            framing._hostplane = saved


# -- pod-axis sharded solve under the circuit breaker ------------------------


@pytest.mark.serving
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_chaos_pod_axis_breaker_host_fallback():
    """PR 16's pod-sharded wavefront under device failure: a wide batch
    (>= WAVEFRONT_MIN_PODS, so it routes through the pod-sharded twin)
    hits two injected solve failures — retry, then the breaker trips and
    the batch heals on the host fallback.  Every pod still binds exactly
    once."""
    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
    from kubernetes_tpu.parallel import sharded

    rng = random.Random(910)
    reg = faults.FaultRegistry(seed=910)
    reg.fail("batch.solve", n=2)  # first retries, second trips the breaker
    store = st.Store()
    audit = _EventAudit(store)
    for i in range(8):
        store.create(
            make_node(f"n{i}")
            .capacity(cpu_milli=64000, mem=128 * GI, pods=110)
            .obj()
        )
    mesh = sharded.make_pod_mesh(8)
    tpu = TPUBatchScheduler(mesh=mesh, solve_shard_axis="pod")
    assert tpu.solve_shard_axis == "pod"
    config = SchedulerConfiguration(
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.05,
        unschedulable_flush_seconds=0.5,
    )
    sched = Scheduler(store, tpu=tpu, assume_ttl=1.0, config=config)
    n_pods = 96  # one wide batch: routes wavefront on the pod axis
    for i in range(n_pods):
        store.create(
            make_pod(f"p{i}").req(
                cpu_milli=rng.choice([50, 100]), mem=GI // 4
            ).obj()
        )
    try:
        with faults.armed(reg):
            sched.start()
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                pods, _ = store.list("Pod")
                if pods and all(p.spec.node_name for p in pods):
                    break
                time.sleep(0.1)
        assert reg.fired.get("batch.solve") == 2
        pods, _ = store.list("Pod")
        unbound = [p.meta.name for p in pods if not p.spec.node_name]
        assert not unbound, (
            f"pods never bound past the breaker fallback: {unbound[:5]}\n"
            f"  breaker={sched.tpu.breaker.state} "
            f"fallbacks={sched.tpu.breaker.fallback_count()}\n"
            f"  queue: {sched.queue.stats()}"
        )
        # the healing path WAS the host fallback, on the pod-axis solver
        assert sched.tpu.breaker.fallback_count() > 0
        assert not audit.violations, audit.violations[:5]
        rebound = {
            k: nodes for k, nodes in audit.bound_nodes.items()
            if len(nodes) > 1
        }
        assert not rebound, f"double binds {rebound}"
    finally:
        faults.disarm()
        sched.stop()
