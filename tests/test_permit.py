"""Permit / WaitOnPermit + the waiting-pods map, and coscheduling held
at Permit.

References: framework/runtime/waiting_pods_map.go, the Permit extension
point (framework/interface.go:330-666), schedule_one.go:231 (RunPermit)
and :278 (WaitOnPermit in the async binding cycle).
"""

import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.coscheduling import CoschedulingPermit
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.scheduler.waitingpods import WaitingPod, WaitingPodsMap
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _mk_scheduler(store):
    s = Scheduler(store)
    s.start()  # informers + the scheduling loop (Permit needs the loop)
    return s


def _wait(cond, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_waiting_pod_allow_reject_timeout():
    wp = WaitingPod(make_pod("p").obj(), "n0", timeout=5)
    wp.allow()
    assert wp.wait() == "allow"
    wp2 = WaitingPod(make_pod("q").obj(), "n0", timeout=5)
    wp2.reject("custom")
    assert wp2.wait() == "custom"
    wp3 = WaitingPod(make_pod("r").obj(), "n0", timeout=0.05)
    assert wp3.wait() == "timeout"


def test_permit_wait_blocks_bind_until_allow():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=4000, pods=10).obj())
    sched = _mk_scheduler(store)
    sched.profiles.default.register(
        "permit", lambda pod, node: ("wait", 10.0)
    )
    try:
        store.create(make_pod("p").req(cpu_milli=100).obj())
        assert _wait(lambda: sched.waiting.get(
            store.get("Pod", "p")
        ) is not None, timeout=30)
        # parked at Permit: bind has NOT happened
        time.sleep(0.3)
        assert not store.get("Pod", "p").spec.node_name
        assert sched.waiting.allow(store.get("Pod", "p"))
        assert _wait(lambda: store.get("Pod", "p").spec.node_name == "n0")
    finally:
        sched.stop()


def test_permit_reject_requeues():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=4000, pods=10).obj())
    sched = _mk_scheduler(store)
    verdicts = iter([("reject", 0.0)])
    sched.profiles.default.register(
        "permit",
        lambda pod, node: next(verdicts, ("allow", 0.0)),
    )
    try:
        store.create(make_pod("p").req(cpu_milli=100).obj())
        # first attempt rejected; the retry (permit now allows) binds
        assert _wait(lambda: store.get("Pod", "p").spec.node_name == "n0",
                     timeout=30)
    finally:
        sched.stop()


def test_permit_timeout_requeues_and_retries():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=4000, pods=10).obj())
    sched = _mk_scheduler(store)
    calls = {"n": 0}

    def permit(pod, node):
        calls["n"] += 1
        if calls["n"] == 1:
            return "wait", 0.2  # nobody allows: times out
        return "allow", 0.0

    sched.profiles.default.register("permit", permit)
    try:
        store.create(make_pod("p").req(cpu_milli=100).obj())
        assert _wait(lambda: store.get("Pod", "p").spec.node_name == "n0",
                     timeout=30)
        assert calls["n"] >= 2
    finally:
        sched.stop()


def test_coscheduling_gang_holds_at_permit():
    """Members of an out-of-band-declared gang wait at Permit; the last
    arrival releases the whole group atomically."""
    store = st.Store()
    for i in range(4):
        store.create(
            make_node(f"n{i}").capacity(cpu_milli=2000, pods=10).obj()
        )
    sched = _mk_scheduler(store)
    cos = CoschedulingPermit(sched.waiting, sizes={"band": 3}, timeout=30)
    for fwk in sched.profiles:
        fwk.register("permit", cos.permit)
    try:
        # two members arrive: both park at Permit, neither binds
        for i in range(2):
            p = make_pod(f"g{i}").req(cpu_milli=500).obj()
            p.spec.scheduling_group = "band"  # no size: queue won't stage
            store.create(p)
        assert _wait(
            lambda: len([
                wp for wp in sched.waiting.iterate()
                if wp.pod.spec.scheduling_group == "band"
            ]) == 2,
            timeout=30,
        )
        time.sleep(0.3)
        assert all(
            not store.get("Pod", f"g{i}").spec.node_name for i in range(2)
        )
        # the third member completes the gang: everyone binds
        p = make_pod("g2").req(cpu_milli=500).obj()
        p.spec.scheduling_group = "band"
        store.create(p)
        assert _wait(
            lambda: all(
                store.get("Pod", f"g{i}").spec.node_name for i in range(3)
            ),
            timeout=30,
        )
    finally:
        sched.stop()


def test_coscheduling_gangs_namespaced():
    """Same-named gangs in different namespaces must not pool toward one
    quorum (review finding r4)."""
    store = st.Store()
    for i in range(6):
        store.create(
            make_node(f"n{i}").capacity(cpu_milli=2000, pods=10).obj()
        )
    sched = _mk_scheduler(store)
    cos = CoschedulingPermit(sched.waiting, sizes={"workers": 2}, timeout=30)
    for fwk in sched.profiles:
        fwk.register("permit", cos.permit)
    try:
        # one member in each namespace: two half-gangs, no quorum
        for ns in ("team-a", "team-b"):
            p = make_pod("w0", namespace=ns).req(cpu_milli=500).obj()
            p.spec.scheduling_group = "workers"
            store.create(p)
        assert _wait(lambda: len(sched.waiting.iterate()) == 2, timeout=30)
        time.sleep(0.3)
        for ns in ("team-a", "team-b"):
            assert not store.get("Pod", "w0", ns).spec.node_name
        # team-a's second member completes ONLY team-a's gang
        p = make_pod("w1", namespace="team-a").req(cpu_milli=500).obj()
        p.spec.scheduling_group = "workers"
        store.create(p)
        assert _wait(
            lambda: store.get("Pod", "w0", "team-a").spec.node_name
            and store.get("Pod", "w1", "team-a").spec.node_name,
            timeout=30,
        )
        assert not store.get("Pod", "w0", "team-b").spec.node_name
    finally:
        sched.stop()
