"""Columnar encoder parity: _build_pods_columnar vs the per-object
_build_pods oracle.

The columnar fast path must be bit-identical — snapshot tensors, dedup
tables, stable-signature ids, expansion watermark — across randomized
pod/node batches AND across the staleness hazards its persistent spec
store must track (vocabulary growth between batches: new node names,
new taints, new label ids under referenced keys, new scalar resources).
Every comparison here is exact array equality, never approximate.
"""

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import schema
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


def _assert_snap_equal(sa, sb):
    """Exact field-by-field equality of two Snapshots (nested
    NamedTuples of numpy arrays)."""
    for part_a, part_b, pname in zip(sa, sb, type(sa)._fields):
        for arr_a, arr_b, fname in zip(
            part_a, part_b, type(part_a)._fields
        ):
            np.testing.assert_array_equal(
                np.asarray(arr_a), np.asarray(arr_b),
                err_msg=f"{pname}.{fname} differs",
            )


def _random_pod(rng, i, known_nodes):
    p = make_pod(f"p{i}").req(
        cpu_milli=int(rng.choice([100, 250, 1000])),
        mem=int(rng.choice([GI, 2 * GI])),
    )
    if rng.random() < 0.3:
        p = p.req(**{"example.com/widgets": int(rng.integers(1, 4))})
    if rng.random() < 0.2:
        # known or (sometimes) not-yet-known node name: exercises the
        # -2 "named but unresolved" rows
        p = p.node_name(
            rng.choice(known_nodes) if rng.random() < 0.7
            else f"future-n{int(rng.integers(0, 4))}"
        )
    if rng.random() < 0.3:
        p = p.node_selector(disk=str(rng.choice(["ssd", "hdd"])))
    if rng.random() < 0.3:
        p = p.toleration(key="dedicated", op=api.OP_EQUAL,
                         value=str(rng.choice(["infra", "batch"])),
                         effect=api.NO_SCHEDULE)
    if rng.random() < 0.2:
        p = p.toleration(op=api.OP_EXISTS)
    if rng.random() < 0.25:
        p = p.host_port(int(rng.choice([8080, 9090, 9443])))
    if rng.random() < 0.3:
        op = rng.choice([api.OP_IN, api.OP_NOT_IN, api.OP_EXISTS])
        vals = () if op == api.OP_EXISTS else ("a", "b")
        p = p.required_affinity("tier", op, vals)
    if rng.random() < 0.25:
        p = p.preferred_affinity(int(rng.integers(1, 100)), "disk",
                                 api.OP_IN, ("ssd",))
    if rng.random() < 0.2:
        p = p.spread(topology_key=api.LABEL_ZONE, selector={"app": "x"})
    if rng.random() < 0.15:
        p = p.group(f"g{int(rng.integers(0, 3))}")
    p = p.priority(int(rng.integers(0, 5)))
    return p.obj()


def _node(i, extra_label=None, taint=None):
    w = (
        make_node(f"n{i}")
        .capacity(cpu_milli=16000, mem=32 * GI, pods=32)
        .zone(f"z{i % 3}")
        .label("disk", "ssd" if i % 2 else "hdd")
        .label("tier", ["a", "b", "c"][i % 3])
    )
    if extra_label:
        w = w.label(*extra_label)
    if taint:
        w = w.taint(*taint)
    return w.obj()


def _pair():
    """(oracle builder+state, columnar builder+state), fed identically."""
    out = []
    for columnar in (False, True):
        b = schema.SnapshotBuilder()
        b.columnar = columnar
        out.append((b, schema.ClusterState(b)))
    return out


def _both(states, fn):
    for _b, st in states:
        fn(st)


def _snap_pair(states, pods, hint=0):
    (bo, so), (bc, sc) = states
    snap_o, meta_o = bo.build_from_state(so, pods, num_pods_hint=hint)
    snap_c, meta_c = bc.build_from_state(sc, pods, num_pods_hint=hint)
    _assert_snap_equal(snap_o, snap_c)
    assert meta_o.sel_stable == meta_c.sel_stable
    assert meta_o.pref_stable == meta_c.pref_stable
    assert bo.expansion_watermark() == bc.expansion_watermark()
    return snap_o, snap_c


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_columnar_matches_per_object_randomized(seed):
    """Randomized multi-batch parity: same pods through both paths on
    the same incremental state produce byte-identical snapshots, stable
    ids, and watermarks — including repeated batches (warm store) and
    re-shuffled repeats of earlier specs."""
    rng = np.random.default_rng(seed)
    states = _pair()
    known = [f"n{i}" for i in range(6)]
    for i in range(6):
        _both(states, lambda st, i=i: st.add_node(_node(i)))

    prev = []
    for _batch in range(4):
        fresh = [
            _random_pod(rng, int(rng.integers(0, 10_000)), known)
            for _ in range(int(rng.integers(1, 24)))
        ]
        # re-offer a sample of earlier pods: warm rows in the store
        resample = [
            prev[j] for j in rng.permutation(len(prev))[: len(prev) // 2]
        ]
        batch = fresh + resample
        _snap_pair(states, batch)
        prev.extend(fresh)


def test_columnar_parity_across_vocab_growth():
    """The three staleness hazards, one per batch boundary: a node add
    that (a) resolves a previously-unknown node_name, (b) grows the
    taint vocabulary under a tolerated key, (c) grows the label ids
    under a referenced selector key — each must re-derive the cached
    columns, keeping parity exact."""
    states = _pair()
    for i in range(3):
        _both(states, lambda st, i=i: st.add_node(_node(i)))

    pods = [
        make_pod("named").req(cpu_milli=100).node_name("late-node").obj(),
        make_pod("tol").req(cpu_milli=100)
        .toleration(key="dedicated", op=api.OP_EXISTS,
                    effect=api.NO_SCHEDULE).obj(),
        make_pod("sel").req(cpu_milli=100)
        .required_affinity("tier", api.OP_EXISTS).obj(),
        make_pod("selnot").req(cpu_milli=100)
        .required_affinity("tier", api.OP_NOT_IN, ("z",)).obj(),
    ]
    _snap_pair(states, pods)

    # (a) the named node arrives: -2 rows must resolve to its id
    _both(states, lambda st: st.add_node(
        make_node("late-node").capacity(cpu_milli=8000, mem=8 * GI)
        .zone("z0").obj()
    ))
    s_o, _ = _snap_pair(states, pods)
    assert (np.asarray(s_o.pods.name_id)[:1] >= 0).all()

    # (b) a new taint under the tolerated key: toleration bitsets grow
    _both(states, lambda st: st.add_node(
        _node(8, taint=("dedicated", "batch", api.NO_SCHEDULE))
    ))
    _snap_pair(states, pods)

    # (c) new label ids under the referenced selector key "tier"
    _both(states, lambda st: st.add_node(
        _node(9, extra_label=("tier", "z"))
    ))
    _snap_pair(states, pods)


def test_columnar_parity_across_resource_axis_growth():
    """A later batch introducing a new scalar resource widens the
    resource axis; cached rows must zero-widen exactly."""
    states = _pair()
    for i in range(2):
        _both(states, lambda st, i=i: st.add_node(_node(i)))
    base = [make_pod("a").req(cpu_milli=100).obj(),
            make_pod("b").req(cpu_milli=250, mem=GI).obj()]
    _snap_pair(states, base)
    grown = base + [
        make_pod("c").req(cpu_milli=100, **{"vendor.io/gadgets": 2}).obj()
    ]
    _snap_pair(states, grown)
    # and the original pods again, post-widening
    _snap_pair(states, base)


def test_columnar_empty_and_padded_batches():
    states = _pair()
    _both(states, lambda st: st.add_node(_node(0)))
    _snap_pair(states, [])
    _snap_pair(states, [make_pod("x").req(cpu_milli=10).obj()], hint=32)
