"""API-server hardening: PATCH (merge semantics + conflict), the status
subresource, list selectors over REST, and authn/authz.

References: apiserver endpoints/handlers/patch.go (merge patch),
registry/core/pod/strategy.go (status strategy), apiserver/pkg/server/
config.go:983-1028 (the authn/authz chain slice).
"""

import pytest

from kubernetes_tpu.api import auth, store as st, types as api
from kubernetes_tpu.api.server import (
    APIServer,
    merge_patch,
    parse_field_selector,
    parse_label_selector,
)
from kubernetes_tpu.client.rest import RestClient
from kubernetes_tpu.testing.wrappers import MI, make_node, make_pod


@pytest.fixture
def server():
    store = st.Store()
    srv = APIServer(store).start()
    yield srv, store, RestClient(srv.url)
    srv.stop()


def test_merge_patch_semantics():
    base = {"a": {"b": 1, "c": 2}, "d": [1, 2], "e": "x"}
    patch = {"a": {"b": 9, "c": None}, "d": [3]}
    assert merge_patch(base, patch) == {"a": {"b": 9}, "d": [3], "e": "x"}


def test_patch_updates_labels(server):
    srv, store, client = server
    store.create(make_pod("p").labels(app="web").obj())
    got = client.patch(
        "Pod", "p", {"meta": {"labels": {"tier": "front"}}}
    )
    assert got.meta.labels == {"app": "web", "tier": "front"}
    assert store.get("Pod", "p").meta.labels["tier"] == "front"


def test_patch_status_subresource_ignores_spec(server):
    srv, store, client = server
    store.create(make_pod("p").req(cpu_milli=100).obj())
    client.patch(
        "Pod", "p",
        {"status": {"phase": "Running"}, "spec": {"node_name": "sneaky"}},
        subresource="status",
    )
    got = store.get("Pod", "p")
    assert got.status.phase == "Running"
    assert got.spec.node_name == ""  # spec write dropped


def test_put_status_subresource_ignores_spec(server):
    srv, store, client = server
    store.create(make_pod("p").obj())
    obj = client.get("Pod", "p")
    obj.status.phase = "Failed"
    obj.spec.node_name = "sneaky"
    got = client.update_status(obj)
    assert got.status.phase == "Failed"
    assert store.get("Pod", "p").spec.node_name == ""


def test_patch_conflict_on_concurrent_write(server):
    srv, store, client = server
    store.create(make_pod("p").obj())

    # patch applies against what it read; simulate a lost race by
    # patching with a stale rv via direct handler behavior: two patches
    # in a row both succeed (each reads fresh), so force staleness by
    # updating between read and write is internal — instead verify rv
    # advances and a stale PUT conflicts
    obj = client.get("Pod", "p")
    obj2 = client.get("Pod", "p")
    obj.meta.labels["a"] = "1"
    client.update(obj)
    obj2.meta.labels["b"] = "2"
    with pytest.raises(st.Conflict):
        client.update(obj2)


def test_list_selectors_over_rest(server):
    srv, store, client = server
    store.create(make_pod("w1").labels(app="web").obj())
    store.create(make_pod("w2").labels(app="web", tier="cache").obj())
    store.create(make_pod("d1").labels(app="db").obj())
    p = make_pod("bound").labels(app="web").obj()
    p.spec.node_name = "n7"
    store.create(p)

    items, _ = client.list("Pod", label_selector="app=web")
    assert {o.meta.name for o in items} == {"w1", "w2", "bound"}
    items, _ = client.list("Pod", label_selector="app=web,tier!=cache")
    assert {o.meta.name for o in items} == {"w1", "bound"}
    items, _ = client.list("Pod", label_selector="tier")
    assert {o.meta.name for o in items} == {"w2"}
    items, _ = client.list("Pod", field_selector="spec.nodeName=n7")
    assert {o.meta.name for o in items} == {"bound"}
    items, _ = client.list(
        "Pod", label_selector="app=web", field_selector="spec.nodeName="
    )
    assert {o.meta.name for o in items} == {"w1", "w2"}


def test_selector_parsers_direct():
    pod = make_pod("x").labels(app="web").obj()
    assert parse_label_selector("app=web")(pod)
    assert not parse_label_selector("app!=web")(pod)
    assert parse_field_selector("metadata.name=x")(pod)
    with pytest.raises(ValueError):
        parse_field_selector("spec.bogus=1")


def test_authn_authz_enforced():
    store = st.Store()
    authn = auth.TokenAuthenticator({
        "admin-token": auth.Subject("admin", ("system:masters",)),
        "viewer-token": auth.Subject("viewer", ("readers",)),
    })
    authz = auth.RuleAuthorizer([
        auth.Rule(subjects=("system:masters",)),               # full access
        auth.Rule(subjects=("readers",), verbs=auth.READ_VERBS),
    ])
    srv = APIServer(store, authn=authn, authz=authz).start()
    try:
        admin = RestClient(srv.url, token="admin-token")
        viewer = RestClient(srv.url, token="viewer-token")
        anon = RestClient(srv.url)
        bad = RestClient(srv.url, token="wrong")

        admin.create(make_pod("p").obj())

        # viewer: reads OK, writes 403
        assert viewer.get("Pod", "p").meta.name == "p"
        assert len(viewer.list("Pod")[0]) == 1
        with pytest.raises(RuntimeError):
            viewer.delete("Pod", "p")
        with pytest.raises(RuntimeError):
            viewer.create(make_pod("q").obj())
        with pytest.raises(RuntimeError):
            viewer.patch("Pod", "p", {"meta": {"labels": {"a": "b"}}})

        # no/unknown token: 401 on everything
        with pytest.raises(RuntimeError):
            anon.get("Pod", "p")
        with pytest.raises(RuntimeError):
            bad.list("Pod")

        # the store is untouched by rejected writes
        assert store.get("Pod", "p").meta.labels == {}
    finally:
        srv.stop()


def test_cli_patch_and_selector(server):
    srv, store, client = server
    from kubernetes_tpu import cli

    store.create(make_pod("p").labels(app="web").obj())
    cli.main([
        "--server", srv.url, "patch", "pod", "p",
        "-p", '{"status": {"phase": "Running"}}', "--subresource", "status",
    ])
    assert store.get("Pod", "p").status.phase == "Running"
    cli.main(["--server", srv.url, "get", "pods", "-l", "app=web"])


def test_label_selector_double_equals(server):
    srv, store, client = server
    store.create(make_pod("p").labels(app="web").obj())
    items, _ = client.list("Pod", label_selector="app==web")
    assert {o.meta.name for o in items} == {"p"}
