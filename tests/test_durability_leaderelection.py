"""Crash-only durability (store journal) and Lease leader election.

VERDICT acceptance: kill-and-restart resumes with identical state; a
standby takes over within the lease period.  Reference:
storage/etcd3 persistence + tools/leaderelection/leaderelection.go.
"""

import time

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.wire import from_wire, to_wire
from kubernetes_tpu.client.leaderelection import LeaderElector
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


def test_wire_roundtrip_pod():
    pod = (
        make_pod("p")
        .req(cpu_milli=500, mem=GI)
        .label("app", "x")
        .pod_anti_affinity({"app": "x"})
        .spread(2, api.LABEL_ZONE, "DoNotSchedule", {"app": "x"})
        .toleration("k", "v")
        .priority(7)
        .obj()
    )
    back = from_wire(to_wire(pod))
    assert back == pod


def test_wire_roundtrip_workloads():
    rs = api.ReplicaSet(
        meta=api.ObjectMeta(name="rs"),
        spec=api.ReplicaSetSpec(
            replicas=3,
            selector=api.LabelSelector(match_labels={"a": "b"}),
            template=api.PodTemplateSpec(
                meta=api.ObjectMeta(name="", labels={"a": "b"}),
                spec=api.PodSpec(containers=[api.Container(requests={api.CPU: 1})]),
            ),
        ),
    )
    assert from_wire(to_wire(rs)) == rs
    node = make_node("n").taint("k", "v").zone("z1").obj()
    assert from_wire(to_wire(node)) == node


def test_store_journal_replay(tmp_path):
    """Kill-and-restart: a journaled store resumes with identical objects
    and resourceVersion."""
    path = str(tmp_path / "journal.jsonl")
    s1 = st.Store(journal_path=path, shards=1)
    s1.create(make_node("n0").capacity(cpu_milli=4000, mem=8 * GI).obj())
    s1.create(make_pod("keep").req(cpu_milli=100).obj())
    doomed = s1.create(make_pod("gone").req(cpu_milli=100).obj())
    kept = s1.get("Pod", "keep")
    kept.spec.node_name = "n0"
    s1.update(kept)
    s1.delete("Pod", "gone", doomed.meta.namespace)
    rv = s1.resource_version

    # "crash": drop the instance, rebuild from the journal alone
    s2 = st.Store(journal_path=path, shards=1)
    assert s2.resource_version == rv
    pods, _ = s2.list("Pod")
    assert [p.meta.name for p in pods] == ["keep"]
    assert s2.get("Pod", "keep").spec.node_name == "n0"
    assert s2.get("Node", "n0", namespace="").status.allocatable[api.CPU] == 4000
    # writes continue after recovery and journal further restarts
    s2.create(make_pod("after").obj())
    s3 = st.Store(journal_path=path, shards=1)
    assert {p.meta.name for p in s3.list("Pod")[0]} == {"keep", "after"}
    # optimistic concurrency still enforced post-replay
    stale = s3.get("Pod", "keep")
    stale.meta.resource_version = 1
    try:
        s3.update(stale)
        assert False, "expected Conflict"
    except st.Conflict:
        pass


def test_leader_election_single_winner():
    store = st.Store()
    a = LeaderElector(store, "sched", "A", lease_duration=0.5, renew_period=0.05).start()
    b = LeaderElector(store, "sched", "B", lease_duration=0.5, renew_period=0.05).start()
    try:
        assert a.wait_for_leadership(5) or b.wait_for_leadership(5)
        time.sleep(0.3)
        assert a.is_leader() != b.is_leader(), "split brain"
    finally:
        a.stop()
        b.stop()


def test_leader_failover_within_lease():
    store = st.Store()
    a = LeaderElector(store, "sched", "A", lease_duration=0.6, renew_period=0.05).start()
    assert a.wait_for_leadership(5)
    b = LeaderElector(store, "sched", "B", lease_duration=0.6, renew_period=0.05).start()
    time.sleep(0.2)
    assert not b.is_leader()
    # leader dies WITHOUT releasing (hard crash): standby must take over
    # within lease_duration + renew_period
    a._stop.set()
    a._thread.join(timeout=5)
    t0 = time.monotonic()
    assert b.wait_for_leadership(5)
    took = time.monotonic() - t0
    assert took <= 0.6 + 0.5, f"failover took {took:.2f}s"
    b.stop()


def test_leader_graceful_release_is_fast():
    store = st.Store()
    a = LeaderElector(store, "sched", "A", lease_duration=5.0, renew_period=0.05).start()
    assert a.wait_for_leadership(5)
    b = LeaderElector(store, "sched", "B", lease_duration=5.0, renew_period=0.05).start()
    a.stop(release=True)  # zeroes renew_time
    assert b.wait_for_leadership(2), "release did not hand over quickly"
    b.stop()


def test_lease_transitions_recorded():
    store = st.Store()
    a = LeaderElector(store, "s", "A", lease_duration=0.3, renew_period=0.05).start()
    assert a.wait_for_leadership(5)
    a.stop(release=True)
    b = LeaderElector(store, "s", "B", lease_duration=0.3, renew_period=0.05).start()
    assert b.wait_for_leadership(5)
    lease = store.get("Lease", "s", "kube-system")
    assert lease.spec.holder_identity == "B"
    assert lease.spec.lease_transitions >= 1
    b.stop()


def test_two_schedulers_fail_over():
    """VERDICT acceptance: two Scheduler instances; the standby takes
    over within the lease period after the leader dies and schedules the
    remaining pods."""
    from kubernetes_tpu.scheduler import Scheduler

    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=8000, mem=8 * GI, pods=20).obj())
    el_a = LeaderElector(store, "kube-scheduler", "A",
                         lease_duration=0.6, renew_period=0.05).start()
    el_b = LeaderElector(store, "kube-scheduler", "B",
                         lease_duration=0.6, renew_period=0.05).start()
    sa = Scheduler(store, leader_elector=el_a)
    sb = Scheduler(store, leader_elector=el_b)
    for s in (sa, sb):
        s.informers.informer("Node").start()
        s.informers.informer("Pod").start()
        assert s.informers.wait_for_sync(10)
        s._thread = __import__("threading").Thread(target=s._run, daemon=True)
        s._thread.start()
    try:
        assert el_a.wait_for_leadership(5)
        store.create(make_pod("p1").req(cpu_milli=100).obj())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not store.get("Pod", "p1").spec.node_name:
            time.sleep(0.05)
        assert store.get("Pod", "p1").spec.node_name == "n0"
        # hard-kill the leader (loop + elector stop, no release)
        sa._stop.set()
        el_a._stop.set()
        el_a._thread.join(timeout=5)
        assert el_b.wait_for_leadership(5), "standby never took over"
        store.create(make_pod("p2").req(cpu_milli=100).obj())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not store.get("Pod", "p2").spec.node_name:
            time.sleep(0.05)
        assert store.get("Pod", "p2").spec.node_name == "n0"
    finally:
        sa.stop()
        sb.stop()
        el_a.stop()
        el_b.stop()


def test_leader_renew_failure_steps_down_once_then_reacquires():
    """Satellite: a failed renew must fire on_stopped_leading EXACTLY
    once (step down), and the holder re-acquires on the next period once
    renewal succeeds again — firing on_started_leading again."""
    from kubernetes_tpu.testing import faults

    store = st.Store()
    started, stopped = [], []
    a = LeaderElector(
        store, "sched", "A", lease_duration=5.0, renew_period=0.05,
        on_started_leading=lambda: started.append(time.monotonic()),
        on_stopped_leading=lambda: stopped.append(time.monotonic()),
    ).start()
    try:
        assert a.wait_for_leadership(5)
        assert len(started) == 1 and not stopped
        reg = faults.FaultRegistry().fail("leader.renew", n=1)
        with faults.armed(reg):
            deadline = time.monotonic() + 5
            while not stopped and time.monotonic() < deadline:
                time.sleep(0.01)
        assert len(stopped) == 1, "step-down did not fire exactly once"
        assert a.renew_errors == 1
        # the lease is still ours in the store: the next healthy renew
        # re-acquires and leadership resumes
        assert a.wait_for_leadership(5), "never re-acquired after renew blip"
        assert len(started) == 2
        assert len(stopped) == 1  # no spurious extra step-downs
    finally:
        faults.disarm()
        a.stop()


def test_renew_failure_pauses_scheduler_dispatch_until_reacquired():
    """Satellite: while stepped down the scheduler hot loop must not
    dispatch; once the elector re-acquires, pending pods schedule."""
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import faults
    from kubernetes_tpu.testing.wrappers import GI as _GI

    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=8000, mem=8 * _GI, pods=20).obj())
    el = LeaderElector(store, "kube-scheduler", "A",
                       lease_duration=5.0, renew_period=0.05).start()
    sched = Scheduler(store, leader_elector=el)
    sched.informers.informer("Node").start()
    sched.informers.informer("Pod").start()
    assert sched.informers.wait_for_sync(10)
    sched._thread = __import__("threading").Thread(
        target=sched._run, daemon=True
    )
    sched._thread.start()
    try:
        assert el.wait_for_leadership(5)
        # renew fails persistently: the holder steps down and STAYS down
        reg = faults.FaultRegistry().fail("leader.renew", n=-1)
        with faults.armed(reg):
            deadline = time.monotonic() + 5
            while el.is_leader() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not el.is_leader()
            store.create(make_pod("paused").req(cpu_milli=100).obj())
            time.sleep(0.4)  # several loop iterations while stepped down
            assert not store.get("Pod", "paused").spec.node_name, (
                "scheduler dispatched while not leading"
            )
        # faults disarmed: renewal recovers, dispatch resumes
        assert el.wait_for_leadership(5)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not store.get("Pod", "paused").spec.node_name:
            time.sleep(0.05)
        assert store.get("Pod", "paused").spec.node_name == "n0"
    finally:
        faults.disarm()
        sched.stop()
        el.stop()


def test_journal_tolerates_torn_tail(tmp_path):
    """A crash mid-append leaves a truncated last line; replay must stop
    at the last good record and keep working (review finding)."""
    path = str(tmp_path / "j.jsonl")
    s1 = st.Store(journal_path=path, shards=1)
    s1.create(make_pod("a").obj())
    s1.create(make_pod("b").obj())
    with open(path, "a") as f:
        f.write('{"op": "ADDED", "rv": 99, "kind": "Pod", "ke')  # torn
    s2 = st.Store(journal_path=path, shards=1)
    assert {p.meta.name for p in s2.list("Pod")[0]} == {"a", "b"}
    s2.create(make_pod("c").obj())  # appends continue cleanly
    s3 = st.Store(journal_path=path, shards=1)
    assert {p.meta.name for p in s3.list("Pod")[0]} == {"a", "b", "c"}


def test_journal_mid_file_corruption_keeps_later_records(tmp_path):
    """A corrupted NON-tail line (partial page write) must not discard
    the acknowledged-durable records after it — only a torn tail may be
    truncated (advisor finding r3)."""
    path = str(tmp_path / "j.jsonl")
    s1 = st.Store(journal_path=path, shards=1)
    s1.create(make_pod("a").obj())
    s1.create(make_pod("b").obj())
    s1.create(make_pod("c").obj())
    lines = open(path, "rb").read().splitlines(keepends=True)
    assert len(lines) == 3
    lines[1] = b'{"op": "ADDED", "rv": 2, "kind": "Pod", "ke\xff\xfe\n'
    with open(path, "wb") as f:
        f.writelines(lines)
    s2 = st.Store(journal_path=path, shards=1)
    names = {p.meta.name for p in s2.list("Pod")[0]}
    assert "c" in names, "record after corruption was dropped"
    assert names == {"a", "c"}
    s2.create(make_pod("d").obj())  # appends continue cleanly
    s3 = st.Store(journal_path=path, shards=1)
    assert {p.meta.name for p in s3.list("Pod")[0]} >= {"a", "c", "d"}


def test_journal_compaction_bounds_growth(tmp_path):
    """Churny updates (lease renewals) must not grow the journal without
    bound: compaction rewrites to one record per live object."""
    path = str(tmp_path / "j.jsonl")
    s = st.Store(journal_path=path, shards=1)
    lease = api.Lease(meta=api.ObjectMeta(name="l", namespace="kube-system"))
    s.create(lease)
    for _ in range(3000):
        fresh = s.get("Lease", "l", "kube-system")
        fresh.spec.renew_time += 1
        s.update(fresh)
    with open(path) as f:
        lines = sum(1 for _ in f)
    assert lines < 2000, f"journal grew to {lines} lines for 1 live object"
    # state survives compaction
    s2 = st.Store(journal_path=path, shards=1)
    assert s2.get("Lease", "l", "kube-system").spec.renew_time >= 2999


def test_journal_structurally_corrupt_line_skipped(tmp_path):
    """A mid-file line that parses as JSON but lost its record shape
    must be skipped like byte corruption, not crash Store startup
    (review finding r4)."""
    path = str(tmp_path / "j.jsonl")
    s1 = st.Store(journal_path=path, shards=1)
    s1.create(make_pod("a").obj())
    s1.create(make_pod("b").obj())
    lines = open(path, "rb").read().splitlines(keepends=True)
    lines[0] = b"42\n"  # valid JSON, not a record
    with open(path, "wb") as f:
        f.writelines(lines)
    s2 = st.Store(journal_path=path, shards=1)  # must not raise
    assert {p.meta.name for p in s2.list("Pod")[0]} == {"b"}


def test_journal_replay_round5_kinds(tmp_path):
    """Crash-resume over the round-5 surface: Services, EndpointSlices,
    CRDs + dynamic instances, RBAC, quotas, secrets, webhook configs,
    HPAs, and half-bound PV pairs all replay; a fresh control plane
    resumes against the recovered store."""
    from kubernetes_tpu.api import admission as adm
    from kubernetes_tpu.api import crd
    from kubernetes_tpu.api import types as api

    path = str(tmp_path / "cluster.jsonl")
    s1 = st.Store(journal_path=path, admission=adm.default_chain())
    s1.create(api.Service(
        meta=api.ObjectMeta(name="web"),
        spec=api.ServiceSpec(selector={"app": "web"},
                             ports=[api.ServicePort(name="http", port=80)]),
    ))
    crd.install_podgroup_crd(s1)
    s1.create(crd.pod_group("g1", min_member=3))
    s1.create(api.Role(meta=api.ObjectMeta(name="r", namespace="team"),
                       rules=[api.PolicyRule(verbs=["get"], resources=["Pod"])]))
    s1.create(api.ResourceQuota(meta=api.ObjectMeta(name="q"),
                                spec=api.ResourceQuotaSpec(hard={"pods": 5})))
    s1.create(api.Secret(meta=api.ObjectMeta(name="creds"),
                         string_data={"token": "abc"}))
    s1.create(api.HorizontalPodAutoscaler(meta=api.ObjectMeta(name="h")))
    s1.create(api.ValidatingAdmissionPolicy(
        meta=api.ObjectMeta(name="pol", namespace=""),
        spec=api.ValidatingAdmissionPolicySpec(
            match=api.WebhookRule(kinds=["Widget"]),
            validations=[api.PolicyValidation(expression="true")],
        ),
    ))
    vip = s1.get("Service", "web").spec.cluster_ip
    rv = s1.resource_version

    # crash: rebuild from the journal alone
    s2 = st.Store(journal_path=path, admission=adm.default_chain())
    assert s2.resource_version == rv
    assert s2.get("Service", "web").spec.cluster_ip == vip
    assert s2.get("PodGroup", "g1").spec["minMember"] == 3
    assert s2.get("CustomResourceDefinition",
                  "podgroups.scheduling.x-k8s.io").spec.names.kind == "PodGroup"
    assert s2.get("Role", "r", "team").rules[0].verbs == ["get"]
    assert s2.get("ResourceQuota", "q").spec.hard["pods"] == 5
    import base64
    assert base64.b64decode(
        s2.get("Secret", "creds").data["token"]
    ).decode() == "abc"
    assert s2.get("ValidatingAdmissionPolicy", "pol").spec.match.kinds == ["Widget"]
    # admission still enforces against the recovered state: an
    # unregistered dynamic kind is rejected
    try:
        s2.create(crd.DynamicObject("Gadget", meta=api.ObjectMeta(name="x")))
        raise AssertionError("unregistered dynamic kind was admitted")
    except adm.AdmissionError:
        pass
