"""VolumeBinding: PVC topology feasibility, Reserve/PreBind binding,
WaitForFirstConsumer provisioning, Unreserve rollback, attach limits.

Mirrors pkg/scheduler/framework/plugins/volumebinding/volume_binding.go
(:69 plugin protocol, :248 PreBind, :369 Reserve) — re-designed so the
per-node Filter work rides the existing selector/resource kernels (see
kubernetes_tpu/scheduler/volumebinding.py).
"""

import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing.wrappers import (
    GI,
    MI,
    make_node,
    make_pod,
    make_pv,
    make_pvc,
    make_storage_class,
)


def _cluster(store, zones=("z1", "z2", "z3"), per_zone=2, **node_kw):
    nodes = []
    for zi, z in enumerate(zones):
        for i in range(per_zone):
            n = (
                make_node(f"n-{z}-{i}")
                .capacity(cpu_milli=8000, mem=16 * GI, pods=32, **node_kw)
                .zone(z)
                .obj()
            )
            store.create(n)
            nodes.append(n)
    return nodes


def _wait_bound(store, name, timeout=30.0, ns="default"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pod = store.get("Pod", name, ns)
        if pod.spec.node_name:
            return pod
        time.sleep(0.05)
    return store.get("Pod", name, ns)


@pytest.fixture
def sched_store():
    store = st.Store()
    sched = Scheduler(store, batch_size=32)
    sched.start()
    yield sched, store
    sched.stop()


def test_bound_pvc_pins_pod_to_pv_topology(sched_store):
    sched, store = sched_store
    _cluster(store)
    pv = make_pv("pv-z2", 10 * GI, "manual", zone="z2")
    pv.spec.claim_ref = "default/claim"
    pv.status.phase = api.PV_BOUND
    store.create(pv)
    pvc = make_pvc("claim", 5 * GI, "manual")
    pvc.spec.volume_name = "pv-z2"
    pvc.status.phase = api.PVC_BOUND
    store.create(pvc)

    store.create(make_pod("p").req(cpu_milli=100, mem=MI).pvc("claim").obj())
    pod = _wait_bound(store, "p")
    assert pod.spec.node_name.startswith("n-z2-"), pod.spec.node_name


def test_unbound_pvc_binds_smallest_sufficient_pv(sched_store):
    sched, store = sched_store
    _cluster(store)
    store.create(make_pv("pv-big", 100 * GI, "manual", zone="z1"))
    store.create(make_pv("pv-small", 10 * GI, "manual", zone="z1"))
    store.create(make_pv("pv-tiny", 1 * GI, "manual", zone="z1"))
    store.create(make_pvc("claim", 5 * GI, "manual"))

    store.create(make_pod("p").req(cpu_milli=100, mem=MI).pvc("claim").obj())
    pod = _wait_bound(store, "p")
    assert pod.spec.node_name.startswith("n-z1-")
    pvc = store.get("PersistentVolumeClaim", "claim", "default")
    assert pvc.spec.volume_name == "pv-small"  # smallest sufficient
    assert pvc.status.phase == api.PVC_BOUND
    pv = store.get("PersistentVolume", "pv-small")
    assert pv.spec.claim_ref == "default/claim"
    assert pv.status.phase == api.PV_BOUND


def test_wait_for_first_consumer_provisions_in_allowed_topology(sched_store):
    sched, store = sched_store
    _cluster(store)
    store.create(
        make_storage_class("fast", provisioner="csi.example.com", zones=["z3"])
    )
    store.create(make_pvc("claim", 8 * GI, "fast"))
    store.create(make_pod("p").req(cpu_milli=100, mem=MI).pvc("claim").obj())
    pod = _wait_bound(store, "p")
    assert pod.spec.node_name.startswith("n-z3-"), pod.spec.node_name
    pvc = store.get("PersistentVolumeClaim", "claim", "default")
    assert pvc.spec.volume_name
    pv = store.get("PersistentVolume", pvc.spec.volume_name)
    assert pv.storage() == 8 * GI
    assert pv.spec.claim_ref == "default/claim"


def test_unsatisfiable_claim_parks_until_pv_appears(sched_store):
    sched, store = sched_store
    _cluster(store)
    store.create(make_pvc("claim", 5 * GI, "manual"))  # no PV, no provisioner
    store.create(make_pod("p").req(cpu_milli=100, mem=MI).pvc("claim").obj())
    time.sleep(2.0)
    assert not store.get("Pod", "p", "default").spec.node_name
    # a matching PV appears -> the PV event requeues the pod
    store.create(make_pv("pv-late", 10 * GI, "manual", zone="z1"))
    pod = _wait_bound(store, "p")
    assert pod.spec.node_name.startswith("n-z1-")


def test_missing_pvc_object_parks_pod(sched_store):
    sched, store = sched_store
    _cluster(store)
    store.create(make_pod("p").req(cpu_milli=100, mem=MI).pvc("ghost").obj())
    time.sleep(2.0)
    assert not store.get("Pod", "p", "default").spec.node_name


def test_attach_limit_spreads_pods_across_nodes(sched_store):
    sched, store = sched_store
    # one zone, 3 nodes, each allowing ONE csi.example.com attachment
    _cluster(
        store, zones=("z1",), per_zone=3,
        **{api.attach_limit_resource("csi.example.com"): 1},
    )
    store.create(
        make_storage_class("fast", provisioner="csi.example.com")
    )
    for i in range(3):
        store.create(make_pvc(f"claim-{i}", GI, "fast"))
        store.create(
            make_pod(f"p{i}").req(cpu_milli=100, mem=MI)
            .pvc(f"claim-{i}").obj()
        )
    pods = [_wait_bound(store, f"p{i}") for i in range(3)]
    nodes = [p.spec.node_name for p in pods]
    assert all(nodes), nodes
    assert len(set(nodes)) == 3, f"attach limit 1 must spread: {nodes}"


def test_unreserve_rolls_back_on_bind_failure(sched_store):
    sched, store = sched_store
    _cluster(store, zones=("z1",), per_zone=1)
    store.create(make_pv("pv-a", 10 * GI, "manual", zone="z1"))
    store.create(make_pvc("claim", GI, "manual"))

    # binds commit through the wave transaction now: inject the failure
    # at that layer (one split error for pod "p", first wave only)
    calls = {"n": 0}
    orig_wave = store.update_wave

    def failing_wave(kind, updates, **kw):
        if calls["n"] == 0 and any(u[0] == "p" for u in updates):
            calls["n"] += 1
            good = [u for u in updates if u[0] != "p"]
            applied, errors = orig_wave(kind, good, **kw)
            errors["default/p"] = RuntimeError("injected bind conflict")
            return applied, errors
        return orig_wave(kind, updates, **kw)

    store.update_wave = failing_wave
    store.create(make_pod("p").req(cpu_milli=100, mem=MI).pvc("claim").obj())
    pod = _wait_bound(store, "p")
    # first attempt failed after Reserve; Unreserve must have rolled the
    # assumption back so the retry could re-reserve the same volume
    assert pod.spec.node_name == "n-z1-0"
    pvc = store.get("PersistentVolumeClaim", "claim", "default")
    assert pvc.spec.volume_name == "pv-a"
    assert calls["n"] == 1
    assert not sched.volumes._assumed_pv and not sched.volumes._assumed_claim


def test_rwo_multi_attach_colocates_consumers():
    """VolumeRestrictions multi-attach (volume_restrictions.go:306): a
    ReadWriteOnce volume in use on node X forces later consumers onto
    X — they share the single attachment instead of failing mounts."""
    import time as _t

    store = st.Store()
    for i in range(3):
        store.create(
            make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI).obj()
        )
    pv = api.PersistentVolume(
        meta=api.ObjectMeta(name="disk"),
        spec=api.PersistentVolumeSpec(
            capacity={api.STORAGE: 10 * GI},
            access_modes=["ReadWriteOnce"],
            storage_class_name="std",
        ),
    )
    store.create(pv)
    pvc = api.PersistentVolumeClaim(
        meta=api.ObjectMeta(name="data"),
        spec=api.PersistentVolumeClaimSpec(
            access_modes=["ReadWriteOnce"],
            storage_class_name="std",
            resources={api.STORAGE: 5 * GI},
            volume_name="disk",
        ),
    )
    store.create(pvc)
    sched = Scheduler(store, batch_size=8)
    sched.start()
    try:
        store.create(make_pod("first").req(cpu_milli=100).pvc("data").obj())
        deadline = _t.monotonic() + 60
        while _t.monotonic() < deadline:
            first = store.get("Pod", "first")
            if first.spec.node_name:
                break
            _t.sleep(0.05)
        assert first.spec.node_name
        # the second consumer must land on the SAME node
        store.create(make_pod("second").req(cpu_milli=100).pvc("data").obj())
        deadline = _t.monotonic() + 60
        while _t.monotonic() < deadline:
            second = store.get("Pod", "second")
            if second.spec.node_name:
                break
            _t.sleep(0.05)
        assert second.spec.node_name == first.spec.node_name
    finally:
        sched.stop()
