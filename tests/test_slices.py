"""TPU slice topology: torus-aware gang carve-outs.

The acceptance surface of the slice subsystem (docs/scheduler_loop.md
"TPU slice topology"):

  * batched carve-out placement is bit-identical to the host per-pod
    oracle on randomized topologies — gangs that cannot fit contiguously
    included — under both the prefer and require policies;
  * require mode parks unfittable gangs whole (all-or-nothing releases
    the anchor too) with REASON_SLICE;
  * the fragmentation kernel scores packing health;
  * topology-shaped device claims record carve-outs and pin sharers
    inside them through the batched filter;
  * CoschedulingPermit's release-point carve-out check (prefer counts,
    require rejects);
  * the sharded-mesh twin is assignment-identical (multichip mark).
"""

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
from kubernetes_tpu.ops import assign, schema, slices as slices_ops
from kubernetes_tpu.testing.oracle import Oracle
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def slice_node(slice_name, x, y, z, dims, name=None, cpu=4000, core=None):
    nw = (
        make_node(name or f"{slice_name}-{x}{y}{z}" + (f"c{core}" if core else ""))
        .capacity(cpu_milli=cpu, mem=8 * GI, pods=16)
        .label(api.LABEL_TPU_SLICE, slice_name)
        .label(api.LABEL_TPU_TOPOLOGY, "x".join(map(str, dims)))
        .label(api.LABEL_TPU_COORDS, f"{x},{y},{z}")
    )
    if core is not None:
        nw.label(api.LABEL_TPU_CORE, str(core))
    return nw.obj()


def mk_slices(n_slices, dims, cpu=4000):
    return [
        slice_node(f"slice-{s}", x, y, z, dims, cpu=cpu)
        for s in range(n_slices)
        for z in range(dims[2])
        for y in range(dims[1])
        for x in range(dims[0])
    ]


def gang(name, size, shape, cpu=100, priority=0):
    out = []
    for i in range(size):
        p = (
            make_pod(f"{name}-{i}")
            .req(cpu_milli=cpu)
            .group(name)
            .priority(priority)
            .obj()
        )
        p.spec.tpu_topology = shape
        out.append(p)
    return out


def host_gang_release(pods, names):
    """The gang all-or-nothing post-pass, host-side (mirrors
    TPUBatchScheduler._host_fallback)."""
    groups = {}
    for i, p in enumerate(pods):
        g = p.spec.scheduling_group
        if g:
            groups.setdefault(g, []).append(i)
    for idx in groups.values():
        if any(names[i] is None for i in idx):
            for i in idx:
                names[i] = None
    return names


def solve_both(nodes, pods, policy, bound=()):
    snap, meta = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    features = assign.features_of(snap, slice_policy=policy)
    n_groups = schema.num_groups(snap)
    result = assign.greedy_assign(snap, features=features, n_groups=n_groups)
    got = [
        meta.node_name(int(i))
        for i in np.asarray(result.assignment)[: len(pods)]
    ]
    # the oracle consumes pods in the solver's pop order (priority desc,
    # batch index asc); scatter its answers back to batch positions
    order = sorted(
        range(len(pods)), key=lambda i: (-pods[i].spec.priority, i)
    )
    oracle = Oracle(nodes, bound_pods=bound, slice_policy=policy)
    want = [None] * len(pods)
    for i in order:
        want[i] = oracle.schedule_one(pods[i])
    want = host_gang_release(pods, want)
    return got, want, result, features


# -- encode ------------------------------------------------------------------


def test_encode_slice_tensors():
    nodes = mk_slices(2, (2, 2, 2))
    snap, _ = schema.SnapshotBuilder().build(nodes, [make_pod("p").obj()])
    cl = snap.cluster
    assert (cl.slice_id[:16] >= 0).all()
    assert set(cl.slice_id[:16].tolist()) == {0, 1}
    # linear in-slice position covers the slice exactly once
    for s in (0, 1):
        pos = cl.slice_pos[:16][cl.slice_id[:16] == s]
        assert sorted(pos.tolist()) == list(range(8))
    assert (cl.slice_dims[:16] == 2).all()
    # padding rows are absent
    assert (cl.slice_id[16:] == -1).all()


def test_encode_malformed_labels_degrade_to_absent():
    good = slice_node("s", 0, 0, 0, (2, 2, 2))
    bad = (
        make_node("bad")
        .capacity(cpu_milli=4000, mem=8 * GI, pods=16)
        .label(api.LABEL_TPU_SLICE, "s")
        .label(api.LABEL_TPU_TOPOLOGY, "wat")
        .label(api.LABEL_TPU_COORDS, "0,0,0")
        .obj()
    )
    oob = slice_node("s", 0, 0, 0, (2, 2, 2), name="oob")
    oob.meta.labels[api.LABEL_TPU_COORDS] = "5,0,0"  # outside the extent
    snap, _ = schema.SnapshotBuilder().build(
        [good, bad, oob], [make_pod("p").obj()]
    )
    assert snap.cluster.slice_id[0] == 0
    assert snap.cluster.slice_id[1] == -1
    assert snap.cluster.slice_id[2] == -1


def test_encode_over_cap_extent_raises():
    node = slice_node("s", 0, 0, 0, (32, 2, 2))
    builder = schema.SnapshotBuilder(schema.SnapshotLimits(max_slice_dim=16))
    with pytest.raises(OverflowError):
        builder.build([node], [make_pod("p").obj()])


def test_pod_shape_encode_and_class_split():
    nodes = mk_slices(1, (2, 2, 2))
    a = make_pod("a").req(cpu_milli=100).obj()
    b = make_pod("b").req(cpu_milli=100).obj()
    b.spec.tpu_topology = "2x1x1"
    snap, _ = schema.SnapshotBuilder().build(nodes, [a, b])
    assert snap.pods.pod_shape[0].tolist() == [0, 0, 0]
    assert snap.pods.pod_shape[1].tolist() == [2, 1, 1]
    # shaped and unshaped pods must not share a spec class
    assert snap.pods.class_id[0] != snap.pods.class_id[1]


# -- kernels -----------------------------------------------------------------


def test_corner_mask_basic():
    import jax.numpy as jnp

    nodes = mk_slices(1, (2, 2, 2))
    snap, meta = schema.SnapshotBuilder().build(nodes, [make_pod("p").obj()])
    cl = snap.cluster
    free = slices_ops.free_devices(
        type(cl)(*[jnp.asarray(x) for x in cl])
    )
    corners = slices_ops.corner_mask(
        type(cl)(*[jnp.asarray(x) for x in cl]), free,
        jnp.asarray([2, 2, 1], jnp.int32), 1, 2,
    )
    got = {
        meta.node_name(i)
        for i in range(len(nodes))
        if bool(np.asarray(corners)[i])
    }
    # a 2x2x1 box anchors at z=0 and z=1 origin corners only
    assert got == {"slice-0-000", "slice-0-001"}


def test_fragmentation_report():
    nodes = mk_slices(2, (2, 2, 2))
    sched = TPUBatchScheduler()
    for nd in nodes:
        sched.add_node(nd)
    rep = slices_ops.fragmentation_report(sched.state.tensors())
    assert rep["score"] == 0.0           # empty slices: two full 2-cubes
    assert rep["largest_cube"] == [2, 2]
    assert rep["free_count"] == [8.0, 8.0]
    # occupy one device of slice 0: its largest cube drops to 1
    pod = make_pod("x").req(cpu_milli=100).obj()
    sched.assume(pod, "slice-0-000")
    rep = slices_ops.fragmentation_report(sched.state.tensors())
    assert rep["largest_cube"] == [1, 2]
    assert rep["free_count"] == [7.0, 8.0]
    assert rep["score"] > 0.0


def test_multicore_coordinate_free_only_when_all_cores_free():
    import jax.numpy as jnp

    # two nodes share coordinate (0,0,0) (core 0/1); occupy one of them
    nodes = [
        slice_node("s", 0, 0, 0, (2, 1, 1), core=0),
        slice_node("s", 0, 0, 0, (2, 1, 1), core=1),
        slice_node("s", 1, 0, 0, (2, 1, 1)),
    ]
    bound = make_pod("b").req(cpu_milli=100).node_name(nodes[0].meta.name).obj()
    snap, _ = schema.SnapshotBuilder().build(
        nodes, [make_pod("p").obj()], bound_pods=[bound]
    )
    cl = type(snap.cluster)(*[jnp.asarray(x) for x in snap.cluster])
    free = slices_ops.free_devices(cl)
    corners = slices_ops.corner_mask(
        cl, free, jnp.asarray([2, 1, 1], jnp.int32), 1, 2
    )
    assert not np.asarray(corners)[:3].any()  # (0,0,0) cell not fully free


# -- solver parity -----------------------------------------------------------


@pytest.mark.parametrize("policy", ["prefer", "require"])
def test_gang_carveout_parity_basic(policy):
    nodes = mk_slices(2, (2, 2, 2))
    pods = gang("g0", 4, "2x2x1") + gang("g1", 8, "2x2x2") + gang(
        "g2", 2, "2x1x1"
    )
    got, want, result, _ = solve_both(nodes, pods, policy)
    assert got == want
    # every gang landed whole and contiguous
    assert int(result.contiguous_gangs) == 3
    assert int(result.carveout_fallbacks) == 0


@pytest.mark.parametrize("policy", ["prefer", "require"])
def test_unfittable_gang_parity(policy):
    """A 3x3x3 request cannot fit a 2x2x2 slice: require parks it whole;
    prefer scatters it (carveout fallback) — both parity-identical."""
    nodes = mk_slices(1, (2, 2, 2))
    pods = gang("big", 4, "3x3x3")
    got, want, result, _ = solve_both(nodes, pods, policy)
    assert got == want
    if policy == "require":
        assert got == [None] * 4
        reasons = np.asarray(result.reasons)[:4]
        assert (reasons == assign.REASON_SLICE).all()
        assert int(result.contiguous_gangs) == 0
    else:
        assert None not in got


def test_prefer_mode_counts_fallbacks():
    """Free devices exist but no contiguous 2x2x1 box: prefer scatters
    and counts the gang as a carve-out fallback."""
    nodes = mk_slices(1, (2, 2, 1))
    # occupy one device so no 2x2x1 box is free
    bound = make_pod("b").req(cpu_milli=100).node_name("slice-0-000").obj()
    pods = gang("g", 2, "2x2x1")
    got, want, result, _ = solve_both(nodes, pods, "prefer", bound=[bound])
    assert got == want
    assert None not in got
    assert int(result.carveout_fallbacks) == 1
    assert int(result.contiguous_gangs) == 0


def test_require_holds_capacity_feasible_but_fragmented():
    """Capacity fits the gang, but the free devices are not contiguous:
    require must park the gang (the workload spread/affinity never
    stresses — fragmentation-aware all-or-nothing)."""
    nodes = mk_slices(1, (2, 2, 1))
    bound = make_pod("b").req(cpu_milli=100).node_name("slice-0-000").obj()
    pods = gang("g", 2, "2x1x1")  # a free 2x1x1 box still exists at y=1
    got, want, result, _ = solve_both(nodes, pods, "require", bound=[bound])
    assert got == want
    assert set(got) == {"slice-0-010", "slice-0-110"}
    # now occupy the diagonal so only scattered singles remain
    bound2 = make_pod("b2").req(cpu_milli=100).node_name("slice-0-110").obj()
    got2, want2, result2, _ = solve_both(
        nodes, pods, "require", bound=[bound, bound2]
    )
    assert got2 == want2 == [None, None]


def test_best_fit_prefers_tighter_slice():
    """Two slices fit; the anchor best-fit (leftover minimization) picks
    the one the gang fills exactly."""
    nodes = mk_slices(1, (2, 2, 2)) + [
        slice_node("small", x, y, 0, (2, 1, 1))
        for x in range(2)
        for y in range(1)
    ]
    pods = gang("g", 2, "2x1x1")
    got, want, result, _ = solve_both(nodes, pods, "prefer")
    assert got == want
    assert all(n.startswith("small") for n in got)


def test_off_policy_disarms_family():
    nodes = mk_slices(1, (2, 2, 2))
    pods = gang("g", 2, "3x3x3")  # unfittable shape, but family is off
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    features = assign.features_of(snap, slice_policy="off")
    assert not features.slices
    result = assign.greedy_assign(
        snap, features=features, n_groups=schema.num_groups(snap)
    )
    assert (np.asarray(result.assignment)[:2] >= 0).all()
    assert result.frag_score is None


@pytest.mark.parametrize("seed", range(6))
def test_randomized_topology_parity(seed):
    """Randomized slices/gangs/occupancy across both policies — the
    acceptance parity suite (gangs that cannot fit included)."""
    rng = np.random.default_rng(seed)
    policy = ["prefer", "require"][seed % 2]
    dims = tuple(int(d) for d in rng.choice([1, 2, 3], size=3) + 1)
    n_slices = int(rng.integers(1, 4))
    nodes = mk_slices(n_slices, dims)
    # a few non-slice nodes ride along (prefer-mode fallback targets)
    for i in range(int(rng.integers(0, 3))):
        nodes.append(
            make_node(f"plain-{i}")
            .capacity(cpu_milli=4000, mem=8 * GI, pods=16)
            .obj()
        )
    # random pre-bound occupancy
    bound = []
    for i, nd in enumerate(nodes):
        if rng.random() < 0.2:
            bound.append(
                make_pod(f"bound-{i}")
                .req(cpu_milli=100)
                .node_name(nd.meta.name)
                .obj()
            )
    pods = []
    for g in range(int(rng.integers(1, 4))):
        shape = [int(s) for s in rng.integers(1, 4, size=3)]
        vol = shape[0] * shape[1] * shape[2]
        size = int(rng.integers(1, vol + 1))
        pods += gang(
            f"g{g}", size, "x".join(map(str, shape)),
            priority=int(rng.integers(0, 3)),
        )
    # unshaped singles mixed in
    for i in range(int(rng.integers(0, 4))):
        pods.append(make_pod(f"solo-{i}").req(cpu_milli=100).obj())
    got, want, _result, features = solve_both(nodes, pods, policy)
    assert features.slices
    assert got == want, (
        f"seed {seed} policy {policy} dims {dims}: {got} != {want}"
    )


def test_host_fallback_parity_with_device_solve():
    """The breaker's host fallback (Oracle) must agree with the device
    solve on slice batches — it IS the parity twin in degraded mode."""
    nodes = mk_slices(2, (2, 2, 1))
    pods = gang("g0", 4, "2x2x1") + gang("g1", 2, "2x1x1")
    sched = TPUBatchScheduler(carveout_policy="require")
    for nd in nodes:
        sched.add_node(nd)
    device_names = sched.schedule_pending(pods)
    fallback = sched._host_fallback(pods)
    assert fallback.names() == device_names


# -- routing -----------------------------------------------------------------


def test_route_pins_slice_batches_to_classic_greedy():
    nodes = mk_slices(8, (2, 2, 2))
    pods = []
    for g in range(16):
        pods += gang(f"g{g}", 4, "2x2x1")
    sched = TPUBatchScheduler()
    for nd in nodes:
        sched.add_node(nd)
    snap, meta = sched.encode_pending(pods)
    assert meta.features.slices
    # 64 pods with gangs would otherwise route wavefront/auction
    assert meta.route == "greedy"
    names = sched.finalize_pending(pods, sched.solve_encoded_async(snap, meta))
    assert all(n is not None for n in names)


def test_wavefront_rejects_slice_features():
    nodes = mk_slices(1, (2, 2, 2))
    pods = gang("g", 2, "2x1x1")
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    features = assign.features_of(snap)
    with pytest.raises(ValueError, match="classic greedy scan"):
        assign.wavefront_assign(snap, None, features=features)


def test_auction_declines_slice_features():
    from kubernetes_tpu.ops.auction import auction_features_ok

    assert not auction_features_ok(
        assign.FeatureFlags(slices=True, slice_z=2, slice_dim=2)
    )
    assert auction_features_ok(assign.FeatureFlags())


# -- incremental state / mirror ----------------------------------------------


def test_mirror_tracks_slice_label_updates():
    """A node's slice labels change (re-tessellation): the delta sync
    must carry the new coordinates into the resident tensors."""
    nodes = mk_slices(1, (2, 2, 1))
    sched = TPUBatchScheduler(carveout_policy="require")
    for nd in nodes:
        sched.add_node(nd)
    pods = gang("g", 4, "2x2x1")
    assert all(n is not None for n in sched.schedule_pending(pods))
    # the slice shrinks to 2x1x1: a 2x2x1 gang no longer fits
    for nd in nodes:
        x, y, _z = api.parse_coords(nd.meta.labels[api.LABEL_TPU_COORDS])
        nd.meta.labels[api.LABEL_TPU_TOPOLOGY] = "2x1x1"
        if y > 0:
            del nd.meta.labels[api.LABEL_TPU_COORDS]
            nd.meta.labels[api.LABEL_TPU_COORDS] = f"{x},5,0"  # out of extent
        sched.update_node(nd)
    got = sched.schedule_pending(gang("g2", 4, "2x2x1"))
    assert got == [None] * 4


# -- topology-shaped device claims -------------------------------------------


def _wait(cond, timeout=30.0):
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


@pytest.fixture
def slice_store():
    from kubernetes_tpu.api import store as st
    from kubernetes_tpu.scheduler.scheduler import Scheduler

    store = st.Store()
    sched = Scheduler(store, batch_size=32)
    sched.start()
    yield sched, store
    sched.stop()


def test_shaped_claim_records_carveout_and_pins_sharers(slice_store):
    from kubernetes_tpu.scheduler.deviceclaims import parse_carveout

    sched, store = slice_store
    for nd in mk_slices(2, (2, 2, 1)):
        nd.status.allocatable[api.device_resource("tpu")] = 1
        store.create(nd)
    store.create(api.DeviceClass(meta=api.ObjectMeta(name="tpu")))
    claim = api.ResourceClaim(
        meta=api.ObjectMeta(name="carve"),
        spec=api.ResourceClaimSpec(
            device_class_name="tpu", count=1, topology="2x2x1"
        ),
    )
    store.create(claim)
    carrier = make_pod("carrier").req(cpu_milli=100, mem=MI).obj()
    carrier.spec.resource_claims = ["carve"]
    store.create(carrier)
    assert _wait(lambda: store.get("Pod", "carrier").spec.node_name)
    got = store.get("ResourceClaim", "carve")
    assert got.status.phase == "Allocated"
    carve = parse_carveout(got.status.carveout)
    assert carve is not None
    sname, lo, shape = carve
    assert shape == (2, 2, 1)
    assert lo == (0, 0, 0)  # the carrier anchored a free-box corner
    # a sharer pins INSIDE the carve-out (batched filter), not onto the
    # carrier's node specifically
    sharer = make_pod("sharer").req(cpu_milli=100, mem=MI).obj()
    sharer.spec.resource_claims = ["carve"]
    store.create(sharer)
    assert _wait(lambda: store.get("Pod", "sharer").spec.node_name)
    node = store.get(
        "Node", store.get("Pod", "sharer").spec.node_name
    )
    assert node.meta.labels[api.LABEL_TPU_SLICE] == sname
    x, y, z = api.parse_coords(node.meta.labels[api.LABEL_TPU_COORDS])
    assert (lo[0] <= x < lo[0] + 2) and (lo[1] <= y < lo[1] + 2) and z == 0


# -- CoschedulingPermit carve-out check --------------------------------------


def _release_gang(permit, members, nodes_of):
    """Drive a gang through Permit: all but the last park, the last
    triggers the release.  Returns the verdicts."""
    import threading

    from kubernetes_tpu.scheduler.waitingpods import WaitingPod

    verdicts = {}
    threads = []
    for pod, node in members[:-1]:
        verdict, timeout = permit.permit(pod, node)
        assert verdict == "wait"
        wp = WaitingPod(pod, node, timeout)
        permit.waiting.add(wp)

        def waiter(wp=wp, pod=pod):
            verdicts[pod.meta.name] = wp.wait()

        t = threading.Thread(target=waiter)
        t.start()
        threads.append(t)
    last_pod, last_node = members[-1]
    verdicts[last_pod.meta.name] = permit.permit(last_pod, last_node)[0]
    for t in threads:
        t.join(timeout=5)
    return verdicts


@pytest.mark.parametrize("carveout", ["prefer", "require"])
def test_coscheduling_carveout_release(carveout):
    from kubernetes_tpu.scheduler.coscheduling import CoschedulingPermit
    from kubernetes_tpu.scheduler.metrics import Registry
    from kubernetes_tpu.scheduler.waitingpods import WaitingPodsMap

    nodes = {n.meta.name: n for n in mk_slices(1, (2, 2, 1))}
    metrics = Registry()
    permit = CoschedulingPermit(
        WaitingPodsMap(), sizes={"g": 2}, timeout=2.0,
        carveout=carveout, node_lookup=nodes.get, metrics=metrics,
    )
    pods = gang("g", 2, "2x1x1")
    # contiguous pair: released either way, counted contiguous
    verdicts = _release_gang(
        permit, list(zip(pods, ["slice-0-000", "slice-0-100"])), nodes
    )
    assert set(verdicts.values()) == {"allow"}
    assert metrics.gang_contiguous_placements.total == 1
    # fragmented pair (diagonal): prefer releases + counts a fallback,
    # require rejects every member
    pods2 = gang("g", 2, "2x1x1")
    verdicts = _release_gang(
        permit, list(zip(pods2, ["slice-0-000", "slice-0-110"])), nodes
    )
    if carveout == "prefer":
        assert set(verdicts.values()) == {"allow"}
        assert metrics.slice_carveout_fallbacks.total == 1
    else:
        assert "allow" not in verdicts.values()
        assert metrics.slice_carveout_fallbacks.total == 1


def test_carveout_contiguous_helper():
    from kubernetes_tpu.scheduler.coscheduling import carveout_contiguous

    nodes = {n.meta.name: n for n in mk_slices(2, (2, 2, 1))}
    assert carveout_contiguous(
        [nodes["slice-0-000"], nodes["slice-0-100"]]
    )
    assert not carveout_contiguous(
        [nodes["slice-0-000"], nodes["slice-0-110"]]  # diagonal: bbox 4 != 2
    )
    assert not carveout_contiguous(
        [nodes["slice-0-000"], nodes["slice-1-000"]]  # two slices
    )
    assert not carveout_contiguous(
        [nodes["slice-0-000"], nodes["slice-0-000"]]  # duplicate device
    )


# -- config / scheduler threading --------------------------------------------


def test_config_knob_reaches_solver():
    from kubernetes_tpu.api import store as st
    from kubernetes_tpu.scheduler.config import SchedulerConfiguration
    from kubernetes_tpu.scheduler.scheduler import Scheduler

    config = SchedulerConfiguration(
        slice_carveout_policy="require", slice_max_dim=8
    )
    sched = Scheduler(st.Store(), config=config)
    assert sched.tpu.carveout_policy == "require"
    assert sched.tpu.builder.limits.max_slice_dim == 8


def test_scheduler_loop_places_gang_and_mirrors_metrics():
    import time

    from kubernetes_tpu.api import store as st
    from kubernetes_tpu.scheduler.config import SchedulerConfiguration
    from kubernetes_tpu.scheduler.scheduler import Scheduler

    store = st.Store()
    for nd in mk_slices(2, (2, 2, 1)):
        store.create(nd)
    sched = Scheduler(
        store,
        batch_size=32,
        config=SchedulerConfiguration(slice_carveout_policy="require"),
    )
    sched.start()
    try:
        for p in gang("g", 4, "2x2x1"):
            p.spec.scheduling_group_size = 4
            store.create(p)
        assert _wait(
            lambda: all(
                q.spec.node_name for q in store.list("Pod")[0]
            ),
            timeout=60,
        )
        slices_used = {
            store.get("Node", q.spec.node_name).meta.labels[
                api.LABEL_TPU_SLICE
            ]
            for q in store.list("Pod")[0]
        }
        assert len(slices_used) == 1  # the gang landed in ONE slice
        deadline = time.time() + 10
        while (
            sched.metrics.gang_contiguous_placements.total < 1
            and time.time() < deadline
        ):
            time.sleep(0.05)
        assert sched.metrics.slice_carveouts.total >= 1
        assert sched.metrics.gang_contiguous_placements.total >= 1
        assert sched.metrics.slice_carveout_fallbacks.total == 0
    finally:
        sched.stop()


# -- sharded-mesh twin -------------------------------------------------------


@pytest.mark.multichip
@pytest.mark.parametrize("policy", ["prefer", "require"])
def test_sharded_slice_parity(policy):
    import jax

    from kubernetes_tpu.parallel import sharded

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    nodes = mk_slices(2, (2, 2, 2))
    pods = gang("g0", 4, "2x2x1") + gang("g1", 8, "2x2x2") + gang(
        "gbig", 3, "3x3x3"
    )
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    features = assign.features_of(snap, slice_policy=policy)
    n_groups = schema.num_groups(snap)
    single = assign.greedy_assign(snap, features=features, n_groups=n_groups)
    mesh = sharded.make_mesh(8)
    multi = sharded.sharded_greedy_assign(
        snap, mesh, features=features, n_groups=n_groups
    )
    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )
    assert float(single.frag_score) == float(multi.frag_score)
    assert int(single.contiguous_gangs) == int(multi.contiguous_gangs)
    assert int(single.carveout_fallbacks) == int(multi.carveout_fallbacks)
