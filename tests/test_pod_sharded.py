"""Pod-axis-sharded kernels must place/score identically to their
single-shard twins.

The node-axis mesh (test_sharded.py) scales N; these twins scale the
OTHER long axis — wave members in the wavefront, preemptors in the
PostFilter batch kernels — with node tensors replicated.  Every parity
assertion here is exact: bit-identical assignments, reasons, counters,
and dry-run tensors.  Runs on the 8-virtual-device CPU mesh from
conftest.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import assign, preemption, schema
from kubernetes_tpu.parallel import sharded
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod

pytestmark = pytest.mark.multichip


def _workload(seed, n_nodes=24, n_pods=72):
    """Wavefront-shaped batch with every dynamic-coupling family active
    (ports, spread, anti-affinity) so the wave partition, the mini-scan
    corrections, and the serialized fallback all exercise under the pod
    shard too."""
    rng = np.random.default_rng(seed)
    zones = ["z1", "z2", "z3"]
    nodes = [
        make_node(f"n{i}")
        .capacity(
            cpu_milli=int(rng.choice([4000, 8000, 16000])),
            mem=int(rng.choice([8, 16, 32])) * GI,
            pods=110,
        )
        .zone(str(rng.choice(zones)))
        .obj()
        for i in range(n_nodes)
    ]
    pods = []
    for i in range(n_pods):
        pw = make_pod(f"p{i}").req(
            cpu_milli=int(rng.choice([100, 500, 1000])),
            mem=int(rng.choice([128, 512])) * MI,
        ).labels(app=f"a{i % 3}")
        if i % 4 == 0:
            pw.spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": f"a{i % 3}"})
        elif i % 4 == 1:
            pw.pod_anti_affinity({"app": f"a{i % 3}"}, api.LABEL_HOSTNAME)
        elif i % 4 == 2:
            pw.host_port(8000 + (i % 5))
        pods.append(pw.obj())
    return nodes, pods


def _assert_solve_equal(single, multi):
    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )
    np.testing.assert_array_equal(
        np.asarray(single.reasons), np.asarray(multi.reasons)
    )
    np.testing.assert_array_equal(
        np.asarray(single.feasible_counts),
        np.asarray(multi.feasible_counts),
    )
    np.testing.assert_array_equal(
        np.asarray(single.cluster.requested),
        np.asarray(multi.cluster.requested),
    )
    assert int(single.wave_count) == int(multi.wave_count)
    assert int(single.wave_fallbacks) == int(multi.wave_fallbacks)


@pytest.mark.parametrize("seed", range(3))
def test_podsharded_wavefront_matches_scan_and_single_chip(seed):
    """The pod-sharded wavefront must equal BOTH the single-chip
    wavefront (bit-identical, including the fallback counters) and the
    classic scan — the same chain the node-sharded wavefront satisfies,
    on the orthogonal axis."""
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    nodes, pods = _workload(seed)
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    plan = assign.plan_waves(snap)
    scan = assign.greedy_assign(snap)
    single = assign.wavefront_assign(snap, plan.members)
    multi = sharded.podsharded_wavefront_assign(
        snap, plan.members, sharded.make_pod_mesh(8)
    )
    np.testing.assert_array_equal(
        np.asarray(scan.assignment), np.asarray(single.assignment)
    )
    _assert_solve_equal(single, multi)


def test_podsharded_wavefront_pads_indivisible_waves():
    """A hand-built wave width NOT divisible by the mesh size: the
    wrapper pads the member axis with inert -1 columns and placements
    stay identical to the unpadded single-chip plan — the padding is
    exercised, not just the error path."""
    nodes, pods = _workload(5)
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    p = np.asarray(snap.pods.req).shape[0]
    order = np.argsort(
        -np.asarray(snap.pods.priority), kind="stable"
    ).astype(np.int32)
    width = 20  # not a multiple of 8 -> padded to 24
    n_waves = (p + width - 1) // width
    members = np.full((max(8, n_waves), width), -1, np.int32)
    for w in range(n_waves):
        chunk = order[w * width:(w + 1) * width]
        members[w, : len(chunk)] = chunk
    mesh = sharded.make_pod_mesh(8)
    padded = sharded.pad_wave_columns(members, mesh)
    assert padded.shape[1] == 24 and (padded[:, width:] == -1).all()
    single = assign.wavefront_assign(snap, members)
    multi = sharded.podsharded_wavefront_assign(snap, members, mesh)
    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )
    np.testing.assert_array_equal(
        np.asarray(single.reasons), np.asarray(multi.reasons)
    )


def test_podsharded_wavefront_serialized_waves_parity():
    """A coupled contiguous partition forces the serialized-wave
    fallback; the pod shard must fall back identically (the serial path
    runs replicated on every device)."""
    nodes, pods = _workload(7)
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    p = np.asarray(snap.pods.req).shape[0]
    order = np.argsort(
        -np.asarray(snap.pods.priority), kind="stable"
    ).astype(np.int32)
    n_waves = (p + 31) // 32
    members = np.full((max(8, n_waves), 32), -1, np.int32)
    for w in range(n_waves):
        chunk = order[w * 32:(w + 1) * 32]
        members[w, : len(chunk)] = chunk
    single = assign.wavefront_assign(snap, members)
    assert int(single.wave_fallbacks) > 0  # coupling actually fired
    multi = sharded.podsharded_wavefront_assign(
        snap, members, sharded.make_pod_mesh(8)
    )
    _assert_solve_equal(single, multi)


def test_podsharded_wavefront_mesh_sizes():
    nodes, pods = _workload(9, n_nodes=16, n_pods=40)
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    plan = assign.plan_waves(snap)
    want = np.asarray(assign.wavefront_assign(snap, plan.members).assignment)
    for n_dev in (2, 4):
        got = sharded.podsharded_wavefront_assign(
            snap, plan.members, sharded.make_pod_mesh(n_dev)
        )
        np.testing.assert_array_equal(want, np.asarray(got.assignment))


def test_podsharded_wavefront_gang_release_parity():
    """Gang all-or-nothing releases identically under the pod shard:
    the post-pass runs replicated on the gathered assignment."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=2000, mem=4 * GI, pods=4).obj()
        for i in range(8)
    ]
    pods = [
        make_pod(f"g{i}").req(cpu_milli=1500, mem=GI).group("g", size=70).obj()
        for i in range(70)
    ] + [
        make_pod(f"s{i}").req(cpu_milli=100, mem=MI).obj() for i in range(10)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    ng = schema.num_groups(snap)
    plan = assign.plan_waves(snap)
    single = assign.wavefront_assign(snap, plan.members, n_groups=ng)
    assert (np.asarray(single.assignment)[:70] == -1).all()  # gang released
    multi = sharded.podsharded_wavefront_assign(
        snap, plan.members, sharded.make_pod_mesh(8), n_groups=ng
    )
    _assert_solve_equal(single, multi)


def test_podsharded_wavefront_jit_dispatch():
    """The jitted wrapper plans, pads, and dispatches like the eager
    wrapper."""
    nodes, pods = _workload(3, n_nodes=16, n_pods=32)
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    mesh = sharded.make_pod_mesh(8)
    call = sharded.podsharded_wavefront_jit(mesh)
    got = call(snap)
    want = assign.wavefront_assign(snap, assign.plan_waves(snap).members)
    np.testing.assert_array_equal(
        np.asarray(want.assignment), np.asarray(got.assignment)
    )


# -- preemption twins --------------------------------------------------------


def _random_preemption_batch(rng, n=16, k=8, l=3, p=16, r=4):
    """Synthetic but well-formed PostFilter batch: per-(level, node) a
    true eviction-order permutation, eligible prefix lengths within K,
    non-negative victim usage, mixed-sign free rows (overcommitted nodes
    included)."""
    perm = np.empty((l, n, k), np.int32)
    for li in range(l):
        for ni in range(n):
            perm[li, ni] = rng.permutation(k)
    return preemption.PreemptionBatch(
        free=jnp.asarray(
            rng.uniform(-2.0, 4.0, size=(n, r)).astype(np.float32)
        ),
        victim_req=jnp.asarray(
            rng.uniform(0.0, 2.0, size=(n, k, r)).astype(np.float32)
        ),
        perm=jnp.asarray(perm),
        elig_len=jnp.asarray(
            rng.integers(0, k + 1, size=(l, n)).astype(np.int32)
        ),
        viol=jnp.asarray(rng.random((l, n, k)) < 0.3),
        pods_req=jnp.asarray(
            rng.uniform(0.0, 3.0, size=(p, r)).astype(np.float32)
        ),
        pod_level=jnp.asarray(
            rng.integers(0, l, size=(p,)).astype(np.int32)
        ),
    )


@pytest.mark.parametrize("seed", range(3))
def test_sharded_batched_dry_run_parity(seed):
    rng = np.random.default_rng(seed)
    batch = _random_preemption_batch(rng)
    single = preemption.batched_dry_run(batch)
    multi = sharded.sharded_batched_dry_run(
        batch, sharded.make_pod_mesh(8)
    )
    np.testing.assert_array_equal(
        np.asarray(single.feasible), np.asarray(multi.feasible)
    )
    np.testing.assert_array_equal(
        np.asarray(single.min_k), np.asarray(multi.min_k)
    )
    np.testing.assert_array_equal(
        np.asarray(single.viol_k), np.asarray(multi.viol_k)
    )


def test_sharded_batched_dry_run_rejects_indivisible():
    rng = np.random.default_rng(0)
    batch = _random_preemption_batch(rng, p=12)
    with pytest.raises(ValueError, match="not divisible"):
        sharded.sharded_batched_dry_run(batch, sharded.make_pod_mesh(8))


@pytest.mark.parametrize("seed", range(2))
def test_sharded_static_feasible_parity(seed):
    """The static-Filter sweep sharded on the preemptor axis: identical
    bool[P, N] rows, including named-node, taint, and affinity pods."""
    nodes, pods = _workload(seed, n_nodes=16, n_pods=40)
    pods[0] = make_pod("named").req(cpu_milli=100).node_name("n3").obj()
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    single = preemption.run_static_feasible_batch(
        snap.cluster, snap.pods, snap.selectors
    )
    multi = sharded.sharded_static_feasible_batch(
        snap.cluster, snap.pods, snap.selectors, sharded.make_pod_mesh(8)
    )
    np.testing.assert_array_equal(np.asarray(single), np.asarray(multi))
