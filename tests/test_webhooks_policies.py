"""Dynamic admission: webhook callouts + expression policies.

VERDICT r4 missing #8.  Reference:
apiserver/pkg/admission/plugin/webhook (AdmissionReview POSTs,
failurePolicy) and admission/plugin/policy/validating/plugin.go (CEL
over object fields).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.api import admission as adm
from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.webhooks import Expression
from kubernetes_tpu.testing.wrappers import make_pod


class _Hook:
    """In-process webhook endpoint returning a scripted response."""

    def __init__(self, respond):
        hooks = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                review = json.loads(self.rfile.read(n))
                hooks.reviews.append(review)
                body = json.dumps(respond(review)).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.reviews = []
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_mutating_webhook_applies_patch():
    hook = _Hook(lambda review: {
        "allowed": True,
        "patch": {"meta": {"labels": {"injected": "yes"}}},
    })
    try:
        store = st.Store(admission=adm.default_chain())
        store.create(api.MutatingWebhookConfiguration(
            meta=api.ObjectMeta(name="labeler", namespace=""),
            webhooks=[api.Webhook(
                name="labeler.example.com", url=hook.url,
                rules=[api.WebhookRule(kinds=["Pod"])],
            )],
        ))
        created = store.create(make_pod("p").obj())
        assert created.meta.labels.get("injected") == "yes"
        assert hook.reviews and hook.reviews[0]["kind"] == "Pod"
        # non-matching kind is untouched
        store.create(api.Namespace(meta=api.ObjectMeta(name="ns", namespace="")))
        assert all(r["kind"] == "Pod" for r in hook.reviews)
    finally:
        hook.stop()


def test_validating_webhook_denies():
    hook = _Hook(lambda review: {
        "allowed": False,
        "status": {"message": "pods named bad are bad"},
    } if review["object"]["meta"]["name"] == "bad" else {"allowed": True})
    try:
        store = st.Store(admission=adm.default_chain())
        store.create(api.ValidatingWebhookConfiguration(
            meta=api.ObjectMeta(name="gate", namespace=""),
            webhooks=[api.Webhook(
                name="gate.example.com", url=hook.url,
                rules=[api.WebhookRule(kinds=["Pod"], operations=["CREATE"])],
            )],
        ))
        store.create(make_pod("good").obj())
        with pytest.raises(adm.AdmissionError, match="bad are bad"):
            store.create(make_pod("bad").obj())
    finally:
        hook.stop()


def test_failure_policy():
    store = st.Store(admission=adm.default_chain())
    # unreachable endpoint, failurePolicy=Ignore: writes pass
    store.create(api.ValidatingWebhookConfiguration(
        meta=api.ObjectMeta(name="down-ignore", namespace=""),
        webhooks=[api.Webhook(
            name="down", url="http://127.0.0.1:1/nope",
            rules=[api.WebhookRule(kinds=["Pod"])],
            failure_policy="Ignore", timeout_seconds=0.2,
        )],
    ))
    store.create(make_pod("p1").obj())
    # failurePolicy=Fail: writes reject
    import time
    store.create(api.ValidatingWebhookConfiguration(
        meta=api.ObjectMeta(name="down-fail", namespace=""),
        webhooks=[api.Webhook(
            name="down", url="http://127.0.0.1:1/nope",
            rules=[api.WebhookRule(kinds=["Pod"])],
            failure_policy="Fail", timeout_seconds=0.2,
        )],
    ))
    time.sleep(0.6)  # config cache TTL
    with pytest.raises(adm.AdmissionError, match="webhook down"):
        store.create(make_pod("p2").obj())


def test_validating_policy_expressions():
    store = st.Store(admission=adm.default_chain())
    store.create(api.ValidatingAdmissionPolicy(
        meta=api.ObjectMeta(name="naming", namespace=""),
        spec=api.ValidatingAdmissionPolicySpec(
            match=api.WebhookRule(kinds=["Pod"]),
            validations=[
                api.PolicyValidation(
                    expression="object.meta.name.startsWith('web-') || "
                               "object.meta.name.startsWith('sys-')",
                    message="pod names must start with web- or sys-",
                ),
                api.PolicyValidation(
                    expression="object.spec.priority <= 100 && "
                               "object.spec.priority >= 0",
                    message="priority out of range",
                ),
            ],
        ),
    ))
    store.create(make_pod("web-1").obj())
    with pytest.raises(adm.AdmissionError, match="must start with"):
        store.create(make_pod("db-1").obj())
    over = make_pod("sys-1").obj()
    over.spec.priority = 5000
    with pytest.raises(adm.AdmissionError, match="priority out of range"):
        store.create(over)


def test_policy_compile_time_rejection_and_sandbox():
    store = st.Store(admission=adm.default_chain())
    # a bad expression rejects the POLICY write itself
    with pytest.raises(adm.AdmissionError, match="not allowed"):
        store.create(api.ValidatingAdmissionPolicy(
            meta=api.ObjectMeta(name="evil", namespace=""),
            spec=api.ValidatingAdmissionPolicySpec(
                validations=[api.PolicyValidation(
                    expression="__import__('os').system('true')")],
            ),
        ))
    # the evaluator cannot escape the wire document
    e = Expression("object.meta.name == 'x'")
    with pytest.raises(adm.AdmissionError):
        Expression("object.__class__")
    # CEL-isms: has(), size(), negation, membership
    env_obj = {"meta": {"name": "x", "labels": {"a": "1"}}, "spec": {}}
    from kubernetes_tpu.api.webhooks import _Doc

    env = {"object": _Doc(env_obj), "true": True, "false": False}
    assert Expression("has(object.meta, 'labels')").evaluate(env)
    assert not Expression("has(object.spec, 'priority')").evaluate(env)
    assert Expression("size(object.meta.labels) == 1").evaluate(env)
    assert Expression("!(object.meta.name == 'y')").evaluate(env)
    assert Expression("object.meta.labels['a'] == '1'").evaluate(env)
