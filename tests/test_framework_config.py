"""Scheduler framework seam: profiles, config validation, extension
points (VERDICT next #7 — done = two profiles with different score
weights coexist in one Scheduler; config validation tests).

Reference: framework/interface.go:330-666, profile/profile.go:46,
apis/config/types.go:37-100.
"""

import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.ops.scores import ScoreConfig
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.config import (
    ProfileConfig,
    SchedulerConfiguration,
)
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _mk_scheduler(store, **kw):
    s = Scheduler(store, **kw)
    s.informers.informer("Node").start()
    s.informers.informer("Pod").start()
    assert s.informers.wait_for_sync(10)
    return s


# -- config validation ------------------------------------------------------


def test_validation_rejects_duplicate_profiles():
    cfg = SchedulerConfiguration(
        profiles=[ProfileConfig("x"), ProfileConfig("x")]
    )
    with pytest.raises(ValueError, match="duplicate"):
        cfg.validate()


def test_validation_rejects_negative_weight():
    cfg = SchedulerConfiguration(
        profiles=[ProfileConfig(score_config=ScoreConfig(taint_weight=-1))]
    )
    with pytest.raises(ValueError, match="taint_weight"):
        cfg.validate()


def test_validation_rejects_unknown_disable():
    cfg = SchedulerConfiguration(
        profiles=[ProfileConfig(disabled_score_plugins=("NodePorts",))]
    )
    with pytest.raises(ValueError, match="non-disableable"):
        cfg.validate()


def test_validation_rejects_bad_backoff_and_strategy():
    with pytest.raises(ValueError, match="backoff"):
        SchedulerConfiguration(
            pod_initial_backoff_seconds=5, pod_max_backoff_seconds=1
        ).validate()
    with pytest.raises(ValueError, match="fit_strategy"):
        SchedulerConfiguration(
            profiles=[ProfileConfig(score_config=ScoreConfig(fit_strategy="Weird"))]
        ).validate()


def test_disabled_score_plugin_zeroes_weight():
    p = ProfileConfig(disabled_score_plugins=("TaintToleration",))
    assert p.effective_score_config().taint_weight == 0.0
    assert p.score_config.taint_weight != 0.0  # original untouched


# -- profiles ---------------------------------------------------------------


def test_two_profiles_different_weights_coexist():
    """Pods select their profile via spec.scheduler_name; the packing
    profile (MostAllocated) stacks one node while the default
    (LeastAllocated) spreads — both against ONE shared cluster state."""
    store = st.Store()
    for i in range(4):
        store.create(
            make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=20).obj()
        )
    cfg = SchedulerConfiguration(
        profiles=[
            ProfileConfig("default-scheduler"),
            ProfileConfig(
                "bin-packer",
                score_config=ScoreConfig(fit_strategy="MostAllocated"),
            ),
        ]
    )
    sched = _mk_scheduler(store, config=cfg)
    try:
        # packing pods name the second profile
        for i in range(4):
            p = make_pod(f"pack-{i}").req(cpu_milli=500, mem=256 * MI).obj()
            p.spec.scheduler_name = "bin-packer"
            store.create(p)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            pods, _ = store.list("Pod")
            if all(p.spec.node_name for p in pods):
                break
        pods, _ = store.list("Pod")
        assert all(p.spec.node_name for p in pods)
        packed_nodes = {p.spec.node_name for p in pods}
        assert len(packed_nodes) == 1, f"MostAllocated spread out: {packed_nodes}"

        # spreading pods use the default profile
        for i in range(4):
            store.create(make_pod(f"spread-{i}").req(cpu_milli=500, mem=256 * MI).obj())
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            pods, _ = store.list("Pod")
            if all(p.spec.node_name for p in pods):
                break
        spread_nodes = {
            p.spec.node_name
            for p in store.list("Pod")[0]
            if p.meta.name.startswith("spread")
        }
        assert len(spread_nodes) >= 3, f"LeastAllocated packed: {spread_nodes}"
    finally:
        sched.stop()


def test_unknown_scheduler_name_ignored():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=4000).obj())
    sched = _mk_scheduler(store)
    try:
        p = make_pod("other").req(cpu_milli=100).obj()
        p.spec.scheduler_name = "some-other-scheduler"
        store.create(p)
        sched.schedule_batch(timeout=0.5)
        assert not store.get("Pod", "other").spec.node_name
        assert sched.queue.pending_count() == 0  # never enqueued
    finally:
        sched.stop()


# -- extension points -------------------------------------------------------


def test_pre_enqueue_plugin_gates_pod():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=4000).obj())
    sched = _mk_scheduler(store)
    sched.profiles.default.register(
        "pre_enqueue",
        lambda pod: "quota exceeded" if pod.meta.labels.get("blocked") else None,
    )
    try:
        store.create(make_pod("ok").req(cpu_milli=100).obj())
        store.create(make_pod("held").req(cpu_milli=100).label("blocked", "1").obj())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            if store.get("Pod", "ok").spec.node_name:
                break
        assert store.get("Pod", "ok").spec.node_name
        assert not store.get("Pod", "held").spec.node_name
    finally:
        sched.stop()


def test_pre_bind_failure_aborts_and_requeues():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=4000).obj())
    sched = _mk_scheduler(store)
    calls = {"n": 0}

    def flaky_prebind(pod, node):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("volume attach failed")

    sched.profiles.default.register("pre_bind", flaky_prebind)
    try:
        store.create(make_pod("p").req(cpu_milli=100).obj())
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            if store.get("Pod", "p").spec.node_name:
                break
        assert store.get("Pod", "p").spec.node_name  # retried and bound
        assert calls["n"] >= 2
        assert sched.cache.assumed_count() <= 1
    finally:
        sched.stop()


def test_post_bind_and_filter_result_hooks():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=4000).obj())
    store.create(make_node("n1").capacity(cpu_milli=4000).obj())
    sched = _mk_scheduler(store)
    seen = []
    sched.profiles.default.register(
        "post_bind", lambda pod, node: seen.append((pod.meta.name, node))
    )
    # filter_result veto: force everything onto n1 (extender-style override)
    sched.profiles.default.register("filter_result", lambda pod, node: "n1")
    try:
        store.create(make_pod("p").req(cpu_milli=100).obj())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            if store.get("Pod", "p").spec.node_name:
                break
        assert store.get("Pod", "p").spec.node_name == "n1"
        assert seen == [("p", "n1")]
    finally:
        sched.stop()


def test_multi_profile_no_double_booking():
    """Groups solve sequentially with assume between them: two profiles'
    slices of one batch must not overcommit a node (review finding)."""
    store = st.Store()
    # one node fits exactly 4 x 1000m
    store.create(make_node("only").capacity(cpu_milli=4000, mem=8 * GI, pods=20).obj())
    cfg = SchedulerConfiguration(
        profiles=[ProfileConfig("default-scheduler"), ProfileConfig("p2")]
    )
    sched = _mk_scheduler(store, config=cfg)
    try:
        for i in range(4):
            store.create(make_pod(f"a{i}").req(cpu_milli=1000).obj())
        for i in range(4):
            p = make_pod(f"b{i}").req(cpu_milli=1000).obj()
            p.spec.scheduler_name = "p2"
            store.create(p)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            pods, _ = store.list("Pod")
            if sum(1 for p in pods if p.spec.node_name) >= 4:
                break
        bound = [p for p in store.list("Pod")[0] if p.spec.node_name]
        assert len(bound) == 4, f"{len(bound)} bound on a 4-pod node"
        used = sum(p.resource_requests()["cpu"] for p in bound)
        assert used <= 4000, f"overcommitted: {used}m"
    finally:
        sched.stop()
