"""Gang all-or-nothing on the GREEDY route (constraint-carrying gangs).

Round-2 verdict: gangs with spread/interpod/port constraints routed to
greedy, which had no group handling — partial placement with no error.
Now greedy_assign carries the same post-pass as the auction (release every
placement of a group with an unplaced member), and the queue stages gangs
until whole (scheduling_group_size) and drains them atomically.

Reference semantics modelled: the out-of-tree coscheduling plugin's
PodGroup minMember contract (no in-tree counterpart; the closest in-tree
machinery is Permit/WaitOnPermit, framework/runtime/waiting_pods_map.go).
"""

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
from kubernetes_tpu.ops import assign, auction, schema
from kubernetes_tpu.scheduler.queue import SchedulingQueue
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


def _solve_greedy(nodes, pods):
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    n_groups = auction.num_groups(snap)
    r = assign.greedy_assign(snap, n_groups=n_groups)
    return np.asarray(r.assignment)[: len(pods)], r, snap


def test_gang_antiaffinity_all_or_nothing():
    """Gang of 3 self-anti-affine pods, 2 nodes: nobody places, and the
    two provisional placements are fully released."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI).obj()
        for i in range(2)
    ]
    pods = [
        make_pod(f"g-{i}")
        .req(cpu_milli=100)
        .label("app", "x")
        .pod_anti_affinity({"app": "x"})
        .group("g")
        .obj()
        for i in range(3)
    ]
    a, r, snap = _solve_greedy(nodes, pods)
    assert (a < 0).all(), a
    np.testing.assert_allclose(np.asarray(r.cluster.requested), 0.0, atol=1e-6)


def test_gang_spread_all_or_nothing():
    """Gang of 4 with maxSkew=1 zone spread over 2 zones but capacity for
    only 1 pod in z1: spread admits 2-per-zone, capacity blocks, gang must
    release entirely."""
    nodes = [
        make_node("n0").capacity(cpu_milli=8000, pods=110).zone("z0").obj(),
        make_node("n1").capacity(cpu_milli=1000, pods=110).zone("z1").obj(),
    ]
    pods = [
        make_pod(f"g-{i}")
        .req(cpu_milli=1000)
        .label("app", "s")
        .spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": "s"})
        .group("g")
        .obj()
        for i in range(4)
    ]
    a, r, snap = _solve_greedy(nodes, pods)
    assert (a < 0).all(), a


def test_solvable_gang_with_spread_places_whole():
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, pods=110).zone(f"z{i % 2}").obj()
        for i in range(4)
    ]
    pods = [
        make_pod(f"g-{i}")
        .req(cpu_milli=1000)
        .label("app", "s")
        .spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": "s"})
        .group("g")
        .obj()
        for i in range(4)
    ]
    a, r, snap = _solve_greedy(nodes, pods)
    assert (a >= 0).all(), a
    # spread held: zone counts differ by at most maxSkew
    zones = [0 if int(i) < 2 else 1 for i in a]  # n0,n1=z0,z1 alternating
    topo = np.asarray(snap.cluster.topo_ids)


def test_mixed_gangs_release_only_failed_group():
    """Unsolvable anti-affine gang + solvable plain gang in one batch:
    the failed group releases, the good one binds, resources match."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI).obj()
        for i in range(2)
    ]
    pods = (
        [
            make_pod(f"bad-{i}")
            .req(cpu_milli=100)
            .label("app", "bad")
            .pod_anti_affinity({"app": "bad"})
            .group("bad")
            .obj()
            for i in range(3)
        ]
        + [
            make_pod(f"ok-{i}").req(cpu_milli=500).group("ok").obj()
            for i in range(4)
        ]
    )
    a, r, snap = _solve_greedy(nodes, pods)
    assert (a[:3] < 0).all(), a
    assert (a[3:] >= 0).all(), a
    req = np.asarray(snap.pods.req)[: len(pods)]
    used = np.zeros_like(np.asarray(r.cluster.requested))
    np.add.at(used, a[a >= 0], req[a >= 0])
    np.testing.assert_allclose(np.asarray(r.cluster.requested), used, atol=1e-5)


def test_router_keeps_gang_semantics_on_greedy_route():
    """TPUBatchScheduler end-to-end: a constrained gang (spread → greedy
    route) that cannot fully place returns None for every member."""
    sched = TPUBatchScheduler()
    nodes = [
        make_node("n0").capacity(cpu_milli=8000, pods=110).zone("z0").obj(),
        make_node("n1").capacity(cpu_milli=1000, pods=110).zone("z1").obj(),
    ]
    pods = [
        make_pod(f"g-{i}")
        .req(cpu_milli=1000)
        .label("app", "s")
        .spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": "s"})
        .group("g")
        .obj()
        for i in range(4)
    ]
    placements = sched.schedule(nodes, pods)
    assert placements == [None] * 4, placements


def test_queue_stages_gang_until_whole():
    q = SchedulingQueue()
    members = [
        make_pod(f"g-{i}").group("g", size=3).obj() for i in range(3)
    ]
    q.add(members[0])
    q.add(members[1])
    assert q.stats()["gang_staged"] == 2
    assert q.stats()["active"] == 0
    q.add(members[2])  # completes the gang → all released
    assert q.stats()["gang_staged"] == 0
    batch = q.pop_batch(10, timeout=0.1)
    assert len(batch) == 3


def test_pop_batch_drains_gang_atomically():
    """max_n smaller than the gang: the batch stretches to keep the gang
    whole (plus independently queued pods may fill earlier slots)."""
    q = SchedulingQueue()
    for i in range(4):
        q.add(make_pod(f"g-{i}").group("g", size=4).obj())
    batch = q.pop_batch(2, timeout=0.1)
    names = sorted(i.pod.meta.name for i in batch)
    assert names == ["g-0", "g-1", "g-2", "g-3"], names


def test_gang_member_delete_while_staged():
    q = SchedulingQueue()
    a = make_pod("g-0").group("g", size=2).obj()
    q.add(a)
    q.delete(a)
    assert q.stats()["gang_staged"] == 0
    # remaining member arrives; still only 1 of 2 → staged
    q.add(make_pod("g-1").group("g", size=2).obj())
    assert q.stats()["gang_staged"] == 1


def test_gated_gang_members_stage_on_gate_clear():
    """Members arriving gated must still stage when their gates clear —
    a cleared member alone must not reach a solve (review finding r3)."""
    q = SchedulingQueue()
    gated = [
        make_pod(f"g-{i}").group("g", size=3).obj() for i in range(3)
    ]
    for p in gated:
        p.spec.scheduling_gates = ["wait"]
        q.add(p)
    assert q.stats()["gated"] == 3
    # clear gates one at a time: first two stage, third releases all
    for i, p in enumerate(gated):
        p2 = make_pod(f"g-{i}").group("g", size=3).obj()
        q.update(p2)
        if i < 2:
            assert q.stats()["gang_staged"] == i + 1
            assert q.stats()["active"] == 0
    batch = q.pop_batch(10, timeout=0.1)
    assert len(batch) == 3


def test_member_without_declared_size_does_not_release_early():
    """One member declaring the size is enough; a sizeless member must
    not bypass staging (review finding: size read per-arriving-pod)."""
    q = SchedulingQueue()
    q.add(make_pod("g-0").group("g", size=3).obj())
    q.add(make_pod("g-1").group("g").obj())  # no size declared
    assert q.stats()["gang_staged"] == 2
    assert q.stats()["active"] == 0
    q.add(make_pod("g-2").group("g").obj())
    batch = q.pop_batch(10, timeout=0.1)
    assert len(batch) == 3


def test_update_group_change_reconciles_membership():
    """Moving a staged pod to another group must retract the old
    registration so the old group's whole-count is not inflated."""
    q = SchedulingQueue()
    q.add(make_pod("p").group("a", size=2).obj())
    assert q.stats()["gang_staged"] == 1
    q.update(make_pod("p").group("b", size=2).obj())
    # still staged, but now under group b
    assert q.stats()["gang_staged"] == 1
    # group a's count must be clean: a fresh 2-gang in group a needs
    # BOTH members before releasing
    q.add(make_pod("a-0").group("a", size=2).obj())
    assert q.stats()["active"] == 0
    q.add(make_pod("a-1").group("a", size=2).obj())
    assert q.pop_batch(10, timeout=0.1) != []


def test_pop_batch_pulls_gang_members_from_backoff():
    """A gang split across active/backoff tiers is drained whole, not
    solved partially (review finding: pull skipped parked tiers)."""
    q = SchedulingQueue(backoff_base=0.01, backoff_max=0.02)
    pods = [make_pod(f"g-{i}").group("g", size=3).obj() for i in range(3)]
    for p in pods:
        q.add(p)
    batch = q.pop_batch(10, timeout=0.1)
    assert len(batch) == 3
    # two members go to backoff (transient failure), one parks unsched
    q.requeue_backoff(batch[0])
    q.requeue_backoff(batch[1])
    q.add_unschedulable(batch[2])
    q.move_all_to_active_or_backoff("NodeAdd")
    # whichever member becomes active first must drag the others along
    got = q.pop_batch(1, timeout=1.0)
    assert len(got) == 3, [i.pod.meta.name for i in got]


def test_update_adds_group_to_active_pod_without_stranding():
    """An active pod gaining a group via update() must be registered and
    remain poppable (review finding: stranded in tier active forever)."""
    q = SchedulingQueue()
    q.add(make_pod("p").obj())
    q.update(make_pod("p").group("g").obj())
    batch = q.pop_batch(10, timeout=0.2)
    assert [i.pod.meta.name for i in batch] == ["p"]


def test_delete_below_declared_size_restages_members():
    """Deleting a member of a whole, released gang drops it below its
    declared size: remaining queued members must re-stage, not solve as a
    partial gang."""
    q = SchedulingQueue()
    pods = [make_pod(f"g-{i}").group("g", size=3).obj() for i in range(3)]
    for p in pods:
        q.add(p)
    assert q.stats()["active"] == 3
    q.delete(pods[2])
    assert q.stats()["gang_staged"] == 2
    assert q.pop_batch(10, timeout=0.1) == []
    # replacement arrives: gang whole again
    q.add(make_pod("g-2b").group("g", size=3).obj())
    assert len(q.pop_batch(10, timeout=0.1)) == 3


def test_gang_completed_by_update_releases_staged():
    """A pod can complete a gang by JOINING via update(); the staged
    members must wake without waiting for an unrelated event (advisor
    finding r3: the release loop only ran from add)."""
    q = SchedulingQueue()
    q.add(make_pod("g-0").group("g", size=2).obj())
    assert q.stats()["gang_staged"] == 1
    # p1 arrives ungrouped (active), then an update joins it to the gang
    loner = make_pod("p-1").obj()
    q.add(loner)
    q.update(make_pod("p-1").group("g", size=2).obj())
    assert q.stats()["gang_staged"] == 0
    batch = q.pop_batch(10, timeout=0.2)
    assert sorted(i.pod.meta.name for i in batch) == ["g-0", "p-1"]


def test_gang_size_declared_via_update_takes_effect():
    """A same-group update that newly declares scheduling_group_size must
    be recorded — and a now-satisfied size releases the staging."""
    q = SchedulingQueue()
    # both members arrive with group but NO declared size -> active
    q.add(make_pod("g-0").group("g").obj())
    q.add(make_pod("g-1").group("g").obj())
    assert q.stats()["gang_staged"] == 0
    # update declares size=3: gang is short; nothing staged yet (queued
    # members stay queued until a delete/restage path runs), but the size
    # must be recorded so the NEXT member completes or stages correctly
    q.update(make_pod("g-0").group("g", size=3).obj())
    assert q._group_size["default/g"] == 3  # gangs key by namespace/group
    q.add(make_pod("g-2").group("g", size=3).obj())
    # gang whole: the new member must not strand in staging
    assert q.stats()["gang_staged"] == 0
    batch = q.pop_batch(10, timeout=0.2)
    assert len(batch) == 3


def test_gang_size_raised_via_update_restages_active():
    """Declaring (or raising) the size via update on a gang whose queued
    members no longer satisfy it must RE-STAGE them — a partial gang must
    never reach a solve (review finding r4)."""
    q = SchedulingQueue()
    for i in range(3):
        q.add(make_pod(f"g-{i}").group("g").obj())  # no size -> active
    assert q.stats()["gang_staged"] == 0
    q.update(make_pod("g-0").group("g", size=5).obj())
    assert q.stats()["gang_staged"] == 3
    batch = q.pop_batch(10, timeout=0.1)
    assert batch == []
    # the remaining two arrive: gang whole, everyone released
    q.add(make_pod("g-3").group("g", size=5).obj())
    q.add(make_pod("g-4").group("g", size=5).obj())
    batch = q.pop_batch(10, timeout=0.2)
    assert len(batch) == 5


def test_same_named_gangs_in_different_namespaces_are_distinct():
    """Gangs key by namespace/group: one namespace's INFLIGHT member must
    never park another namespace's whole gang in pop_batch's gang pull
    (the queue half of the r4 per-namespace quorum fix; the sharded
    store's per-shard fan-out skews cross-namespace pop timing enough to
    hit this deterministically)."""
    q = SchedulingQueue()
    a0 = make_pod("w0", namespace="team-a").group("workers").obj()
    q.add(a0)
    # team-a's member pops alone (its own gang, no declared size) ...
    batch = q.pop_batch(10, timeout=0.2)
    assert [f"{i.pod.meta.namespace}/{i.pod.meta.name}" for i in batch] == [
        "team-a/w0"
    ]
    # ... and stays inflight (parked at Permit, say) while team-b's
    # same-NAMED gang arrives whole: it must pop immediately
    q.add(make_pod("w0", namespace="team-b").group("workers").obj())
    q.add(make_pod("w1", namespace="team-b").group("workers").obj())
    batch = q.pop_batch(10, timeout=0.5)
    assert sorted(
        f"{i.pod.meta.namespace}/{i.pod.meta.name}" for i in batch
    ) == ["team-b/w0", "team-b/w1"]
