"""Fleet-scale serving plane regressions (fast tier-1 surface).

Covers the serving-path hardening contracts: the per-watcher HTTP
write deadline (a stalled TCP client trips Expired and frees the
handler thread — it never pins it), read-replica API servers over one
shared store (kill/restart leaves no watcher wedged), the multiplexed
watch client's failover, and the serving-plane gauge mirror the
scheduler reads each cycle.  The randomized chaos-grade versions live
in tests/test_chaos.py (SERVING_SEEDS, `make chaos-serving`).
"""

import socket
import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api.server import APIServer, APIServerReplicaSet
from kubernetes_tpu.client.rest import RestClient
from kubernetes_tpu.client.watchmux import HttpWatchMux
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


@pytest.fixture(autouse=True)
def _disarmed():
    yield
    faults.disarm()


def _wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


# -- per-watcher write deadline ----------------------------------------------


def test_watch_write_deadline_expires_stalled_client():
    """A watch client that stops READING (socket deliberately unread,
    tiny buffers) must not pin the handler thread: the per-watcher
    write deadline trips, the stall is counted, the watch expires
    (watch_expired_total) and the handler thread is freed."""
    store = st.Store()
    srv = APIServer(
        store, watch_write_deadline=1.0, watch_sndbuf=4096
    ).start()
    try:
        expired0 = store.watch_stats()["watch_expired_total"]
        host, port = srv.httpd.server_address[:2]
        sock = socket.create_connection((host, port), timeout=5)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        sock.sendall(
            b"GET /api/v1/watch/Pod HTTP/1.1\r\n"
            b"Host: x\r\nAccept: application/json\r\n\r\n"
        )
        # the stream is live: the handler thread is inside _watch now
        assert _wait_for(lambda: srv.httpd.active_handlers() >= 1)
        # flood events the client never reads — kernel buffers fill,
        # the next frame write blocks, and the 1s deadline trips
        for i in range(400):
            store.create(make_pod(f"flood-{i}").req(
                cpu_milli=100, mem=8 * MI
            ).obj())
        assert _wait_for(
            lambda: srv.httpd.watch_write_stalls_total >= 1, timeout=30
        ), "write deadline never tripped"
        assert srv.watch_write_stalls_total >= 1
        # the watch expired (the consumer would relist on reconnect)
        assert _wait_for(
            lambda: store.watch_stats()["watch_expired_total"] > expired0
        )
        # and the handler thread is FREED, not pinned by the dead client
        assert _wait_for(
            lambda: srv.httpd.active_handlers() == 0, timeout=10
        ), "handler thread still pinned by the stalled client"
        sock.close()
        # the store-side registration is gone too
        assert _wait_for(
            lambda: sum(len(v) for v in store._watchers.values()) == 0
        )
    finally:
        srv.stop()


def test_watch_survives_without_deadline_pressure():
    """Control case: a NORMALLY consuming client under the same tiny
    deadline never trips it — the deadline only fires on stalls."""
    store = st.Store()
    srv = APIServer(store, watch_write_deadline=1.0).start()
    try:
        client = RestClient(srv.url)
        store.create(make_pod("p0").obj())
        gen = client.watch("Pod", from_rv=0)  # ring replay delivers p0
        typ, obj, rv = next(gen)
        assert (typ, obj.meta.name) == ("ADDED", "p0")
        time.sleep(1.5)  # a few bookmark intervals pass
        store.create(make_pod("p1").obj())
        typ, obj, rv = next(gen)
        assert obj.meta.name == "p1"
        gen.close()
        assert srv.watch_write_stalls_total == 0
    finally:
        srv.stop()


# -- read-replica API servers ------------------------------------------------


def test_replica_set_shares_store_and_gate():
    store = st.Store()
    plane = APIServerReplicaSet(store, replicas=3)
    try:
        urls = plane.urls()
        assert len(urls) == 3 and len(set(urls)) == 3
        # one shared store: a write through any replica is read from all
        RestClient(urls[0]).create(make_pod("p").obj())
        for u in urls:
            assert RestClient(u).get("Pod", "p").meta.name == "p"
        # one shared APF gate across replicas
        handlers = {s.httpd.RequestHandlerClass.apf for s in plane.servers()}
        assert len(handlers) == 1
        # the store back-reference the scheduler mirror derefs
        assert store.serving_plane() is plane
    finally:
        plane.stop()


def test_replica_kill_restart_leaves_no_watcher_wedged():
    """kill() severs a replica's live connections like a process death:
    a blocking watch client on the dead replica unblocks promptly
    (Expired/connection error — not a hang), no handler thread stays
    pinned, and a restarted instance serves fresh watches."""
    import threading

    store = st.Store()
    plane = APIServerReplicaSet(store, replicas=2)
    try:
        dead_url = plane.urls()[0]
        outcome = []

        def consume():
            client = RestClient(dead_url, timeout=5)
            try:
                for _ in client.watch("Pod"):
                    pass
                outcome.append("ended")
            except Exception as e:  # Expired or a connection error
                outcome.append(type(e).__name__)

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        assert _wait_for(lambda: plane.active_handlers() >= 1)
        plane.kill(0)
        t.join(timeout=10)
        assert not t.is_alive(), "watch client wedged after replica kill"
        assert outcome, "consumer never returned"
        assert _wait_for(lambda: plane.active_handlers() == 0)
        assert plane.serving_stats()["replica_failovers_total"] == 1
        # the fresh instance serves the same shared store
        srv = plane.restart(0)
        store.create(make_pod("after").obj())
        assert RestClient(srv.url).get("Pod", "after").meta.name == "after"
        gen = RestClient(srv.url).watch("Pod", from_rv=0)
        typ, obj, rv = next(gen)
        gen.close()
        assert typ == "ADDED"
    finally:
        plane.stop()


def test_mux_informers_failover_across_replica_kill():
    """The multiplexed watch client: informers spread over the replica
    set fail over on a kill, keep delivering (rv-monotonic per shard
    segment), and none ends up wedged."""
    store = st.Store()
    plane = APIServerReplicaSet(store, replicas=2)
    mux = HttpWatchMux(plane.urls(), threads=2)
    try:
        infs = [mux.add_informer("Pod") for _ in range(8)]
        mux.start()
        assert _wait_for(lambda: all(i.synced for i in infs))
        cli = RestClient(plane.urls()[0])
        for i in range(10):
            cli.create(make_pod(f"a-{i}").obj())
        assert _wait_for(
            lambda: all(len(i.cache) == 10 for i in infs), timeout=15
        )
        plane.kill(0)
        cli = RestClient(plane.urls()[0])  # the survivor
        for i in range(10, 20):
            cli.create(make_pod(f"a-{i}").obj())
        assert _wait_for(
            lambda: all(len(i.cache) == 20 for i in infs), timeout=20
        ), "informer wedged after replica kill"
        assert sum(i.failovers for i in infs) >= 1
        assert mux.violations() == []
    finally:
        mux.stop()
        plane.stop()


# -- the scheduler's serving-plane mirror ------------------------------------


def test_note_scheduler_drives_adaptive_gate():
    store = st.Store()
    plane = APIServerReplicaSet(store, replicas=1, recover_after=2)
    try:
        full = plane.apf.seats_current()
        assert plane.note_scheduler(2) == 2
        stats = plane.serving_stats()
        assert stats["apf_seats_current"] < full
        # hysteresis: two calm cycles per step down
        assert plane.note_scheduler(0) == 2
        assert plane.note_scheduler(0) == 1
        assert plane.note_scheduler(0) == 1
        assert plane.note_scheduler(0) == 0
        assert plane.serving_stats()["apf_seats_current"] == full
    finally:
        plane.stop()


def test_scheduler_cycle_mirrors_serving_gauges():
    """A real scheduler cycle dereferences store.serving_plane, feeds
    the adaptive controller, and mirrors the four serving gauges into
    its Registry."""
    store = st.Store()
    plane = APIServerReplicaSet(store, replicas=2)
    sched = None
    try:
        store.create(
            make_node("n0").capacity(
                cpu_milli=4000, mem=8 * GI, pods=10
            ).obj()
        )
        store.create(make_pod("p0").req(cpu_milli=100, mem=8 * MI).obj())
        sched = Scheduler(store)
        sched.informers.informer("Node").start()
        sched.informers.informer("Pod").start()
        assert sched.informers.wait_for_sync(10)
        plane.kill(1)  # give replica_failovers_total something to show
        sched.schedule_batch(timeout=2)
        assert sched.metrics.apf_seats_current.get() == float(
            plane.apf.seats_current()
        )
        assert sched.metrics.replica_failovers_total.get() == 1.0
        assert sched.metrics.server_watch_write_stalls_total.get() == 0.0
    finally:
        if sched is not None:
            sched.stop()
        plane.stop()
