"""Degraded-mode hardening of the solve→assume→bind pipeline.

Fast (tier-1) regression coverage for the fault-injection registry
(testing/faults.py) and the hardening it drives: the device-solve
circuit breaker + host fallback, binder supervision (watchdog restart,
poison-wave splitting), the CRC'd crash-safe journal, duplicate-assume
containment, cycle salvage, and the watch overflow → Expired → relist →
resume contract.  The randomized seeded schedules live in
tests/test_chaos.py (mark: chaos).
"""

import threading
import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.models.batch_scheduler import (
    SolveCircuitBreaker,
    TPUBatchScheduler,
)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.config import SchedulerConfiguration
from kubernetes_tpu.scheduler.queue import QueuedPodInfo, pod_key
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


def _mk_scheduler(store, **kw):
    s = Scheduler(store, **kw)
    s.informers.informer("Node").start()
    s.informers.informer("Pod").start()
    assert s.informers.wait_for_sync(10)
    return s


@pytest.fixture(autouse=True)
def _disarmed():
    yield
    faults.disarm()


# -- the registry itself ----------------------------------------------------


def test_disarmed_fire_is_noop():
    assert faults.fire("batch.solve") is None  # no registry: no effect


def test_unknown_point_rejected():
    reg = faults.FaultRegistry()
    with pytest.raises(ValueError):
        reg.fail("no.such.point")


def test_fail_n_counts_down_then_stops():
    reg = faults.FaultRegistry()
    reg.fail("batch.solve", n=2)
    with faults.armed(reg):
        for _ in range(2):
            with pytest.raises(faults.FaultInjected):
                faults.fire("batch.solve")
        assert faults.fire("batch.solve") is None  # schedule drained
    assert reg.fired["batch.solve"] == 2
    assert reg.pending()["batch.solve"] == 0


def test_probabilistic_schedule_is_seed_deterministic():
    def run(seed):
        reg = faults.FaultRegistry(seed=seed)
        reg.fail("watch.offer", n=-1, probability=0.5)
        hits = []
        for _ in range(32):
            try:
                reg.fire("watch.offer")
                hits.append(0)
            except faults.FaultInjected:
                hits.append(1)
        return hits

    assert run(7) == run(7)
    assert run(7) != run(8)  # different seed, different plan


def test_armed_context_disarms_on_exit():
    reg = faults.FaultRegistry()
    reg.fail("batch.solve", n=1)
    with faults.armed(reg):
        pass
    assert faults.fire("batch.solve") is None


def test_delay_composes_with_failure():
    reg = faults.FaultRegistry()
    reg.delay("batch.solve", seconds=0.02, n=1)
    reg.fail("batch.solve", n=1)
    t0 = time.monotonic()
    with faults.armed(reg), pytest.raises(faults.FaultInjected):
        faults.fire("batch.solve")
    assert time.monotonic() - t0 >= 0.02


# -- crash-safe journal (CRC path) ------------------------------------------


def test_journal_crc_detects_value_corruption(tmp_path):
    """A corrupted record that still parses as JSON (a flipped value,
    stale CRC) must be caught by the CRC check, skipped, and counted."""
    path = str(tmp_path / "j.jsonl")
    s1 = st.Store(journal_path=path, shards=1)
    s1.create(make_pod("a").req(cpu_milli=100).obj())
    s1.create(make_pod("b").req(cpu_milli=100).obj())
    s1.create(make_pod("c").req(cpu_milli=100).obj())
    lines = open(path, "rb").read().splitlines(keepends=True)
    # flip the payload of the middle record without breaking JSON
    lines[1] = lines[1].replace(b'"name": "b"', b'"name": "x"')
    with open(path, "wb") as f:
        f.writelines(lines)
    s2 = st.Store(journal_path=path, shards=1)
    names = {p.meta.name for p in s2.list("Pod")[0]}
    assert names == {"a", "c"}, "CRC mismatch record was not skipped"
    assert s2.journal_recovered_records == 1
    assert s2.journal_tail_truncations == 0


def test_journal_torn_tail_truncates_and_counts(tmp_path):
    path = str(tmp_path / "j.jsonl")
    s1 = st.Store(journal_path=path, shards=1)
    s1.create(make_pod("a").obj())
    s1.create(make_pod("b").obj())
    with open(path, "a") as f:
        f.write('{"op": "ADDED", "rv": 99, "kind": "Pod", "ke')  # torn
    s2 = st.Store(journal_path=path, shards=1)
    assert {p.meta.name for p in s2.list("Pod")[0]} == {"a", "b"}
    assert s2.journal_recovered_records == 1
    assert s2.journal_tail_truncations == 1


def test_injected_torn_write_is_contained_and_recovered(tmp_path):
    """A torn append (crash mid-write) degrades durability for that
    record only: the store keeps serving, and replay truncates the torn
    tail back to the last good record."""
    path = str(tmp_path / "j.jsonl")
    store = st.Store(journal_path=path, shards=1)
    store.create(make_pod("durable").obj())
    reg = faults.FaultRegistry().torn_write("store.journal.append", n=1)
    with faults.armed(reg):
        store.create(make_pod("torn").obj())  # append tears; API write OK
    assert store.journal_write_errors == 1
    assert store.get("Pod", "torn") is not None  # in-memory commit held
    store.create(make_pod("after").obj())  # appends continue
    s2 = st.Store(journal_path=path, shards=1)
    names = {p.meta.name for p in s2.list("Pod")[0]}
    # the torn record was never durable; records around it replay
    assert "durable" in names
    assert "torn" not in names
    assert s2.journal_recovered_records >= 1


def test_injected_fsync_failure_contained(tmp_path):
    path = str(tmp_path / "j.jsonl")
    store = st.Store(journal_path=path, shards=1)
    reg = faults.FaultRegistry().fail("store.journal.fsync", n=1)
    with faults.armed(reg):
        store.create(make_pod("a").obj())
    assert store.journal_write_errors == 1
    store.create(make_pod("b").obj())
    assert {p.meta.name for p in st.Store(journal_path=path, shards=1).list("Pod")[0]} >= {"b"}


def test_compaction_output_replays_with_crc(tmp_path):
    path = str(tmp_path / "j.jsonl")
    s = st.Store(journal_path=path, shards=1)
    s.create(make_pod("keep").obj())
    for _ in range(1500):  # push past the compaction threshold
        fresh = s.get("Pod", "keep")
        s.update(fresh)
    s2 = st.Store(journal_path=path, shards=1)
    assert s2.get("Pod", "keep") is not None
    assert s2.journal_recovered_records == 0  # compacted file is clean


# -- circuit breaker + host fallback ----------------------------------------


def _cluster(store, nodes=2, cpu=4000):
    for i in range(nodes):
        store.create(
            make_node(f"n{i}").capacity(cpu_milli=cpu, mem=8 * GI, pods=50).obj()
        )


def test_breaker_trips_after_retry_and_falls_back_to_host():
    store = st.Store()
    _cluster(store)
    for i in range(4):
        store.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    sched = _mk_scheduler(store)
    reg = faults.FaultRegistry().fail("batch.solve", n=-1)  # device dead
    try:
        with faults.armed(reg):
            stats = sched.schedule_batch(timeout=2)
            assert stats["scheduled"] == 4  # host fallback placed them
            assert sched.flush_binds(30)
        assert sched.tpu.breaker.state == SolveCircuitBreaker.OPEN
        assert sched.tpu.breaker.fallbacks >= 1
        assert reg.fired["batch.solve"] == 2  # attempt + ONE retry
        for i in range(4):
            assert store.get("Pod", f"p{i}").spec.node_name
        assert sched.metrics.solve_breaker_state.get() == 2.0
        assert sched.metrics.solve_fallback_total.get() >= 1.0
    finally:
        sched.stop()


def test_tripped_breaker_keeps_scheduling_throughput():
    """With the breaker open, later batches go straight to the host path
    (no device attempt) and still schedule."""
    store = st.Store()
    _cluster(store)
    sched = _mk_scheduler(store)
    sched.tpu.breaker.record_failure()  # force open, long cooldown
    sched.tpu.breaker.cooldown = 3600.0
    try:
        store.create(make_pod("q0").req(cpu_milli=100).obj())
        stats = sched.schedule_batch(timeout=2)
        assert stats["scheduled"] == 1
        assert sched.flush_binds(30)
        assert store.get("Pod", "q0").spec.node_name
    finally:
        sched.stop()


def test_nonfinite_scores_trip_breaker_via_health_check():
    store = st.Store()
    _cluster(store)
    for i in range(2):
        store.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    sched = _mk_scheduler(store)
    reg = faults.FaultRegistry().corrupt("batch.solve", n=-1)
    try:
        with faults.armed(reg):
            stats = sched.schedule_batch(timeout=2)
            assert stats["scheduled"] == 2
            assert sched.flush_binds(30)
        assert sched.tpu.breaker.state == SolveCircuitBreaker.OPEN
        assert sched.tpu.breaker.fallbacks >= 1
    finally:
        sched.stop()


def test_breaker_half_open_probe_recovers():
    now = [0.0]
    br = SolveCircuitBreaker(cooldown=5.0, clock=lambda: now[0])
    assert br.allow_device()
    br.record_failure()
    assert br.state == br.OPEN
    assert not br.allow_device()  # inside the cooldown
    now[0] = 6.0
    assert br.allow_device()  # the half-open probe
    assert br.state == br.HALF_OPEN
    assert not br.allow_device()  # only ONE probe flows
    br.record_success()
    assert br.state == br.CLOSED
    # failure during the probe re-opens with a fresh cooldown
    br.record_failure()
    now[0] = 12.0
    assert br.allow_device()
    br.record_failure()
    assert br.state == br.OPEN and not br.allow_device()


def test_fallback_parity_with_device_solve():
    """Acceptance: on a healthy snapshot the host fallback must place
    identically to the device solve (the oracle-parity families)."""
    nodes = [
        make_node(f"n{i}")
        .capacity(cpu_milli=4000, mem=8 * GI, pods=20)
        .zone(f"z{i % 2}")
        .label("disk", "ssd" if i % 2 else "hdd")
        .obj()
        for i in range(6)
    ]
    def pods():
        out = []
        for i in range(12):
            p = make_pod(f"p{i}").req(cpu_milli=200 + 50 * (i % 3), mem=GI)
            if i % 4 == 0:
                p = p.label("app", "web").pod_anti_affinity({"app": "web"})
            if i % 3 == 0:
                p = p.node_selector(disk="ssd")
            out.append(p.obj())
        return out

    device = TPUBatchScheduler()
    for n in nodes:
        device.add_node(n)
    want = device.schedule_pending(pods())

    host = TPUBatchScheduler()
    for n in nodes:
        host.add_node(n)
    host.breaker.record_failure()
    host.breaker.cooldown = 3600.0  # pinned open: every batch host-solves
    got = host.schedule_pending(pods())
    assert host.breaker.fallbacks >= 1
    assert got == want, "fallback placements diverge from the device solve"


# -- binder supervision ------------------------------------------------------


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_binder_watchdog_restarts_crashed_worker_and_recommits():
    store = st.Store()
    _cluster(store)
    for i in range(3):
        store.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    # whole-wave path pinned: binder supervision (watchdog restart,
    # poison split) belongs to the non-streamed wave worker — the
    # streamed path requeues a failed sub-wave instead by design
    sched = _mk_scheduler(
        store, config=SchedulerConfiguration(stream_subwaves=False)
    )
    reg = faults.FaultRegistry().crash("binder.commit_wave", n=1)
    try:
        with faults.armed(reg):
            stats = sched.schedule_batch(timeout=2)
            assert stats["scheduled"] == 3
            # the worker dies mid-commit; flush_binds' watchdog restarts
            # it and the preserved wave commits
            assert sched.flush_binds(30)
        assert sched.metrics.binder_restarts.total >= 1
        for i in range(3):
            assert store.get("Pod", f"p{i}").spec.node_name
    finally:
        sched.stop()


def test_poison_wave_splits_to_per_pod_commits():
    store = st.Store()
    _cluster(store)
    for i in range(3):
        store.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    sched = _mk_scheduler(  # whole-wave path: see watchdog test
        store, config=SchedulerConfiguration(stream_subwaves=False)
    )
    # the whole wave fails twice (attempt + retry) -> split; the per-pod
    # commits run with the schedule drained and succeed
    reg = faults.FaultRegistry().fail("binder.commit_wave", n=2)
    try:
        with faults.armed(reg):
            sched.schedule_batch(timeout=2)
            assert sched.flush_binds(30)
        assert sched.metrics.binder_poison_waves.total == 1
        for i in range(3):
            assert store.get("Pod", f"p{i}").spec.node_name
    finally:
        sched.stop()


def test_poison_pod_in_split_requeues_with_backoff():
    store = st.Store()
    _cluster(store)
    for i in range(3):
        store.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    sched = _mk_scheduler(  # whole-wave path: see watchdog test
        store, config=SchedulerConfiguration(stream_subwaves=False)
    )
    # wave fails twice, then the FIRST per-pod commit fails too: that one
    # pod requeues with backoff instead of riding the assume-TTL
    reg = faults.FaultRegistry().fail("binder.commit_wave", n=3)
    try:
        with faults.armed(reg):
            sched.schedule_batch(timeout=2)
            assert sched.flush_binds(30)
            bound = sum(
                1 for i in range(3)
                if store.get("Pod", f"p{i}").spec.node_name
            )
            assert bound == 2
            assert sched.queue.stats()["backoff"] == 1
            assert sched.cache.assumed_count() <= 2  # failed assume forgotten
            # the requeued pod retries and lands once faults drain
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and bound < 3:
                sched.schedule_batch(timeout=0.3)
                sched.flush_binds(10)
                bound = sum(
                    1 for i in range(3)
                    if store.get("Pod", f"p{i}").spec.node_name
                )
        assert bound == 3
    finally:
        sched.stop()


# -- duplicate-assume containment + cycle salvage ---------------------------


def test_duplicate_pod_in_one_batch_contained_per_pod():
    """The same pod popped twice across the accumulation window (delete +
    recreate racing a requeue) must not kill the cycle: the duplicate
    requeues with backoff, the first instance schedules."""
    store = st.Store()
    _cluster(store)
    store.create(make_pod("dup").req(cpu_milli=100).obj())
    sched = _mk_scheduler(store)
    try:
        pod = store.get("Pod", "dup")
        batch1 = sched.queue.pop_batch(1, timeout=2)
        assert len(batch1) == 1
        sched.queue.delete(pod)
        sched.queue.add(pod)
        batch2 = sched.queue.pop_batch(1, timeout=2)
        assert len(batch2) == 1
        # one batch containing the same pod twice
        cycle = sched._dispatch_batch(batch1 + batch2)
        stats = sched._finish_cycle(cycle)
        assert stats["scheduled"] == 1
        assert sched.metrics.schedule_attempts.get("error") == 1
        assert sched.flush_binds(30)
        assert store.get("Pod", "dup").spec.node_name
    finally:
        sched.stop()


def test_already_assumed_pod_contained_to_requeue():
    """cache.assume raising 'already assumed' must cost that pod one
    backoff, never the cycle (the _stage_group containment)."""
    store = st.Store()
    _cluster(store)
    store.create(make_pod("twice").req(cpu_milli=100).obj())
    store.create(make_pod("ok").req(cpu_milli=100).obj())
    sched = _mk_scheduler(store)
    try:
        sched.cache.assume(store.get("Pod", "twice"), "n0")
        stats = sched.schedule_batch(timeout=2)
        assert stats["popped"] == 2
        assert stats["bind_errors"] == 1  # the duplicate assume
        assert stats["scheduled"] == 1
        assert sched.flush_binds(30)
        assert store.get("Pod", "ok").spec.node_name
    finally:
        sched.stop()


def test_cycle_fault_salvages_popped_pods():
    """A cycle dying mid-stage (a plugin raising) must requeue every
    popped pod and forget stray assumes — no pod strands inflight."""
    store = st.Store()
    _cluster(store)
    for i in range(3):
        store.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    sched = _mk_scheduler(store)

    def bad_permit(pod, node):
        raise RuntimeError("injected plugin fault")

    sched.profiles.default.run_permit = bad_permit
    try:
        with pytest.raises(RuntimeError):
            sched.schedule_batch(timeout=2)
        s = sched.queue.stats()
        assert s["inflight"] == 0, "pods stranded inflight"
        assert s["backoff"] == 3
        assert sched.cache.assumed_count() == 0, "stray assume leaked"
    finally:
        sched.stop()


# -- watch overflow → Expired → relist → resume -----------------------------


def _wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return False


def test_watch_overflow_expires_instead_of_terminates():
    """Coalescing overflow (more DISTINCT pending objects than the
    capacity) must EXPIRE the watcher — bookmark rv + forced relist —
    never destructively terminate it: iteration raises Expired, and the
    relist + watch(from_rv=rv) resume loses nothing and dupes nothing."""
    store = st.Store(watch_capacity=4)
    w = store.watch("Pod")
    for i in range(8):  # 8 distinct keys against a 4-entry buffer
        store.create(make_pod(f"p{i}").obj())
    assert _wait_for(lambda: w.expired)  # fan-out thread expires it
    assert store.watchers_terminated == 0
    assert store.terminated_by_kind == {}
    assert store.watch_stats()["watch_expired_total"] == 1
    with pytest.raises(st.Expired):
        list(w)  # the 410 signal, never a hang
    # the relist half: list gives a consistent snapshot + resume rv
    items, rv = store.list("Pod")
    assert {p.meta.name for p in items} == {f"p{i}" for i in range(8)}
    w2 = store.watch("Pod", from_rv=rv)
    store.create(make_pod("late").obj())
    ev = w2.get(timeout=2)
    assert ev is not None and ev.obj.meta.name == "late"
    assert w2.get(timeout=0.1) is None  # exactly once: no replayed dupes
    w2.stop()


def test_watch_expired_consistent_after_buffer_eviction():
    store = st.Store(buffer_size=4)
    store.create(make_pod("x").obj())
    old_rv = store.resource_version
    for i in range(16):  # push the buffer past old_rv
        store.create(make_pod(f"y{i}").obj())
    with pytest.raises(st.Expired):
        store.watch("Pod", from_rv=old_rv)
    # relist + resume from the fresh rv works
    _, rv = store.list("Pod")
    w = store.watch("Pod", from_rv=rv)
    store.create(make_pod("z").obj())
    assert w.get(timeout=2).obj.meta.name == "z"
    w.stop()


def test_watch_replay_overflow_raises_expired_not_silent_loss():
    """Chaos-found regression (seed 11): a watch(from_rv=...) whose
    buffered REPLAY overflows (or is fault-dropped) must raise Expired so
    the reflector relists — the old path silently dropped the replayed
    event on a brand-new stream, leaving the consumer stale forever with
    no overflow-kill to expose it."""
    store = st.Store()
    store.create(make_pod("a").obj())
    rv0 = 0  # replay everything
    reg = faults.FaultRegistry().drop("watch.offer", n=1)
    with faults.armed(reg), pytest.raises(st.Expired):
        store.watch("Pod", from_rv=rv0)
    # the refused stream counts as an EXPIRY (observability), never a
    # destructive termination, and a fresh relist + watch works
    assert store.watchers_terminated == 0
    assert store.watch_stats()["watch_expired_total"] == 1
    items, rv = store.list("Pod")
    assert [p.meta.name for p in items] == ["a"]
    w = store.watch("Pod", from_rv=rv)
    store.create(make_pod("b").obj())
    assert w.get(timeout=2).obj.meta.name == "b"
    w.stop()


def test_injected_watch_drop_expires_and_relist_recovers():
    store = st.Store()
    w = store.watch("Pod")
    reg = faults.FaultRegistry().drop("watch.offer", n=1)
    with faults.armed(reg):
        store.create(make_pod("dropped").obj())
        # the drop fires on the fan-out thread: stay armed until it did
        assert _wait_for(lambda: w.expired)
    assert store.watchers_terminated == 0
    assert store.watch_stats()["watch_expired_total"] == 1
    with pytest.raises(st.Expired):
        list(w)  # the 410 signal: relist
    items, rv = store.list("Pod")
    assert [p.meta.name for p in items] == ["dropped"]  # relist sees it
