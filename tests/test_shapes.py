"""recompile-discipline (analysis/shapes.py + analysis/retrace.py).

Three layers:

  * the real-tree gate: the full --shapes suite (encode lattice
    validation, eval_shape kernel/contract parity, gang-retry bucket
    closure) runs over the actual repository and must be clean — the
    tier-1 twin of `make lint-shapes`;
  * drift detection: a deliberately-corrupted contract must produce
    findings (the suite is not vacuously green);
  * the runtime retrace tracker: trace counting, the steady window,
    duplicate-key detection, and the real-solver integration (a new
    pad bucket after mark_steady() is a violation).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_tpu.analysis import contracts as ct
from kubernetes_tpu.analysis import retrace, shapes
from kubernetes_tpu.utils import vocab as vb

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Tests that seed their own steady-window state must not run while the
# session-wide tracker is armed (GRAFTLINT_SHAPES=1): nested tracked()
# shares the session tracker, so the seeded events would leak into it.
_armed = os.environ.get("GRAFTLINT_SHAPES") == "1"
skip_if_armed = pytest.mark.skipif(
    _armed, reason="seeds retrace events; session-wide tracker is armed"
)


# -- the real-tree gate ------------------------------------------------------

def test_shapes_tree_is_clean():
    """ISSUE acceptance: `python -m kubernetes_tpu.analysis --shapes`
    exits clean on the tree."""
    findings = shapes.check(REPO_ROOT)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_preemption_lattice_closure_and_contracts():
    """The batched preemption kernel: every raw (candidate, victim,
    level, pod) size pads onto the power-of-two family, and eval_shape
    at one lattice bucket matches the BatchDryRunResult contracts (the
    targeted twin of the tree gate's _check_preemption_kernel)."""
    from kubernetes_tpu.ops import preemption as pre_ops

    for raw_n, raw_k, raw_l, raw_p in shapes.PREEMPT_RAW_SIZES:
        for dim, floor in (
            (raw_n, 8), (raw_k, 4), (raw_l, 1), (raw_p, 4),
        ):
            assert vb.is_pad_bucket(vb.pad_dim(dim, floor), 1)
    n, k, l, p = shapes.PREEMPT_LATTICE[-1]
    r = 4
    batch = pre_ops.PreemptionBatch(
        free=jax.ShapeDtypeStruct((n, r), np.float32),
        victim_req=jax.ShapeDtypeStruct((n, k, r), np.float32),
        perm=jax.ShapeDtypeStruct((l, n, k), np.int32),
        elig_len=jax.ShapeDtypeStruct((l, n), np.int32),
        viol=jax.ShapeDtypeStruct((l, n, k), bool),
        pods_req=jax.ShapeDtypeStruct((p, r), np.float32),
        pod_level=jax.ShapeDtypeStruct((p,), np.int32),
    )
    res = jax.eval_shape(pre_ops.batched_dry_run, batch)
    assert tuple(res.feasible.shape) == (p, n) and str(res.feasible.dtype) == "bool"
    assert tuple(res.min_k.shape) == (p, n) and str(res.min_k.dtype) == "int32"
    assert tuple(res.viol_k.shape) == (p, n) and str(res.viol_k.dtype) == "int32"


def test_gang_retry_bucket_closure():
    """The pad-bucket lattice is closed under the gang-admission-retry
    subset solves: with num_pods_hint pinned to the full batch, every
    subset size lands in the full batch's bucket."""
    findings = []
    shapes._check_gang_retry_closure(findings)
    assert findings == []
    # the property itself, spelled out: any k <= full shares the bucket
    for full in (5, 100, 1024):
        bucket = vb.pad_dim(full, 8)
        assert all(
            vb.pad_dim(max(k, full), 8) == bucket for k in range(1, full + 1)
        )


def test_axis_transition_coverage():
    """The elastic-node-axis check (ISSUE 15) runs clean on the real
    tree AND actually detects its failure modes (not vacuously green):
    a broken shrink dwell — the bucket moving before the dwell is
    served — must produce findings."""
    byclass = shapes._schema_contracts(REPO_ROOT)
    findings = []
    shapes._check_axis_transitions(byclass, findings)
    assert findings == []

    from kubernetes_tpu.ops import schema

    orig = schema.ClusterState.configure_elastic_axis

    def no_dwell(self, headroom=None, shrink_dwell=None,
                 compaction_batch_rows=None):
        orig(self, headroom, 1, compaction_batch_rows)

    schema.ClusterState.configure_elastic_axis = no_dwell
    try:
        findings = []
        shapes._check_axis_transitions(byclass, findings)
        assert findings, "a broken shrink dwell must be detected"
        assert any("dwell" in f.message for f in findings)
    finally:
        schema.ClusterState.configure_elastic_axis = orig


def test_abstract_snapshot_matches_real_encode():
    """The contract-built abstract snapshot has exactly the shapes and
    dtypes the real encoder produces for the same buckets — the two
    halves of the pass can't drift apart."""
    from kubernetes_tpu.ops import schema
    from kubernetes_tpu.testing.wrappers import MI, make_node, make_pod

    byclass = shapes._schema_contracts(REPO_ROOT)
    nodes = [make_node(f"n{i}").obj() for i in range(3)]
    pods = [
        make_pod(f"p{i}").req(cpu_milli=100, mem=128 * MI).obj()
        for i in range(2)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    # 2 pod classes: the identical real specs collapse to one, the
    # invalid pad rows form the other
    abstract = shapes.abstract_snapshot(byclass, n=8, p=8,
                                        rows={"classes": 2})
    real_leaves = jax.tree_util.tree_leaves(snap)
    abs_leaves = jax.tree_util.tree_leaves(abstract)
    assert len(real_leaves) == len(abs_leaves)
    for r, a in zip(real_leaves, abs_leaves):
        assert tuple(np.asarray(r).shape) == tuple(a.shape)
        assert str(np.asarray(r).dtype) == str(a.dtype)


# -- drift detection (the suite is not vacuously green) ----------------------

def test_encode_validation_detects_dtype_drift():
    byclass = shapes._schema_contracts(REPO_ROOT)
    c = byclass["ClusterTensors"]["allocatable"]
    byclass["ClusterTensors"]["allocatable"] = ct.Contract(
        c.cls, c.field, "float64", c.axes, c.line, c.file
    )
    findings = []
    shapes._check_encode(byclass, findings)
    assert any(
        f.symbol == "ClusterTensors.allocatable" and "dtype" in f.message
        for f in findings
    )


def test_encode_validation_detects_axis_drift():
    byclass = shapes._schema_contracts(REPO_ROOT)
    c = byclass["PodBatch"]["req"]
    # claim req is [N, R]: the pod bucket lands elsewhere -> mismatch
    bad_axes = (ct.Axis(sym="N"), c.axes[1])
    byclass["PodBatch"]["req"] = ct.Contract(
        c.cls, c.field, c.dtype, bad_axes, c.line, c.file
    )
    findings = []
    shapes._check_encode(byclass, findings)
    assert any(f.symbol == "PodBatch.req" for f in findings)


# -- runtime retrace tracker -------------------------------------------------

@skip_if_armed
def test_retrace_tracker_counts_traces_and_steady_window():
    f = jax.jit(lambda x: x + 1)
    with retrace.tracked() as tr:
        x = jnp.zeros(4, jnp.float32)
        f(x)
        retrace.note("k", f, lambda: retrace.signature(x))
        assert tr.total == 1
        f(x)  # warm: no new executable
        retrace.note("k", f, lambda: retrace.signature(x))
        assert tr.total == 1
        tr.assert_no_steady_recompiles()
        retrace.mark_steady()
        y = jnp.zeros(8, jnp.float32)
        f(y)  # new shape after steady: violation
        retrace.note("k", f, lambda: retrace.signature(y))
        assert tr.steady_total == 1
        with pytest.raises(retrace.RetraceViolation):
            tr.assert_no_steady_recompiles()
        tr.assert_no_duplicate_traces()  # two DISTINCT keys: fine
    assert retrace.active() is None


@skip_if_armed
def test_retrace_tracker_flags_duplicate_executable_keys():
    """The same signature traced twice means the compile cache is not
    holding the key — always a failure, steady window or not."""
    tr = retrace.RetraceTracker()

    class FakeJit:
        def __init__(self):
            self.n = 0

        def _cache_size(self):
            return self.n

    fj = FakeJit()
    fj.n = 1
    tr.note("k", fj, lambda: ("sig",))
    fj.n = 2  # cache grew again for the SAME signature
    tr.note("k", fj, lambda: ("sig",))
    assert tr.duplicates
    with pytest.raises(retrace.RetraceViolation):
        tr.assert_no_duplicate_traces()


@skip_if_armed
def test_retrace_tracker_disarmed_is_noop():
    f = jax.jit(lambda x: x * 2)
    f(jnp.zeros(4))
    retrace.note("k", f, lambda: retrace.signature(jnp.zeros(4)))
    assert retrace.total() == 0 and retrace.steady_total() == 0


@skip_if_armed
def test_solver_dispatch_reports_to_tracker():
    """Real integration: the greedy jit wrapper notes its traces; a
    same-bucket re-solve is silent, a new pod bucket after the steady
    mark is a steady-state recompile."""
    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
    from kubernetes_tpu.testing.wrappers import MI, make_node, make_pod

    def pods(tag, k):
        return [
            make_pod(f"{tag}-{i}").req(cpu_milli=100, mem=128 * MI).obj()
            for i in range(k)
        ]

    with retrace.tracked() as tr:
        sched = TPUBatchScheduler()
        for i in range(4):
            sched.add_node(make_node(f"n{i}").obj())
        sched.schedule_pending(pods("warm", 4))
        assert tr.total >= 1
        retrace.mark_steady()
        sched.schedule_pending(pods("run", 4))  # same bucket: no trace
        assert tr.steady_total == 0
        sched.schedule_pending(pods("big", 9))  # bucket 8 -> 16: trace
        assert tr.steady_total >= 1
        with pytest.raises(retrace.RetraceViolation):
            tr.assert_no_steady_recompiles()
        tr.assert_no_duplicate_traces()
