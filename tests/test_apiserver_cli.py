"""API server (REST + watch over the store) and the kubectl-style CLI.

Reference shapes: apiserver endpoints/handlers (+watch.go chunked
streams), client-go rest.Request, kubectl verb set."""

import io
import sys
import threading
import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.server import APIServer
from kubernetes_tpu.cli import main as cli_main
from kubernetes_tpu.client.rest import RestClient
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


@pytest.fixture
def server():
    store = st.Store()
    srv = APIServer(store).start()
    yield store, srv
    srv.stop()


def test_rest_crud_roundtrip(server):
    store, srv = server
    client = RestClient(srv.url)
    pod = make_pod("p").req(cpu_milli=500, mem=GI).label("app", "x").obj()
    created = client.create(pod)
    assert created.meta.resource_version > 0
    got = client.get("Pod", "p")
    assert got == created
    got.spec.node_name = "n0"
    updated = client.update(got)
    assert updated.spec.node_name == "n0"
    items, rv = client.list("Pod")
    assert len(items) == 1 and rv >= updated.meta.resource_version
    client.delete("Pod", "p")
    with pytest.raises(st.NotFound):
        client.get("Pod", "p")


def test_rest_error_mapping(server):
    _, srv = server
    client = RestClient(srv.url)
    with pytest.raises(st.NotFound):
        client.get("Pod", "missing")
    pod = make_pod("dup").obj()
    client.create(pod)
    with pytest.raises(st.AlreadyExists):
        client.create(pod)
    stale = client.get("Pod", "dup")
    client.update(stale)  # bumps rv
    with pytest.raises(st.Conflict):
        client.update(stale)  # stale rv now


def test_rest_watch_stream(server):
    store, srv = server
    client = RestClient(srv.url)
    _, rv = client.list("Pod")
    got = []

    def consume():
        for typ, obj, _rv in client.watch("Pod", from_rv=rv):
            got.append((typ, obj.meta.name))
            if len(got) >= 2:
                break

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)
    store.create(make_pod("w1").obj())
    # wait for the ADDED to cross the wire before deleting: an
    # un-consumed ADDED+DELETED pair legitimately annihilates in the
    # watcher's coalescing buffer (docs/robustness.md) — the stream
    # contract under test here is that both event TYPES flow through
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(got) < 1:
        time.sleep(0.01)
    store.delete("Pod", "w1")
    t.join(timeout=5)
    assert got == [("ADDED", "w1"), ("DELETED", "w1")]


def _run_cli(argv):
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        cli_main(argv)
    finally:
        sys.stdout = old
    return out.getvalue()


def test_cli_get_create_scale_delete(server, tmp_path):
    store, srv = server
    base = ["--server", srv.url]
    store.create(make_node("n0").capacity(cpu_milli=4000, mem=8 * GI).obj())
    # create -f
    f = tmp_path / "pod.yaml"
    f.write_text(
        "kind: Pod\nmetadata: {name: web}\n"
        "spec:\n  containers:\n  - resources: {requests: {cpu: 500m}}\n"
    )
    out = _run_cli(base + ["create", "-f", str(f)])
    assert "pod/web created" in out
    out = _run_cli(base + ["get", "pods"])
    assert "default/web" in out
    out = _run_cli(base + ["get", "nodes"])
    assert "n0" in out
    out = _run_cli(base + ["describe", "pod", "web"])
    assert '"name": "web"' in out
    # scale a deployment
    store.create(
        api.Deployment(
            meta=api.ObjectMeta(name="front"),
            spec=api.DeploymentSpec(replicas=1),
        )
    )
    out = _run_cli(base + ["scale", "deploy", "front", "--replicas", "5"])
    assert "scaled to 5" in out
    assert store.get("Deployment", "front").spec.replicas == 5
    out = _run_cli(base + ["delete", "pod", "web"])
    assert "deleted" in out


def test_remote_controllers_via_rest_informer(server):
    """The watch protocol is strong enough to drive a reflector-style
    consumer out of process: list+watch sees a consistent sequence."""
    store, srv = server
    client = RestClient(srv.url)
    store.create(make_pod("a").obj())
    items, rv = client.list("Pod")
    cache = {p.meta.name: p for p in items}
    done = threading.Event()

    def reflector():
        for typ, obj, _rv in client.watch("Pod", from_rv=rv):
            if typ == "DELETED":
                cache.pop(obj.meta.name, None)
            else:
                cache[obj.meta.name] = obj
            if obj.meta.name == "stop":
                done.set()
                return

    t = threading.Thread(target=reflector, daemon=True)
    t.start()
    time.sleep(0.2)
    store.create(make_pod("b").obj())
    store.delete("Pod", "a")
    store.create(make_pod("stop").obj())
    assert done.wait(5)
    assert set(cache) == {"b", "stop"}


def test_cluster_scoped_objects_addressable(server):
    """Nodes live in namespace '' — the REST path uses the '-' sentinel
    so get/update/delete work (review finding: empty segment collapsed
    into a 404)."""
    store, srv = server
    client = RestClient(srv.url)
    node = make_node("n0").capacity(cpu_milli=4000, mem=8 * GI).obj()
    client.create(node)
    got = client.get("Node", "n0", namespace="")
    assert got.meta.name == "n0"
    got.meta.labels["x"] = "y"
    client.update(got)
    assert client.get("Node", "n0", namespace="").meta.labels["x"] == "y"
    # CLI paths use the cluster scope automatically
    out = _run_cli(["--server", srv.url, "get", "node", "n0"])
    assert "n0" in out
    out = _run_cli(["--server", srv.url, "describe", "node", "n0"])
    assert '"name": "n0"' in out
    _run_cli(["--server", srv.url, "delete", "node", "n0"])
    with pytest.raises(st.NotFound):
        client.get("Node", "n0", namespace="")


def test_cli_namespace_scoping(server):
    store, srv = server
    store.create(make_pod("a", namespace="team-a").obj())
    store.create(make_pod("b", namespace="team-b").obj())
    out = _run_cli(["--server", srv.url, "-n", "team-a", "get", "pods"])
    assert "team-a/a" in out and "team-b/b" not in out
    out = _run_cli(["--server", srv.url, "get", "pods", "-A"])
    assert "team-a/a" in out and "team-b/b" in out


def test_idle_watch_gets_bookmarks(server):
    """An idle watch receives keepalive BOOKMARK frames (so dead clients
    surface server-side) and the client generator filters them."""
    import urllib.request

    store, srv = server
    req = urllib.request.Request(srv.url + "/api/v1/watch/Lease")
    with urllib.request.urlopen(req, timeout=5) as r:
        line = r.readline()
    doc = __import__("json").loads(line)
    assert doc["type"] == "BOOKMARK"


def test_cli_create_deployment_yaml(server, tmp_path):
    """create -f accepts workload YAML, as the CLI help advertises
    (review finding: only Pod/Node were handled)."""
    store, srv = server
    f = tmp_path / "deploy.yaml"
    f.write_text(
        "kind: Deployment\n"
        "metadata: {name: front}\n"
        "spec:\n"
        "  replicas: 3\n"
        "  selector: {matchLabels: {app: front}}\n"
        "  template:\n"
        "    metadata: {labels: {app: front}}\n"
        "    spec:\n"
        "      containers:\n"
        "      - resources: {requests: {cpu: 250m}}\n"
    )
    out = _run_cli(["--server", srv.url, "create", "-f", str(f)])
    assert "deployment/front created" in out
    dep = store.get("Deployment", "front")
    assert dep.spec.replicas == 3
    assert dep.spec.template.meta.labels == {"app": "front"}
    assert dep.spec.template.spec.containers[0].requests["cpu"] == 250


def test_watch_raises_expired_on_stale_rv(server):
    store, srv = server
    client = RestClient(srv.url)
    # overflow the event buffer so rv 1 falls out
    small = st.Store(buffer_size=8)
    srv2 = APIServer(small).start()
    try:
        c2 = RestClient(srv2.url)
        for i in range(50):
            small.create(make_pod(f"x{i}").obj())
        with pytest.raises(st.Expired):
            for _ in c2.watch("Pod", from_rv=1):
                break
    finally:
        srv2.stop()
