"""Sharded solve must place pods identically to the single-chip solve.

Runs on the 8-virtual-device CPU mesh from conftest.py.
"""

import jax
import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import assign, schema
from kubernetes_tpu.parallel import sharded
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod

pytestmark = pytest.mark.multichip


def _workload(seed, n_nodes=32, n_pods=40):
    rng = np.random.default_rng(seed)
    zones = ["z1", "z2", "z3"]
    nodes = []
    for i in range(n_nodes):
        nw = (
            make_node(f"n{i}")
            .capacity(
                cpu_milli=int(rng.choice([4000, 8000, 16000])),
                mem=int(rng.choice([8, 16, 32])) * GI,
                pods=110,
            )
            .zone(str(rng.choice(zones)))
        )
        if rng.random() < 0.2:
            nw.taint("dedicated", "batch", api.NO_SCHEDULE)
        if rng.random() < 0.2:
            nw.taint("flaky", "true", api.PREFER_NO_SCHEDULE)
        nodes.append(nw.obj())
    pods = []
    for i in range(n_pods):
        pw = make_pod(f"p{i}").req(
            cpu_milli=int(rng.choice([100, 500, 1000, 2000])),
            mem=int(rng.choice([128, 512, 1024])) * MI,
        )
        if rng.random() < 0.3:
            pw.node_selector_kv(api.LABEL_ZONE, str(rng.choice(zones)))
        if rng.random() < 0.2:
            pw.toleration("dedicated", api.OP_EQUAL, "batch", api.NO_SCHEDULE)
        if rng.random() < 0.25:
            pw.preferred_affinity(
                int(rng.integers(1, 50)), api.LABEL_ZONE, api.OP_IN, [str(rng.choice(zones))]
            )
        pods.append(pw.obj())
    return nodes, pods


@pytest.mark.parametrize("seed", range(3))
def test_sharded_matches_single_chip(seed):
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    nodes, pods = _workload(seed)
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)

    single = assign.greedy_assign(snap)
    mesh = sharded.make_mesh(8)
    multi = sharded.sharded_greedy_assign(snap, mesh)

    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )
    np.testing.assert_array_equal(
        np.asarray(single.feasible_counts), np.asarray(multi.feasible_counts)
    )
    # post-solve cluster state matches too (gather the sharded one)
    np.testing.assert_allclose(
        np.asarray(single.cluster.requested),
        np.asarray(multi.cluster.requested),
        rtol=0,
        atol=0,
    )


def test_mesh_sizes():
    nodes, pods = _workload(7, n_nodes=16, n_pods=12)
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    want = np.asarray(assign.greedy_assign(snap).assignment)
    for n_dev in (2, 4):
        mesh = sharded.make_mesh(n_dev)
        got = np.asarray(sharded.sharded_greedy_assign(snap, mesh).assignment)
        np.testing.assert_array_equal(want, got)


def test_sharded_with_spread_and_interpod():
    """Constraint count-state must stay consistent across shards (the
    psum-broadcast of the winning node's topology values)."""
    from kubernetes_tpu.testing.oracle import Oracle

    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI, pods=20)
        .zone(f"z{i % 3}").obj()
        for i in range(16)
    ]
    pods = []
    for i in range(24):
        pw = make_pod(f"p{i}").labels(app=f"a{i % 2}").req(cpu_milli=500)
        if i % 3 == 0:
            pw.spread(max_skew=1, topology_key=api.LABEL_ZONE,
                      selector={"app": f"a{i % 2}"})
        elif i % 3 == 1:
            pw.pod_anti_affinity({"app": f"a{i % 2}"}, topology_key=api.LABEL_HOSTNAME)
        else:
            pw.pod_affinity({"app": f"a{i % 2}"}, topology_key=api.LABEL_ZONE)
        pods.append(pw.obj())

    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    single = assign.greedy_assign(snap, topo_z=meta.topo_z)
    mesh = sharded.make_mesh(8)
    multi = sharded.sharded_greedy_assign(snap, mesh, topo_z=meta.topo_z)
    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )
    # and both match the oracle
    got = [meta.node_name(int(i)) for i in np.asarray(single.assignment)[:24]]
    want = Oracle(nodes).schedule(pods)
    assert got == want


def test_sharded_greedy_scores_prefpod_and_images():
    """Round-4: the extra-score families (preferred inter-pod affinity,
    ImageLocality) are now psum-hoisted — the sharded greedy must match
    the single-chip solve instead of raising."""
    nodes = []
    for i in range(16):
        nw = (
            make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI, pods=20)
            .zone(f"z{i % 3}")
        )
        if i % 2 == 0:
            nw.image(f"img-{i % 4}", 500 * MI)
        nodes.append(nw.obj())
    def _pref(pw, selector):
        aff = pw.pod.spec.affinity or api.Affinity()
        pw.pod.spec.affinity = aff
        if aff.pod_affinity is None:
            aff.pod_affinity = api.PodAffinity()
        aff.pod_affinity.preferred.append(
            api.WeightedPodAffinityTerm(
                weight=40,
                term=api.PodAffinityTerm(
                    label_selector=api.LabelSelector(match_labels=selector),
                    topology_key=api.LABEL_ZONE,
                ),
            )
        )

    pods = []
    for i in range(20):
        pw = make_pod(f"p{i}").labels(app=f"a{i % 2}").req(cpu_milli=400)
        if i % 2 == 0:
            _pref(pw, {"app": f"a{i % 2}"})
        if i % 3 == 0:
            pw.image(f"img-{i % 4}")
        pods.append(pw.obj())
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    feats = assign.features_of(snap)
    assert feats.interpod_pref or feats.images
    single = assign.greedy_assign(snap, topo_z=meta.topo_z)
    mesh = sharded.make_mesh(8)
    multi = sharded.sharded_greedy_assign(snap, mesh, topo_z=meta.topo_z)
    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )


def _wavefront_workload(seed, n_nodes=32, n_pods=80):
    """Wavefront-shaped batch: every dynamic-coupling family active
    (ports, spread, anti-affinity) so the wave partition, the mini-scan
    corrections, and the serialized fallback all exercise."""
    rng = np.random.default_rng(seed)
    zones = ["z1", "z2", "z3"]
    nodes = [
        make_node(f"n{i}")
        .capacity(
            cpu_milli=int(rng.choice([4000, 8000, 16000])),
            mem=int(rng.choice([8, 16, 32])) * GI,
            pods=110,
        )
        .zone(str(rng.choice(zones)))
        .obj()
        for i in range(n_nodes)
    ]
    pods = []
    for i in range(n_pods):
        pw = make_pod(f"p{i}").req(
            cpu_milli=int(rng.choice([100, 500, 1000])),
            mem=int(rng.choice([128, 512])) * MI,
        ).labels(app=f"a{i % 3}")
        if i % 4 == 0:
            pw.spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": f"a{i % 3}"})
        elif i % 4 == 1:
            pw.pod_anti_affinity({"app": f"a{i % 3}"}, api.LABEL_HOSTNAME)
        elif i % 4 == 2:
            pw.host_port(8000 + (i % 5))
        pods.append(pw.obj())
    return nodes, pods


@pytest.mark.parametrize("seed", range(3))
def test_sharded_wavefront_matches_scan_and_single_chip(seed):
    """The sharded wavefront must equal BOTH the single-chip wavefront
    (bit-identical, including the fallback counters) and the classic
    scan (the wavefront's own parity contract) — the full chain the
    mesh hot path rests on."""
    nodes, pods = _wavefront_workload(seed)
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    plan = assign.plan_waves(snap)
    scan = assign.greedy_assign(snap)
    single = assign.wavefront_assign(snap, plan.members)
    mesh = sharded.make_mesh(8)
    multi = sharded.sharded_wavefront_assign(snap, plan.members, mesh)
    np.testing.assert_array_equal(
        np.asarray(scan.assignment), np.asarray(single.assignment)
    )
    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )
    np.testing.assert_array_equal(
        np.asarray(single.reasons), np.asarray(multi.reasons)
    )
    np.testing.assert_array_equal(
        np.asarray(single.feasible_counts),
        np.asarray(multi.feasible_counts),
    )
    np.testing.assert_allclose(
        np.asarray(single.cluster.requested),
        np.asarray(multi.cluster.requested),
        rtol=0, atol=0,
    )
    assert int(single.wave_count) == int(multi.wave_count)
    assert int(single.wave_fallbacks) == int(multi.wave_fallbacks)


def test_sharded_wavefront_serialized_waves_parity():
    """A hand-built COUPLED partition (naive contiguous 32-chunks of the
    solve order) forces the device-side safety check to serialize waves:
    any contiguous partition is scan-identical, on both layouts."""
    nodes, pods = _wavefront_workload(5)
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    p = np.asarray(snap.pods.req).shape[0]
    order = np.argsort(
        -np.asarray(snap.pods.priority), kind="stable"
    ).astype(np.int32)
    n_waves = (p + 31) // 32
    members = np.full((max(8, n_waves), 32), -1, np.int32)
    for w in range(n_waves):
        chunk = order[w * 32:(w + 1) * 32]
        members[w, : len(chunk)] = chunk
    scan = assign.greedy_assign(snap)
    single = assign.wavefront_assign(snap, members)
    multi = sharded.sharded_wavefront_assign(
        snap, members, sharded.make_mesh(8)
    )
    assert int(single.wave_fallbacks) > 0  # coupling actually fired
    np.testing.assert_array_equal(
        np.asarray(scan.assignment), np.asarray(single.assignment)
    )
    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )
    assert int(single.wave_fallbacks) == int(multi.wave_fallbacks)


def test_sharded_wavefront_and_greedy_gang_release_parity():
    """Gang all-or-nothing releases identically across shards: the
    shared post-pass subtracts only owned rows per shard."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=2000, mem=4 * GI, pods=4).obj()
        for i in range(8)
    ]
    pods = [
        make_pod(f"g{i}").req(cpu_milli=1500, mem=GI).group("g", size=70).obj()
        for i in range(70)
    ] + [
        make_pod(f"s{i}").req(cpu_milli=100, mem=MI).obj() for i in range(10)
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    ng = schema.num_groups(snap)
    plan = assign.plan_waves(snap)
    mesh = sharded.make_mesh(8)
    scan = assign.greedy_assign(snap, n_groups=ng)
    wf_multi = sharded.sharded_wavefront_assign(
        snap, plan.members, mesh, n_groups=ng
    )
    gr_multi = sharded.sharded_greedy_assign(snap, mesh, n_groups=ng)
    assert (np.asarray(scan.assignment)[:70] == -1).all()  # gang released
    for got in (wf_multi, gr_multi):
        np.testing.assert_array_equal(
            np.asarray(scan.assignment), np.asarray(got.assignment)
        )
        np.testing.assert_array_equal(
            np.asarray(scan.reasons), np.asarray(got.reasons)
        )
        np.testing.assert_allclose(
            np.asarray(scan.cluster.requested),
            np.asarray(got.cluster.requested),
            rtol=0, atol=0,
        )


def _auction_parity(nodes, pods, tie_k=64, n_dev=8):
    from kubernetes_tpu.ops import auction as auc

    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    feats = assign.features_of(snap)
    tsplit = assign.required_topo_z_split(snap)
    ng = schema.num_groups(snap)
    single = auc.auction_assign(
        snap, n_groups=ng, features=feats, topo_z=tsplit, tie_k=tie_k
    )
    mesh = sharded.make_mesh(n_dev)
    multi = sharded.sharded_auction_assign(
        snap, mesh, n_groups=ng, features=feats, topo_z=tsplit, tie_k=tie_k
    )
    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )
    np.testing.assert_array_equal(
        np.asarray(single.reasons), np.asarray(multi.reasons)
    )
    np.testing.assert_allclose(
        np.asarray(single.cluster.requested),
        np.asarray(multi.cluster.requested),
        rtol=0, atol=0,
    )
    return single, multi, meta


def test_sharded_auction_basic_parity():
    """Sharded auction == single-chip auction, resources-only + gangs."""
    rng = np.random.default_rng(11)
    nodes = [
        make_node(f"n{i}")
        .capacity(cpu_milli=int(rng.choice([8000, 16000])), mem=32 * GI, pods=64)
        .zone(f"z{i % 3}").obj()
        for i in range(32)
    ]
    pods = [
        make_pod(f"p{i}")
        .req(cpu_milli=int(rng.choice([500, 1000])), mem=512 * MI)
        .group(f"g{i % 4}", size=8)
        .obj()
        for i in range(32)
    ]
    single, multi, _ = _auction_parity(nodes, pods)
    assert (np.asarray(single.assignment) >= 0).sum() == 32


def test_sharded_auction_spread_interpod_parity():
    """Sharded auction must repair spread + anti-affinity identically."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI, pods=20)
        .zone(f"z{i % 4}").obj()
        for i in range(32)
    ]
    pods = []
    for i in range(40):
        pw = make_pod(f"p{i}").labels(app=f"s{i % 5}").req(cpu_milli=300)
        if i % 2 == 0:
            pw.spread(1, api.LABEL_ZONE, "DoNotSchedule", {"app": f"s{i % 5}"})
        else:
            pw.pod_anti_affinity({"app": f"s{i % 5}"}, api.LABEL_HOSTNAME)
        pods.append(pw.obj())
    single, multi, meta = _auction_parity(nodes, pods)
    placed = (np.asarray(single.assignment)[:40] >= 0).sum()
    assert placed > 0


def test_sharded_auction_gang_release_parity():
    """An unplaceable gang releases identically on both layouts."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=2000, mem=4 * GI, pods=4).obj()
        for i in range(8)
    ]
    # gang of 12 pods each needing 1500m: at most 8 can place -> released
    pods = [
        make_pod(f"g{i}").req(cpu_milli=1500, mem=GI).group("g", size=12).obj()
        for i in range(12)
    ]
    single, multi, _ = _auction_parity(nodes, pods, n_dev=4)
    assert (np.asarray(single.assignment)[:12] == -1).all()
    assert np.asarray(single.gang_dropped).any()
    np.testing.assert_array_equal(
        np.asarray(single.gang_dropped), np.asarray(multi.gang_dropped)
    )
