"""Sharded solve must place pods identically to the single-chip solve.

Runs on the 8-virtual-device CPU mesh from conftest.py.
"""

import jax
import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import assign, schema
from kubernetes_tpu.parallel import sharded
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _workload(seed, n_nodes=32, n_pods=40):
    rng = np.random.default_rng(seed)
    zones = ["z1", "z2", "z3"]
    nodes = []
    for i in range(n_nodes):
        nw = (
            make_node(f"n{i}")
            .capacity(
                cpu_milli=int(rng.choice([4000, 8000, 16000])),
                mem=int(rng.choice([8, 16, 32])) * GI,
                pods=110,
            )
            .zone(str(rng.choice(zones)))
        )
        if rng.random() < 0.2:
            nw.taint("dedicated", "batch", api.NO_SCHEDULE)
        if rng.random() < 0.2:
            nw.taint("flaky", "true", api.PREFER_NO_SCHEDULE)
        nodes.append(nw.obj())
    pods = []
    for i in range(n_pods):
        pw = make_pod(f"p{i}").req(
            cpu_milli=int(rng.choice([100, 500, 1000, 2000])),
            mem=int(rng.choice([128, 512, 1024])) * MI,
        )
        if rng.random() < 0.3:
            pw.node_selector_kv(api.LABEL_ZONE, str(rng.choice(zones)))
        if rng.random() < 0.2:
            pw.toleration("dedicated", api.OP_EQUAL, "batch", api.NO_SCHEDULE)
        if rng.random() < 0.25:
            pw.preferred_affinity(
                int(rng.integers(1, 50)), api.LABEL_ZONE, api.OP_IN, [str(rng.choice(zones))]
            )
        pods.append(pw.obj())
    return nodes, pods


@pytest.mark.parametrize("seed", range(3))
def test_sharded_matches_single_chip(seed):
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    nodes, pods = _workload(seed)
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)

    single = assign.greedy_assign(snap)
    mesh = sharded.make_mesh(8)
    multi = sharded.sharded_greedy_assign(snap, mesh)

    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )
    np.testing.assert_array_equal(
        np.asarray(single.feasible_counts), np.asarray(multi.feasible_counts)
    )
    # post-solve cluster state matches too (gather the sharded one)
    np.testing.assert_allclose(
        np.asarray(single.cluster.requested),
        np.asarray(multi.cluster.requested),
        rtol=0,
        atol=0,
    )


def test_mesh_sizes():
    nodes, pods = _workload(7, n_nodes=16, n_pods=12)
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    want = np.asarray(assign.greedy_assign(snap).assignment)
    for n_dev in (2, 4):
        mesh = sharded.make_mesh(n_dev)
        got = np.asarray(sharded.sharded_greedy_assign(snap, mesh).assignment)
        np.testing.assert_array_equal(want, got)


def test_sharded_with_spread_and_interpod():
    """Constraint count-state must stay consistent across shards (the
    psum-broadcast of the winning node's topology values)."""
    from kubernetes_tpu.testing.oracle import Oracle

    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI, pods=20)
        .zone(f"z{i % 3}").obj()
        for i in range(16)
    ]
    pods = []
    for i in range(24):
        pw = make_pod(f"p{i}").labels(app=f"a{i % 2}").req(cpu_milli=500)
        if i % 3 == 0:
            pw.spread(max_skew=1, topology_key=api.LABEL_ZONE,
                      selector={"app": f"a{i % 2}"})
        elif i % 3 == 1:
            pw.pod_anti_affinity({"app": f"a{i % 2}"}, topology_key=api.LABEL_HOSTNAME)
        else:
            pw.pod_affinity({"app": f"a{i % 2}"}, topology_key=api.LABEL_ZONE)
        pods.append(pw.obj())

    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    single = assign.greedy_assign(snap, topo_z=meta.topo_z)
    mesh = sharded.make_mesh(8)
    multi = sharded.sharded_greedy_assign(snap, mesh, topo_z=meta.topo_z)
    np.testing.assert_array_equal(
        np.asarray(single.assignment), np.asarray(multi.assignment)
    )
    # and both match the oracle
    got = [meta.node_name(int(i)) for i in np.asarray(single.assignment)[:24]]
    want = Oracle(nodes).schedule(pods)
    assert got == want
