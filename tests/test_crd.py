"""CRD-lite: dynamic kinds through store, wire, REST, informers;
PodGroup as the proving instance driving coscheduling gang sizes.

VERDICT r4 #8 acceptance: create a CRD, create instances through REST,
watch them from an informer, drive gang sizes from PodGroup objects.
Reference: staging/src/k8s.io/apiextensions-apiserver.
"""

import time

import pytest

from kubernetes_tpu.api import admission as adm
from kubernetes_tpu.api import crd
from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api import wire
from kubernetes_tpu.api.server import APIServer
from kubernetes_tpu.client.informers import InformerFactory
from kubernetes_tpu.client.rest import RestClient


def _wait(cond, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(every)
    return False


def _widget_crd():
    return crd.CustomResourceDefinition(
        meta=api.ObjectMeta(name="widgets.example.com", namespace=""),
        spec=crd.CustomResourceDefinitionSpec(
            group="example.com",
            names=crd.CRDNames(kind="Widget", plural="widgets"),
            schema={
                "properties": {
                    "size": {"type": "integer", "minimum": 1, "maximum": 64},
                    "color": {"type": "string", "enum": ["red", "blue"]},
                    "tags": {"type": "array", "items": {"type": "string"}},
                },
                "required": ["size"],
            },
        ),
    )


def test_dynamic_kind_crud_and_schema_validation():
    store = st.Store(admission=adm.default_chain())
    store.create(_widget_crd())
    w = crd.DynamicObject(
        "Widget",
        meta=api.ObjectMeta(name="w1"),
        spec={"size": 4, "color": "red", "tags": ["a"]},
    )
    store.create(w)
    got = store.get("Widget", "w1")
    assert got.spec["size"] == 4 and got.KIND == "Widget"

    # schema violations reject at admission
    with pytest.raises(adm.AdmissionError, match="required"):
        store.create(crd.DynamicObject(
            "Widget", meta=api.ObjectMeta(name="w2"), spec={}))
    with pytest.raises(adm.AdmissionError, match="minimum"):
        store.create(crd.DynamicObject(
            "Widget", meta=api.ObjectMeta(name="w3"), spec={"size": 0}))
    with pytest.raises(adm.AdmissionError, match="not one of"):
        store.create(crd.DynamicObject(
            "Widget", meta=api.ObjectMeta(name="w4"),
            spec={"size": 1, "color": "green"}))
    with pytest.raises(adm.AdmissionError, match="expected integer"):
        store.create(crd.DynamicObject(
            "Widget", meta=api.ObjectMeta(name="w5"), spec={"size": "big"}))
    # unregistered kind rejects
    with pytest.raises(adm.AdmissionError, match="no CustomResourceDefinition"):
        store.create(crd.DynamicObject(
            "Gadget", meta=api.ObjectMeta(name="g1"), spec={}))


def test_wire_round_trip_and_journal_replay(tmp_path):
    path = str(tmp_path / "j.log")
    s1 = st.Store(journal_path=path)
    s1.create(_widget_crd())
    s1.create(crd.DynamicObject(
        "Widget", meta=api.ObjectMeta(name="w1"), spec={"size": 2}))
    # wire round-trip preserves identity
    doc = wire.to_wire(s1.get("Widget", "w1"))
    back = wire.from_wire(doc)
    assert back == s1.get("Widget", "w1")
    # crash-replay recovers dynamic instances
    s2 = st.Store(journal_path=path)
    assert s2.get("Widget", "w1").spec["size"] == 2
    assert s2.get("CustomResourceDefinition",
                  "widgets.example.com", "").spec.names.kind == "Widget"


def test_dynamic_kind_over_rest_and_informers():
    store = st.Store(admission=adm.default_chain())
    store.create(_widget_crd())
    srv = APIServer(store).start()
    factory = InformerFactory(store)
    inf = factory.informer("Widget")
    seen = []
    inf.add_handler(lambda typ, obj, old: seen.append((typ, obj.meta.name)))
    inf.start()
    try:
        cli = RestClient(srv.url)
        cli.create(crd.DynamicObject(
            "Widget", meta=api.ObjectMeta(name="w1"), spec={"size": 8}))
        got = cli.get("Widget", "w1")
        assert isinstance(got, crd.DynamicObject) and got.spec["size"] == 8
        assert _wait(lambda: (st.ADDED, "w1") in seen)
        cli.delete("Widget", "w1")
        assert _wait(lambda: (st.DELETED, "w1") in seen)
    finally:
        factory.stop()
        srv.stop()


def test_podgroup_drives_gang_sizes():
    from kubernetes_tpu.scheduler.coscheduling import CoschedulingPermit
    from kubernetes_tpu.scheduler.waitingpods import WaitingPodsMap

    store = st.Store(admission=adm.default_chain())
    crd.install_podgroup_crd(store)
    store.create(crd.pod_group("g1", min_member=2, timeout_s=7.5))
    waiting = WaitingPodsMap()
    cos = CoschedulingPermit(waiting, directory=crd.PodGroupDirectory(store))

    def member(name):
        return api.Pod(
            meta=api.ObjectMeta(name=name),
            spec=api.PodSpec(scheduling_group="g1"),
        )

    # first member waits with the PodGroup's timeout
    verdict, timeout = cos.permit(member("a"), "n0")
    assert verdict == "wait" and timeout == 7.5
    # park it, second member completes the quorum
    from kubernetes_tpu.scheduler.waitingpods import WaitingPod

    wp = WaitingPod(member("a"), "n0", timeout)
    waiting.add(wp)
    verdict, _ = cos.permit(member("b"), "n0")
    assert verdict == "allow"
    assert wp.wait() == "allow"
    # minMember schema: zero rejects
    with pytest.raises(adm.AdmissionError, match="minimum"):
        store.create(crd.pod_group("bad", min_member=0))
