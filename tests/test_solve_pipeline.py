"""Solve-side pipeline: wavefront routing, deferred readback through the
hot loop, the prewarm pool, and the new solve metrics."""

import time

import numpy as np
import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.models.batch_scheduler import (
    DeviceSolve,
    SolverPrewarmPool,
    TPUBatchScheduler,
)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing.oracle import Oracle
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def mk_nodes(n, cpu=8000):
    return [
        make_node(f"n{i}").capacity(cpu_milli=cpu, mem=16 * GI, pods=110).obj()
        for i in range(n)
    ]


def mk_pods(p, prefix="p"):
    return [
        make_pod(f"{prefix}-{i}").req(cpu_milli=200, mem=128 * MI).obj()
        for i in range(p)
    ]


def test_wavefront_route_matches_oracle():
    """Batches over WAVEFRONT_MIN_PODS route to the wavefront solver and
    still place exactly like the reference-semantics oracle."""
    nodes = mk_nodes(16)
    pods = mk_pods(100)
    s = TPUBatchScheduler()
    for nd in nodes:
        s.add_node(nd)
    names = s.schedule_pending(pods)
    assert names == Oracle(nodes).schedule(pods)
    assert s.last_result.wave_count is not None
    assert int(s.last_result.wave_count) >= 1
    # the wavefront gate off must yield identical placements (scan route)
    s2 = TPUBatchScheduler(use_wavefront=False)
    for nd in nodes:
        s2.add_node(nd)
    assert s2.schedule_pending(pods) == names
    assert s2.last_result.wave_count is None


def test_small_batches_stay_on_scan():
    s = TPUBatchScheduler()
    for nd in mk_nodes(4):
        s.add_node(nd)
    s.schedule_pending(mk_pods(8))
    assert s.last_result.wave_count is None  # scan route, no wave pass


def test_device_solve_defers_and_coalesces_decode():
    s = TPUBatchScheduler()
    for nd in mk_nodes(8):
        s.add_node(nd)
    pods = mk_pods(80)
    ds = s.schedule_pending_async(pods)
    assert ds is not None
    time.sleep(0.02)  # host work the readback would overlap
    names = s.finalize_pending(pods, ds)
    assert sum(n is not None for n in names) == 80
    assert ds.deferred_s >= 0.02  # the decode really was deferred
    # reasons ride the same readback — no second transfer path
    assert ds.reasons() is not None
    assert len(ds.reasons()) == 80
    assert set(s.last_timings) >= {
        "encode_s", "compile_s", "solve_s", "decode_wait_s",
        "decode_overlap_s",
    }


def test_gang_retry_reuses_full_batch_bucket():
    """The gang admission retry's subset solves must encode into the full
    batch's pad bucket (one executable), not per-subset buckets."""
    nodes = [
        make_node("n0").capacity(cpu_milli=4000, mem=8 * GI, pods=110).obj()
    ]
    # three gangs of 3 x 1000m on a 4000m node: no two gangs fit, every
    # full solve releases everything -> the binary search runs
    pods = [
        make_pod(f"g{i}")
        .req(cpu_milli=1000, mem=256 * MI)
        .group(f"gang-{i // 3}")
        .obj()
        for i in range(9)
    ]
    s = TPUBatchScheduler()
    for nd in nodes:
        s.add_node(nd)
    seen_buckets = set()
    orig = s.builder.build_from_state

    def spy(state, pending, num_pods_hint=0, **kw):
        snap, meta = orig(state, pending, num_pods_hint=num_pods_hint, **kw)
        seen_buckets.add(snap.pods.valid.shape[0])
        return snap, meta

    s.builder.build_from_state = spy
    names = s.schedule_pending(pods)
    # one gang admitted whole
    placed = [i for i, n in enumerate(names) if n is not None]
    assert len(placed) == 3
    assert len(seen_buckets) == 1, seen_buckets  # one pad bucket only


def test_hot_loop_pipeline_end_to_end():
    """The deferred-readback hot loop: pods created through the store
    bind correctly, and the overlap metric records the hidden readback."""
    store = st.Store()
    sched = Scheduler(store, batch_size=256)
    for nd in mk_nodes(8):
        store.create(nd)
    sched.start()
    try:
        pods = mk_pods(80, prefix="loop")
        for p in pods:
            store.create(p)
        deadline = time.monotonic() + 60
        bound = 0
        while time.monotonic() < deadline:
            bound = sum(
                1
                for p in sched.informers.informer("Pod").list()
                if p.meta.name.startswith("loop-") and p.spec.node_name
            )
            if bound == 80:
                break
            time.sleep(0.05)
        assert bound == 80
        assert sched.flush_binds(timeout=10)
        assert sched.metrics.decode_overlap.n >= 1
        assert sched.metrics.batch_solve_duration.n >= 1
        # 80 pods routed wavefront -> wave metrics observed
        assert sched.metrics.solve_wave_count.n >= 1
    finally:
        sched.stop()


def test_prewarm_pool_compiles_neighbors():
    s = TPUBatchScheduler(prewarm=True)
    try:
        for nd in mk_nodes(8):
            s.add_node(nd)
        s.schedule_pending(mk_pods(80))
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and s.prewarm_pool.compiled < 2:
            time.sleep(0.2)
        # the adjacent pod buckets compiled off-thread, no errors
        assert s.prewarm_pool.compiled >= 2
        assert s.prewarm_pool.errors == 0
    finally:
        s.prewarm_pool.close()


def test_prewarm_pool_dedupes_and_drops_when_full():
    pool = SolverPrewarmPool(max_pending=1)
    ran = []
    try:
        assert pool.mark_seen(("k", 1)) is True
        assert pool.mark_seen(("k", 1)) is False  # dispatch-path dedupe
        assert pool.offer(("k", 1), "dup", lambda: ran.append(1)) is False
        assert pool.offer(("k", 2), "a", lambda: ran.append(2)) is True
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and pool.compiled < 1:
            time.sleep(0.05)
        assert pool.compiled == 1 and ran == [2]
    finally:
        pool.close()


def test_packed_device_put_scratch_reuse():
    """Consecutive same-layout encodes reuse the double-buffered staging
    scratch instead of allocating fresh buffers."""
    s = TPUBatchScheduler()
    for nd in mk_nodes(8):
        s.add_node(nd)
    pods = mk_pods(80)
    s.schedule_pending(pods)  # allocates buffer A
    s.schedule_pending(mk_pods(80, prefix="q"))  # allocates buffer B
    cache1 = {
        k: [id(b) for b in v["bufs"]] for k, v in s._unpack_cache.items()
    }
    s.schedule_pending(mk_pods(80, prefix="r"))  # reuses A
    names3 = s.schedule_pending(mk_pods(80, prefix="t"))  # reuses B
    cache2 = {
        k: [id(b) for b in v["bufs"]] for k, v in s._unpack_cache.items()
    }
    assert cache1.keys() == cache2.keys()
    for k in cache1:
        assert cache1[k] == cache2[k]  # same buffers, alternated in place
    # and the placements stay correct across reuse
    assert sum(n is not None for n in names3) == 80
