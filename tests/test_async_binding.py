"""The two-stage solve/bind pipeline: wave commits through one store
transaction, assume-cache bridging between overlapped batches, per-pod
failure splitting, and the queue's bounded batch-accumulation window.

Reference anchors: schedule_one.go:118 (async bindingCycle),
scheduling_queue.go:117 (event-driven batching).
"""

import threading
import time

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.admission import default_chain
from kubernetes_tpu.scheduler import Scheduler, SchedulingQueue
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _mk_scheduler(store, **kw):
    s = Scheduler(store, **kw)
    s.informers.informer("Node").start()
    s.informers.informer("Pod").start()
    assert s.informers.wait_for_sync(10)
    return s


# -- store.update_wave: the transactional wave commit -----------------------


def test_update_wave_commits_all_and_streams_per_object_events():
    store = st.Store()
    for i in range(4):
        store.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    w = store.watch("Pod")

    def mutator(node):
        def mutate(pod):
            pod.spec.node_name = node
        return mutate

    applied, errors = store.update_wave(
        "Pod", [(f"p{i}", "default", mutator(f"n{i}")) for i in range(4)]
    )
    assert errors == {}
    assert applied == [f"default/p{i}" for i in range(4)]
    # every object got its own monotonic rv and its own watch event
    rvs = []
    for i in range(4):
        ev = w.get(timeout=2)
        assert ev.type == st.MODIFIED
        assert ev.obj.spec.node_name == f"n{i}"
        rvs.append(ev.rv)
    assert rvs == sorted(rvs) and len(set(rvs)) == 4
    assert store.get("Pod", "p2").spec.node_name == "n2"


def test_update_wave_splits_failures_per_object():
    store = st.Store()
    store.create(make_pod("ok").req(cpu_milli=100).obj())

    def set_node(pod):
        pod.spec.node_name = "n0"

    def boom(pod):
        raise RuntimeError("mutate failed")

    applied, errors = store.update_wave(
        "Pod",
        [
            ("ok", "default", set_node),
            ("missing", "default", set_node),
            ("ok2", "default", boom),
        ],
    )
    assert applied == ["default/ok"]
    assert isinstance(errors["default/missing"], st.NotFound)
    assert "default/ok2" in errors
    assert store.get("Pod", "ok").spec.node_name == "n0"


def test_update_wave_single_journal_append(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    store = st.Store(journal_path=path, shards=1)
    for i in range(3):
        store.create(make_pod(f"p{i}").req(cpu_milli=100).obj())

    flushes = {"n": 0}
    orig_flush = store._shards[0]._journal.flush

    def counting_flush():
        flushes["n"] += 1
        orig_flush()

    store._shards[0]._journal.flush = counting_flush

    def set_node(pod):
        pod.spec.node_name = "n0"

    store.update_wave(
        "Pod", [(f"p{i}", "default", set_node) for i in range(3)]
    )
    # one coalesced append: a single flush covers the whole wave
    assert flushes["n"] == 1
    # ... and the journal replays to the committed state
    store2 = st.Store(journal_path=path, shards=1)
    assert all(
        store2.get("Pod", f"p{i}").spec.node_name == "n0" for i in range(3)
    )


def test_concurrent_service_creates_get_unique_cluster_ips():
    """Admission (ClusterIP allocation) runs under the store lock, so the
    list-then-allocate sequence cannot race a concurrent create."""
    store = st.Store(admission=default_chain())
    errs = []

    def create(i):
        svc = api.Service(
            meta=api.ObjectMeta(name=f"svc-{i}", namespace="default"),
            spec=api.ServiceSpec(ports=[api.ServicePort(port=80)]),
        )
        try:
            store.create(svc)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [
        threading.Thread(target=create, args=(i,)) for i in range(32)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5)
    assert not errs
    services, _ = store.list("Service")
    ips = [s.spec.cluster_ip for s in services]
    assert len(ips) == 32 and len(set(ips)) == 32, ips


# -- the pipeline: assume bridges solve N+1 over commit N -------------------


def test_assumed_pods_gate_next_solve_before_commit_lands():
    """Batch N's placements must be visible to batch N+1's snapshot even
    while batch N's bind wave is still committing (the assume/bind split):
    a full node stays full, no pod is double-placed."""
    store = st.Store()
    store.create(
        make_node("only").capacity(cpu_milli=1000, mem=8 * GI, pods=10).obj()
    )
    store.create(make_pod("a").req(cpu_milli=1000).obj())
    sched = _mk_scheduler(store)

    # hold every wave commit until released, forcing the second solve to
    # run strictly before the first bind lands
    gate = threading.Event()
    orig = sched._commit_wave

    def gated(wave):
        gate.wait(10)
        orig(wave)

    sched._commit_wave = gated
    try:
        stats = sched.schedule_batch(timeout=2)
        assert stats["scheduled"] == 1
        assert not store.get("Pod", "a").spec.node_name  # not committed yet
        store.create(make_pod("b").req(cpu_milli=1000).obj())
        stats2 = sched.schedule_batch(timeout=2)
        assert stats2["unschedulable"] == 1  # assume blocked the double-place
        gate.set()
        assert sched.flush_binds(timeout=30)
        assert store.get("Pod", "a").spec.node_name == "only"
        assert not store.get("Pod", "b").spec.node_name
    finally:
        gate.set()
        sched.stop()


def test_commit_wave_failure_splits_to_individual_requeue():
    """One bad pod in a wave requeues alone; the rest of the wave binds."""
    store = st.Store()
    store.create(
        make_node("n0").capacity(cpu_milli=8000, mem=8 * GI, pods=20).obj()
    )
    for i in range(3):
        store.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    sched = _mk_scheduler(store)

    orig = store.update_wave
    injected = {"on": True}

    def flaky(kind, updates, **kw):
        if not injected["on"]:
            return orig(kind, updates, **kw)
        good = [u for u in updates if u[0] != "p1"]
        applied, errors = orig(kind, good, **kw)
        errors["default/p1"] = st.NotFound("injected bind failure")
        return applied, errors

    store.update_wave = flaky
    try:
        stats = sched.schedule_batch(timeout=2)
        assert stats["scheduled"] == 3
        assert sched.flush_binds(timeout=30)
        assert store.get("Pod", "p0").spec.node_name == "n0"
        assert store.get("Pod", "p2").spec.node_name == "n0"
        assert not store.get("Pod", "p1").spec.node_name
        # the failed pod's assume was forgotten and it sits in backoff
        assert not sched.cache.is_assumed(store.get("Pod", "p1"))
        assert sched.queue.stats()["backoff"] == 1
        # after backoff it retries and binds
        injected["on"] = False
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            if store.get("Pod", "p1").spec.node_name:
                break
        assert store.get("Pod", "p1").spec.node_name == "n0"
    finally:
        sched.stop()


def test_pipeline_metrics_populated():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=8000, mem=8 * GI).obj())
    for i in range(4):
        store.create(make_pod(f"p{i}").req(cpu_milli=100).obj())
    sched = _mk_scheduler(store)
    try:
        sched.schedule_batch(timeout=2)
        assert sched.flush_binds(timeout=30)
        assert sched.metrics.schedule_batch_duration.n == 1
        assert sched.metrics.commit_wave_duration.n == 1
        assert sched.metrics.commit_wave_size.n == 1
        assert sched.metrics.commit_wave_size.total == 4.0
        assert sched.metrics.pipeline_overlap.n == 1
        assert sched.metrics.schedule_attempts.get("scheduled") == 4
    finally:
        sched.stop()


# -- the churn batch-accumulation window -----------------------------------


def test_batch_window_accumulates_churn_arrivals_fake_clock():
    now = [0.0]
    q = SchedulingQueue(clock=lambda: now[0], batch_window=0.05)
    q.add(make_pod("p0").req(cpu_milli=1).obj())
    out = {}

    def popper():
        out["batch"] = q.pop_batch(10, timeout=10)

    t = threading.Thread(target=popper)
    t.start()
    time.sleep(0.1)  # popper holds p0, parked inside the (frozen) window
    assert "batch" not in out
    q.add(make_pod("p1").req(cpu_milli=1).obj())  # arrives inside the window
    q.add(make_pod("p2").req(cpu_milli=1).obj())
    time.sleep(0.1)
    assert "batch" not in out  # clock frozen: window cannot expire
    now[0] = 0.2  # window expires; the popper's next wake returns
    t.join(5)
    assert not t.is_alive()
    names = sorted(i.pod.meta.name for i in out["batch"])
    assert names == ["p0", "p1", "p2"]


def test_batch_window_skipped_for_nonblocking_pop():
    """timeout=0 stays non-blocking: the window never exceeds timeout."""
    q = SchedulingQueue(batch_window=5.0)
    q.add(make_pod("p0").req(cpu_milli=1).obj())
    t0 = time.monotonic()
    batch = q.pop_batch(10, timeout=0)
    assert time.monotonic() - t0 < 1.0
    assert len(batch) == 1


def test_batch_window_returns_immediately_when_full():
    q = SchedulingQueue(batch_window=5.0)
    for i in range(3):
        q.add(make_pod(f"p{i}").req(cpu_milli=1).obj())
    t0 = time.monotonic()
    batch = q.pop_batch(3, timeout=10)
    assert time.monotonic() - t0 < 1.0  # max_n reached: no window wait
    assert len(batch) == 3


# -- throughput sampler: sub-window bursts ----------------------------------


def test_throughput_collector_samples_sub_interval_burst():
    """A burst that schedules entirely between two sampler ticks must
    still produce non-zero samples (the PreemptionBasic Average=0.0 bug)."""
    from kubernetes_tpu.perf.collectors import ThroughputCollector

    store = st.Store()
    pods = [make_pod(f"p{i}").req(cpu_milli=1).obj() for i in range(50)]
    for p in pods:
        store.create(p)
    col = ThroughputCollector(store, interval=0.05).start()
    time.sleep(0.12)  # a couple of idle ticks first
    for p in pods:  # the whole burst lands inside one interval
        cur = store.get("Pod", p.meta.name)
        cur.spec.node_name = "n0"
        store.update(cur, force=True)
    time.sleep(0.12)
    col.stop()
    items = col.collect()
    assert items, "burst produced no samples"
    assert items[0]["data"]["Average"] > 0.0
