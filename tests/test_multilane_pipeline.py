"""The pipelined multi-lane cycle (docs/scheduler_loop.md):

  * per-profile-class deficit round-robin in SchedulingQueue.pop_batch
    (one hot profile cannot starve another lane) + the `profiles` lane
    filter;
  * concurrent profile LANES — one pop→encode→solve pipeline per
    profile sharing one device through the DispatchArbiter;
  * SPECULATIVE solve overlap — batch N+1 dispatched over batch N's
    assumed placements while N's wave commits; a commit failure/fence
    invalidates the speculative batch and requeues exactly it;
  * STREAMED sub-wave commits — each store shard's slice of a wave
    hands to the commit pool as it stages, bound-exactly-once per
    sub-wave;
  * the DeviceClusterMirror speculation double-buffer (bookmark +
    rollback);
  * the scheduler_lanes / speculativeSolve / streamSubwaves knobs.
"""

import threading
import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.models.batch_scheduler import DispatchArbiter
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.config import (
    ProfileConfig,
    SchedulerConfiguration,
    load_config,
)
from kubernetes_tpu.scheduler.queue import SchedulingQueue
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


def _pod(name, cls=None, namespace="default", prio=0):
    p = make_pod(name, namespace=namespace).req(cpu_milli=50, mem=GI // 8)
    if prio:
        p = p.priority(prio)
    pod = p.obj()
    if cls is not None:
        pod.spec.scheduler_name = cls
    return pod


# -- pop_batch: per-profile fairness + lane filter ---------------------------


def test_pop_batch_round_robin_across_profile_classes():
    """A 10:1 arrival skew between two profile classes must not let the
    hot class fill the whole batch: the deficit round-robin serves one
    pod per class per rotation, so the cold class's pods ride every
    batch and both classes drain."""
    q = SchedulingQueue()
    for i in range(20):
        q.add(_pod(f"hot-{i}"))
    for i in range(2):
        q.add(_pod(f"cold-{i}", cls="batch-scheduler"))
    batch = q.pop_batch(10, timeout=0)
    assert len(batch) == 10
    cold = [i for i in batch if i.pod.spec.scheduler_name == "batch-scheduler"]
    # both cold pods made the first batch despite the 10:1 skew
    assert len(cold) == 2
    # everything drains across subsequent pops
    seen = {i.pod.meta.name for i in batch}
    while True:
        more = q.pop_batch(10, timeout=0)
        if not more:
            break
        seen |= {i.pod.meta.name for i in more}
    assert len(seen) == 22


def test_pop_batch_single_class_keeps_queuesort_order():
    """One class (the default profile) must pop in exactly the old
    global queuesort order: priority desc, then arrival."""
    q = SchedulingQueue()
    q.add(_pod("low-a", prio=1))
    q.add(_pod("high", prio=9))
    q.add(_pod("low-b", prio=1))
    batch = q.pop_batch(3, timeout=0)
    assert [i.pod.meta.name for i in batch] == ["high", "low-a", "low-b"]


def test_pop_batch_profiles_filter_pops_only_that_lane():
    q = SchedulingQueue()
    q.add(_pod("a0"))
    q.add(_pod("b0", cls="batch-scheduler"))
    q.add(_pod("b1", cls="batch-scheduler"))
    lane_b = q.pop_batch(10, timeout=0, profiles={"batch-scheduler"})
    assert sorted(i.pod.meta.name for i in lane_b) == ["b0", "b1"]
    # the other class is untouched and pops for its own lane
    lane_a = q.pop_batch(10, timeout=0, profiles={"default-scheduler"})
    assert [i.pod.meta.name for i in lane_a] == ["a0"]
    # an empty lane pops nothing even though pods exist elsewhere
    assert q.pop_batch(10, timeout=0, profiles={"ghost"}) == []


# -- concurrent profile lanes ------------------------------------------------


def _two_profile_config(**kw):
    return SchedulerConfiguration(
        profiles=[
            ProfileConfig(),
            ProfileConfig(scheduler_name="batch-scheduler"),
        ],
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.4,
        batch_window_seconds=0.01,
        **kw,
    )


def test_two_profile_lanes_schedule_both_classes():
    """Two profiles run as two concurrent lanes (scheduler_lanes=0 auto)
    sharing one device through the dispatch arbiter; both pod classes
    place, nothing double-binds."""
    store = st.Store()
    sched = Scheduler(store, config=_two_profile_config())
    assert len(sched._lane_profiles) == 2
    assert sched.metrics.lane_count.total == 2.0
    assert sched.profiles.arbiter is not None
    for i in range(3):
        store.create(
            make_node(f"n{i}").capacity(
                cpu_milli=8000, mem=16 * GI, pods=110
            ).obj()
        )
    try:
        sched.start()
        for i in range(12):
            store.create(_pod(f"d-{i}"))
            store.create(_pod(f"b-{i}", cls="batch-scheduler"))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            pods, _ = store.list("Pod")
            if pods and all(p.spec.node_name for p in pods):
                break
            time.sleep(0.05)
        pods, _ = store.list("Pod")
        unbound = [p.meta.name for p in pods if not p.spec.node_name]
        assert not unbound, f"unbound after both lanes ran: {unbound}"
        assert sched.flush_binds(10)
    finally:
        sched.stop()


def test_scheduler_lanes_knob_pins_serial_loop():
    """scheduler_lanes=1 keeps the serial single-thread loop even with
    two profiles (the rollback knob)."""
    store = st.Store()
    sched = Scheduler(store, config=_two_profile_config(scheduler_lanes=1))
    assert len(sched._lane_profiles) == 1
    assert sched._lane_profiles[0] is None  # one lane pops every class
    assert sched.metrics.lane_count.total == 1.0


# -- speculative solve overlap ----------------------------------------------


def _lone_node_scheduler(store, **cfg_kw):
    cfg = SchedulerConfiguration(
        pod_initial_backoff_seconds=0.02,
        pod_max_backoff_seconds=0.1,
        batch_window_seconds=0.0,
        adaptive_batch_window=False,
        **cfg_kw,
    )
    sched = Scheduler(store, config=cfg)
    node = make_node("n1").capacity(
        cpu_milli=64000, mem=64 * GI, pods=110
    ).obj()
    store.create(node)
    sched.cache.add_node(node)
    return sched


def test_speculative_batch_invalidated_by_commit_failure():
    """A batch dispatched while a wave is in flight records the
    wave-failure generation; a commit failure before its harvest must
    requeue EXACTLY that batch (no staging, no assumes) and count one
    mis-speculation — then the requeued pods place on a later healthy
    cycle."""
    store = st.Store()
    sched = _lone_node_scheduler(store)
    try:
        for i in range(2):
            pod = _pod(f"p{i}")
            store.create(pod)
            sched.queue.add(pod)
        sched._waves_in_flight = lambda: True  # a wave is "committing"
        batch = sched.queue.pop_batch(4, timeout=0)
        assert len(batch) == 2
        cycle = sched._dispatch_batch(batch)
        assert cycle.spec_token is not None
        assert sched.metrics.speculative_solves_total.total == 1.0
        # the wave it speculated over fails before the harvest
        sched._note_commit_failure()
        stats = sched._finish_cycle(cycle)
        assert stats["scheduled"] == 0
        assert sched.metrics.misspeculation_total.total == 1.0
        assert sched.cache.assumed_count() == 0  # nothing was assumed
        tiers = sched.queue.stats()
        assert tiers["backoff"] == 2 and tiers["inflight"] == 0
        # healthy retry: speculation holds, the pods place
        time.sleep(0.15)
        sched._waves_in_flight = lambda: False
        stats = sched.schedule_batch(timeout=0)
        assert stats["scheduled"] == 2
        assert sched.flush_binds(10)
        pods, _ = store.list("Pod")
        assert all(p.spec.node_name == "n1" for p in pods)
    finally:
        sched.stop()


def test_speculation_holds_on_healthy_commits():
    """No commit failure => the speculative batch stages normally
    (zero mis-speculations); placements match the serial path."""
    store = st.Store()
    sched = _lone_node_scheduler(store)
    try:
        for i in range(2):
            pod = _pod(f"p{i}")
            store.create(pod)
            sched.queue.add(pod)
        sched._waves_in_flight = lambda: True
        batch = sched.queue.pop_batch(4, timeout=0)
        cycle = sched._dispatch_batch(batch)
        assert cycle.spec_token is not None
        stats = sched._finish_cycle(cycle)
        assert stats["scheduled"] == 2
        assert sched.metrics.misspeculation_total.total == 0.0
        assert sched.flush_binds(10)
    finally:
        sched.stop()


def test_speculative_solve_gate_off_serializes():
    """speculative_solve=false: batches only dispatch over drained
    waves — the speculative counter never moves."""
    store = st.Store()
    sched = _lone_node_scheduler(store, speculative_solve=False)
    try:
        assert not sched._speculation_enabled
        for i in range(4):
            pod = _pod(f"p{i}")
            store.create(pod)
            sched.queue.add(pod)
        stats = sched.schedule_batch(timeout=0)
        assert stats["scheduled"] == 4
        assert sched.metrics.speculative_solves_total.total == 0.0
        assert sched.flush_binds(10)
    finally:
        sched.stop()


# -- streamed sub-wave commits ----------------------------------------------


def test_streamed_subwaves_commit_per_shard():
    """A wave spanning namespaces on different store shards streams one
    sub-wave per shard to the commit pool as it stages; every pod binds
    exactly once and the stream-lead histogram records the hand-offs."""
    store = st.Store()  # default 4 shards -> commit pool exists
    sched = _lone_node_scheduler(store)
    assert sched._stream_enabled
    namespaces = [f"ns-{i}" for i in range(6)]
    shards = {store.shard_index("Pod", ns) for ns in namespaces}
    assert len(shards) > 1  # the wave genuinely spans shards
    try:
        for i, ns in enumerate(namespaces):
            pod = _pod(f"p{i}", namespace=ns)
            store.create(pod)
            sched.queue.add(pod)
        stats = sched.schedule_batch(timeout=0)
        assert stats["scheduled"] == 6
        assert sched.flush_binds(10)
        pods, _ = store.list("Pod")
        assert all(p.spec.node_name == "n1" for p in pods)
        assert sched.metrics.subwave_stream_lead_ms.n >= len(shards)
    finally:
        sched.stop()


def test_streamed_subwave_fault_requeues_only_its_pods():
    """A fail-grade fault at the streamed hand-off requeues that
    sub-wave's pods with backoff; they bind on a later cycle — no pod
    lost, bound exactly once."""
    store = st.Store()
    sched = _lone_node_scheduler(store)
    namespaces = [f"ns-{i}" for i in range(6)]
    reg = faults.FaultRegistry(seed=1)
    reg.fail("binder.stream_subwave", n=1)
    try:
        for i, ns in enumerate(namespaces):
            pod = _pod(f"p{i}", namespace=ns)
            store.create(pod)
            sched.queue.add(pod)
        with faults.armed(reg):
            sched.schedule_batch(timeout=0)
            assert sched.flush_binds(10)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                pods, _ = store.list("Pod")
                if all(p.spec.node_name for p in pods):
                    break
                sched.schedule_batch(timeout=0.05)
                sched.flush_binds(5)
        pods, _ = store.list("Pod")
        unbound = [p.meta.name for p in pods if not p.spec.node_name]
        assert not unbound, f"streamed fault lost pods: {unbound}"
        assert reg.fired.get("binder.stream_subwave") == 1
        assert sched.cache.assumed_count() == 0 or sched.flush_binds(5)
    finally:
        faults.disarm()
        sched.stop()


def test_stream_subwaves_gate_off_keeps_whole_wave_path():
    store = st.Store()
    sched = _lone_node_scheduler(store, stream_subwaves=False)
    try:
        assert not sched._stream_enabled
        for i in range(3):
            pod = _pod(f"p{i}", namespace=f"ns-{i}")
            store.create(pod)
            sched.queue.add(pod)
        stats = sched.schedule_batch(timeout=0)
        assert stats["scheduled"] == 3
        assert sched.flush_binds(10)
        assert sched.metrics.subwave_stream_lead_ms.n == 0
    finally:
        sched.stop()


def test_pipelined_placements_parity_with_serial_path():
    """Acceptance pin: with healthy commits, the pipelined loop
    (speculation + streaming on) places a pinned workload IDENTICALLY
    to the fully-serialized path (speculative_solve=false,
    stream_subwaves=false) — batch composition held fixed."""

    def run(speculative, streaming):
        store = st.Store()
        for i in range(4):
            store.create(
                make_node(f"n{i}").capacity(
                    cpu_milli=4000, mem=8 * GI, pods=32
                ).obj()
            )
        cfg = SchedulerConfiguration(
            speculative_solve=speculative,
            stream_subwaves=streaming,
            batch_window_seconds=0.0,
            adaptive_batch_window=False,
        )
        sched = Scheduler(store, config=cfg)
        for i in range(4):
            sched.cache.add_node(store.get("Node", f"n{i}", namespace=""))
        try:
            # three fixed batches so later solves see earlier assumes
            for lo in (0, 8, 16):
                for i in range(lo, lo + 8):
                    pod = _pod(f"p{i:02d}", namespace=f"ns-{i % 3}")
                    store.create(pod)
                    sched.queue.add(pod)
                sched.schedule_batch(timeout=0)
            assert sched.flush_binds(10)
            pods, _ = store.list("Pod")
            return {p.meta.name: p.spec.node_name for p in pods}
        finally:
            sched.stop()

    pipelined = run(speculative=True, streaming=True)
    serial = run(speculative=False, streaming=False)
    assert pipelined == serial
    assert all(pipelined.values())


# -- dispatch arbiter --------------------------------------------------------


def test_dispatch_arbiter_bounds_inflight_and_fifo_releases():
    arb = DispatchArbiter(depth=1, timeout=5.0)
    assert arb.acquire()
    got = []

    def second():
        got.append(arb.acquire())

    t = threading.Thread(target=second)
    t.start()
    time.sleep(0.1)
    assert not got  # blocked behind the held slot
    arb.release()
    t.join(timeout=5)
    assert got == [True]
    arb.release()
    assert arb.inflight() == 0


def test_dispatch_arbiter_timeout_is_a_safety_valve():
    arb = DispatchArbiter(depth=1, timeout=0.05)
    assert arb.acquire()
    assert arb.acquire() is False  # forced through after the deadline
    assert arb.forced == 1
    arb.release()
    arb.release()
    assert arb.inflight() == 0


# -- mirror speculation double-buffer ----------------------------------------


def test_mirror_speculation_rollback_resyncs_cleanly():
    """rollback(speculation_point()) restores the pre-speculation
    resident buffer; the next sync re-scatters every row dirtied since
    the bookmark, converging on exactly the live state."""
    import numpy as np

    from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler

    tpu = TPUBatchScheduler()
    for i in range(4):
        tpu.add_node(
            make_node(f"n{i}").capacity(
                cpu_milli=4000, mem=8 * GI, pods=110
            ).obj()
        )
    mirror = tpu._mirror
    mirror.sync()
    point = mirror.speculation_point()
    # speculative delta: a pod assumed on n1 dirties its usage row
    pod = _pod("spec-pod")
    tpu.assume(pod, "n1")
    dev_spec = mirror.sync()
    assert dev_spec is not mirror.speculation_point()[0] or True
    # invalidation: drop the speculative chain, then mutate further
    mirror.rollback(point)
    tpu.forget(pod)
    tpu.assume(_pod("other-pod"), "n2")
    dev = mirror.sync()
    want = tpu.state.tensors()
    for field in want._fields:
        got = np.asarray(getattr(dev, field))
        exp = np.asarray(getattr(want, field))
        assert np.array_equal(got, exp), f"mirror diverged on {field}"


# -- config knobs ------------------------------------------------------------


def test_multilane_yaml_knobs_load_and_validate():
    cfg = load_config(
        {
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
            "schedulerLanes": 2,
            "speculativeSolve": False,
            "streamSubwaves": False,
        }
    )
    assert cfg.scheduler_lanes == 2
    assert cfg.speculative_solve is False
    assert cfg.stream_subwaves is False
    with pytest.raises(ValueError):
        SchedulerConfiguration(scheduler_lanes=-1).validate()
