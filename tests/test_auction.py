"""Joint (auction) solve: parity with greedy where semantics coincide,
capacity safety under contention, gang all-or-nothing, priority order."""

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import assign, auction, schema
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def test_no_contention_matches_greedy():
    """Each pod's best node is unique (distinct required zones), so the
    joint round-1 bids equal the sequential greedy picks."""
    nodes = [
        make_node(f"n{i}")
        .capacity(cpu_milli=8000, mem=16 * GI, pods=10)
        .zone(f"z{i}")
        .obj()
        for i in range(8)
    ]
    pods = [
        make_pod(f"p{i}")
        .req(cpu_milli=1000, mem=GI)
        .node_selector_kv(api.LABEL_ZONE, f"z{i}")
        .obj()
        for i in range(8)
    ]
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    a = np.asarray(auction.auction_assign(snap).assignment)[:8]
    g = np.asarray(assign.greedy_assign(snap).assignment)[:8]
    np.testing.assert_array_equal(a, g)


def test_capacity_never_oversubscribed(rng):
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=5).obj()
        for i in range(8)
    ]
    pods = [
        make_pod(f"p{i}")
        .req(cpu_milli=int(rng.choice([500, 1000, 2000, 3000])), mem=GI)
        .obj()
        for i in range(40)
    ]
    snap, meta = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[:40]
    req = np.asarray(snap.pods.req)[:40]
    alloc = np.asarray(snap.cluster.allocatable)
    used = np.zeros_like(alloc)
    np.add.at(used, a[a >= 0], req[a >= 0])
    assert (used <= alloc + 1e-5).all()
    # cluster usage in the result matches the committed assignments
    np.testing.assert_allclose(
        np.asarray(r.cluster.requested), used, atol=1e-5
    )


def test_unschedulable_stays_unplaced():
    nodes = [make_node("n0").capacity(cpu_milli=1000, mem=GI, pods=5).obj()]
    pods = [make_pod("big").req(cpu_milli=64000).obj()]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap)
    assert int(r.assignment[0]) == -1


def test_gang_all_or_nothing():
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=110).obj()
        for i in range(2)
    ]
    # total cpu 8000: g1 needs 6000, g2 needs 4000 — both can't fit.
    pods = (
        [make_pod(f"g1-{i}").req(cpu_milli=2000).group("g1").obj() for i in range(3)]
        + [make_pod(f"g2-{i}").req(cpu_milli=1000).group("g2").obj() for i in range(4)]
    )
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap, n_groups=auction.num_groups(snap))
    a = np.asarray(r.assignment)[:7]
    for arr in (a[:3], a[3:]):
        assert (arr >= 0).all() or (arr < 0).all(), f"gang split: {a}"
    # the dropped gang's resources were released
    req = np.asarray(snap.pods.req)[:7]
    used = np.zeros_like(np.asarray(r.cluster.requested))
    np.add.at(used, a[a >= 0], req[a >= 0])
    np.testing.assert_allclose(np.asarray(r.cluster.requested), used, atol=1e-5)


def test_priority_wins_contended_slot():
    nodes = [make_node("only").capacity(cpu_milli=1000, mem=8 * GI, pods=110).obj()]
    pods = [
        make_pod("low").req(cpu_milli=1000).priority(1).obj(),
        make_pod("high").req(cpu_milli=1000).priority(10).obj(),
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    for result in (auction.auction_assign(snap), assign.greedy_assign(snap)):
        a = np.asarray(result.assignment)[:2]
        assert a[1] == 0 and a[0] == -1, a


def test_routes_unsupported_families_to_greedy():
    """Affinity-DIRECTION inter-pod terms (co-location) stay greedy-only;
    spread and anti-affinity are auction-covered since round 3."""
    nodes = [make_node("n0").capacity(cpu_milli=8000, mem=8 * GI).zone("z").obj()]
    pods = [
        make_pod("p0")
        .label("app", "x")
        .pod_affinity({"app": "x"}, api.LABEL_ZONE)
        .obj()
    ]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    with pytest.raises(ValueError):
        auction.auction_assign(snap)


def test_contended_identical_pods_fill_cluster(rng):
    """Uniform cluster, identical pods: tie-hash diversification must
    spread bids so the burst converges in few rounds, all placed."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=8000, mem=16 * GI, pods=16).obj()
        for i in range(32)
    ]
    pods = [make_pod(f"p{i}").req(cpu_milli=500, mem=512 * MI).obj() for i in range(256)]
    snap, _ = schema.SnapshotBuilder().build(nodes, pods)
    r = auction.auction_assign(snap)
    a = np.asarray(r.assignment)[:256]
    assert (a >= 0).all()
    assert int(r.rounds) <= 12
