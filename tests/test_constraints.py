"""PodTopologySpread + InterPodAffinity kernel tests (parity vs oracle and
pinned semantic cases)."""

import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import assign, schema
from kubernetes_tpu.testing.oracle import Oracle
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def run_both(nodes, pods, bound=()):
    snap, meta = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    result = assign.greedy_assign(snap, topo_z=meta.topo_z)
    got = [meta.node_name(int(i)) for i in np.asarray(result.assignment)[: len(pods)]]
    want = Oracle(nodes, bound_pods=bound).schedule(pods)
    return got, want


def _zoned_nodes(n, zones=3):
    return [
        make_node(f"n{i}").capacity(cpu_milli=16000, mem=32 * GI, pods=110)
        .zone(f"z{i % zones}").obj()
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# PodTopologySpread
# ---------------------------------------------------------------------------


def test_hard_spread_by_zone():
    nodes = _zoned_nodes(6)
    pods = [
        make_pod(f"p{i}").labels(app="web").req(cpu_milli=100)
        .spread(max_skew=1, topology_key=api.LABEL_ZONE, selector={"app": "web"})
        .obj()
        for i in range(9)
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    # 9 pods over 3 zones with maxSkew 1 -> exactly 3 per zone
    zones = [int(g[1]) % 3 for g in got]
    assert sorted(np.bincount(zones, minlength=3).tolist()) == [3, 3, 3]


def test_hard_spread_blocks_when_skew_exceeded():
    nodes = [
        make_node("a").capacity(cpu_milli=16000, mem=32 * GI, pods=110).zone("z0").obj(),
        make_node("b").capacity(cpu_milli=50, mem=32 * GI, pods=110).zone("z1").obj(),
    ]
    # z1 can hold exactly one tiny pod.  p0->a, p1->b, p2->a (skew 1); p3
    # would need z0=3 vs min(z1)=1 -> skew 2 > maxSkew 1, and z1 is out of
    # cpu -> unschedulable from then on.
    pods = [
        make_pod(f"p{i}").labels(app="x").req(cpu_milli=50)
        .spread(max_skew=1, topology_key=api.LABEL_ZONE, selector={"app": "x"})
        .obj()
        for i in range(5)
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert got[3] is None and got[4] is None


def test_spread_requires_topology_key():
    nodes = [
        make_node("zoned").zone("z1").obj(),
        make_node("bare").obj(),  # no zone label
    ]
    pods = [
        make_pod("p").labels(app="x")
        .spread(max_skew=1, topology_key=api.LABEL_ZONE, selector={"app": "x"})
        .obj()
    ]
    got, want = run_both(nodes, pods)
    assert got == want == ["zoned"]


def test_soft_spread_prefers_low_count_zone():
    nodes = _zoned_nodes(4, zones=2)
    bound = [
        make_pod(f"b{i}").labels(app="w").node_name("n0").obj() for i in range(3)
    ]
    pods = [
        make_pod("p").labels(app="w").req(cpu_milli=100)
        .spread(
            max_skew=1,
            topology_key=api.LABEL_ZONE,
            when_unsatisfiable="ScheduleAnyway",
            selector={"app": "w"},
        )
        .obj()
    ]
    got, want = run_both(nodes, pods, bound=bound)
    assert got == want
    # z0 already has 3 matching pods -> z1 preferred
    assert int(got[0][1]) % 2 == 1


# ---------------------------------------------------------------------------
# InterPodAffinity
# ---------------------------------------------------------------------------


def test_required_anti_affinity_by_hostname():
    nodes = _zoned_nodes(3)
    pods = [
        make_pod(f"p{i}").labels(app="db").req(cpu_milli=100)
        .pod_anti_affinity({"app": "db"}, topology_key=api.LABEL_HOSTNAME)
        .obj()
        for i in range(4)
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert sorted(g for g in got[:3]) == ["n0", "n1", "n2"]
    assert got[3] is None  # no fourth distinct node


def test_required_affinity_colocates():
    nodes = _zoned_nodes(6)
    first = make_pod("lead").labels(app="grp").req(cpu_milli=100).obj()
    followers = [
        make_pod(f"f{i}").labels(app="grp").req(cpu_milli=100)
        .pod_affinity({"app": "grp"}, topology_key=api.LABEL_ZONE)
        .obj()
        for i in range(3)
    ]
    got, want = run_both(nodes, [first] + followers)
    assert got == want
    lead_zone = int(got[0][1]) % 3
    assert all(int(g[1]) % 3 == lead_zone for g in got[1:])


def test_first_pod_self_match_escape():
    """A pod whose affinity matches itself may schedule when nothing in the
    cluster matches yet (filtering.go:352-360)."""
    nodes = _zoned_nodes(3)
    pods = [
        make_pod("solo").labels(app="self").req(cpu_milli=100)
        .pod_affinity({"app": "self"}, topology_key=api.LABEL_ZONE)
        .obj()
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert got[0] is not None


def test_first_pod_no_self_match_stays_pending():
    nodes = _zoned_nodes(3)
    pods = [
        make_pod("orphan").labels(app="other").req(cpu_milli=100)
        .pod_affinity({"app": "missing"}, topology_key=api.LABEL_ZONE)
        .obj()
    ]
    got, want = run_both(nodes, pods)
    assert got == want == [None]


def test_existing_pods_anti_affinity_blocks_incoming():
    nodes = _zoned_nodes(2, zones=2)
    bound = [
        make_pod("guard").labels(app="guard")
        .pod_anti_affinity({"app": "noisy"}, topology_key=api.LABEL_ZONE)
        .node_name("n0")
        .obj()
    ]
    pods = [make_pod("noisy-1").labels(app="noisy").req(cpu_milli=100).obj()]
    got, want = run_both(nodes, pods, bound=bound)
    assert got == want
    # n0 is in z0 where the guard's anti-affinity applies -> must land z1
    assert got[0] == "n1"


def test_batch_pod_anti_affinity_carries_forward():
    """Anti-affinity of a pod placed earlier in the batch must constrain
    later pods in the same solve (the in-scan counts_owner update)."""
    nodes = _zoned_nodes(2, zones=2)
    pods = [
        make_pod("guard").labels(app="guard").req(cpu_milli=100)
        .pod_anti_affinity({"app": "noisy"}, topology_key=api.LABEL_ZONE)
        .obj(),
        make_pod("noisy-1").labels(app="noisy").req(cpu_milli=100).obj(),
    ]
    got, want = run_both(nodes, pods)
    assert got == want
    assert got[0] is not None and got[1] is not None
    assert int(got[0][1]) % 2 != int(got[1][1]) % 2  # different zones


# ---------------------------------------------------------------------------
# Randomized parity with everything on
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(3))
def test_randomized_parity_with_constraints(seed):
    rng = np.random.default_rng(seed + 100)
    nodes = []
    for i in range(10):
        nw = make_node(f"n{i}").capacity(
            cpu_milli=int(rng.choice([4000, 8000])), mem=16 * GI, pods=20
        ).zone(f"z{i % 3}")
        nodes.append(nw.obj())
    apps = ["a", "b", "c"]
    pods = []
    for i in range(30):
        app = str(rng.choice(apps))
        pw = make_pod(f"p{i}").labels(app=app).req(
            cpu_milli=int(rng.choice([100, 500, 1000]))
        )
        r = rng.random()
        if r < 0.25:
            pw.spread(
                max_skew=int(rng.choice([1, 2])),
                topology_key=api.LABEL_ZONE,
                when_unsatisfiable=str(
                    rng.choice(["DoNotSchedule", "ScheduleAnyway"])
                ),
                selector={"app": app},
            )
        elif r < 0.45:
            pw.pod_anti_affinity({"app": app}, topology_key=str(
                rng.choice([api.LABEL_HOSTNAME, api.LABEL_ZONE])
            ))
        elif r < 0.6:
            pw.pod_affinity({"app": app}, topology_key=api.LABEL_ZONE)
        pods.append(pw.obj())
    got, want = run_both(nodes, pods)
    assert got == want
