"""Overload protection: backpressured watch fan-out, per-watcher
coalescing, Expired-instead-of-terminate, reflector relist backoff +
storm gating, and the adaptive batch window / overload controller.

The chaos-grade randomized versions (slow-consumer and relist-storm
seeds) live in tests/test_chaos.py; this file is the fast tier-1
regression surface for the same contracts.
"""

import threading
import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.client.informers import InformerFactory, RelistGate
from kubernetes_tpu.scheduler.queue import AdaptiveBatchWindow, SchedulingQueue
from kubernetes_tpu.scheduler.scheduler import OverloadController
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import GI, make_node, make_pod


@pytest.fixture(autouse=True)
def _disarmed():
    yield
    faults.disarm()


def _wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return False


def _settled(store, w):
    """True once the fan-out thread delivered every committed event into
    the watcher's buffer (its dedup horizon reached the store rv).
    The rv is read BEFORE taking the watch mutex: the store's lock
    order is publish-lock -> Watch._mu, never the reverse."""
    rv = store.resource_version
    with w._mu:
        return w._last_rv >= rv


# -- per-watcher coalescing --------------------------------------------------


def test_modified_run_coalesces_to_latest_with_monotonic_rv():
    """A MODIFIED run on one key compacts latest-wins: the un-drained
    consumer receives exactly ONE event carrying the newest object and
    the final rv — never an intermediate revision."""
    store = st.Store()
    w = store.watch("Pod")
    pod = store.create(make_pod("a").obj())
    for i in range(10):
        pod.meta.labels["v"] = str(i)
        pod = store.update(pod)
    assert _wait_for(lambda: _settled(store, w))
    ev = w.get(timeout=2)
    assert ev is not None
    # the consumer never saw the create, so the compacted event is
    # still the ADDED — with the latest object and the final rv
    assert ev.type == st.ADDED
    assert ev.obj.meta.labels["v"] == "9"
    assert ev.rv == store.resource_version
    assert w.get(timeout=0.05) is None  # exactly one event
    assert store.watch_stats()["watch_coalesced_total"] >= 10
    w.stop()


def test_added_deleted_annihilation():
    """An object created AND deleted while the consumer lagged is never
    delivered at all — the pending pair annihilates."""
    store = st.Store()
    w = store.watch("Pod")
    store.create(make_pod("ghost").obj())
    store.delete("Pod", "ghost")
    assert _wait_for(lambda: _settled(store, w))
    assert w.get(timeout=0.05) is None
    assert not w.expired and not w.stopped
    w.stop()


def test_delete_recreate_coalesces_to_modified():
    """DELETED followed by a recreate compacts to MODIFIED with the new
    object: cache-diffing consumers converge on the recreated state."""
    store = st.Store()
    w = store.watch("Pod")
    store.create(make_pod("a").label("gen", "1").obj())
    assert _wait_for(lambda: _settled(store, w))
    assert w.get(timeout=2).type == st.ADDED  # consume the create
    store.delete("Pod", "a")
    store.create(make_pod("a").label("gen", "2").obj())
    assert _wait_for(lambda: _settled(store, w))
    ev = w.get(timeout=2)
    assert ev.type == st.MODIFIED
    assert ev.obj.meta.labels["gen"] == "2"
    assert w.get(timeout=0.05) is None
    w.stop()


def test_delivery_rv_monotonic_through_compaction():
    """Compaction re-sorts updated keys to the back, so the delivered
    stream stays strictly rv-monotonic across interleaved keys."""
    store = st.Store()
    w = store.watch("Pod")
    pods = [store.create(make_pod(f"p{i}").obj()) for i in range(6)]
    for k in range(3):
        for i in (0, 3, 5):
            pods[i].meta.labels["k"] = str(k)
            pods[i] = store.update(pods[i])
    assert _wait_for(lambda: _settled(store, w))
    last = 0
    while True:
        ev = w.get(timeout=0.1)
        if ev is None:
            break
        assert ev.rv > last
        last = ev.rv
    w.stop()


def test_slow_consumer_is_backpressured_not_terminated():
    """A consumer that drains slowly while a writer churns one hot key
    sees coalesced snapshots and is NEVER terminated — the write path
    also never blocks on it (fan-out runs off the store lock)."""
    store = st.Store(watch_capacity=8)
    w = store.watch("Pod")
    pod = store.create(make_pod("hot").obj())
    stop = threading.Event()

    def churn():
        p = pod
        while not stop.is_set():
            p.meta.labels["t"] = str(time.monotonic())
            p = store.update(p, force=True)

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    last = 0
    for _ in range(20):  # slow consumer: 2ms per event
        ev = w.get(timeout=2)
        assert ev is not None
        assert ev.rv > last
        last = ev.rv
        time.sleep(0.002)
    stop.set()
    t.join(timeout=2)
    assert not w.expired
    assert store.watchers_terminated == 0
    w.stop()


def test_informer_resynthesizes_delete_recreate_split():
    """A delete + recreate the watch buffer compacted into one MODIFIED
    must reach informer handlers as DELETED(old) then ADDED(new): uid-
    sensitive consumers (PV controller's claimRef.UID check, scheduler
    cache accounting) depend on seeing the true transition."""
    store = st.Store()
    factory = InformerFactory(store)
    inf = factory.informer("Pod")
    events = []
    inf.add_handler(
        lambda t, o, old: events.append((t, o.meta.uid))
    )
    inf.start()
    assert inf.wait_for_sync(5)
    first = store.create(make_pod("a").obj())
    assert _wait_for(lambda: len(events) >= 1)
    # stall the informer consumer so the DELETE + recreate compact into
    # one MODIFIED event in its watch buffer
    reg = faults.FaultRegistry().delay("watch.consume", seconds=0.3, n=1)
    with faults.armed(reg):
        store.delete("Pod", "a")
        second = store.create(make_pod("a").obj())
        assert _wait_for(lambda: len(events) >= 3, timeout=10)
    assert events[0] == (st.ADDED, first.meta.uid)
    assert (st.DELETED, first.meta.uid) in events
    assert (st.ADDED, second.meta.uid) in events
    factory.stop()


# -- Expired semantics + reflector recovery ----------------------------------


def test_overflow_expiry_bookmarks_and_informer_recovers():
    """An informer whose watch the store expires relists (the 410 path)
    and converges on the store's state — nothing lost, nothing dup'd."""
    store = st.Store(watch_capacity=4)
    factory = InformerFactory(store)
    inf = factory.informer("Pod")
    inf.start()
    assert inf.wait_for_sync(5)
    # stall the informer's consumer thread with injected consume latency
    # while more distinct keys than the capacity commit
    reg = faults.FaultRegistry().delay("watch.consume", seconds=0.3, n=2)
    with faults.armed(reg):
        for i in range(12):
            store.create(make_pod(f"p{i}").obj())
        assert _wait_for(
            lambda: store.watch_stats()["watch_expired_total"] >= 1, timeout=10
        )
    assert store.watchers_terminated == 0
    # bounded staleness: the relist converges the cache on the store
    assert _wait_for(lambda: len(inf.list()) == 12, timeout=10)
    factory.stop()


def test_simultaneous_expiries_relist_through_bounded_gate():
    """N informers expiring together must not synchronously hammer
    Store.list: concurrent relists are capped by the factory's shared
    RelistGate and the jittered backoff spreads the retries."""
    kinds = [
        "Pod", "Node", "PersistentVolume", "PersistentVolumeClaim",
        "StorageClass", "ResourceClaim",
    ]
    mk = {
        "Pod": lambda: make_pod("seed").obj(),
        "Node": lambda: make_node("seed").capacity(
            cpu_milli=1000, mem=GI
        ).obj(),
    }
    store = st.Store()
    concurrency = {"cur": 0, "max": 0}
    mu = threading.Lock()
    orig_list = store.list

    def slow_list(kind, *a, **kw):
        with mu:
            concurrency["cur"] += 1
            concurrency["max"] = max(concurrency["max"], concurrency["cur"])
        try:
            time.sleep(0.02)  # make overlap observable
            return orig_list(kind, *a, **kw)
        finally:
            with mu:
                concurrency["cur"] -= 1

    store.list = slow_list
    factory = InformerFactory(store)
    infs = [factory.informer(k) for k in kinds]
    factory.start()
    assert factory.wait_for_sync(10)
    concurrency["max"] = 0  # measure the storm, not the initial sync
    # one drop per kind's watcher: every informer expires at once
    reg = faults.FaultRegistry().drop("watch.offer", n=len(kinds))
    with faults.armed(reg):
        for kind in kinds:
            if kind in mk:
                store.create(mk[kind]())
            else:
                store._dispatch_wave(  # synthetic event: kind-only churn
                    kind, [st.Event(st.ADDED, kind, make_pod("x").obj(),
                                    store.resource_version + 1)],
                )
        assert _wait_for(
            lambda: store.watch_stats()["watch_expired_total"] >= len(kinds),
            timeout=10,
        )
        # every informer recovers (relist + rewatch)
        assert _wait_for(
            lambda: all(i.relists >= 2 for i in infs), timeout=10
        )
    assert concurrency["max"] <= factory.relist_gate.max_concurrent
    assert store.watchers_terminated == 0
    factory.stop()


# -- adaptive batch window + overload controller -----------------------------


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_adaptive_window_widens_under_churn_and_floors_when_idle():
    clk = _FakeClock()
    ctl = AdaptiveBatchWindow(
        base_window=0.05, min_window=0.005, max_window=0.25,
        slo_seconds=0.5, clock=clk,
    )
    assert ctl.window() == pytest.approx(0.05)  # no signal: base
    # sustained churn at ~1000 pods/s with ~1ms/pod pipeline cost
    for _ in range(40):
        ctl.note_arrival(250)
        clk.t += 0.25
    ctl.note_solve(1000, 0.5)
    ctl.note_commit(1000, 0.5)
    w = ctl.window()
    assert 0.1 <= w <= 0.25  # slo/(1+r*c) capped at max
    # idle decay: the rate EWMA falls and the window floors
    clk.t += 30.0
    assert ctl.window() == pytest.approx(0.005)


def test_adaptive_window_respects_slo_as_cost_grows():
    clk = _FakeClock()
    ctl = AdaptiveBatchWindow(
        min_window=0.005, max_window=0.5, slo_seconds=0.5, clock=clk
    )
    for _ in range(40):
        ctl.note_arrival(500)  # 2000 pods/s
        clk.t += 0.25
    for _ in range(20):
        ctl.note_solve(100, 0.2)   # 2ms/pod solve
        ctl.note_commit(100, 0.2)  # 2ms/pod commit
    w = ctl.window()
    # w* = 0.5 / (1 + 2000*0.004) = 0.5/9 — batches sized so processing
    # still fits the SLO
    assert w == pytest.approx(0.5 / 9.0, rel=0.35)


def test_adaptive_window_pinned_wide_under_severe_overload():
    ctl = AdaptiveBatchWindow(max_window=0.25, clock=_FakeClock())
    ctl.set_overload(2)
    assert ctl.window() == 0.25
    ctl.set_overload(0)
    assert ctl.window() != 0.25 or ctl.window() == ctl.base


def test_queue_uses_window_controller_default():
    clk = _FakeClock()
    ctl = AdaptiveBatchWindow(base_window=0.0, clock=clk)
    q = SchedulingQueue(clock=clk, batch_window=99.0, window_ctl=ctl)
    q.add(make_pod("a").obj())
    # base window 0: pop returns immediately despite the fixed 99s
    batch = q.pop_batch(8, timeout=0.0)
    assert [i.pod.meta.name for i in batch] == ["a"]


def test_overload_controller_ladder_and_hysteresis():
    ctl = OverloadController(slo_seconds=0.1)
    assert ctl.note_cycle(0.01) == 0
    for _ in range(10):
        lvl = ctl.note_cycle(0.15)  # > slo: shed background
    assert lvl == 1
    for _ in range(10):
        lvl = ctl.note_cycle(0.5)   # > 2*slo: severe
    assert lvl == 2
    lvl = ctl.note_cycle(0.12)      # still above 80% of 2*slo? no — drops
    for _ in range(10):
        lvl = ctl.note_cycle(0.12)
    assert lvl == 1                 # between slo and 2*slo: overloaded
    for _ in range(20):
        lvl = ctl.note_cycle(0.01)
    assert lvl == 0                 # healthy again


# -- bookkeeping + registry surfaces -----------------------------------------


def test_terminated_kinds_is_bounded_counter_dict():
    store = st.Store()
    assert store.terminated_by_kind == {}
    assert not hasattr(store, "terminated_kinds")  # the unbounded list


def test_new_fault_points_registered():
    assert "watch.consume" in faults.KNOWN_POINTS
    assert "store.list" in faults.KNOWN_POINTS


# -- read-replica bounded staleness ------------------------------------------


def test_replica_bounded_staleness_contract():
    """The replica-set staleness contract: a list at rv R from ANY
    replica followed by watch?from_rv=R against any OTHER replica —
    including a freshly restarted one — replays exactly the events
    committed after R (the shared event ring), converging on exact
    leader state; an rv that fell out of the ring still answers 410 so
    the client relists (the single-server Expired semantics,
    unchanged)."""
    from kubernetes_tpu.api.server import APIServerReplicaSet
    from kubernetes_tpu.client.rest import RestClient

    store = st.Store(buffer_size=64)
    plane = APIServerReplicaSet(store, replicas=2)
    try:
        a, b = (RestClient(u) for u in plane.urls())
        for i in range(5):
            a.create(make_pod(f"pre-{i}").obj())
        # list from the OTHER replica: rv R is a consistent cut
        items, rv = b.list("Pod")
        assert len(items) == 5
        # leader state advances past R through replica a
        for i in range(5, 10):
            a.create(make_pod(f"pre-{i}").obj())
        # watch?from_rv=R on replica b replays exactly the gap
        gen = b.watch("Pod", from_rv=rv)
        seen = {}
        for typ, obj, erv in gen:
            assert typ == "ADDED"
            seen[obj.meta.name] = erv
            if len(seen) == 5:
                break
        gen.close()
        assert set(seen) == {f"pre-{i}" for i in range(5, 10)}
        assert all(erv > rv for erv in seen.values())
        # a replica killed and RESTARTED serves the same contract: the
        # fresh instance shares the store, so the old rv still replays
        plane.kill(1)
        plane.restart(1)
        c = RestClient(plane.urls()[1])
        items2, rv2 = c.list("Pod")
        assert {o.meta.name for o in items2} == {
            f"pre-{i}" for i in range(10)
        }
        assert rv2 >= max(seen.values())
        gen2 = c.watch("Pod", from_rv=rv)
        names = set()
        for typ, obj, erv in gen2:
            names.add(obj.meta.name)
            if len(names) == 5:
                break
        gen2.close()
        assert names == {f"pre-{i}" for i in range(5, 10)}
        # relist-on-Expired preserved: age R out of the (small) ring
        for i in range(200):
            a.create(make_pod(f"age-{i}").obj())
        with pytest.raises(st.Expired):
            next(c.watch("Pod", from_rv=rv))
    finally:
        plane.stop()
