"""The dense-snapshot proto boundary (SURVEY §2.6 north-star shim).

VERDICT r4 #7 acceptance: a round-trip integration test scheduling 500
pods through the proto service — here twice: a second Python "process
role" over the real TCP transport, and a stock C++ client built from
protoc-generated code (the Go-stand-in; the image has no Go toolchain
or grpcio, so the framed-protobuf transport carries the contract).
"""

import shutil
import subprocess
import time

import numpy as np
import pytest

from kubernetes_tpu.extender.protoserver import (
    ProtoSchedulerServer,
    solve_over_socket,
)
from kubernetes_tpu.proto import snapshot_pb2 as pb

MI = 1 << 20


def _request(n_nodes=50, n_pods=500, used_cpu=0.0, gangs=0):
    req = pb.SolveRequest()
    req.cluster.resources.names.extend(["cpu", "memory", "pods"])
    req.cluster.allocatable.rows = n_nodes
    req.cluster.allocatable.cols = 3
    for i in range(n_nodes):
        req.cluster.node_names.append(f"node-{i}")
        req.cluster.allocatable.data.extend([32000.0, 64.0 * MI, 110.0])
    if used_cpu:
        req.cluster.requested.rows = n_nodes
        req.cluster.requested.cols = 3
        for i in range(n_nodes):
            req.cluster.requested.data.extend([used_cpu, 0.0, 1.0])
    req.pods.requests.rows = n_pods
    req.pods.requests.cols = 3
    for i in range(n_pods):
        req.pods.pod_names.append(f"pod-{i}")
        req.pods.requests.data.extend([500.0, 0.5 * MI, 1.0])
        if gangs:
            req.pods.group_ids.append(f"gang-{i % gangs}")
    return req


def test_python_round_trip_500_pods():
    srv = ProtoSchedulerServer().start()
    try:
        resp = solve_over_socket("127.0.0.1", srv.port, _request())
        assert len(resp.assignments) == 500
        placed = [a for a in resp.assignments if a.node_name]
        assert len(placed) == 500
        # node_index agrees with node_names order
        for a in placed:
            assert a.node_name == f"node-{a.node_index}"
        # spread across nodes within pod capacity
        per_node = {}
        for a in placed:
            per_node[a.node_name] = per_node.get(a.node_name, 0) + 1
        assert max(per_node.values()) <= 110
    finally:
        srv.stop()


def test_requested_rows_constrain_capacity():
    srv = ProtoSchedulerServer().start()
    try:
        # nodes 32 cores, 30 already used -> 2000m free -> 4 pods of
        # 500m per node; 50 nodes can hold only 200 of 500 pods
        resp = solve_over_socket(
            "127.0.0.1", srv.port, _request(used_cpu=30000.0)
        )
        placed = [a for a in resp.assignments if a.node_name]
        assert len(placed) == 200
        unplaced_reasons = {
            r for a, r in zip(resp.assignments, resp.reasons)
            if not a.node_name
        }
        assert unplaced_reasons  # rejection reasons reported
    finally:
        srv.stop()


def test_gang_groups_all_or_nothing():
    srv = ProtoSchedulerServer().start()
    try:
        # 10 gangs x 50 members; capacity for ~200 pods -> whole gangs
        # place or park, never fragments
        resp = solve_over_socket(
            "127.0.0.1", srv.port,
            _request(used_cpu=30000.0, gangs=10),
        )
        by_gang = {}
        for i, a in enumerate(resp.assignments):
            by_gang.setdefault(f"gang-{i % 10}", []).append(bool(a.node_name))
        for gang, placed in by_gang.items():
            assert all(placed) or not any(placed), gang
        assert any(all(p) for p in by_gang.values())
    finally:
        srv.stop()


@pytest.mark.skipif(
    shutil.which("protoc") is None or shutil.which("g++") is None,
    reason="native toolchain unavailable",
)
def test_cpp_client_drives_the_solver(tmp_path):
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    gen = tmp_path / "gen"
    gen.mkdir()
    subprocess.run(
        ["protoc", f"--cpp_out={gen}", "snapshot.proto"],
        cwd=os.path.join(repo, "kubernetes_tpu", "proto"),
        check=True,
    )
    exe = tmp_path / "proto_client"
    pkg = subprocess.run(
        ["pkg-config", "--cflags", "--libs", "protobuf"],
        capture_output=True, text=True,
    )
    flags = pkg.stdout.split() if pkg.returncode == 0 else ["-lprotobuf"]
    subprocess.run(
        ["g++", "-O2", "-o", str(exe),
         os.path.join(repo, "native", "proto_client.cpp"),
         str(gen / "snapshot.pb.cc"), f"-I{gen}"] + flags,
        check=True,
    )
    srv = ProtoSchedulerServer().start()
    try:
        out = subprocess.run(
            [str(exe), str(srv.port), "50", "500"],
            capture_output=True, text=True, timeout=300,
        )
        assert out.returncode == 0, out.stderr + out.stdout
        assert "placed 500/500" in out.stdout
    finally:
        srv.stop()
