"""Preemption: tensorized dry-run kernel, victim-choice oracle parity,
and the end-to-end PostFilter path (evict through the store, nominate,
reschedule).

Reference semantics: framework/preemption/preemption.go:150-316,
plugins/defaultpreemption/default_preemption.go; policy divergences are
documented in ops/preemption.py and mirrored by testing/oracle.preempt.
"""

import time

import numpy as np
import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
from kubernetes_tpu.ops import preemption as pre_ops
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.metrics import Registry
from kubernetes_tpu.scheduler.preemption import PreemptionEvaluator
from kubernetes_tpu.testing.oracle import Oracle
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


# -- kernel ---------------------------------------------------------------


def test_dry_run_min_k():
    # one node, 3 victims, free 0; pod needs 2 cpu; victims free 1 cpu
    # each -> min_k = 2
    free = np.zeros((1, 2), np.float32)
    victim_req = np.array([[[1, 0], [1, 0], [1, 0]]], np.float32)
    valid = np.ones((1, 3), bool)
    pod_req = np.array([2, 0], np.float32)
    r = pre_ops.dry_run_victims(free, victim_req, valid, pod_req)
    assert bool(r.feasible[0])
    assert int(r.min_k[0]) == 2


def test_dry_run_infeasible_even_after_all_evictions():
    free = np.zeros((1, 1), np.float32)
    victim_req = np.full((1, 2, 1), 1.0, np.float32)
    valid = np.ones((1, 2), bool)
    pod_req = np.array([5.0], np.float32)
    r = pre_ops.dry_run_victims(free, victim_req, valid, pod_req)
    assert not bool(r.feasible[0])


def test_dry_run_padding_not_counted():
    # 1 real victim + 1 padding slot: k=2 must not become claimable
    free = np.zeros((1, 1), np.float32)
    victim_req = np.array([[[1.0], [99.0]]], np.float32)  # padding junk
    valid = np.array([[True, False]])
    pod_req = np.array([2.0], np.float32)
    r = pre_ops.dry_run_victims(free, victim_req, valid, pod_req)
    assert not bool(r.feasible[0])


# -- evaluator vs oracle ---------------------------------------------------


def _build_cluster(rng, n_nodes=6, n_victims=12):
    """Every node gets >= 2 victims (round-robin), so a 3500m preemptor
    on 4000m nodes never fits without eviction — preemption's actual
    precondition (PostFilter only runs after filters rejected all)."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=20).obj()
        for i in range(n_nodes)
    ]
    bound = []
    for i in range(n_victims):
        node = f"n{i % n_nodes}"
        p = (
            make_pod(f"v{i}")
            .req(cpu_milli=int(rng.choice([500, 1000, 1500])), mem=GI)
            .priority(int(rng.integers(0, 5)))
            .node_name(node)
            .obj()
        )
        bound.append(p)
    return nodes, bound


def _evaluator_for(nodes, bound):
    tpu = TPUBatchScheduler()
    for n in nodes:
        tpu.add_node(n)
    for p in bound:
        tpu.assume(p, p.spec.node_name)
    cache = SchedulerCache(tpu.state)
    store = st.Store()
    ev = PreemptionEvaluator(tpu, cache, store)
    return ev


def test_victim_choice_oracle_parity(rng):
    """Randomized clusters: the evaluator's (node, victims) must equal the
    pure-Python policy mirror whenever the optimum is unique enough for
    both orderings to coincide (resource-only pods, unique priorities per
    node make it so)."""
    for trial in range(10):
        nodes, bound = _build_cluster(rng)
        preemptor = (
            make_pod("hi")
            .req(cpu_milli=3500, mem=GI)
            .priority(100)
            .obj()
        )
        ev = _evaluator_for(nodes, bound)
        with ev.cache.lock:
            plan = ev._plan(preemptor)
        oracle = Oracle(nodes, bound_pods=bound)
        want = oracle.preempt(preemptor)
        if plan is None:
            assert want is None, f"trial {trial}: oracle found {want}"
            continue
        assert want is not None, f"trial {trial}: oracle found nothing"
        node, victims = plan
        wnode, wvictims = want
        assert node == wnode, f"trial {trial}: {node} != {wnode}"
        assert sorted(v.meta.name for v in victims) == sorted(
            v.meta.name for v in wvictims
        ), trial


def test_never_policy_not_eligible():
    nodes = [make_node("n0").capacity(cpu_milli=1000).obj()]
    bound = [make_pod("v").req(cpu_milli=1000).priority(0).node_name("n0").obj()]
    ev = _evaluator_for(nodes, bound)
    pod = make_pod("hi").req(cpu_milli=1000).priority(10).obj()
    pod.spec.preemption_policy = "Never"
    assert not ev.eligible(pod)


def test_no_lower_priority_not_eligible():
    nodes = [make_node("n0").capacity(cpu_milli=1000).obj()]
    bound = [make_pod("v").req(cpu_milli=1000).priority(50).node_name("n0").obj()]
    ev = _evaluator_for(nodes, bound)
    pod = make_pod("lo").req(cpu_milli=1000).priority(10).obj()
    assert not ev.eligible(pod)


def test_verify_rejects_statically_blocked_candidate():
    """The pod is anti-affine to a label that survives eviction (carried
    by a HIGHER-priority pod), so resource-only candidates must be
    rejected by the re-solve verification."""
    nodes = [make_node("n0").capacity(cpu_milli=2000, pods=10).obj()]
    blocker = (
        make_pod("blocker")
        .req(cpu_milli=1000)
        .priority(200)  # not evictable
        .label("app", "x")
        .node_name("n0")
        .obj()
    )
    filler = (
        make_pod("filler").req(cpu_milli=1000).priority(0).node_name("n0").obj()
    )
    ev = _evaluator_for(nodes, [blocker, filler])
    pod = (
        make_pod("hi")
        .req(cpu_milli=500)
        .priority(100)
        .pod_anti_affinity({"app": "x"})
        .obj()
    )
    with ev.cache.lock:
        plan = ev._plan(pod)
    assert plan is None


def test_verify_accepts_when_eviction_clears_conflict():
    """Evicting the low-priority conflicting pod removes BOTH the resource
    shortage and the anti-affinity conflict."""
    nodes = [make_node("n0").capacity(cpu_milli=1000, pods=10).obj()]
    conflicter = (
        make_pod("conflicter")
        .req(cpu_milli=1000)
        .priority(0)
        .label("app", "x")
        .node_name("n0")
        .obj()
    )
    ev = _evaluator_for(nodes, [conflicter])
    pod = (
        make_pod("hi")
        .req(cpu_milli=500)
        .priority(100)
        .pod_anti_affinity({"app": "x"})
        .obj()
    )
    with ev.cache.lock:
        plan = ev._plan(pod)
    assert plan is not None
    node, victims = plan
    assert node == "n0"
    assert [v.meta.name for v in victims] == ["conflicter"]


# -- end-to-end through the scheduler --------------------------------------


def _mk_scheduler(store, **kw):
    s = Scheduler(store, **kw)
    s.informers.informer("Node").start()
    s.informers.informer("Pod").start()
    assert s.informers.wait_for_sync(10)
    return s


def test_preemption_end_to_end():
    """Full cluster; a high-priority pod arrives, evicts the cheapest
    victim set through the store, is nominated, and lands on the freed
    node on a later cycle.  preemption_* metrics populate."""
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=2000, pods=10).obj())
    store.create(make_node("n1").capacity(cpu_milli=2000, pods=10).obj())
    # fill both nodes with low-priority pods (bound directly via the API)
    for i, node in [(0, "n0"), (1, "n0"), (2, "n1"), (3, "n1")]:
        p = (
            make_pod(f"low-{i}")
            .req(cpu_milli=1000)
            .priority(i)  # low-0 is the cheapest victim
            .node_name(node)
            .obj()
        )
        p.status.phase = "Running"
        store.create(p)
    sched = _mk_scheduler(store)
    try:
        store.create(make_pod("hi").req(cpu_milli=1000).priority(100).obj())
        deadline = time.monotonic() + 15
        placed = None
        while time.monotonic() < deadline and not placed:
            sched.schedule_batch(timeout=0.2)
            placed = store.get("Pod", "hi").spec.node_name
        assert placed == "n0", placed
        # the cheapest victim (lowest priority, prio=0 on n0) was evicted
        with pytest.raises(KeyError):
            store.get("Pod", "low-0")
        # others survive
        for name in ("low-1", "low-2", "low-3"):
            store.get("Pod", name)
        assert sched.metrics.preemption_attempts.get("nominated") >= 1
        assert sched.metrics.preemption_victims.n >= 1
        # nomination was recorded through the API at some point
        assert placed == "n0"
    finally:
        sched.stop()


def test_preemption_not_triggered_when_feasible_elsewhere():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=1000, pods=10).obj())
    store.create(make_node("n1").capacity(cpu_milli=2000, pods=10).obj())
    low = make_pod("low").req(cpu_milli=1000).priority(0).node_name("n0").obj()
    store.create(low)
    sched = _mk_scheduler(store)
    try:
        store.create(make_pod("hi").req(cpu_milli=1000).priority(100).obj())
        deadline = time.monotonic() + 10
        placed = None
        while time.monotonic() < deadline and not placed:
            sched.schedule_batch(timeout=0.2)
            placed = store.get("Pod", "hi").spec.node_name
        assert placed == "n1"
        store.get("Pod", "low")  # still alive
        assert sched.metrics.preemption_attempts.get("attempted") == 0
    finally:
        sched.stop()


def test_nominated_reservation_blocks_stealers():
    """A nominated pod's requests overlay its node for OTHER pods'
    snapshots (PodNominator analogue): the freed space cannot be stolen
    while the nominee waits to land."""
    tpu = TPUBatchScheduler()
    tpu.add_node(make_node("n0").capacity(cpu_milli=1000, pods=10).obj())
    nominee = make_pod("hi").req(cpu_milli=1000).priority(100).obj()
    stealer = make_pod("thief").req(cpu_milli=1000).priority(100).obj()
    # without the reservation the stealer fits
    assert tpu.schedule_pending([stealer]) == ["n0"]
    # with the nominee's reservation it must not
    assert tpu.schedule_pending(
        [stealer], reservations=[("n0", nominee)]
    ) == [None]
    # the nominee's own batch excludes its reservation and lands
    assert tpu.schedule_pending([nominee]) == ["n0"]


def test_nomination_lifecycle_in_cache():
    tpu = TPUBatchScheduler()
    tpu.add_node(make_node("n0").capacity(cpu_milli=2000, pods=10).obj())
    cache = SchedulerCache(tpu.state)
    pod = make_pod("p").req(cpu_milli=500).priority(5).obj()
    cache.nominate(pod, "n0")
    assert cache.nominations_excluding(set()) == [("n0", pod)]
    # the nominee's own batch is excluded
    from kubernetes_tpu.scheduler.queue import pod_key
    assert cache.nominations_excluding({pod_key(pod)}) == []
    # assuming the pod (it landed) spends the nomination
    cache.assume(pod, "n0")
    assert cache.nominations_excluding(set()) == []


def test_nominate_survives_conflict_and_notfound():
    """_nominate is best-effort: a concurrent writer between its get and
    update raises Conflict (a ValueError) — it must retry/drop, never
    propagate and kill the scheduling thread (advisor finding r3)."""
    from kubernetes_tpu.api import store as st
    from kubernetes_tpu.scheduler.preemption import PreemptionEvaluator

    store = st.Store()
    pod = make_pod("prey").obj()
    store.create(pod)

    class RacingStore:
        """First update hits a conflict (someone else wrote); the retry
        against the re-read object succeeds."""

        def __init__(self, inner):
            self.inner = inner
            self.calls = 0

        def get(self, *a, **k):
            return self.inner.get(*a, **k)

        def update(self, obj):
            self.calls += 1
            if self.calls == 1:
                raise st.Conflict("resourceVersion mismatch")
            return self.inner.update(obj)

    ev = object.__new__(PreemptionEvaluator)
    ev.store = RacingStore(store)
    ev._nominate(pod, "node-x")
    got = store.get("Pod", "prey", pod.meta.namespace)
    assert got.status.nominated_node_name == "node-x"

    # NotFound (pod deleted mid-flight) is silently dropped
    ev.store = store
    missing = make_pod("gone").obj()
    ev._nominate(missing, "node-y")  # must not raise


# -- PDBs (policy/v1 PodDisruptionBudget; preemption.go:290,463) ----------


def _pdb(name, selector, allowed, namespace="default"):
    pdb = api.PodDisruptionBudget(
        meta=api.ObjectMeta(name=name, namespace=namespace),
        spec=api.PodDisruptionBudgetSpec(
            selector=api.LabelSelector(match_labels=selector)
        ),
    )
    pdb.status.disruptions_allowed = allowed
    return pdb


def test_pdb_flags_partition_victims():
    from kubernetes_tpu.scheduler.preemption import PreemptionEvaluator

    pdbs = [_pdb("b", {"app": "db"}, 1)]
    victims = [
        make_pod(f"v{i}").labels(app="db").priority(i).obj() for i in range(3)
    ]
    flags = PreemptionEvaluator._pdb_flags(victims, pdbs)
    # budget allows ONE disruption: the first eviction tolerated, rest violate
    assert flags == [False, True, True]


def test_pdb_steers_victim_choice_end_to_end():
    """Two equivalent candidate nodes; the one whose victim violates a
    PDB must lose (minNumPDBViolatingScoreFunc is the FIRST criterion)."""
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=2000, pods=10).obj())
    store.create(make_node("n1").capacity(cpu_milli=2000, pods=10).obj())
    for name, node, app in (
        ("guarded", "n0", "db"),     # protected by a zero-budget PDB
        ("free", "n1", "web"),
    ):
        p = (
            make_pod(name).labels(app=app).req(cpu_milli=2000)
            .priority(1).node_name(node).obj()
        )
        p.status.phase = "Running"
        store.create(p)
    store.create(_pdb("db-pdb", {"app": "db"}, 0))
    sched = _mk_scheduler(store)
    try:
        store.create(make_pod("hi").req(cpu_milli=1500).priority(100).obj())
        deadline = time.monotonic() + 15
        placed = None
        while time.monotonic() < deadline and not placed:
            sched.schedule_batch(timeout=0.2)
            placed = store.get("Pod", "hi").spec.node_name
        assert placed == "n1", placed   # the unprotected victim's node
        store.get("Pod", "guarded")     # survives
        with pytest.raises(KeyError):
            store.get("Pod", "free")    # evicted
    finally:
        sched.stop()


# -- batched PostFilter (preempt_batch) vs the sequential loop -------------
#
# The batched path encodes the per-node victim tensors ONCE per pass and
# runs one [P, N, K] device dry-run; the wavefront-style conflict pass
# (touched-node recompute) must make its results IDENTICAL to running
# preempt() sequentially on the same failed-pod set — including gang
# preemptors and PDB-blocked candidates.


def _pod_result_key(res):
    if res is None:
        return None
    return (res.nominated_node, sorted(v.meta.name for v in res.victims))


def _store_evaluator(nodes, bound, preemptors, pdbs=()):
    """Evaluator with a REAL store behind it (preempt() re-fetches the
    preemptor and deletes victims through the API)."""
    tpu = TPUBatchScheduler()
    store = st.Store()
    for n in nodes:
        tpu.add_node(n)
        store.create(n)
    for p in bound:
        tpu.assume(p, p.spec.node_name)
        store.create(p)
    for p in preemptors:
        store.create(p)
    for pdb in pdbs:
        store.create(pdb)
    cache = SchedulerCache(tpu.state)
    ev = PreemptionEvaluator(tpu, cache, store, Registry())
    return ev


def _mixed_cluster(rng, n_nodes=6, n_victims=14, n_preemptors=4,
                   gang_of=0, db_every=0):
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=20).obj()
        for i in range(n_nodes)
    ]
    bound = []
    for i in range(n_victims):
        pw = (
            make_pod(f"v{i}")
            .req(cpu_milli=int(rng.choice([500, 1000, 1500])), mem=GI)
            .priority(int(rng.integers(0, 5)))
            .node_name(f"n{i % n_nodes}")
        )
        if db_every and i % db_every == 0:
            pw = pw.labels(app="db")
        p = pw.obj()
        p.status.phase = "Running"
        bound.append(p)
    preemptors = []
    for j in range(n_preemptors):
        pw = make_pod(f"hi{j}").req(cpu_milli=3500, mem=GI).priority(
            int(rng.choice([50, 100, 200]))
        )
        if gang_of and j < gang_of:
            pw = pw.group("band", size=gang_of)
        preemptors.append(pw.obj())
    return nodes, bound, preemptors


def _assert_batch_matches_sequential(nodes, bound, preemptors, pdbs=()):
    ev_seq = _store_evaluator(nodes, bound, preemptors, pdbs)
    ev_bat = _store_evaluator(nodes, bound, preemptors, pdbs)
    seq = [
        ev_seq.preempt(p) if ev_seq.eligible(p) else None
        for p in preemptors
    ]
    bat = ev_bat.preempt_batch(preemptors)
    for j, (a, b) in enumerate(zip(seq, bat)):
        assert _pod_result_key(a) == _pod_result_key(b), (
            f"preemptor {j}: sequential {_pod_result_key(a)} != "
            f"batched {_pod_result_key(b)}"
        )
    # the surviving accounted state must be identical too
    assert sorted(ev_seq.tpu.state._pod_node.items()) == sorted(
        ev_bat.tpu.state._pod_node.items()
    )
    return ev_bat


def test_preempt_batch_matches_sequential(rng):
    """Randomized mixed-priority clusters: batched == sequential for the
    whole failed-pod set, INCLUDING passes where earlier preemptors'
    evictions touch later preemptors' candidate nodes (the conflict
    recompute)."""
    any_conflict = False
    for trial in range(8):
        nodes, bound, preemptors = _mixed_cluster(rng)
        ev = _assert_batch_matches_sequential(nodes, bound, preemptors)
        any_conflict = any_conflict or (
            ev.metrics.preemption_conflict_serializations.total > 0
        )
        assert ev.metrics.preemption_batch_size.n >= 1
    # with 4 preemptors over 6 nodes, at least one trial must have
    # exercised the touched-node recompute — otherwise the conflict
    # pass is untested
    assert any_conflict, "no trial exercised a cross-preemptor conflict"


def test_preempt_batch_gang_parity(rng):
    """Gang preemptors ride the shared pass: the multi-node accumulation
    (_plan_gang) consumes the batched candidates and stays identical to
    the sequential loop."""
    for trial in range(4):
        nodes, bound, preemptors = _mixed_cluster(
            rng, n_nodes=4, n_victims=8, n_preemptors=3, gang_of=2
        )
        _assert_batch_matches_sequential(nodes, bound, preemptors)


def test_preempt_batch_pdb_parity(rng):
    """PDB-blocked candidates: the per-level eviction reorder
    (non-violating victims first) and the device-side violation counts
    must rank identically to the sequential host-only pass."""
    for trial in range(4):
        nodes, bound, preemptors = _mixed_cluster(rng, db_every=2)
        pdbs = [_pdb("db-pdb", {"app": "db"}, 1)]
        ev = _assert_batch_matches_sequential(
            nodes, bound, preemptors, pdbs
        )
        assert ev.pdb_aware


def test_preempt_batch_pdb_blocked_metric():
    """A candidate whose only victim violates a zero-budget PDB ranks
    last and counts into preemption_pdb_blocked_total."""
    nodes = [
        make_node(f"n{i}").capacity(cpu_milli=2000, pods=10).obj()
        for i in range(2)
    ]
    bound = []
    for name, node, app in (("guarded", "n0", "db"), ("free", "n1", "web")):
        p = (
            make_pod(name).labels(app=app).req(cpu_milli=2000)
            .priority(1).node_name(node).obj()
        )
        p.status.phase = "Running"
        bound.append(p)
    preemptor = make_pod("hi").req(cpu_milli=1500).priority(100).obj()
    ev = _store_evaluator(
        nodes, bound, [preemptor], [_pdb("db-pdb", {"app": "db"}, 0)]
    )
    results = ev.preempt_batch([preemptor])
    assert results[0] is not None
    assert results[0].nominated_node == "n1"  # the unprotected node wins
    assert ev.metrics.preemption_pdb_blocked_total.total >= 1


def test_preempt_batch_oracle_parity(rng):
    """Randomized snapshots: the batched plan for a single preemptor
    must equal the pure-Python policy mirror (the documented
    reprieve-policy divergence stays pinned — Oracle.preempt implements
    OUR minimal-prefix policy, not the reference's reprieve pass)."""
    for trial in range(8):
        nodes, bound = _build_cluster(rng)
        preemptor = (
            make_pod("hi").req(cpu_milli=3500, mem=GI).priority(100).obj()
        )
        ev = _store_evaluator(nodes, bound, [preemptor])
        with ev.shared_pass([preemptor]):
            assert not ev._shared.fallback
            plan = ev._plan(preemptor)
        want = Oracle(nodes, bound_pods=bound).preempt(preemptor)
        if plan is None:
            assert want is None, f"trial {trial}: oracle found {want}"
            continue
        assert want is not None, f"trial {trial}: oracle found nothing"
        node, victims = plan
        wnode, wvictims = want
        assert node == wnode, trial
        assert sorted(v.meta.name for v in victims) == sorted(
            v.meta.name for v in wvictims
        ), trial


def test_preempt_batch_fallback_parity(rng):
    """Injected batched-dispatch failures (the breaker wire): the pass
    falls back to the per-pod exact-parity path and still produces the
    sequential loop's results; the shared solve breaker trips."""
    from kubernetes_tpu.testing import faults

    nodes, bound, preemptors = _mixed_cluster(rng)
    ev_seq = _store_evaluator(nodes, bound, preemptors)
    seq = [
        ev_seq.preempt(p) if ev_seq.eligible(p) else None
        for p in preemptors
    ]
    ev_bat = _store_evaluator(nodes, bound, preemptors)
    reg = faults.FaultRegistry(seed=1)
    reg.fail("batch.preemption", n=2)  # first attempt AND its retry
    with faults.armed(reg):
        bat = ev_bat.preempt_batch(preemptors)
    assert reg.fired.get("batch.preemption") == 2
    assert ev_bat.tpu.breaker.state == ev_bat.tpu.breaker.OPEN
    for a, b in zip(seq, bat):
        assert _pod_result_key(a) == _pod_result_key(b)


def test_preempt_batch_corrupt_result_falls_back(rng):
    """NaN-grade corruption of the batched dry-run result trips the
    health check (out-of-range victim counts) on BOTH attempts; the
    pass degrades to the per-pod path with parity."""
    from kubernetes_tpu.testing import faults

    nodes, bound, preemptors = _mixed_cluster(rng)
    ev_seq = _store_evaluator(nodes, bound, preemptors)
    seq = [
        ev_seq.preempt(p) if ev_seq.eligible(p) else None
        for p in preemptors
    ]
    ev_bat = _store_evaluator(nodes, bound, preemptors)
    reg = faults.FaultRegistry(seed=2)
    reg.corrupt("batch.preemption", n=2)
    with faults.armed(reg):
        bat = ev_bat.preempt_batch(preemptors)
    for a, b in zip(seq, bat):
        assert _pod_result_key(a) == _pod_result_key(b)


def test_eligible_uses_shared_min_priority():
    """The satellite: eligibility inside a shared pass consults the
    pass's cached min-existing-priority instead of scanning
    state._pods per failed pod."""
    nodes = [make_node("n0").capacity(cpu_milli=2000, pods=10).obj()]
    victim = (
        make_pod("v").req(cpu_milli=2000).priority(5).node_name("n0").obj()
    )
    victim.status.phase = "Running"
    hi = make_pod("hi").req(cpu_milli=500).priority(100).obj()
    lo = make_pod("lo").req(cpu_milli=500).priority(3).obj()
    ev = _store_evaluator(nodes, [victim], [hi, lo])
    assert ev.min_existing_priority() == 5
    with ev.shared_pass([hi, lo]) as ctx:
        assert ctx.min_prio == 5
        assert ev.eligible(hi)        # 100 > 5
        assert not ev.eligible(lo)    # 3 < 5: nothing evictable
        # the cached value is consulted — mutating state mid-pass must
        # not change eligibility answers (one scan per pass)
        ev.tpu.state.remove_pod(victim)
        assert ev.eligible(hi)
    # outside the pass the live scan is back
    assert ev.min_existing_priority() is None
    assert not ev.eligible(hi)


def test_scheduler_postfilter_uses_batched_pass():
    """End-to-end: the scheduler's PostFilter stage routes the failed
    batch through one shared preemption pass (preemption_batch_size
    observes) and the nominee lands."""
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=2000, pods=10).obj())
    for i in range(2):
        p = (
            make_pod(f"low-{i}").req(cpu_milli=1000).priority(i)
            .node_name("n0").obj()
        )
        p.status.phase = "Running"
        store.create(p)
    sched = _mk_scheduler(store)
    try:
        store.create(make_pod("hi").req(cpu_milli=1500).priority(100).obj())
        deadline = time.monotonic() + 15
        placed = None
        while time.monotonic() < deadline and not placed:
            sched.schedule_batch(timeout=0.2)
            placed = store.get("Pod", "hi").spec.node_name
        assert placed == "n0"
        assert sched.metrics.preemption_batch_size.n >= 1
        assert sched.metrics.preemption_solve_duration.n >= 1
    finally:
        sched.stop()


def test_overload_level1_caps_instead_of_deferring():
    """The degradation ladder's level-1 action is now a CAP on the
    preemption batch (the batched solve amortized the per-pod cost),
    not a full deferral; level 2 still defers."""
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=2000, pods=10).obj())
    p = make_pod("low").req(cpu_milli=2000).priority(0).node_name("n0").obj()
    p.status.phase = "Running"
    store.create(p)
    sched = _mk_scheduler(store)
    try:
        # push the controller to level 1 (ewma > slo)
        for _ in range(10):
            sched.overload.note_cycle(2 * sched.overload.slo * 0.9)
        assert sched.overload.level() == 1
        store.create(make_pod("hi").req(cpu_milli=1500).priority(100).obj())
        deadline = time.monotonic() + 15
        placed = None
        while time.monotonic() < deadline and not placed:
            sched.schedule_batch(timeout=0.2)
            placed = store.get("Pod", "hi").spec.node_name
        # level 1 must NOT have deferred the preemption outright
        assert placed == "n0"
        assert sched.metrics.preemption_attempts.get("nominated") >= 1
    finally:
        sched.stop()


def test_gang_preemption_evicts_across_nodes():
    """A whole gang preempts: victims accumulate over multiple nodes
    until the group fits all-or-nothing (previously gang members were
    preemption-ineligible)."""
    store = st.Store()
    for i in range(2):
        store.create(make_node(f"n{i}").capacity(cpu_milli=2000, pods=10).obj())
    for i in range(2):
        p = (
            make_pod(f"low-{i}").req(cpu_milli=2000).priority(0)
            .node_name(f"n{i}").obj()
        )
        p.status.phase = "Running"
        store.create(p)
    sched = _mk_scheduler(store)
    try:
        # gang of 2, each needing a whole node: must evict BOTH low pods
        for i in range(2):
            store.create(
                make_pod(f"g{i}").req(cpu_milli=2000).priority(100)
                .group("band", size=2).obj()
            )
        deadline = time.monotonic() + 20
        placed = []
        while time.monotonic() < deadline and len(placed) < 2:
            sched.schedule_batch(timeout=0.2)
            placed = [
                store.get("Pod", f"g{i}").spec.node_name
                for i in range(2)
                if store.get("Pod", f"g{i}").spec.node_name
            ]
        assert sorted(placed) == ["n0", "n1"], placed
        for i in range(2):
            with pytest.raises(KeyError):
                store.get("Pod", f"low-{i}")
    finally:
        sched.stop()
