"""Extender endpoint: wire-type conformance against recorded fixtures
(the JSON a stock kube-scheduler's HTTPExtender sends/expects —
extender.go:397 send(), extender/v1/types.go:73-132) and verb behaviour
over live HTTP."""

import json
import urllib.request

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.extender import ExtenderBackend, ExtenderServer
from kubernetes_tpu.extender.types import ExtenderArgs
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod

# The JSON document shape kube-scheduler POSTs in nodeCacheCapable mode
# (field names = Go struct field names; no json tags in types.go).
FILTER_REQUEST_FIXTURE = {
    "Pod": {
        "metadata": {"name": "p1", "namespace": "default", "labels": {"app": "web"}},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "resources": {"requests": {"cpu": "500m", "memory": "512Mi"}},
                }
            ]
        },
    },
    "Nodes": None,
    "NodeNames": ["n0", "n1", "tiny"],
}


def _backend():
    be = ExtenderBackend()
    be.add_node(make_node("n0").capacity(cpu_milli=4000, mem=8 * GI, pods=10).obj())
    be.add_node(make_node("n1").capacity(cpu_milli=4000, mem=8 * GI, pods=10).obj())
    be.add_node(make_node("tiny").capacity(cpu_milli=100, mem=128 * MI, pods=10).obj())
    return be


def test_filter_result_wire_shape():
    be = _backend()
    res = be.filter(ExtenderArgs.from_dict(FILTER_REQUEST_FIXTURE))
    # exact ExtenderFilterResult keys (types.go:88-104)
    assert set(res.keys()) == {
        "Nodes", "NodeNames", "FailedNodes",
        "FailedAndUnresolvableNodes", "Error",
    }
    assert sorted(res["NodeNames"]) == ["n0", "n1"]
    assert "tiny" in res["FailedNodes"]
    assert res["Error"] == ""
    json.dumps(res)  # serializable


def test_prioritize_wire_shape():
    be = _backend()
    out = be.prioritize(ExtenderArgs.from_dict(FILTER_REQUEST_FIXTURE))
    assert isinstance(out, list)
    for item in out:
        assert set(item.keys()) == {"Host", "Score"}
        assert 0 <= item["Score"] <= 10  # MaxExtenderPriority
    by_host = {i["Host"]: i["Score"] for i in out}
    assert by_host["tiny"] == 0
    assert max(by_host.values()) == 10


def test_filter_non_cache_mode_ships_nodes():
    """Nodes arrive as full v1.Node objects; the extender upserts and
    evaluates without any pre-fed inventory."""
    be = ExtenderBackend()
    req = {
        "Pod": FILTER_REQUEST_FIXTURE["Pod"],
        "Nodes": {
            "items": [
                {
                    "metadata": {"name": "fresh"},
                    "status": {"capacity": {"cpu": "4", "memory": "8Gi", "pods": "10"}},
                }
            ]
        },
        "NodeNames": None,
    }
    res = be.filter(ExtenderArgs.from_dict(req))
    assert res["NodeNames"] == ["fresh"]


def test_filter_respects_taints_and_affinity():
    be = ExtenderBackend()
    be.add_node(
        make_node("tainted")
        .capacity(cpu_milli=4000, mem=8 * GI, pods=10)
        .taint("dedicated", "gpu")
        .obj()
    )
    be.add_node(make_node("plain").capacity(cpu_milli=4000, mem=8 * GI, pods=10).obj())
    req = dict(FILTER_REQUEST_FIXTURE, NodeNames=["tainted", "plain"])
    res = be.filter(ExtenderArgs.from_dict(req))
    assert res["NodeNames"] == ["plain"]


def test_bind_through_store():
    store = st.Store()
    store.create(make_pod("p1").req(cpu_milli=100).obj())
    be = _backend()
    be.store = store
    res = be.bind(
        {"PodName": "p1", "PodNamespace": "default", "PodUID": "u", "Node": "n0"}
    )
    assert res == {"Error": ""}
    assert store.get("Pod", "p1").spec.node_name == "n0"


def test_preemption_passthrough():
    be = _backend()
    victims = {"n0": {"Pods": [{"UID": "u1"}], "NumPDBViolations": 0}}
    res = be.preemption({"NodeNameToMetaVictims": victims})
    assert res == {"NodeNameToMetaVictims": victims}


def test_http_server_end_to_end():
    be = _backend()
    srv = ExtenderServer(be).start()
    try:
        url = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(url + "/healthz") as r:
            assert json.load(r) == {"ok": True}
        req = urllib.request.Request(
            url + "/filter",
            data=json.dumps(FILTER_REQUEST_FIXTURE).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            res = json.load(r)
        assert sorted(res["NodeNames"]) == ["n0", "n1"]
        req = urllib.request.Request(
            url + "/prioritize",
            data=json.dumps(FILTER_REQUEST_FIXTURE).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            scores = json.load(r)
        assert {i["Host"] for i in scores} == {"n0", "n1", "tiny"}
    finally:
        srv.stop()


def test_sync_store_accounts_bound_pods():
    store = st.Store()
    store.create(make_node("n0").capacity(cpu_milli=1000, mem=8 * GI, pods=10).obj())
    bound = make_pod("existing").req(cpu_milli=900).node_name("n0").obj()
    store.create(bound)
    be = ExtenderBackend()
    be.sync_store(store)
    req = {
        "Pod": {
            "metadata": {"name": "big"},
            "spec": {
                "containers": [
                    {"resources": {"requests": {"cpu": "500m"}}}
                ]
            },
        },
        "Nodes": None,
        "NodeNames": ["n0"],
    }
    res = be.filter(ExtenderArgs.from_dict(req))
    assert res["NodeNames"] == []  # 900m bound + 500m pending > 1000m


def test_filter_non_cache_mode_echoes_node_objects():
    """nodeCacheCapable=false schedulers read result.Nodes.items, not
    NodeNames (extender.go Filter) — passing nodes must echo as full
    objects (review finding)."""
    be = ExtenderBackend()
    req = {
        "Pod": FILTER_REQUEST_FIXTURE["Pod"],
        "Nodes": {
            "items": [
                {
                    "metadata": {"name": "okay"},
                    "status": {"capacity": {"cpu": "4", "memory": "8Gi", "pods": "10"}},
                },
                {
                    "metadata": {"name": "small"},
                    "status": {"capacity": {"cpu": "100m", "memory": "64Mi", "pods": "10"}},
                },
            ]
        },
        "NodeNames": None,
    }
    res = be.filter(ExtenderArgs.from_dict(req))
    items = res["Nodes"]["items"]
    assert [d["metadata"]["name"] for d in items] == ["okay"]
    assert res["NodeNames"] == ["okay"]


def test_bind_accounts_capacity_in_extender_state():
    """A /bind must consume capacity in the extender's own state so the
    next /filter sees it (review finding)."""
    store = st.Store()
    store.create(make_pod("a").req(cpu_milli=900).obj())
    be = ExtenderBackend()
    be.store = store
    be.add_node(make_node("n0").capacity(cpu_milli=1000, mem=8 * GI, pods=10).obj())
    assert be.bind(
        {"PodName": "a", "PodNamespace": "default", "Node": "n0"}
    ) == {"Error": ""}
    req = {
        "Pod": {
            "metadata": {"name": "b"},
            "spec": {"containers": [{"resources": {"requests": {"cpu": "500m"}}}]},
        },
        "Nodes": None,
        "NodeNames": ["n0"],
    }
    res = be.filter(ExtenderArgs.from_dict(req))
    assert res["NodeNames"] == []
