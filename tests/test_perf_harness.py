"""scheduler_perf harness: YAML loading, k8s-YAML conversion, and small
end-to-end workload runs through the host scheduler.

Reference shapes: test/integration/scheduler_perf/{scheduler_perf.go,
util.go, config/performance-config.yaml}.
"""

import os
import textwrap

import pytest
import yaml

from kubernetes_tpu.api import types as api
from kubernetes_tpu.perf import (
    DEFAULT_CONFIG,
    load_config,
    run_workloads,
    select,
)
from kubernetes_tpu.api.kubeyaml import node_from_dict, parse_quantity, pod_from_dict
from kubernetes_tpu.perf.runner import _substitute_index


def test_parse_quantity():
    assert parse_quantity("500m", cpu=True) == 500
    assert parse_quantity("4", cpu=True) == 4000
    assert parse_quantity("512Mi") == 512 * 2**20
    assert parse_quantity("32Gi") == 32 * 2**30
    assert parse_quantity("1k") == 1000
    assert parse_quantity("110") == 110


def test_pod_from_dict_full():
    d = yaml.safe_load(
        textwrap.dedent(
            """
            apiVersion: v1
            kind: Pod
            metadata:
              name: p
              labels: {color: green}
            spec:
              priority: 10
              nodeSelector: {disk: ssd}
              containers:
              - name: c
                resources:
                  requests: {cpu: 100m, memory: 500Mi}
                ports:
                - containerPort: 80
                  hostPort: 8080
              affinity:
                podAntiAffinity:
                  requiredDuringSchedulingIgnoredDuringExecution:
                  - labelSelector:
                      matchLabels: {color: green}
                    topologyKey: kubernetes.io/hostname
                nodeAffinity:
                  requiredDuringSchedulingIgnoredDuringExecution:
                    nodeSelectorTerms:
                    - matchExpressions:
                      - {key: zone, operator: In, values: [a, b]}
              topologySpreadConstraints:
              - maxSkew: 2
                topologyKey: topology.kubernetes.io/zone
                whenUnsatisfiable: DoNotSchedule
                labelSelector:
                  matchLabels: {color: green}
              tolerations:
              - {key: foo, operator: Exists, effect: NoSchedule}
            """
        )
    )
    pod = pod_from_dict(d)
    assert pod.meta.name == "p"
    assert pod.spec.priority == 10
    assert pod.resource_requests()[api.CPU] == 100
    assert pod.resource_requests()[api.MEMORY] == 500 * 2**20
    assert pod.host_ports() == [("TCP", "0.0.0.0", 8080)]
    assert pod.spec.affinity.pod_anti_affinity.required[0].topology_key == api.LABEL_HOSTNAME
    assert pod.spec.affinity.node_affinity.required.terms[0].match_expressions[0].values == ["a", "b"]
    c = pod.spec.topology_spread_constraints[0]
    assert c.max_skew == 2 and c.when_unsatisfiable == "DoNotSchedule"
    assert pod.spec.tolerations[0].op == "Exists"


def test_node_from_dict():
    d = yaml.safe_load(
        textwrap.dedent(
            """
            kind: Node
            metadata:
              name: n1
              labels: {topology.kubernetes.io/zone: z1}
            spec:
              unschedulable: true
              taints:
              - {key: dedicated, value: gpu, effect: NoSchedule}
            status:
              capacity: {cpu: "4", memory: 32Gi, pods: "110"}
            """
        )
    )
    node = node_from_dict(d)
    assert node.status.allocatable[api.CPU] == 4000
    assert node.status.allocatable[api.PODS] == 110
    assert node.meta.labels[api.LABEL_HOSTNAME] == "n1"
    assert node.spec.unschedulable
    assert node.spec.taints[0].key == "dedicated"


def test_index_substitution():
    t = {"metadata": {"labels": {"zone": "zone-$index_mod8", "n": "x$index"}}}
    out = _substitute_index(t, 11)
    assert out["metadata"]["labels"]["zone"] == "zone-3"
    assert out["metadata"]["labels"]["n"] == "x11"


def test_default_config_loads_and_selects():
    wls = load_config(DEFAULT_CONFIG)
    names = [w.full_name for w in wls]
    assert "SchedulingBasic/500Nodes" in names
    assert "TopologySpreading/5000Nodes" in names
    assert "PreemptionBasic/500Nodes" in names
    fast = select(wls, label="integration-test")
    assert all("integration-test" in w.labels for w in fast)
    one = select(wls, name="SchedulingBasic/500Nodes")
    assert len(one) == 1


def test_unknown_opcode_raises(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "- name: X\n  workloadTemplate:\n  - opcode: createVolume\n"
        "  workloads:\n  - name: w\n    params: {}\n"
    )
    with pytest.raises(ValueError, match="createVolume"):
        load_config(str(cfg))


def _tiny_config(tmp_path, body):
    cfg = tmp_path / "perf.yaml"
    cfg.write_text(body)
    return str(cfg)


def test_basic_workload_end_to_end(tmp_path):
    cfg = _tiny_config(
        tmp_path,
        textwrap.dedent(
            """
            - name: Tiny
              workloadTemplate:
              - opcode: createNodes
                countParam: $nodes
              - opcode: createPods
                countParam: $pods
                collectMetrics: true
              workloads:
              - name: basic
                params: {nodes: 8, pods: 24}
            """
        ),
    )
    wls = load_config(cfg)
    result = run_workloads(wls, sample_interval=0.02)
    metrics = {i["labels"]["Metric"] for i in result["dataItems"]}
    assert "WallClockThroughput" in metrics, result["dataItems"]
    assert (
        "scheduler_scheduling_algorithm_duration_seconds" in metrics
    ), result["dataItems"]
    wall = [
        i for i in result["dataItems"]
        if i["labels"]["Metric"] == "WallClockThroughput"
    ][0]
    assert wall["data"]["Average"] > 0, result["dataItems"]


def test_churn_and_barrier_end_to_end(tmp_path):
    cfg = _tiny_config(
        tmp_path,
        textwrap.dedent(
            """
            - name: TinyChurn
              workloadTemplate:
              - opcode: createNodes
                count: 4
              - opcode: churn
                mode: recreate
                number: 3
                intervalMilliseconds: 5
              - opcode: createPods
                count: 8
                collectMetrics: true
              - opcode: barrier
              - opcode: sleep
                duration: 10ms
              workloads:
              - name: w
                params: {}
            """
        ),
    )
    result = run_workloads(load_config(cfg), sample_interval=0.02)
    assert result["dataItems"]


def test_unschedulable_workload_terminates(tmp_path):
    node = tmp_path / "bad-node.yaml"
    node.write_text(
        "kind: Node\nspec: {unschedulable: true}\n"
        "status: {capacity: {cpu: '4', memory: 32Gi, pods: '110'}}\n"
    )
    cfg = _tiny_config(
        tmp_path,
        textwrap.dedent(
            """
            - name: TinyUnsched
              workloadTemplate:
              - opcode: createNodes
                count: 2
                nodeTemplatePath: bad-node.yaml
              - opcode: createPods
                count: 5
                collectMetrics: true
              workloads:
              - name: w
                params: {}
            """
        ),
    )
    result = run_workloads(load_config(cfg), sample_interval=0.02)
    # nothing scheduled; the run must still terminate via the parked path
    assert all(
        i["labels"]["Metric"] != "SchedulingThroughput"
        or not i["data"]
        for i in result["dataItems"]
    )
