"""graftlint (kubernetes_tpu/analysis) — the static analysis suite.

Two layers:

  * fixture tests: per-checker good/bad snippets (constructed as
    in-memory SourceFiles) prove each pass flags seeded violations and
    stays quiet on conforming code;
  * the real-tree gate: all eight static passes run over the actual
    repository and must produce nothing beyond the reviewed baseline —
    the tier-1 regression wire for lock discipline, lock atomicity,
    hot-path purity, registry consistency, lock ordering, tensor
    contracts, resident-cache coherence and linear obligations.  (The
    JAX-backed recompile-discipline pass has its own tier-1 gate in
    tests/test_shapes.py.)

Plus the runtime lock-order tracker's inversion regression tests
(analysis/runtime.py).
"""

import os
import textwrap
import threading

import pytest

from kubernetes_tpu.analysis import (
    SourceFile,
    apply_baseline,
    default_baseline_path,
    load_baseline,
    run_all,
)
from kubernetes_tpu.analysis import (
    atomicity,
    coherence,
    guarded,
    lockorder,
    obligations,
    purity,
    registry,
)
from kubernetes_tpu.analysis import runtime as rt
from kubernetes_tpu.analysis import tensorcontract

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def src(relpath: str, code: str) -> SourceFile:
    return SourceFile(relpath, relpath, textwrap.dedent(code))


# -- guarded-by --------------------------------------------------------------

GUARDED_BAD = '''
import threading

class Cache:
    GUARDED_FIELDS = {"_items": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def get(self, k):
        return self._items.get(k)      # bare access: finding

    def put(self, k, v):
        with self._lock:
            self._items[k] = v         # locked: fine
'''

GUARDED_GOOD = '''
import threading

class Cache:
    GUARDED_FIELDS = {"_items": "_lock", "_n": "_cond"}
    LOCKED_METHODS = frozenset({"_bump"})

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._items = {}
        self._n = 0

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def drain(self):
        with self._cond:
            def take():
                # closures defined under the with inherit the lock
                self._n -= 1
            take()

    def _bump(self):
        self._n += 1  # LOCKED_METHODS: caller holds _cond

    def _flush_locked(self):
        self._items.clear()  # *_locked naming convention

    def peek(self):
        return len(self._items)  # graftlint: disable=guarded-by -- test escape
'''

GUARDED_INLINE = '''
import threading

class W:
    def __init__(self):
        self._mu = threading.Lock()
        self._state = None  # guarded_by: _mu

    def read(self):
        return self._state       # bare access: finding

    def write(self, v):
        with self._mu:
            self._state = v
'''


def test_guarded_by_flags_bare_access():
    findings = guarded.check([src("kubernetes_tpu/x.py", GUARDED_BAD)])
    assert len(findings) == 1
    f = findings[0]
    assert f.check == "guarded-by"
    assert f.symbol == "Cache.get"
    assert "_items" in f.message and "_lock" in f.message


def test_guarded_by_quiet_on_conforming_code():
    assert guarded.check([src("kubernetes_tpu/x.py", GUARDED_GOOD)]) == []


def test_guarded_by_inline_comment_declaration():
    findings = guarded.check([src("kubernetes_tpu/x.py", GUARDED_INLINE)])
    assert [f.symbol for f in findings] == ["W.read"]
    assert "_mu" in findings[0].message


# -- purity ------------------------------------------------------------------

PURITY_BAD = '''
import time
import numpy as np
import jax.numpy as jnp
from kubernetes_tpu.analysis.markers import hot_path

def helper(x):
    return np.asarray(x)           # transitive: reached via solve

@hot_path
def solve(snap):
    t = time.time()                # wall clock: finding
    a = helper(snap)               # pulls helper onto the hot path
    v = float(a[0])                # tracer leak shape: finding
    return jnp.sum(a) + t + v

def cold(x):
    return np.asarray(x)           # unreachable from roots: quiet
'''

PURITY_LOCK = '''
from kubernetes_tpu.analysis.markers import hot_path

class Solver:
    @hot_path
    def dispatch(self, snap):
        with self._lock:           # lock on the hot path: finding
            return snap
'''

PURITY_GOOD = '''
import numpy as np
import jax.numpy as jnp
from kubernetes_tpu.analysis.markers import hot_path

def features_of(snap):  # graftlint: disable=purity -- host-side prep
    return np.asarray(snap).any()

@hot_path
def solve(snap, features=None):
    if features is None:
        features = features_of(snap)   # exempt callee: edge cut
    return jnp.sum(jnp.asarray(snap))
'''


def test_purity_flags_syncs_clocks_and_transitive_calls():
    findings = purity.check(
        [src("kubernetes_tpu/ops/k.py", PURITY_BAD)]
    )
    msgs = {(f.symbol, f.message.split(" (")[0]) for f in findings}
    assert ("solve", "time.time()") in msgs
    assert ("solve", "float() on a computed value") in msgs
    assert ("helper", "np.asarray") in msgs       # transitive reach
    assert all(f.symbol != "cold" for f in findings)


def test_purity_flags_locks_on_hot_path():
    findings = purity.check([src("kubernetes_tpu/ops/k.py", PURITY_LOCK)])
    assert len(findings) == 1
    assert "lock" in findings[0].message
    assert findings[0].symbol == "Solver.dispatch"


def test_purity_def_line_suppression_cuts_the_edge():
    assert purity.check([src("kubernetes_tpu/ops/k.py", PURITY_GOOD)]) == []


def test_purity_ignores_out_of_scope_packages():
    # same violation, but under scheduler/ (host-side by design)
    assert (
        purity.check([src("kubernetes_tpu/scheduler/k.py", PURITY_BAD)]) == []
    )


# -- registry ----------------------------------------------------------------

FAULTS_DECL = '''
KNOWN_POINTS = frozenset({"a.b", "dead.point"})
'''

FIRE_SITES = '''
from ..testing import faults

def f():
    faults.fire("a.b")
    faults.fire("undeclared.point")
'''

METRICS_SRC = '''
class Histogram:
    pass

class Registry:
    def __init__(self):
        self.h = Histogram("scheduler_x_seconds")
        self.unexported = Histogram("scheduler_y_seconds")
'''

COLLECTORS_SRC = '''
class MetricsCollector:
    DEFAULT_METRICS = (
        "scheduler_x_seconds",
        "scheduler_ghost_seconds",
    )
    SCALAR_METRICS = ()
'''


def _registry_fixture():
    return [
        src("kubernetes_tpu/testing/faults.py", FAULTS_DECL),
        src("kubernetes_tpu/api/store.py", FIRE_SITES),
        src("kubernetes_tpu/scheduler/metrics.py", METRICS_SRC),
        src("kubernetes_tpu/perf/collectors.py", COLLECTORS_SRC),
    ]


def test_registry_flags_drift_in_both_directions():
    findings = registry.check(_registry_fixture())
    by_symbol = {f.symbol: f.message for f in findings}
    assert "undeclared.point" in by_symbol        # fired, not declared
    assert "dead.point" in by_symbol              # declared, never fired
    assert "scheduler_ghost_seconds" in by_symbol  # exported, not defined
    assert "scheduler_y_seconds" in by_symbol     # defined, not exported
    assert "a.b" not in by_symbol                 # aligned both ways
    assert "scheduler_x_seconds" not in by_symbol
    assert len(findings) == 4


def test_registry_quiet_when_aligned():
    files = [
        src("kubernetes_tpu/testing/faults.py",
            'KNOWN_POINTS = frozenset({"a.b"})'),
        src("kubernetes_tpu/api/store.py",
            'from ..testing import faults\nfaults.fire("a.b")'),
        src("kubernetes_tpu/scheduler/metrics.py", '''
class Histogram: pass
class Registry:
    def __init__(self):
        self.h = Histogram("scheduler_x_seconds")
'''),
        src("kubernetes_tpu/perf/collectors.py", '''
class MetricsCollector:
    DEFAULT_METRICS = ("scheduler_x_seconds",)
'''),
    ]
    assert registry.check(files) == []


def test_registry_flags_dynamic_point_names():
    files = [
        src("kubernetes_tpu/testing/faults.py", FAULTS_DECL),
        src("kubernetes_tpu/api/store.py", '''
from ..testing import faults
def f(name):
    faults.fire(name)
    faults.fire("a.b")
    faults.fire("dead.point")
'''),
    ]
    findings = registry.check(files)
    assert any("string literal" in f.message for f in findings)


# -- tensor-contract ---------------------------------------------------------

TC_BAD = '''
from typing import NamedTuple
import numpy as np
import jax.numpy as jnp


class Cluster(NamedTuple):
    alloc: np.ndarray      # f32[N, R]
    bits: np.ndarray       # u32[N, LW]
    naked: np.ndarray      # no contract here: finding


class Pods(NamedTuple):
    req: np.ndarray        # f32[P, R]
    garbled: np.ndarray    # float32 of shape [P]  (unparseable: finding)


class Snap(NamedTuple):
    cluster: Cluster
    pods: Pods


def mix_axes(snap):
    p = snap.pods.req.shape[0]
    return snap.cluster.alloc[:p]          # P-var on the N axis: finding


def widen(values):
    demand = np.zeros(4, dtype=np.float64)  # 64-bit dtype: finding
    return demand + values


def shift(bits, i):
    bits[i >> 5] |= 1 << (i & 31)          # bare int shift: finding
    return bits


def half_wrapped(ids):
    return np.uint32(1) << (ids & 31)      # i64 promotion: finding


def transfer(rows):
    return jnp.asarray([1.5, 2.5])         # literal without dtype: finding
'''

TC_GOOD = '''
from typing import NamedTuple
import numpy as np
import jax.numpy as jnp


class Cluster(NamedTuple):
    alloc: np.ndarray      # f32[N, R]
    bits: np.ndarray       # u32[N, LW]
    packed: np.ndarray     # u32[P, ceil(T/32)] packed membership
    rounds: np.ndarray     # i32[]  scalar telemetry


class Snap(NamedTuple):
    cluster: Cluster


def consistent(snap):
    n = snap.cluster.alloc.shape[0]
    return snap.cluster.bits[:n]           # N-var on the N axis: fine


def gen_counter(cap):
    # justified host-only 64-bit state
    return np.zeros(cap, dtype=np.int64)  # graftlint: disable=tensor-contract -- host-only counter


def shift_ok(bits, i):
    bits[i >> 5] |= np.uint32(1 << (i & 31))
    return bits


def half_wrapped_ok(ids):
    return np.uint32(1) << (ids & 31).astype(np.uint32)


def transfer_ok(rows):
    return jnp.asarray([1, 2], dtype=np.int32)
'''


def test_tensor_contract_flags_seeded_violations():
    findings = tensorcontract.check([src("kubernetes_tpu/ops/k.py", TC_BAD)])
    msgs = {(f.symbol, f.message.split(" (")[0].split(":")[0]) for f in findings}
    assert ("Cluster.naked", "array field without a tensor contract") in msgs
    assert ("Pods.garbled", "array field without a tensor contract") in msgs
    assert any(s == "mix_axes" for s, _ in msgs)
    assert any(
        f.symbol == "mix_axes" and "declared N" in f.message
        and "'p'" in f.message
        for f in findings
    )
    assert any(
        f.symbol == "widen" and "64-bit dtype np.float64" in f.message
        for f in findings
    )
    assert any(
        f.symbol == "shift" and "bare Python int shift" in f.message
        for f in findings
    )
    assert any(
        f.symbol == "half_wrapped" and "promotes to i64" in f.message
        for f in findings
    )
    assert any(
        f.symbol == "transfer" and "without dtype" in f.message
        for f in findings
    )


def test_tensor_contract_quiet_on_conforming_code():
    assert tensorcontract.check(
        [src("kubernetes_tpu/ops/k.py", TC_GOOD)]
    ) == []


def test_tensor_contract_ignores_out_of_scope_packages():
    # same code under scheduler/ (host-side by design): quiet
    assert tensorcontract.check(
        [src("kubernetes_tpu/scheduler/k.py", TC_BAD)]
    ) == []


def test_contract_parser_grammar():
    from kubernetes_tpu.analysis import contracts as ct

    dtype, axes = ct.parse_spec(" f32[N, R]   trailing prose")
    assert dtype == "float32"
    assert [a.render() for a in axes] == ["N", "R"]
    dtype, axes = ct.parse_spec("u32[3, N, TW]  effect-major")
    assert dtype == "uint32" and axes[0].const == 3 and axes[0].sym is None
    dtype, axes = ct.parse_spec("u32[P, ceil(T/32)] packed")
    assert axes[1].ceil and axes[1].resolve({"P": 8, "T": 33}) == 2
    dtype, axes = ct.parse_spec("i32[]: scalar")
    assert dtype == "int32" and axes == ()
    assert ct.parse_spec("[C, N] missing dtype") is None
    assert ct.parse_spec("f33[N]") is None


# -- atomicity ---------------------------------------------------------------

ATOMICITY_CTA = '''
import threading

class Q:
    GUARDED_FIELDS = {"_items": "_lock", "_n": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._n = 0

    def drain(self):
        with self._lock:
            pending = self._items
        if pending:                  # check-then-act: finding
            with self._lock:
                self._items = []

    def bump(self):
        with self._lock:
            n = self._n
        with self._lock:
            self._n = n + 1          # split-rmw: finding
'''

ATOMICITY_GOOD = '''
import threading

class Q:
    GUARDED_FIELDS = {"_items": "_lock", "_n": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._n = 0

    def same_section(self):
        with self._lock:
            n = self._n
            if n > 0:                # same critical section: atomic
                self._n = n - 1

    def revalidated(self):
        with self._lock:
            n = self._n
        with self._lock:
            n = self._n              # re-captured under the lock
            self._n = n + 1

    def plain_read(self):
        with self._lock:
            n = self._n
        return n                     # no branch/write-back: telemetry

    def reviewed(self):
        with self._lock:
            n = self._n
        if n:  # graftlint: disable=atomicity -- reviewed snapshot probe
            return True
        return False
'''

ATOMICITY_CV_BAD = '''
import threading

def pump(cv, backlog):
    with cv:
        if not backlog:
            cv.wait(0.2)             # if-guarded wait: finding
        if backlog:
            return backlog.pop()
'''

ATOMICITY_CV_GOOD = '''
import threading

def pump(cv, backlog):
    with cv:
        while not backlog:
            cv.wait(0.2)             # predicate loop: fine
        return backlog.pop()

def pump_forever(cv, backlog, out):
    with cv:
        while True:                  # while-True predicate loop: fine
            if backlog:
                out.append(backlog.pop())
                continue
            cv.wait(0.5)

def event_style(stop):
    stop.wait(1.0)                   # no enclosing `with stop:` — not a cv
'''


def test_atomicity_flags_check_then_act_and_split_rmw():
    findings = atomicity.check([src("kubernetes_tpu/x.py", ATOMICITY_CTA)])
    by_symbol = {}
    for f in findings:
        by_symbol.setdefault(f.symbol, []).append(f.message)
    assert any(
        "check-then-act" in m and "'pending'" in m and "'_items'" in m
        for m in by_symbol.get("Q.drain", [])
    ), findings
    assert any(
        "split read-modify-write" in m and "'n'" in m and "'_n'" in m
        for m in by_symbol.get("Q.bump", [])
    ), findings
    assert len(findings) == 2


def test_atomicity_quiet_on_conforming_code():
    assert atomicity.check([src("kubernetes_tpu/x.py", ATOMICITY_GOOD)]) == []


def test_atomicity_flags_cv_wait_without_predicate_loop():
    findings = atomicity.check([src("kubernetes_tpu/x.py", ATOMICITY_CV_BAD)])
    assert len(findings) == 1
    assert "while-predicate loop" in findings[0].message
    assert findings[0].symbol == "pump"


def test_atomicity_quiet_on_predicate_loops():
    assert atomicity.check(
        [src("kubernetes_tpu/x.py", ATOMICITY_CV_GOOD)]
    ) == []


def test_atomicity_pins_the_dispatch_loop_shape():
    """Regression pin for the true positive the pass found in
    Store._watch_dispatch_loop: an if-guarded `shard._dispatch_cv.wait`
    whose re-check lived in the NEXT outer-loop iteration (a fresh
    acquisition).  The exact pre-fix shape must stay flagged."""
    code = '''
def _watch_dispatch_loop(store_ref, sid):
    while True:
        store = store_ref()
        if store is None:
            return
        shard = store._shards[sid]
        batch = None
        with shard._dispatch_cv:
            if not shard._dispatch_backlog:
                shard._dispatch_cv.wait(0.2)
            if shard._dispatch_backlog:
                batch = shard._dispatch_backlog.popleft()
'''
    findings = atomicity.check([src("kubernetes_tpu/api/x.py", code)])
    assert len(findings) == 1
    assert "shard._dispatch_cv" in findings[0].message


# -- lock-order (static) -----------------------------------------------------

LOCKORDER_CYCLE = '''
import threading

class A:
    def __init__(self, b):
        self._lock = threading.Lock()
        self.b = b

    def one(self):
        with self._lock:
            self.b.poke_b()        # A._lock held -> acquires B._lock

class B:
    def __init__(self, a):
        self._lock = threading.Lock()
        self.a = a

    def poke_b(self):
        with self._lock:
            pass

    def two(self):
        with self._lock:
            self.a.poke_a()        # B._lock held -> acquires A._lock

# make poke_a resolvable (unique name)
class A2(A):
    pass
'''

LOCKORDER_ACYCLIC = '''
import threading

class A:
    def __init__(self, b):
        self._lock = threading.Lock()
        self.b = b

    def one(self):
        with self._lock:
            self.b.poke_b()

class B:
    def __init__(self):
        self._lock = threading.Lock()

    def poke_b(self):
        with self._lock:
            pass
'''


def test_lockorder_flags_cycle():
    code = LOCKORDER_CYCLE + '''

def _helper(a):
    a.poke_a()
'''
    # give A a uniquely-named method that acquires its lock, called by B
    code = code.replace(
        "    def one(self):",
        "    def poke_a(self):\n"
        "        with self._lock:\n"
        "            pass\n\n"
        "    def one(self):",
    )
    findings = lockorder.check([src("kubernetes_tpu/x.py", code)])
    assert len(findings) == 1
    assert "cycle" in findings[0].message
    assert "A._lock" in findings[0].symbol and "B._lock" in findings[0].symbol


def test_lockorder_quiet_on_one_direction():
    assert lockorder.check([src("kubernetes_tpu/x.py", LOCKORDER_ACYCLIC)]) == []


def test_lockorder_suppression_cuts_edge():
    code = LOCKORDER_CYCLE.replace(
        "            self.a.poke_a()        # B._lock held -> acquires A._lock",
        "            self.a.poke_a()  # graftlint: disable=lock-order -- test",
    ).replace(
        "    def one(self):",
        "    def poke_a(self):\n"
        "        with self._lock:\n"
        "            pass\n\n"
        "    def one(self):",
    )
    assert lockorder.check([src("kubernetes_tpu/x.py", code)]) == []


# -- lock-order (runtime tracker) --------------------------------------------

# Tests that DELIBERATELY create inversions must not run while the
# session-wide tracker is armed (GRAFTLINT_LOCK_ORDER=1): the patched
# constructors double-track their locks, so the seeded inversion would
# land on the shared session tracker and fail the whole session.
_armed = os.environ.get("GRAFTLINT_LOCK_ORDER") == "1"
skip_if_armed = pytest.mark.skipif(
    _armed, reason="seeds an inversion; session-wide tracker is armed"
)


@skip_if_armed
def test_runtime_tracker_detects_inversion():
    tracker = rt.LockOrderTracker()
    a = rt.wrap(threading.Lock(), "A", tracker)
    b = rt.wrap(threading.Lock(), "B", tracker)
    with a:
        with b:
            pass
    with b:
        with a:           # inversion: B held while acquiring A
            pass
    assert tracker.inversions
    with pytest.raises(rt.LockOrderViolation):
        tracker.assert_no_inversions()


def test_runtime_tracker_quiet_on_consistent_order():
    tracker = rt.LockOrderTracker()
    a = rt.wrap(threading.Lock(), "A", tracker)
    b = rt.wrap(threading.Lock(), "B", tracker)
    for _ in range(3):
        with a:
            with b:
                pass
    tracker.assert_no_inversions()
    assert ("A", "B") in tracker.edges()


def test_runtime_tracker_ignores_reentrant_rlock():
    tracker = rt.LockOrderTracker()
    r = rt.wrap(threading.RLock(), "R", tracker)
    with r:
        with r:
            pass
    tracker.assert_no_inversions()


@skip_if_armed
def test_tracked_patches_new_locks_and_restores():
    real_lock = threading.Lock
    with rt.tracked() as tracker:
        l1 = threading.Lock()
        l2 = threading.Lock()
        assert isinstance(l1, rt.TrackedLock)
        with l1:
            with l2:
                pass
        with l2:
            with l1:
                pass
    assert threading.Lock is real_lock          # restored
    assert tracker.inversions                   # and it saw the inversion


def test_runtime_tracker_on_real_store_flow():
    """Smoke: a store + queue exercising real locks under the tracker
    records edges but no inversions (the clean-tree complement of the
    seeded tests above)."""
    with rt.tracked() as tracker:
        from kubernetes_tpu.api import store as st
        from kubernetes_tpu.api import types as api
        from kubernetes_tpu.scheduler.queue import SchedulingQueue

        store = st.Store()
        q = SchedulingQueue()
        w = store.watch("Pod")
        for i in range(4):
            pod = api.Pod(meta=api.ObjectMeta(name=f"p{i}"))
            store.create(pod)
            q.add(pod)
        batch = q.pop_batch(4, timeout=1.0)
        assert len(batch) == 4
        w.stop()
    tracker.assert_no_inversions()


# -- condition-variable integration (threading.Condition over tracked lock) --

def test_tracked_lock_supports_condition():
    with rt.tracked():
        cv = threading.Condition()
        hit = []

        def waiter():
            with cv:
                cv.wait(timeout=5)
                hit.append(True)

        t = threading.Thread(target=waiter)
        t.start()
        import time as _t

        _t.sleep(0.05)
        with cv:
            cv.notify_all()
        t.join(timeout=5)
        assert hit == [True]


# -- coherence ---------------------------------------------------------------

# fixture chaos families (the real pass reads tests/test_chaos.py from
# disk; fixtures pass the set explicitly so they never depend on CWD)
COH_FAMILIES = {"NODE_CHURN_SEEDS", "PARTIALS_SEEDS"}

COH_FAULTS = '''
KNOWN_POINTS = frozenset({"mirror.grow", "solve.partials"})
'''

COH_GOOD = '''
class Mirror:
    def __init__(self):
        self._dev = None  # resident: fault=mirror.grow chaos=NODE_CHURN_SEEDS oracle=full-resync

    def speculation_point(self):
        return (self._dev,)

    def rollback(self, point):
        (self._dev,) = point

    def invalidate(self):
        self._dev = None

    def sync(self):
        return self._dev


class Partials:
    def __init__(self):
        self._store = None  # resident: fault=solve.partials chaos=PARTIALS_SEEDS

    def speculation_point(self):
        return (self._store,)

    def rollback(self, point):
        (self._store,) = point

    def invalidate(self):
        self._store = None

    def verify(self):
        return True

    def sync(self):
        return self._store


class Sched:
    def __init__(self):
        self._mirror = Mirror()
        self._partials = Partials()

    def heal(self):
        self._partials.invalidate()
        self._mirror.invalidate()

    def bookmark(self):
        return (
            self._mirror.speculation_point(),
            self._partials.speculation_point(),
        )

    def solo(self):
        self._partials.invalidate()  # graftlint: disable=coherence -- partials-only fault
'''

COH_MISSING_ROLLBACK = '''
class Mirror:
    def __init__(self):
        self._dev = None  # resident: fault=mirror.grow chaos=NODE_CHURN_SEEDS oracle=full-resync

    def speculation_point(self):
        return (self._dev,)

    def invalidate(self):
        self._dev = None
'''

COH_BAD_FAULT = '''
class Mirror:
    def __init__(self):
        self._dev = None  # resident: fault=not.a.point chaos=NODE_CHURN_SEEDS oracle=full-resync

    def speculation_point(self):
        return (self._dev,)

    def rollback(self, point):
        (self._dev,) = point

    def invalidate(self):
        self._dev = None
'''

COH_HOT_READ = '''
from .markers import hot_path


class Mirror:
    def __init__(self):
        self._dev = None  # resident: fault=mirror.grow chaos=NODE_CHURN_SEEDS oracle=full-resync

    def speculation_point(self):
        return (self._dev,)

    def rollback(self, point):
        (self._dev,) = point

    def invalidate(self):
        self._dev = None

    def sync(self):
        return self._dev


class Solver:
    def __init__(self):
        self._mirror = Mirror()

    @hot_path
    def solve(self):
        return self._mirror._dev
'''

COH_CHOKE_BAD = '''
class Mirror:
    def __init__(self):
        self._dev = None  # resident: fault=mirror.grow chaos=NODE_CHURN_SEEDS oracle=full-resync

    def speculation_point(self):
        return (self._dev,)

    def rollback(self, point):
        (self._dev,) = point

    def invalidate(self):
        self._dev = None


class Partials:
    def __init__(self):
        self._store = None  # resident: fault=solve.partials chaos=PARTIALS_SEEDS oracle=resync

    def speculation_point(self):
        return (self._store,)

    def rollback(self, point):
        (self._store,) = point

    def invalidate(self):
        self._store = None


class Sched:
    def __init__(self):
        self._mirror = Mirror()
        self._partials = Partials()

    def retry(self):
        self._partials.invalidate()
'''

COH_REBUILD_CACHED = '''
# coherence: rebuilt-per-solve -- derives from this snapshot only
def prep_grid(cluster):
    return cluster


class Solver:
    def __init__(self, cluster):
        self._grid = prep_grid(cluster)
'''

COH_REBUILD_PERSISTS = '''
# coherence: rebuilt-per-solve -- derives from this snapshot only
def prep_grid(cluster, scratch):
    scratch.grid = cluster
    return cluster
'''

COH_REBUILD_UNDECLARED = '''
def prep_spread(cluster):
    return cluster
'''


def test_coherence_clean_on_conforming_tree():
    files = [
        src("kubernetes_tpu/models/m.py", COH_GOOD),
        src("kubernetes_tpu/testing/faults.py", COH_FAULTS),
    ]
    assert coherence.check(files, chaos_families=COH_FAMILIES) == []


def test_coherence_flags_missing_rollback_wire():
    files = [src("kubernetes_tpu/models/m.py", COH_MISSING_ROLLBACK)]
    findings = coherence.check(files, chaos_families=COH_FAMILIES)
    assert len(findings) == 1
    f = findings[0]
    assert f.check == "coherence"
    assert f.symbol == "Mirror"
    assert "missing discipline method 'rollback'" in f.message


def test_coherence_flags_unregistered_fault_point():
    files = [
        src("kubernetes_tpu/models/m.py", COH_BAD_FAULT),
        src("kubernetes_tpu/testing/faults.py", COH_FAULTS),
    ]
    findings = coherence.check(files, chaos_families=COH_FAMILIES)
    assert len(findings) == 1
    assert "'not.a.point' is not declared" in findings[0].message


def test_coherence_flags_unknown_chaos_family():
    bad = COH_BAD_FAULT.replace("not.a.point", "mirror.grow").replace(
        "NODE_CHURN_SEEDS", "NOPE_SEEDS"
    )
    files = [
        src("kubernetes_tpu/models/m.py", bad),
        src("kubernetes_tpu/testing/faults.py", COH_FAULTS),
    ]
    findings = coherence.check(files, chaos_families=COH_FAMILIES)
    assert len(findings) == 1
    assert "'NOPE_SEEDS' not found" in findings[0].message


def test_coherence_flags_hot_path_resident_read():
    files = [src("kubernetes_tpu/models/m.py", COH_HOT_READ)]
    findings = coherence.check(files, chaos_families=COH_FAMILIES)
    assert len(findings) == 1
    f = findings[0]
    assert f.symbol == "Solver.solve"
    assert "reads resident field 'Mirror._dev' directly" in f.message


def test_coherence_flags_asymmetric_choke_point():
    files = [src("kubernetes_tpu/models/m.py", COH_CHOKE_BAD)]
    findings = coherence.check(files, chaos_families=COH_FAMILIES)
    assert len(findings) == 1
    f = findings[0]
    assert f.symbol == "Sched.retry"
    assert "invalidate() on Partials but not on Mirror" in f.message


def test_coherence_suppression_covers_justified_solo_site():
    # COH_GOOD's Sched.solo invalidates one resident with a justified
    # disable on the call line — exercised by the clean test above; here
    # the same site WITHOUT the pragma must be flagged
    stripped = COH_GOOD.replace(
        "  # graftlint: disable=coherence -- partials-only fault", ""
    )
    files = [
        src("kubernetes_tpu/models/m.py", stripped),
        src("kubernetes_tpu/testing/faults.py", COH_FAULTS),
    ]
    findings = coherence.check(files, chaos_families=COH_FAMILIES)
    assert [f.symbol for f in findings] == ["Sched.solo"]


def test_coherence_flags_rebuild_cached_on_attribute():
    files = [src("kubernetes_tpu/ops/o.py", COH_REBUILD_CACHED)]
    findings = coherence.check(files, chaos_families=COH_FAMILIES)
    assert len(findings) == 1
    assert "silently caching across solves" in findings[0].message


def test_coherence_flags_rebuild_persisting_state():
    files = [src("kubernetes_tpu/ops/o.py", COH_REBUILD_PERSISTS)]
    findings = coherence.check(files, chaos_families=COH_FAMILIES)
    assert len(findings) == 1
    assert "persists state through an attribute store" in findings[0].message


def test_coherence_requires_declaration_on_known_prep_builders():
    files = [src("kubernetes_tpu/ops/o.py", COH_REBUILD_UNDECLARED)]
    findings = coherence.check(files, chaos_families=COH_FAMILIES)
    assert len(findings) == 1
    assert "must carry '# coherence: rebuilt-per-solve'" in findings[0].message


def test_coherence_seeded_registry_requires_annotation():
    code = '''
class DeviceClusterMirror:
    def __init__(self):
        self._dev = None
'''
    files = [src("kubernetes_tpu/models/m.py", code)]
    findings = coherence.check(files, chaos_families=COH_FAMILIES)
    assert len(findings) == 1
    assert "declares no '# resident:'" in findings[0].message


# -- obligations -------------------------------------------------------------
# fixture tests always pass test_files=[] explicitly so the fault-spec
# disk scan never runs against the real tests/ tree from a fixture

def _obl(relpath, code):
    return obligations.check([src(relpath, code)], test_files=[])


OBL_POD_BAD = '''
class S:
    def run_once(self):
        batch = self.queue.pop_batch(64, timeout=0.1)
        if self.lost_leadership():
            return
        for info in batch:
            self.queue.requeue_backoff(info)
'''

OBL_POD_GOOD = '''
class S:
    def run_once(self):
        batch = self.queue.pop_batch(64, timeout=0.1)
        if not batch:
            return
        if self.lost_leadership():
            for info in batch:
                self.queue.requeue_backoff(info)
            return
        self._dispatch_batch(batch)
'''


def test_obligations_flags_pod_batch_dropped_on_branch():
    findings = _obl("kubernetes_tpu/scheduler/scheduler.py", OBL_POD_BAD)
    assert len(findings) == 1
    f = findings[0]
    assert f.check == "obligations"
    assert "pod obligation on 'batch'" in f.message
    assert "return" in f.message


def test_obligations_pod_clean_on_refined_branches_and_loop_requeue():
    assert _obl("kubernetes_tpu/scheduler/scheduler.py", OBL_POD_GOOD) == []


OBL_SLOT_RETURN_BAD = '''
class D:
    def dispatch(self, snap):
        self.arbiter.acquire()
        if snap is None:
            return None
        fut = self.submit(snap)
        self.arbiter.release()
        return fut
'''

OBL_SLOT_RAISE_BAD = '''
class D:
    def dispatch(self, snap):
        self.arbiter.acquire()
        if self.closed:
            raise RuntimeError("closed")
        self.arbiter.release()
'''

OBL_SLOT_GOOD = '''
class D:
    def dispatch(self, snap):
        self.arbiter.acquire()
        try:
            fut = self.submit(snap)
        except Exception:
            self.arbiter.release()
            raise
        ds = DeviceSolve(fut)
        ds._slot = self.arbiter
        return ds
'''


def test_obligations_flags_slot_leak_on_early_return():
    findings = _obl(
        "kubernetes_tpu/models/batch_scheduler.py", OBL_SLOT_RETURN_BAD
    )
    assert len(findings) == 1
    assert "slot obligation on 'self.arbiter'" in findings[0].message


def test_obligations_flags_slot_leak_on_raise_edge():
    findings = _obl(
        "kubernetes_tpu/models/batch_scheduler.py", OBL_SLOT_RAISE_BAD
    )
    assert len(findings) == 1
    assert "exception" in findings[0].message


def test_obligations_slot_clean_on_handler_release_and_ownership_store():
    assert _obl(
        "kubernetes_tpu/models/batch_scheduler.py", OBL_SLOT_GOOD
    ) == []


OBL_SEAT_DISCARDED = '''
class H:
    def handle(self, subject, verb):
        self.apf.acquire(subject, verb)
        self.process(subject)
'''

OBL_SEAT_GOOD = '''
class H:
    def handle(self, subject, verb):
        seat = self.apf.acquire(subject, verb)
        if seat is None:
            return False
        try:
            return self.process(subject)
        finally:
            seat.release()
'''


def test_obligations_flags_discarded_seat_result():
    findings = _obl("kubernetes_tpu/api/server.py", OBL_SEAT_DISCARDED)
    assert len(findings) == 1
    assert "discards the obligated result" in findings[0].message


def test_obligations_seat_clean_on_none_guard_and_finally():
    assert _obl("kubernetes_tpu/api/server.py", OBL_SEAT_GOOD) == []


OBL_ASSUME_BAD = '''
class S:
    def stage(self, info, node):
        self.cache.assume(info.pod, node)
        verdict = self.permit(info.pod, node)
        if verdict == "reject":
            self.queue.requeue_backoff(info)
            return None
        return node
'''

OBL_ASSUME_GOOD = '''
class S:
    def stage(self, info, node):
        self.cache.assume(info.pod, node)
        verdict = self.permit(info.pod, node)
        if verdict == "reject":
            self.cache.forget(info.pod)
            self.queue.requeue_backoff(info)
            return None
        return (info, node)
'''


def test_obligations_flags_assume_without_forget_on_reject():
    findings = _obl("kubernetes_tpu/scheduler/scheduler.py", OBL_ASSUME_BAD)
    assert len(findings) == 1
    assert "assume obligation on 'info.pod'" in findings[0].message


def test_obligations_assume_clean_on_forget_and_return_transfer():
    assert _obl(
        "kubernetes_tpu/scheduler/scheduler.py", OBL_ASSUME_GOOD
    ) == []


OBL_COUNTER_BAD = '''
class S:
    def hand_off(self, entries):
        with self._cv:
            self._stream_inflight += 1
        if not entries:
            return
        self.pool.submit(self.deliver, entries)
'''

OBL_COUNTER_GOOD = '''
class S:
    def hand_off(self, entries):
        with self._cv:
            self._stream_inflight += 1
        try:
            self.pool.submit(self._commit_stream_subwave, entries)
        except BaseException:
            with self._cv:
                self._stream_inflight -= 1
            raise
'''


def test_obligations_flags_inflight_increment_without_decrement():
    findings = _obl("kubernetes_tpu/scheduler/scheduler.py", OBL_COUNTER_BAD)
    assert len(findings) == 1
    assert "stream_inflight" in findings[0].message


def test_obligations_counter_clean_on_handoff_and_failure_decrement():
    assert _obl(
        "kubernetes_tpu/scheduler/scheduler.py", OBL_COUNTER_GOOD
    ) == []


OBL_FAULT_BAD = '''
from kubernetes_tpu.testing import faults

def test_chaos_run(tmp_path):
    reg = faults.FaultRegistry(seed=1)
    faults.arm(reg)
    run_cluster(tmp_path)
    faults.disarm()
'''

OBL_FAULT_GOOD = '''
from kubernetes_tpu.testing import faults

def test_chaos_run(tmp_path):
    reg = faults.FaultRegistry(seed=1)
    faults.arm(reg)
    try:
        run_cluster(tmp_path)
    finally:
        faults.disarm()
'''

OBL_FAULT_CTX_GOOD = '''
from kubernetes_tpu.testing import faults

def test_chaos_run(tmp_path):
    with faults.armed(faults.FaultRegistry(seed=1)):
        run_cluster(tmp_path)
'''


def test_obligations_flags_unprotected_armed_registry():
    """Any call between arm() and disarm() is a potential raise edge —
    a fault registry exists to make arbitrary calls raise."""
    findings = obligations.check(
        [], test_files=[src("tests/test_fixture_chaos.py", OBL_FAULT_BAD)]
    )
    assert len(findings) == 1
    assert "fault obligation" in findings[0].message


def test_obligations_fault_clean_on_try_finally_and_armed_context():
    for code in (OBL_FAULT_GOOD, OBL_FAULT_CTX_GOOD):
        findings = obligations.check(
            [], test_files=[src("tests/test_fixture_chaos.py", code)]
        )
        assert findings == [], code


def test_obligations_suppression_covers_justified_site():
    code = OBL_SLOT_RETURN_BAD.replace(
        "self.arbiter.acquire()",
        "self.arbiter.acquire()  # graftlint: disable=obligations"
        " -- slot handed to the watchdog out of band",
    )
    assert _obl("kubernetes_tpu/models/batch_scheduler.py", code) == []


def test_obligations_summary_propagates_through_local_helper():
    """A helper whose body discharges a kind summarizes as discharging
    it — calling the helper with the obligated value counts."""
    code = '''
class S:
    def _park(self, info):
        self.queue.requeue_backoff(info)

    def run_once(self):
        batch = self.queue.pop_batch(64, timeout=0.1)
        for info in batch:
            self._park(info)
'''
    assert _obl("kubernetes_tpu/scheduler/scheduler.py", code) == []


# -- the real-tree gate ------------------------------------------------------

def test_tree_is_clean_beyond_baseline():
    findings = run_all(REPO_ROOT)
    baseline = load_baseline(default_baseline_path())
    new, stale = apply_baseline(findings, baseline)
    assert not new, "new graftlint findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert not stale, f"stale baseline entries (remove them): {stale}"


def test_tree_declares_guarded_state_and_roots():
    """The annotations the suite enforces must actually exist — a
    refactor that silently drops GUARDED_FIELDS or the @hot_path roots
    would turn the passes into no-ops."""
    from kubernetes_tpu.analysis import load_sources
    files = load_sources(REPO_ROOT, ["kubernetes_tpu"])
    by_path = {f.relpath.replace(os.sep, "/"): f for f in files}
    for path in (
        "kubernetes_tpu/api/store.py",
        "kubernetes_tpu/scheduler/cache.py",
        "kubernetes_tpu/scheduler/queue.py",
        "kubernetes_tpu/scheduler/waitingpods.py",
    ):
        assert (
            "GUARDED_FIELDS" in by_path[path].text
            or "guarded_by:" in by_path[path].text
        ), f"{path} lost its guarded-by declarations"
    table = purity._collect_functions(
        files, "kubernetes_tpu", purity.DEFAULT_SCOPE
    )
    roots = {q.split(":")[-1] for q, fi in table.items() if fi.is_root}
    for expected in (
        "greedy_assign", "wavefront_assign", "auction_assign",
        "TPUBatchScheduler._dispatch",
        "TPUBatchScheduler.solve_encoded_async",
    ):
        assert expected in roots, f"@hot_path root {expected} missing"


def test_baseline_has_no_unexplained_entries():
    """ISSUE acceptance: the checked-in baseline is empty (every true
    positive the passes found was fixed, not grandfathered)."""
    assert load_baseline(default_baseline_path()) == []
