"""StatefulSet, DaemonSet, and CronJob controllers.

References: pkg/controller/statefulset/stateful_set_control.go,
pkg/controller/daemon/daemon_controller.go,
pkg/controller/cronjob/cronjob_controllerv2.go.
"""

import time

import pytest

from kubernetes_tpu.api import store as st
from kubernetes_tpu.api import types as api
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.cronjob import (
    matches,
    most_recent_fire,
    parse_cron,
)
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _template(labels, cpu=100):
    return api.PodTemplateSpec(
        meta=api.ObjectMeta(name="", labels=dict(labels)),
        spec=api.PodSpec(
            containers=[
                api.Container(
                    name="c0", requests={api.CPU: cpu, api.MEMORY: 64 * MI}
                )
            ]
        ),
    )


def _wait(cond, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def _mark_running(store, names=None):
    pods, _ = store.list("Pod")
    for p in pods:
        if names is not None and p.meta.name not in names:
            continue
        if not p.spec.node_name or p.status.phase != "Running":
            p.spec.node_name = p.spec.node_name or "n0"
            p.status.phase = "Running"
            try:
                store.update(p)
            except (st.Conflict, st.NotFound):
                pass


@pytest.fixture
def cm_store():
    store = st.Store()
    cm = ControllerManager(store).start()
    yield cm, store
    cm.stop()


def test_statefulset_ordered_creation_and_identity(cm_store):
    cm, store = cm_store
    sts = api.StatefulSet(
        meta=api.ObjectMeta(name="db"),
        spec=api.StatefulSetSpec(
            replicas=3,
            selector=api.LabelSelector(match_labels={"app": "db"}),
            template=_template({"app": "db"}),
            volume_claim_templates=[
                api.PersistentVolumeClaim(
                    meta=api.ObjectMeta(name="data"),
                    spec=api.PersistentVolumeClaimSpec(
                        storage_class_name="fast",
                        resources={api.STORAGE: GI},
                    ),
                )
            ],
        ),
    )
    store.create(sts)
    # OrderedReady: db-0 first; db-1 must NOT appear until db-0 is ready
    assert _wait(lambda: any(
        p.meta.name == "db-0" for p in store.list("Pod")[0]
    ))
    time.sleep(0.5)
    names = {p.meta.name for p in store.list("Pod")[0]}
    assert "db-1" not in names, names
    _mark_running(store, {"db-0"})
    assert _wait(lambda: any(
        p.meta.name == "db-1" for p in store.list("Pod")[0]
    ))
    _mark_running(store)
    assert _wait(lambda: {"db-0", "db-1", "db-2"} <= {
        p.meta.name for p in store.list("Pod")[0]
    })
    # one PVC per ordinal, named <tpl>-<set>-<i>
    pvcs = {c.meta.name for c in store.list("PersistentVolumeClaim")[0]}
    assert {"data-db-0", "data-db-1", "data-db-2"} <= pvcs
    # pod identity: db-1 deleted -> recreated under the SAME name with
    # the SAME claim
    store.delete("Pod", "db-1")
    assert _wait(lambda: any(
        p.meta.name == "db-1" for p in store.list("Pod")[0]
    ))
    pod = store.get("Pod", "db-1")
    assert pod.spec.volumes[0].persistent_volume_claim == "data-db-1"
    # scale down removes the HIGHEST ordinal, claims survive
    sts = store.get("StatefulSet", "db")
    sts.spec.replicas = 2
    store.update(sts)
    assert _wait(lambda: {"db-0", "db-1"} == {
        p.meta.name for p in store.list("Pod")[0]
        if p.meta.name.startswith("db-")
    })
    assert "data-db-2" in {
        c.meta.name for c in store.list("PersistentVolumeClaim")[0]
    }


def test_daemonset_one_pod_per_eligible_node(cm_store):
    # daemon pods now route THROUGH the scheduler (per-node affinity
    # pin, daemonset_util.go semantics) — a real Scheduler binds them
    from kubernetes_tpu.scheduler import Scheduler

    cm, store = cm_store
    sched = Scheduler(store)
    sched.start()
    for i in range(3):
        store.create(make_node(f"n{i}").capacity(cpu_milli=4000, pods=10).obj())
    tainted = make_node("n-tainted").capacity(cpu_milli=4000, pods=10) \
        .taint("dedicated", "x", api.NO_SCHEDULE).obj()
    store.create(tainted)
    ds = api.DaemonSet(
        meta=api.ObjectMeta(name="agent"),
        spec=api.DaemonSetSpec(
            selector=api.LabelSelector(match_labels={"app": "agent"}),
            template=_template({"app": "agent"}),
        ),
    )
    store.create(ds)
    try:
        # the scheduler binds each daemon pod onto its pinned node
        assert _wait(
            lambda: {p.spec.node_name for p in store.list("Pod")[0]}
            == {"n0", "n1", "n2"},
            timeout=60,
        )
        # a new node joining gets a daemon pod
        store.create(make_node("n9").capacity(cpu_milli=4000, pods=10).obj())
        assert _wait(lambda: "n9" in {
            p.spec.node_name for p in store.list("Pod")[0]
        }, timeout=60)
        # node leaving: its pod is reaped (nodelifecycle/GC semantics are
        # store-side here — the controller deletes pods on vanished nodes)
        store.delete("Node", "n1", namespace="")
        assert _wait(lambda: "n1" not in {
            p.spec.node_name for p in store.list("Pod")[0]
        })
        got = store.get("DaemonSet", "agent")
        assert got.status.desired_number_scheduled == 3
    finally:
        sched.stop()


def test_daemonset_toleration_allows_tainted_node(cm_store):
    from kubernetes_tpu.scheduler import Scheduler

    cm, store = cm_store
    sched = Scheduler(store)
    sched.start()
    store.create(
        make_node("gpu").capacity(cpu_milli=4000, pods=10)
        .taint("dedicated", "gpu", api.NO_SCHEDULE).obj()
    )
    tmpl = _template({"app": "gpu-agent"})
    tmpl.spec.tolerations.append(
        api.Toleration(key="dedicated", op=api.OP_EQUAL, value="gpu",
                       effect=api.NO_SCHEDULE)
    )
    ds = api.DaemonSet(
        meta=api.ObjectMeta(name="gpu-agent"),
        spec=api.DaemonSetSpec(
            selector=api.LabelSelector(match_labels={"app": "gpu-agent"}),
            template=tmpl,
        ),
    )
    store.create(ds)
    try:
        assert _wait(lambda: {
            p.spec.node_name for p in store.list("Pod")[0]
        } == {"gpu"}, timeout=60)
    finally:
        sched.stop()


def test_daemonset_full_node_rejects_daemon_pod(cm_store):
    """VERDICT r4 #9 acceptance: a full node REJECTS its daemon pod
    (fit kernels apply) instead of silently overcommitting."""
    from kubernetes_tpu.scheduler import Scheduler

    cm, store = cm_store
    sched = Scheduler(store)
    sched.start()
    store.create(make_node("full").capacity(cpu_milli=200, pods=10).obj())
    store.create(make_node("roomy").capacity(cpu_milli=4000, pods=10).obj())
    ds = api.DaemonSet(
        meta=api.ObjectMeta(name="heavy"),
        spec=api.DaemonSetSpec(
            selector=api.LabelSelector(match_labels={"app": "heavy"}),
            template=_template({"app": "heavy"}, cpu=500),
        ),
    )
    store.create(ds)
    try:
        # the roomy node binds; the full node's pod stays Pending with a
        # FailedScheduling event
        assert _wait(lambda: any(
            p.spec.node_name == "roomy" for p in store.list("Pod")[0]
        ), timeout=60)
        full_pod = next(
            p for p in store.list("Pod")[0]
            if p.meta.name == "heavy-full"
        )
        assert not full_pod.spec.node_name

        def rejected():
            return any(
                e.reason == "FailedScheduling"
                and "heavy-full" in e.meta.name
                for e in store.list("Event")[0]
            )
        assert _wait(rejected, timeout=30)
    finally:
        sched.stop()


def test_cron_parser_and_fire_times():
    fields = parse_cron("*/15 2 * * *")
    t = time.mktime((2026, 7, 30, 2, 45, 0, 0, 0, -1))
    assert matches(fields, t)
    assert not matches(fields, t + 60)
    assert not matches(fields, time.mktime((2026, 7, 30, 3, 0, 0, 0, 0, -1)))
    # most recent fire within a window
    now = time.mktime((2026, 7, 30, 2, 50, 0, 0, 0, -1))
    since = now - 3600
    fire = most_recent_fire(fields, since, now)
    assert fire == time.mktime((2026, 7, 30, 2, 45, 0, 0, 0, -1))
    with pytest.raises(ValueError):
        parse_cron("* * * *")
    with pytest.raises(ValueError):
        parse_cron("99 * * * *")


def test_cronjob_fires_and_respects_forbid(cm_store):
    cm, store = cm_store
    ctrl = cm.controllers["CronJob"]
    # a fake clock the test advances minute by minute
    now = {"t": time.time()}
    ctrl.clock = lambda: now["t"]
    cj = api.CronJob(
        meta=api.ObjectMeta(name="tick"),
        spec=api.CronJobSpec(
            schedule="* * * * *",  # every minute
            concurrency_policy="Forbid",
            job_template=api.JobSpec(
                parallelism=1, completions=1,
                template=_template({"app": "tick"}),
            ),
        ),
    )
    store.create(cj)
    assert _wait(lambda: len(store.list("Job")[0]) == 1, timeout=15)
    # Forbid: while the job is active, the next minute must NOT fire
    now["t"] += 60
    time.sleep(0.5)
    ctrl.enqueue(store.get("CronJob", "tick"))
    time.sleep(1.0)
    assert len(store.list("Job")[0]) == 1
    # complete the job: the next minute fires a second one
    job = store.list("Job")[0][0]
    job.status.completion_time = now["t"]
    store.update(job)
    now["t"] += 60
    assert _wait(lambda: len(store.list("Job")[0]) == 2, timeout=15)


def test_cron_dom_dow_or_rule():
    """Vixie-cron: both day fields restricted -> OR; one starred -> AND."""
    both = parse_cron("0 0 13 * 5")
    # Fri 2026-07-17 (a Friday, not the 13th)
    assert matches(both, time.mktime((2026, 7, 17, 0, 0, 0, 0, 0, -1)))
    # Mon 2026-07-13 (the 13th, not a Friday)
    assert matches(both, time.mktime((2026, 7, 13, 0, 0, 0, 0, 0, -1)))
    assert not matches(both, time.mktime((2026, 7, 14, 0, 0, 0, 0, 0, -1)))
    dow_only = parse_cron("0 0 * * 5")
    assert matches(dow_only, time.mktime((2026, 7, 17, 0, 0, 0, 0, 0, -1)))
    assert not matches(dow_only, time.mktime((2026, 7, 13, 0, 0, 0, 0, 0, -1)))


def test_statefulset_rolling_update_one_at_a_time(cm_store):
    """Template change: at most ONE replica down at a time; each ordinal
    is recreated and readied before the next is touched (review finding:
    the stale sweep must not drain the whole set)."""
    cm, store = cm_store
    sts = api.StatefulSet(
        meta=api.ObjectMeta(name="kv"),
        spec=api.StatefulSetSpec(
            replicas=3,
            selector=api.LabelSelector(match_labels={"app": "kv"}),
            template=_template({"app": "kv"}),
        ),
    )
    store.create(sts)

    def pump():
        _mark_running(store)
        pods = [p for p in store.list("Pod")[0]
                if p.meta.name.startswith("kv-")]
        return pods

    assert _wait(lambda: len(pump()) == 3, timeout=20)
    sts = store.get("StatefulSet", "kv")
    sts.spec.template = _template({"app": "kv"}, cpu=200)
    store.update(sts)
    low_water = 3
    deadline = time.time() + 30
    done = False
    while time.time() < deadline and not done:
        pods = pump()
        low_water = min(low_water, len(pods))
        done = len(pods) == 3 and all(
            p.resource_requests()[api.CPU] == 200 for p in pods
        )
        time.sleep(0.05)
    assert done, [(p.meta.name, p.resource_requests()) for p in pump()]
    assert low_water >= 2, f"rollout drained to {low_water} replicas"


def test_daemonset_survives_cordon(cm_store):
    """Cordoning a node must NOT evict its daemon pod — the controller
    implicitly tolerates node.kubernetes.io/unschedulable (review
    finding; daemon_controller.go AddOrUpdateDaemonPodTolerations)."""
    cm, store = cm_store
    store.create(make_node("n0").capacity(cpu_milli=4000, pods=10).obj())
    ds = api.DaemonSet(
        meta=api.ObjectMeta(name="agent"),
        spec=api.DaemonSetSpec(
            selector=api.LabelSelector(match_labels={"app": "agent"}),
            template=_template({"app": "agent"}),
        ),
    )
    store.create(ds)
    assert _wait(lambda: len(store.list("Pod")[0]) == 1)
    node = store.get("Node", "n0", namespace="")
    node.spec.unschedulable = True
    store.update(node)
    time.sleep(1.0)
    assert len(store.list("Pod")[0]) == 1, "cordon evicted the daemon pod"


def test_daemonset_toleration_effect_must_match(cm_store):
    """A NoExecute-only toleration must not cover a NoSchedule taint
    (review finding)."""
    cm, store = cm_store
    store.create(
        make_node("t").capacity(cpu_milli=4000, pods=10)
        .taint("dedicated", "x", api.NO_SCHEDULE).obj()
    )
    tmpl = _template({"app": "a"})
    tmpl.spec.tolerations.append(
        api.Toleration(key="dedicated", op=api.OP_EQUAL, value="x",
                       effect=api.NO_EXECUTE)
    )
    store.create(api.DaemonSet(
        meta=api.ObjectMeta(name="a"),
        spec=api.DaemonSetSpec(
            selector=api.LabelSelector(match_labels={"app": "a"}),
            template=tmpl,
        ),
    ))
    time.sleep(1.0)
    assert len(store.list("Pod")[0]) == 0


def test_failed_job_unblocks_forbid_cronjob(cm_store):
    """A job whose pods exceed backoffLimit gets completion_time, so a
    Forbid CronJob keeps firing (review finding)."""
    cm, store = cm_store
    ctrl = cm.controllers["CronJob"]
    now = {"t": time.time()}
    ctrl.clock = lambda: now["t"]
    cj = api.CronJob(
        meta=api.ObjectMeta(name="flaky"),
        spec=api.CronJobSpec(
            schedule="* * * * *",
            concurrency_policy="Forbid",
            job_template=api.JobSpec(
                parallelism=1, completions=1,
                template=_template({"app": "flaky"}),
            ),
        ),
    )
    cj.spec.job_template.backoff_limit = 0
    store.create(cj)
    assert _wait(lambda: len(store.list("Job")[0]) == 1, timeout=15)
    # all its pods fail -> the job must become terminal
    def fail_pods():
        for p in store.list("Pod")[0]:
            if p.status.phase != "Failed":
                p.status.phase = "Failed"
                try:
                    store.update(p)
                except (st.Conflict, st.NotFound):
                    pass
        jobs = store.list("Job")[0]
        return jobs and jobs[0].status.completion_time is not None
    assert _wait(fail_pods, timeout=15), "failed job never became terminal"
    now["t"] += 60
    assert _wait(lambda: len(store.list("Job")[0]) == 2, timeout=15), \
        "Forbid cron stuck behind a failed job"
