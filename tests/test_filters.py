"""Fused filter kernel tests (each case pins one plugin's semantics)."""

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops import filters, schema
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod


def _feasible(nodes, pods, bound=()):
    snap, meta = schema.SnapshotBuilder().build(nodes, pods, bound_pods=bound)
    mask = filters.feasible_batch(snap.cluster, snap.pods, snap.selectors)
    return np.asarray(mask)[: len(pods), : len(nodes)], meta


def test_resources_fit():
    nodes = [
        make_node("small").capacity(cpu_milli=1000, mem=1 * GI, pods=10).obj(),
        make_node("big").capacity(cpu_milli=8000, mem=16 * GI, pods=10).obj(),
    ]
    pods = [make_pod("p").req(cpu_milli=2000, mem=2 * GI).obj()]
    m, _ = _feasible(nodes, pods)
    assert m.tolist() == [[False, True]]


def test_fit_boundary_exact():
    """requested + pod == allocatable fits (<=, fit.go:446)."""
    nodes = [make_node("n").capacity(cpu_milli=1000, mem=1 * GI, pods=10).obj()]
    pods = [make_pod("p").req(cpu_milli=1000, mem=1 * GI).obj()]
    m, _ = _feasible(nodes, pods)
    assert m.tolist() == [[True]]


def test_pod_count_capacity():
    nodes = [make_node("n").capacity(cpu_milli=64000, mem=64 * GI, pods=2).obj()]
    bound = [
        make_pod("b0").node_name("n").obj(),
        make_pod("b1").node_name("n").obj(),
    ]
    pods = [make_pod("p").obj()]
    m, _ = _feasible(nodes, pods, bound=bound)
    assert m.tolist() == [[False]]


def test_node_name():
    nodes = [make_node("a").obj(), make_node("b").obj()]
    pods = [make_pod("p").node_name("b").obj(), make_pod("q").node_name("ghost").obj()]
    m, _ = _feasible(nodes, pods)
    assert m.tolist() == [[False, True], [False, False]]


def test_taints_and_tolerations():
    nodes = [
        make_node("tainted").taint("dedicated", "gpu", api.NO_SCHEDULE).obj(),
        make_node("clean").obj(),
        make_node("prefer").taint("x", "y", api.PREFER_NO_SCHEDULE).obj(),
    ]
    pods = [
        make_pod("plain").obj(),
        make_pod("tolerant").toleration("dedicated", api.OP_EQUAL, "gpu", api.NO_SCHEDULE).obj(),
        make_pod("tolerate-all").toleration().obj(),
    ]
    m, _ = _feasible(nodes, pods)
    # PreferNoSchedule never blocks (scoring only)
    assert m.tolist() == [
        [False, True, True],
        [True, True, True],
        [True, True, True],
    ]


def test_unschedulable_node_and_toleration():
    nodes = [make_node("cordoned").unschedulable().obj(), make_node("ok").obj()]
    pods = [
        make_pod("plain").obj(),
        make_pod("tol").toleration(api.TAINT_NODE_UNSCHEDULABLE, api.OP_EXISTS).obj(),
    ]
    m, _ = _feasible(nodes, pods)
    assert m.tolist() == [[False, True], [True, True]]


def test_node_selector_and_affinity():
    nodes = [
        make_node("a").zone("us-a").obj(),
        make_node("b").zone("us-b").obj(),
        make_node("c").obj(),  # no zone label
    ]
    pods = [
        make_pod("sel").node_selector_kv(api.LABEL_ZONE, "us-a").obj(),
        make_pod("in").required_affinity(api.LABEL_ZONE, api.OP_IN, ["us-b"]).obj(),
        # NotIn matches nodes without the key at all (selector.go semantics)
        make_pod("notin").required_affinity(api.LABEL_ZONE, api.OP_NOT_IN, ["us-a"]).obj(),
        make_pod("exists").required_affinity(api.LABEL_ZONE, api.OP_EXISTS).obj(),
        make_pod("absent").required_affinity(api.LABEL_ZONE, api.OP_DOES_NOT_EXIST).obj(),
        # In naming a value no node carries matches nowhere
        make_pod("ghost").required_affinity(api.LABEL_ZONE, api.OP_IN, ["mars"]).obj(),
    ]
    m, _ = _feasible(nodes, pods)
    assert m.tolist() == [
        [True, False, False],
        [False, True, False],
        [False, True, True],
        [True, True, False],
        [False, False, True],
        [False, False, False],
    ]


def test_or_of_terms():
    nodes = [make_node("a").zone("z1").obj(), make_node("b").zone("z2").obj(),
             make_node("c").zone("z3").obj()]
    pods = [
        make_pod("p")
        .required_affinity(api.LABEL_ZONE, api.OP_IN, ["z1"])
        .required_affinity(api.LABEL_ZONE, api.OP_IN, ["z3"])
        .obj()
    ]
    m, _ = _feasible(nodes, pods)
    assert m.tolist() == [[True, False, True]]


def test_node_selector_ands_with_affinity_terms():
    """spec.nodeSelector must hold in addition to every affinity term."""
    nodes = [
        make_node("a").zone("z1").label("disk", "ssd").obj(),
        make_node("b").zone("z1").obj(),
    ]
    pods = [
        make_pod("p")
        .node_selector_kv("disk", "ssd")
        .required_affinity(api.LABEL_ZONE, api.OP_IN, ["z1"])
        .obj()
    ]
    m, _ = _feasible(nodes, pods)
    assert m.tolist() == [[True, False]]


def test_host_ports():
    nodes = [make_node("n0").obj(), make_node("n1").obj()]
    bound = [make_pod("b").host_port(8080).node_name("n0").obj()]
    pods = [
        make_pod("p").host_port(8080).obj(),
        make_pod("q").host_port(8080, protocol="UDP").obj(),
    ]
    m, _ = _feasible(nodes, pods, bound=bound)
    assert m.tolist() == [[False, True], [True, True]]


def test_gt_lt_operators():
    nodes = [
        make_node("n0").label("cores", "8").obj(),
        make_node("n1").label("cores", "32").obj(),
    ]
    pods = [make_pod("p").required_affinity("cores", api.OP_GT, ["16"]).obj()]
    m, _ = _feasible(nodes, pods)
    assert m.tolist() == [[False, True]]


def test_fit_ignores_resources_pod_does_not_request():
    """A node over-committed on a scalar resource stays feasible for pods
    that don't request it (fit.go checks only podRequest > 0)."""
    nodes = [make_node("n").capacity(cpu_milli=4000, mem=8 * GI, pods=10).obj()]
    # bound pod requests a gpu the node doesn't expose (requested 1 > alloc 0)
    bound = [make_pod("b").req(**{"example.com/gpu": 1}).node_name("n").obj()]
    pods = [make_pod("p").req(cpu_milli=100).obj()]
    m, _ = _feasible(nodes, pods, bound=bound)
    assert m.tolist() == [[True]]


def test_gt_with_unparseable_values():
    """Non-numeric label values / bounds never match Gt/Lt — and never
    crash the batch encode."""
    nodes = [
        make_node("num").label("cores", "32").obj(),
        make_node("alpha").label("cores", "lots").obj(),
    ]
    pods = [
        make_pod("p").required_affinity("cores", api.OP_GT, ["16"]).obj(),
        make_pod("bad").required_affinity("cores", api.OP_GT, ["much"]).obj(),
    ]
    m, _ = _feasible(nodes, pods)
    assert m.tolist() == [[True, False], [False, False]]
