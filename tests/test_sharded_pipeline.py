"""Mesh mode as a production configuration (ISSUE 7): real
schedule_batch solves routed through the sharded kernels at full
pipeline speed — wavefront/greedy/auction routing, the gang-admission
retry across shards, the NamedSharding-resident mirror, the deferred
coalesced readback, the single-chip fallback counter, the circuit
breaker's host fallback from mesh mode, and the meshDevices config
surface.

Runs on the 8-virtual-device CPU mesh from conftest.py.
"""

import jax
import numpy as np
import pytest

from kubernetes_tpu.api import types as api
from kubernetes_tpu.models.batch_scheduler import (
    DeviceSolve,
    HostSolve,
    SolveCircuitBreaker,
    TPUBatchScheduler,
)
from kubernetes_tpu.ops import schema
from kubernetes_tpu.parallel.sharded import make_mesh
from kubernetes_tpu.testing import faults
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod

pytestmark = pytest.mark.multichip


@pytest.fixture(autouse=True)
def _disarmed():
    yield
    faults.disarm()


def _mk_nodes(n, cpu=16000, mem_gi=32, pods=110):
    return [
        make_node(f"n{i}")
        .capacity(cpu_milli=cpu, mem=mem_gi * GI, pods=pods)
        .zone(f"z{i % 4}")
        .obj()
        for i in range(n)
    ]


def _mk_pods(p, tag, spread=False):
    out = []
    for i in range(p):
        pw = make_pod(f"{tag}-{i}").req(
            cpu_milli=100 + (i % 5) * 100, mem=256 * MI
        ).labels(app=f"s{i % 7}")
        if spread:
            pw.spread(2, api.LABEL_ZONE, "DoNotSchedule", {"app": f"s{i % 7}"})
        out.append(pw.obj())
    return out


def _pair(n_nodes):
    single = TPUBatchScheduler(mesh=None)
    multi = TPUBatchScheduler(mesh=make_mesh(8))
    for nd in _mk_nodes(n_nodes):
        single.add_node(nd)
        multi.add_node(nd)
    return single, multi


@pytest.mark.parametrize("spread", [False, True])
def test_mesh_schedule_pending_steps_match_single_chip(spread):
    """Repeated wavefront-routed schedule_pending steps with assumes
    between them (the steady production loop: mirror delta syncs +
    sharded solves) place identically to the single-chip scheduler."""
    single, multi = _pair(100)
    for step in range(3):
        pods = _mk_pods(128, f"w{step}", spread=spread)
        n1 = single.schedule_pending(pods)
        n2 = multi.schedule_pending(pods)
        assert n1 == n2
        for p, nm in zip(pods[:16], n1[:16]):
            if nm is not None:
                single.assume(p, nm)
                multi.assume(p, nm)
    # the batch actually routed through the wavefront (>= 64 pods)
    assert multi.last_solve.wave_count is not None
    assert multi.sharded_fallbacks == 0
    # and the steady steps synced through the delta path, not re-uploads
    stats = multi._mirror.stats()
    assert stats["resync_total"] == 1  # the first sync only
    assert stats["delta_syncs"] >= 1


def test_mesh_small_greedy_batch_matches_single_chip():
    """Batches under WAVEFRONT_MIN_PODS route to the sharded greedy
    scan; placements (and reason codes) match the single chip."""
    single, multi = _pair(32)
    pods = _mk_pods(8, "g")
    assert single.schedule_pending(pods) == multi.schedule_pending(pods)
    r1 = [int(r) for r in np.asarray(single.last_result.reasons)[:8]]
    r2 = [int(r) for r in np.asarray(multi.last_result.reasons)[:8]]
    assert r1 == r2


def test_mesh_gang_admission_retry_matches_single_chip():
    """Gang scarcity (no gang fits alongside the others) drives the
    binary-search admission retry; the subset solves run sharded and
    admit exactly the same gang prefix."""
    single = TPUBatchScheduler(mesh=None)
    multi = TPUBatchScheduler(mesh=make_mesh(8))
    for nd in [
        make_node(f"n{i}").capacity(cpu_milli=4000, mem=8 * GI, pods=8).obj()
        for i in range(16)
    ]:
        single.add_node(nd)
        multi.add_node(nd)
    pods = []
    for g in range(6):
        for i in range(24):
            pods.append(
                make_pod(f"g{g}-{i}")
                .req(cpu_milli=900, mem=GI)
                .group(f"gang-{g}", size=24)
                .priority(10 - g)
                .obj()
            )
    n1 = single.schedule_pending(pods)
    n2 = multi.schedule_pending(pods)
    assert n1 == n2
    admitted = {
        p.spec.scheduling_group for p, nm in zip(pods, n1) if nm is not None
    }
    assert admitted  # scarcity admission landed at least one gang


def test_mesh_auction_route_matches_single_chip():
    single, multi = _pair(64)
    pods = [
        make_pod(f"a{i}").req(cpu_milli=500, mem=512 * MI)
        .group(f"gg-{i % 4}", size=16).obj()
        for i in range(64)
    ]
    assert single.schedule_pending(pods) == multi.schedule_pending(pods)
    assert type(multi.last_result).__name__ == "AuctionResult"


def test_mesh_results_ride_deferred_coalesced_readback():
    """Mesh results are sharded device futures, not host numpy: the
    DeviceSolve defers decode until names() and reads back through ONE
    coalesced device_get — decode overlap survives sharding."""
    multi = TPUBatchScheduler(mesh=make_mesh(8))
    for nd in _mk_nodes(64):
        multi.add_node(nd)
    pods = _mk_pods(96, "d")
    ds = multi.schedule_pending_async(pods)
    assert isinstance(ds, DeviceSolve) and not isinstance(ds, HostSolve)
    assert isinstance(ds.result.assignment, jax.Array)
    assert ds._decoded is None  # nothing read back yet
    ds.ready()                  # non-blocking probe works on shards
    names = multi.finalize_pending(pods, ds)
    assert ds._decoded is not None
    assert all(n is not None for n in names)
    assert multi.last_timings["decode_overlap_s"] >= 0.0


def test_mesh_padded_bucket_smaller_than_mesh_falls_back_single_chip():
    """A cluster whose padded bucket can't split across the mesh solves
    single-chip and counts a sharded_solve_fallback — it must still
    place correctly."""
    multi = TPUBatchScheduler(
        mesh=make_mesh(8), limits=schema.SnapshotLimits(min_nodes=4)
    )
    for nd in _mk_nodes(3):
        multi.add_node(nd)
    names = multi.schedule_pending(_mk_pods(4, "f"))
    assert all(n is not None for n in names)
    assert multi.sharded_fallbacks >= 1


def test_mesh_circuit_breaker_host_fallback_engages():
    """A dead device path trips the breaker from mesh mode exactly like
    single-chip: attempt + one retry, then the host per-pod fallback
    carries the batch (and parks the breaker open)."""
    multi = TPUBatchScheduler(mesh=make_mesh(8))
    for nd in _mk_nodes(16):
        multi.add_node(nd)
    pods = _mk_pods(8, "brk")
    reg = faults.FaultRegistry().fail("batch.solve", n=-1)
    with faults.armed(reg):
        names = multi.schedule_pending(pods)
    assert all(n is not None for n in names)
    assert multi.breaker.state == SolveCircuitBreaker.OPEN
    assert multi.breaker.fallbacks >= 1
    assert isinstance(multi.last_solve, HostSolve)


def test_mesh_constructible_from_config():
    """meshDevices + the ShardedSolve gate build a mesh-mode registry
    from YAML; gate off (or meshDevices 0) stays single-chip."""
    from kubernetes_tpu.scheduler.config import load_config
    from kubernetes_tpu.scheduler.framework import FrameworkRegistry

    cfg = load_config(
        """
apiVersion: kubescheduler.config.k8s.io/v1
kind: KubeSchedulerConfiguration
meshDevices: 8
"""
    )
    assert cfg.mesh_devices == 8
    reg = FrameworkRegistry(cfg)
    tpu = reg.default.tpu
    assert tpu.mesh is not None and tpu.shard_count == 8
    for nd in _mk_nodes(16):
        tpu.add_node(nd)
    assert all(
        n is not None for n in tpu.schedule_pending(_mk_pods(8, "cfg"))
    )

    from kubernetes_tpu.scheduler.config import SchedulerConfiguration

    off = FrameworkRegistry(
        SchedulerConfiguration(
            mesh_devices=8, feature_gates={"ShardedSolve": False}
        )
    )
    assert off.default.tpu.mesh is None
    assert off.default.tpu.shard_count == 0
