"""graftcoh runtime half — the resident-epoch auditor (analysis/epochs.py).

Proves the auditor observes the real warm path (audits > 0, zero
violations on steady churn), detects an injected stale epoch with the
divergent (resident, field, epoch) triple, and pins the two true
positives the coherence work surfaced:

  * the dispatch-retry failure path invalidated the resident partials
    but NOT the resident mirror (batch_scheduler.schedule_pending_async
    — asymmetric against finalize_pending's heal wire, which names both
    residents as fault suspects);
  * rollback() unconditionally restored a bookmarked buffer even when
    an invalidate() (heal wire, leadership reconcile) landed after the
    bookmark — resurrecting the deliberately-dropped resident so later
    delta syncs layered onto stale state.  The invalidation fence keeps
    the resident invalidated instead.

The smoke subset rides tier-1 ('coherence and not slow'); chaos runs
arm the auditor session-wide via GRAFTLINT_COHERENCE=1 (conftest).
"""

import contextlib

import numpy as np
import pytest

from kubernetes_tpu.analysis import epochs
from kubernetes_tpu.api import types as api
from kubernetes_tpu.models.batch_scheduler import TPUBatchScheduler
from kubernetes_tpu.testing.wrappers import GI, MI, make_node, make_pod

pytestmark = pytest.mark.coherence


@contextlib.contextmanager
def _isolated():
    """A private armed auditor, even when the GRAFTLINT_COHERENCE=1
    session auditor is active — the stale-injection tests must not
    poison the session-teardown assert_clean()."""
    prev = epochs._active
    epochs._active = None
    try:
        with epochs.tracked() as auditor:
            yield auditor
    finally:
        epochs._active = prev


def _mk_sched(**kw):
    return TPUBatchScheduler(mode="greedy", use_partials=True, **kw)


def _add_nodes(sched, n=12, seed=0):
    rng = np.random.default_rng(seed)
    for i in range(n):
        nd = (
            make_node(f"n-{i}")
            .capacity(cpu_milli=8000, mem=16 * GI, pods=110)
            .zone(f"z-{i % 3}")
        )
        if rng.random() < 0.3:
            nd.label("disk", "ssd")
        sched.add_node(nd.obj())


def _mk_pods(step, p, seed):
    rng = np.random.default_rng(seed)
    pods = []
    for i in range(p):
        pw = make_pod(f"s{step}-p{i}").req(
            cpu_milli=int(rng.choice([100, 250, 500])), mem=256 * MI
        )
        if i % 3 == 0:
            pw.required_affinity(api.LABEL_ZONE, api.OP_IN, [f"z-{i % 3}"])
        elif i % 3 == 1:
            pw.preferred_affinity(10, "disk", api.OP_IN, ["ssd"])
        pods.append(pw.obj())
    return pods


def _churn(sched, step):
    p = make_pod(f"churn-{step}").req(cpu_milli=50, mem=64 * MI).obj()
    sched.assume(p, f"n-{step % 8}")


# -- clean steady state ------------------------------------------------------

def test_clean_steady_state_audits():
    """Warm solves over bounded churn: the armed auditor observes every
    consume site and records zero violations."""
    sched = _mk_sched()
    _add_nodes(sched)
    with _isolated() as auditor:
        for step in range(4):
            _churn(sched, step)
            sched.schedule_pending(_mk_pods(step, 8, seed=step))
        assert auditor.audits_total > 0
        assert auditor.violations_total == 0
        auditor.assert_clean()


def test_disarmed_hooks_are_noops():
    prev = epochs._active
    epochs._active = None
    try:
        assert epochs.audits_total() == 0
        assert epochs.violations_total() == 0
        sched = _mk_sched()
        _add_nodes(sched, n=8, seed=1)
        sched.schedule_pending(_mk_pods(0, 6, seed=1))
        assert epochs.audits_total() == 0
    finally:
        epochs._active = prev


# -- injected staleness ------------------------------------------------------

def test_injected_stale_epoch_detected():
    """A resident reporting a stale epoch at consume time fails loudly
    with the (resident, field, epoch) triple."""
    sched = _mk_sched()
    _add_nodes(sched)
    sched.schedule_pending(_mk_pods(0, 8, seed=2))
    stale = epochs.EpochStamp("mirror", 0, None, -1, 1)
    sched._mirror.epoch = lambda: stale
    with _isolated() as auditor:
        sched.schedule_pending(_mk_pods(1, 8, seed=3))
        assert auditor.violations_total > 0
        blob = "\n".join(auditor.violations)
        assert "(mirror, synced_gen" in blob
        with pytest.raises(epochs.CoherenceViolation):
            auditor.assert_clean()


def test_missing_stamp_is_a_violation():
    with _isolated() as auditor:
        auditor.audit_consume(None, "mirror", 1, 1)
        assert auditor.violations_total == 1
        assert "(mirror, stamp, None)" in auditor.violations[0]


def test_cross_resident_pair_divergence_detected():
    """Dispatch-time audit: partials evaluated against a different
    epoch than the mirror the solve consumes."""
    m = epochs.EpochStamp("mirror", 2, None, 7, 3)
    p = epochs.EpochStamp("partials", 2, (), 5, 3)  # dirty mark behind
    with _isolated() as auditor:
        auditor.audit_pair(m, p)
        assert auditor.violations_total == 1
        assert "(partials, synced_gen" in auditor.violations[0]


# -- epoch transitions -------------------------------------------------------

def test_rollback_restores_bookmarked_epoch():
    sched = _mk_sched()
    _add_nodes(sched)
    sched.schedule_pending(_mk_pods(0, 8, seed=4))
    m_stamp = sched._mirror.epoch()
    p_stamp = sched._partials.epoch()
    assert m_stamp is not None and p_stamp is not None
    m_point = sched._mirror.speculation_point()
    p_point = sched._partials.speculation_point()
    # speculative progress moves the epochs forward
    _churn(sched, 0)
    sched.schedule_pending(_mk_pods(1, 8, seed=5))
    assert sched._mirror.epoch() != m_stamp
    sched._mirror.rollback(m_point)
    sched._partials.rollback(p_point)
    assert sched._mirror.epoch() == m_stamp
    assert sched._partials.epoch() == p_stamp
    # and the next warm solve re-syncs cleanly under the auditor
    with _isolated() as auditor:
        sched.schedule_pending(_mk_pods(2, 8, seed=6))
        assert auditor.audits_total > 0
        auditor.assert_clean()


def test_invalidate_clears_epoch_and_forces_full_upload():
    sched = _mk_sched()
    _add_nodes(sched)
    sched.schedule_pending(_mk_pods(0, 8, seed=7))
    resyncs = sched._mirror.resync_total
    sched._mirror.invalidate()
    sched._partials.invalidate()
    assert sched._mirror.epoch() is None
    assert sched._partials.epoch() is None
    with _isolated() as auditor:
        sched.schedule_pending(_mk_pods(1, 8, seed=8))
        auditor.assert_clean()
    assert sched._mirror.resync_total > resyncs
    assert sched._mirror.epoch() is not None


def test_invalidate_then_rollback_does_not_resurrect():
    """Regression pin: a bookmark taken BEFORE an invalidate() must not
    roll the resident back to life — the heal wire / leader reconcile
    dropped that buffer on purpose, and resurrecting it would base later
    delta syncs on stale state.  The invalidation fence refuses the
    rollback (counted, not a violation) and the next sync performs the
    full re-upload."""
    sched = _mk_sched()
    _add_nodes(sched)
    sched.schedule_pending(_mk_pods(0, 8, seed=9))
    m_point = sched._mirror.speculation_point()
    p_point = sched._partials.speculation_point()
    # the heal wire fires between bookmark and rollback
    sched._mirror.invalidate()
    sched._partials.invalidate()
    with _isolated() as auditor:
        sched._mirror.rollback(m_point)
        sched._partials.rollback(p_point)
        assert auditor.rollbacks_blocked == 2
    # stayed invalidated: no resurrected buffer, no stamp
    assert sched._mirror._dev is None
    assert sched._mirror.epoch() is None
    assert sched._partials._store is None
    assert sched._partials.epoch() is None
    resyncs = sched._mirror.resync_total
    with _isolated() as auditor:
        sched.schedule_pending(_mk_pods(1, 8, seed=10))
        auditor.assert_clean()
    assert sched._mirror.resync_total > resyncs


def test_fresh_buffer_lineage_only_on_full_upload():
    sched = _mk_sched()
    _add_nodes(sched)
    sched.schedule_pending(_mk_pods(0, 8, seed=11))
    buf0 = sched._mirror.epoch().buffer_id
    assert buf0 > 0
    # bounded churn: delta syncs keep the buffer lineage
    _churn(sched, 1)
    sched.schedule_pending(_mk_pods(1, 8, seed=12))
    assert sched._mirror.epoch().buffer_id == buf0
    # invalidate: the next sync is a full upload with a NEW lineage
    sched._mirror.invalidate()
    sched.schedule_pending(_mk_pods(2, 8, seed=13))
    assert sched._mirror.epoch().buffer_id > buf0


# -- the dispatch-retry heal wire (true positive #1) -------------------------

def test_dispatch_retry_invalidates_both_residents():
    """Regression pin: the schedule_pending_async double-dispatch
    failure must invalidate the mirror alongside the partials (it used
    to drop only the partials — asymmetric against finalize_pending's
    heal wire, which names BOTH residents as fault suspects)."""
    sched = _mk_sched()
    _add_nodes(sched)
    names0 = sched.schedule_pending(_mk_pods(0, 8, seed=14))
    assert sched._mirror._dev is not None
    assert sched._partials._store is not None

    def boom(snap, meta):
        raise RuntimeError("injected dispatch fault")

    sched.solve_encoded_async = boom
    pods = _mk_pods(1, 8, seed=15)
    ds = sched.schedule_pending_async(pods)
    assert ds is not None  # host fallback still places the batch
    assert len(ds.names()) == len(pods)
    assert sched._mirror._dev is None
    assert sched._mirror.epoch() is None
    assert sched._partials._store is None
    assert sched._partials.epoch() is None
    # the residents heal on the next device solve
    del sched.solve_encoded_async
    sched.breaker.reset()
    with _isolated() as auditor:
        sched.schedule_pending(_mk_pods(2, 8, seed=16))
        assert auditor.audits_total > 0
        auditor.assert_clean()
    assert names0 is not None


# -- sharded mesh ------------------------------------------------------------

@pytest.mark.multichip
def test_mesh_sharded_epochs_stay_clean():
    """Mesh-sharded residents carry epochs exactly like single-chip,
    including across a sharded→replicated bucket transition."""
    from kubernetes_tpu.parallel.sharded import make_mesh

    mesh = make_mesh(8)
    sched = _mk_sched(mesh=mesh)
    _add_nodes(sched, n=16, seed=21)
    with _isolated() as auditor:
        for step in range(3):
            _churn(sched, step)
            sched.schedule_pending(_mk_pods(step, 8, seed=20 + step))
        assert auditor.audits_total > 0
        auditor.assert_clean()
    assert sched._mirror.epoch() is not None
