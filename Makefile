# Developer entry points.  Tier-1 is the gate CI runs on every PR; the
# chaos suite (randomized seeded fault injection, tests/test_chaos.py)
# is opt-in because each of its 20 fixed seeds drives a full cluster
# run.

PY ?= python

.PHONY: test chaos chaos-restart chaos-serving bench lint lint-shapes \
	lint-coherence lint-obligations multichip race native-ext test-journal

# graftlint: the project-native static analysis suite (guarded-by,
# hot-path purity, registry drift, lock-order, tensor-contract,
# atomicity, coherence, obligations — docs/static_analysis.md).  Exits
# non-zero on any finding outside kubernetes_tpu/analysis/baseline.json
# and on stale baseline entries.  Import-light: no JAX init.
lint:
	$(PY) -m kubernetes_tpu.analysis

# recompile-discipline: eval_shape over the pad-bucket lattice + real
# encoder shape validation (analysis/shapes.py).  Imports JAX, hence a
# separate mode — `make lint` must stay import-light.
lint-shapes:
	JAX_PLATFORMS=cpu $(PY) -m kubernetes_tpu.analysis --shapes

# graftcoh focused mode: the resident-cache discipline matrix alone
# (analysis/coherence.py; it also rides `make lint`).  The runtime half
# is the GRAFTLINT_COHERENCE=1 epoch auditor (analysis/epochs.py).
lint-coherence:
	$(PY) -m kubernetes_tpu.analysis --coherence

# graftobl focused mode: the linear-obligation engine alone
# (analysis/obligations.py; it also rides `make lint`).  The runtime
# half is the GRAFTLINT_OBLIGATIONS=1 exactly-once ledger
# (analysis/ledger.py).
lint-obligations:
	$(PY) -m kubernetes_tpu.analysis --obligations

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow and not chaos' \
		--continue-on-collection-errors -p no:cacheprovider

# graftsched: the concurrency gate (docs/static_analysis.md).  Arms the
# runtime lock-order tracker for the whole session and runs the
# deterministic interleaving suite — the DEEP sweeps (200+ seeded
# schedules per scenario, every invariant oracle green, seed-replay
# determinism) plus the atomicity-sensitive test files.  Tier-1 carries
# only the fast interleave smoke subset ('interleave and not slow').
race:
	GRAFTLINT_LOCK_ORDER=1 JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_interleave.py tests/test_static_analysis.py \
		tests/test_concurrency_stress.py tests/test_watch_backpressure.py \
		-q -m 'not chaos' -p no:cacheprovider

# the fixed seed matrices live in tests/test_chaos.py: SEEDS = range(20)
# for the full-pipeline plans plus the overload-protection scenarios
# (SLOW_CONSUMER_SEEDS, RELIST_STORM_SEEDS — backpressured fan-out,
# coalescing, relist-storm containment), the mixed-priority preemption
# churn (PREEMPT_SEEDS — batched-dry-run faults, PDB-guarded victims),
# the gang carve-outs (CARVEOUT_SEEDS) and the incremental-solve
# partials poison (PARTIALS_SEEDS = 700-704 — resident-store CORRUPT
# must trip the parity gate, never be absorbed); every seed replays
# byte-identically via FaultRegistry(seed)
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py -m chaos -q \
		-p no:cacheprovider

# the kill-restart subset only (RESTART_SEEDS = range(300, 310)): tear a
# component down at a registered crash point (store mid-fsync, binder
# mid-wave, leader mid-pop-window), restart it, and prove no pod lost,
# no double bind, rv monotonic across the restart, and snapshot+suffix
# recovery bit-identical to a full-journal-replay oracle
chaos-restart:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py -m restart -q \
		-p no:cacheprovider

# the serving-plane subset (SERVING_SEEDS = range(900, 910) plus the
# journal-frame native/fallback parity seed and the pod-axis breaker
# fallback): pods created THROUGH the read-replica HTTP plane under
# injected request failures, torn watch frames and admission stalls,
# with a replica killed and restarted mid-run — no watcher terminated,
# no pinned handler thread, informer caches converge on the store's
# bindings exactly once
chaos-serving:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py -m serving -q \
		-p no:cacheprovider

# the sharded multichip suite on a FORCED 8-device host-platform mesh:
# sharded-vs-single-chip parity (greedy/wavefront/auction + gang retry),
# the mesh-sharded mirror, and mesh-mode pipeline/fallback behavior.
# conftest.py forces the same device count for every pytest run; the
# explicit flag keeps this target correct in any environment.
multichip:
	XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
		$(PY) -m pytest tests/ -q -m multichip -p no:cacheprovider

bench:
	JAX_PLATFORMS=cpu BENCH_STRICT=1 $(PY) bench.py

# optional _hostplane C extension (native/hostplane.c): journal frame
# trailer splice + CRC and proto wire framing.  Pure accelerator —
# api/framing.py is the contract and the fallback, so this target is
# best-effort: no compiler, no extension, everything still runs.
native-ext:
	@cc=$$($(PY) -c "import sysconfig; print(sysconfig.get_config_var('CC') or 'cc')" | cut -d' ' -f1); \
	if command -v $$cc >/dev/null 2>&1; then \
		inc=$$($(PY) -c "import sysconfig; print(sysconfig.get_paths()['include'])"); \
		ext=$$($(PY) -c "import sysconfig; print(sysconfig.get_config_var('EXT_SUFFIX'))"); \
		$$cc -O2 -Wall -shared -fPIC -I$$inc native/hostplane.c \
			-o _hostplane$$ext && echo "built _hostplane$$ext"; \
	else \
		echo "no C compiler; skipping _hostplane (pure-Python fallback)"; \
	fi

# journal/framing tests in BOTH modes: with the native extension if it
# builds, and with the pure-Python fallback forced — the fallback must
# stay green on machines with no compiler at all.
test-journal: native-ext
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_journal_framing.py \
		tests/test_restart_recovery.py tests/test_durability_leaderelection.py \
		-q -p no:cacheprovider
	HOSTPLANE_DISABLE=1 JAX_PLATFORMS=cpu $(PY) -m pytest \
		tests/test_journal_framing.py -q -p no:cacheprovider
