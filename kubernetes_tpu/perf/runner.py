"""scheduler_perf runner: executes workloads against the HOST scheduler
through the store — the full informer/cache/queue/solve/bind path, not
the solver directly (the round-2 bench's shortcut).

Reference: mustSetupCluster + runWorkload
(test/integration/scheduler_perf/{util.go:82,scheduler_perf.go:700ish}):
a real apiserver+etcd in-process, nodes and pods created as API objects,
collectors sampling while measured pods schedule.  Ours: the in-memory
Store is the apiserver, Scheduler runs its informer-fed loop in a
thread, and opcodes mutate the store exactly like a client would.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from ..api import store as st
from ..api import types as api
from ..scheduler import Scheduler
from ..api import kubeyaml
from .collectors import (
    DataItem,
    MetricsCollector,
    ThroughputCollector,
    histogram_baseline,
)
from .workload import Op, Workload

_DEFAULT_NODE = {
    "metadata": {"labels": {"topology.kubernetes.io/zone": "zone-$index_mod8"}},
    "status": {
        "allocatable": {"cpu": "32", "memory": "64Gi", "pods": "110"}
    },
}
_DEFAULT_POD = {
    "spec": {
        "containers": [
            {"resources": {"requests": {"cpu": "500m", "memory": "500Mi"}}}
        ]
    }
}


def _substitute_index(obj: Any, index: int) -> Any:
    """Replace $index / $index_modN tokens in template string values."""
    if isinstance(obj, dict):
        return {k: _substitute_index(v, index) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_substitute_index(v, index) for v in obj]
    if isinstance(obj, str) and "$index" in obj:
        out = obj
        while "$index_mod" in out:
            pos = out.find("$index_mod")
            tail = out[pos + len("$index_mod"):]
            digits = ""
            for ch in tail:
                if ch.isdigit():
                    digits += ch
                else:
                    break
            mod = int(digits) if digits else 1
            out = out.replace(f"$index_mod{digits}", str(index % mod), 1)
        return out.replace("$index", str(index))
    return obj


class WorkloadRunner:
    def __init__(
        self,
        batch_size: int = 4096,
        sample_interval: float = 0.1,
        warmup: bool = True,
    ):
        self.batch_size = batch_size
        self.sample_interval = sample_interval
        # Pre-compile solver executables before the measured window
        # (Scheduler.warmup): the framework's analogue of the reference
        # binary's ahead-of-time compilation.  The harness reports the
        # warm window as WallClockThroughput, the warmup cost as
        # WarmupDuration, and the cold total as
        # WallClockThroughputIncludingWarmup — disable with --no-warmup
        # for fully cold numbers.
        self.warmup = warmup

    def run(self, workload: Workload) -> List[DataItem]:
        """Execute one workload; returns its DataItems."""
        store = st.Store()
        sched = Scheduler(store, batch_size=self.batch_size)
        sched.start()
        created = {"nodes": 0, "pods": 0, "namespaces": []}
        items: List[DataItem] = []
        try:
            for op in workload.ops:
                self._execute(op, store, sched, created, items, workload)
        finally:
            sched.stop()
        if not created.get("metrics_done"):
            # no measured op collected a window: summarize the whole run
            items.extend(
                MetricsCollector(
                    sched.metrics,
                    labels={"Name": workload.full_name},
                ).collect()
            )
        return items

    # -- opcodes -----------------------------------------------------------

    def _execute(
        self,
        op: Op,
        store: st.Store,
        sched: Scheduler,
        created: Dict[str, Any],
        items: List[DataItem],
        workload: Workload,
    ) -> None:
        if op.opcode == "createNodes":
            template = op.node_template or _DEFAULT_NODE
            base = created["nodes"]
            for i in range(op.count):
                d = _substitute_index(template, base + i)
                d.setdefault("metadata", {})["name"] = f"node-{base + i}"
                store.create(kubeyaml.node_from_dict(d))
            created["nodes"] += op.count
        elif op.opcode == "createNamespaces":
            for i in range(op.count):
                created["namespaces"].append(f"{op.prefix}-{i}")
        elif op.opcode == "createPods":
            self._create_pods(op, store, sched, created, items, workload)
        elif op.opcode == "churn":
            self._churn(op, store)
        elif op.opcode == "barrier":
            self._barrier(store, op.namespace, sched=sched)
        elif op.opcode == "sleep":
            time.sleep(op.duration_s)
        else:
            raise ValueError(f"unsupported opcode {op.opcode}")

    def _warmup(self, op, sched, created, items, workload) -> float:
        """Compile the executables this op's pods will need, outside the
        measured window, using pods built from the op's own template so
        feature flags and constraint-table shapes match."""
        template = op.pod_template or _DEFAULT_POD
        namespace = op.namespace or "default"
        # the informer must have delivered every created node to the
        # scheduler cache first — warmup compiles for the node bucket
        deadline = time.monotonic() + 60.0
        while (
            len(sched.tpu.state._rows) < created["nodes"]
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        base = created["pods"]
        pods = []
        for i in range(min(op.count, self.batch_size)):
            d = _substitute_index(template, base + i)
            meta = d.setdefault("metadata", {})
            meta["name"] = f"warmup-{base + i}"
            meta["namespace"] = namespace
            pods.append(kubeyaml.pod_from_dict(d))
        dt = sched.warmup(pods)
        items.append(
            DataItem(
                {"Average": dt},
                "s",
                {"Name": workload.full_name, "Metric": "WarmupDuration"},
            )
        )
        return dt

    def _create_pods(self, op, store, sched, created, items, workload) -> None:
        template = op.pod_template or _DEFAULT_POD
        namespace = op.namespace or "default"
        base = created["pods"]
        collector = None
        warmup_s = 0.0
        if op.collect_metrics:
            # drain the init-phase backlog FIRST: (a) leftover init pods
            # binding inside the measured window pollute its percentiles
            # and jump to unwarmed merged-batch buckets; (b) warmup's
            # round-B phantom assume must not coexist with live pending
            # pods, or they could be repelled by the phantom
            sched.wait_for_idle(timeout=300)
        if op.collect_metrics and self.warmup:
            warmup_s = self._warmup(op, sched, created, items, workload)
        if op.collect_metrics:
            measured = {f"pod-{base + i}" for i in range(op.count)}
            collector = ThroughputCollector(
                store,
                namespaces=[namespace],
                interval=self.sample_interval,
                labels={"Name": workload.full_name},
                pod_names=measured,
                lister=(
                    sched.informers.informer("Pod").list
                    if sched is not None else None
                ),
            ).start()
        baseline = (
            histogram_baseline(sched.metrics) if op.collect_metrics else None
        )
        t0 = time.monotonic()
        for i in range(op.count):
            d = _substitute_index(template, base + i)
            meta = d.setdefault("metadata", {})
            meta["name"] = f"pod-{base + i}"
            meta["namespace"] = namespace
            store.create(kubeyaml.pod_from_dict(d))
        created["pods"] += op.count
        if collector is not None:
            # the barrier reads the scheduler's informer cache, which can
            # LAG the creations just written — a first poll that sees no
            # pending pods yet would declare victory with 0 scheduled.
            # Wait for the cache to observe every measured pod first.
            measured = {f"pod-{base + i}" for i in range(op.count)}
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                seen = sum(
                    1
                    for p in self._pods_snapshot(store, sched)
                    if p.meta.name in measured
                )
                if seen >= op.count:
                    break
                time.sleep(0.01)
            # measured pods: wait for them all to schedule, then collect
            self._barrier(store, namespace, sched=sched)
            wall = time.monotonic() - t0
            collector.stop()
            items.extend(collector.collect())
            scheduled = self._scheduled(store, namespace, sched=sched)
            items.append(
                DataItem(
                    {"Average": scheduled / wall if wall > 0 else 0.0},
                    "pods/s",
                    {"Name": workload.full_name, "Metric": "WallClockThroughput"},
                )
            )
            # the cold view: what a fresh process pays including the
            # pre-window compile warmup (0 when warmup is disabled —
            # then WallClockThroughput itself is the cold number)
            if warmup_s > 0:
                cold = scheduled / (wall + warmup_s)
                items.append(
                    DataItem(
                        {"Average": cold},
                        "pods/s",
                        {
                            "Name": workload.full_name,
                            "Metric": "WallClockThroughputIncludingWarmup",
                        },
                    )
                )
            # window-scoped attempt/algorithm percentiles (diffed over
            # the pre-window baseline, metricsCollector-style)
            items.extend(
                MetricsCollector(
                    sched.metrics,
                    labels={"Name": workload.full_name},
                    baseline=baseline,
                ).collect()
            )
            created["metrics_done"] = True

    @staticmethod
    def _pods_snapshot(
        store: st.Store, sched: Optional[Scheduler]
    ) -> List[api.Pod]:
        """Pods for polling loops.  The scheduler's informer cache is
        the cheap source: store.list deep-copies every object per call,
        and a 50ms poll over thousands of pods becomes a GIL-saturating
        copy storm that starves the commit loop it is waiting on
        (observed: 15 pods/s in TopologySpreading until the barrier
        stopped hammering store.list)."""
        if sched is not None:
            return sched.informers.informer("Pod").list()
        return store.list("Pod")[0]

    @classmethod
    def _scheduled(
        cls,
        store: st.Store,
        namespace: Optional[str],
        sched: Optional[Scheduler] = None,
    ) -> int:
        return sum(
            1
            for p in cls._pods_snapshot(store, sched)
            if p.spec.node_name
            and (namespace is None or p.meta.namespace == namespace)
        )

    def _barrier(
        self,
        store: st.Store,
        namespace: Optional[str],
        sched: Optional[Scheduler] = None,
        timeout: float = 300.0,
    ) -> None:
        """Wait until every created pod (in namespace, or all) is either
        scheduled or provably unschedulable-and-parked (barrierOp,
        scheduler_perf.go:593 — reference waits for scheduled only; we
        also accept parked pods so Unschedulable-style workloads
        terminate)."""
        deadline = time.monotonic() + timeout
        pending: List[api.Pod] = []
        stable = 0
        last_sig = None
        while time.monotonic() < deadline:
            pods = self._pods_snapshot(store, sched)
            pending = [
                p
                for p in pods
                if not p.spec.node_name
                and (namespace is None or p.meta.namespace == namespace)
            ]
            if not pending:
                return
            if sched is not None:
                qs = sched.queue.stats()
                live = qs["active"] + qs["inflight"] + qs["backoff"]
                parked = qs["unschedulable"] + qs["gated"] + qs["gang_staged"]
                # preemption (or any event) can un-park pods, so parked
                # counts only terminate the barrier once the system has
                # been quiescent for ~1s (20 consecutive identical polls)
                sig = (
                    len(pending),
                    parked,
                    sched.metrics.preemption_attempts.total,
                )
                stable = stable + 1 if (live == 0 and sig == last_sig) else 0
                last_sig = sig
                if stable >= 20 and parked >= len(pending):
                    return  # everything left is provably parked
            time.sleep(0.05)
        raise TimeoutError(
            f"barrier: {len(pending)} pods still unscheduled after {timeout}s"
        )

    def _churn(self, op: Op, store: st.Store) -> None:
        """Create (or create+delete) churn objects at an interval, once
        through `number` iterations (churnOp, scheduler_perf.go:540-588;
        the reference churns in a background goroutine for the workload's
        remainder — we run the iterations inline, which bounds runtime
        deterministically)."""
        ns = op.namespace or "churn"
        for i in range(op.number):
            objs = []
            for t, template in enumerate(op.templates or [_DEFAULT_POD]):
                d = _substitute_index(template, i)
                meta = d.setdefault("metadata", {})
                meta["name"] = f"churn-{t}-{i}"
                meta["namespace"] = ns
                obj = (
                    kubeyaml.node_from_dict(d)
                    if d.get("kind") == "Node"
                    else kubeyaml.pod_from_dict(d)
                )
                store.create(obj)
                objs.append(obj)
            if op.mode == "recreate":
                for obj in objs:
                    store.delete(
                        obj.KIND, obj.meta.name, obj.meta.namespace
                    )
            time.sleep(op.interval_ms / 1000.0)


def run_workloads(
    workloads: List[Workload], out_path: Optional[str] = None, **kw
) -> Dict[str, Any]:  # kw: batch_size / sample_interval / warmup
    """Run a list of workloads; returns (and optionally writes) the
    reference's result-JSON shape {version, dataItems}."""
    runner = WorkloadRunner(**kw)
    all_items: List[DataItem] = []
    for wl in workloads:
        all_items.extend(runner.run(wl))
    result = {"version": "v1", "dataItems": all_items}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
    return result
