"""CLI: python -m kubernetes_tpu.perf [--config F] [--label L] [--name N]
[--out results.json]

The scheduler_perf entry point: runs the selected workloads against the
host scheduler and prints/writes DataItems JSON (the reference's
perf-dash format)."""

import argparse
import json

from . import DEFAULT_CONFIG, load_config, run_workloads, select


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default=DEFAULT_CONFIG)
    ap.add_argument("--label", default=None, help="e.g. integration-test, fast")
    ap.add_argument("--name", default=None, help="substring of Case/Workload")
    ap.add_argument("--out", default=None, help="write JSON here")
    ap.add_argument("--batch-size", type=int, default=4096)
    ap.add_argument(
        "--no-warmup", action="store_true",
        help="skip the pre-window compile warmup (fully cold numbers)",
    )
    args = ap.parse_args()
    wls = select(load_config(args.config), label=args.label, name=args.name)
    if not wls:
        raise SystemExit("no workloads selected")
    print(f"running {len(wls)} workloads: {[w.full_name for w in wls]}")
    result = run_workloads(
        wls, out_path=args.out, batch_size=args.batch_size,
        warmup=not args.no_warmup,
    )
    print(json.dumps(result, indent=1))


if __name__ == "__main__":
    main()
