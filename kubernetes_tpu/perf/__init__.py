"""scheduler_perf port: YAML workloads driving the host scheduler
through the store, with throughput/metrics collectors emitting DataItems
(reference: test/integration/scheduler_perf).

  from kubernetes_tpu.perf import load_config, run_workloads, select
  wls = select(load_config(DEFAULT_CONFIG), label="integration-test")
  result = run_workloads(wls)
"""

import os

from .collectors import DataItem, MetricsCollector, ThroughputCollector
from .runner import WorkloadRunner, run_workloads
from .workload import Workload, load_config, select

DEFAULT_CONFIG = os.path.join(
    os.path.dirname(__file__), "config", "performance-config.yaml"
)

__all__ = [
    "DataItem",
    "DEFAULT_CONFIG",
    "MetricsCollector",
    "ThroughputCollector",
    "Workload",
    "WorkloadRunner",
    "load_config",
    "run_workloads",
    "select",
]
