"""scheduler_perf workload config loader.

Accepts the reference's performance-config.yaml schema verbatim
(test/integration/scheduler_perf/scheduler_perf.go:66-78; config format
in config/performance-config.yaml): a list of test cases, each with a
workloadTemplate (ordered opcodes) and named workloads supplying
params.  `$param` strings and `countParam` references resolve against
the workload's params at expansion time.

Opcodes implemented (of scheduler_perf.go's ten): createNodes,
createNamespaces, createPods, churn, barrier, sleep — the set the
non-DRA/PV cases use.  Unknown opcodes raise (silent skips would turn a
coverage gap into a fake pass).

Template paths resolve relative to the config file; templates are
Kubernetes YAML parsed by perf.kubeyaml.  `$index` appearing in template
metadata/label string values is substituted with the object's creation
index (how our shipped configs express per-node zones; reference
configs without it are unaffected).
"""

from __future__ import annotations

import copy
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml


@dataclass
class Op:
    opcode: str
    count: int = 0
    namespace: Optional[str] = None
    prefix: str = ""
    collect_metrics: bool = False
    pod_template: Optional[dict] = None
    node_template: Optional[dict] = None
    # churn
    mode: str = "recreate"
    number: int = 1
    interval_ms: int = 500
    templates: List[dict] = field(default_factory=list)
    # sleep
    duration_s: float = 0.0
    # barrier
    wait_for_pods_scheduled: bool = True


@dataclass
class Workload:
    case_name: str
    name: str
    labels: List[str]
    ops: List[Op]

    @property
    def full_name(self) -> str:
        return f"{self.case_name}/{self.name}"


def _resolve(value: Any, params: Dict[str, Any]) -> Any:
    if isinstance(value, str) and value.startswith("$"):
        key = value[1:]
        if key not in params:
            raise KeyError(f"workload param {value} not supplied")
        return params[key]
    return value


def _load_template(path: Optional[str], base_dir: str) -> Optional[dict]:
    if not path:
        return None
    full = path if os.path.isabs(path) else os.path.join(base_dir, path)
    # reference configs reference templates under "config/"; ours live
    # next to the config file — try both
    if not os.path.exists(full):
        alt = os.path.join(base_dir, os.path.basename(path))
        if os.path.exists(alt):
            full = alt
    with open(full) as f:
        return yaml.safe_load(f)


def _parse_duration(v: Any) -> float:
    """Go-style duration strings ('5s', '100ms', '1m') or numbers."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    for suf, mult in (("ms", 0.001), ("s", 1.0), ("m", 60.0), ("h", 3600.0)):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    return float(s)


def _expand_op(
    raw: Dict[str, Any], params: Dict[str, Any], base_dir: str, default_pod: Optional[dict]
) -> Op:
    opcode = raw.get("opcode")
    if opcode == "createNodes":
        count = int(_resolve(raw.get("countParam", raw.get("count", 0)), params))
        return Op(
            opcode=opcode,
            count=count,
            node_template=_load_template(raw.get("nodeTemplatePath"), base_dir),
        )
    if opcode == "createNamespaces":
        return Op(
            opcode=opcode,
            count=int(_resolve(raw.get("countParam", raw.get("count", 0)), params)),
            prefix=raw.get("prefix", "ns"),
        )
    if opcode == "createPods":
        return Op(
            opcode=opcode,
            count=int(_resolve(raw.get("countParam", raw.get("count", 0)), params)),
            namespace=raw.get("namespace"),
            collect_metrics=bool(raw.get("collectMetrics", False)),
            pod_template=_load_template(raw.get("podTemplatePath"), base_dir)
            or default_pod,
        )
    if opcode == "churn":
        templates = [
            _load_template(p, base_dir) for p in raw.get("templatePaths") or []
        ]
        return Op(
            opcode=opcode,
            mode=raw.get("mode", "recreate"),
            number=int(_resolve(raw.get("numberParam", raw.get("number", 1)), params)),
            interval_ms=int(raw.get("intervalMilliseconds", 500)),
            namespace=raw.get("namespace"),
            templates=[t for t in templates if t],
        )
    if opcode == "barrier":
        return Op(opcode=opcode, namespace=raw.get("namespace"))
    if opcode == "sleep":
        return Op(opcode=opcode, duration_s=_parse_duration(raw.get("duration", 0)))
    raise ValueError(f"unsupported opcode {opcode!r} (scheduler_perf.go:66-78)")


def load_config(path: str) -> List[Workload]:
    """Parse a performance-config.yaml into expanded workloads."""
    base_dir = os.path.dirname(os.path.abspath(path))
    with open(path) as f:
        cases = yaml.safe_load(f)
    out: List[Workload] = []
    for case in cases:
        default_pod = _load_template(case.get("defaultPodTemplatePath"), base_dir)
        template = case.get("workloadTemplate") or []
        for wl in case.get("workloads") or []:
            params = dict(wl.get("params") or {})
            ops = [
                _expand_op(copy.deepcopy(op), params, base_dir, default_pod)
                for op in template
            ]
            out.append(
                Workload(
                    case_name=case["name"],
                    name=wl["name"],
                    labels=list(wl.get("labels") or []),
                    ops=ops,
                )
            )
    return out


def select(
    workloads: List[Workload], label: Optional[str] = None, name: Optional[str] = None
) -> List[Workload]:
    picked = workloads
    if label:
        picked = [w for w in picked if label in w.labels]
    if name:
        picked = [w for w in picked if name in w.full_name]
    return picked
