"""Throughput and metrics collectors emitting DataItems.

Reference: test/integration/scheduler_perf/util.go:364-475
(throughputCollector sampling scheduled-pod deltas on a fixed interval;
collect() summarizing Average/Perc50/90/95/99) and
scheduler_perf.go:100-112 (metricsCollector scraping histograms).
DataItem JSON shape matches the reference's {data, unit, labels} so
perf-dash-style tooling can ingest either.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional

from ..api import store as st
from ..scheduler.metrics import Counter, Gauge, Histogram, Registry


class DataItem(dict):
    """{"data": {...}, "unit": str, "labels": {...}} — util.go DataItem."""

    def __init__(self, data: Dict[str, float], unit: str, labels: Dict[str, str]):
        super().__init__(data=data, unit=unit, labels=labels)


def _percentiles(sorted_vals: List[float]) -> Dict[str, float]:
    n = len(sorted_vals)
    if n == 0:
        return {}
    pick = lambda q: sorted_vals[max(0, int(math.ceil(n * q / 100)) - 1)]
    return {
        "Average": sum(sorted_vals) / n,
        "Perc50": pick(50),
        "Perc90": pick(90),
        "Perc95": pick(95),
        "Perc99": pick(99),
    }


class ThroughputCollector:
    """Samples scheduled-pod count deltas every `interval` seconds in a
    thread (util.go:364 run()); zero-delta intervals are coalesced into
    the next non-zero sample, skipped-interval style."""

    def __init__(
        self,
        store: st.Store,
        namespaces: Optional[List[str]] = None,
        interval: float = 0.1,
        labels: Optional[Dict[str, str]] = None,
        pod_names: Optional[set] = None,
        lister=None,
    ):
        self.store = store
        self.namespaces = namespaces
        self.interval = interval
        self.labels = dict(labels or {})
        # When set, only these pods count — preemption workloads DELETE
        # bound victims, so counting every scheduled pod in the namespace
        # would produce negative deltas.
        self.pod_names = pod_names
        # cheap pod source (e.g. an informer cache's list): store.list
        # deep-copies every object per call, and a 100ms sampling loop
        # over thousands of pods GIL-starves the scheduler it measures
        self.lister = lister or (lambda: store.list("Pod")[0])
        self.samples: List[float] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _scheduled_count(self) -> int:
        pods = self.lister()
        return sum(
            1
            for p in pods
            if p.spec.node_name
            and (self.namespaces is None or p.meta.namespace in self.namespaces)
            and (self.pod_names is None or p.meta.name in self.pod_names)
        )

    def _run(self) -> None:
        last = self._scheduled_count()
        last_t = time.monotonic()
        self._baseline = last
        skipped = 0
        while not self._stop.wait(self.interval):
            now = time.monotonic()
            cur = self._scheduled_count()
            delta = cur - last
            if delta == 0:
                if cur == last and last == self._baseline:
                    # still idle before the run's first placement: slide
                    # the window start so the FIRST non-zero delta is
                    # measured over one interval, not the whole idle
                    # lead-in.  The old first-observation reset discarded
                    # that delta entirely — a burst that completed inside
                    # one interval produced NO samples and the summary
                    # reported Average=0.0 (PreemptionBasic/500Nodes).
                    last_t = now
                else:
                    skipped += 1  # mid-run stall: coalesce into the next
                continue
            throughput = delta / max(now - last_t, 1e-9)
            for _ in range(skipped + 1):
                self.samples.append(throughput)
            last, last_t, skipped = cur, now, 0
        # final sub-window sample: a burst that finished after the last
        # tick (or entirely between start and stop) would otherwise be
        # dropped on the floor
        now = time.monotonic()
        cur = self._scheduled_count()
        if cur - last > 0:
            self.samples.append((cur - last) / max(now - last_t, 1e-9))

    def start(self) -> "ThroughputCollector":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def collect(self) -> List[DataItem]:
        vals = sorted(self.samples)
        if not vals:
            return []
        labels = dict(self.labels)
        labels["Metric"] = "SchedulingThroughput"
        return [DataItem(_percentiles(vals), "pods/s", labels)]


def histogram_baseline(registry: Registry) -> Dict[str, tuple]:
    """Snapshot histogram counters so a later MetricsCollector can report
    the measured WINDOW only — the reference's metricsCollector inits at
    the collectMetrics op's start and diffs at collect
    (scheduler_perf.go:100-112); without the diff the summary mixes the
    init-phase and warmup attempts into the measured percentiles."""
    out: Dict[str, tuple] = {}
    for name, m in registry.snapshot().items():
        if isinstance(m, Histogram):
            with m._lock:
                out[name] = (list(m.counts), m.total, m.n)
    return out


class MetricsCollector:
    """Extracts percentile summaries from the scheduler's histograms by
    reference metric name (scheduler_perf.go:100-112)."""

    # seconds-unit histograms, reported as ms percentiles.  The three
    # export surfaces below are reconciled against scheduler/metrics.py
    # Registry by graftlint's registry pass (make lint): every name here
    # must exist there, and every Registry metric must appear in exactly
    # one of these tuples.
    DEFAULT_METRICS = (
        "scheduler_scheduling_attempt_duration_seconds",
        "scheduler_scheduling_algorithm_duration_seconds",
        "scheduler_batch_solve_duration_seconds",
        "scheduler_pod_scheduling_sli_duration_seconds",
        # solve-side pipeline: exposed compile time and the readback
        # hidden behind host work (scheduler/metrics.py)
        "scheduler_solve_compile_duration_seconds",
        "scheduler_decode_overlap_seconds",
        # solve/bind pipeline stages (docs/scheduler_loop.md) — were
        # registered but never exported (graftlint registry drift)
        "scheduler_schedule_batch_duration_seconds",
        "scheduler_commit_wave_duration_seconds",
        "scheduler_pipeline_overlap_seconds",
        # sharded-store commit fan-out: per-shard sub-wave durations and
        # the realized cross-shard commit overlap (docs/scheduler_loop.md)
        "scheduler_commit_subwave_duration_seconds",
        "scheduler_commit_subwave_overlap_seconds",
        # batched PostFilter: one shared encode + [P, N, K] dry-run per
        # preemption pass (docs/scheduler_loop.md preemption section)
        "scheduler_preemption_solve_duration_seconds",
    )

    # count-unit histograms: reported as raw percentiles (no ms scaling —
    # wave/batch sizes and victim counts, not durations)
    COUNT_METRICS = (
        "scheduler_commit_wave_size_pods",
        "scheduler_solve_wave_count",
        "scheduler_solve_wave_fallbacks",
        "scheduler_preemption_victims",
        # failed pods sharing one batched preemption dry-run
        "scheduler_preemption_batch_size_pods",
        # commit lead (ms) each streamed sub-wave gained over the
        # whole-wave hand-off (docs/scheduler_loop.md multi-lane cycle)
        "scheduler_subwave_stream_lead_ms",
    )

    # breaker / supervision / journal-recovery scalars (gauges and
    # counters, reported as one Total value — docs/robustness.md), plus
    # the attempt/pending totals that were registered but unexported
    SCALAR_METRICS = (
        "scheduler_solve_breaker_state",
        "scheduler_solve_fallback_total",
        # solver XLA traces seen by the retrace tracker (armed runs only)
        "scheduler_solve_retrace_total",
        # sharded multichip solve: mesh size, device-mirror transfer
        # accounting (resyncs / delta rows), and single-chip fallbacks
        # (docs/scheduler_loop.md mesh mode)
        "scheduler_solve_shard_count",
        "scheduler_mirror_resync_total",
        "scheduler_mirror_delta_rows",
        "scheduler_sharded_solve_fallbacks",
        # elastic node axis: in-place resident grows (vs full resyncs),
        # the rows they added, the hysteresis-governed pad bucket, and
        # deferred-compaction work (docs/scheduler_loop.md)
        "scheduler_mirror_grow_total",
        "scheduler_mirror_grow_rows",
        "scheduler_node_axis_bucket",
        "scheduler_compactions_total",
        "scheduler_compaction_moved_rows",
        # incremental O(changes) solving: resident-partials hit/recompute
        # accounting, full recomputes, and speculation rollbacks
        # (docs/scheduler_loop.md incremental-solve section)
        "scheduler_partials_hit_rows",
        "scheduler_partials_recomputed_rows",
        "scheduler_partials_full_recomputes_total",
        "scheduler_partials_rollbacks_total",
        # graftcoh runtime epoch auditor (GRAFTLINT_COHERENCE=1; 0 when
        # disarmed — docs/static_analysis.md coherence section)
        "scheduler_coherence_audits_total",
        "scheduler_coherence_violations_total",
        # graftobl runtime exactly-once ledger (GRAFTLINT_OBLIGATIONS=1;
        # all 0 when disarmed — docs/static_analysis.md obligations
        # section)
        "scheduler_obligations_tracked_total",
        "scheduler_obligation_leaks_total",
        "scheduler_obligation_double_discharge_total",
        "scheduler_binder_restarts_total",
        "scheduler_binder_poison_waves_total",
        "scheduler_journal_recovered_records",
        # crash-restart recovery: store snapshot/suffix recovery cost,
        # checkpoint count, stale-leader fenced waves, and leadership
        # reconciliations (docs/robustness.md recovery contract)
        "scheduler_store_recovery_duration_ms",
        "scheduler_store_snapshot_records",
        "scheduler_store_journal_suffix_records",
        "scheduler_store_checkpoints_total",
        "scheduler_store_shard_count",
        "scheduler_fenced_writes_total",
        "scheduler_leader_reconcile_total",
        # overload protection: watch fan-out backpressure + adaptive
        # batch window (docs/robustness.md)
        "scheduler_watch_queue_depth",
        "scheduler_watch_coalesced_total",
        "scheduler_watch_expired_total",
        "scheduler_watch_terminated_total",
        "scheduler_batch_window_ms",
        "scheduler_overload_level",
        "scheduler_overload_shed_total",
        "scheduler_schedule_attempts_total",
        "scheduler_pending_pods",
        "scheduler_preemption_attempts_total",
        # batched preemption: cross-preemptor conflict recomputes and
        # PDB-blocked candidate rankings (docs/scheduler_loop.md)
        "scheduler_preemption_conflict_serializations_total",
        "scheduler_preemption_pdb_blocked_total",
        # pipelined multi-lane cycle: concurrent profile lanes,
        # speculative dispatches and invalidated speculations
        # (docs/scheduler_loop.md)
        "scheduler_lane_count",
        "scheduler_speculative_solves_total",
        "scheduler_misspeculation_total",
        # columnar host plane: encode throughput, framed journal bytes,
        # fan-out chunking, and the c6s ramp knee
        # (docs/scheduler_loop.md host plane section)
        "scheduler_encode_rows_per_s",
        "scheduler_journal_frame_bytes",
        "scheduler_fanout_chunk_size",
        "scheduler_c6s_arrival_knee_pods_per_s",
        # serving plane: adaptive APF seat/shed accounting, write-
        # deadline stalls, and replica failovers
        # (docs/robustness.md serving-plane section)
        "scheduler_apf_seats_current",
        "scheduler_apf_rejected_total",
        "scheduler_server_watch_write_stalls_total",
        "scheduler_replica_failovers_total",
        # graftsched: interleaving schedules explored / yield points
        # scheduled (analysis/interleave.py) and static atomicity
        # findings at the last mirrored run (docs/static_analysis.md)
        "scheduler_interleave_schedules_total",
        "scheduler_interleave_yield_points",
        "scheduler_atomicity_findings",
        # TPU slice topology: post-solve fragmentation and gang
        # carve-out outcomes (docs/scheduler_loop.md)
        "scheduler_fragmentation_score",
        "scheduler_slice_carveouts_total",
        "scheduler_slice_carveout_fallbacks_total",
        "scheduler_gang_contiguous_placements_total",
    )

    def __init__(
        self,
        registry: Registry,
        labels: Optional[Dict[str, str]] = None,
        baseline: Optional[Dict[str, tuple]] = None,
    ):
        self.registry = registry
        self.labels = dict(labels or {})
        self.baseline = baseline or {}

    def _windowed(self, name: str, h: Histogram) -> Histogram:
        base = self.baseline.get(name)
        if base is None:
            return h
        counts0, total0, n0 = base
        with h._lock:
            d = Histogram(name, tuple(h.buckets))
            d.counts = [c - c0 for c, c0 in zip(h.counts, counts0)]
            d.total = h.total - total0
            d.n = h.n - n0
            d.max = h.max  # upper bound; per-window max isn't tracked
        return d

    def collect(self) -> List[DataItem]:
        out: List[DataItem] = []
        snap = self.registry.snapshot()
        for name in self.DEFAULT_METRICS:
            h = snap.get(name)
            if not isinstance(h, Histogram):
                continue
            h = self._windowed(name, h)
            if h.n == 0:
                continue
            labels = dict(self.labels)
            labels["Metric"] = name
            ms = 1000.0  # histograms record seconds; DataItems report ms
            out.append(
                DataItem(
                    {
                        "Average": h.average * ms,
                        "Perc50": h.percentile(0.50) * ms,
                        "Perc90": h.percentile(0.90) * ms,
                        "Perc95": h.percentile(0.95) * ms,
                        "Perc99": h.percentile(0.99) * ms,
                    },
                    "ms",
                    labels,
                )
            )
        for name in self.COUNT_METRICS:
            h = snap.get(name)
            if not isinstance(h, Histogram):
                continue
            h = self._windowed(name, h)
            if h.n == 0:
                continue
            labels = dict(self.labels)
            labels["Metric"] = name
            out.append(
                DataItem(
                    {
                        "Average": h.average,
                        "Perc50": h.percentile(0.50),
                        "Perc90": h.percentile(0.90),
                        "Perc95": h.percentile(0.95),
                        "Perc99": h.percentile(0.99),
                    },
                    "count",
                    labels,
                )
            )
        for name in self.SCALAR_METRICS:
            m = snap.get(name)
            if isinstance(m, Counter):
                value = m.total
            elif isinstance(m, Gauge):
                # labeled gauges (pending_pods per tier) report the
                # cross-label total; unlabeled ones their bare value
                value = m.total
            else:
                continue
            if value == 0.0:
                continue  # quiet metrics don't clutter the summary
            labels = dict(self.labels)
            labels["Metric"] = name
            out.append(DataItem({"Total": value}, "count", labels))
        return out
