"""Cache debugger: dual-bookkeeping comparison + state dump.

Reference: internal/cache/debugger — CacheComparer diffs the scheduler
cache against the informer's authoritative view (comparer.go:135) and
CacheDumper snapshots it, both wired to SIGUSR2 (signal.go:26).  The
race-detection value is the invariant: after any interleaving of
informer events and solve/assume/forget traffic, the tensor state must
equal what the store says.
"""

from __future__ import annotations

import signal
from typing import Dict, List, Tuple

from ..api import store as st
from .cache import SchedulerCache
from .queue import pod_key


class CacheComparer:
    def __init__(self, store: st.Store, cache: SchedulerCache):
        self.store = store
        self.cache = cache

    def compare(self) -> List[str]:
        """Differences between the store's authoritative state and the
        scheduler cache's tensor bookkeeping; empty list = consistent.
        Assumed-but-unconfirmed pods are expected deltas and excluded
        (the comparer tolerates in-flight assumes, comparer.go:68)."""
        problems: List[str] = []
        with self.cache.lock:
            state = self.cache.state
            assumed = set(self.cache._assumed.keys())
            waiting = {
                k
                for by_node in self.cache._waiting_on_node.values()
                for k in by_node.keys()
            }

            nodes, _ = self.store.list("Node")
            store_nodes = {n.meta.name for n in nodes}
            cache_nodes = set(state._rows.keys())
            for missing in store_nodes - cache_nodes:
                problems.append(f"node {missing} in store but not cache")
            for extra in cache_nodes - store_nodes:
                problems.append(f"node {extra} in cache but not store")

            pods, _ = self.store.list("Pod")
            store_bound = {
                pod_key(p): p.spec.node_name
                for p in pods
                if p.spec.node_name and p.spec.node_name in cache_nodes
            }
            cache_bound = dict(state._pod_node)
            for k, node in store_bound.items():
                if k in waiting:
                    continue  # delivered before its node; parked by design
                got = cache_bound.get(k)
                if got is None and k not in assumed:
                    problems.append(f"pod {k} bound to {node} missing from cache")
                elif got is not None and got != node:
                    problems.append(
                        f"pod {k}: store says {node}, cache says {got}"
                    )
            for k, node in cache_bound.items():
                if k not in store_bound and k not in assumed:
                    problems.append(f"pod {k} on {node} in cache but not store")
        return problems

    def dump(self) -> Dict[str, object]:
        """The CacheDumper analogue: a host-readable snapshot summary."""
        with self.cache.lock:
            state = self.cache.state
            return {
                "nodes": len(state._rows),
                "bound_pods": len(state._pods),
                "assumed": len(self.cache._assumed),
                "waiting_on_node": sum(
                    len(v) for v in self.cache._waiting_on_node.values()
                ),
                "nominated": len(self.cache._nominated),
            }

    def install_signal_handler(self, signum=signal.SIGUSR2) -> None:
        """Dump + compare on SIGUSR2 (debugger/signal.go:26)."""

        def handler(_sig, _frame):
            import logging

            log = logging.getLogger(__name__)
            log.warning("cache dump: %s", self.dump())
            for p in self.compare():
                log.warning("cache comparer: %s", p)

        signal.signal(signum, handler)
