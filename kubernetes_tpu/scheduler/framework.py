"""The scheduling framework seam: extension points + profiles.

Reference: the 11-point plugin API (framework/interface.go:330-666) and
profile.Map (profile/profile.go:46).  The TPU redesign keeps the
HOST-side extension points as ordered plugin lists — out-of-tree code
registers plain callables — while the device-side points (PreFilter/
Filter/Score/Normalize) are the fused kernels, configured per profile
through ScoreConfig rather than per-plugin chains (you cannot insert a
Python callback into the middle of one XLA dispatch; that coupling is
the design).

Extension points exposed here and where they run:

  pre_enqueue(pod) -> Optional[str]   gate a pod out of the queue with a
                                      reason (SchedulingGates built in)
  post_filter(pod) -> Optional[str]   after a failed cycle; returns a
                                      nominated node (preemption default)
  pre_bind(pod, node) -> None         before the API bind; raise to abort
                                      (volume-attach analogue)
  post_bind(pod, node) -> None        fire-and-forget after bind
  filter_result(pod, node) -> node    final veto/override hook on a
                                      placement before assume (the
                                      extender call-site analogue)

A Framework belongs to one profile; FrameworkRegistry maps
pod.spec.scheduler_name -> Framework (frameworkForPod, scheduler.go:358
— pods naming an unknown scheduler are not ours to schedule).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

from ..api import types as api
from ..models.batch_scheduler import TPUBatchScheduler
from ..ops import schema
from .config import ProfileConfig, SchedulerConfiguration


class Framework:
    """One profile's runtime: its jitted solvers + host extension points."""

    def __init__(self, profile: ProfileConfig, tpu: TPUBatchScheduler):
        self.profile = profile
        self.tpu = tpu
        self.pre_enqueue: List[Callable[[api.Pod], Optional[str]]] = []
        self.post_filter: List[Callable[[api.Pod], Optional[str]]] = []
        self.pre_bind: List[Callable[[api.Pod, str], None]] = []
        self.post_bind: List[Callable[[api.Pod, str], None]] = []
        self.filter_result: List[Callable[[api.Pod, str], Optional[str]]] = []
        # Reserve's rollback half (interface.go Reserve/Unreserve): runs
        # when a placement is abandoned after filter_result accepted it
        # (assume failure, PreBind error, bind conflict)
        self.unreserve: List[Callable[[api.Pod], None]] = []
        # Permit (interface.go:330-666): each plugin returns
        # ("allow" | "reject" | "wait", timeout_seconds); any reject
        # wins, any wait parks the pod in the waiting map and its
        # binding thread blocks in WaitOnPermit (schedule_one.go:278)
        self.permit: List[Callable[[api.Pod, str], tuple]] = []
        # set by the Scheduler: the metrics Registry whose
        # framework_extension_point_duration vec the runners observe
        # (frameworkImpl.metricsRecorder, runtime/framework.go)
        self.metrics = None

    def _observe(self, point: str, t0: float) -> None:
        if self.metrics is not None:
            self.metrics.framework_extension_point_duration.labels(
                point
            ).observe(time.monotonic() - t0)

    @property
    def scheduler_name(self) -> str:
        return self.profile.scheduler_name

    def register(self, point: str, fn: Callable) -> None:
        """Out-of-tree plugin registration (the merge at scheduler.go:
        278-281): `point` names one of the host extension lists."""
        getattr(self, point).append(fn)

    # -- runners -----------------------------------------------------------

    def run_pre_enqueue(self, pod: api.Pod) -> Optional[str]:
        t0 = time.monotonic()
        try:
            for fn in self.pre_enqueue:
                reason = fn(pod)
                if reason:
                    return reason
            return None
        finally:
            self._observe("PreEnqueue", t0)

    def run_post_filter(self, pod: api.Pod) -> Optional[str]:
        t0 = time.monotonic()
        try:
            for fn in self.post_filter:
                nominated = fn(pod)
                if nominated:
                    return nominated
            return None
        finally:
            self._observe("PostFilter", t0)

    def run_pre_bind(self, pod: api.Pod, node: str) -> None:
        t0 = time.monotonic()
        try:
            for fn in self.pre_bind:
                fn(pod, node)  # raising aborts the bind (reference semantics)
        finally:
            self._observe("PreBind", t0)

    def run_post_bind(self, pod: api.Pod, node: str) -> None:
        t0 = time.monotonic()
        for fn in self.post_bind:
            try:
                fn(pod, node)
            except Exception:
                pass  # PostBind is informational (interface.go:624)
        self._observe("PostBind", t0)

    def run_filter_result(self, pod: api.Pod, node: str) -> Optional[str]:
        t0 = time.monotonic()
        try:
            for fn in self.filter_result:
                node = fn(pod, node)
                if node is None:
                    return None
            return node
        finally:
            self._observe("Reserve", t0)

    def run_unreserve(self, pod: api.Pod) -> None:
        t0 = time.monotonic()
        for fn in self.unreserve:
            try:
                fn(pod)
            except Exception:
                pass  # rollback must not mask the original failure
        self._observe("Unreserve", t0)

    def run_permit(self, pod: api.Pod, node: str) -> tuple:
        """Combined Permit verdict: ("allow"|"reject"|"wait", timeout).
        Reject short-circuits; wait accumulates the LONGEST requested
        timeout (RunPermitPlugins, runtime/framework.go).  A plugin
        exception is a reject (the reference turns plugin errors into a
        non-success Status) — letting it propagate after cache.assume
        would leak the assumed capacity forever."""
        t0 = time.monotonic()
        try:
            verdict, timeout = "allow", 0.0
            for fn in self.permit:
                try:
                    v, t = fn(pod, node)
                except Exception:
                    logging.getLogger(__name__).exception(
                        "permit plugin %r failed for %s/%s; rejecting",
                        fn, pod.meta.namespace, pod.meta.name,
                    )
                    return "reject", 0.0
                if v == "reject":
                    return "reject", 0.0
                if v == "wait":
                    verdict = "wait"
                    timeout = max(timeout, float(t))
            return verdict, timeout
        finally:
            self._observe("Permit", t0)


class FrameworkRegistry:
    """profile.Map: scheduler_name -> Framework, all profiles sharing ONE
    cluster state (the reference shares one cache across profiles)."""

    def __init__(
        self,
        config: SchedulerConfiguration,
        state: Optional[schema.ClusterState] = None,
    ):
        config.validate()
        self.config = config
        self.gate = config.gate()
        # AuctionSolver gate pins the router to the greedy scan — the
        # registry build-time consult, like the reference's gate-driven
        # plugin registry (plugins/registry.go:58-70)
        mode = "auto" if self.gate.enabled("AuctionSolver") else "greedy"
        use_mirror = self.gate.enabled("DeviceClusterMirror")
        # incremental O(changes) solving: per-profile PartialsCache
        # warm-starting the greedy/wavefront solves from the mirror's
        # resident tensors (models/partials.py; needs the mirror)
        use_partials = use_mirror and self.gate.enabled("IncrementalSolve")
        # meshDevices + the ShardedSolve gate make mesh mode a
        # config-constructible production configuration: one mesh shared
        # by every profile, node axis sharded in all three solver
        # families (parallel/sharded.py)
        mesh = None
        if config.mesh_devices and self.gate.enabled("ShardedSolve"):
            import jax

            from ..parallel.sharded import make_mesh

            avail = len(jax.devices())
            if avail < config.mesh_devices:
                raise ValueError(
                    f"meshDevices={config.mesh_devices} but only {avail} "
                    "JAX devices are visible (set "
                    "XLA_FLAGS=--xla_force_host_platform_device_count=N "
                    "for a forced host-platform mesh)"
                )
            mesh = make_mesh(config.mesh_devices)
        first: Optional[TPUBatchScheduler] = None
        self.frameworks: Dict[str, Framework] = {}
        # multi-profile configs run concurrent LANES sharing one
        # device/mesh: one dispatch arbiter admits their device programs
        # (double-buffer depth, FIFO-ish fairness).  A single profile
        # has no contention and pays nothing.
        from ..models.batch_scheduler import DispatchArbiter

        self.arbiter = (
            DispatchArbiter() if len(config.profiles) > 1 else None
        )
        for profile in config.profiles:
            tpu = TPUBatchScheduler(
                score_config=profile.effective_score_config(),
                limits=config.effective_limits() if first is None else None,
                state=first.state if first is not None else state,
                mode=mode,
                use_mirror=use_mirror,
                mesh=mesh,
                arbiter=self.arbiter,
                carveout_policy=config.slice_carveout_policy,
                use_partials=use_partials,
                partials_resync_interval=config.partials_resync_interval,
            )
            if first is None:
                first = tpu
            self.frameworks[profile.scheduler_name] = Framework(profile, tpu)
        self.default = next(iter(self.frameworks.values()))
        # elastic node axis: the knobs live on the ONE ClusterState all
        # profiles share (tensors()'s bucket hysteresis and remove_node's
        # deferred compaction are state-side, not per-profile)
        self.state.configure_elastic_axis(
            headroom=config.node_axis_headroom,
            shrink_dwell=config.bucket_shrink_dwell,
            compaction_batch_rows=config.compaction_batch_rows,
        )

    @property
    def state(self) -> schema.ClusterState:
        return self.default.tpu.state

    def for_pod(self, pod: api.Pod) -> Optional[Framework]:
        """frameworkForPod: None means the pod names another scheduler
        and is not ours (scheduler.go:358-367 skipPodSchedule)."""
        return self.frameworks.get(pod.spec.scheduler_name)

    def __iter__(self):
        return iter(self.frameworks.values())
