"""The versioned scheduler configuration API.

Reference: KubeSchedulerConfiguration (apis/config/types.go:37-100) —
profiles with per-plugin weights/enablement, backoff bounds, parallelism
and percentageOfNodesToScore — with defaulting and validation
(apis/config/{v1,validation}).  Mapped onto the TPU design:

  * score-plugin weights/disables become the profile's ScoreConfig (a
    disabled score plugin is weight 0 — kernels read weights directly);
  * FILTER plugins cannot be individually disabled: the filter chain is
    one fused kernel, and validation rejects the attempt rather than
    silently ignoring it;
  * parallelism (goroutine fan-out, types.go:48) and
    percentageOfNodesToScore (adaptive sampling) have no TPU meaning —
    one dispatch filters and scores every node (SURVEY §2.7).  They are
    accepted for config-file parity and validated, nothing more.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

from ..ops.schema import SnapshotLimits
from ..ops.scores import DEFAULT_SCORE_CONFIG, ScoreConfig
from ..utils.featuregate import FeatureGate

# Score plugins that map onto ScoreConfig weights (names/names.go:20-43).
SCORE_PLUGIN_WEIGHTS = {
    "NodeResourcesFit": "fit_weight",
    "NodeResourcesBalancedAllocation": "balanced_weight",
    "NodeAffinity": "node_affinity_weight",
    "TaintToleration": "taint_weight",
    "PodTopologySpread": "spread_weight",
    "InterPodAffinity": "interpod_weight",
    "ImageLocality": "image_weight",
}


@dataclass
class ProfileConfig:
    """One scheduler profile (apis/config KubeSchedulerProfile)."""

    scheduler_name: str = "default-scheduler"
    score_config: ScoreConfig = field(default_factory=lambda: DEFAULT_SCORE_CONFIG)
    disabled_score_plugins: Tuple[str, ...] = ()

    def effective_score_config(self) -> ScoreConfig:
        cfg = self.score_config
        for name in self.disabled_score_plugins:
            cfg = replace(cfg, **{SCORE_PLUGIN_WEIGHTS[name]: 0.0})
        return cfg


@dataclass
class SchedulerConfiguration:
    profiles: List[ProfileConfig] = field(
        default_factory=lambda: [ProfileConfig()]
    )
    batch_size: int = 4096
    # bounded batch-accumulation window: how long pop_batch keeps
    # collecting arrivals once it has at least one pod but fewer than
    # batch_size, so churn-paced creates form real batches instead of
    # near-empty solves.  Every pod in the batch pays the window as
    # queueing latency, so it is capped at the attempt-latency budget
    # (validation rejects > 1s; default 50ms).  With the adaptive
    # controller enabled this is the no-signal starting window.
    batch_window_seconds: float = 0.05
    # adaptive window (docs/scheduler_loop.md): pop_batch's window tracks
    # observed arrival rate and solve/commit cost so sustained churn
    # forms big batches while sparse arrivals pop near-immediately;
    # bounds and the latency SLO the sizing targets (w + r*w*c <= slo).
    adaptive_batch_window: bool = True
    batch_window_min_seconds: float = 0.005
    batch_window_max_seconds: float = 0.25
    batch_latency_slo_seconds: float = 0.5
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    assume_ttl_seconds: float = 30.0
    unschedulable_flush_seconds: float = 300.0
    max_preemptions_per_cycle: int = 16
    # sharded multichip solve (docs/scheduler_loop.md mesh mode): shard
    # the node axis of every solve across this many devices.  0 (the
    # default) stays single-chip; mesh sizes must be powers of two so
    # padded node buckets split evenly.  Consulted at registry build
    # time together with the ShardedSolve feature gate.
    mesh_devices: int = 0
    # sharded-store commit fan-out (docs/scheduler_loop.md): a bind wave
    # is partitioned into per-store-shard sub-waves and the binder
    # commits up to this many concurrently, so shard A's journal fsync /
    # watch fan-out overlaps shard B's (and the next solve).  1
    # serializes sub-waves; the effective width is min(this, store
    # shards).
    commit_subwave_concurrency: int = 4
    # Pipelined multi-lane scheduling (docs/scheduler_loop.md):
    # scheduler_lanes caps the number of concurrent profile lanes — each
    # lane runs its own pop→encode→solve pipeline over its profiles'
    # disjoint pod classes, sharing one device/mesh through the dispatch
    # arbiter.  0 = auto (one lane per configured profile); 1 pins the
    # serial single-thread loop regardless of profile count.
    scheduler_lanes: int = 0
    # Speculative solve overlap: batch N+1's encode/solve runs against
    # batch N's ASSUMED placements while N's wave is still committing
    # (the PR 1 assume-cache bridge extended across the commit seam).  A
    # commit failure / fence after the speculative dispatch invalidates
    # the in-flight batch — it requeues with backoff and counts into
    # scheduler_misspeculation_total.  False serializes strictly: a new
    # batch dispatches only once every staged wave has committed.
    speculative_solve: bool = True
    # Streamed sub-wave commits: staged placements are handed to the
    # commit pool per STORE SHARD as each shard's slice of the wave is
    # decoded+staged, instead of after the whole wave stages — shard A's
    # commit overlaps shard B's staging and the next solve.  Requires a
    # multi-shard store (a 1-shard store keeps the whole-wave path).
    stream_subwaves: bool = True
    # TPU slice carve-outs (docs/scheduler_loop.md "TPU slice topology"):
    # how gang/claim carve-out requests (pod.spec.tpu_topology /
    # ResourceClaim.spec.topology) bind to slice sub-cuboids.
    #   prefer  — carve-out quality rides the score (contiguous
    #             placements rank strictly above fragmenting ones; a
    #             gang that can't fit contiguously scatters and counts a
    #             carveout fallback);
    #   require — the carve-out preference becomes a filter: a gang
    #             without a free contiguous sub-cuboid parks whole
    #             (all-or-nothing releases the anchor too);
    #   off     — the slice family is disarmed.
    slice_carveout_policy: str = "prefer"
    # largest per-axis torus extent a slice may declare
    # (SnapshotLimits.max_slice_dim — bounds the carve-out grid);
    # 0 keeps the SnapshotLimits default
    slice_max_dim: int = 0
    # Incremental O(changes) solving (docs/scheduler_loop.md
    # "Incremental solve: resident partials"): forced full recompute of
    # the device-resident Filter/Score partials every this many delta
    # syncs — the periodic half of the cache's resync/parity discipline
    # (struct/vocab invalidation and the decode-side parity gate are
    # unconditional).  Armed by the IncrementalSolve feature gate.
    partials_resync_interval: int = 1024
    # Elastic node axis (docs/scheduler_loop.md "Elastic node axis"):
    # nodeAxisHeadroom is the backing-array growth factor applied when
    # ClusterState reallocates under autoscaler growth (rounded up to
    # the next power-of-two bucket; >= 1.0 — larger values amortize
    # host-side reallocs across more node adds);
    node_axis_headroom: float = 2.0
    # bucketShrinkDwell is the number of consecutive snapshot
    # generations occupancy must sit below the lower pad bucket before
    # tensors() shrinks the exposed bucket — the hysteresis that keeps
    # scale-up/down oscillation around a boundary from flip-flopping
    # compile keys and resident device arrays;
    bucket_shrink_dwell: int = 8
    # compactionBatchRows caps the rows one deferred-compaction
    # invocation relocates during scale-down (amortized trigger: a
    # full drain does O(live) total work, never O(live^2)).
    compaction_batch_rows: int = 512
    # parity-only knobs (see module docstring)
    parallelism: int = 16
    percentage_of_nodes_to_score: int = 100
    limits: Optional[SnapshotLimits] = None
    # feature-gate overrides (utils.featuregate.DEFAULT_FEATURES),
    # consulted at registry/router build time — e.g. AuctionSolver=false
    # pins every profile's solver to the greedy scan
    feature_gates: Dict[str, bool] = field(default_factory=dict)

    def gate(self) -> FeatureGate:
        return FeatureGate(overrides=self.feature_gates)

    def effective_limits(self) -> Optional[SnapshotLimits]:
        """The SnapshotLimits every profile's builder uses: the explicit
        `limits` when given (None means builder defaults), with a
        non-zero sliceMaxDim knob folded in."""
        lim = self.limits
        if self.slice_max_dim > 0:
            lim = lim if lim is not None else SnapshotLimits()
            lim.max_slice_dim = self.slice_max_dim
        return lim

    def validate(self) -> "SchedulerConfiguration":
        """Raise ValueError on an invalid configuration (the
        apis/config/validation analogue); returns self for chaining."""
        if not self.profiles:
            raise ValueError("at least one profile is required")
        names = [p.scheduler_name for p in self.profiles]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate profile schedulerName in {names}")
        for p in self.profiles:
            for plugin in p.disabled_score_plugins:
                if plugin not in SCORE_PLUGIN_WEIGHTS:
                    raise ValueError(
                        f"unknown or non-disableable score plugin {plugin!r} "
                        f"(filter plugins are fused; known: "
                        f"{sorted(SCORE_PLUGIN_WEIGHTS)})"
                    )
            cfg = p.score_config
            for f_name in (
                "fit_weight", "balanced_weight", "node_affinity_weight",
                "taint_weight", "spread_weight", "interpod_weight",
                "image_weight",
            ):
                if getattr(cfg, f_name) < 0:
                    raise ValueError(f"{p.scheduler_name}: {f_name} < 0")
            shape = cfg.rtcr_shape
            if not shape or any(
                b[0] <= a[0] for a, b in zip(shape, shape[1:])
            ):
                raise ValueError(
                    f"{p.scheduler_name}: rtcr_shape utilization points "
                    "must be non-empty and strictly increasing "
                    "(apis/config/validation's shape check)"
                )
            if cfg.fit_strategy not in (
                "LeastAllocated", "MostAllocated", "RequestedToCapacityRatio"
            ):
                raise ValueError(
                    f"{p.scheduler_name}: unknown fit_strategy "
                    f"{cfg.fit_strategy!r}"
                )
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if not (0 <= self.batch_window_seconds <= 1.0):
            raise ValueError(
                "batch_window_seconds must be within [0, 1] — the window "
                "is pure queueing latency for every pod in the batch"
            )
        if not (
            0
            <= self.batch_window_min_seconds
            <= self.batch_window_max_seconds
            <= 1.0
        ):
            raise ValueError(
                "adaptive window bounds must satisfy "
                "0 <= min <= max <= 1s (queueing-latency budget)"
            )
        if self.batch_latency_slo_seconds <= 0:
            raise ValueError("batch_latency_slo_seconds must be positive")
        if self.pod_initial_backoff_seconds <= 0:
            raise ValueError("pod_initial_backoff_seconds must be positive")
        if self.pod_max_backoff_seconds < self.pod_initial_backoff_seconds:
            raise ValueError(
                "pod_max_backoff_seconds < pod_initial_backoff_seconds"
            )
        if not (0 <= self.percentage_of_nodes_to_score <= 100):
            raise ValueError("percentage_of_nodes_to_score must be 0..100")
        if self.max_preemptions_per_cycle < 0:
            raise ValueError("max_preemptions_per_cycle must be >= 0")
        if self.commit_subwave_concurrency < 1:
            raise ValueError("commit_subwave_concurrency must be >= 1")
        if self.scheduler_lanes < 0:
            raise ValueError(
                "scheduler_lanes must be >= 0 (0 = one lane per profile)"
            )
        if self.mesh_devices < 0:
            raise ValueError("mesh_devices must be >= 0")
        if self.mesh_devices and (
            self.mesh_devices & (self.mesh_devices - 1)
        ):
            raise ValueError(
                "mesh_devices must be a power of two: padded node "
                "buckets are powers of two, and the node axis must "
                "split evenly across the mesh (parallel/sharded.py)"
            )
        if self.slice_carveout_policy not in ("prefer", "require", "off"):
            raise ValueError(
                "slice_carveout_policy must be one of prefer|require|off"
            )
        if self.slice_max_dim < 0:
            raise ValueError(
                "slice_max_dim must be >= 0 (0 = SnapshotLimits default)"
            )
        if self.partials_resync_interval < 1:
            raise ValueError(
                "partials_resync_interval must be >= 1 (every delta sync "
                "may force a full recompute, never none)"
            )
        if self.node_axis_headroom < 1.0:
            raise ValueError(
                "node_axis_headroom must be >= 1.0 (the backing arrays "
                "must at least fit the rows that forced the realloc)"
            )
        if self.bucket_shrink_dwell < 1:
            raise ValueError(
                "bucket_shrink_dwell must be >= 1 (a 1-generation dwell "
                "is the minimum hysteresis; 0 would shrink mid-encode)"
            )
        if self.compaction_batch_rows < 1:
            raise ValueError(
                "compaction_batch_rows must be >= 1 (a 0 budget would "
                "never relocate a row and the watermark could only trim)"
            )
        self.gate()  # unknown/locked gate overrides raise here
        return self


# ---------------------------------------------------------------------------
# Versioned config-file loading: KubeSchedulerConfiguration-shaped YAML
# -> defaults -> validation -> SchedulerConfiguration (the
# apis/config/{v1,validation} pipeline; scheduler.go:268-276 wires it).
# ---------------------------------------------------------------------------

_API_VERSIONS = (
    "kubescheduler.config.k8s.io/v1",
    "kubescheduler.config.tpu/v1",
)
_TOP_KEYS = {
    "apiVersion", "kind", "parallelism", "percentageOfNodesToScore",
    "podInitialBackoffSeconds", "podMaxBackoffSeconds", "profiles",
    "featureGates", "batchSize", "batchWindowSeconds", "assumeTTLSeconds",
    "unschedulableFlushSeconds", "maxPreemptionsPerCycle",
    "adaptiveBatchWindow", "batchWindowMinSeconds", "batchWindowMaxSeconds",
    "batchLatencySLOSeconds", "meshDevices", "commitSubwaveConcurrency",
    "schedulerLanes", "speculativeSolve", "streamSubwaves",
    "sliceCarveoutPolicy", "sliceMaxDim", "partialsResyncInterval",
    "nodeAxisHeadroom", "bucketShrinkDwell", "compactionBatchRows",
}


def load_config(source: Any) -> SchedulerConfiguration:
    """Load a KubeSchedulerConfiguration-shaped document: a YAML file
    path, a YAML string, or an already-parsed dict.  Unknown top-level
    fields are rejected (the strict-decoding posture); the result is
    defaulted and validated."""
    import os

    if isinstance(source, dict):
        doc = source
    else:
        import yaml

        text = source
        if isinstance(source, str) and os.path.exists(source):
            with open(source) as f:
                text = f.read()
        doc = yaml.safe_load(text) or {}
    if doc.get("kind", "KubeSchedulerConfiguration") != "KubeSchedulerConfiguration":
        raise ValueError(f"unexpected kind {doc.get('kind')!r}")
    api_version = doc.get("apiVersion", _API_VERSIONS[0])
    if api_version not in _API_VERSIONS:
        raise ValueError(
            f"unsupported apiVersion {api_version!r}; known: {_API_VERSIONS}"
        )
    unknown = set(doc) - _TOP_KEYS
    if unknown:
        raise ValueError(f"unknown configuration fields: {sorted(unknown)}")

    cfg = SchedulerConfiguration()
    if "parallelism" in doc:
        cfg.parallelism = int(doc["parallelism"])
    if "percentageOfNodesToScore" in doc:
        cfg.percentage_of_nodes_to_score = int(doc["percentageOfNodesToScore"])
    if "podInitialBackoffSeconds" in doc:
        cfg.pod_initial_backoff_seconds = float(doc["podInitialBackoffSeconds"])
    if "podMaxBackoffSeconds" in doc:
        cfg.pod_max_backoff_seconds = float(doc["podMaxBackoffSeconds"])
    if "batchSize" in doc:
        cfg.batch_size = int(doc["batchSize"])
    if "batchWindowSeconds" in doc:
        cfg.batch_window_seconds = float(doc["batchWindowSeconds"])
    if "adaptiveBatchWindow" in doc:
        cfg.adaptive_batch_window = bool(doc["adaptiveBatchWindow"])
    if "batchWindowMinSeconds" in doc:
        cfg.batch_window_min_seconds = float(doc["batchWindowMinSeconds"])
    if "batchWindowMaxSeconds" in doc:
        cfg.batch_window_max_seconds = float(doc["batchWindowMaxSeconds"])
    if "batchLatencySLOSeconds" in doc:
        cfg.batch_latency_slo_seconds = float(doc["batchLatencySLOSeconds"])
    if "assumeTTLSeconds" in doc:
        cfg.assume_ttl_seconds = float(doc["assumeTTLSeconds"])
    if "unschedulableFlushSeconds" in doc:
        cfg.unschedulable_flush_seconds = float(doc["unschedulableFlushSeconds"])
    if "maxPreemptionsPerCycle" in doc:
        cfg.max_preemptions_per_cycle = int(doc["maxPreemptionsPerCycle"])
    if "meshDevices" in doc:
        cfg.mesh_devices = int(doc["meshDevices"])
    if "commitSubwaveConcurrency" in doc:
        cfg.commit_subwave_concurrency = int(doc["commitSubwaveConcurrency"])
    if "schedulerLanes" in doc:
        cfg.scheduler_lanes = int(doc["schedulerLanes"])
    if "speculativeSolve" in doc:
        cfg.speculative_solve = bool(doc["speculativeSolve"])
    if "streamSubwaves" in doc:
        cfg.stream_subwaves = bool(doc["streamSubwaves"])
    if "sliceCarveoutPolicy" in doc:
        cfg.slice_carveout_policy = str(doc["sliceCarveoutPolicy"])
    if "sliceMaxDim" in doc:
        cfg.slice_max_dim = int(doc["sliceMaxDim"])
    if "partialsResyncInterval" in doc:
        cfg.partials_resync_interval = int(doc["partialsResyncInterval"])
    if "nodeAxisHeadroom" in doc:
        cfg.node_axis_headroom = float(doc["nodeAxisHeadroom"])
    if "bucketShrinkDwell" in doc:
        cfg.bucket_shrink_dwell = int(doc["bucketShrinkDwell"])
    if "compactionBatchRows" in doc:
        cfg.compaction_batch_rows = int(doc["compactionBatchRows"])
    if "featureGates" in doc:
        cfg.feature_gates = {
            str(k): bool(v) for k, v in (doc["featureGates"] or {}).items()
        }
    if "profiles" in doc:
        cfg.profiles = [_load_profile(p) for p in doc["profiles"] or []]
    return cfg.validate()


def _load_profile(doc: Dict[str, Any]) -> ProfileConfig:
    unknown = set(doc) - {"schedulerName", "plugins", "pluginConfig"}
    if unknown:
        raise ValueError(f"unknown profile fields: {sorted(unknown)}")
    profile = ProfileConfig(
        scheduler_name=doc.get("schedulerName", "default-scheduler")
    )
    score_kwargs: Dict[str, Any] = {}
    plugins = doc.get("plugins") or {}
    score = plugins.get("score") or {}
    disabled = tuple(
        d["name"] for d in score.get("disabled") or [] if d.get("name") != "*"
    )
    profile.disabled_score_plugins = disabled
    for e in score.get("enabled") or []:
        name, weight = e.get("name"), e.get("weight")
        if name not in SCORE_PLUGIN_WEIGHTS:
            raise ValueError(
                f"unknown score plugin {name!r}; known: "
                f"{sorted(SCORE_PLUGIN_WEIGHTS)}"
            )
        if weight is not None:
            score_kwargs[SCORE_PLUGIN_WEIGHTS[name]] = float(weight)
    for pc in doc.get("pluginConfig") or []:
        if pc.get("name") == "NodeResourcesFit":
            strat = (pc.get("args") or {}).get("scoringStrategy") or {}
            if "type" in strat:
                score_kwargs["fit_strategy"] = strat["type"]
            shape = strat.get("requestedToCapacityRatio", {}).get("shape")
            if shape:
                score_kwargs["rtcr_shape"] = tuple(
                    (float(p["utilization"]), float(p["score"]))
                    for p in shape
                )
    if score_kwargs:
        profile.score_config = replace(DEFAULT_SCORE_CONFIG, **score_kwargs)
    return profile
